"""Mesh encode coordinator: N live sessions → one sharded dispatch per tick.

This is the integration layer that makes BASELINE config 5 a *product*
path rather than a benchmark: the server's per-display capture loops keep
their shape (one asyncio task per display, reference selkies.py:2846-2904),
but instead of each owning a solo encoder pipeline they submit frames to a
per-session facade, and a single worker thread batches every session's
latest frame into one :class:`~selkies_tpu.parallel.mesh.MeshStripeEncoder`
dispatch over the ("session", "stripe") device mesh.

Facades expose the PipelinedJpegEncoder surface the capture loop already
speaks (``try_submit`` / ``poll`` / ``flush`` / ``force_keyframe`` /
``close``), so the server code path is identical either way.

Scheduling model: the worker ticks at the configured framerate. A tick
encodes the newest submitted frame per attached session; sessions without
a new frame re-present their previous frame, which damage gating then
suppresses on device — the dispatch stays dense and mesh-uniform (SPMD
needs every device to run the same program) while idle sessions cost no
wire bytes. Mesh batching uses the server-wide quality settings; per-client
encoder overrides are ignored in this mode (they would break SPMD
uniformity), which mirrors the shared-pipeline restriction the reference
has for shared displays.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("selkies_tpu.parallel")


class MeshSessionFacade:
    """One session's encoder-shaped handle onto the coordinator."""

    def __init__(self, coord: "MeshEncodeCoordinator", slot: int) -> None:
        self._coord = coord
        self.slot = slot
        self.closed = False

    def try_submit(self, frame) -> Optional[int]:
        return self._coord._submit(self.slot, frame)

    submit = try_submit

    def poll(self) -> List[Tuple[int, list]]:
        return self._coord._poll(self.slot)

    def flush(self) -> List[Tuple[int, list]]:
        return self._coord._flush(self.slot)

    def force_keyframe(self) -> None:
        self._coord._force_keyframe(self.slot)

    def pop_trace(self, seq: int):
        """Flight-recorder stage intervals for a harvested frame.

        Mesh attribution is coarser than the solo pipelines: the sharded
        harvest interleaves the D2H fetch with host assembly, so the
        whole harvest wall rides ``fetch_wait`` and there is no separate
        ``pack`` interval (docs/observability.md, stage glossary)."""
        return self._coord._pop_trace(self.slot, seq)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._coord._release(self.slot)


class MeshEncodeCoordinator:
    """Owns the mesh encoder, the session slot table, and the tick thread."""

    def __init__(
        self,
        mesh_spec: str,
        sessions_per_chip: int,
        width: int,
        height: int,
        settings=None,
        framerate: float = 60.0,
        stripe_h: int = 64,
        profile: str = "jpeg",
        max_inflight: int = 2,
    ) -> None:
        from .mesh import MeshStripeEncoder, parse_mesh_spec
        from .mesh_h264 import MeshH264Encoder

        self.mesh = parse_mesh_spec(mesh_spec)
        self.profile = profile
        n_sessions = self.mesh.shape["session"] * max(1, sessions_per_chip)
        kwargs: Dict[str, Any] = {}
        if profile == "x264enc-striped":
            # H.264 stripes over the mesh (VERDICT r3 item 3); CRF
            # settings map onto the QP scale like the solo factory does
            if settings is not None:
                kwargs = dict(
                    qp=int(settings.h264_crf.default),
                    paint_over_qp=int(settings.h264_paintover_crf.default),
                    use_paint_over_quality=bool(
                        settings.use_paint_over_quality.value),
                    stripe_h=int(settings.tpu_stripe_height),
                )
            else:
                kwargs = dict(stripe_h=stripe_h)
            self.enc = MeshH264Encoder(
                self.mesh, n_sessions, width, height, **kwargs)
        else:
            if settings is not None:
                kwargs = dict(
                    quality=int(settings.jpeg_quality.default),
                    paintover_quality=int(
                        settings.paint_over_jpeg_quality.default),
                    use_paint_over_quality=bool(
                        settings.use_paint_over_quality.value),
                    stripe_h=int(settings.tpu_stripe_height),
                )
            else:
                kwargs = dict(stripe_h=stripe_h)
            self.enc = MeshStripeEncoder(
                self.mesh, n_sessions, width, height, **kwargs)
        self.width, self.height = width, height
        self.framerate = float(framerate)
        self.n_sessions = n_sessions

        self._lock = threading.Lock()
        self._free = list(range(n_sessions))
        self._attached: Dict[int, bool] = {}
        self._pending: Dict[int, Any] = {}       # slot -> newest frame
        self._results: Dict[int, List] = {}      # slot -> [(seq, stripes)]
        self._seq: Dict[int, int] = {}
        #: slot -> {seq: stage intervals} for the flight recorder,
        #: bounded per slot; popped by the facade alongside _poll results
        self._traces: Dict[int, Dict[int, dict]] = {}
        self._want_key: set = set()
        self._want_reset: set = set()
        #: bounded in-flight window (ISSUE 12): up to ``max_inflight``
        #: dispatched ticks ride the device at once — dispatch of tick
        #: N+1 overlaps the D2H fetch of tick N, the same discipline as
        #: the solo async driver — drained oldest-first (harvest order
        #: is mandatory: per-stripe host state advances per tick)
        self.max_inflight = max(1, int(max_inflight))
        self._inflight_q: "deque" = deque()   # (pending, [(slot, gen)])
        self._inflight_slots: set = set()
        self.inflight_batches_max = 0
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: total coded bytes per slot from the device rate feedback
        self.coded_bytes = [0] * n_sessions
        #: per-shard fault accounting (ISSUE 2): frames lost to failed
        #: dispatch/harvest ticks, counted against the slots that were in
        #: that tick so a single noisy session is attributable
        self.slot_errors = [0] * n_sessions
        #: failed ticks total plus the worker's consecutive-failure streak
        #: (drives the capped backoff in _run)
        self.tick_errors_total = 0
        self._consecutive_tick_failures = 0
        #: times the worker thread was found dead and re-spawned
        self.worker_restarts_total = 0
        #: bumped on every acquire: harvests tagged with an older generation
        #: are dropped so a reused slot never receives the previous
        #: occupant's pixels (results dispatched before the handover)
        self._gen = [0] * n_sessions

    # -- session lifecycle (event-loop side) -------------------------------

    @property
    def active_sessions(self) -> int:
        """Currently attached sessions (live occupancy, not cumulative)."""
        with self._lock:
            return len(self._attached)

    def acquire(self, width: int, height: int) -> Optional[MeshSessionFacade]:
        """Attach a session; None when geometry differs or slots are full."""
        if (width, height) != (self.width, self.height):
            return None
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop(0)
            self._gen[slot] += 1
            self._attached[slot] = True
            self._results[slot] = []
            self._traces[slot] = {}
            self._seq[slot] = 0
            # applied at tick time: the worker may be mid-dispatch and the
            # encoder's host state is not safe to touch from here. A new
            # occupant gets a full reset (zeroed prev planes), not just a
            # keyframe — stale pixels must not leak across occupants.
            self._want_reset.add(slot)
        self._ensure_thread()
        return MeshSessionFacade(self, slot)

    def _release(self, slot: int) -> None:
        with self._lock:
            self._attached.pop(slot, None)
            self._pending.pop(slot, None)
            self._results.pop(slot, None)
            self._traces.pop(slot, None)
            self._free.append(slot)

    def _pop_trace(self, slot: int, seq: int):
        with self._lock:
            return self._traces.get(slot, {}).pop(seq, None)

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- facade surface ----------------------------------------------------

    def _submit(self, slot: int, frame) -> Optional[int]:
        with self._lock:
            if slot not in self._attached:
                return None
            dropped = slot in self._pending
            self._pending[slot] = frame
            # the seq THIS frame will harvest under: _seq advances only
            # at harvest, so frames of this slot already in the in-flight
            # window (same generation) come first — without the offset,
            # overlapped steady state would hand the in-flight frame's
            # seq to every new submit (trace correlation off by one)
            gen = self._gen[slot]
            inflight = sum(1 for entry in self._inflight_q
                           for s, g in entry[1] if s == slot and g == gen)
            seq = self._seq[slot] + inflight
        self._kick.set()
        return None if dropped else seq

    def _poll(self, slot: int) -> List[Tuple[int, list]]:
        with self._lock:
            out = self._results.get(slot, [])
            if out:
                self._results[slot] = []
            return out

    def _flush(self, slot: int) -> List[Tuple[int, list]]:
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if slot not in self._pending and \
                        slot not in self._inflight_slots:
                    break
            time.sleep(0.005)
        return self._poll(slot)

    def _force_keyframe(self, slot: int) -> None:
        with self._lock:
            self._want_key.add(slot)
        self._kick.set()

    # -- worker ------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            if self._thread is not None:
                # the previous worker died (tick exception storm or device
                # loss); account for the re-spawn so it is observable
                self.worker_restarts_total += 1
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="mesh-encode", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        interval = 1.0 / max(1.0, self.framerate)
        next_tick = time.monotonic()
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0:
                self._kick.wait(timeout=delay)
            self._kick.clear()
            now = time.monotonic()
            if now < next_tick:
                continue
            next_tick = max(next_tick + interval, now - interval)
            try:
                self._tick()
                self._consecutive_tick_failures = 0
            except Exception:
                # _tick already reattributed the failed slots; back off with
                # a capped exponential so a persistent device fault doesn't
                # spin the worker at tick rate
                self.tick_errors_total += 1
                self._consecutive_tick_failures += 1
                logger.exception("mesh encode tick failed (streak %d)",
                                 self._consecutive_tick_failures)
                # interruptible: stop() must not wait out the backoff
                from ..robustness import backoff_delay

                self._stop.wait(backoff_delay(
                    self._consecutive_tick_failures, 0.5, 5.0))

    def stats(self) -> dict:
        """Per-shard fault/restart accounting for health feeds and tests."""
        with self._lock:
            return {
                "active_sessions": len(self._attached),
                "tick_errors_total": self.tick_errors_total,
                "worker_restarts_total": self.worker_restarts_total,
                "slot_errors": list(self.slot_errors),
                "inflight_batches": len(self._inflight_q),
                "inflight_batches_max": self.inflight_batches_max,
            }

    def _recompute_inflight_slots_locked(self) -> None:
        self._inflight_slots = {
            s for entry in self._inflight_q for s, _ in entry[1]}

    def _fetch_ready(self, pending) -> bool:
        ready = getattr(self.enc, "fetch_ready", None)
        if ready is None:
            return True
        try:
            return bool(ready(pending))
        except Exception:
            return True

    def _harvest_oldest(self) -> None:
        """Harvest the head of the in-flight window (dispatch order is
        mandatory: per-stripe host state advances per tick)."""
        pending, took, dispatch_iv = self._inflight_q[0]
        t0 = time.monotonic()
        try:
            out, session_bytes = self.enc.harvest(pending)
        except Exception:
            with self._lock:
                self._inflight_q.popleft()
                for slot, _ in took:
                    self.slot_errors[slot] += 1
                self._recompute_inflight_slots_locked()
            raise
        # flight-recorder intervals: the sharded harvest interleaves the
        # D2H materialization with host assembly, so the whole wall is
        # attributed to fetch_wait (coarser than the solo pipelines; the
        # stage glossary in docs/observability.md documents this)
        harvest_iv = (t0, time.monotonic())
        with self._lock:
            self._inflight_q.popleft()
            self._recompute_inflight_slots_locked()
            for slot, gen in took:
                if slot not in self._attached or self._gen[slot] != gen:
                    continue
                self.coded_bytes[slot] += int(session_bytes[slot])
                seq = self._seq[slot]
                self._seq[slot] = seq + 1
                self._results[slot].append((seq, out[slot]))
                traces = self._traces.setdefault(slot, {})
                traces[seq] = {"dispatch": dispatch_iv,
                               "fetch_wait": harvest_iv}
                while len(traces) > 32:
                    traces.pop(next(iter(traces)))

    def _tick(self) -> None:
        """Dispatch this tick's frames, then drain the in-flight window:
        up to ``max_inflight`` dispatched ticks stay on the device at
        once (their prefix fetches were started eagerly at dispatch), so
        the round trip of tick N hides behind the compute of ticks
        N+1..N+k — the same in-flight discipline as the solo async
        pipeline driver (docs/pipeline.md)."""
        with self._lock:
            for slot in self._want_reset:
                if slot in self._attached or slot in self._free:
                    self.enc.reset_session(slot)
            self._want_reset.clear()
            for slot in self._want_key:
                if slot in self._attached or slot in self._free:
                    self.enc.force_keyframe(slot)
            self._want_key.clear()
            frames = [None] * self.n_sessions
            took: List[Tuple[int, int]] = []   # (slot, generation)
            for slot in self._attached:
                if slot in self._pending:
                    frames[slot] = self._pending.pop(slot)
                    took.append((slot, self._gen[slot]))
            self._inflight_slots |= {s for s, _ in took}
        # make room FIRST: the window is a hard bound on dispatched-
        # unharvested ticks, so a full window blocks on the oldest
        # fetch BEFORE the new dispatch, never after
        while took and len(self._inflight_q) >= self.max_inflight:
            self._harvest_oldest()
        t_disp0 = time.monotonic()
        try:
            pending = self.enc.dispatch(frames) if took else None
        except Exception:
            # a failed dispatch must not strand its slots in
            # _inflight_slots (facade.flush would block on them forever);
            # attribute the lost frames per shard, then let _run back off
            with self._lock:
                for slot, _ in took:
                    self.slot_errors[slot] += 1
                self._recompute_inflight_slots_locked()
            raise
        if pending is not None:
            with self._lock:
                self._inflight_q.append(
                    (pending, took, (t_disp0, time.monotonic())))
                self.inflight_batches_max = max(self.inflight_batches_max,
                                                len(self._inflight_q))
        # opportunistic drain: only fetches that already landed are
        # taken here, so this tick's dispatch is never stalled by a
        # slow transfer (the window cap above is the blocking site)
        while self._inflight_q and self._fetch_ready(self._inflight_q[0][0]):
            self._harvest_oldest()
