"""Mesh encode coordinator: a dynamic, failure-isolated session scheduler.

This is the integration layer that makes BASELINE config 5 a *product*
path rather than a benchmark: the server's per-display capture loops keep
their shape (one asyncio task per display, reference selkies.py:2846-2904),
but instead of each owning a solo encoder pipeline they submit frames to a
per-session facade, and a single worker thread batches sessions into
sharded :class:`~selkies_tpu.parallel.mesh.MeshStripeEncoder` dispatches
over the ("session", "stripe") device mesh.

Scheduling model (ISSUE 14, docs/scaling.md). Sessions pack into **batch
lanes**: each lane owns one compiled SPMD encoder with a fixed number of
slots, its own bounded in-flight window, and its own fault accounting — a
lane is a fault domain, and a *slot* is the sub-domain one session rides.

* **Dynamic admission** — a join takes a free slot in any live lane; when
  every lane is full a new lane is built on demand, up to ``max_lanes``.
  A full scheduler is therefore a real capacity statement (the server's
  admission control turns it into queue/shed verdicts), not an artifact
  of a construction-time constant.
* **Rebalance on leave** — a lane with no sessions and an empty window is
  retired after a grace period, freeing its device arrays; the tick never
  dispatches an empty lane, so a freed lane shrinks the dispatched work
  instead of ticking dead slots. One healthy lane is kept warm to spare
  the next joiner a rebuild (unless it carries quarantined slots — then
  retiring it is how the poisoned fault domain gets recycled).
* **Slot health + quarantine + live migration** — per-slot error EWMAs
  (:class:`~selkies_tpu.robustness.SlotHealth`) accumulate from failed
  dispatch/harvest ticks and injected slot faults. A slot that keeps
  faulting is quarantined (never returns to the free list) and its
  session is **migrated in place** to a healthy slot — the facade stays
  the same object, the new slot gets a full state reset (zeroed prev
  planes + keyframe), and the capture loop is told via
  ``consume_migration()`` so it can ride the PR 2 reset path
  (PIPELINE_RESETTING + ``Supervisor.forgive``). Cohabiting sessions keep
  streaming throughout: a slot failure must never become a mesh failure.
* **Lane-contained errors** — a failing lane charges its own slots and
  backs off by itself (``skip_until``); other lanes' ticks proceed. The
  worker thread only sees ``mesh.tick_raise``-style whole-tick faults.

A tick encodes the newest submitted frame per attached session; sessions
without a new frame re-present their previous frame, which damage gating
suppresses on device — each dispatch stays dense and mesh-uniform (SPMD
needs every device to run the same program) while idle sessions cost no
wire bytes. Mesh batching uses the server-wide quality settings; per-client
encoder overrides are ignored in this mode (they would break SPMD
uniformity), which mirrors the shared-pipeline restriction the reference
has for shared displays.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..robustness import SlotHealth, backoff_delay

logger = logging.getLogger("selkies_tpu.parallel")

#: seconds a failed lane build blocks further build attempts — a broken
#: device must not be re-probed on every join
LANE_BUILD_BLOCK_S = 30.0

#: process-global lane id counter: geometry buckets share one fault
#: injector, so a ``mesh.slot_raise=lane:slot`` arming must name exactly
#: one lane across ALL coordinators, not one per bucket
_lane_ids = itertools.count()


def _p50(samples, ndigits: int = 3) -> float:
    """Median of a small sample window (0.0 when empty). Shared with
    the bench reporters so every ``*_ms_p50`` surface agrees."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return round(s[len(s) // 2], ndigits)


class MeshSessionFacade:
    """One session's encoder-shaped handle onto the coordinator.

    The facade survives migration: the coordinator rebinds the session to
    a new (lane, slot) underneath it, and the capture loop polls
    :meth:`consume_migration` to learn a rebind happened (so it can reset
    frame ids and notify the client)."""

    def __init__(self, coord: "MeshEncodeCoordinator", sid: int) -> None:
        self._coord = coord
        self.sid = sid
        self.closed = False

    @property
    def slot(self) -> Optional[int]:
        """Current slot index (None once released)."""
        return self._coord._slot_of(self.sid)

    @property
    def lane_id(self) -> Optional[int]:
        return self._coord._lane_of(self.sid)

    def try_submit(self, frame) -> Optional[int]:
        return self._coord._submit(self.sid, frame)

    submit = try_submit

    def poll(self) -> List[Tuple[int, list]]:
        return self._coord._poll(self.sid)

    def flush(self) -> List[Tuple[int, list]]:
        return self._coord._flush(self.sid)

    def force_keyframe(self) -> None:
        self._coord._force_keyframe(self.sid)

    def consume_migration(self) -> bool:
        """True once per quarantine migration since the last call — the
        capture loop's cue to reset frame ids (keyframe is already forced
        on the new slot by the coordinator)."""
        return self._coord._consume_migration(self.sid)

    def pop_trace(self, seq: int):
        """Flight-recorder stage intervals for a harvested frame.

        The mesh encoders split the harvest wall into ``fetch_wait``
        (D2H materialization, attributed per SFE stripe shard in their
        ``last_harvest_stages``) and ``pack`` (host slice concat /
        entropy glue); injected encoders without the split fall back to
        whole-wall ``fetch_wait`` (docs/observability.md)."""
        return self._coord._pop_trace(self.sid, seq)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._coord._release(self.sid)


class _Session:
    """Scheduler-side state of one attached session (slot-independent, so
    migration only touches the lane/slot binding)."""

    __slots__ = ("sid", "lane", "slot", "gen", "seq", "pending", "results",
                 "traces", "inflight", "want_key", "want_reset",
                 "migrations_pending", "coded_bytes_total", "closed")

    def __init__(self, sid: int, lane: "_Lane", slot: int) -> None:
        self.sid = sid
        self.lane = lane
        self.slot = slot
        #: bumped on migration: harvests tagged with an older generation
        #: are dropped, so in-flight results of the old binding (or a
        #: previous occupant of a reused slot) never reach this session
        self.gen = 0
        self.seq = 0
        self.pending: Any = None
        self.results: List[Tuple[int, list]] = []
        #: seq -> stage intervals for the flight recorder (bounded)
        self.traces: Dict[int, dict] = {}
        #: frames of this session inside some lane's in-flight window
        self.inflight = 0
        self.want_key = False
        self.want_reset = False
        self.migrations_pending = 0
        self.coded_bytes_total = 0
        self.closed = False


class _Lane:
    """One SPMD batch lane: a compiled mesh encoder, its slot table, its
    bounded in-flight window, and its fault accounting."""

    __slots__ = ("id", "enc", "n_slots", "free", "sessions", "health",
                 "slot_errors", "inflight_q", "error_streak", "skip_until",
                 "idle_since")

    def __init__(self, lane_id: int, enc, n_slots: int,
                 health: SlotHealth) -> None:
        self.id = lane_id
        self.enc = enc
        self.n_slots = n_slots
        self.free = list(range(n_slots))
        self.sessions: Dict[int, _Session] = {}   # slot -> session
        self.health = health
        #: frames lost to failed dispatch/harvest ticks, per slot (so a
        #: single noisy session is attributable)
        self.slot_errors = [0] * n_slots
        #: (pending, [(session, slot, gen)], dispatch_interval)
        self.inflight_q: deque = deque()
        #: consecutive failed ticks of THIS lane; drives the per-lane
        #: capped backoff so a sick lane never slows its neighbours
        self.error_streak = 0
        self.skip_until = 0.0
        self.idle_since: Optional[float] = None


class _LaneTickError(RuntimeError):
    """Internal: a lane's dispatch/harvest failed (already attributed)."""


class MeshEncodeCoordinator:
    """Owns the batch lanes, the session table, and the tick thread."""

    def __init__(
        self,
        mesh_spec: str,
        sessions_per_chip: int,
        width: int,
        height: int,
        settings=None,
        framerate: float = 60.0,
        stripe_h: int = 64,
        profile: str = "jpeg",
        max_inflight: int = 2,
        max_lanes: Optional[int] = None,
        slots_per_lane: Optional[int] = None,
        enc_factory: Optional[Callable[[int], Any]] = None,
        health_sick_errors: Optional[float] = None,
        health_window_s: Optional[float] = None,
        lane_retire_s: float = 5.0,
        sfe_shards: int = 1,
    ) -> None:
        self.profile = profile
        self.width, self.height = width, height
        self.framerate = float(framerate)
        #: split-frame encoding (ISSUE 15, docs/scaling.md): when > 1,
        #: every lane of this bucket is an SFE lane — one session slot
        #: spans this many chips, each encoding a stripe band of the
        #: same frame. The default factory decides from sfe_min_pixels;
        #: injected-encoder harnesses pass it explicitly.
        self.sfe_shards = max(1, int(sfe_shards))
        if enc_factory is not None:
            # injected lanes (tests, tools/swarm_run.py): no jax import,
            # capacity comes from the caller
            self.chips = max(1, self._chips_from_spec(mesh_spec))
            self.slots_per_lane = int(
                slots_per_lane or max(1, sessions_per_chip))
            self._enc_factory = enc_factory
        else:
            self._enc_factory = self._build_default_factory(
                mesh_spec, sessions_per_chip, width, height,
                settings, stripe_h, profile)
        if max_lanes is None and settings is not None:
            max_lanes = int(getattr(settings, "mesh_max_lanes", 4) or 4)
        self.max_lanes = max(1, int(max_lanes or 4))
        if health_sick_errors is None and settings is not None:
            health_sick_errors = float(
                getattr(settings, "slot_quarantine_errors", 3) or 3)
        if health_window_s is None and settings is not None:
            health_window_s = float(
                getattr(settings, "slot_health_window_s", 30) or 30)
        self._health_sick_errors = float(health_sick_errors or 3.0)
        self._health_window_s = float(health_window_s or 30.0)
        self.lane_retire_s = float(lane_retire_s)

        self._lock = threading.Lock()
        #: serializes lane BUILDS only: device allocation can take
        #: seconds and must never happen under the main lock (it would
        #: freeze every ticking lane and every facade poll/submit)
        self._build_lock = threading.Lock()
        self.lanes: List[_Lane] = []
        self._lane_build_block_until = 0.0
        #: sids currently blocked from migrating (nowhere healthy to
        #: go): membership makes migrations_blocked_total count blocked
        #: EVENTS, not retry ticks
        self._blocked_sids: set = set()
        self._sessions: Dict[int, _Session] = {}
        self._next_sid = 0
        #: fault-injection registry checked at the tick/slot sites
        #: (mesh.tick_raise / mesh.slot_raise); wired by the server
        self.faults = None

        #: bounded in-flight window PER LANE (ISSUE 12): up to
        #: ``max_inflight`` dispatched ticks ride the device at once —
        #: dispatch of tick N+1 overlaps the D2H fetch of tick N, drained
        #: oldest-first (per-stripe host state advances per tick)
        self.max_inflight = max(1, int(max_inflight))
        self.inflight_batches_max = 0
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # -- aggregate fault/scheduling accounting (health feeds + tests)
        self.tick_errors_total = 0
        self._consecutive_tick_failures = 0
        self.worker_restarts_total = 0
        self.slot_faults_total = 0
        self.quarantined_total = 0
        self.migrations_total = 0
        self.migrations_blocked_total = 0
        self.lanes_built_total = 0
        self.lanes_retired_total = 0
        #: recent harvest fetch/concat walls (ms) from the lane encoders'
        #: last_harvest_stages — the sfe_concat_ms observability feed
        self._fetch_ms_window: deque = deque(maxlen=128)
        self._concat_ms_window: deque = deque(maxlen=128)
        # first lane is built eagerly so construction failures surface at
        # coordinator-build time (the server scopes those per geometry)
        if self._build_lane() is None:
            raise RuntimeError("mesh lane construction failed")

    # -- construction helpers ----------------------------------------------

    @staticmethod
    def _chips_from_spec(spec: str) -> int:
        """Device count implied by a ``tpu_mesh`` spec string, computed
        textually so injected-encoder mode never imports jax. Malformed
        parts are a configuration error and REJECTED — a typo'd axis
        must not silently collapse a multi-chip slice to one chip."""
        chips = 1
        for part in str(spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, num = part.partition(":")
            if not sep or not name.strip():
                raise ValueError(f"malformed tpu_mesh part {part!r} "
                                 f"(want axis:size)")
            try:
                chips *= max(1, int(num))
            except ValueError:
                raise ValueError(
                    f"malformed tpu_mesh part {part!r}: size "
                    f"{num!r} is not an integer") from None
        return chips

    @staticmethod
    def _sfe_shard_count(total_chips: int, width: int, height: int,
                         settings) -> int:
        """Stripe shards one frame of this geometry should span: 1 below
        ``sfe_min_pixels`` (or on a single chip), else ``sfe_shards``
        (0 = every chip), clamped to the largest count that tiles the
        slice. Pure policy — unit-testable without devices."""
        sfe_min = int(getattr(settings, "sfe_min_pixels", 0) or 0) \
            if settings is not None else 0
        if not sfe_min or total_chips <= 1 or width * height < sfe_min:
            return 1
        want = int(getattr(settings, "sfe_shards", 0) or 0) \
            if settings is not None else 0
        shards = max(1, min(want or total_chips, total_chips))
        while total_chips % shards:    # largest count that tiles the slice
            shards -= 1
        return shards

    def _build_default_factory(self, mesh_spec, sessions_per_chip, width,
                               height, settings, stripe_h, profile):
        from .mesh import MeshStripeEncoder, parse_mesh_spec
        from .mesh_h264 import MeshH264Encoder

        mesh = parse_mesh_spec(mesh_spec)
        total = mesh.shape["session"] * mesh.shape["stripe"]
        shards = self._sfe_shard_count(total, width, height, settings)
        if shards > 1:
            # SFE lane kind (ISSUE 15): this geometry's frames are too
            # big for one chip — re-partition the slice stripe-major so
            # one session's stripe bands shard across `shards` chips
            # (H.264 stripes are independently decodable, so the shards
            # run shard-local device CAVLC and the host concatenates).
            import numpy as _np
            from jax.sharding import Mesh as _Mesh

            devs = _np.asarray(mesh.devices).reshape(-1)
            mesh = _Mesh(devs.reshape(total // shards, shards),
                         ("session", "stripe"))
            self.sfe_shards = shards
            logger.info(
                "SFE lane geometry for %dx%d (%s): %d stripe shards "
                "per frame, %d session slot(s) per lane axis",
                width, height, profile, shards, total // shards)
        # an operator-configured stripe axis (tpu_mesh "…,stripe:M") is
        # stripe sharding too: shard-keyed faults and SFE accounting
        # must see it even when sfe_min_pixels never fired
        self.sfe_shards = max(self.sfe_shards, mesh.shape["stripe"])
        self.chips = mesh.shape["session"] * mesh.shape["stripe"]
        self.slots_per_lane = (
            mesh.shape["session"] * max(1, sessions_per_chip))
        kwargs: Dict[str, Any] = {}
        if profile == "x264enc-striped":
            # H.264 stripes over the mesh (VERDICT r3 item 3); CRF
            # settings map onto the QP scale like the solo factory does
            if settings is not None:
                kwargs = dict(
                    qp=int(settings.h264_crf.default),
                    paint_over_qp=int(settings.h264_paintover_crf.default),
                    use_paint_over_quality=bool(
                        settings.use_paint_over_quality.value),
                    stripe_h=int(settings.tpu_stripe_height),
                )
            else:
                kwargs = dict(stripe_h=stripe_h)

            def factory(n: int):
                return MeshH264Encoder(mesh, n, width, height, **kwargs)
        else:
            if settings is not None:
                kwargs = dict(
                    quality=int(settings.jpeg_quality.default),
                    paintover_quality=int(
                        settings.paint_over_jpeg_quality.default),
                    use_paint_over_quality=bool(
                        settings.use_paint_over_quality.value),
                    stripe_h=int(settings.tpu_stripe_height),
                )
            else:
                kwargs = dict(stripe_h=stripe_h)

            def factory(n: int):
                return MeshStripeEncoder(mesh, n, width, height, **kwargs)
        return factory

    def _build_lane(self) -> Optional[_Lane]:
        """Build and publish one lane, holding the main lock only for
        the capacity check and the publish — the encoder construction
        (device allocation) runs outside it, so ticking lanes and facade
        polls never freeze behind a build. ``_build_lock`` serializes
        concurrent builders (two joins racing must not overshoot
        ``max_lanes``)."""
        with self._build_lock:
            with self._lock:
                if len(self.lanes) >= self.max_lanes:
                    return None
                if time.monotonic() < self._lane_build_block_until:
                    return None
            try:
                enc = self._enc_factory(self.slots_per_lane)
            except Exception:
                # a broken device tier must not be re-probed per join
                with self._lock:
                    self._lane_build_block_until = (
                        time.monotonic() + LANE_BUILD_BLOCK_S)
                logger.exception("mesh lane build failed; blocking "
                                 "builds for %.0fs", LANE_BUILD_BLOCK_S)
                return None
            lane = _Lane(next(_lane_ids), enc, self.slots_per_lane,
                         SlotHealth(self.slots_per_lane,
                                    sick_errors=self._health_sick_errors,
                                    window_s=self._health_window_s))
            with self._lock:
                self.lanes.append(lane)
                self.lanes_built_total += 1
            logger.info("mesh lane %d built (%d slots, %d lanes live)",
                        lane.id, lane.n_slots, len(self.lanes))
            return lane

    # -- session lifecycle (event-loop side) -------------------------------

    @property
    def active_sessions(self) -> int:
        """Currently attached sessions (live occupancy, not cumulative)."""
        with self._lock:
            return len(self._sessions)

    @property
    def n_sessions(self) -> int:
        """Batch width of one lane (compat: the pre-lane slot count)."""
        return self.slots_per_lane

    @property
    def _attached(self) -> Dict[int, _Session]:
        """Compat view for tests: sid -> session."""
        with self._lock:
            return dict(self._sessions)

    def _bind_free_slot_locked(self) -> Optional[int]:
        lane = next((ln for ln in self.lanes if ln.free), None)
        if lane is None:
            return None
        slot = lane.free.pop(0)
        sid = self._next_sid
        self._next_sid += 1
        sess = _Session(sid, lane, slot)
        lane.sessions[slot] = sess
        lane.idle_since = None
        self._sessions[sid] = sess
        # applied at tick time: the worker may be mid-dispatch and the
        # encoder's host state is not safe to touch from here. A new
        # occupant gets a full reset (zeroed prev planes), not just a
        # keyframe — stale pixels must not leak across occupants.
        sess.want_reset = True
        return sid

    def acquire(self, width: int, height: int) -> Optional[MeshSessionFacade]:
        """Attach a session; None when geometry differs or — after trying
        to grow a fresh lane — the scheduler is genuinely out of slots."""
        if (width, height) != (self.width, self.height):
            return None
        with self._lock:
            sid = self._bind_free_slot_locked()
        if sid is None:
            # grow on demand: the build runs outside the main lock, so
            # existing lanes keep ticking while the new one allocates
            self._build_lane()
            with self._lock:
                sid = self._bind_free_slot_locked()
        if sid is None:
            return None
        self._ensure_thread()
        return MeshSessionFacade(self, sid)

    def capacity(self) -> Dict[str, int]:
        """Live lane capacity for the server's admission verdicts."""
        with self._lock:
            free = sum(len(ln.free) for ln in self.lanes)
            quarantined = sum(len(ln.health.quarantined)
                              for ln in self.lanes)
            growable = ((self.max_lanes - len(self.lanes))
                        * self.slots_per_lane
                        if time.monotonic() >= self._lane_build_block_until
                        else 0)
            return {
                "slots_free": free,
                "growable_slots": growable,
                "slots_total": len(self.lanes) * self.slots_per_lane,
                "quarantined_slots": quarantined,
                "active_sessions": len(self._sessions),
                "lanes": len(self.lanes),
                # SFE lanes span several chips per session slot: the
                # admission verdict still thinks in slots (correct), but
                # capacity consumers must see what one slot costs
                "sfe_shards": self.sfe_shards,
                "chips_per_slot": self.sfe_shards,
            }

    def _release(self, sid: int) -> None:
        with self._lock:
            sess = self._sessions.pop(sid, None)
            if sess is None:
                return
            sess.closed = True
            sess.pending = None
            sess.results = []
            sess.traces = {}
            self._blocked_sids.discard(sid)
            lane = sess.lane
            if lane.sessions.get(sess.slot) is sess:
                lane.sessions.pop(sess.slot, None)
                # quarantined slots never return to service; the lane is
                # recycled wholesale once it drains
                if sess.slot not in lane.health.quarantined:
                    lane.free.append(sess.slot)

    def _slot_of(self, sid: int) -> Optional[int]:
        with self._lock:
            sess = self._sessions.get(sid)
            return sess.slot if sess is not None else None

    def _lane_of(self, sid: int) -> Optional[int]:
        with self._lock:
            sess = self._sessions.get(sid)
            return sess.lane.id if sess is not None else None

    def _pop_trace(self, sid: int, seq: int):
        with self._lock:
            sess = self._sessions.get(sid)
            return sess.traces.pop(seq, None) if sess is not None else None

    def _consume_migration(self, sid: int) -> bool:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None and sess.migrations_pending:
                sess.migrations_pending = 0
                return True
            return False

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- facade surface ----------------------------------------------------

    def _submit(self, sid: int, frame) -> Optional[int]:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return None
            dropped = sess.pending is not None
            sess.pending = frame
            # the seq THIS frame will harvest under: seq advances only at
            # harvest, so same-generation frames already in the in-flight
            # window come first — without the offset, overlapped steady
            # state would hand the in-flight frame's seq to every new
            # submit (trace correlation off by one)
            inflight = sum(
                1 for entry in sess.lane.inflight_q
                for s, _slot, g in entry[1] if s is sess and g == sess.gen)
            seq = sess.seq + inflight
        self._kick.set()
        return None if dropped else seq

    def _poll(self, sid: int) -> List[Tuple[int, list]]:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return []
            out = sess.results
            if out:
                sess.results = []
            return out

    def _flush(self, sid: int) -> List[Tuple[int, list]]:
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                sess = self._sessions.get(sid)
                if sess is None or (sess.pending is None
                                    and sess.inflight == 0):
                    break
            time.sleep(0.005)
        return self._poll(sid)

    def _force_keyframe(self, sid: int) -> None:
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                sess.want_key = True
        self._kick.set()

    # -- worker ------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            if self._thread is not None:
                # the previous worker died (tick exception storm or device
                # loss); account for the re-spawn so it is observable
                self.worker_restarts_total += 1
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="mesh-encode", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        interval = 1.0 / max(1.0, self.framerate)
        next_tick = time.monotonic()
        while not self._stop.is_set():
            delay = next_tick - time.monotonic()
            if delay > 0:
                self._kick.wait(timeout=delay)
            self._kick.clear()
            now = time.monotonic()
            if now < next_tick:
                continue
            next_tick = max(next_tick + interval, now - interval)
            try:
                self._tick()
                self._consecutive_tick_failures = 0
            except Exception:
                # whole-tick failure (mesh.tick_raise / unexpected): lane
                # errors are contained per lane, so reaching here is rare;
                # back off with a capped exponential so a persistent fault
                # doesn't spin the worker at tick rate
                self.tick_errors_total += 1
                self._consecutive_tick_failures += 1
                logger.exception("mesh encode tick failed (streak %d)",
                                 self._consecutive_tick_failures)
                # interruptible: stop() must not wait out the backoff
                self._stop.wait(backoff_delay(
                    self._consecutive_tick_failures, 0.5, 5.0))

    def stats(self) -> dict:
        """Scheduler + per-slot fault accounting for health feeds/tests."""
        with self._lock:
            lane_detail = []
            for ln in self.lanes:
                lane_detail.append({
                    "id": ln.id,
                    "slots": ln.n_slots,
                    "free": len(ln.free),
                    "sessions": len(ln.sessions),
                    "slot_errors": list(ln.slot_errors),
                    "error_streak": ln.error_streak,
                    "inflight": len(ln.inflight_q),
                    "health": ln.health.state(),
                })
            return {
                "active_sessions": len(self._sessions),
                "lanes": len(self.lanes),
                "slots_per_lane": self.slots_per_lane,
                "capacity_slots": len(self.lanes) * self.slots_per_lane,
                "free_slots": sum(len(ln.free) for ln in self.lanes),
                "quarantined_slots": sum(
                    len(ln.health.quarantined) for ln in self.lanes),
                "tick_errors_total": self.tick_errors_total,
                "worker_restarts_total": self.worker_restarts_total,
                "slot_errors": [e for ln in self.lanes
                                for e in ln.slot_errors],
                "slot_faults_total": self.slot_faults_total,
                "quarantined_total": self.quarantined_total,
                "migrations_total": self.migrations_total,
                "migrations_blocked_total": self.migrations_blocked_total,
                "lanes_built_total": self.lanes_built_total,
                "lanes_retired_total": self.lanes_retired_total,
                "inflight_batches": sum(
                    len(ln.inflight_q) for ln in self.lanes),
                "inflight_batches_max": self.inflight_batches_max,
                "sfe_shards": self.sfe_shards,
                "sfe_fetch_ms_p50": _p50(self._fetch_ms_window),
                "sfe_concat_ms_p50": _p50(self._concat_ms_window),
                "lane_detail": lane_detail,
            }

    def verify_slot_accounting(self) -> List[str]:
        """Leak check for tests/harnesses: every slot of every lane must
        be exactly one of free / occupied / quarantined."""
        problems: List[str] = []
        with self._lock:
            for ln in self.lanes:
                occupied = set(ln.sessions)
                free = set(ln.free)
                quarantined = set(ln.health.quarantined)
                if len(ln.free) != len(free):
                    problems.append(f"lane {ln.id}: duplicate free slots")
                if free & occupied:
                    problems.append(
                        f"lane {ln.id}: slots both free and occupied: "
                        f"{sorted(free & occupied)}")
                if quarantined & free:
                    problems.append(
                        f"lane {ln.id}: quarantined slots back in the "
                        f"free list: {sorted(quarantined & free)}")
                accounted = free | occupied | quarantined
                missing = set(range(ln.n_slots)) - accounted
                if missing:
                    problems.append(
                        f"lane {ln.id}: leaked slots {sorted(missing)}")
            for sid, sess in self._sessions.items():
                if sess.lane.sessions.get(sess.slot) is not sess:
                    problems.append(f"session {sid}: dangling slot binding")
        return problems

    def _fetch_ready(self, lane: _Lane, pending) -> bool:
        ready = getattr(lane.enc, "fetch_ready", None)
        if ready is None:
            return True
        try:
            return bool(ready(pending))
        except Exception:
            return True

    def _harvest_oldest(self, lane: _Lane) -> None:
        """Harvest the head of a lane's in-flight window (dispatch order
        is mandatory: per-stripe host state advances per tick)."""
        pending, took, dispatch_iv = lane.inflight_q[0]
        t0 = time.monotonic()
        try:
            out, session_bytes = lane.enc.harvest(pending)
        except Exception:
            with self._lock:
                lane.inflight_q.popleft()
                for sess, slot, _gen in took:
                    lane.slot_errors[slot] += 1
                    lane.health.record_error(slot)
                    sess.inflight = max(0, sess.inflight - 1)
            raise
        # flight-recorder intervals: the mesh encoders report the
        # fetch/concat split of the harvest wall (last_harvest_stages,
        # with per-shard fetch attribution for SFE lanes) — D2H
        # materialization rides fetch_wait, host slice-concat/entropy
        # glue rides pack. Encoders without the split (injected fakes)
        # keep the coarse whole-wall fetch_wait attribution.
        t1 = time.monotonic()
        harvest_ms = (t1 - t0) * 1000.0
        stages = getattr(lane.enc, "last_harvest_stages", None)
        if isinstance(stages, dict) and "fetch_ms" in stages:
            t_split = min(t1, t0 + float(stages["fetch_ms"]) / 1000.0)
            trace_iv = {"dispatch": dispatch_iv,
                        "fetch_wait": (t0, t_split),
                        "pack": (t_split, t1)}
        else:
            trace_iv = {"dispatch": dispatch_iv, "fetch_wait": (t0, t1)}
        # encoder-internal stripe-job failures (whole-frame containment
        # withheld the AU without raising) must charge the slot exactly
        # like a harvest raise or an injected fault — otherwise a sick
        # shard chip freezes its session forever while health records ok
        # and quarantine/migration never fire
        failed = getattr(lane.enc, "last_failed_sessions", None) \
            or frozenset()
        with self._lock:
            if isinstance(stages, dict) and "fetch_ms" in stages:
                # under the lock: stats() sorts these windows while the
                # worker appends — deques must not be mutated mid-iteration
                self._fetch_ms_window.append(float(stages["fetch_ms"]))
                self._concat_ms_window.append(
                    float(stages.get("concat_ms", 0.0)))
            lane.inflight_q.popleft()
            for sess, slot, gen in took:
                sess.inflight = max(0, sess.inflight - 1)
                if slot in failed:
                    lane.slot_errors[slot] += 1
                    lane.health.record_error(slot)
                else:
                    lane.health.record_ok(slot, harvest_ms)
                if sess.closed or sess.gen != gen:
                    # released or migrated mid-flight: the old binding's
                    # pixels must not reach the (re-homed) session
                    continue
                sess.coded_bytes_total += int(session_bytes[slot])
                seq = sess.seq
                sess.seq = seq + 1
                sess.results.append((seq, out[slot]))
                sess.traces[seq] = dict(trace_iv)
                while len(sess.traces) > 32:
                    sess.traces.pop(next(iter(sess.traces)))

    def _unwind_took_locked(self, lane: _Lane, took) -> None:
        """A batch that never reached the in-flight window lost its
        frames: attribute per slot and release the inflight holds."""
        for sess, slot, _gen in took:
            lane.slot_errors[slot] += 1
            lane.health.record_error(slot)
            sess.inflight = max(0, sess.inflight - 1)

    def _tick(self) -> None:
        """One scheduler tick: apply deferred resets, build each lane's
        batch (with slot-fault screening), dispatch/drain every lane's
        bounded in-flight window, then run the quarantine/migration pass.
        Lane failures are contained to the lane (its slots charged, its
        own backoff armed); only whole-tick faults propagate to _run."""
        faults = self.faults
        if faults is not None:
            faults.maybe_raise("mesh.tick_raise")
        now = time.monotonic()
        plans: List[Tuple[_Lane, list, list]] = []
        with self._lock:
            self._retire_idle_lanes_locked(now)
            for sess in self._sessions.values():
                lane = sess.lane
                try:
                    if sess.want_reset:
                        # a new occupant / migration target gets zeroed
                        # prev planes AND a keyframe (reset implies it)
                        lane.enc.reset_session(sess.slot)
                    elif sess.want_key:
                        lane.enc.force_keyframe(sess.slot)
                except Exception:
                    # a broken lane must not take the whole tick down:
                    # charge the slot and let health/quarantine decide
                    lane.slot_errors[sess.slot] += 1
                    lane.health.record_error(sess.slot)
                    logger.exception("lane %d reset/keyframe failed for "
                                     "slot %d", lane.id, sess.slot)
                sess.want_reset = False
                sess.want_key = False
            for lane in self.lanes:
                if now < lane.skip_until:
                    continue
                frames = [None] * lane.n_slots
                took: List[Tuple[_Session, int, int]] = []
                for slot, sess in list(lane.sessions.items()):
                    if sess.pending is None:
                        continue
                    keys = ()
                    if faults is not None:
                        keys = [f"{lane.id}:{slot}", slot]
                        if self.sfe_shards > 1:
                            # an SFE slot answers to its shard identities
                            # too: a fault targeting ONE stripe shard of
                            # the frame still drops the WHOLE frame
                            # (whole-frame containment — a torn access
                            # unit is never an outcome) and charges this
                            # session's slot
                            for k in range(self.sfe_shards):
                                keys += [f"{lane.id}:{slot}:{k}",
                                         f"shard:{k}"]
                    if faults is not None and faults.should_fire_for(
                            "mesh.slot_raise", *keys):
                        # slot-scoped fault: charge THIS slot and drop its
                        # frame; cohabiting sessions' tick proceeds — a
                        # slot failure must never become a mesh failure
                        lane.slot_errors[slot] += 1
                        lane.health.record_error(slot)
                        self.slot_faults_total += 1
                        sess.pending = None
                        continue
                    frames[slot] = sess.pending
                    sess.pending = None
                    sess.inflight += 1
                    took.append((sess, slot, sess.gen))
                if took or lane.inflight_q:
                    plans.append((lane, frames, took))
        for lane, frames, took in plans:
            self._tick_lane(lane, frames, took)
        self._migrate_sick_sessions()

    def _tick_lane(self, lane: _Lane, frames: list, took: list) -> None:
        dispatched = False
        try:
            # make room FIRST: the window is a hard bound on dispatched-
            # unharvested ticks, so a full window blocks on the oldest
            # fetch BEFORE the new dispatch, never after
            while took and len(lane.inflight_q) >= self.max_inflight:
                self._harvest_oldest(lane)
            t_disp0 = time.monotonic()
            pending = lane.enc.dispatch(frames) if took else None
            if pending is not None:
                with self._lock:
                    lane.inflight_q.append(
                        (pending, took, (t_disp0, time.monotonic())))
                    depth = sum(len(ln.inflight_q) for ln in self.lanes)
                    self.inflight_batches_max = max(
                        self.inflight_batches_max, depth)
                dispatched = True
            elif took:
                # an encoder that swallowed a batch without a pending must
                # not strand the inflight holds (facade.flush would block
                # on them for its full timeout)
                with self._lock:
                    for sess, _slot, _gen in took:
                        sess.inflight = max(0, sess.inflight - 1)
                dispatched = True
            # opportunistic drain: only fetches that already landed are
            # taken here, so this tick's dispatch is never stalled by a
            # slow transfer (the window cap above is the blocking site)
            while lane.inflight_q and self._fetch_ready(
                    lane, lane.inflight_q[0][0]):
                self._harvest_oldest(lane)
        except Exception:
            # lane-contained failure: charge the batch that was lost, arm
            # this lane's own backoff, and keep every other lane ticking
            with self._lock:
                if took and not dispatched:
                    self._unwind_took_locked(lane, took)
                lane.error_streak += 1
                lane.skip_until = time.monotonic() + backoff_delay(
                    lane.error_streak, 0.5, 5.0)
            self.tick_errors_total += 1
            logger.exception("mesh lane %d tick failed (streak %d)",
                             lane.id, lane.error_streak)
        else:
            lane.error_streak = 0

    def _retire_idle_lanes_locked(self, now: float) -> None:
        """Rebalance on leave: a drained lane is retired after a grace
        period so its device arrays are freed — except the last healthy
        lane, which stays warm for the next joiner. A drained lane with
        quarantined slots is always retired: that is how a poisoned
        fault domain gets recycled into a fresh one."""
        if self.lane_retire_s < 0:
            return
        for lane in list(self.lanes):
            if lane.sessions or lane.inflight_q:
                lane.idle_since = None
                continue
            if lane.idle_since is None:
                lane.idle_since = now
                continue
            if now - lane.idle_since < self.lane_retire_s:
                continue
            if len(self.lanes) == 1 and not lane.health.quarantined:
                continue
            self.lanes.remove(lane)
            self.lanes_retired_total += 1
            logger.info("mesh lane %d retired (%d lanes live, %d slots "
                        "quarantined)", lane.id, len(self.lanes),
                        len(lane.health.quarantined))

    # -- quarantine + live migration ---------------------------------------

    def _migrate_sick_sessions(self) -> None:
        """Quarantine slots whose error EWMA crossed the threshold and
        re-home their sessions onto healthy slots, preferring a different
        lane (the whole lane may be the sick domain). The facade is
        untouched: only the binding moves, the new slot gets a full reset,
        and the capture loop learns via ``consume_migration()``.

        When no free slot exists anywhere, ONE lane build is attempted
        (outside the main lock — the build blocks only this tick thread,
        which already pays first-dispatch compiles by design, never the
        facades) and the pass retries. Still nowhere to go after that:
        the session keeps serving on the sick slot — degraded beats dead
        — counted once per blocked episode in ``migrations_blocked_total``
        and retried every tick while the EWMA keeps the slot flagged."""
        for attempt in (0, 1):
            with self._lock:
                blocked: List[_Session] = []
                for sess in list(self._sessions.values()):
                    if not sess.lane.health.is_sick(sess.slot):
                        continue
                    dest = self._find_migration_slot_locked(sess.lane)
                    if dest is None:
                        blocked.append(sess)
                        continue
                    self._do_migrate_locked(sess, *dest)
            if not blocked:
                return
            if attempt == 0 and self._build_lane() is not None:
                continue            # retry against the fresh lane
            with self._lock:
                for sess in blocked:
                    if sess.sid not in self._blocked_sids:
                        self._blocked_sids.add(sess.sid)
                        self.migrations_blocked_total += 1
            return

    def _do_migrate_locked(self, sess: _Session, dest_lane: _Lane,
                           dest_slot: int) -> None:
        old_lane, old_slot = sess.lane, sess.slot
        old_lane.health.quarantine(old_slot)
        old_lane.sessions.pop(old_slot, None)
        self.quarantined_total += 1
        dest_lane.sessions[dest_slot] = sess
        dest_lane.idle_since = None
        sess.lane, sess.slot = dest_lane, dest_slot
        sess.gen += 1              # drop the old binding's in-flights
        sess.pending = None        # staged for a dead slot
        sess.want_reset = True
        sess.migrations_pending += 1
        self.migrations_total += 1
        self._blocked_sids.discard(sess.sid)
        logger.warning(
            "session %d migrated off sick slot %d/lane %d -> "
            "slot %d/lane %d (slot quarantined)",
            sess.sid, old_slot, old_lane.id, dest_slot, dest_lane.id)

    def _find_migration_slot_locked(
            self, avoid: _Lane) -> Optional[Tuple[_Lane, int]]:
        candidates = [ln for ln in self.lanes
                      if ln is not avoid and ln.free]
        if not candidates and avoid.free:
            # same lane, different slot: weaker isolation, still a new
            # fault domain at slot granularity
            candidates = [avoid]
        if not candidates:
            return None
        lane = min(candidates, key=lambda ln: ln.error_streak)
        return lane, lane.free.pop(0)
