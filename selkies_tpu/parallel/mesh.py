"""Device mesh construction and the sharded multi-session encode step.

Replaces (TPU-natively) the reference's per-display C++ thread-pool
parallelism (pixelflux capture/encode threads, reference selkies.py:2846-2904)
with SPMD over a ``jax.sharding.Mesh``: sessions are data-parallel, a frame's
height is spatially sharded on stripe boundaries, and the global rate signal
is a psum over both axes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..encoder.jpeg import _encode_body


def make_mesh(
    devices=None,
    stripe_axis: Optional[int] = None,
) -> Mesh:
    """Build a ("session", "stripe") mesh over the given (or all) devices.

    ``stripe_axis`` defaults to 2 when the device count is even so both mesh
    axes are exercised, else 1 (pure session parallelism).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if stripe_axis is None:
        stripe_axis = 2 if (n % 2 == 0 and n > 1) else 1
    if n % stripe_axis:
        raise ValueError(f"{n} devices not divisible by stripe_axis={stripe_axis}")
    arr = np.asarray(devices).reshape(n // stripe_axis, stripe_axis)
    return Mesh(arr, ("session", "stripe"))


def make_batched_step(mesh: Mesh, stripe_h: int):
    """Jitted sharded step: encode one frame for every session in the batch.

    fn(frames, prev, qy, qc, qsel) with
      frames/prev [N, H, W, 3] uint8  — sharded (session, stripe) on (N, H);
      qy/qc       [nq, 8, 8] float32  — replicated quant tables;
      qsel        [N, S] int32        — per-session per-stripe table index.
    Returns (yq, cbq, crq, damage, new_prev, session_bits, total_bits):
      coefficient planes and damage sharded like their inputs, ``new_prev``
      for the next tick (donated chain), per-session nonzero-coefficient
      counts [N] (the rate-control feedback, psum over "stripe"), and the
      replicated global total (psum over "session" too).
    """
    n_session, n_stripe = mesh.shape["session"], mesh.shape["stripe"]

    def local_step(frames, prev, qy, qc, qsel):
        enc = functools.partial(_encode_body, stripe_h=stripe_h)
        yq, cbq, crq, damage, new_prev = jax.vmap(
            enc, in_axes=(0, 0, None, None, 0))(frames, prev, qy, qc, qsel)
        nz = (
            (yq != 0).sum(axis=(1, 2, 3))
            + (cbq != 0).sum(axis=(1, 2, 3))
            + (crq != 0).sum(axis=(1, 2, 3))
        ).astype(jnp.int32)
        # A session's stripes live on different chips along "stripe": the
        # per-session coded-size estimate is the ICI psum across that axis.
        session_bits = jax.lax.psum(nz, "stripe")
        total_bits = jax.lax.psum(session_bits.sum(), "session")
        return yq, cbq, crq, damage, new_prev, session_bits, total_bits

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("session", "stripe"),  # frames
            P("session", "stripe"),  # prev
            P(),                     # qy
            P(),                     # qc
            P("session", "stripe"),  # qsel
        ),
        out_specs=(
            P("session", "stripe"),  # yq
            P("session", "stripe"),  # cbq
            P("session", "stripe"),  # crq
            P("session", "stripe"),  # damage
            P("session", "stripe"),  # new_prev
            P("session"),            # session_bits
            P(),                     # total_bits
        ),
    )
    return jax.jit(sharded, donate_argnums=(1,)), (n_session, n_stripe)


class BatchedSessionEncoder:
    """Frame-batched multi-session encoder (BASELINE config 5 skeleton).

    Holds the sharded previous-frame state on device and dispatches one
    mesh-wide step per tick. Geometry constraints: ``height`` must divide
    evenly into ``mesh stripe axis × stripe_h`` bands and ``n_sessions``
    into the session axis.
    """

    def __init__(
        self,
        mesh: Mesh,
        n_sessions: int,
        width: int,
        height: int,
        stripe_h: int = 64,
        quality: int = 40,
        paintover_quality: int = 90,
    ) -> None:
        from ..ops.quant import quality_scaled_tables

        n_sess_ax = mesh.shape["session"]
        n_stripe_ax = mesh.shape["stripe"]
        if n_sessions % n_sess_ax:
            raise ValueError(
                f"{n_sessions} sessions not divisible by session axis {n_sess_ax}")
        if height % (n_stripe_ax * stripe_h):
            raise ValueError(
                f"height {height} not divisible by stripe axis {n_stripe_ax}"
                f" × stripe_h {stripe_h}")
        if width % 16:
            raise ValueError("width must be a multiple of 16 (4:2:0 MCUs)")
        self.mesh = mesh
        self.n_sessions = n_sessions
        self.width, self.height, self.stripe_h = width, height, stripe_h
        self.n_stripes = height // stripe_h

        ly, lc = quality_scaled_tables(quality)
        py, pc = quality_scaled_tables(paintover_quality)
        self._qy = jnp.stack([jnp.asarray(ly, jnp.float32),
                              jnp.asarray(py, jnp.float32)])
        self._qc = jnp.stack([jnp.asarray(lc, jnp.float32),
                              jnp.asarray(pc, jnp.float32)])

        self._step, _ = make_batched_step(mesh, stripe_h)
        frame_sharding = NamedSharding(mesh, P("session", "stripe"))
        self._frame_sharding = frame_sharding
        self._prev = jax.device_put(
            jnp.zeros((n_sessions, height, width, 3), jnp.uint8), frame_sharding)

    def step(self, frames: np.ndarray, qsel: Optional[np.ndarray] = None):
        """Encode one frame per session; returns
        (yq, cbq, crq, damage, session_bits, total_bits)."""
        if qsel is None:
            qsel = np.zeros((self.n_sessions, self.n_stripes), np.int32)
        frames_d = jax.device_put(
            jnp.asarray(frames, jnp.uint8), self._frame_sharding)
        yq, cbq, crq, damage, self._prev, session_bits, total_bits = self._step(
            frames_d, self._prev, self._qy, self._qc,
            jnp.asarray(qsel, jnp.int32))
        return yq, cbq, crq, damage, session_bits, total_bits
