"""Device mesh construction and the sharded multi-session encode step.

Replaces (TPU-natively) the reference's per-display C++ thread-pool
parallelism (pixelflux capture/encode threads, reference selkies.py:2846-2904)
with SPMD over a ``jax.sharding.Mesh``: sessions are data-parallel, a frame's
height is spatially sharded on stripe boundaries, and the global rate signal
is a psum over both axes.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..encoder.jpeg import _encode_body

#: jax ≥ 0.5 promoted shard_map out of experimental; accept either spelling
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - older runtimes
    from jax.experimental.shard_map import shard_map


def fetch_sharded_prefix(prefix):
    """Materialize an eagerly-fetching sharded device array shard by
    shard, attributing the D2H wall to each stripe-axis block.

    Returns ``(host, per_shard_ms)``: the assembled host ndarray and a
    map of stripe-axis block index (dim 1 of the array) to the host
    milliseconds spent blocked on that shard's transfer — the flight
    recorder's per-shard fetch attribution for split-frame encoding
    (ISSUE 15). Several sessions' shards on the same stripe block fold
    to the max (the gating wall). Falls back to one whole-array gather
    when shards are not addressable from this process."""
    try:
        if not getattr(prefix, "is_fully_addressable", True):
            # a process-spanning mesh would leave the remote shards'
            # regions of the np.empty buffer as garbage — fall through
            # to the whole-array gather, which fails loudly instead
            raise ValueError("prefix not fully addressable")
        shards = list(prefix.addressable_shards)
        if not shards:
            raise ValueError("no addressable shards")
        host = np.empty(prefix.shape, dtype=prefix.dtype)
        per_shard: dict = {}
        for sh in shards:
            t0 = time.perf_counter()
            host[sh.index] = np.asarray(sh.data)
            ms = (time.perf_counter() - t0) * 1000.0
            k = 0
            if len(sh.index) > 1 and isinstance(sh.index[1], slice):
                k = int(sh.index[1].start or 0)
            per_shard[k] = max(per_shard.get(k, 0.0), ms)
        return host, per_shard
    except Exception:
        t0 = time.perf_counter()
        host = np.asarray(prefix)
        return host, {0: (time.perf_counter() - t0) * 1000.0}


def make_mesh(
    devices=None,
    stripe_axis: Optional[int] = None,
) -> Mesh:
    """Build a ("session", "stripe") mesh over the given (or all) devices.

    ``stripe_axis`` defaults to 2 when the device count is even so both mesh
    axes are exercised, else 1 (pure session parallelism).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if stripe_axis is None:
        stripe_axis = 2 if (n % 2 == 0 and n > 1) else 1
    if n % stripe_axis:
        raise ValueError(f"{n} devices not divisible by stripe_axis={stripe_axis}")
    arr = np.asarray(devices).reshape(n // stripe_axis, stripe_axis)
    return Mesh(arr, ("session", "stripe"))


def parse_mesh_spec(spec: str, devices=None) -> Mesh:
    """Build a mesh from the ``tpu_mesh`` setting, e.g. ``"session:4"`` or
    ``"session:4,stripe:2"``. Axis sizes must multiply to ≤ the available
    device count; missing axes default to 1."""
    if devices is None:
        devices = jax.devices()
    sizes = {"session": 1, "stripe": 1}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, num = part.partition(":")
        name = name.strip()
        if name not in sizes:
            raise ValueError(f"unknown mesh axis {name!r} (session|stripe)")
        sizes[name] = int(num)
    total = sizes["session"] * sizes["stripe"]
    if total < 1 or total > len(devices):
        raise ValueError(
            f"mesh {spec!r} needs {total} devices; {len(devices)} available")
    arr = np.asarray(devices[:total]).reshape(sizes["session"], sizes["stripe"])
    return Mesh(arr, ("session", "stripe"))


def make_batched_step(mesh: Mesh, stripe_h: int):
    """Jitted sharded step: encode one frame for every session in the batch.

    fn(frames, prev, qy, qc, qsel) with
      frames/prev [N, H, W, 3] uint8  — sharded (session, stripe) on (N, H);
      qy/qc       [nq, 8, 8] float32  — replicated quant tables;
      qsel        [N, S] int32        — per-session per-stripe table index.
    Returns (yq, cbq, crq, damage, new_prev, session_bits, total_bits):
      coefficient planes and damage sharded like their inputs, ``new_prev``
      for the next tick (donated chain), per-session nonzero-coefficient
      counts [N] (the rate-control feedback, psum over "stripe"), and the
      replicated global total (psum over "session" too).
    """
    n_session, n_stripe = mesh.shape["session"], mesh.shape["stripe"]

    def local_step(frames, prev, qy, qc, qsel):
        enc = functools.partial(_encode_body, stripe_h=stripe_h)
        yq, cbq, crq, damage, new_prev = jax.vmap(
            enc, in_axes=(0, 0, None, None, 0))(frames, prev, qy, qc, qsel)
        nz = (
            (yq != 0).sum(axis=(1, 2, 3))
            + (cbq != 0).sum(axis=(1, 2, 3))
            + (crq != 0).sum(axis=(1, 2, 3))
        ).astype(jnp.int32)
        # A session's stripes live on different chips along "stripe": the
        # per-session coded-size estimate is the ICI psum across that axis.
        session_bits = jax.lax.psum(nz, "stripe")
        total_bits = jax.lax.psum(session_bits.sum(), "session")
        return yq, cbq, crq, damage, new_prev, session_bits, total_bits

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("session", "stripe"),  # frames
            P("session", "stripe"),  # prev
            P(),                     # qy
            P(),                     # qc
            P("session", "stripe"),  # qsel
        ),
        out_specs=(
            P("session", "stripe"),  # yq
            P("session", "stripe"),  # cbq
            P("session", "stripe"),  # crq
            P("session", "stripe"),  # damage
            P("session", "stripe"),  # new_prev
            P("session"),            # session_bits
            P(),                     # total_bits
        ),
    )
    return jax.jit(sharded, donate_argnums=(1,)), (n_session, n_stripe)


def make_batched_entropy_step(mesh: Mesh, pad_h: int, pad_w: int,
                              stripe_h: int):
    """Sharded multi-session step that carries encode *through* device
    entropy coding: one mesh dispatch yields wire-ready packed bitstreams
    for every session (VERDICT round-1 item 2 — BASELINE config 5).

    Stripes are independent JPEGs (DC prediction resets per stripe,
    device_entropy.scan_geometry), so each device entropy-codes its local
    height shard with a packer built for the *local* geometry — no
    cross-device bitstream stitching is needed; only the scalar rate
    feedback crosses the ICI (psum over "stripe" then "session").

    Returns (jitted_fn, meta): fn(frames, prev, qy, qc, qsel) →
      packed [N, stripe_ax, mw + cap_words] uint32 — per session per height
          shard: 4*S_local metadata words (nbytes/base/overflow/damage,
          see jpeg.split_meta) then the compacted stripe bitstreams;
      new_prev, yq, cbq, crq — sharded, stay on device (the coefficient
          planes are only materialized for rare overflow fallbacks);
      session_bytes [N] int32 — coded bytes per session (rate feedback);
      total_bytes  [] int32 — replicated global sum.
    meta = (S_local, mw, cap_words, packer) for host-side assembly.
    """
    from ..encoder.device_entropy import DeviceEntropyPacker

    n_stripe_ax = mesh.shape["stripe"]
    if pad_h % (n_stripe_ax * stripe_h):
        raise ValueError("pad_h must divide into stripe_ax × stripe_h bands")
    h_local = pad_h // n_stripe_ax
    # Same budgets as the solo streaming path (jpeg._device_pipeline):
    # pathological blocks/stripes overflow-flag and fall back to host coding.
    packer = DeviceEntropyPacker(h_local, pad_w, stripe_h,
                                 block_words=16, max_stripe_bytes=1 << 14)
    s_local = h_local // stripe_h
    mw = 4 * s_local
    cap = packer.cap_words

    def local_step(frames, prev, qy, qc, qsel):
        enc = functools.partial(_encode_body, stripe_h=stripe_h)
        yq, cbq, crq, damage, new_prev = jax.vmap(
            enc, in_axes=(0, 0, None, None, 0))(frames, prev, qy, qc, qsel)
        words, nbytes, base, ovf = jax.vmap(packer._pack_fn)(yq, cbq, crq)
        session_bytes = jax.lax.psum(
            nbytes.sum(axis=1).astype(jnp.int32), "stripe")
        total_bytes = jax.lax.psum(session_bytes.sum(), "session")
        # session_bytes rides the fetched head (one extra word) so the
        # host never pays a second D2H round trip for rate feedback
        head = jnp.concatenate([
            nbytes.astype(jnp.uint32),
            base.astype(jnp.uint32),
            ovf.astype(jnp.uint32),
            damage.astype(jnp.uint32),
            session_bytes[:, None].astype(jnp.uint32),
        ], axis=1)                                    # [N_local, mw + 1]
        packed = jnp.concatenate([head, words], axis=1)[:, None, :]
        return (packed, new_prev, yq, cbq, crq, session_bytes, total_bytes)

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("session", "stripe"),        # frames [N, H, W, 3]
            P("session", "stripe"),        # prev
            P(),                           # qy
            P(),                           # qc
            P("session", "stripe"),        # qsel [N, S_total]
        ),
        out_specs=(
            P("session", "stripe", None),  # packed [N, stripe_ax, mw+cap]
            P("session", "stripe"),        # new_prev
            P("session", "stripe"),        # yq
            P("session", "stripe"),        # cbq
            P("session", "stripe"),        # crq
            P("session"),                  # session_bytes
            P(),                           # total_bytes
        ),
    )
    return jax.jit(sharded, donate_argnums=(1,)), (s_local, mw, cap, packer)


class BatchedSessionEncoder:
    """Frame-batched multi-session encoder (BASELINE config 5 skeleton).

    Holds the sharded previous-frame state on device and dispatches one
    mesh-wide step per tick. Geometry constraints: ``height`` must divide
    evenly into ``mesh stripe axis × stripe_h`` bands and ``n_sessions``
    into the session axis.
    """

    def __init__(
        self,
        mesh: Mesh,
        n_sessions: int,
        width: int,
        height: int,
        stripe_h: int = 64,
        quality: int = 40,
        paintover_quality: int = 90,
    ) -> None:
        from ..ops.quant import quality_scaled_tables

        n_sess_ax = mesh.shape["session"]
        n_stripe_ax = mesh.shape["stripe"]
        if n_sessions % n_sess_ax:
            raise ValueError(
                f"{n_sessions} sessions not divisible by session axis {n_sess_ax}")
        if height % (n_stripe_ax * stripe_h):
            raise ValueError(
                f"height {height} not divisible by stripe axis {n_stripe_ax}"
                f" × stripe_h {stripe_h}")
        if width % 16:
            raise ValueError("width must be a multiple of 16 (4:2:0 MCUs)")
        self.mesh = mesh
        self.n_sessions = n_sessions
        self.width, self.height, self.stripe_h = width, height, stripe_h
        self.n_stripes = height // stripe_h

        ly, lc = quality_scaled_tables(quality)
        py, pc = quality_scaled_tables(paintover_quality)
        self._qy = jnp.stack([jnp.asarray(ly, jnp.float32),
                              jnp.asarray(py, jnp.float32)])
        self._qc = jnp.stack([jnp.asarray(lc, jnp.float32),
                              jnp.asarray(pc, jnp.float32)])

        self._step, _ = make_batched_step(mesh, stripe_h)
        frame_sharding = NamedSharding(mesh, P("session", "stripe"))
        self._frame_sharding = frame_sharding
        self._prev = jax.device_put(
            jnp.zeros((n_sessions, height, width, 3), jnp.uint8), frame_sharding)

    def step(self, frames: np.ndarray, qsel: Optional[np.ndarray] = None):
        """Encode one frame per session; returns
        (yq, cbq, crq, damage, session_bits, total_bits)."""
        if qsel is None:
            qsel = np.zeros((self.n_sessions, self.n_stripes), np.int32)
        frames_d = jax.device_put(
            jnp.asarray(frames, jnp.uint8), self._frame_sharding)
        yq, cbq, crq, damage, self._prev, session_bits, total_bits = self._step(
            frames_d, self._prev, self._qy, self._qc,
            jnp.asarray(qsel, jnp.int32))
        return yq, cbq, crq, damage, session_bits, total_bits


@dataclass
class _MeshPending:
    """One in-flight mesh dispatch (device handles + dispatch-time state)."""

    prefix: Any                 # async-fetching head+payload-guess slice
    packed: Any                 # full device buffer (refetch on miss)
    yq: Any                     # coefficient planes (overflow fallback only)
    cbq: Any
    crq: Any
    paint_candidate: np.ndarray
    reuse_prev: np.ndarray
    first: np.ndarray
    stride: int


class MeshStripeEncoder:
    """Multi-session JPEG-stripe encoder over a device mesh: one sharded
    dispatch per tick carries every session's frame through color convert,
    DCT, quantization AND device entropy coding, returning wire-ready 0x03
    stripe payloads per session (BASELINE config 5, completed end-to-end).

    Role: N solo ``JpegStripeEncoder``s collapsed into one SPMD program —
    sessions are data-parallel on the "session" mesh axis, each frame's
    height is sharded on the "stripe" axis, and damage gating / paint-over
    history run vectorized on host across the whole batch.
    """

    def __init__(
        self,
        mesh: Mesh,
        n_sessions: int,
        width: int,
        height: int,
        stripe_h: int = 64,
        quality: int = 40,
        paintover_quality: int = 90,
        use_paint_over_quality: bool = True,
        paint_over_trigger_frames: int = 15,
        damage_threshold: int = 0,
    ) -> None:
        from ..encoder.jfif import jfif_headers
        from ..ops.quant import quality_scaled_tables

        n_sess_ax = mesh.shape["session"]
        self.n_stripe_ax = mesh.shape["stripe"]
        if n_sessions % n_sess_ax:
            raise ValueError(
                f"{n_sessions} sessions not divisible by session axis {n_sess_ax}")
        if stripe_h % 16:
            raise ValueError("stripe_h must be a multiple of 16 (4:2:0 MCUs)")
        band = self.n_stripe_ax * stripe_h
        self.width, self.height = width, height
        self.pad_w = -(-width // 16) * 16
        self.pad_h = -(-height // band) * band
        self.stripe_h = stripe_h
        self.n_stripes = self.pad_h // stripe_h
        self.n_sessions = n_sessions
        self.mesh = mesh
        self.damage_threshold = int(damage_threshold)
        self.use_paint_over_quality = bool(use_paint_over_quality)
        self.paint_over_trigger_frames = int(paint_over_trigger_frames)

        ly, lc = quality_scaled_tables(quality)
        py, pc = quality_scaled_tables(paintover_quality)
        self._qy = jnp.stack([jnp.asarray(ly, jnp.float32),
                              jnp.asarray(py, jnp.float32)])
        self._qc = jnp.stack([jnp.asarray(lc, jnp.float32),
                              jnp.asarray(pc, jnp.float32)])
        self._headers = tuple(
            jfif_headers(self.pad_w, stripe_h, qy_np, qc_np, subsampling="420")
            for qy_np, qc_np in ((ly, lc), (py, pc)))

        self._step, (self.s_local, self._mw, self._cap, self._packer) = \
            make_batched_entropy_step(mesh, self.pad_h, self.pad_w, stripe_h)
        self._frame_sharding = NamedSharding(mesh, P("session", "stripe"))
        self._qsel_sharding = NamedSharding(mesh, P("session", "stripe"))
        self._prev = jax.device_put(
            jnp.zeros((n_sessions, self.pad_h, self.pad_w, 3), jnp.uint8),
            self._frame_sharding)

        S = self.n_stripes
        self._static = np.zeros((n_sessions, S), np.int64)
        self._painted = np.zeros((n_sessions, S), bool)
        self._first = np.ones(n_sessions, bool)
        #: host mirror of each slot's last submitted padded frame (idle
        #: ticks re-present it without touching the device prev buffer)
        self._last_host = np.zeros(
            (n_sessions, self.pad_h, self.pad_w, 3), np.uint8)
        #: adaptive D2H prefix (words per (session, shard) fetched besides
        #: metadata); a miss costs one extra read of the missing slice
        self._guess = self._packer.bucket_words(8192)
        #: fetch/concat split of the latest harvest wall, with per-shard
        #: fetch attribution (the coordinator's flight-recorder feed)
        self.last_harvest_stages: Optional[dict] = None

    # -- control -----------------------------------------------------------

    def force_keyframe(self, session: int) -> None:
        """Next frame emits every stripe of one session (viewer join)."""
        self._first[session] = True
        self._static[session] = 0
        self._painted[session] = False

    def reset_session(self, session: int) -> None:
        """Recycle a slot for a new session: fresh damage history AND a
        zeroed prev frame so no stale pixels leak across occupants.

        force_keyframe alone is NOT enough the day an inter profile
        rides the mesh (VERDICT r2 weak item 6): the previous occupant's
        pixels would persist in the prev/reference planes and in the
        idle-tick re-present buffer."""
        self.force_keyframe(session)
        self._last_host[session] = 0
        self._prev = jax.device_put(
            jnp.asarray(self._prev).at[session].set(0),
            self._frame_sharding)

    # -- per-tick ----------------------------------------------------------

    def _pad(self, frame: np.ndarray) -> np.ndarray:
        if frame.shape[0] == self.pad_h and frame.shape[1] == self.pad_w:
            return frame
        return np.pad(
            frame,
            ((0, self.pad_h - frame.shape[0]),
             (0, self.pad_w - frame.shape[1]), (0, 0)),
            mode="edge")

    def dispatch(self, frames) -> "_MeshPending":
        """Dispatch one mesh step for all sessions and start the async D2H
        prefix fetch; pair with :meth:`harvest`. Keeping one dispatch in
        flight while harvesting the previous one hides the device
        round-trip exactly like the solo PipelinedJpegEncoder does.

        ``frames``: [N, H, W, 3] uint8 array, a device-resident pre-padded
        jnp array, or a length-N sequence (entries may be unpadded; None
        reuses the previous frame, which damage gating then suppresses).
        """
        reuse_prev = np.zeros(self.n_sessions, bool)
        if isinstance(frames, jnp.ndarray):
            # device-resident batch (bench/synthetic sources): must already
            # be padded to the encoder geometry
            want = (self.n_sessions, self.pad_h, self.pad_w, 3)
            if frames.shape != want:
                raise ValueError(f"device batch must be pre-padded to {want}")
            batch = frames
        elif isinstance(frames, np.ndarray) and frames.ndim == 4:
            for n in range(self.n_sessions):
                self._last_host[n] = self._pad(np.asarray(frames[n], np.uint8))
            batch = self._last_host
        else:
            # the persistent host batch doubles as the last-frame cache:
            # slots without a new frame this tick keep their old pixels
            # (damage then reads all-zero on device) with no realloc and
            # never a blocking device prev readback
            for n, f in enumerate(frames):
                if f is None:
                    reuse_prev[n] = True
                else:
                    self._last_host[n] = self._pad(np.asarray(f, np.uint8))
            batch = self._last_host

        paint_candidate = (
            self.use_paint_over_quality
            & (self._static >= self.paint_over_trigger_frames)
            & ~self._painted)
        paint_candidate &= ~reuse_prev[:, None] & ~self._first[:, None]
        first = self._first.copy()
        # a keyframe request on a slot with no frame this tick stays armed
        self._first &= reuse_prev
        # optimistic mark (cleared again by damage at harvest): frames
        # dispatched before this one harvests must not re-trigger the
        # same paint-over
        self._painted |= paint_candidate

        qsel = jax.device_put(
            jnp.asarray(paint_candidate.astype(np.int32)),
            self._qsel_sharding)
        frames_d = jax.device_put(jnp.asarray(batch), self._frame_sharding)
        packed, self._prev, yq, cbq, crq, _sb, _total = self._step(
            frames_d, self._prev, self._qy, self._qc, qsel)

        stride = self._mw + 1 + min(self._guess, self._cap)
        prefix = packed[:, :, :stride]
        prefix.copy_to_host_async()
        return _MeshPending(
            prefix=prefix, packed=packed, yq=yq, cbq=cbq, crq=crq,
            paint_candidate=paint_candidate, reuse_prev=reuse_prev,
            first=first, stride=stride)

    def fetch_ready(self, p: "_MeshPending") -> bool:
        """True when the eagerly-started prefix fetch has landed — the
        coordinator's in-flight window harvests without blocking then."""
        return bool(p.prefix.is_ready())

    def harvest(self, p: "_MeshPending") -> Tuple[List[List], np.ndarray]:
        """Complete one dispatched step: returns (stripes_per_session,
        session_coded_bytes). Must be called in dispatch order.

        Sets :attr:`last_harvest_stages` — the fetch/concat split of the
        harvest wall with per-stripe-shard fetch attribution — which the
        coordinator folds into each frame's flight-recorder span."""
        from ..encoder.jpeg import StripeOutput, split_meta

        t_h0 = time.perf_counter()
        host, per_shard_ms = fetch_sharded_prefix(p.prefix)
        fetch_ms = sum(per_shard_ms.values())
        head = self._mw + 1

        damaged = np.zeros((self.n_sessions, self.n_stripes), bool)
        session_bytes = np.zeros(self.n_sessions, np.int64)
        metas = {}
        max_total = 0
        for n in range(self.n_sessions):
            session_bytes[n] = int(host[n, 0, self._mw])
            for k in range(self.n_stripe_ax):
                nbytes, base, ovf, damage = split_meta(
                    host[n, k, :self._mw], self.s_local)
                metas[(n, k)] = (nbytes, base, ovf)
                total = int(base[-1]) + (int(nbytes[-1]) + 3) // 4
                max_total = max(max_total, total)
                gs = slice(k * self.s_local, (k + 1) * self.s_local)
                damaged[n, gs] = damage > self.damage_threshold

        damaged[p.first] = True
        damaged[p.reuse_prev] = False
        emit = damaged | p.paint_candidate
        is_paint = p.paint_candidate
        self._static = np.where(damaged, 0, self._static + 1)
        # paint marks were set optimistically at dispatch; damage clears
        self._painted = np.where(damaged, False, self._painted)

        # start every miss-refetch before blocking on any (parallel RPCs)
        refetch = {}
        for n in range(self.n_sessions):
            if not emit[n].any():
                continue
            for k in range(self.n_stripe_ax):
                gs0 = k * self.s_local
                if not emit[n, gs0:gs0 + self.s_local].any():
                    continue
                nbytes, base, ovf = metas[(n, k)]
                total = int(base[-1]) + (int(nbytes[-1]) + 3) // 4
                if total > p.stride - head:
                    sl = p.packed[n, k, head:head + total]
                    sl.copy_to_host_async()
                    refetch[(n, k)] = sl

        out: List[List[StripeOutput]] = []
        for n in range(self.n_sessions):
            stripes: List[StripeOutput] = []
            if emit[n].any():
                for k in range(self.n_stripe_ax):
                    gs0 = k * self.s_local
                    if not emit[n, gs0:gs0 + self.s_local].any():
                        continue
                    nbytes, base, ovf = metas[(n, k)]
                    total = int(base[-1]) + (int(nbytes[-1]) + 3) // 4
                    if (n, k) in refetch:
                        t_rf = time.perf_counter()
                        words = np.asarray(refetch[(n, k)])
                        rf_ms = (time.perf_counter() - t_rf) * 1000.0
                        fetch_ms += rf_ms
                        per_shard_ms[k] = per_shard_ms.get(k, 0.0) + rf_ms
                    else:
                        words = host[n, k, head:head + total]
                    stripes += self._shard_stripes(
                        n, k, words, nbytes, base, ovf,
                        emit[n], is_paint[n], p.yq, p.cbq, p.crq)
            out.append(stripes)

        self._guess = max(self._packer.bucket_words(max(max_total * 2, 8192)),
                          self._guess // 2)
        total_ms = (time.perf_counter() - t_h0) * 1000.0
        self.last_harvest_stages = {
            "fetch_ms": fetch_ms,
            "concat_ms": max(0.0, total_ms - fetch_ms),
            "per_shard_fetch_ms": [
                round(per_shard_ms.get(k, 0.0), 3)
                for k in range(self.n_stripe_ax)],
        }
        return out, session_bytes

    def encode_frames(self, frames) -> Tuple[List[List], np.ndarray]:
        """Synchronous dispatch + harvest (tests, simple callers)."""
        return self.harvest(self.dispatch(frames))

    def _shard_stripes(self, n, k, words, nbytes, base, ovf,
                       emit, is_paint, yq, cbq, crq):
        from ..encoder.device_entropy import stuff_bytes, words_to_stripe_bytes
        from ..encoder.jfif import EOI
        from ..encoder.jpeg import StripeOutput, _entropy_encode_420

        raw = words_to_stripe_bytes(words, base, nbytes)
        yrows, crows = self.stripe_h // 8, self.stripe_h // 16
        out = []
        for s in range(self.s_local):
            g = k * self.s_local + s
            if not emit[g]:
                continue
            if ovf[s]:  # pathological stripe: host-code its coefficients
                scan = _entropy_encode_420(
                    np.asarray(yq[n, g * yrows:(g + 1) * yrows]),
                    np.asarray(cbq[n, g * crows:(g + 1) * crows]),
                    np.asarray(crq[n, g * crows:(g + 1) * crows]))
            else:
                scan = stuff_bytes(raw[s])
            qidx = 1 if is_paint[g] else 0
            out.append(StripeOutput(
                y_start=g * self.stripe_h,
                height=self.stripe_h,
                jpeg=self._headers[qidx] + scan + EOI,
                is_paintover=bool(is_paint[g])))
        return out
