"""Virtual gamepad emulation over the interposer unix-socket protocol.

Protocol parity with the reference (input_handler.py:118-760 and
addons/js-interposer/joystick_interposer.c):

* Per pad slot N, two unix-socket servers: ``selkies_js{N}.sock`` (legacy
  joystick API) and ``selkies_event{1000+N}.sock`` (evdev API).
* On connect the server writes one 1360-byte ``js_config_t`` (name[255],
  1 pad, vendor/product/version/num_btns/num_axes u16, btn_map[512] u16,
  axes_map[64] u8, 6 pad), then reads ONE byte: the client's
  ``sizeof(long)`` (4 or 8) which fixes the timeval width of subsequent
  evdev ``input_event`` structs.
* Events: js sockets get ``struct js_event {u32 time_ms; s16 value;
  u8 type; u8 number}``; evdev sockets get ``struct input_event`` followed
  by a ``SYN_REPORT``.

The browser side speaks the W3C "standard gamepad" layout; we present a
Linux ``xpad``-style Xbox-360 controller to the apps, so the mapper below
translates W3C indices → evdev codes.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("selkies_tpu.input.gamepad")

# -- evdev constants (linux/input-event-codes.h) -----------------------------

EV_SYN, EV_KEY, EV_REL, EV_ABS = 0x00, 0x01, 0x02, 0x03
SYN_REPORT = 0

BTN_A, BTN_B, BTN_X, BTN_Y = 0x130, 0x131, 0x133, 0x134
BTN_TL, BTN_TR = 0x136, 0x137
BTN_SELECT, BTN_START, BTN_MODE = 0x13A, 0x13B, 0x13C
BTN_THUMBL, BTN_THUMBR = 0x13D, 0x13E

ABS_X, ABS_Y, ABS_Z = 0x00, 0x01, 0x02
ABS_RX, ABS_RY, ABS_RZ = 0x03, 0x04, 0x05
ABS_HAT0X, ABS_HAT0Y = 0x10, 0x11

JS_EVENT_BUTTON, JS_EVENT_AXIS, JS_EVENT_INIT = 0x01, 0x02, 0x80

AXIS_MAX = 32767
AXIS_MIN = -32767

# -- interposer config struct -------------------------------------------------

NAME_LEN = 255
MAX_BTNS = 512
MAX_AXES = 64
CONFIG_STRUCT_SIZE = 1360
# name[255] | 1 align pad | 5×u16 | btn_map[512]×u16 | axes_map[64]×u8 | 6 pad
_CONFIG_FMT = f"={NAME_LEN}sx5H{MAX_BTNS}H{MAX_AXES}B6x"
assert struct.calcsize(_CONFIG_FMT) == CONFIG_STRUCT_SIZE


@dataclass(frozen=True)
class PadModel:
    """The virtual controller we expose to applications."""
    name: str
    vendor: int
    product: int
    version: int
    buttons: Tuple[int, ...]   # internal button index → evdev key code
    axes: Tuple[int, ...]      # internal axis index → evdev abs code


#: Linux xpad-driver presentation of an Xbox-360 controller.
XPAD_MODEL = PadModel(
    name="Microsoft X-Box 360 pad",
    vendor=0x045E, product=0x028E, version=0x0114,
    buttons=(BTN_A, BTN_B, BTN_X, BTN_Y, BTN_TL, BTN_TR,
             BTN_SELECT, BTN_START, BTN_MODE, BTN_THUMBL, BTN_THUMBR),
    axes=(ABS_X, ABS_Y, ABS_Z, ABS_RX, ABS_RY, ABS_RZ,
          ABS_HAT0X, ABS_HAT0Y),
)

# W3C standard-gamepad button index → internal button index
_W3C_BTN_TO_INTERNAL = {0: 0, 1: 1, 2: 2, 3: 3, 4: 4, 5: 5,
                        8: 6, 9: 7, 16: 8, 10: 9, 11: 10}
# W3C buttons 6/7 are the analog triggers → internal axes 2 (ABS_Z) / 5 (ABS_RZ)
_W3C_TRIGGER_TO_AXIS = {6: 2, 7: 5}
# W3C buttons 12-15 are the d-pad → (internal hat axis, direction)
_W3C_DPAD_TO_HAT = {12: (7, -1), 13: (7, 1), 14: (6, -1), 15: (6, 1)}
# W3C axes 0-3 are the sticks → internal axes 0,1 (left) 3,4 (right)
_W3C_AXIS_TO_INTERNAL = {0: 0, 1: 1, 2: 3, 3: 4}

_TRIGGER_AXES = frozenset({2, 5})
_HAT_AXES = frozenset({6, 7})


def pack_config(model: PadModel) -> bytes:
    name = model.name.encode("utf-8")[:NAME_LEN - 1]
    btn_map = list(model.buttons)[:MAX_BTNS]
    axes_map = list(model.axes)[:MAX_AXES]
    return struct.pack(
        _CONFIG_FMT, name,
        model.vendor, model.product, model.version,
        len(btn_map), len(axes_map),
        *(btn_map + [0] * (MAX_BTNS - len(btn_map))),
        *(axes_map + [0] * (MAX_AXES - len(axes_map))))


def pack_js_event(ev_type: int, number: int, value: int,
                  ts_ms: Optional[int] = None) -> bytes:
    if ts_ms is None:
        ts_ms = int(time.time() * 1000) & 0xFFFFFFFF
    return struct.pack("=IhBB", ts_ms, int(value), ev_type, number)


def pack_evdev_event(ev_type: int, code: int, value: int,
                     arch_bits: int = 64) -> bytes:
    """input_event + SYN_REPORT with arch-correct timeval width."""
    now = time.time()
    sec, usec = int(now), int((now % 1.0) * 1_000_000)
    fmt = "=qqHHi" if arch_bits == 64 else "=llHHi"
    return (struct.pack(fmt, sec, usec, ev_type, code, int(value)) +
            struct.pack(fmt, sec, usec, EV_SYN, SYN_REPORT, 0))


def normalize_axis(value: float, *, trigger: bool = False, hat: bool = False,
                   for_js: bool = False) -> int:
    """Client float → device int. Triggers 0..1, sticks -1..1, hats -1/0/1."""
    if hat:
        v = int(max(-1, min(1, round(value))))
        return v * AXIS_MAX if for_js else v
    if trigger:
        return int(AXIS_MIN + max(0.0, min(1.0, value)) * (AXIS_MAX - AXIS_MIN))
    v = max(-1.0, min(1.0, value))
    return int(AXIS_MIN + ((v + 1.0) / 2.0) * (AXIS_MAX - AXIS_MIN))


@dataclass
class MappedEvent:
    """One abstract device event, packable for either socket flavor."""
    is_button: bool
    index: int          # internal button/axis index (js `number` field)
    evdev_code: int
    value_js: int
    value_evdev: int

    def js_bytes(self) -> bytes:
        t = JS_EVENT_BUTTON if self.is_button else JS_EVENT_AXIS
        return pack_js_event(t, self.index, self.value_js)

    def evdev_bytes(self, arch_bits: int) -> bytes:
        t = EV_KEY if self.is_button else EV_ABS
        return pack_evdev_event(t, self.evdev_code, self.value_evdev,
                                arch_bits)


class GamepadMapper:
    """W3C standard-gamepad events → xpad-model device events."""

    def __init__(self, model: PadModel = XPAD_MODEL) -> None:
        self.model = model

    def map_button(self, w3c_index: int, value: float
                   ) -> Optional[MappedEvent]:
        if w3c_index in _W3C_TRIGGER_TO_AXIS:
            axis = _W3C_TRIGGER_TO_AXIS[w3c_index]
            return MappedEvent(
                is_button=False, index=axis,
                evdev_code=self.model.axes[axis],
                value_js=normalize_axis(value, trigger=True, for_js=True),
                value_evdev=normalize_axis(value, trigger=True))
        if w3c_index in _W3C_DPAD_TO_HAT:
            axis, direction = _W3C_DPAD_TO_HAT[w3c_index]
            hat = direction if value > 0.5 else 0
            return MappedEvent(
                is_button=False, index=axis,
                evdev_code=self.model.axes[axis],
                value_js=normalize_axis(hat, hat=True, for_js=True),
                value_evdev=normalize_axis(hat, hat=True))
        internal = _W3C_BTN_TO_INTERNAL.get(w3c_index)
        if internal is None or internal >= len(self.model.buttons):
            return None
        pressed = 1 if value > 0.5 else 0
        return MappedEvent(
            is_button=True, index=internal,
            evdev_code=self.model.buttons[internal],
            value_js=pressed, value_evdev=pressed)

    def map_axis(self, w3c_index: int, value: float) -> Optional[MappedEvent]:
        internal = _W3C_AXIS_TO_INTERNAL.get(w3c_index)
        if internal is None or internal >= len(self.model.axes):
            return None
        return MappedEvent(
            is_button=False, index=internal,
            evdev_code=self.model.axes[internal],
            value_js=normalize_axis(value, for_js=True),
            value_evdev=normalize_axis(value))


@dataclass
class _Client:
    writer: asyncio.StreamWriter
    arch_bits: int = 64


class VirtualGamepad:
    """One pad slot: mapper + js/evdev unix-socket servers + event fan-out."""

    def __init__(self, index: int, socket_dir: str = "/tmp",
                 model: PadModel = XPAD_MODEL) -> None:
        self.index = index
        self.js_path = os.path.join(socket_dir, f"selkies_js{index}.sock")
        self.ev_path = os.path.join(
            socket_dir, f"selkies_event{1000 + index}.sock")
        self.mapper = GamepadMapper(model)
        self.model = model
        self._config = pack_config(model)
        self._js_clients: List[_Client] = []
        self._ev_clients: List[_Client] = []
        self._servers: List[asyncio.base_events.Server] = []
        self._queue: asyncio.Queue = asyncio.Queue()
        self._pump_task: Optional[asyncio.Task] = None
        self.running = False

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self.running:
            return
        self.running = True
        for path, is_ev in ((self.js_path, False), (self.ev_path, True)):
            if os.path.exists(path):
                os.unlink(path)
            server = await asyncio.start_unix_server(
                lambda r, w, ev=is_ev: self._on_client(r, w, ev), path=path)
            self._servers.append(server)
        self._pump_task = asyncio.create_task(self._pump())
        logger.info("gamepad %d listening on %s / %s",
                    self.index, self.js_path, self.ev_path)

    async def stop(self) -> None:
        self.running = False
        for server in self._servers:
            server.close()
            await server.wait_closed()
        self._servers.clear()
        if self._pump_task:
            self._queue.put_nowait(None)
            try:
                await asyncio.wait_for(self._pump_task, timeout=2.0)
            except asyncio.TimeoutError:
                self._pump_task.cancel()
            self._pump_task = None
        for c in self._js_clients + self._ev_clients:
            c.writer.close()
        self._js_clients.clear()
        self._ev_clients.clear()
        for path in (self.js_path, self.ev_path):
            if os.path.exists(path):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    @property
    def client_count(self) -> int:
        return len(self._js_clients) + len(self._ev_clients)

    # -- socket handling ---------------------------------------------------

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter, is_ev: bool) -> None:
        clients = self._ev_clients if is_ev else self._js_clients
        client = _Client(writer)
        try:
            writer.write(self._config)
            await writer.drain()
            arch_byte = await reader.readexactly(1)
            client.arch_bits = arch_byte[0] * 8
            clients.append(client)
            logger.info("gamepad %d %s client connected (%d-bit)",
                        self.index, "evdev" if is_ev else "js",
                        client.arch_bits)
            while self.running and not writer.is_closing():
                # the interposer never writes again; poll for hangup
                try:
                    data = await asyncio.wait_for(reader.read(64), timeout=0.5)
                    if not data:
                        break
                except asyncio.TimeoutError:
                    continue
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            if client in clients:
                clients.remove(client)
            writer.close()

    # -- event path --------------------------------------------------------

    def send_button(self, w3c_index: int, value: float) -> None:
        ev = self.mapper.map_button(w3c_index, value)
        if ev is not None and self.running:
            self._queue.put_nowait(ev)

    def send_axis(self, w3c_index: int, value: float) -> None:
        ev = self.mapper.map_axis(w3c_index, value)
        if ev is not None and self.running:
            self._queue.put_nowait(ev)

    async def _pump(self) -> None:
        while self.running:
            ev = await self._queue.get()
            if ev is None:
                break
            js_data = ev.js_bytes()
            for c in list(self._js_clients):
                try:
                    c.writer.write(js_data)
                    await c.writer.drain()
                except ConnectionError:
                    if c in self._js_clients:
                        self._js_clients.remove(c)
            for c in list(self._ev_clients):
                try:
                    c.writer.write(ev.evdev_bytes(c.arch_bits))
                    await c.writer.drain()
                except ConnectionError:
                    if c in self._ev_clients:
                        self._ev_clients.remove(c)


class GamepadManager:
    """Lifecycle for up to ``num_slots`` virtual pads (reference: 4)."""

    def __init__(self, num_slots: int = 4, socket_dir: str = "/tmp") -> None:
        self.num_slots = num_slots
        self.socket_dir = socket_dir
        self.pads: Dict[int, VirtualGamepad] = {}

    async def connect(self, index: int, client_name: str = "",
                      num_btns: int = 17, num_axes: int = 4
                      ) -> Optional[VirtualGamepad]:
        if not (0 <= index < self.num_slots):
            logger.error("gamepad index %d out of range", index)
            return None
        pad = self.pads.get(index)
        if pad is None:
            pad = VirtualGamepad(index, self.socket_dir)
            self.pads[index] = pad
        if not pad.running:
            await pad.start()
        return pad

    async def disconnect(self, index: int) -> None:
        pad = self.pads.get(index)
        if pad is not None:
            await pad.stop()

    def send_button(self, index: int, w3c_index: int, value: float) -> None:
        pad = self.pads.get(index)
        if pad is not None:
            pad.send_button(w3c_index, value)

    def send_axis(self, index: int, w3c_index: int, value: float) -> None:
        pad = self.pads.get(index)
        if pad is not None:
            pad.send_axis(w3c_index, value)

    async def close(self) -> None:
        for pad in self.pads.values():
            await pad.stop()
        self.pads.clear()
