"""Clipboard bridging backends.

Reference behavior: read/write the X selection through ``xclip`` subprocesses
with optional binary (image) MIME targets, polled every 0.5 s for outbound
sync (input_handler.py:1313-1404).  Here the transport is a backend object:
``XclipClipboard`` shells out like the reference, ``MemoryClipboard`` is an
in-process store for tests and headless operation.
"""

from __future__ import annotations

import asyncio
import logging
import shutil
from typing import Optional, Tuple

logger = logging.getLogger("selkies_tpu.input.clipboard")

#: binary MIME types we will offer/accept, most-preferred first
BINARY_TARGETS = ("image/png", "image/jpeg", "image/webp", "image/bmp")


class ClipboardBackend:
    async def read(self, use_binary: bool = False
                   ) -> Tuple[Optional[bytes], str]:
        """Return (data, mime_type); data is None when empty/unavailable."""
        raise NotImplementedError

    async def write(self, data: bytes, mime_type: str = "text/plain") -> bool:
        raise NotImplementedError


class MemoryClipboard(ClipboardBackend):
    def __init__(self) -> None:
        self.data: bytes = b""
        self.mime_type: str = "text/plain"

    async def read(self, use_binary: bool = False
                   ) -> Tuple[Optional[bytes], str]:
        if not self.data:
            return None, "text/plain"
        if not use_binary and self.mime_type != "text/plain":
            return None, "text/plain"
        return self.data, self.mime_type

    async def write(self, data: bytes, mime_type: str = "text/plain") -> bool:
        self.data = bytes(data)
        self.mime_type = mime_type
        return True


class XclipClipboard(ClipboardBackend):
    """X selection via ``xclip`` subprocesses (same tool as the reference)."""

    def __init__(self, selection: str = "clipboard",
                 timeout: float = 2.0) -> None:
        if shutil.which("xclip") is None:
            raise RuntimeError("xclip not on PATH")
        self.selection = selection
        self.timeout = timeout

    async def _run(self, args, stdin_data: Optional[bytes] = None
                   ) -> Tuple[int, bytes]:
        proc = await asyncio.create_subprocess_exec(
            *args,
            stdin=asyncio.subprocess.PIPE if stdin_data is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL)
        try:
            out, _ = await asyncio.wait_for(
                proc.communicate(stdin_data), timeout=self.timeout)
        except asyncio.TimeoutError:
            proc.kill()
            return 1, b""
        return proc.returncode or 0, out or b""

    async def _targets(self) -> Tuple[str, ...]:
        rc, out = await self._run(
            ["xclip", "-selection", self.selection, "-o", "-t", "TARGETS"])
        if rc != 0:
            return ()
        return tuple(out.decode("ascii", "ignore").split())

    async def read(self, use_binary: bool = False
                   ) -> Tuple[Optional[bytes], str]:
        if use_binary:
            targets = await self._targets()
            for mime in BINARY_TARGETS:
                if mime in targets:
                    rc, out = await self._run(
                        ["xclip", "-selection", self.selection,
                         "-o", "-t", mime])
                    if rc == 0 and out:
                        return out, mime
        rc, out = await self._run(
            ["xclip", "-selection", self.selection, "-o"])
        if rc != 0 or not out:
            return None, "text/plain"
        return out, "text/plain"

    async def write(self, data: bytes, mime_type: str = "text/plain") -> bool:
        args = ["xclip", "-selection", self.selection, "-i"]
        if mime_type != "text/plain":
            args += ["-t", mime_type]
        rc, _ = await self._run(args, stdin_data=bytes(data))
        return rc == 0


def open_clipboard_backend() -> ClipboardBackend:
    try:
        return XclipClipboard()
    except Exception as e:
        logger.info("xclip unavailable (%s); using MemoryClipboard", e)
        return MemoryClipboard()
