"""Input plane: browser events → X11 injection + gamepad emulation.

Parity target: reference ``src/selkies/input_handler.py`` (1,726 LoC) — the
``kd/ku/kr/m/m2/p/js/c*`` wire grammar (input_handler.py:1507 on_message),
XTEST key/mouse injection, clipboard bridging, XFixes cursor monitoring, and
per-pad unix-socket gamepad servers speaking the C interposer protocol
(input_handler.py:118-760).  Fresh design: every OS touchpoint sits behind a
swappable backend (ctypes-dlopen X11, subprocess xclip, or in-memory fake),
so the full handler logic runs under tests with no display.
"""

from .clipboard import (ClipboardBackend, MemoryClipboard, XclipClipboard,
                        open_clipboard_backend)
from .cursor import (CursorImage, CursorMonitor, FakeCursorSource,
                     XFixesCursorSource, cursor_to_msg, open_cursor_source)
from .gamepad import (GamepadManager, GamepadMapper, PadModel, VirtualGamepad,
                      XPAD_MODEL, pack_config)
from .handler import InputHandler
from .keysyms import MODIFIER_KEYSYMS, keysym_to_char, keysym_to_name
from .x11 import FakeX11Backend, X11Backend, XTestBackend, open_x11_backend

__all__ = [
    "InputHandler",
    "MODIFIER_KEYSYMS", "keysym_to_name", "keysym_to_char",
    "X11Backend", "XTestBackend", "FakeX11Backend", "open_x11_backend",
    "ClipboardBackend", "MemoryClipboard", "XclipClipboard",
    "open_clipboard_backend",
    "CursorImage", "CursorMonitor", "FakeCursorSource", "XFixesCursorSource",
    "cursor_to_msg", "open_cursor_source",
    "GamepadManager", "GamepadMapper", "PadModel", "VirtualGamepad",
    "XPAD_MODEL", "pack_config",
]
