"""Cursor-shape monitoring (XFixes) → client ``cursor,{json}`` payloads.

Parity with the reference cursor monitor (input_handler.py:1407-1505):
watch XFixesDisplayCursorNotify, fetch the ARGB cursor image, crop to its
alpha bounding box, cap oversized cursors, and ship
``{curdata: <b64 png>, width, height, hotx, hoty, handle}``.

The X touchpoint is a swappable source; the PNG writer is self-contained
(zlib) so no imaging library is needed.
"""

from __future__ import annotations

import asyncio
import base64
import ctypes
import ctypes.util
import logging
import struct
import zlib
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger("selkies_tpu.input.cursor")


@dataclass
class CursorImage:
    width: int
    height: int
    xhot: int
    yhot: int
    serial: int
    rgba: bytes  # width*height*4, row-major RGBA


def encode_png_rgba(rgba: bytes, width: int, height: int) -> bytes:
    """Minimal RGBA PNG writer (filter 0 rows + zlib)."""
    raw = bytearray()
    stride = width * 4
    for y in range(height):
        raw.append(0)
        raw += rgba[y * stride:(y + 1) * stride]

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload +
                struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", width, height, 8, 6, 0, 0, 0)
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr) +
            chunk(b"IDAT", zlib.compress(bytes(raw), 6)) +
            chunk(b"IEND", b""))


def cursor_to_msg(cur: Optional[CursorImage],
                  size_cap: int = 64) -> Dict[str, Any]:
    """Crop/cap/encode a cursor image into the wire JSON dict."""
    empty = {"curdata": "", "width": 0, "height": 0, "hotx": 0, "hoty": 0,
             "handle": cur.serial if cur else 0}
    if cur is None or cur.width == 0 or cur.height == 0:
        return empty
    img = np.frombuffer(cur.rgba, np.uint8).reshape(cur.height, cur.width, 4)
    alpha = img[:, :, 3]
    ys, xs = np.nonzero(alpha)
    if ys.size == 0:
        return empty
    top, bottom = int(ys.min()), int(ys.max()) + 1
    left, right = int(xs.min()), int(xs.max()) + 1
    img = img[top:bottom, left:right]
    hotx, hoty = cur.xhot - left, cur.yhot - top
    h, w = img.shape[:2]
    if w > size_cap or h > size_cap:
        scale = size_cap / max(w, h)
        nw, nh = max(1, int(w * scale)), max(1, int(h * scale))
        yi = (np.arange(nh) * (h / nh)).astype(np.int64)
        xi = (np.arange(nw) * (w / nw)).astype(np.int64)
        img = img[yi][:, xi]
        hotx, hoty = int(hotx * scale), int(hoty * scale)
        w, h = nw, nh
    png = encode_png_rgba(np.ascontiguousarray(img).tobytes(), w, h)
    return {
        "curdata": base64.b64encode(png).decode("ascii"),
        "width": w, "height": h,
        "hotx": int(hotx), "hoty": int(hoty),
        "handle": cur.serial,
    }


# ---------------------------------------------------------------------------
# sources


class CursorSource:
    def get_cursor(self) -> Optional[CursorImage]:
        raise NotImplementedError

    def pending_change(self) -> bool:
        """True when a cursor-change notification is queued."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class FakeCursorSource(CursorSource):
    """Test source: set .cursor and flip .changed to simulate updates."""

    def __init__(self) -> None:
        self.cursor: Optional[CursorImage] = None
        self.changed = False

    def set_cursor(self, cur: CursorImage) -> None:
        self.cursor = cur
        self.changed = True

    def get_cursor(self) -> Optional[CursorImage]:
        return self.cursor

    def pending_change(self) -> bool:
        if self.changed:
            self.changed = False
            return True
        return False


class _XFixesCursorImage(ctypes.Structure):
    _fields_ = [
        ("x", ctypes.c_short), ("y", ctypes.c_short),
        ("width", ctypes.c_ushort), ("height", ctypes.c_ushort),
        ("xhot", ctypes.c_ushort), ("yhot", ctypes.c_ushort),
        ("cursor_serial", ctypes.c_ulong),
        # pixels are packed ARGB but each stored in an unsigned long
        ("pixels", ctypes.POINTER(ctypes.c_ulong)),
        ("atom", ctypes.c_ulong),
        ("name", ctypes.c_char_p),
    ]


XFIXES_DISPLAY_CURSOR_NOTIFY_MASK = 1 << 0


class XFixesCursorSource(CursorSource):
    """Live cursor shapes from the X server via dlopen'd libXfixes."""

    def __init__(self, display_name: Optional[str] = None) -> None:
        x11_path = ctypes.util.find_library("X11")
        xfixes_path = ctypes.util.find_library("Xfixes")
        if not x11_path or not xfixes_path:
            raise RuntimeError("libX11/libXfixes not available")
        self._x = ctypes.CDLL(x11_path)
        self._xf = ctypes.CDLL(xfixes_path)
        self._x.XOpenDisplay.restype = ctypes.c_void_p
        self._x.XOpenDisplay.argtypes = [ctypes.c_char_p]
        self._x.XDefaultRootWindow.restype = ctypes.c_ulong
        self._x.XDefaultRootWindow.argtypes = [ctypes.c_void_p]
        self._x.XPending.argtypes = [ctypes.c_void_p]
        self._x.XNextEvent.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        self._x.XFree.argtypes = [ctypes.c_void_p]
        self._xf.XFixesQueryExtension.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int)]
        self._xf.XFixesGetCursorImage.restype = \
            ctypes.POINTER(_XFixesCursorImage)
        self._xf.XFixesGetCursorImage.argtypes = [ctypes.c_void_p]
        self._xf.XFixesSelectCursorInput.argtypes = [
            ctypes.c_void_p, ctypes.c_ulong, ctypes.c_ulong]
        name = display_name.encode() if display_name else None
        self._dpy = self._x.XOpenDisplay(name)
        if not self._dpy:
            raise RuntimeError("cannot open X display")
        ev_base = ctypes.c_int()
        err_base = ctypes.c_int()
        if not self._xf.XFixesQueryExtension(
                self._dpy, ctypes.byref(ev_base), ctypes.byref(err_base)):
            raise RuntimeError("XFIXES extension missing")
        self._cursor_notify_event = ev_base.value + 1  # XFixesCursorNotify
        root = self._x.XDefaultRootWindow(self._dpy)
        self._xf.XFixesSelectCursorInput(
            self._dpy, root, XFIXES_DISPLAY_CURSOR_NOTIFY_MASK)

    def get_cursor(self) -> Optional[CursorImage]:
        img_ptr = self._xf.XFixesGetCursorImage(self._dpy)
        if not img_ptr:
            return None
        img = img_ptr.contents
        w, h = img.width, img.height
        n = w * h
        # unpack long-per-pixel ARGB → RGBA bytes
        px = np.ctypeslib.as_array(img.pixels, shape=(n,)).astype(np.uint32)
        rgba = np.empty((n, 4), np.uint8)
        rgba[:, 0] = (px >> 16) & 0xFF
        rgba[:, 1] = (px >> 8) & 0xFF
        rgba[:, 2] = px & 0xFF
        rgba[:, 3] = (px >> 24) & 0xFF
        out = CursorImage(w, h, img.xhot, img.yhot,
                          int(img.cursor_serial), rgba.tobytes())
        self._x.XFree(img_ptr)
        return out

    def pending_change(self) -> bool:
        saw = False
        while self._x.XPending(self._dpy) > 0:
            buf = ctypes.create_string_buffer(192)  # sizeof(XEvent)
            self._x.XNextEvent(self._dpy, buf)
            ev_type = struct.unpack_from("i", buf.raw, 0)[0]
            if ev_type == self._cursor_notify_event:
                saw = True
        return saw

    def close(self) -> None:
        if self._dpy:
            self._x.XCloseDisplay(self._dpy)
            self._dpy = None


class CursorMonitor:
    """Poll a source at ~50 Hz; emit payloads on serial change."""

    def __init__(self, source: CursorSource, on_cursor, size_cap: int = 64,
                 interval: float = 0.02) -> None:
        self.source = source
        self.on_cursor = on_cursor
        self.size_cap = size_cap
        self.interval = interval
        self._last_serial: Optional[int] = None
        self.running = False

    def _emit_current(self) -> None:
        cur = self.source.get_cursor()
        if cur is not None and cur.serial != self._last_serial:
            self._last_serial = cur.serial
            self.on_cursor(cursor_to_msg(cur, self.size_cap))

    async def run(self) -> None:
        self.running = True
        try:
            self._emit_current()
        except Exception as e:
            logger.warning("initial cursor fetch failed: %s", e)
        while self.running:
            try:
                if self.source.pending_change():
                    self._emit_current()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.debug("cursor poll error: %s", e)
            await asyncio.sleep(self.interval)

    def stop(self) -> None:
        self.running = False


def open_cursor_source() -> CursorSource:
    try:
        return XFixesCursorSource()
    except Exception as e:
        logger.info("XFixes unavailable (%s); using FakeCursorSource", e)
        return FakeCursorSource()
