"""Browser input message grammar → X11/gamepad/clipboard actions.

Grammar parity with the reference ``WebRTCInput.on_message``
(input_handler.py:1507-1697):

========  ===================================================================
verb      meaning
========  ===================================================================
pong      RTT probe reply
kd/ku     key down/up by X keysym (modifier tracking; non-alpha printables
          typed atomically to avoid stuck-modifier layouts)
kr        release-all keyboard reset
co,end,T  atomically type text T
m/m2      absolute/relative pointer: x,y,button_mask,scroll_magnitude
p         pointer-visibility toggle
vb/ab     video/audio encoder bitrate request
js        gamepad: c(onnect)/d(isconnect)/b(utton)/a(xis)
cw/cb     clipboard write text / binary (base64)
cr        clipboard read request → broadcast back
cws/cbs,  multipart clipboard write: start (text/binary), data chunk, end
cwd/cbd,
cwe/cbe
_arg_fps  set target framerate
_arg_resize  enable/disable manual resize
_f/_l     client-reported fps / latency
========  ===================================================================

All OS side effects go through injectable backends, so the whole grammar is
unit-testable headless.
"""

from __future__ import annotations

import asyncio
import base64
import io
import logging
import re
import time
from typing import Awaitable, Callable, Optional, Set

from .clipboard import ClipboardBackend, MemoryClipboard
from .gamepad import GamepadManager
from .keysyms import (MODIFIER_KEYSYMS, is_printable_keysym, is_unicode_keysym,
                      keysym_to_char)
from .x11 import FakeX11Backend, X11Backend

logger = logging.getLogger("selkies_tpu.input.handler")

KEYSYM_ALT_L = 0xFFE9
KEYSYM_LEFT = 0xFF51
KEYSYM_RIGHT = 0xFF53

# X core button numbers
BTN_LEFT, BTN_MIDDLE, BTN_RIGHT = 1, 2, 3
SCROLL_UP, SCROLL_DOWN, SCROLL_LEFT, SCROLL_RIGHT = 4, 5, 6, 7


class InputHandler:
    """Routes the client input grammar onto pluggable OS backends."""

    def __init__(
        self,
        backend: Optional[X11Backend] = None,
        clipboard: Optional[ClipboardBackend] = None,
        gamepads: Optional[GamepadManager] = None,
        data_server=None,
        enable_clipboard: str = "true",       # true|in|out|false
        enable_binary_clipboard: bool = True,
    ) -> None:
        self.backend = backend if backend is not None else FakeX11Backend()
        self.clipboard = clipboard if clipboard is not None else MemoryClipboard()
        self.gamepads = gamepads if gamepads is not None else GamepadManager()
        self.data_server = data_server
        self.enable_clipboard = enable_clipboard
        self.enable_binary_clipboard = enable_binary_clipboard

        # keyboard state
        self.active_modifiers: Set[int] = set()
        self.atomically_typed: Set[int] = set()
        self.pressed_keysyms: Set[int] = set()
        # mouse state
        self.button_mask = 0
        self.last_x = 0
        self.last_y = 0
        # ping state
        self.ping_start: Optional[float] = None
        # multipart clipboard receive state
        self._mp_buffer: Optional[io.BytesIO] = None
        self._mp_total = 0
        self._mp_mime = "text/plain"

        # callbacks (wired by main())
        self.on_ping_response: Callable[[float], None] = lambda ms: None
        self.on_pointer_visible: Callable[[bool], None] = lambda v: None
        self.on_video_bitrate: Callable[[int], None] = lambda kbps: None
        self.on_audio_bitrate: Callable[[int], None] = lambda kbps: None
        self.on_set_fps: Callable[[int], None] = lambda fps: None
        self.on_set_enable_resize: Callable[[bool, Optional[str]], None] = \
            lambda enabled, res: None
        self.on_client_fps: Callable[[int], None] = lambda fps: None
        self.on_client_latency: Callable[[int], None] = lambda ms: None
        self.on_clipboard_read: Callable[[bytes, str], Awaitable[None]]
        self.on_clipboard_read = self._default_clipboard_out

    async def _default_clipboard_out(self, data: bytes, mime: str) -> None:
        app = getattr(self.data_server, "app", None)
        if app is not None:
            await app.send_clipboard(
                data.decode("utf-8", "ignore") if mime == "text/plain"
                else data,
                mime_type=mime)

    # ------------------------------------------------------------------
    # dispatch

    async def on_message(self, msg: str, display_id: str = "primary") -> None:
        toks = msg.split(",")
        verb = toks[0]
        try:
            await self._dispatch(verb, toks, msg, display_id)
        except (IndexError, ValueError) as e:
            logger.warning("malformed input message %r: %s", msg[:80], e)

    async def _dispatch(self, verb, toks, msg, display_id) -> None:
        if verb == "pong":
            if self.ping_start is not None:
                rtt_ms = (time.monotonic() - self.ping_start) / 2 * 1000
                self.on_ping_response(round(rtt_ms, 3))
        elif verb == "kd":
            await self.key_down(int(toks[1]))
        elif verb == "ku":
            await self.key_up(int(toks[1]))
        elif verb == "kr":
            await self.reset_keyboard()
        elif verb == "co" and len(toks) > 2 and toks[1] == "end":
            # everything after "co,end," is literal text (may hold commas)
            self.backend.type_text(msg[7:])
        elif verb in ("m", "m2"):
            relative = verb == "m2"
            try:
                x, y, mask, scroll = (int(t) for t in toks[1:5])
            except (ValueError, IndexError):
                x = y = scroll = 0
                mask = self.button_mask
                relative = False
            await self.mouse(x, y, mask, scroll, relative, display_id)
        elif verb == "p":
            self.on_pointer_visible(bool(int(toks[1])))
        elif verb == "vb":
            self.on_video_bitrate(int(toks[1]))
        elif verb == "ab":
            self.on_audio_bitrate(int(toks[1]))
        elif verb == "js":
            await self._on_gamepad(toks)
        elif verb == "cw":
            await self._clipboard_write(
                base64.b64decode(toks[1]), "text/plain")
        elif verb == "cb":
            await self._clipboard_write(
                base64.b64decode(toks[2]), toks[1])
        elif verb == "cr":
            await self._clipboard_read_request()
        elif verb == "cws":
            self._multipart_start(int(toks[1]), "text/plain")
        elif verb == "cbs":
            self._multipart_start(int(toks[2]), toks[1])
        elif verb in ("cwd", "cbd"):
            if self._mp_buffer is not None:
                self._mp_buffer.write(base64.b64decode(toks[1]))
        elif verb in ("cwe", "cbe"):
            await self._multipart_end()
        elif verb == "_arg_fps":
            self.on_set_fps(int(toks[1]))
        elif verb == "_arg_resize":
            if len(toks) == 3:
                enabled = toks[1].lower() == "true"
                res = None
                if re.fullmatch(r"\d+x\d+", toks[2]):
                    w, h = (int(v) + int(v) % 2 for v in toks[2].split("x"))
                    res = f"{w}x{h}"
                self.on_set_enable_resize(enabled, res)
        elif verb == "_f":
            self.on_client_fps(int(toks[1]))
        elif verb == "_l":
            self.on_client_latency(int(toks[1]))
        else:
            logger.debug("unknown input verb %r", verb)

    # ------------------------------------------------------------------
    # keyboard

    async def key_down(self, keysym: int) -> None:
        if keysym in MODIFIER_KEYSYMS:
            self.active_modifiers.add(keysym)
        ch = keysym_to_char(keysym)
        if (is_printable_keysym(keysym) and not self.active_modifiers
                and ch is not None and not ch.isalpha()):
            # bare non-alpha printable: atomic type avoids layout-dependent
            # shift state corruption (reference input_handler.py:1520-1527)
            self.backend.type_text(ch)
            self.atomically_typed.add(keysym)
            return
        if self.backend.key(keysym, True):
            self.pressed_keysyms.add(keysym)

    async def key_up(self, keysym: int) -> None:
        if keysym in MODIFIER_KEYSYMS:
            self.active_modifiers.discard(keysym)
        if keysym in self.atomically_typed:
            self.atomically_typed.discard(keysym)
            return
        self.backend.key(keysym, False)
        self.pressed_keysyms.discard(keysym)

    async def reset_keyboard(self) -> None:
        for keysym in list(self.pressed_keysyms):
            self.backend.key(keysym, False)
        self.pressed_keysyms.clear()
        self.active_modifiers.clear()
        self.atomically_typed.clear()

    # ------------------------------------------------------------------
    # mouse

    def _display_offset(self, display_id: str):
        layouts = getattr(self.data_server, "display_layouts", None)
        if layouts:
            layout = layouts.get(display_id)
            if layout:
                return layout.get("x", 0), layout.get("y", 0)
        return 0, 0

    async def mouse(self, x: int, y: int, mask: int, scroll: int,
                    relative: bool, display_id: str = "primary") -> None:
        if relative:
            self.backend.pointer_move_relative(x, y)
        else:
            ox, oy = self._display_offset(display_id)
            fx, fy = x + ox, y + oy
            if fx != self.last_x or fy != self.last_y:
                self.backend.pointer_move(fx, fy)
            self.last_x, self.last_y = fx, fy

        if mask != self.button_mask:
            await self._apply_button_mask(mask, scroll)
            self.button_mask = mask
        self.backend.sync()

    async def _apply_button_mask(self, mask: int, scroll: int) -> None:
        for bit in range(8):
            flag = 1 << bit
            if (mask ^ self.button_mask) & flag == 0:
                continue
            pressed = bool(mask & flag)
            if bit == 0:
                self.backend.button(BTN_LEFT, pressed)
            elif bit == 1:
                self.backend.button(BTN_MIDDLE, pressed)
            elif bit == 2:
                self.backend.button(BTN_RIGHT, pressed)
            elif bit == 3:
                if scroll > 0:
                    if pressed:
                        self._click_n(SCROLL_UP, scroll)
                elif pressed:     # browser Back = Alt+Left
                    await self._combo(KEYSYM_ALT_L, KEYSYM_LEFT)
            elif bit == 4:
                if scroll > 0:
                    if pressed:
                        self._click_n(SCROLL_DOWN, scroll)
                elif pressed:     # browser Forward = Alt+Right
                    await self._combo(KEYSYM_ALT_L, KEYSYM_RIGHT)
            elif bit == 6:
                if scroll > 0 and pressed:
                    self._click_n(SCROLL_LEFT, scroll)
            elif bit == 7:
                if scroll > 0 and pressed:
                    self._click_n(SCROLL_RIGHT, scroll)

    def _click_n(self, button: int, count: int) -> None:
        for _ in range(max(1, count)):
            self.backend.button(button, True)
            self.backend.button(button, False)

    async def _combo(self, modifier: int, key: int) -> None:
        self.backend.key(modifier, True)
        self.backend.key(key, True)
        self.backend.key(key, False)
        self.backend.key(modifier, False)

    # ------------------------------------------------------------------
    # gamepad

    async def _on_gamepad(self, toks) -> None:
        cmd = toks[1]
        index = int(toks[2])
        if cmd == "c":
            try:
                name = base64.b64decode(toks[3]).decode("latin-1",
                                                        "ignore")[:255]
            except Exception:
                name = f"ClientGamepad{index}"
            num_axes, num_btns = int(toks[4]), int(toks[5])
            await self.gamepads.connect(index, name, num_btns, num_axes)
        elif cmd == "d":
            await self.gamepads.disconnect(index)
        elif cmd == "b":
            self.gamepads.send_button(index, int(toks[3]), float(toks[4]))
        elif cmd == "a":
            self.gamepads.send_axis(index, int(toks[3]), float(toks[4]))
        else:
            logger.debug("unknown gamepad cmd %r", cmd)

    # ------------------------------------------------------------------
    # clipboard

    def _clipboard_in_allowed(self) -> bool:
        return self.enable_clipboard in ("true", "in")

    def _clipboard_out_allowed(self) -> bool:
        return self.enable_clipboard in ("true", "out")

    async def _clipboard_write(self, data: bytes, mime: str) -> None:
        if not self._clipboard_in_allowed():
            logger.warning("inbound clipboard disabled; dropping write")
            return
        if mime != "text/plain" and not self.enable_binary_clipboard:
            logger.warning("binary clipboard disabled; dropping %s", mime)
            return
        await self.clipboard.write(data, mime)

    async def _clipboard_read_request(self) -> None:
        if not self._clipboard_out_allowed():
            logger.warning("outbound clipboard disabled; dropping read")
            return
        data, mime = await self.clipboard.read(
            use_binary=self.enable_binary_clipboard)
        if data:
            await self.on_clipboard_read(data, mime)

    def _multipart_start(self, total: int, mime: str) -> None:
        if not self._clipboard_in_allowed():
            logger.warning("inbound clipboard disabled; dropping multipart")
            return
        self._mp_buffer = io.BytesIO()
        self._mp_total = total
        self._mp_mime = mime

    async def _multipart_end(self) -> None:
        if self._mp_buffer is None:
            return
        data = self._mp_buffer.getvalue()
        self._mp_buffer = None
        if len(data) != self._mp_total:
            logger.error("multipart clipboard size mismatch: %d != %d",
                         len(data), self._mp_total)
            return
        await self._clipboard_write(data, self._mp_mime)

    # ------------------------------------------------------------------
    # outbound clipboard poll (reference: 0.5 s loop input_handler.py:1374)

    async def run_clipboard_poll(self, interval: float = 0.5) -> None:
        last: Optional[bytes] = None
        while True:
            try:
                if self._clipboard_out_allowed():
                    data, mime = await self.clipboard.read(
                        use_binary=self.enable_binary_clipboard)
                    if data and data != last:
                        last = data
                        await self.on_clipboard_read(data, mime)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.debug("clipboard poll error: %s", e)
            await asyncio.sleep(interval)

    async def ping(self, send: Callable[[str], Awaitable[None]]) -> None:
        self.ping_start = time.monotonic()
        await send("ping")

    async def close(self) -> None:
        await self.gamepads.close()
        self.backend.close()
