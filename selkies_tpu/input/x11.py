"""X11 injection backends.

The reference injects input through python-xlib XTEST plus ``xdotool``
subprocess fallbacks (input_handler.py:1063-1160, :1203-1297).  We get the
same capability without the python-xlib dependency by dlopen-ing
``libX11``/``libXtst`` through ctypes at runtime; when no X display is
reachable (tests, CI) a ``FakeX11Backend`` records the exact event stream so
handler logic is fully testable.

X button numbering (X11 core protocol): 1=left 2=middle 3=right 4=scroll-up
5=scroll-down 6=scroll-left 7=scroll-right 8=back 9=forward.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import threading
from typing import List, Optional, Tuple

from .keysyms import is_unicode_keysym, keysym_to_name

logger = logging.getLogger("selkies_tpu.input.x11")


class X11Backend:
    """Interface every injection backend implements."""

    def key(self, keysym: int, down: bool) -> bool:
        raise NotImplementedError

    def pointer_move(self, x: int, y: int) -> None:
        raise NotImplementedError

    def pointer_move_relative(self, dx: int, dy: int) -> None:
        raise NotImplementedError

    def button(self, button: int, down: bool) -> None:
        raise NotImplementedError

    def type_text(self, text: str) -> bool:
        """Atomically type printable text (clears/ignores held modifiers)."""
        raise NotImplementedError

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# ctypes XTEST backend


class XTestBackend(X11Backend):
    """Direct XTEST injection via dlopen'd libX11 + libXtst.

    Unicode/unbound keysyms are handled the way xdotool does it: a spare
    keycode (one whose keysym column is empty) is temporarily rebound with
    ``XChangeKeyboardMapping`` and pressed, then released back.
    """

    def __init__(self, display_name: Optional[str] = None) -> None:
        x11_path = ctypes.util.find_library("X11")
        xtst_path = ctypes.util.find_library("Xtst")
        if not x11_path or not xtst_path:
            raise RuntimeError("libX11/libXtst not available")
        self._x = ctypes.CDLL(x11_path)
        self._xtst = ctypes.CDLL(xtst_path)
        self._configure_prototypes()
        name = display_name.encode() if display_name else None
        self._dpy = self._x.XOpenDisplay(name)
        if not self._dpy:
            raise RuntimeError("cannot open X display")
        ev = ctypes.c_int()
        err = ctypes.c_int()
        maj = ctypes.c_int()
        mnr = ctypes.c_int()
        if not self._xtst.XTestQueryExtension(
                self._dpy, ctypes.byref(ev), ctypes.byref(err),
                ctypes.byref(maj), ctypes.byref(mnr)):
            self._x.XCloseDisplay(self._dpy)
            raise RuntimeError("XTEST extension missing")
        self._lock = threading.Lock()
        kc_lo = ctypes.c_int()
        kc_hi = ctypes.c_int()
        self._x.XDisplayKeycodes(
            self._dpy, ctypes.byref(kc_lo), ctypes.byref(kc_hi))
        self._kc_lo, self._kc_hi = kc_lo.value, kc_hi.value
        self._spare_keycode = self._find_spare_keycode()
        self._spare_bound: Optional[int] = None

    def _configure_prototypes(self) -> None:
        x = self._x
        x.XOpenDisplay.restype = ctypes.c_void_p
        x.XOpenDisplay.argtypes = [ctypes.c_char_p]
        x.XCloseDisplay.argtypes = [ctypes.c_void_p]
        x.XFlush.argtypes = [ctypes.c_void_p]
        x.XSync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        x.XKeysymToKeycode.restype = ctypes.c_ubyte
        x.XKeysymToKeycode.argtypes = [ctypes.c_void_p, ctypes.c_ulong]
        x.XDisplayKeycodes.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int)]
        x.XGetKeyboardMapping.restype = ctypes.POINTER(ctypes.c_ulong)
        x.XGetKeyboardMapping.argtypes = [
            ctypes.c_void_p, ctypes.c_ubyte, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        x.XChangeKeyboardMapping.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ulong), ctypes.c_int]
        x.XFree.argtypes = [ctypes.c_void_p]
        x.XStringToKeysym.restype = ctypes.c_ulong
        x.XStringToKeysym.argtypes = [ctypes.c_char_p]
        t = self._xtst
        t.XTestQueryExtension.argtypes = [
            ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_int)] * 4
        t.XTestFakeKeyEvent.argtypes = [
            ctypes.c_void_p, ctypes.c_uint, ctypes.c_int, ctypes.c_ulong]
        t.XTestFakeButtonEvent.argtypes = [
            ctypes.c_void_p, ctypes.c_uint, ctypes.c_int, ctypes.c_ulong]
        t.XTestFakeMotionEvent.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_ulong]
        t.XTestFakeRelativeMotionEvent.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_ulong]

    # -- keycode management ----------------------------------------------

    def _find_spare_keycode(self) -> int:
        n = ctypes.c_int()
        count = self._kc_hi - self._kc_lo + 1
        syms = self._x.XGetKeyboardMapping(
            self._dpy, self._kc_lo, count, ctypes.byref(n))
        spare = 0
        if syms:
            per = n.value
            for i in range(count - 1, -1, -1):
                if all(syms[i * per + j] == 0 for j in range(per)):
                    spare = self._kc_lo + i
                    break
            self._x.XFree(syms)
        return spare

    def _bind_spare(self, keysym: int) -> int:
        if not self._spare_keycode:
            return 0
        if self._spare_bound != keysym:
            arr = (ctypes.c_ulong * 2)(keysym, keysym)
            self._x.XChangeKeyboardMapping(
                self._dpy, self._spare_keycode, 2, arr, 1)
            self._x.XSync(self._dpy, 0)
            self._spare_bound = keysym
        return self._spare_keycode

    def _keysym_to_keycode(self, keysym: int) -> int:
        # Unicode keysyms carry the 0x01000000 flag; the X server stores
        # them the same way, so try the direct lookup first.
        kc = self._x.XKeysymToKeycode(self._dpy, keysym)
        if kc:
            return kc
        if is_unicode_keysym(keysym):
            # Latin-1 codepoints double as legacy keysyms.
            cp = keysym & 0x00FFFFFF
            if cp <= 0xFF:
                kc = self._x.XKeysymToKeycode(self._dpy, cp)
                if kc:
                    return kc
        return self._bind_spare(keysym)

    # -- backend interface -------------------------------------------------

    def key(self, keysym: int, down: bool) -> bool:
        with self._lock:
            kc = self._keysym_to_keycode(keysym)
            if not kc:
                return False
            self._xtst.XTestFakeKeyEvent(self._dpy, kc, int(down), 0)
            self._x.XFlush(self._dpy)
            return True

    def pointer_move(self, x: int, y: int) -> None:
        with self._lock:
            self._xtst.XTestFakeMotionEvent(self._dpy, -1, x, y, 0)
            self._x.XFlush(self._dpy)

    def pointer_move_relative(self, dx: int, dy: int) -> None:
        with self._lock:
            self._xtst.XTestFakeRelativeMotionEvent(self._dpy, dx, dy, 0)
            self._x.XFlush(self._dpy)

    def button(self, button: int, down: bool) -> None:
        with self._lock:
            self._xtst.XTestFakeButtonEvent(self._dpy, button, int(down), 0)
            self._x.XFlush(self._dpy)

    def type_text(self, text: str) -> bool:
        ok = True
        for ch in text:
            keysym = ord(ch) if ord(ch) <= 0xFF else 0x01000000 | ord(ch)
            ok = self.key(keysym, True) and ok
            ok = self.key(keysym, False) and ok
        return ok

    def sync(self) -> None:
        with self._lock:
            self._x.XSync(self._dpy, 0)

    def close(self) -> None:
        with self._lock:
            if self._dpy:
                self._x.XCloseDisplay(self._dpy)
                self._dpy = None


# ---------------------------------------------------------------------------
# fake backend (tests / headless)


class FakeX11Backend(X11Backend):
    """Records the injected event stream; always succeeds."""

    def __init__(self) -> None:
        self.events: List[Tuple] = []
        self.synced = 0

    def key(self, keysym: int, down: bool) -> bool:
        self.events.append(("key", keysym, down))
        return True

    def pointer_move(self, x: int, y: int) -> None:
        self.events.append(("move", x, y))

    def pointer_move_relative(self, dx: int, dy: int) -> None:
        self.events.append(("rel", dx, dy))

    def button(self, button: int, down: bool) -> None:
        self.events.append(("button", button, down))

    def type_text(self, text: str) -> bool:
        self.events.append(("type", text))
        return True

    def sync(self) -> None:
        self.synced += 1

    # test helpers
    def clear(self) -> None:
        self.events.clear()

    def keys_pressed(self) -> List[int]:
        return [ks for kind, ks, down in self.events
                if kind == "key" and down]


def open_x11_backend(display_name: Optional[str] = None) -> X11Backend:
    """Real XTEST backend when a display is reachable, fake otherwise."""
    try:
        return XTestBackend(display_name)
    except Exception as e:
        logger.info("X display unavailable (%s); using FakeX11Backend", e)
        return FakeX11Backend()


def xkey_name_for(keysym: int) -> Optional[str]:
    return keysym_to_name(keysym)
