"""X11 keysym database for key injection.

Role parity with the reference's ``server_keysym_map.py`` (1,537 LoC data
table mapping keysym → X key name).  Instead of a hand-maintained table we
assemble the map programmatically from the well-known X11 ``keysymdef.h``
ranges: Latin-1 keysyms are their own codepoints (0x20-0xFF), Unicode
keysyms are ``0x01000000 | codepoint``, and the function/TTY/keypad/modifier
blocks (0xFF00-0xFFFF) are enumerated below by name.

``keysym_to_name(ks)`` returns the X key name usable with ``xdotool key`` /
``XStringToKeysym``; ``keysym_to_char(ks)`` returns the printable character,
if any.
"""

from __future__ import annotations

from typing import Dict, Optional

# -- function / control keysym block (0xFF00-0xFFFF + misc) ------------------

_NAMED: Dict[int, str] = {
    0xFF08: "BackSpace",
    0xFF09: "Tab",
    0xFF0A: "Linefeed",
    0xFF0B: "Clear",
    0xFF0D: "Return",
    0xFF13: "Pause",
    0xFF14: "Scroll_Lock",
    0xFF15: "Sys_Req",
    0xFF1B: "Escape",
    0xFFFF: "Delete",
    # international
    0xFF20: "Multi_key",
    0xFF37: "Codeinput",
    0xFF3C: "SingleCandidate",
    0xFF3D: "MultipleCandidate",
    0xFF3E: "PreviousCandidate",
    # japanese
    0xFF21: "Kanji",
    0xFF22: "Muhenkan",
    0xFF23: "Henkan_Mode",
    0xFF24: "Romaji",
    0xFF25: "Hiragana",
    0xFF26: "Katakana",
    0xFF27: "Hiragana_Katakana",
    0xFF28: "Zenkaku",
    0xFF29: "Hankaku",
    0xFF2A: "Zenkaku_Hankaku",
    0xFF2B: "Touroku",
    0xFF2C: "Massyo",
    0xFF2D: "Kana_Lock",
    0xFF2E: "Kana_Shift",
    0xFF2F: "Eisu_Shift",
    0xFF30: "Eisu_toggle",
    # cursor
    0xFF50: "Home",
    0xFF51: "Left",
    0xFF52: "Up",
    0xFF53: "Right",
    0xFF54: "Down",
    0xFF55: "Prior",  # Page_Up
    0xFF56: "Next",   # Page_Down
    0xFF57: "End",
    0xFF58: "Begin",
    # misc functions
    0xFF60: "Select",
    0xFF61: "Print",
    0xFF62: "Execute",
    0xFF63: "Insert",
    0xFF65: "Undo",
    0xFF66: "Redo",
    0xFF67: "Menu",
    0xFF68: "Find",
    0xFF69: "Cancel",
    0xFF6A: "Help",
    0xFF6B: "Break",
    0xFF7E: "Mode_switch",
    0xFF7F: "Num_Lock",
    # keypad
    0xFF80: "KP_Space",
    0xFF89: "KP_Tab",
    0xFF8D: "KP_Enter",
    0xFF91: "KP_F1",
    0xFF92: "KP_F2",
    0xFF93: "KP_F3",
    0xFF94: "KP_F4",
    0xFF95: "KP_Home",
    0xFF96: "KP_Left",
    0xFF97: "KP_Up",
    0xFF98: "KP_Right",
    0xFF99: "KP_Down",
    0xFF9A: "KP_Prior",
    0xFF9B: "KP_Next",
    0xFF9C: "KP_End",
    0xFF9D: "KP_Begin",
    0xFF9E: "KP_Insert",
    0xFF9F: "KP_Delete",
    0xFFBD: "KP_Equal",
    0xFFAA: "KP_Multiply",
    0xFFAB: "KP_Add",
    0xFFAC: "KP_Separator",
    0xFFAD: "KP_Subtract",
    0xFFAE: "KP_Decimal",
    0xFFAF: "KP_Divide",
    # modifiers
    0xFFE1: "Shift_L",
    0xFFE2: "Shift_R",
    0xFFE3: "Control_L",
    0xFFE4: "Control_R",
    0xFFE5: "Caps_Lock",
    0xFFE6: "Shift_Lock",
    0xFFE7: "Meta_L",
    0xFFE8: "Meta_R",
    0xFFE9: "Alt_L",
    0xFFEA: "Alt_R",
    0xFFEB: "Super_L",
    0xFFEC: "Super_R",
    0xFFED: "Hyper_L",
    0xFFEE: "Hyper_R",
    # ISO extensions
    0xFE03: "ISO_Level3_Shift",
    0xFE04: "ISO_Level3_Latch",
    0xFE08: "ISO_Level5_Shift",
    0xFE20: "ISO_Left_Tab",
    0xFE50: "dead_grave",
    0xFE51: "dead_acute",
    0xFE52: "dead_circumflex",
    0xFE53: "dead_tilde",
    0xFE54: "dead_macron",
    0xFE55: "dead_breve",
    0xFE56: "dead_abovedot",
    0xFE57: "dead_diaeresis",
    0xFE58: "dead_abovering",
    0xFE59: "dead_doubleacute",
    0xFE5A: "dead_caron",
    0xFE5B: "dead_cedilla",
    0xFE5C: "dead_ogonek",
    0xFE5D: "dead_iota",
}

# F1-F35 (0xFFBE..0xFFE0)
for _i in range(35):
    _NAMED[0xFFBE + _i] = f"F{_i + 1}"
# KP_0..KP_9 (0xFFB0..0xFFB9)
for _i in range(10):
    _NAMED[0xFFB0 + _i] = f"KP_{_i}"

# XF86 multimedia keys commonly sent by browsers
_XF86: Dict[int, str] = {
    0x1008FF11: "XF86AudioLowerVolume",
    0x1008FF12: "XF86AudioMute",
    0x1008FF13: "XF86AudioRaiseVolume",
    0x1008FF14: "XF86AudioPlay",
    0x1008FF15: "XF86AudioStop",
    0x1008FF16: "XF86AudioPrev",
    0x1008FF17: "XF86AudioNext",
    0x1008FF18: "XF86HomePage",
    0x1008FF19: "XF86Mail",
    0x1008FF26: "XF86Back",
    0x1008FF27: "XF86Forward",
    0x1008FF2A: "XF86PowerOff",
    0x1008FF2F: "XF86Sleep",
    0x1008FF30: "XF86Favorites",
    0x1008FF31: "XF86AudioPause",
    0x1008FF41: "XF86Launch1",
    0x1008FF73: "XF86Reload",
    0x1008FF74: "XF86Search",
}
_NAMED.update(_XF86)

# Latin-1 punctuation/symbol key names (needed for xdotool by-name paths)
_LATIN1_NAMES: Dict[int, str] = {
    0x20: "space", 0x21: "exclam", 0x22: "quotedbl", 0x23: "numbersign",
    0x24: "dollar", 0x25: "percent", 0x26: "ampersand", 0x27: "apostrophe",
    0x28: "parenleft", 0x29: "parenright", 0x2A: "asterisk", 0x2B: "plus",
    0x2C: "comma", 0x2D: "minus", 0x2E: "period", 0x2F: "slash",
    0x3A: "colon", 0x3B: "semicolon", 0x3C: "less", 0x3D: "equal",
    0x3E: "greater", 0x3F: "question", 0x40: "at",
    0x5B: "bracketleft", 0x5C: "backslash", 0x5D: "bracketright",
    0x5E: "asciicircum", 0x5F: "underscore", 0x60: "grave",
    0x7B: "braceleft", 0x7C: "bar", 0x7D: "braceright", 0x7E: "asciitilde",
    0xA3: "sterling", 0xA7: "section", 0xB0: "degree", 0xB4: "acute",
    0xB5: "mu", 0xB7: "periodcentered", 0xBF: "questiondown",
    0xDF: "ssharp", 0xE9: "eacute", 0xE8: "egrave", 0xE7: "ccedilla",
    0xE0: "agrave", 0xF9: "ugrave",
}

MODIFIER_KEYSYMS = frozenset({
    0xFFE1, 0xFFE2,  # Shift
    0xFFE3, 0xFFE4,  # Control
    0xFFE5,          # Caps_Lock
    0xFFE7, 0xFFE8,  # Meta
    0xFFE9, 0xFFEA,  # Alt
    0xFFEB, 0xFFEC,  # Super
    0xFFED, 0xFFEE,  # Hyper
    0xFE03, 0xFE04, 0xFE08,  # ISO level shifts
})

#: names that act as shortcut modifiers for xdotool --clearmodifiers logic
SHORTCUT_MODIFIER_NAMES = frozenset({
    "Shift_L", "Shift_R", "Control_L", "Control_R",
    "Alt_L", "Alt_R", "Meta_L", "Meta_R", "Super_L", "Super_R",
})

UNICODE_KEYSYM_FLAG = 0x01000000


def is_unicode_keysym(keysym: int) -> bool:
    return (keysym & 0xFF000000) == UNICODE_KEYSYM_FLAG


def is_printable_keysym(keysym: int) -> bool:
    """Matches the reference's printable test (input_handler.py:1516)."""
    return (0x20 <= keysym <= 0xFF) or is_unicode_keysym(keysym)


def keysym_to_char(keysym: int) -> Optional[str]:
    """The character a keysym produces, or None for function keys."""
    if is_unicode_keysym(keysym):
        cp = keysym & 0x00FFFFFF
    elif 0x20 <= keysym <= 0xFF:
        cp = keysym
    else:
        return None
    try:
        return chr(cp)
    except ValueError:
        return None


def keysym_to_name(keysym: int) -> Optional[str]:
    """X key name for ``xdotool key`` / ``XStringToKeysym``.

    Unicode keysyms render as ``U<HEX>`` which xdotool accepts directly.
    """
    name = _NAMED.get(keysym)
    if name:
        return name
    if is_unicode_keysym(keysym):
        return f"U{keysym & 0x00FFFFFF:04X}"
    if 0x20 <= keysym <= 0xFF:
        name = _LATIN1_NAMES.get(keysym)
        if name:
            return name
        ch = chr(keysym)
        if ch.isalnum():
            return ch
        return f"U{keysym:04X}"
    return None
