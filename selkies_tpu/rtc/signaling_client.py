"""In-process signaling client (gst-examples grammar).

Parity: ``legacy/webrtc_signalling.py`` — HELLO registration, SESSION
setup, JSON ``{"sdp": ...}`` / ``{"ice": ...}`` relay, callback surface
(`on_connect`, `on_session`, `on_sdp`, `on_ice`, `on_error`,
`on_disconnect`).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import ssl
from typing import Awaitable, Callable, Optional, Union

import websockets
import websockets.asyncio.client

logger = logging.getLogger("selkies_tpu.rtc.signaling_client")

MaybeAsync = Union[None, Awaitable[None]]


class SignalingError(Exception):
    pass


class SignalingNoPeerError(SignalingError):
    pass


async def _call(cb: Optional[Callable], *args) -> None:
    if cb is None:
        return
    result = cb(*args)
    if asyncio.iscoroutine(result):
        await result


class SignalingClient:
    def __init__(
        self,
        server: str,
        uid: str,
        peer_id: Optional[str] = None,
        meta: Optional[dict] = None,
        enable_https: bool = False,
        basic_auth_user: Optional[str] = None,
        basic_auth_password: Optional[str] = None,
        retry_interval: float = 2.0,
    ):
        self.server = server
        self.uid = str(uid)
        self.peer_id = str(peer_id) if peer_id is not None else None
        self.meta = meta
        self.enable_https = enable_https
        self.basic_auth_user = basic_auth_user
        self.basic_auth_password = basic_auth_password
        self.retry_interval = retry_interval
        self.conn = None

        self.on_connect: Optional[Callable[[], MaybeAsync]] = None
        self.on_disconnect: Optional[Callable[[], MaybeAsync]] = None
        self.on_session: Optional[Callable[[Optional[str], dict], MaybeAsync]] = None
        self.on_sdp: Optional[Callable[[str, str], MaybeAsync]] = None
        self.on_ice: Optional[Callable[[int, str], MaybeAsync]] = None
        self.on_error: Optional[Callable[[Exception], MaybeAsync]] = None

    async def connect(self) -> None:
        sslctx = None
        if self.enable_https:
            sslctx = ssl.create_default_context(purpose=ssl.Purpose.SERVER_AUTH)
            sslctx.check_hostname = False
            sslctx.verify_mode = ssl.CERT_NONE
        headers = None
        if self.basic_auth_user is not None:
            auth64 = base64.b64encode(
                f"{self.basic_auth_user}:{self.basic_auth_password or ''}".encode()
            ).decode()
            headers = [("Authorization", f"Basic {auth64}")]
        while True:
            try:
                self.conn = await websockets.asyncio.client.connect(
                    self.server, additional_headers=headers, ssl=sslctx
                )
                break
            except ConnectionRefusedError:
                await asyncio.sleep(self.retry_interval)
        hello = f"HELLO {self.uid}"
        if self.meta:
            hello += " " + base64.b64encode(json.dumps(self.meta).encode()).decode()
        await self.conn.send(hello)

    async def setup_call(self) -> None:
        await self.conn.send(f"SESSION {self.peer_id}")

    async def send_sdp(self, sdp_type: str, sdp: str) -> None:
        await self.conn.send(json.dumps({"sdp": {"type": sdp_type, "sdp": sdp}}))

    async def send_ice(self, mlineindex: int, candidate: str) -> None:
        await self.conn.send(
            json.dumps({"ice": {"candidate": candidate, "sdpMLineIndex": mlineindex}})
        )

    async def send_raw(self, msg: str) -> None:
        await self.conn.send(msg)

    async def stop(self) -> None:
        if self.conn is not None:
            await self.conn.close()

    async def start(self) -> None:
        try:
            async for message in self.conn:
                await self._dispatch(message)
        except websockets.exceptions.ConnectionClosed:
            pass
        await _call(self.on_disconnect)

    async def _dispatch(self, message: str) -> None:
        if message == "HELLO":
            await _call(self.on_connect)
        elif message.startswith("SESSION_OK"):
            toks = message.split()
            meta = json.loads(base64.b64decode(toks[1])) if len(toks) > 1 else {}
            await _call(self.on_session, self.peer_id, meta)
        elif message.startswith("ERROR"):
            if "not found" in message:
                await _call(self.on_error, SignalingNoPeerError(message))
            else:
                await _call(self.on_error, SignalingError(message))
        else:
            try:
                data = json.loads(message)
            except json.JSONDecodeError:
                await _call(self.on_error, SignalingError(f"bad JSON: {message!r}"))
                return
            if data.get("sdp"):
                await _call(self.on_sdp, data["sdp"].get("type"), data["sdp"].get("sdp"))
            elif data.get("ice"):
                await _call(
                    self.on_ice, data["ice"].get("sdpMLineIndex"), data["ice"].get("candidate")
                )
            else:
                await _call(self.on_error, SignalingError(f"unhandled message: {message!r}"))
