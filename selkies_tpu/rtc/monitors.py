"""Periodic RTC-config sources.

Each monitor owns one way of obtaining an RTC config (local HMAC minting, a
turn-rest endpoint, a JSON file on disk) and invokes
``on_rtc_config(stun_servers, turn_servers, rtc_config_json)`` whenever a
fresh config is available.

Parity: ``legacy/webrtc.py:62-185`` (HMACRTCMonitor / RESTRTCMonitor /
RTCConfigFileMonitor). Design differences from the reference, on purpose:

  * the reference busy-polls ``time.time() % period == 0`` every 0.5 s;
    we sleep the period directly and fire immediately on start so consumers
    have a config before the first session.
  * the file monitor uses mtime polling instead of a watchdog observer
    (no inotify dependency; 1 s resolution is ample for a config file).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Awaitable, Callable, List, Optional, Union

from .turn import fetch_turn_rest, generate_rtc_config, parse_rtc_config

logger = logging.getLogger("selkies_tpu.rtc.monitors")

RTCConfigCallback = Callable[[List[str], List[str], str], Union[None, Awaitable[None]]]


async def _emit(cb: Optional[RTCConfigCallback], stun, turn, cfg) -> None:
    if cb is None:
        logger.warning("unhandled on_rtc_config")
        return
    result = cb(stun, turn, cfg)
    if asyncio.iscoroutine(result):
        await result


class _PeriodicMonitor:
    """Shared run loop: produce a config now, then every ``period`` seconds."""

    def __init__(self, period: float = 60.0, enabled: bool = True):
        self.period = period
        self.enabled = enabled
        self.running = False
        self.on_rtc_config: Optional[RTCConfigCallback] = None

    async def _produce(self):  # -> (stun_uris, turn_uris, rtc_config_json)
        raise NotImplementedError

    async def start(self) -> None:
        if not self.enabled:
            return
        self.running = True
        while self.running:
            try:
                stun, turn, cfg = await self._produce()
                await _emit(self.on_rtc_config, stun, turn, cfg)
            except Exception as exc:
                logger.warning("RTC config monitor fetch failed: %s", exc)
            # sleep in small slices so stop() takes effect promptly
            remaining = self.period
            while self.running and remaining > 0:
                step = min(0.25, remaining)
                await asyncio.sleep(step)
                remaining -= step

    async def stop(self) -> None:
        self.running = False


class HMACRTCMonitor(_PeriodicMonitor):
    """Re-mints coturn HMAC credentials locally on a timer."""

    def __init__(
        self,
        turn_host: str,
        turn_port,
        turn_shared_secret: str,
        turn_username: str,
        turn_protocol: str = "udp",
        turn_tls: bool = False,
        stun_host: Optional[str] = None,
        stun_port=None,
        period: float = 60.0,
        enabled: bool = True,
    ):
        super().__init__(period, enabled)
        self.turn_host = turn_host
        self.turn_port = turn_port
        self.turn_shared_secret = turn_shared_secret
        self.turn_username = turn_username
        self.turn_protocol = turn_protocol
        self.turn_tls = turn_tls
        self.stun_host = stun_host
        self.stun_port = stun_port

    async def _produce(self):
        cfg = generate_rtc_config(
            self.turn_host,
            self.turn_port,
            self.turn_shared_secret,
            self.turn_username,
            self.turn_protocol,
            self.turn_tls,
            self.stun_host,
            self.stun_port,
        )
        return parse_rtc_config(cfg)


class RESTRTCMonitor(_PeriodicMonitor):
    """Polls a turn-rest endpoint for fresh credentials."""

    def __init__(
        self,
        turn_rest_uri: str,
        turn_rest_username: str,
        turn_rest_username_auth_header: str = "x-auth-user",
        turn_protocol: str = "udp",
        turn_rest_protocol_header: str = "x-turn-protocol",
        turn_tls: bool = False,
        turn_rest_tls_header: str = "x-turn-tls",
        period: float = 60.0,
        enabled: bool = True,
    ):
        super().__init__(period, enabled)
        self.turn_rest_uri = turn_rest_uri
        self.turn_rest_username = turn_rest_username.replace(":", "-")
        self.turn_rest_username_auth_header = turn_rest_username_auth_header
        self.turn_protocol = turn_protocol
        self.turn_rest_protocol_header = turn_rest_protocol_header
        self.turn_tls = turn_tls
        self.turn_rest_tls_header = turn_rest_tls_header

    async def _produce(self):
        return await asyncio.to_thread(
            fetch_turn_rest,
            self.turn_rest_uri,
            self.turn_rest_username,
            self.turn_rest_username_auth_header,
            self.turn_protocol,
            self.turn_rest_protocol_header,
            self.turn_tls,
            self.turn_rest_tls_header,
        )


class RTCConfigFileMonitor:
    """Watches an RTC-config JSON file by mtime; fires on start and on change."""

    def __init__(self, rtc_file: str, enabled: bool = True, poll_interval: float = 1.0):
        self.rtc_file = rtc_file
        self.enabled = enabled
        self.poll_interval = poll_interval
        self.running = False
        self.on_rtc_config: Optional[RTCConfigCallback] = None
        self._last_mtime: Optional[float] = None

    async def _read_and_emit(self) -> None:
        try:
            with open(self.rtc_file, "rb") as f:
                data = f.read()
            stun, turn, cfg = parse_rtc_config(data)
        except Exception as exc:
            logger.warning("could not read RTC config file %s: %s", self.rtc_file, exc)
            return
        await _emit(self.on_rtc_config, stun, turn, cfg)

    async def start(self) -> None:
        if not self.enabled:
            return
        self.running = True
        while self.running:
            try:
                mtime = os.stat(self.rtc_file).st_mtime
            except OSError:
                mtime = None
            if mtime is not None and mtime != self._last_mtime:
                self._last_mtime = mtime
                await self._read_and_emit()
            await asyncio.sleep(self.poll_interval)

    async def stop(self) -> None:
        self.running = False
