"""Combined HTTP static-file + WebSocket signaling server.

Speaks the gst-examples signaling grammar the reference uses
(``legacy/signalling_web.py:326-520``):

  client → ``HELLO <uid> [meta_b64]``          register
  server → ``HELLO``                           ack
  client → ``SESSION <peer_id>``               request 1:1 session
  server → ``SESSION_OK [meta_b64]``           both peers now relay-only
  client → ``ROOM <room_id>`` /
           ``ROOM_PEER_MSG <peer> <msg>``      multi-party rooms
  server → ``ROOM_OK <peers>`` / ``ROOM_PEER_JOINED/LEFT <uid>``
  anything else inside a session is relayed verbatim to the paired peer.

HTTP side (same socket, via websockets' ``process_request``):
  ``/health``   liveness;  ``/turn``  RTC config JSON (HMAC-minted per
  request when a shared secret is set, else a static config);  any other
  path is served from ``web_root`` with path-traversal containment and
  optional basic auth — reference ``legacy/signalling_web.py:197-264``.
"""

from __future__ import annotations

import asyncio
import base64
import http
import json
import logging
import mimetypes
import os
from typing import Dict, Optional, Set, Tuple

import websockets
import websockets.asyncio.server
from websockets.datastructures import Headers
from websockets.http11 import Response

from .turn import generate_rtc_config

logger = logging.getLogger("selkies_tpu.rtc.signaling")


class SignalingServer:
    #: whole-body Response API: bound what a single /files download pins
    MAX_DOWNLOAD_BYTES = 256 * 1024 * 1024

    def __init__(
        self,
        addr: str = "0.0.0.0",
        port: int = 8080,
        web_root: Optional[str] = None,
        health_path: str = "/health",
        keepalive_timeout: float = 30.0,
        enable_basic_auth: bool = False,
        basic_auth_user: str = "",
        basic_auth_password: str = "",
        turn_shared_secret: str = "",
        turn_host: str = "",
        turn_port: str = "",
        turn_protocol: str = "udp",
        turn_tls: bool = False,
        stun_host: Optional[str] = None,
        stun_port=None,
        turn_auth_header_name: str = "x-auth-user",
        rtc_config: Optional[str] = None,
        files_root: Optional[str] = None,
    ):
        self.addr = addr
        self.port = port
        self.web_root = os.path.realpath(web_root) if web_root else None
        #: downloadable-files tree (the reference dashboard's "Download
        #: Files" iframe points at ./files/ — legacy FILE_MANAGER_PATH)
        self.files_root = os.path.realpath(files_root) if files_root else None
        self.health_path = health_path.rstrip("/")
        self.keepalive_timeout = keepalive_timeout
        self.enable_basic_auth = enable_basic_auth
        self.basic_auth_user = basic_auth_user
        self.basic_auth_password = basic_auth_password
        self.turn_shared_secret = turn_shared_secret
        self.turn_host = turn_host
        self.turn_port = turn_port
        self.turn_protocol = turn_protocol
        self.turn_tls = turn_tls
        self.stun_host = stun_host
        self.stun_port = stun_port
        self.turn_auth_header_name = turn_auth_header_name
        self.rtc_config = rtc_config

        # uid -> (ws, status, meta); status: None | 'session' | room_id
        self.peers: Dict[str, list] = {}
        self.sessions: Dict[str, str] = {}
        self.rooms: Dict[str, Set[str]] = {}

        self.server = None
        self._stop: Optional[asyncio.Future] = None

    # ------------------------------------------------------------- HTTP

    @staticmethod
    def _response(status: http.HTTPStatus, body: bytes, headers: Optional[Headers] = None) -> Response:
        hdrs = Headers([("Connection", "close"), ("Content-Length", str(len(body)))])
        if headers is None or "Content-Type" not in headers:
            hdrs["Content-Type"] = "text/plain; charset=utf-8"
        if headers:
            for k, v in headers.raw_items():
                if k in hdrs:
                    del hdrs[k]
                hdrs[k] = v
        return Response(status.value, status.phrase, hdrs, body)

    def _check_basic_auth(self, request) -> bool:
        auth = request.headers.get("authorization", "")
        if not auth.lower().startswith("basic "):
            return False
        try:
            user, pw = base64.b64decode(auth.split(None, 1)[1]).decode().split(":", 1)
        except Exception:
            return False
        import hmac as hmac_mod

        return hmac_mod.compare_digest(
            user.encode(), self.basic_auth_user.encode()) \
            & hmac_mod.compare_digest(
                pw.encode(), self.basic_auth_password.encode())

    async def process_request(self, connection, request):
        path = request.path
        if self.enable_basic_auth and not self._check_basic_auth(request):
            hdrs = Headers()
            hdrs["WWW-Authenticate"] = 'Basic realm="restricted", charset="UTF-8"'
            return self._response(http.HTTPStatus.UNAUTHORIZED, b"Authorization required", hdrs)

        stripped = path.split("?")[0].rstrip("/")
        if stripped == "/ws" or stripped.endswith("/signalling"):
            return None  # proceed with the WebSocket upgrade

        if path.rstrip("/") == self.health_path:
            return self._response(http.HTTPStatus.OK, b"OK\n")

        if path.rstrip("/") == "/turn":
            return self._turn_response(request)

        if path.split("?")[0] == "/files" or path.split("?")[0].startswith("/files/"):
            return await asyncio.to_thread(self._files_response, path)

        # disk I/O off the event loop: a big asset read must not stall
        # concurrent SDP/ICE relays
        return await asyncio.to_thread(self._static_response, path)

    def _turn_response(self, request) -> Response:
        hdrs = Headers()
        hdrs["Content-Type"] = "application/json"
        if self.turn_shared_secret:
            user = request.headers.get(self.turn_auth_header_name, "") or "anonymous"
            body = generate_rtc_config(
                self.turn_host,
                self.turn_port,
                self.turn_shared_secret,
                user,
                self.turn_protocol,
                self.turn_tls,
                self.stun_host,
                self.stun_port,
            ).encode()
            return self._response(http.HTTPStatus.OK, body, hdrs)
        if self.rtc_config:
            cfg = self.rtc_config
            return self._response(
                http.HTTPStatus.OK, cfg.encode() if isinstance(cfg, str) else cfg, hdrs
            )
        return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")

    def _files_response(self, path: str) -> Response:
        """File-download plane: directory listings + attachment serving
        from ``files_root`` (reference: dashboard "Download Files" iframe
        at ./files/, FILE_MANAGER_PATH at reference selkies.py:98-103)."""
        import html
        import urllib.parse

        if self.files_root is None:
            return self._response(http.HTTPStatus.NOT_FOUND,
                                  b"file downloads disabled")
        rel = urllib.parse.unquote(path.split("?")[0][len("/files"):])
        if "\x00" in rel:
            # realpath raises ValueError on embedded NULs; hostile paths
            # must 404, not 500
            return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")
        full = os.path.realpath(
            os.path.join(self.files_root, rel.lstrip("/")))
        if os.path.commonpath((self.files_root, full)) != self.files_root:
            return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")
        if os.path.isdir(full):
            rows = []
            base = "/files" + (rel.rstrip("/") if rel.strip("/") else "")

            def href(path: str) -> str:
                # quote THEN escape: a hostile directory name must neither
                # break out of the attribute nor smuggle markup
                return html.escape(urllib.parse.quote(path, safe="/"))

            if full != self.files_root:
                rows.append('<li><a href="%s/">../</a></li>'
                            % href(os.path.dirname(base.rstrip("/"))))
            try:
                names = sorted(os.listdir(full))
            except OSError:
                return self._response(http.HTTPStatus.NOT_FOUND,
                                      b"404 NOT FOUND")
            for name in names:
                p = os.path.join(full, name)
                try:
                    if os.path.isdir(p):
                        rows.append(f'<li><a href="{href(base + "/" + name)}/">'
                                    f'{html.escape(name)}/</a></li>')
                    else:
                        size = os.path.getsize(p)
                        rows.append(
                            f'<li><a href="{href(base + "/" + name)}" download>'
                            f'{html.escape(name)}</a>'
                            f' <small>({size:,} B)</small></li>')
                except OSError:
                    continue    # dangling symlink / raced deletion
            body = (
                "<!DOCTYPE html><meta charset=utf-8>"
                "<style>body{font:14px system-ui;background:#101214;"
                "color:#d7dadd;padding:14px}a{color:#9ecbff}"
                "li{margin:3px 0}</style>"
                f"<h3>Files — {html.escape(rel or '/')}</h3>"
                "<ul>" + "".join(rows) + "</ul>").encode()
            hdrs = Headers()
            hdrs["Content-Type"] = "text/html; charset=utf-8"
            return self._response(http.HTTPStatus.OK, body, hdrs)
        if os.path.isfile(full):
            import re as _re

            try:
                size = os.path.getsize(full)
            except OSError:
                return self._response(http.HTTPStatus.NOT_FOUND,
                                      b"404 NOT FOUND")
            # the Response API is whole-body; cap what one request may pin
            # in memory rather than letting a Desktop disk image OOM the
            # streaming host
            if size > self.MAX_DOWNLOAD_BYTES:
                return self._response(
                    http.HTTPStatus.REQUEST_ENTITY_TOO_LARGE,
                    b"file exceeds the download size limit")
            mime = mimetypes.guess_type(full)[0] or "application/octet-stream"
            with open(full, "rb") as f:
                body = f.read()
            # header values must stay single-line and quote-free: strip
            # control characters and quotes from the advertised filename
            safe_name = _re.sub(r'[\x00-\x1f"\\\x7f]', "_",
                                os.path.basename(full)) or "download"
            hdrs = Headers()
            hdrs["Content-Type"] = mime
            hdrs["Content-Disposition"] = (
                'attachment; filename="%s"' % safe_name)
            return self._response(http.HTTPStatus.OK, body, hdrs)
        return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")

    def _static_response(self, path: str) -> Response:
        if self.web_root is None:
            return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")
        path = path.split("?")[0]
        if path == "/":
            path = "/index.html"
        full = os.path.realpath(os.path.join(self.web_root, path.lstrip("/")))
        if (
            os.path.commonpath((self.web_root, full)) != self.web_root
            or not os.path.isfile(full)
        ):
            return self._response(http.HTTPStatus.NOT_FOUND, b"404 NOT FOUND")
        mime = mimetypes.guess_type(full)[0] or "application/octet-stream"
        with open(full, "rb") as f:
            body = f.read()
        hdrs = Headers()
        hdrs["Content-Type"] = mime
        return self._response(http.HTTPStatus.OK, body, hdrs)

    # ------------------------------------------------------- WS signaling

    async def _recv_with_keepalive(self, ws):
        while True:
            try:
                return await asyncio.wait_for(ws.recv(), self.keepalive_timeout)
            except asyncio.TimeoutError:
                await ws.ping()

    async def _hello(self, ws) -> Tuple[str, Optional[dict]]:
        toks = (await ws.recv()).split(maxsplit=2)
        if len(toks) < 2 or toks[0] != "HELLO":
            await ws.close(code=1002, reason="invalid protocol")
            raise ValueError("invalid hello")
        uid = toks[1]
        if not uid or uid in self.peers or uid.split() != [uid]:
            await ws.close(code=1002, reason="invalid peer uid")
            raise ValueError(f"invalid uid {uid!r}")
        meta = json.loads(base64.b64decode(toks[2])) if len(toks) > 2 else None
        await ws.send("HELLO")
        return uid, meta

    async def _cleanup_session(self, uid: str) -> None:
        other = self.sessions.pop(uid, None)
        if other is None:
            return
        if self.sessions.pop(other, None) is not None and other in self.peers:
            ws_other = self.peers.pop(other)[0]
            await ws_other.close()

    async def _cleanup_room(self, uid: str, room_id: str) -> None:
        members = self.rooms.get(room_id)
        if not members or uid not in members:
            return
        members.remove(uid)
        for pid in members:
            try:
                await self.peers[pid][0].send(f"ROOM_PEER_LEFT {uid}")
            except Exception:
                pass

    async def _remove_peer(self, uid: str) -> None:
        await self._cleanup_session(uid)
        entry = self.peers.pop(uid, None)
        if entry is not None:
            ws, status, _ = entry
            if status and status != "session":
                await self._cleanup_room(uid, status)
            await ws.close()

    async def _handle_peer(self, ws, uid: str) -> None:
        while True:
            msg = await self._recv_with_keepalive(ws)
            if not isinstance(msg, str):
                await ws.send("ERROR binary frames not supported")
                continue
            entry = self.peers.get(uid)
            if entry is None:  # partner teardown removed us mid-flight
                return
            status = entry[1]
            if status == "session":
                other = self.sessions.get(uid)
                peer = self.peers.get(other) if other is not None else None
                if peer is not None:
                    await peer[0].send(msg)
            elif status is not None:  # in a room
                if msg.startswith("ROOM_PEER_MSG"):
                    try:
                        _, other, payload = msg.split(maxsplit=2)
                    except ValueError:
                        await ws.send("ERROR invalid ROOM_PEER_MSG")
                        continue
                    if other not in self.peers or self.peers[other][1] != status:
                        await ws.send(f"ERROR peer {other!r} not in the room")
                        continue
                    await self.peers[other][0].send(f"ROOM_PEER_MSG {uid} {payload}")
                else:
                    await ws.send("ERROR invalid msg, already in room")
            elif msg.startswith("SESSION"):
                try:
                    _, callee = msg.split(maxsplit=1)
                except ValueError:
                    await ws.send("ERROR invalid SESSION command")
                    continue
                if callee not in self.peers:
                    await ws.send(f"ERROR peer {callee!r} not found")
                    continue
                if self.peers[callee][1] is not None:
                    await ws.send(f"ERROR peer {callee!r} busy")
                    continue
                meta = self.peers[callee][2]
                meta64 = (
                    base64.b64encode(json.dumps(meta).encode()).decode() if meta else ""
                )
                # register the session before the await so a concurrent
                # SESSION to either peer sees them as busy
                self.peers[uid][1] = "session"
                self.peers[callee][1] = "session"
                self.sessions[uid] = callee
                self.sessions[callee] = uid
                await ws.send(f"SESSION_OK {meta64}".rstrip())
            elif msg.startswith("ROOM"):
                try:
                    _, room_id = msg.split(maxsplit=1)
                except ValueError:
                    await ws.send("ERROR invalid ROOM command")
                    continue
                if room_id == "session" or room_id.split() != [room_id]:
                    await ws.send(f"ERROR invalid room id {room_id!r}")
                    continue
                members = self.rooms.setdefault(room_id, set())
                # join before the first await so concurrent joiners see us
                existing = sorted(members)
                members.add(uid)
                self.peers[uid][1] = room_id
                await ws.send(("ROOM_OK " + " ".join(existing)).rstrip())
                for pid in existing:
                    peer = self.peers.get(pid)
                    if peer is not None:
                        await peer[0].send(f"ROOM_PEER_JOINED {uid}")
            else:
                logger.info("ignoring unknown message %r from %r", msg, uid)

    async def _ws_handler(self, ws) -> None:
        try:
            uid, meta = await self._hello(ws)
        except Exception:
            return
        self.peers[uid] = [ws, None, meta]
        try:
            await self._handle_peer(ws, uid)
        except websockets.exceptions.ConnectionClosed:
            pass
        finally:
            await self._remove_peer(uid)

    # --------------------------------------------------------- lifecycle

    async def run(self) -> None:
        self._stop = asyncio.get_running_loop().create_future()
        async with websockets.asyncio.server.serve(
            self._ws_handler,
            self.addr,
            self.port,
            process_request=self.process_request,
            max_queue=16,
        ) as self.server:
            # report the bound port (0 → ephemeral) for tests
            self.port = self.server.sockets[0].getsockname()[1]
            await self._stop

    async def stop(self) -> None:
        if self._stop and not self._stop.done():
            self._stop.set_result(None)
