"""WebRTC session plumbing: TURN/STUN credential management, RTC config
monitors, the turn-rest credential microservice, and the combined
HTTP + WebSocket signaling server/client.

Parity targets (reference, read-only):
  - ``legacy/signalling_web.py`` — signaling + web server
  - ``legacy/webrtc_signalling.py`` — in-process signaling client
  - ``legacy/webrtc.py:62-328`` — RTC config monitors + fetchers
  - ``addons/turn-rest/app.py`` — HMAC credential REST service
"""

from .turn import (
    DEFAULT_RTC_CONFIG,
    TurnCredentials,
    build_rtc_config,
    fetch_cloudflare_turn,
    fetch_turn_rest,
    generate_rtc_config,
    hmac_credentials,
    parse_rtc_config,
)
from .monitors import HMACRTCMonitor, RESTRTCMonitor, RTCConfigFileMonitor
from .signaling import SignalingServer
from .signaling_client import SignalingClient, SignalingError, SignalingNoPeerError

__all__ = [
    "DEFAULT_RTC_CONFIG",
    "TurnCredentials",
    "build_rtc_config",
    "fetch_cloudflare_turn",
    "fetch_turn_rest",
    "generate_rtc_config",
    "hmac_credentials",
    "parse_rtc_config",
    "HMACRTCMonitor",
    "RESTRTCMonitor",
    "RTCConfigFileMonitor",
    "SignalingServer",
    "SignalingClient",
    "SignalingError",
    "SignalingNoPeerError",
]
