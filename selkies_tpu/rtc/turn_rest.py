"""turn-rest: a tiny HTTP service minting time-limited coturn HMAC
credentials as browser-shaped RTC config JSON.

Parity with ``addons/turn-rest/app.py`` (Flask in the reference; aiohttp
here — Flask is not in this image and an async server matches the rest of
the framework). Same request contract:

  GET/POST /  with  ?username=&protocol=&tls=  or headers
  ``x-auth-user`` / ``x-turn-username``, ``x-turn-protocol``, ``x-turn-tls``
  → RTC config JSON carrying ``exp:user`` + HMAC-SHA1 credential.
"""

from __future__ import annotations

import os
from typing import Optional

from aiohttp import web

from .turn import build_rtc_config, hmac_credentials


class TurnRestService:
    def __init__(
        self,
        shared_secret: Optional[str] = None,
        turn_host: Optional[str] = None,
        turn_port: Optional[str] = None,
        stun_host: Optional[str] = None,
        stun_port: Optional[str] = None,
        turn_protocol: Optional[str] = None,
        turn_tls: Optional[str] = None,
        ttl_seconds: int = 86400,
    ):
        env = os.environ.get
        self.shared_secret = shared_secret or env("TURN_SHARED_SECRET", "changeme")
        self.turn_host = (turn_host or env("TURN_HOST", "localhost")).lower()
        self.turn_port = turn_port or env("TURN_PORT", "3478")
        if not str(self.turn_port).isdigit():
            self.turn_port = "3478"
        self.stun_host = (stun_host or env("STUN_HOST", self.turn_host)).lower()
        self.stun_port = stun_port or env("STUN_PORT", self.turn_port)
        if not str(self.stun_port).isdigit():
            self.stun_host, self.stun_port = "stun.l.google.com", "19302"
        self.turn_protocol_default = turn_protocol or env("TURN_PROTOCOL", "udp")
        self.turn_tls_default = turn_tls or env("TURN_TLS", "false")
        self.ttl_seconds = ttl_seconds

    async def handle(self, request: web.Request) -> web.Response:
        values = dict(request.query)
        if request.method == "POST":
            try:
                values.update(dict(await request.post()))
            except Exception:
                pass
        headers = request.headers

        user = (
            values.get("username")
            or headers.get("x-auth-user")
            or headers.get("x-turn-username")
            or "turn-rest"
        ).lower()
        protocol = (
            values.get("protocol") or headers.get("x-turn-protocol") or self.turn_protocol_default
        )
        protocol = "tcp" if protocol.lower() == "tcp" else "udp"
        tls_raw = values.get("tls") or headers.get("x-turn-tls") or self.turn_tls_default
        turn_tls = str(tls_raw).lower() == "true"

        creds = hmac_credentials(self.shared_secret, user, self.ttl_seconds)
        body = build_rtc_config(
            self.turn_host,
            self.turn_port,
            creds,
            protocol,
            turn_tls,
            self.stun_host,
            self.stun_port,
            self.ttl_seconds,
        )
        return web.Response(text=body, content_type="application/json")

    def make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_route("GET", "/", self.handle)
        app.router.add_route("POST", "/", self.handle)
        return app

    async def start(self, host: str = "0.0.0.0", port: int = 8008) -> web.AppRunner:
        runner = web.AppRunner(self.make_app())
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        return runner


def main() -> None:  # pragma: no cover - console entry
    web.run_app(TurnRestService().make_app(), host="0.0.0.0", port=int(os.environ.get("PORT", "8008")))


if __name__ == "__main__":  # pragma: no cover
    main()
