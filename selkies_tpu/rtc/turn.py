"""TURN/STUN credential generation and RTC-config handling.

Behavioral parity with the reference (cited, not copied):
  - HMAC time-limited credentials per the coturn ``--use-auth-secret``
    scheme (``legacy/signalling_web.py:51-85``, ``addons/turn-rest/app.py``):
    username is ``"<unix-expiry>:<user>"``, password is
    base64(HMAC-SHA1(shared_secret, username)).
  - RTC config JSON shape consumed by browsers and by ``parse_rtc_config``
    (``legacy/webrtc.py:187-266``): ``iceServers`` with a STUN url list and
    one TURN entry carrying username/credential.
  - REST fetcher headers ``x-auth-user`` / ``x-turn-protocol`` /
    ``x-turn-tls`` (``legacy/webrtc.py:227-264``).
  - Cloudflare TURN credential endpoint (``legacy/webrtc.py:266-290``).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import time
import urllib.parse
from dataclasses import dataclass
from typing import List, Optional, Tuple

GOOGLE_STUN = ("stun.l.google.com", "19302")

DEFAULT_RTC_CONFIG = json.dumps(
    {
        "lifetimeDuration": "86400s",
        "iceServers": [{"urls": ["stun:%s:%s" % GOOGLE_STUN]}],
        "blockStatus": "NOT_BLOCKED",
        "iceTransportPolicy": "all",
    },
    indent=2,
)


@dataclass(frozen=True)
class TurnCredentials:
    """A minted time-limited TURN credential pair."""

    username: str
    password: str
    expires_at: int


def hmac_credentials(
    shared_secret: str, user: str, ttl_seconds: int = 86400, now: Optional[float] = None
) -> TurnCredentials:
    """Mint coturn REST-API credentials: ``exp:user`` + b64(HMAC-SHA1)."""
    user = user.replace(":", "-")
    exp = int(now if now is not None else time.time()) + ttl_seconds
    username = f"{exp}:{user}"
    digest = hmac.new(shared_secret.encode(), username.encode(), hashlib.sha1).digest()
    return TurnCredentials(username, base64.b64encode(digest).decode(), exp)


def _stun_url_list(turn_host: str, turn_port, stun_host=None, stun_port=None) -> List[str]:
    """STUN list: optional distinct STUN host first, TURN host, Google fallback."""
    urls = [f"stun:{turn_host}:{turn_port}"]
    if stun_host and stun_port and (stun_host != turn_host or str(stun_port) != str(turn_port)):
        urls.insert(0, f"stun:{stun_host}:{stun_port}")
    if (stun_host, str(stun_port)) != GOOGLE_STUN:
        urls.append("stun:%s:%s" % GOOGLE_STUN)
    return urls


def build_rtc_config(
    turn_host: str,
    turn_port,
    creds: TurnCredentials,
    protocol: str = "udp",
    turn_tls: bool = False,
    stun_host: Optional[str] = None,
    stun_port=None,
    ttl_seconds: int = 86400,
) -> str:
    """Browser-shaped RTCConfiguration JSON with one STUN and one TURN entry."""
    scheme = "turns" if turn_tls else "turn"
    cfg = {
        "lifetimeDuration": f"{ttl_seconds}s",
        "blockStatus": "NOT_BLOCKED",
        "iceTransportPolicy": "all",
        "iceServers": [
            {"urls": _stun_url_list(turn_host, turn_port, stun_host, stun_port)},
            {
                "urls": [f"{scheme}:{turn_host}:{turn_port}?transport={protocol}"],
                "username": creds.username,
                "credential": creds.password,
            },
        ],
    }
    return json.dumps(cfg, indent=2)


def generate_rtc_config(
    turn_host: str,
    turn_port,
    shared_secret: str,
    user: str,
    protocol: str = "udp",
    turn_tls: bool = False,
    stun_host: Optional[str] = None,
    stun_port=None,
) -> str:
    """Mint HMAC credentials and wrap them in RTC config JSON
    (reference ``signalling_web.py:51``)."""
    creds = hmac_credentials(shared_secret, user)
    return build_rtc_config(
        turn_host, turn_port, creds, protocol, turn_tls, stun_host, stun_port
    )


def parse_rtc_config(data) -> Tuple[List[str], List[str], str]:
    """Extract ``stun://`` and ``turn(s)://user:pass@host:port`` URI lists
    from RTC config JSON (reference ``legacy/webrtc.py:187``)."""
    if isinstance(data, bytes):
        data = data.decode()
    stun_uris: List[str] = []
    turn_uris: List[str] = []
    for server in json.loads(data).get("iceServers", []):
        for url in server.get("urls", []):
            scheme, _, rest = url.partition(":")
            host, _, port_q = rest.partition(":")
            port = port_q.split("?")[0]
            if scheme == "stun":
                stun_uris.append(f"stun://{host}:{port}")
            elif scheme in ("turn", "turns"):
                user = urllib.parse.quote(server["username"], safe="")
                cred = urllib.parse.quote(server["credential"], safe="")
                turn_uris.append(f"{scheme}://{user}:{cred}@{host}:{port}")
    return stun_uris, turn_uris, data


def fetch_turn_rest(
    uri: str,
    user: str,
    auth_header_username: str = "x-auth-user",
    protocol: str = "udp",
    header_protocol: str = "x-turn-protocol",
    turn_tls: bool = False,
    header_tls: str = "x-turn-tls",
    timeout: float = 10.0,
) -> Tuple[List[str], List[str], str]:
    """GET an RTC config from a turn-rest service, identifying via headers."""
    parsed = urllib.parse.urlparse(uri)
    conn_cls = (
        http.client.HTTPSConnection if parsed.scheme == "https" else http.client.HTTPConnection
    )
    conn = conn_cls(parsed.netloc, timeout=timeout)
    request_path = (parsed.path or "/") + (f"?{parsed.query}" if parsed.query else "")
    try:
        conn.request(
            "GET",
            request_path,
            headers={
                auth_header_username: user,
                header_protocol: protocol,
                header_tls: "true" if turn_tls else "false",
            },
        )
        resp = conn.getresponse()
        body = resp.read()
        if resp.status >= 400:
            raise RuntimeError(f"turn-rest fetch failed: {resp.status} {resp.reason}")
    finally:
        conn.close()
    if not body:
        raise RuntimeError("turn-rest returned an empty body")
    return parse_rtc_config(body)


def fetch_cloudflare_turn(turn_token_id: str, api_token: str, ttl: int = 86400, timeout: float = 10.0) -> dict:
    """POST to the Cloudflare Calls credential generator
    (reference ``legacy/webrtc.py:266``)."""
    host = "rtc.live.cloudflare.com"
    path = f"/v1/turn/keys/{turn_token_id}/credentials/generate"
    conn = http.client.HTTPSConnection(host, timeout=timeout)
    try:
        conn.request(
            "POST",
            path,
            json.dumps({"ttl": ttl}),
            headers={
                "authorization": f"Bearer {api_token}",
                "content-type": "application/json",
            },
        )
        resp = conn.getresponse()
        body = resp.read()
        if resp.status >= 400:
            raise RuntimeError(f"cloudflare TURN fetch failed: {resp.status}")
    finally:
        conn.close()
    return json.loads(body)
