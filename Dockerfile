# Wheel-build + runtime image for selkies-tpu (parity: reference root
# Dockerfile, the py-build container in SURVEY.md §2.6).
#
# The runtime stage expects a JAX with TPU support baked into the base
# image (libtpu containers) — the framework itself is pure Python + two
# small C shims built here.

FROM python:3.12-slim AS build
WORKDIR /src
COPY pyproject.toml ./
COPY selkies_tpu ./selkies_tpu
COPY web ./web
RUN pip install --no-cache-dir build && python -m build --wheel --outdir /dist

FROM python:3.12-slim AS shims
RUN apt-get update && apt-get install -y --no-install-recommends \
        gcc make libc6-dev && rm -rf /var/lib/apt/lists/*
COPY native /src/native
RUN make -C /src/native/interposer && make -C /src/native/fake-udev

FROM python:3.12-slim
LABEL org.opencontainers.image.title="selkies-tpu"
COPY --from=build /dist/*.whl /tmp/
COPY --from=shims /src/native/interposer/selkies_joystick_interposer.so \
        /usr/lib/selkies/selkies_joystick_interposer.so
COPY --from=shims /src/native/fake-udev/libudev.so.1.0.0-fake \
        /usr/lib/selkies/libudev.so.1.0.0-fake
COPY web /opt/selkies-tpu/web
RUN pip install --no-cache-dir /tmp/*.whl websockets aiohttp numpy \
        prometheus-client && rm /tmp/*.whl
EXPOSE 8080 8082 8000
ENTRYPOINT ["selkies-tpu"]
