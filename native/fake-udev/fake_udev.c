/*
 * fake_udev.c — drop-in libudev.so.1 replacement fabricating the four
 * selkies virtual gamepads for device discovery inside containers.
 *
 * SDL2/Wine/game engines enumerate joysticks through libudev; in a
 * container there is no udevd and no /run/udev database, so enumeration
 * finds nothing even though the joystick interposer (joystick_interposer.c)
 * can serve /dev/input/js0-3 + event1000-1003. Preloading (or bind-mounting
 * over libudev.so.1) this stub makes enumeration return exactly those
 * devices with the properties SDL checks (ID_INPUT_JOYSTICK=1 etc.), and
 * provides an inert monitor whose fd never signals.
 *
 * Role parity with the reference's addons/fake-udev (SURVEY.md §2.2);
 * fresh implementation. Build: make -C native/fake-udev
 */

#define _GNU_SOURCE
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define NUM_PADS 4
#define EVDEV_BASE 1000

/* opaque handle types (all alias to internal structs) */
struct udev { int refs; };

typedef struct fake_device {
    const char *syspath;
    const char *sysname;
    const char *devnode;
    const char *subsystem;
    int is_evdev;
    int pad;
    int refs;
} fake_device;

struct udev_device { fake_device d; };

struct udev_list_entry {
    const char *name;
    const char *value;
    struct udev_list_entry *next;
};

struct udev_enumerate {
    struct udev *udev;
    int want_input;
    int refs;
    struct udev_list_entry entries[NUM_PADS * 2 + 1];
    char names[NUM_PADS * 2][64];
    int count;
};

struct udev_monitor {
    int pipefd[2];
    int refs;
};

/* ------------------------------------------------------------- tables */

static char g_syspaths[NUM_PADS * 2][64];
static char g_sysnames[NUM_PADS * 2][32];
static char g_devnodes[NUM_PADS * 2][32];
static int g_init_done = 0;

static void tables_init(void)
{
    if (g_init_done) return;
    for (int i = 0; i < NUM_PADS; i++) {
        /* js device at slot i, evdev device at slot NUM_PADS + i */
        snprintf(g_sysnames[i], sizeof(g_sysnames[i]), "js%d", i);
        snprintf(g_syspaths[i], sizeof(g_syspaths[i]),
                 "/sys/devices/virtual/input/input%d/js%d", i, i);
        snprintf(g_devnodes[i], sizeof(g_devnodes[i]), "/dev/input/js%d", i);
        int e = EVDEV_BASE + i;
        snprintf(g_sysnames[NUM_PADS + i], sizeof(g_sysnames[0]),
                 "event%d", e);
        snprintf(g_syspaths[NUM_PADS + i], sizeof(g_syspaths[0]),
                 "/sys/devices/virtual/input/input%d/event%d", i, e);
        snprintf(g_devnodes[NUM_PADS + i], sizeof(g_devnodes[0]),
                 "/dev/input/event%d", e);
    }
    g_init_done = 1;
}

static int slot_from_syspath(const char *syspath)
{
    tables_init();
    if (!syspath) return -1;
    for (int i = 0; i < NUM_PADS * 2; i++)
        if (strcmp(g_syspaths[i], syspath) == 0) return i;
    return -1;
}

/* ---------------------------------------------------------- udev core */

struct udev *udev_new(void)
{
    tables_init();
    struct udev *u = calloc(1, sizeof(*u));
    if (u) u->refs = 1;
    return u;
}

struct udev *udev_ref(struct udev *u)
{
    if (u) u->refs++;
    return u;
}

struct udev *udev_unref(struct udev *u)
{
    if (u && --u->refs == 0) free(u);
    return NULL;
}

void udev_set_log_fn(struct udev *u, void *fn) { (void)u; (void)fn; }
void udev_set_log_priority(struct udev *u, int p) { (void)u; (void)p; }
int udev_get_log_priority(struct udev *u) { (void)u; return 0; }
void *udev_get_userdata(struct udev *u) { (void)u; return NULL; }
void udev_set_userdata(struct udev *u, void *d) { (void)u; (void)d; }

/* ---------------------------------------------------------- enumerate */

struct udev_enumerate *udev_enumerate_new(struct udev *u)
{
    struct udev_enumerate *e = calloc(1, sizeof(*e));
    if (e) { e->udev = u; e->refs = 1; }
    return e;
}

struct udev_enumerate *udev_enumerate_ref(struct udev_enumerate *e)
{
    if (e) e->refs++;
    return e;
}

struct udev_enumerate *udev_enumerate_unref(struct udev_enumerate *e)
{
    if (e && --e->refs == 0) free(e);
    return NULL;
}

int udev_enumerate_add_match_subsystem(struct udev_enumerate *e,
                                       const char *subsystem)
{
    if (e && subsystem && strcmp(subsystem, "input") == 0)
        e->want_input = 1;
    return 0;
}

int udev_enumerate_add_match_property(struct udev_enumerate *e,
                                      const char *prop, const char *value)
{
    (void)e; (void)prop; (void)value;
    return 0;  /* our devices match the joystick properties SDL filters on */
}

int udev_enumerate_add_match_sysname(struct udev_enumerate *e,
                                     const char *sysname)
{
    (void)e; (void)sysname;
    return 0;
}

int udev_enumerate_add_match_tag(struct udev_enumerate *e, const char *tag)
{
    (void)e; (void)tag;
    return 0;
}

int udev_enumerate_scan_devices(struct udev_enumerate *e)
{
    if (!e) return -1;
    tables_init();
    e->count = 0;
    if (!e->want_input) return 0;
    for (int i = 0; i < NUM_PADS * 2; i++) {
        struct udev_list_entry *ent = &e->entries[e->count];
        ent->name = g_syspaths[i];
        ent->value = NULL;
        ent->next = NULL;
        if (e->count > 0)
            e->entries[e->count - 1].next = ent;
        e->count++;
    }
    return 0;
}

struct udev_list_entry *
udev_enumerate_get_list_entry(struct udev_enumerate *e)
{
    if (!e || e->count == 0) return NULL;
    return &e->entries[0];
}

struct udev *udev_enumerate_get_udev(struct udev_enumerate *e)
{
    return e ? e->udev : NULL;
}

/* --------------------------------------------------------- list entry */

struct udev_list_entry *
udev_list_entry_get_next(struct udev_list_entry *ent)
{
    return ent ? ent->next : NULL;
}

const char *udev_list_entry_get_name(struct udev_list_entry *ent)
{
    return ent ? ent->name : NULL;
}

const char *udev_list_entry_get_value(struct udev_list_entry *ent)
{
    return ent ? ent->value : NULL;
}

struct udev_list_entry *
udev_list_entry_get_by_name(struct udev_list_entry *ent, const char *name)
{
    for (; ent; ent = ent->next)
        if (ent->name && name && strcmp(ent->name, name) == 0) return ent;
    return NULL;
}

/* ------------------------------------------------------------- device */

static struct udev_device *device_for_slot(int slot)
{
    struct udev_device *d = calloc(1, sizeof(*d));
    if (!d) return NULL;
    d->d.syspath = g_syspaths[slot];
    d->d.sysname = g_sysnames[slot];
    d->d.devnode = g_devnodes[slot];
    d->d.subsystem = "input";
    d->d.is_evdev = slot >= NUM_PADS;
    d->d.pad = slot % NUM_PADS;
    d->d.refs = 1;
    return d;
}

struct udev_device *udev_device_new_from_syspath(struct udev *u,
                                                 const char *syspath)
{
    (void)u;
    int slot = slot_from_syspath(syspath);
    if (slot < 0) return NULL;
    return device_for_slot(slot);
}

struct udev_device *udev_device_new_from_devnum(struct udev *u, char type,
                                                unsigned long devnum)
{
    (void)u; (void)type;
    tables_init();
    /* major 13: js minors 0..3, event minors 64+EVDEV_BASE+i */
    unsigned minor = devnum & 0xFF;
    if (minor < NUM_PADS) return device_for_slot((int)minor);
    return NULL;
}

struct udev_device *udev_device_ref(struct udev_device *d)
{
    if (d) d->d.refs++;
    return d;
}

struct udev_device *udev_device_unref(struct udev_device *d)
{
    if (d && --d->d.refs == 0) free(d);
    return NULL;
}

const char *udev_device_get_syspath(struct udev_device *d)
{
    return d ? d->d.syspath : NULL;
}

const char *udev_device_get_sysname(struct udev_device *d)
{
    return d ? d->d.sysname : NULL;
}

const char *udev_device_get_devnode(struct udev_device *d)
{
    return d ? d->d.devnode : NULL;
}

const char *udev_device_get_subsystem(struct udev_device *d)
{
    return d ? d->d.subsystem : NULL;
}

const char *udev_device_get_devtype(struct udev_device *d)
{
    (void)d;
    return NULL;
}

const char *udev_device_get_action(struct udev_device *d)
{
    (void)d;
    return "add";
}

unsigned long udev_device_get_devnum(struct udev_device *d)
{
    if (!d) return 0;
    unsigned major = 13;
    unsigned minor = d->d.is_evdev ? (64u + EVDEV_BASE + d->d.pad)
                                   : (unsigned)d->d.pad;
    return (major << 8) | (minor & 0xFF);
}

int udev_device_get_is_initialized(struct udev_device *d)
{
    (void)d;
    return 1;
}

const char *udev_device_get_property_value(struct udev_device *d,
                                           const char *key)
{
    static char buf[32];
    if (!d || !key) return NULL;
    if (strcmp(key, "ID_INPUT") == 0) return "1";
    if (strcmp(key, "ID_INPUT_JOYSTICK") == 0) return "1";
    if (strcmp(key, "DEVNAME") == 0) return d->d.devnode;
    if (strcmp(key, "SUBSYSTEM") == 0) return d->d.subsystem;
    if (strcmp(key, "ID_VENDOR_ID") == 0) return "045e";
    if (strcmp(key, "ID_MODEL_ID") == 0) return "028e";
    if (strcmp(key, "ID_BUS") == 0) return "usb";
    if (strcmp(key, "MAJOR") == 0) return "13";
    if (strcmp(key, "MINOR") == 0) {
        snprintf(buf, sizeof(buf), "%lu",
                 udev_device_get_devnum(d) & 0xFF);
        return buf;
    }
    return NULL;
}

const char *udev_device_get_sysattr_value(struct udev_device *d,
                                          const char *attr)
{
    if (!d || !attr) return NULL;
    if (strcmp(attr, "name") == 0) return "Microsoft X-Box 360 pad";
    if (strcmp(attr, "id/vendor") == 0) return "045e";
    if (strcmp(attr, "id/product") == 0) return "028e";
    if (strcmp(attr, "id/version") == 0) return "0114";
    return NULL;
}

struct udev_device *udev_device_get_parent(struct udev_device *d)
{
    (void)d;
    return NULL;  /* flat hierarchy; SDL tolerates missing parents */
}

struct udev_device *
udev_device_get_parent_with_subsystem_devtype(struct udev_device *d,
                                              const char *subsystem,
                                              const char *devtype)
{
    (void)d; (void)subsystem; (void)devtype;
    return NULL;
}

struct udev_list_entry *
udev_device_get_properties_list_entry(struct udev_device *d)
{
    (void)d;
    return NULL;
}

struct udev *udev_device_get_udev(struct udev_device *d)
{
    (void)d;
    return NULL;
}

/* ------------------------------------------------------------ monitor */

struct udev_monitor *udev_monitor_new_from_netlink(struct udev *u,
                                                   const char *name)
{
    (void)u; (void)name;
    struct udev_monitor *m = calloc(1, sizeof(*m));
    if (!m) return NULL;
    if (pipe2(m->pipefd, O_NONBLOCK | O_CLOEXEC) != 0) {
        free(m);
        return NULL;
    }
    m->refs = 1;
    return m;
}

int udev_monitor_filter_add_match_subsystem_devtype(struct udev_monitor *m,
                                                    const char *subsystem,
                                                    const char *devtype)
{
    (void)m; (void)subsystem; (void)devtype;
    return 0;
}

int udev_monitor_enable_receiving(struct udev_monitor *m)
{
    (void)m;
    return 0;
}

int udev_monitor_get_fd(struct udev_monitor *m)
{
    return m ? m->pipefd[0] : -1;  /* never readable: hotplug never fires */
}

int udev_monitor_set_receive_buffer_size(struct udev_monitor *m, int size)
{
    (void)m; (void)size;
    return 0;
}

struct udev_device *udev_monitor_receive_device(struct udev_monitor *m)
{
    (void)m;
    return NULL;
}

struct udev_monitor *udev_monitor_ref(struct udev_monitor *m)
{
    if (m) m->refs++;
    return m;
}

struct udev_monitor *udev_monitor_unref(struct udev_monitor *m)
{
    if (m && --m->refs == 0) {
        close(m->pipefd[0]);
        close(m->pipefd[1]);
        free(m);
    }
    return NULL;
}
