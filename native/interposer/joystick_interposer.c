/*
 * joystick_interposer.c — LD_PRELOAD shim redirecting /dev/input joystick
 * device access to the selkies-tpu virtual-gamepad unix sockets.
 *
 * Containerized games cannot see real /dev/input devices; the streaming
 * server instead runs per-pad unix-socket servers
 * (selkies_tpu/input/gamepad.py) speaking a tiny protocol:
 *
 *   connect  → server sends one 1360-byte js_config_t
 *              { char name[255]; pad; u16 vendor,product,version,
 *                num_btns,num_axes; u16 btn_map[512]; u8 axes_map[64]; pad[6] }
 *   then     → a stream of struct js_event (js sockets) or
 *              struct input_event (+ SYN_REPORT) (evdev sockets).
 *
 * This shim intercepts open()/openat()/access() on
 *   /dev/input/js{0-3}          → /tmp/selkies_js{N}.sock
 *   /dev/input/event{1000-1003} → /tmp/selkies_event{1000+N}.sock
 * consumes the config blob at open time, returns the SOCKET fd to the
 * application (reads/poll/epoll then work natively on the event stream),
 * and answers the joystick/evdev ioctl surface from the stored config.
 *
 * Equivalent role to the reference's addons/js-interposer (protocol
 * contract mirrored in selkies_tpu/input/gamepad.py); implementation is
 * original. Build: make -C native/interposer
 */

#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <linux/input.h>
#include <linux/joystick.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#define NUM_PADS 4
#define NAME_LEN 255
#define MAX_BTNS 512
#define MAX_AXES 64
#define EVDEV_BASE 1000

typedef struct {
    char name[NAME_LEN];
    uint8_t _pad0;
    uint16_t vendor;
    uint16_t product;
    uint16_t version;
    uint16_t num_btns;
    uint16_t num_axes;
    uint16_t btn_map[MAX_BTNS];
    uint8_t axes_map[MAX_AXES];
    uint8_t _pad1[6];
} __attribute__((packed)) js_config_t;

_Static_assert(sizeof(js_config_t) == 1360, "js_config_t must be 1360 bytes");

typedef struct {
    int fd;          /* socket fd handed to the app; -1 = free slot */
    int is_evdev;
    js_config_t cfg;
} shim_fd_t;

#define MAX_SHIM_FDS 64
static shim_fd_t g_fds[MAX_SHIM_FDS];
static pthread_mutex_t g_lock = PTHREAD_MUTEX_INITIALIZER;

static int (*real_open)(const char *, int, ...) = NULL;
static int (*real_open64)(const char *, int, ...) = NULL;
static int (*real_openat)(int, const char *, int, ...) = NULL;
static int (*real_ioctl)(int, unsigned long, ...) = NULL;
static int (*real_close)(int) = NULL;
static int (*real_access)(const char *, int) = NULL;

static void shim_init(void)
{
    static int done = 0;
    if (done) return;
    real_open   = dlsym(RTLD_NEXT, "open");
    real_open64 = dlsym(RTLD_NEXT, "open64");
    real_openat = dlsym(RTLD_NEXT, "openat");
    real_ioctl  = dlsym(RTLD_NEXT, "ioctl");
    real_close  = dlsym(RTLD_NEXT, "close");
    real_access = dlsym(RTLD_NEXT, "access");
    for (int i = 0; i < MAX_SHIM_FDS; i++) g_fds[i].fd = -1;
    done = 1;
}

__attribute__((constructor)) static void shim_ctor(void) { shim_init(); }

/* Map a device path to (pad index, is_evdev); -1 if not ours. */
static int match_path(const char *path, int *is_evdev)
{
    if (!path) return -1;
    int n;
    if (sscanf(path, "/dev/input/js%d", &n) == 1 && n >= 0 && n < NUM_PADS) {
        *is_evdev = 0;
        return n;
    }
    if (sscanf(path, "/dev/input/event%d", &n) == 1 &&
        n >= EVDEV_BASE && n < EVDEV_BASE + NUM_PADS) {
        *is_evdev = 1;
        return n - EVDEV_BASE;
    }
    return -1;
}

static void socket_path_for(int pad, int is_evdev, char *out, size_t cap)
{
    const char *dir = getenv("SELKIES_INTERPOSER_SOCKET_DIR");
    if (!dir) dir = "/tmp";
    if (is_evdev)
        snprintf(out, cap, "%s/selkies_event%d.sock", dir, EVDEV_BASE + pad);
    else
        snprintf(out, cap, "%s/selkies_js%d.sock", dir, pad);
}

static ssize_t read_full(int fd, void *buf, size_t len)
{
    size_t got = 0;
    while (got < len) {
        ssize_t r = read(fd, (char *)buf + got, len - got);
        if (r <= 0) {
            if (r < 0 && (errno == EINTR)) continue;
            return -1;
        }
        got += (size_t)r;
    }
    return (ssize_t)got;
}

static int shim_open_device(const char *path, int flags)
{
    int is_evdev = 0;
    int pad = match_path(path, &is_evdev);
    if (pad < 0) return -2; /* not ours */

    char spath[256];
    socket_path_for(pad, is_evdev, spath, sizeof(spath));

    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    struct sockaddr_un addr;
    memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, spath, sizeof(addr.sun_path) - 1);
    if (connect(fd, (struct sockaddr *)&addr, sizeof(addr)) != 0) {
        real_close(fd);
        errno = ENOENT;
        return -1;
    }

    js_config_t cfg;
    if (read_full(fd, &cfg, sizeof(cfg)) != (ssize_t)sizeof(cfg)) {
        real_close(fd);
        errno = EIO;
        return -1;
    }

    /* protocol: reply with our pointer width so the server packs
     * input_event timevals with the right layout */
    uint8_t arch = (uint8_t)sizeof(void *);
    if (write(fd, &arch, 1) != 1) {
        real_close(fd);
        errno = EIO;
        return -1;
    }

    if (flags & O_NONBLOCK) {
        int fl = fcntl(fd, F_GETFL, 0);
        fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    }

    pthread_mutex_lock(&g_lock);
    for (int i = 0; i < MAX_SHIM_FDS; i++) {
        if (g_fds[i].fd == -1) {
            g_fds[i].fd = fd;
            g_fds[i].is_evdev = is_evdev;
            g_fds[i].cfg = cfg;
            break;
        }
    }
    pthread_mutex_unlock(&g_lock);
    return fd;
}

static shim_fd_t *lookup(int fd)
{
    for (int i = 0; i < MAX_SHIM_FDS; i++)
        if (g_fds[i].fd == fd) return &g_fds[i];
    return NULL;
}

/* ------------------------------------------------------------- open() */

int open(const char *path, int flags, ...)
{
    shim_init();
    mode_t mode = 0;
    if (flags & O_CREAT) {
        va_list ap;
        va_start(ap, flags);
        mode = va_arg(ap, mode_t);
        va_end(ap);
    }
    int r = shim_open_device(path, flags);
    if (r != -2) return r;
    return real_open(path, flags, mode);
}

int open64(const char *path, int flags, ...)
{
    shim_init();
    mode_t mode = 0;
    if (flags & O_CREAT) {
        va_list ap;
        va_start(ap, flags);
        mode = va_arg(ap, mode_t);
        va_end(ap);
    }
    int r = shim_open_device(path, flags);
    if (r != -2) return r;
    if (real_open64) return real_open64(path, flags, mode);
    return real_open(path, flags, mode);
}

int openat(int dirfd, const char *path, int flags, ...)
{
    shim_init();
    mode_t mode = 0;
    if (flags & O_CREAT) {
        va_list ap;
        va_start(ap, flags);
        mode = va_arg(ap, mode_t);
        va_end(ap);
    }
    if (path && strncmp(path, "/dev/input/", 11) == 0) {
        int r = shim_open_device(path, flags);
        if (r != -2) return r;
    }
    return real_openat(dirfd, path, flags, mode);
}

int access(const char *path, int mode)
{
    shim_init();
    int is_evdev;
    if (match_path(path, &is_evdev) >= 0) return 0; /* device "exists" */
    return real_access(path, mode);
}

int close(int fd)
{
    shim_init();
    pthread_mutex_lock(&g_lock);
    shim_fd_t *s = lookup(fd);
    if (s) s->fd = -1;
    pthread_mutex_unlock(&g_lock);
    return real_close(fd);
}

/* ------------------------------------------------------------- ioctl() */

static void set_bit(uint8_t *mask, int bit, size_t cap)
{
    if (bit >= 0 && (size_t)(bit / 8) < cap) mask[bit / 8] |= 1u << (bit % 8);
}

static int evdev_ioctl(shim_fd_t *s, unsigned long req, void *arg)
{
    js_config_t *c = &s->cfg;
    unsigned dir = _IOC_DIR(req), type = _IOC_TYPE(req);
    unsigned nr = _IOC_NR(req), size = _IOC_SIZE(req);
    (void)dir;
    if (type != 'E') { errno = EINVAL; return -1; }

    if (nr == _IOC_NR(EVIOCGVERSION)) {
        *(int *)arg = 0x010001;
        return 0;
    }
    if (nr == _IOC_NR(EVIOCGID)) {
        struct input_id *id = arg;
        id->bustype = BUS_USB;
        id->vendor = c->vendor;
        id->product = c->product;
        id->version = c->version;
        return 0;
    }
    if (nr == _IOC_NR(EVIOCGNAME(0))) {
        size_t n = strnlen(c->name, NAME_LEN);
        if (n >= size) n = size ? size - 1 : 0;
        memcpy(arg, c->name, n);
        ((char *)arg)[n] = 0;
        return (int)n;
    }
    if (nr >= _IOC_NR(EVIOCGBIT(0, 0)) &&
        nr < _IOC_NR(EVIOCGBIT(EV_MAX, 0))) {
        int ev = (int)(nr - _IOC_NR(EVIOCGBIT(0, 0)));
        memset(arg, 0, size);
        uint8_t *mask = arg;
        if (ev == 0) {                      /* supported event types */
            set_bit(mask, EV_SYN, size);
            set_bit(mask, EV_KEY, size);
            set_bit(mask, EV_ABS, size);
        } else if (ev == EV_KEY) {
            for (int i = 0; i < c->num_btns && i < MAX_BTNS; i++)
                set_bit(mask, c->btn_map[i], size);
        } else if (ev == EV_ABS) {
            for (int i = 0; i < c->num_axes && i < MAX_AXES; i++)
                set_bit(mask, c->axes_map[i], size);
        }
        return (int)size;
    }
    if (nr >= _IOC_NR(EVIOCGABS(0)) && nr <= _IOC_NR(EVIOCGABS(ABS_MAX))) {
        int axis = (int)(nr - _IOC_NR(EVIOCGABS(0)));
        struct input_absinfo *ai = arg;
        memset(ai, 0, sizeof(*ai));
        /* triggers 0..255, hats -1..1, sticks -32768..32767 */
        if (axis == ABS_Z || axis == ABS_RZ) {
            ai->minimum = 0; ai->maximum = 255;
        } else if (axis >= ABS_HAT0X && axis <= ABS_HAT3Y) {
            ai->minimum = -1; ai->maximum = 1;
        } else {
            ai->minimum = -32768; ai->maximum = 32767;
            ai->fuzz = 16; ai->flat = 128;
        }
        return 0;
    }
    if (nr == _IOC_NR(EVIOCGPHYS(0)) || nr == _IOC_NR(EVIOCGUNIQ(0))) {
        if (size) ((char *)arg)[0] = 0;
        return 0;
    }
    if (nr == _IOC_NR(EVIOCGRAB)) return 0;
    if (nr == _IOC_NR(EVIOCGKEY(0)) || nr == _IOC_NR(EVIOCGLED(0)) ||
        nr == _IOC_NR(EVIOCGSW(0))) {
        memset(arg, 0, size);
        return (int)size;
    }
    if (nr == _IOC_NR(EVIOCGPROP(0))) {
        memset(arg, 0, size);
        return (int)size;
    }
    errno = EINVAL;
    return -1;
}

static int js_ioctl(shim_fd_t *s, unsigned long req, void *arg)
{
    js_config_t *c = &s->cfg;
    unsigned type = _IOC_TYPE(req), nr = _IOC_NR(req), size = _IOC_SIZE(req);
    if (type != 'j') { errno = EINVAL; return -1; }

    if (nr == _IOC_NR(JSIOCGVERSION)) { *(uint32_t *)arg = 0x020100; return 0; }
    if (nr == _IOC_NR(JSIOCGAXES))    { *(uint8_t *)arg = (uint8_t)c->num_axes; return 0; }
    if (nr == _IOC_NR(JSIOCGBUTTONS)) { *(uint8_t *)arg = (uint8_t)c->num_btns; return 0; }
    if (nr == _IOC_NR(JSIOCGNAME(0))) {
        size_t n = strnlen(c->name, NAME_LEN);
        if (n >= size) n = size ? size - 1 : 0;
        memcpy(arg, c->name, n);
        ((char *)arg)[n] = 0;
        return (int)n;
    }
    if (nr == _IOC_NR(JSIOCGAXMAP)) {
        uint8_t *map = arg;
        size_t cnt = size < MAX_AXES ? size : MAX_AXES;
        for (size_t i = 0; i < cnt; i++)
            map[i] = (uint8_t)(i < c->num_axes ? c->axes_map[i] : 0);
        return 0;
    }
    if (nr == _IOC_NR(JSIOCGBTNMAP)) {
        uint16_t *map = arg;
        size_t cnt = size / 2 < MAX_BTNS ? size / 2 : MAX_BTNS;
        for (size_t i = 0; i < cnt; i++)
            map[i] = (uint16_t)(i < c->num_btns ? c->btn_map[i] : 0);
        return 0;
    }
    if (nr == _IOC_NR(JSIOCGCORR)) {
        memset(arg, 0, size);
        return 0;
    }
    if (nr == _IOC_NR(JSIOCSCORR)) return 0;
    errno = EINVAL;
    return -1;
}

int ioctl(int fd, unsigned long req, ...)
{
    shim_init();
    va_list ap;
    va_start(ap, req);
    void *arg = va_arg(ap, void *);
    va_end(ap);

    pthread_mutex_lock(&g_lock);
    shim_fd_t *s = lookup(fd);
    shim_fd_t copy;
    if (s) copy = *s;
    pthread_mutex_unlock(&g_lock);

    if (!s) return real_ioctl(fd, req, arg);
    return copy.is_evdev ? evdev_ioctl(&copy, req, arg)
                         : js_ioctl(&copy, req, arg);
}
