/*
 * input.js — browser input capture → selkies wire messages.
 *
 * Role parity with the reference's addons/gst-web-core/lib/input.js
 * (Guacamole-derived, 2,505 LoC): keyboard → X11 keysyms ("kd,"/"ku,"),
 * composition/IME and dead keys → atomic "co,end,<text>" typing (the
 * server binds spare keycodes for any codepoint), on-screen keyboard
 * trigger, pointer/touch → "m," absolute / "m2," relative (pointer lock),
 * touch-trackpad mode, wheel, gamepad polling → "js,c/b/a/d" messages.
 * Printable keys map through the X11 rule (latin-1 keysym = codepoint,
 * others 0x01000000+codepoint); non-printables through explicit tables.
 */

"use strict";

const KEY_TO_KEYSYM = {
  Backspace: 0xff08, Tab: 0xff09, Enter: 0xff0d, Escape: 0xff1b,
  Delete: 0xffff, Home: 0xff50, End: 0xff57, PageUp: 0xff55,
  PageDown: 0xff56, ArrowLeft: 0xff51, ArrowUp: 0xff52,
  ArrowRight: 0xff53, ArrowDown: 0xff54, Insert: 0xff63,
  F1: 0xffbe, F2: 0xffbf, F3: 0xffc0, F4: 0xffc1, F5: 0xffc2,
  F6: 0xffc3, F7: 0xffc4, F8: 0xffc5, F9: 0xffc6, F10: 0xffc7,
  F11: 0xffc8, F12: 0xffc9, Shift: 0xffe1, Control: 0xffe3,
  Alt: 0xffe9, AltGraph: 0xffea, Meta: 0xffe7, CapsLock: 0xffe5,
  NumLock: 0xff7f, ScrollLock: 0xff14, Pause: 0xff13,
  PrintScreen: 0xff61, ContextMenu: 0xff67,
  // IME control keys (reference lib/input.js keysym tables)
  Convert: 0xff21, NonConvert: 0xff22, KanaMode: 0xff2d,
  HiraganaKatakana: 0xff27, ZenkakuHankaku: 0xff2a,
  HangulMode: 0xff31, HanjaMode: 0xff34,
  // media / XF86 keys
  AudioVolumeMute: 0x1008ff12, AudioVolumeDown: 0x1008ff11,
  AudioVolumeUp: 0x1008ff13, MediaPlayPause: 0x1008ff14,
  MediaStop: 0x1008ff15, MediaTrackPrevious: 0x1008ff16,
  MediaTrackNext: 0x1008ff17, BrowserBack: 0x1008ff26,
  BrowserForward: 0x1008ff27, BrowserRefresh: 0x1008ff29,
  BrowserHome: 0x1008ff18,
};

const CODE_TO_KEYSYM = {
  ShiftRight: 0xffe2, ControlRight: 0xffe4, AltRight: 0xffea,
  MetaRight: 0xffe8,
  // keypad: ev.key reports the printable digit/operator, but X apps
  // distinguish KP_* keysyms (NumLock handling, games)
  NumpadEnter: 0xff8d, NumpadDivide: 0xffaf, NumpadMultiply: 0xffaa,
  NumpadSubtract: 0xffad, NumpadAdd: 0xffab, NumpadDecimal: 0xffae,
  Numpad0: 0xffb0, Numpad1: 0xffb1, Numpad2: 0xffb2, Numpad3: 0xffb3,
  Numpad4: 0xffb4, Numpad5: 0xffb5, Numpad6: 0xffb6, Numpad7: 0xffb7,
  Numpad8: 0xffb8, Numpad9: 0xffb9,
};

function eventKeysym(ev) {
  if (ev.code in CODE_TO_KEYSYM) return CODE_TO_KEYSYM[ev.code];
  if (ev.key && ev.key.length === 1) {
    const cp = ev.key.codePointAt(0);
    if (cp < 0x100) return cp;                  // latin-1 direct
    return 0x01000000 + cp;                     // X11 unicode rule
  }
  if (ev.key in KEY_TO_KEYSYM) return KEY_TO_KEYSYM[ev.key];
  return null;
}

class SelkiesInput {
  constructor(client, element) {
    this.client = client;
    this.el = element;
    this.buttonMask = 0;
    this.pointerLocked = false;
    this.gamepadTimer = null;
    this.gamepadState = new Map();   // index -> {buttons:[], axes:[]}
    this.gamepadIndexOffset = 0;     // player2-4 sharing: remap pad slot
    this._handlers = [];
    this.composing = false;
    this.trackpadMode = false;
    this._trackpad = { lastX: 0, lastY: 0, moved: 0, downAt: 0,
                       fingers: 0 };
    this._imeProxy = null;
  }

  /* Hidden text field hosting IME composition and summoning the mobile
     on-screen keyboard: dead keys and CJK input only produce composition
     events when an editable element has focus (reference lib/input.js
     composition handling). */
  _makeImeProxy() {
    const t = document.createElement("textarea");
    t.setAttribute("autocapitalize", "off");
    t.setAttribute("autocomplete", "off");
    t.setAttribute("spellcheck", "false");
    t.style.cssText = "position:fixed;left:-1000px;top:0;width:1px;" +
      "height:1px;opacity:0;z-index:-1;";
    document.body.appendChild(t);
    this._on(t, "compositionstart", () => { this.composing = true; });
    this._on(t, "compositionend", (ev) => {
      this.composing = false;
      if (ev.data) this.client.send("co,end," + ev.data);
      t.value = "";
    });
    this._on(t, "input", (ev) => {
      // mobile keyboards often emit no usable key events: text arrives
      // only here. Composition text is handled by compositionend.
      if (this.composing) return;
      if (ev.inputType === "insertText" && ev.data && !this._sentKey) {
        this.client.send("co,end," + ev.data);
      }
      if (ev.inputType === "deleteContentBackward" && !this._sentKey) {
        this.client.send("kd,65288");   // Backspace keysym 0xff08
        this.client.send("ku,65288");
      }
      t.value = "";
      this._sentKey = false;
    });
    return t;
  }

  popKeyboard() {
    if (!this._imeProxy) this._imeProxy = this._makeImeProxy();
    this._imeProxy.focus();
  }

  toggleTrackpadMode() {
    this.trackpadMode = !this.trackpadMode;
    return this.trackpadMode;
  }

  _on(target, type, fn, opts) {
    target.addEventListener(type, fn, opts);
    this._handlers.push([target, type, fn, opts]);
  }

  /* Sharing modes: a #player2-4 client contributes only its gamepad
     (reference sharing links, selkies-core.js hash modes). */
  attachGamepadOnly() {
    this._on(window, "gamepadconnected", (e) => this._gamepadConnected(e));
    this._on(window, "gamepaddisconnected",
             (e) => this._gamepadDisconnected(e));
  }

  attach() {
    const on = (target, type, fn, opts) => this._on(target, type, fn, opts);
    if (!this._imeProxy) this._imeProxy = this._makeImeProxy();
    on(window, "keydown", (e) => this._key(e, true));
    on(window, "keyup", (e) => this._key(e, false));
    on(window, "blur", () => this.client.send("kr"));
    on(this.el, "mousemove", (e) => this._motion(e));
    on(this.el, "mousedown", (e) => {
      this._button(e, true);
      // keep an editable element focused so dead keys / IME compose
      this._imeProxy.focus({ preventScroll: true });
    });
    on(this.el, "mouseup", (e) => this._button(e, false));
    on(this.el, "wheel", (e) => this._wheel(e), { passive: false });
    on(this.el, "contextmenu", (e) => e.preventDefault());
    on(this.el, "touchstart", (e) => this._touch(e, 1), { passive: false });
    on(this.el, "touchmove", (e) => this._touch(e, 1), { passive: false });
    on(this.el, "touchend", (e) => this._touch(e, 0), { passive: false });
    on(this.el, "touchcancel", (e) => this._touch(e, 0), { passive: false });
    on(document, "pointerlockchange",
       () => { this.pointerLocked = document.pointerLockElement === this.el; });
    on(window, "gamepadconnected", (e) => this._gamepadConnected(e));
    on(window, "gamepaddisconnected", (e) => this._gamepadDisconnected(e));
  }

  detach() {
    for (const [t, type, fn, opts] of this._handlers) {
      t.removeEventListener(type, fn, opts);
    }
    this._handlers = [];
    if (this.gamepadTimer) clearInterval(this.gamepadTimer);
    if (this._imeProxy) {
      this._imeProxy.remove();
      this._imeProxy = null;
    }
  }

  requestPointerLock() { this.el.requestPointerLock(); }

  /* -------------------------------------------------------- keyboard */

  _key(ev, down) {
    // _sentKey mirrors "was the most recent key event handled here?" —
    // reset on EVERY key event, set only when a keysym is sent. The
    // ime-proxy "input" event always follows its causal keydown, so this
    // is exactly the suppression it needs; a latched flag (cleared only
    // in the input handler) would swallow the first OSK character after
    // an Enter/Backspace, whose preventDefault'ed keydown fires no input.
    this._sentKey = false;
    // IME in progress: the composed string arrives via compositionend
    // (keydown during composition reports keyCode 229 / isComposing)
    if (ev.isComposing || ev.keyCode === 229 ||
        ev.key === "Process" || ev.key === "Dead" ||
        ev.key === "Unidentified") {
      return;
    }
    const keysym = eventKeysym(ev);
    if (keysym === null) return;
    ev.preventDefault();
    this._sentKey = true;
    this.client.send((down ? "kd," : "ku,") + keysym);
  }

  /* ----------------------------------------------------------- mouse */

  _canvasCoords(ev) {
    const rect = this.el.getBoundingClientRect();
    const sx = this.el.width / rect.width;
    const sy = this.el.height / rect.height;
    return [Math.round((ev.clientX - rect.left) * sx),
            Math.round((ev.clientY - rect.top) * sy)];
  }

  _motion(ev) {
    if (this.pointerLocked) {
      this.client.send(`m2,${ev.movementX},${ev.movementY},${this.buttonMask},0`);
    } else {
      const [x, y] = this._canvasCoords(ev);
      this.client.send(`m,${x},${y},${this.buttonMask},0`);
    }
  }

  _button(ev, down) {
    ev.preventDefault();
    const bit = 1 << ev.button;
    if (down) this.buttonMask |= bit;
    else this.buttonMask &= ~bit;
    this._motion(ev);
  }

  /* Direct mode: single-touch maps to a left-button drag at the touch
     point. Trackpad mode: the canvas becomes a laptop touchpad — one
     finger moves the remote pointer relatively, a quick tap clicks,
     two-finger vertical drag scrolls (reference trackpad touch mode). */
  _touch(ev, down) {
    ev.preventDefault();
    if (this.trackpadMode) {
      this._touchTrackpad(ev, down);
      return;
    }
    // on lift, report the finger that left; only release the button once
    // no touches remain (a brushing second finger must not break a drag)
    const t = down ? ev.touches[0] : ev.changedTouches[0];
    if (!t) return;
    const [x, y] = this._canvasCoords(t);
    if (down) this.buttonMask |= 1;
    else if (ev.touches.length === 0) this.buttonMask &= ~1;
    this.client.send(`m,${x},${y},${this.buttonMask},0`);
  }

  _touchTrackpad(ev, down) {
    const tp = this._trackpad;
    const t = ev.touches[0];
    if (ev.type === "touchstart") {
      tp.fingers = ev.touches.length;
      tp.lastX = t.clientX;
      tp.lastY = t.clientY;
      if (tp.fingers === 1) {
        tp.moved = 0;
        tp.downAt = performance.now();
      }
      return;
    }
    if (ev.type === "touchmove" && t) {
      const dx = t.clientX - tp.lastX;
      const dy = t.clientY - tp.lastY;
      tp.lastX = t.clientX;
      tp.lastY = t.clientY;
      tp.moved += Math.abs(dx) + Math.abs(dy);
      tp.fingers = Math.max(tp.fingers, ev.touches.length);
      if (ev.touches.length >= 2) {
        // two-finger scroll: wheel events at ~20 px per notch. The server
        // acts on mask EDGES, so each notch must be a press/release pair —
        // a held scroll bit would latch after the first notch.
        tp.scrollAcc = (tp.scrollAcc || 0) + dy;
        while (Math.abs(tp.scrollAcc) >= 20) {
          const bit = tp.scrollAcc > 0 ? 8 : 16;   // natural scrolling
          this.client.send(`m2,0,0,${this.buttonMask | bit},1`);
          this.client.send(`m2,0,0,${this.buttonMask},0`);
          tp.scrollAcc -= Math.sign(tp.scrollAcc) * 20;
        }
      } else {
        this.client.send(
          `m2,${Math.round(dx * 1.5)},${Math.round(dy * 1.5)},` +
          `${this.buttonMask},0`);
      }
      return;
    }
    // touchend / touchcancel
    if (ev.touches.length === 0) {
      const quick = performance.now() - tp.downAt < 250;
      if (quick && tp.moved < 8) {
        // tap → click; two-finger tap → right click
        const btn = tp.fingers >= 2 ? 4 : 1;
        this.client.send(`m2,0,0,${this.buttonMask | btn},0`);
        this.client.send(`m2,0,0,${this.buttonMask},0`);
      }
      tp.fingers = 0;
      tp.scrollAcc = 0;
    } else {
      // a finger lifted but others remain: re-baseline on the survivor so
      // the next move doesn't jump by the inter-finger distance
      tp.lastX = ev.touches[0].clientX;
      tp.lastY = ev.touches[0].clientY;
      tp.fingers = ev.touches.length;
    }
  }

  _wheel(ev) {
    ev.preventDefault();
    // scroll bits ride the mask like the reference: bit 3 up, bit 4 down
    const scrollBit = ev.deltaY < 0 ? 8 : 16;
    const magnitude = Math.min(15, Math.max(1,
      Math.round(Math.abs(ev.deltaY) / 40)));
    const [x, y] = this.pointerLocked ? [0, 0] : this._canvasCoords(ev);
    const prefix = this.pointerLocked ? "m2" : "m";
    this.client.send(
      `${prefix},${x},${y},${this.buttonMask | scrollBit},${magnitude}`);
  }

  /* --------------------------------------------------------- gamepad */

  /* A player2-4 sharing client owns exactly ONE fixed server slot
     (its offset); the host keeps local indices. Anything else collides
     when two clients both have a pad at local index 0. */
  _slotOf(localIndex) {
    if (this.gamepadIndexOffset) {
      return localIndex === 0 ? this.gamepadIndexOffset : null;
    }
    return localIndex;
  }

  _gamepadConnected(ev) {
    const gp = ev.gamepad;
    const slot = this._slotOf(gp.index);
    if (slot === null) return;
    // wire order is axes,buttons (server handler.py gamepad connect)
    this.client.send(
      `js,c,${slot},${btoa(gp.id).slice(0, 32)},` +
      `${gp.axes.length},${gp.buttons.length}`);
    this.gamepadState.set(gp.index, {
      buttons: gp.buttons.map((b) => b.value),
      axes: gp.axes.slice(),
    });
    if (!this.gamepadTimer) {
      this.gamepadTimer = setInterval(() => this._pollGamepads(), 16);
    }
  }

  _gamepadDisconnected(ev) {
    const slot = this._slotOf(ev.gamepad.index);
    if (slot !== null) this.client.send(`js,d,${slot}`);
    this.gamepadState.delete(ev.gamepad.index);
    if (!this.gamepadState.size && this.gamepadTimer) {
      clearInterval(this.gamepadTimer);
      this.gamepadTimer = null;
    }
  }

  _pollGamepads() {
    for (const gp of navigator.getGamepads()) {
      if (!gp) continue;
      const prev = this.gamepadState.get(gp.index);
      const slot = this._slotOf(gp.index);
      if (!prev || slot === null) continue;
      gp.buttons.forEach((b, i) => {
        if (b.value !== prev.buttons[i]) {
          prev.buttons[i] = b.value;
          this.client.send(`js,b,${slot},${i},${b.value.toFixed(3)}`);
        }
      });
      gp.axes.forEach((v, i) => {
        if (Math.abs(v - prev.axes[i]) > 0.01) {
          prev.axes[i] = v;
          this.client.send(`js,a,${slot},${i},${v.toFixed(3)}`);
        }
      });
    }
  }
}

if (typeof module !== "undefined") {
  module.exports = { SelkiesInput, eventKeysym, KEY_TO_KEYSYM };
}
