/*
 * selkies-client.js — browser client core for the selkies-tpu streaming
 * server (websockets mode).
 *
 * Role parity with the reference's addons/gst-web-core/selkies-core.js
 * (4,207 LoC): WebSocket connect + SETTINGS handshake, binary demux by
 * first byte (0x00 full-frame H.264 → VideoDecoder, 0x03 JPEG stripes →
 * ImageDecoder/createImageBitmap, 0x04 striped H.264 → per-stripe
 * VideoDecoder pool, 0x01 Opus → AudioDecoder → AudioWorklet), canvas
 * compositor, CLIENT_FRAME_ACK backpressure, clipboard and stats plumbing.
 * Fresh implementation against the byte-exact protocol documented in
 * selkies_tpu/protocol/wire.py.
 */

"use strict";

class SelkiesClient {
  constructor(opts) {
    this.canvas = opts.canvas;
    this.ctx = this.canvas.getContext("2d");
    this.url = opts.url ||
      (location.protocol === "https:" ? "wss://" : "ws://") +
      location.host + "/websockets";
    this.displayId = opts.displayId || "primary";
    this.onStatus = opts.onStatus || (() => {});
    this.onStats = opts.onStats || (() => {});
    this.onServerSettings = opts.onServerSettings || (() => {});
    this.onClipboard = opts.onClipboard || (() => {});
    this.onCursor = opts.onCursor || (() => {});

    this.settings = Object.assign({
      videoWidth: 1920, videoHeight: 1080, framerate: 60,
      encoder: "jpeg", videoQuality: 60,
    }, opts.settings || {});
    // Sharing viewers receive the primary broadcast without negotiating:
    // sending SETTINGS would take over (and kill) the host's session.
    this.claimDisplay = opts.claimDisplay !== false;

    this.ws = null;
    this.connected = false;
    this.lastFrameId = -1;
    this.ackTimer = null;
    this.statTimer = null;

    // decoders
    this.videoDecoder = null;          // full-frame H.264
    this._needKey = false;             // delta dropped: wait for key
    this.stripeDecoders = new Map();   // y_start -> VideoDecoder
    this.stripeSeq = new Map();        // y_start -> last frame_id painted
    this.audioCtx = null;
    this.audioDecoder = null;
    this.audioQueueTime = 0;

    // render fps accounting
    this.framesRendered = 0;
    this.lastFpsAt = performance.now();
    this.renderFps = 0;
    this.bytesReceived = 0;
  }

  /* ------------------------------------------------------ connection */

  connect() {
    this.onStatus("connecting");
    const ws = new WebSocket(this.url);
    ws.binaryType = "arraybuffer";
    this.ws = ws;
    ws.onopen = () => this._onOpen();
    ws.onmessage = (ev) => this._onMessage(ev);
    ws.onclose = () => this._onClose();
    ws.onerror = () => this.onStatus("error");
  }

  disconnect() {
    if (this.ackTimer) clearInterval(this.ackTimer);
    if (this.statTimer) clearInterval(this.statTimer);
    if (this.ws) this.ws.close();
  }

  _onOpen() {
    this.onStatus("negotiating");
    this.connected = true;
    this._acquireWakeLock();
    if (this.claimDisplay) {
      this.send("SETTINGS," + JSON.stringify(this.settings));
    }
    // client-ACK backpressure loop (reference selkies-core.js:2551-2560)
    this.ackTimer = setInterval(() => {
      if (this.lastFrameId >= 0 && this.connected) {
        this.send("CLIENT_FRAME_ACK " + this.lastFrameId);
      }
    }, 50);
    this.statTimer = setInterval(() => this._reportStats(), 1000);
    this.connected = true;
    this.onStatus("connected");
  }

  _onClose() {
    this.connected = false;
    this.onStatus("disconnected");
    if (this.ackTimer) clearInterval(this.ackTimer);
    if (this.statTimer) clearInterval(this.statTimer);
    this._releaseWakeLock();
    this._resetDecoders();
  }

  /* Screen wake lock: a remote desktop must not dim/lock mid-session
     (reference selkies-core.js wake-lock handling). Re-acquired when the
     tab returns to the foreground — the UA auto-releases on hide. */
  async _acquireWakeLock() {
    if (!navigator.wakeLock) return;
    try {
      const lock = await navigator.wakeLock.request("screen");
      // the connection may have closed while the request was pending —
      // a late resolve must not resurrect a lock release() already ended
      if (!this.connected) {
        try { lock.release(); } catch (e) {}
        return;
      }
      this._wakeLock = lock;
    } catch (e) { this._wakeLock = null; }
    if (!this._wakeVis) {
      this._wakeVis = () => {
        if (document.visibilityState === "visible" && this.connected) {
          this._acquireWakeLock();
        }
      };
      document.addEventListener("visibilitychange", this._wakeVis);
    }
  }

  _releaseWakeLock() {
    if (this._wakeLock) {
      try { this._wakeLock.release(); } catch (e) {}
      this._wakeLock = null;
    }
    if (this._wakeVis) {
      document.removeEventListener("visibilitychange", this._wakeVis);
      this._wakeVis = null;
    }
  }

  send(text) {
    if (this.ws && this.ws.readyState === WebSocket.OPEN) this.ws.send(text);
  }

  sendBinary(buf) {
    if (this.ws && this.ws.readyState === WebSocket.OPEN) this.ws.send(buf);
  }

  /* ----------------------------------------------------------- demux */

  _onMessage(ev) {
    if (typeof ev.data === "string") {
      this._onText(ev.data);
      return;
    }
    const data = new Uint8Array(ev.data);
    if (!data.length) return;
    this.bytesReceived += data.length;
    switch (data[0]) {
      case 0x00: this._onFullFrame(data); break;
      case 0x01: this._onAudio(data); break;
      case 0x03: this._onJpegStripe(data); break;
      case 0x04: this._onH264Stripe(data); break;
      default: break;
    }
  }

  _onText(msg) {
    if (msg.startsWith("MODE ")) return;
    if (msg.startsWith("PIPELINE_RESETTING")) {
      this.lastFrameId = -1;
      this._resetDecoders();
      return;
    }
    if (msg.startsWith("KILL")) {
      this.onStatus("superseded");
      this.disconnect();
      return;
    }
    if (msg.startsWith("cursor,")) {
      try { this.onCursor(JSON.parse(msg.slice(7))); } catch (e) {}
      return;
    }
    if (msg.startsWith("clipboard,")) {
      // inverse of sendClipboard: base64 of UTF-8 bytes
      try {
        this.onClipboard(decodeURIComponent(escape(atob(msg.slice(10)))));
      } catch (e) {}
      return;
    }
    if (msg.startsWith("VIDEO_") || msg.startsWith("AUDIO_")) return;
    if (msg.startsWith("{")) {
      let body;
      try { body = JSON.parse(msg); } catch (e) { return; }
      if (body.type === "server_settings") {
        this.onServerSettings(body.settings || body);
      } else if (body.type === "stream_resolution") {
        this._applyResolution(body);
      } else if (body.type && (body.type.endsWith("_stats") ||
                               body.type === "system_health")) {
        // system_health carries the flight-recorder stage breakdown
        // (where each frame's time went) alongside supervision state
        this.onStats(body);
      }
    }
  }

  _applyResolution(body) {
    const w = body.width || this.settings.videoWidth;
    const h = body.height || this.settings.videoHeight;
    if (this.canvas.width !== w || this.canvas.height !== h) {
      this.canvas.width = w;
      this.canvas.height = h;
      this._resetDecoders();
    }
  }

  _u16(data, off) { return (data[off] << 8) | data[off + 1]; }

  /* ------------------------------------------------------ video: JPEG */

  async _onJpegStripe(data) {
    const frameId = this._u16(data, 2);
    const yStart = this._u16(data, 4);
    const blob = new Blob([data.subarray(6)], { type: "image/jpeg" });
    try {
      const bmp = await createImageBitmap(blob);
      // async decode can complete out of order: never paint a stripe
      // older (mod 2^16) than what's already on screen at this y
      const prev = this.stripeSeq.get(yStart);
      if (prev !== undefined && ((frameId - prev) & 0xffff) > 0x8000) {
        bmp.close();
        return;
      }
      this.stripeSeq.set(yStart, frameId);
      this.ctx.drawImage(bmp, 0, yStart);
      bmp.close();
      this._frameDelivered(frameId);
    } catch (e) { /* damaged stripe: skip, next key stripe repairs */ }
  }

  /* ------------------------------------------------ video: full H.264 */

  _makeVideoDecoder(onFrame) {
    const dec = new VideoDecoder({
      output: onFrame,
      error: (e) => { console.warn("VideoDecoder error", e); },
    });
    dec.configure({
      codec: "avc1.42e01f",
      optimizeForLatency: true,
    });
    return dec;
  }

  _onFullFrame(data) {
    const isKey = data[1] === 1;
    const frameId = this._u16(data, 2);
    if (!this.videoDecoder || this.videoDecoder.state === "closed") {
      if (!isKey) return;   // wait for a keyframe to start
      this.videoDecoder = this._makeVideoDecoder((frame) => {
        this.ctx.drawImage(frame, 0, 0);
        frame.close();
      });
    }
    // after any skipped delta the reference chain is broken: discard
    // further deltas until the next keyframe repairs it
    if (!isKey && this._needKey) return;
    if (!isKey && this.videoDecoder.decodeQueueSize > 8) {
      this._needKey = true;
      return;
    }
    if (isKey) this._needKey = false;
    try {
      this.videoDecoder.decode(new EncodedVideoChunk({
        type: isKey ? "key" : "delta",
        timestamp: performance.now() * 1000,
        data: data.subarray(4),
      }));
      this._frameDelivered(frameId);
    } catch (e) { this._resetDecoders(); }
  }

  /* --------------------------------------------- video: striped H.264 */

  _onH264Stripe(data) {
    const isKey = data[1] === 1;
    const frameId = this._u16(data, 2);
    const yStart = this._u16(data, 4);
    let entry = this.stripeDecoders.get(yStart);
    if (!entry) {
      if (!isKey) return;
      const dec = this._makeVideoDecoder((frame) => {
        this.ctx.drawImage(frame, 0, yStart);
        frame.close();
      });
      entry = { dec };
      this.stripeDecoders.set(yStart, entry);
    }
    try {
      entry.dec.decode(new EncodedVideoChunk({
        type: isKey ? "key" : "delta",
        timestamp: performance.now() * 1000,
        data: data.subarray(10),
      }));
      this._frameDelivered(frameId);
    } catch (e) {
      this.stripeDecoders.delete(yStart);
    }
  }

  /* ----------------------------------------------------------- audio */

  /* AudioWorklet ring processor (reference selkies-core.js:2360-2460):
     decoded PCM lands in a ring buffer drained by the audio render
     thread; a ~40 ms jitter threshold absorbs network/decode timing
     wobble that the old per-chunk createBufferSource scheduling turned
     into audible glitches. */
  static AUDIO_WORKLET = `
    class SelkiesRing extends AudioWorkletProcessor {
      constructor() {
        super();
        this.cap = 48000;                       // 1 s per channel
        this.ring = [new Float32Array(this.cap),
                     new Float32Array(this.cap)];
        this.w = 0; this.r = 0; this.started = false;
        this.jitter = 1920;                     // 40 ms @ 48 kHz
        this.port.onmessage = (ev) => {
          const { ch0, ch1 } = ev.data;
          for (let i = 0; i < ch0.length; i++) {
            const p = this.w % this.cap;
            this.ring[0][p] = ch0[i];
            this.ring[1][p] = (ch1 || ch0)[i];
            this.w++;
          }
          // overrun: drop the oldest (reader too slow / tab throttled)
          if (this.w - this.r > this.cap) this.r = this.w - this.cap;
        };
      }
      process(inputs, outputs) {
        const out = outputs[0];
        const avail = this.w - this.r;
        if (!this.started) {
          if (avail < this.jitter) return true;  // build the jitter floor
          this.started = true;
        }
        if (avail < out[0].length) {
          this.started = false;                  // underrun: rebuffer
          return true;
        }
        for (let i = 0; i < out[0].length; i++) {
          const p = this.r % this.cap;
          out[0][i] = this.ring[0][p];
          if (out[1]) out[1][i] = this.ring[1][p];
          this.r++;
        }
        return true;
      }
    }
    registerProcessor("selkies-ring", SelkiesRing);`;

  _ensureAudio() {
    // single-flight init: concurrent _onAudio calls await the same setup,
    // and a worklet failure degrades to the per-chunk fallback instead of
    // leaving audio permanently dead (e.g. CSP without blob: scripts)
    if (this._audioInit) return this._audioInit;
    this._audioInit = (async () => {
      this.audioCtx = new AudioContext({ sampleRate: 48000 });
      try {
        if (this.audioCtx.audioWorklet) {
          const url = URL.createObjectURL(new Blob(
            [SelkiesClient.AUDIO_WORKLET],
            { type: "application/javascript" }));
          try {
            await this.audioCtx.audioWorklet.addModule(url);
          } finally {
            URL.revokeObjectURL(url);
          }
          this.audioNode = new AudioWorkletNode(
            this.audioCtx, "selkies-ring", { outputChannelCount: [2] });
          this.audioNode.connect(this.audioCtx.destination);
        }
      } catch (e) {
        console.warn("AudioWorklet unavailable; per-chunk fallback", e);
        this.audioNode = null;
      }
      this.audioDecoder = new AudioDecoder({
        output: (audioData) => this._playAudio(audioData),
        error: (e) => console.warn("AudioDecoder error", e),
      });
      this.audioDecoder.configure({
        codec: "opus", sampleRate: 48000, numberOfChannels: 2,
      });
    })();
    return this._audioInit;
  }

  async _onAudio(data) {
    try {
      await this._ensureAudio();
      this.audioDecoder.decode(new EncodedAudioChunk({
        type: "key",
        timestamp: performance.now() * 1000,
        data: data.subarray(2),
      }));
    } catch (e) { /* audio is best-effort */ }
  }

  _playAudio(audioData) {
    if (this.audioNode) {
      const n = audioData.numberOfFrames;
      const ch0 = new Float32Array(n);
      audioData.copyTo(ch0, { planeIndex: 0, format: "f32-planar" });
      let ch1 = ch0;
      if (audioData.numberOfChannels > 1) {
        ch1 = new Float32Array(n);
        audioData.copyTo(ch1, { planeIndex: 1, format: "f32-planar" });
      }
      audioData.close();
      this.audioNode.port.postMessage({ ch0, ch1 },
        ch1 === ch0 ? [ch0.buffer] : [ch0.buffer, ch1.buffer]);
      return;
    }
    // fallback path: per-chunk scheduling (no AudioWorklet support)
    const ctx = this.audioCtx;
    const buf = ctx.createBuffer(
      audioData.numberOfChannels, audioData.numberOfFrames, 48000);
    for (let ch = 0; ch < audioData.numberOfChannels; ch++) {
      const arr = new Float32Array(audioData.numberOfFrames);
      audioData.copyTo(arr, { planeIndex: ch, format: "f32-planar" });
      buf.copyToChannel(arr, ch);
    }
    audioData.close();
    const src = ctx.createBufferSource();
    src.buffer = buf;
    src.connect(ctx.destination);
    const now = ctx.currentTime;
    if (this.audioQueueTime < now + 0.02) this.audioQueueTime = now + 0.02;
    src.start(this.audioQueueTime);
    this.audioQueueTime += buf.duration;
  }

  /* -------------------------------------------------- mic (reverse) */

  async startMicrophone() {
    const stream = await navigator.mediaDevices.getUserMedia({ audio: true });
    // server MicSink plays at the capture settings rate (48 kHz default)
    const ctx = new AudioContext({ sampleRate: 48000 });
    const srcNode = ctx.createMediaStreamSource(stream);
    const proc = ctx.createScriptProcessor(1024, 1, 1);
    proc.onaudioprocess = (ev) => {
      const f32 = ev.inputBuffer.getChannelData(0);
      const s16 = new Int16Array(f32.length);
      for (let i = 0; i < f32.length; i++) {
        s16[i] = Math.max(-32768, Math.min(32767, f32[i] * 32768));
      }
      const framed = new Uint8Array(1 + s16.byteLength);
      framed[0] = 0x02;                    // MIC_PCM
      framed.set(new Uint8Array(s16.buffer), 1);
      this.sendBinary(framed.buffer);
    };
    srcNode.connect(proc);
    proc.connect(ctx.destination);
    this._micCtx = ctx;
  }

  /* ------------------------------------------------------- clipboard */

  sendClipboard(text) {
    this.send("cw," + btoa(unescape(encodeURIComponent(text))));
  }

  requestClipboard() { this.send("cr"); }

  /* ----------------------------------------------------- file upload */

  async uploadFile(file) {
    this.send(`FILE_UPLOAD_START:${file.name}:${file.size}`);
    const chunk = 256 * 1024;
    const highWater = 4 * 1024 * 1024;
    for (let off = 0; off < file.size; off += chunk) {
      // backpressure: don't balloon the socket buffer past the drain rate
      while (this.ws && this.ws.bufferedAmount > highWater) {
        await new Promise((r) => setTimeout(r, 20));
      }
      if (!this.ws || this.ws.readyState !== WebSocket.OPEN) {
        this.send(`FILE_UPLOAD_ERROR:${file.name}:connection lost`);
        return;
      }
      const slice = await file.slice(off, off + chunk).arrayBuffer();
      const framed = new Uint8Array(1 + slice.byteLength);
      framed[0] = 0x01;                    // FILE_CHUNK
      framed.set(new Uint8Array(slice), 1);
      this.sendBinary(framed.buffer);
    }
    this.send(`FILE_UPLOAD_END:${file.name}`);
  }

  /* --------------------------------------------------------- control */

  requestResize(w, h) {
    this.send(`r,${w}x${h},${this.displayId}`);
  }

  setVideoEnabled(on) { this.send(on ? "START_VIDEO" : "STOP_VIDEO"); }
  setAudioEnabled(on) { this.send(on ? "START_AUDIO" : "STOP_AUDIO"); }

  /* ----------------------------------------------------------- stats */

  _frameDelivered(frameId) {
    // only advance the ACK id forward (mod 2^16): a late stripe must not
    // regress it and inflate the server's backpressure estimate
    if (this.lastFrameId < 0 ||
        ((frameId - this.lastFrameId) & 0xffff) < 0x8000) {
      this.lastFrameId = frameId;
    }
    this.framesRendered++;
  }

  _reportStats() {
    const now = performance.now();
    const dt = (now - this.lastFpsAt) / 1000;
    this.renderFps = this.framesRendered / Math.max(dt, 1e-3);
    this.framesRendered = 0;
    this.lastFpsAt = now;
    this.send("_f " + Math.round(this.renderFps));
    this.onStats({
      type: "client_stats",
      fps: this.renderFps,
      kbps: Math.round(this.bytesReceived * 8 / 1000 / Math.max(dt, 1e-3)),
    });
    this.bytesReceived = 0;
  }

  _resetDecoders() {
    if (this.videoDecoder && this.videoDecoder.state !== "closed") {
      try { this.videoDecoder.close(); } catch (e) {}
    }
    this.videoDecoder = null;
    for (const { dec } of this.stripeDecoders.values()) {
      try { dec.close(); } catch (e) {}
    }
    this.stripeDecoders.clear();
  }
}

if (typeof module !== "undefined") module.exports = { SelkiesClient };
