/*
 * touch-gamepad.js — fullscreen multi-touch overlay that fakes a standard
 * gamepad into navigator.getGamepads().
 *
 * Role parity with the reference's addons/universal-touch-gamepad
 * (universalTouchGamepad.js, 863 LoC): a left virtual stick (axes 0/1), a
 * right cluster of A/B/X/Y buttons, shoulder buttons, and start/select,
 * surfaced through a getGamepads() patch so the existing SelkiesInput
 * gamepad polling ships events unchanged. Enable with
 * `TouchGamepad.enable(canvas)`, disable to restore the native API.
 */

"use strict";

const TouchGamepad = (() => {
  const state = {
    enabled: false,
    overlay: null,
    nativeGetGamepads: null,
    pad: null,
    touches: new Map(),   // identifier -> control
  };

  const VIRTUAL_INDEX = 3;   // stay clear of physical pads at 0-2

  function makePad() {
    return {
      id: "Selkies Touch Gamepad (virtual)",
      index: VIRTUAL_INDEX,
      connected: true,
      mapping: "standard",
      timestamp: performance.now(),
      axes: [0, 0, 0, 0],
      buttons: Array.from({ length: 17 }, () => ({
        pressed: false, touched: false, value: 0 })),
    };
  }

  // layout: fractions of viewport; [kind, payload, cx, cy, radius]
  const CONTROLS = [
    ["stick", null, 0.18, 0.72, 0.13],
    ["button", 0, 0.88, 0.72, 0.055],   // A
    ["button", 1, 0.94, 0.62, 0.055],   // B
    ["button", 2, 0.82, 0.62, 0.055],   // X
    ["button", 3, 0.88, 0.52, 0.055],   // Y
    ["button", 4, 0.12, 0.38, 0.06],    // LB
    ["button", 5, 0.88, 0.38, 0.06],    // RB
    ["button", 8, 0.42, 0.88, 0.045],   // select
    ["button", 9, 0.58, 0.88, 0.045],   // start
  ];

  function controlAt(x, y, w, h) {
    for (const c of CONTROLS) {
      const [kind, payload, fx, fy, fr] = c;
      const dx = x - fx * w;
      const dy = y - fy * h;
      const r = fr * Math.min(w, h) * 2.2;   // generous hit area
      if (dx * dx + dy * dy < r * r) return { kind, payload, fx, fy, fr };
    }
    return null;
  }

  function onTouch(ev) {
    ev.preventDefault();
    const w = window.innerWidth;
    const h = window.innerHeight;
    const pad = state.pad;
    for (const t of ev.changedTouches) {
      if (ev.type === "touchstart") {
        const ctl = controlAt(t.clientX, t.clientY, w, h);
        if (ctl) state.touches.set(t.identifier, ctl);
      }
      const ctl = state.touches.get(t.identifier);
      if (!ctl) continue;
      if (ctl.kind === "stick") {
        if (ev.type === "touchend" || ev.type === "touchcancel") {
          pad.axes[0] = pad.axes[1] = 0;
          state.touches.delete(t.identifier);
        } else {
          const r = ctl.fr * Math.min(w, h);
          pad.axes[0] = Math.max(-1, Math.min(1, (t.clientX - ctl.fx * w) / r));
          pad.axes[1] = Math.max(-1, Math.min(1, (t.clientY - ctl.fy * h) / r));
        }
      } else {
        const down = ev.type === "touchstart" || ev.type === "touchmove";
        const b = pad.buttons[ctl.payload];
        b.pressed = b.touched = down;
        b.value = down ? 1 : 0;
        if (!down) state.touches.delete(t.identifier);
      }
    }
    pad.timestamp = performance.now();
  }

  function drawOverlay(el) {
    el.innerHTML = "";
    const w = window.innerWidth;
    const h = window.innerHeight;
    for (const [kind, payload, fx, fy, fr] of CONTROLS) {
      const d = document.createElement("div");
      const r = fr * Math.min(w, h);
      d.style.cssText =
        "position:absolute;border:2px solid rgba(255,255,255,.45);" +
        "border-radius:50%;background:rgba(255,255,255,.08);" +
        "display:flex;align-items:center;justify-content:center;" +
        "color:rgba(255,255,255,.6);font:12px system-ui;" +
        `left:${fx * w - r}px;top:${fy * h - r}px;` +
        `width:${2 * r}px;height:${2 * r}px;`;
      d.textContent = kind === "stick" ? "" :
        ({0: "A", 1: "B", 2: "X", 3: "Y", 4: "LB", 5: "RB",
          8: "SEL", 9: "ST"})[payload] || "";
      el.appendChild(d);
    }
  }

  function enable() {
    if (state.enabled) return;
    state.enabled = true;
    state.pad = makePad();
    const el = document.createElement("div");
    el.style.cssText = "position:fixed;inset:0;z-index:50;touch-action:none;";
    drawOverlay(el);
    for (const t of ["touchstart", "touchmove", "touchend", "touchcancel"]) {
      el.addEventListener(t, onTouch, { passive: false });
    }
    document.body.appendChild(el);
    state.overlay = el;
    state.onResize = () => drawOverlay(el);
    window.addEventListener("resize", state.onResize);

    state.nativeGetGamepads = navigator.getGamepads.bind(navigator);
    navigator.getGamepads = () => {
      const pads = Array.from(state.nativeGetGamepads() || []);
      while (pads.length <= VIRTUAL_INDEX) pads.push(null);
      pads[VIRTUAL_INDEX] = state.pad;   // never clobber a physical pad
      return pads;
    };
    const ev = new Event("gamepadconnected");
    ev.gamepad = state.pad;
    window.dispatchEvent(ev);
  }

  function disable() {
    if (!state.enabled) return;
    state.enabled = false;
    if (state.onResize) window.removeEventListener("resize", state.onResize);
    if (state.overlay) state.overlay.remove();
    if (state.nativeGetGamepads) {
      navigator.getGamepads = state.nativeGetGamepads;
    }
    const ev = new Event("gamepaddisconnected");
    ev.gamepad = state.pad;
    window.dispatchEvent(ev);
  }

  return { enable, disable };
})();

if (typeof module !== "undefined") module.exports = { TouchGamepad };
