/*
 * dashboard.js — schema-driven control sidebar for the selkies-tpu client.
 *
 * Role parity with the reference React dashboard
 * (addons/selkies-dashboard/src/components/Sidebar.jsx:338-1395): settings
 * panels bound to the server_settings schema the server pushes at connect
 * (every range/enum/locked constraint is rendered from that push, so
 * whatever the server can clamp, the user can tune — and nothing more),
 * stats readout, clipboard, file upload + download modal (./files/ on the
 * web port), sharing links per enable_* flag, command launcher, gamepad
 * visualizer, and core buttons (fullscreen / gaming mode / keyboard /
 * trackpad / touch gamepad). No build step: plain DOM, the TPU repo image
 * carries no node toolchain.
 */

"use strict";

class SelkiesDashboard {
  constructor(opts) {
    this.root = opts.root;
    this.canvas = opts.canvas;
    this.wsUrl = opts.wsUrl;
    this.mode = opts.mode || "full";       // full | shared | player2..4
    this.client = null;
    this.input = null;
    this.schema = null;                    // server_settings push
    this.stats = {};
    this.widgets = new Map();              // setting name -> input element
    this.overrides = this._loadLocal();
    this._sendTimer = null;
    this._gamepadTimer = null;
    this._build();
  }

  /* ------------------------------------------------------- persistence */

  _loadLocal() {
    try {
      return JSON.parse(localStorage.getItem("selkies_settings") || "{}");
    } catch (e) { return {}; }
  }

  _saveLocal() {
    try {
      localStorage.setItem("selkies_settings",
        JSON.stringify(this.overrides));
    } catch (e) {}
  }

  /* -------------------------------------------------------- DOM helpers */

  _el(tag, attrs, ...children) {
    const el = document.createElement(tag);
    for (const [k, v] of Object.entries(attrs || {})) {
      if (k === "class") el.className = v;
      else if (k.startsWith("on")) el[k] = v;
      else el.setAttribute(k, v);
    }
    for (const c of children) {
      el.append(c);
    }
    return el;
  }

  _section(title, bodyEl, open) {
    const content = this._el("div", { class: "sect-body" });
    content.append(bodyEl);
    if (!open) content.classList.add("hidden");
    const head = this._el("div", {
      class: "sect-head",
      onclick: () => {
        content.classList.toggle("hidden");
        if (title === "Gamepads") this._gamepadVisibility();
      },
    }, title);
    const wrap = this._el("div", { class: "sect" }, head, content);
    wrap._content = content;
    return wrap;
  }

  _label(text, control) {
    return this._el("label", {}, this._el("span", {}, text), control);
  }

  static pretty(name) {
    return name.replace(/^(h264|ui|is)_/, (m) => m.toUpperCase()
        .replace("_", " ") + " ")
      .replace(/_/g, " ")
      .replace(/\b\w/g, (c) => c.toUpperCase())
      .replace("Jpeg", "JPEG").replace("Crf", "CRF").replace("Dpi", "DPI")
      .replace("Cpu", "CPU").replace("Css", "CSS");
  }

  /* --------------------------------------------------------- skeleton */

  _build() {
    this.root.textContent = "";
    this.titleEl = this._el("h1", {}, "selkies-tpu");
    this.statusEl = this._el("div", { id: "status" }, "idle");
    this.connectBtn = this._el("button", {
      onclick: () => this.connect(),
    }, "Connect");
    this.coreBtns = this._buildCoreButtons();
    this.settingsHost = this._el("div", {});   // filled on schema push
    this.root.append(this.titleEl, this.statusEl, this.connectBtn,
      this.coreBtns, this.settingsHost);
    // settings/stats/clipboard/... sections materialize when the server
    // pushes its schema (onServerSettings) — the schema is the source of
    // truth for what exists, so nothing renders speculatively before it
  }

  _buildCoreButtons() {
    const mk = (label, fn) => this._el("button",
      { class: "secondary", onclick: fn }, label);
    const wrap = this._el("div", { class: "btnrow" });
    wrap.append(
      mk("Fullscreen", () => this.canvas.requestFullscreen()),
      mk("Gaming mode", () => this.input && this.input.requestPointerLock()),
      mk("Keyboard", () => this.input && this.input.popKeyboard
        ? this.input.popKeyboard() : this.canvas.focus()),
      this.trackpadBtn = mk("Trackpad", () => {
        if (!this.input) return;
        const on = this.input.toggleTrackpadMode
          ? this.input.toggleTrackpadMode() : false;
        this.trackpadBtn.classList.toggle("active", on);
      }),
      mk("Touch pad", () => {
        this._touchpadOn = !this._touchpadOn;
        if (this._touchpadOn) TouchGamepad.enable();
        else TouchGamepad.disable();
      }),
      mk("Mic", () => this.client && this.client.startMicrophone()),
    );
    return wrap;
  }

  /* -------------------------------------------- schema-driven settings */

  onServerSettings(schema) {
    this.schema = schema;
    if (schema.ui_title && schema.ui_title.value) {
      document.title = schema.ui_title.value;
      this.titleEl.textContent = schema.ui_title.value;
    }
    if (schema.ui_show_logo && schema.ui_show_logo.value === false) {
      this.titleEl.classList.add("hidden");
    }
    if (schema.ui_show_core_buttons &&
        schema.ui_show_core_buttons.value === false) {
      this.coreBtns.classList.add("hidden");
    }
    this._renderSettingSections();
    this._renderSharing();
    this._renderFiles();
    this._renderApps();
  }

  static SECTIONS = [
    ["Video", "ui_sidebar_show_video_settings", [
      "encoder", "framerate", "jpeg_quality", "h264_crf",
      "use_paint_over_quality", "paint_over_jpeg_quality",
      "h264_paintover_crf", "h264_paintover_burst_frames",
      "h264_fullcolor", "h264_streaming_mode", "use_cpu"]],
    ["Screen", "ui_sidebar_show_screen_settings", [
      "is_manual_resolution_mode", "manual_width", "manual_height",
      "scaling_dpi", "use_css_scaling", "use_browser_cursors",
      "second_screen", "second_screen_position"]],
    ["Audio", "ui_sidebar_show_audio_settings", [
      "audio_enabled", "audio_bitrate", "microphone_enabled"]],
  ];

  _renderSettingSections() {
    this.settingsHost.textContent = "";
    this.widgets.clear();
    if (this._gamepadTimer) {     // old sidebar's draw loop dies with it
      clearInterval(this._gamepadTimer);
      this._gamepadTimer = null;
    }
    const used = new Set();
    for (const [title, gate, names] of SelkiesDashboard.SECTIONS) {
      names.forEach((n) => used.add(n));
      if (this.schema[gate] && this.schema[gate].value === false) continue;
      const body = this._el("div", {});
      if (title === "Screen") this._appendResolutionControls(body);
      for (const name of names) {
        const entry = this.schema[name];
        if (!entry) continue;
        const w = this._widgetFor(name, entry);
        if (w) body.append(w);
      }
      this.settingsHost.append(this._section(title, body, title === "Video"));
    }
    // everything else the server exposes lands in Advanced — the schema,
    // not this file, is the source of truth for what is tunable
    const adv = this._el("div", {});
    for (const [name, entry] of Object.entries(this.schema)) {
      if (used.has(name) || name.startsWith("ui_") ||
          name.startsWith("enable_") || name === "type" ||
          name === "settings" || name === "file_transfers" ||
          name === "command_enabled" || name === "watermark_location") {
        continue;
      }
      if (typeof entry !== "object" || entry === null) continue;
      const w = this._widgetFor(name, entry);
      if (w) adv.append(w);
    }
    if (adv.childNodes.length) {
      this.settingsHost.append(this._section("Advanced", adv, false));
    }
    this._appendStatsSection();
    this._appendClipboardSection();
    this._appendGamepadSection();
  }

  _widgetFor(name, entry) {
    let control;
    const current = name in this.overrides ? this.overrides[name]
      : entry.value;
    if (typeof entry.value === "boolean") {
      control = this._el("input", {
        type: "checkbox",
        onchange: (ev) => this._setSetting(name, ev.target.checked),
      });
      control.checked = !!current;
      if (entry.locked) control.disabled = true;
    } else if ("min" in entry && "max" in entry) {
      if (entry.min === entry.max) {            // single-value range: locked
        control = this._el("input", { type: "number", disabled: "" });
        control.value = entry.min;
      } else {
        control = this._el("input", {
          type: "number", min: entry.min, max: entry.max,
          onchange: (ev) => {
            const v = Math.min(entry.max,
              Math.max(entry.min, +ev.target.value));
            ev.target.value = v;
            this._setSetting(name, v);
          },
        });
        control.value = current;
      }
    } else if (Array.isArray(entry.allowed) &&
               !Array.isArray(entry.value)) {
      control = this._el("select", {
        onchange: (ev) => this._setSetting(name, ev.target.value),
      });
      for (const v of entry.allowed) {
        control.append(this._el("option", { value: v }, String(v)));
      }
      control.value = String(current);
    } else {
      return null;  // capability lists / free strings: not user-tunable
    }
    this.widgets.set(name, control);
    return this._label(SelkiesDashboard.pretty(name), control);
  }

  _setSetting(name, value) {
    this.overrides[name] = value;
    this._saveLocal();
    if (name === "audio_enabled" && this.client) {
      this.client.setAudioEnabled(!!value);
    }
    clearTimeout(this._sendTimer);
    this._sendTimer = setTimeout(() => this._pushSettings(), 250);
  }

  _pushSettings() {
    if (!this.client || !this.client.connected || this.mode !== "full") {
      return;
    }
    this.client.send("SETTINGS," + JSON.stringify(Object.assign({
      displayId: "primary",
      initialClientWidth: this.canvas.width,
      initialClientHeight: this.canvas.height,
    }, this.overrides)));
  }

  _appendResolutionControls(body) {
    const presets = ["1280x720", "1920x1080", "2560x1440", "3840x2160"];
    const sel = this._el("select", {
      onchange: (ev) => {
        const [w, h] = ev.target.value.split("x").map(Number);
        if (this.client) this.client.requestResize(w, h);
      },
    });
    sel.append(this._el("option", { value: "" }, "window size"));
    for (const p of presets) sel.append(this._el("option", { value: p }, p));
    body.append(this._label("Resolution", sel));
  }

  /* ------------------------------------------------------------ stats */

  _appendStatsSection() {
    if (this.schema.ui_sidebar_show_stats &&
        this.schema.ui_sidebar_show_stats.value === false) return;
    this.statsEl = this._el("div", { id: "stats" });
    this.settingsHost.append(
      this._section("Stats", this.statsEl, true));
    this._renderStats();
  }

  onStats(s) {
    if (s.type === "client_stats") {
      this.stats.fps = s.fps.toFixed(1);
      this.stats.kbps = s.kbps;
    } else if (s.type === "system_stats") {
      if ("cpu_percent" in s) this.stats.cpu = s.cpu_percent + "%";
      if ("mem_percent" in s) this.stats.mem = s.mem_percent + "%";
    } else if (s.type === "gpu_stats") {
      if ("utilization" in s) this.stats.tpu = s.utilization + "%";
    } else if (s.type === "network_stats") {
      if ("bytes_sent" in s) {
        this.stats.sent = (s.bytes_sent / 1e6).toFixed(1) + " MB";
      }
      if ("rtt_ms" in s) this.stats.rtt = s.rtt_ms + " ms";
    } else if (s.type === "system_health") {
      // flight-recorder stage breakdown: where each frame's time went
      // (p50 ms per stage, pushed by the server's system_health feed)
      for (const [id, d] of Object.entries(s.displays || {})) {
        if (!d.stages) continue;
        const parts = Object.entries(d.stages)
          .map(([st, v]) => st + " " + v.p50_ms.toFixed(1));
        let line = parts.join(" | ");
        if ("glass_to_glass_p50_ms" in d) {
          line = "g2g " + d.glass_to_glass_p50_ms + " ms | " + line;
        }
        this.stats["t:" + id] = line;
      }
      if (s.mesh) {
        // session-scheduler occupancy per geometry bucket: attached/
        // capacity slots, lanes, and any quarantined fault domains
        const parts = Object.entries(s.mesh).map(([bucket, m]) => {
          let line = bucket + " " + m.active_sessions + "/" +
            m.capacity_slots + " (" + m.lanes + " lanes)";
          if (m.sfe_shards > 1) {
            // split-frame encoding: one frame sharded across N chips,
            // with the host-side slice-concat share of the harvest
            line += " sfe" + m.sfe_shards;
            if (m.sfe_concat_ms_p50) {
              line += " cat" + m.sfe_concat_ms_p50.toFixed(1);
            }
          }
          if (m.quarantined_slots) {
            line += " q" + m.quarantined_slots;
          }
          if (m.migrations_total) {
            line += " mig" + m.migrations_total;
          }
          return line;
        });
        this.stats.mesh = parts.join(" | ");
      }
    }
    this._renderStats();
  }

  _renderStats() {
    if (!this.statsEl) return;
    this.statsEl.textContent = Object.entries(this.stats)
      .map(([k, v]) => `${k.padEnd(6)} ${v}`).join("\n");
  }

  /* -------------------------------------------------------- clipboard */

  _appendClipboardSection() {
    if (this.schema.ui_sidebar_show_clipboard &&
        this.schema.ui_sidebar_show_clipboard.value === false) return;
    if (this.schema.clipboard_enabled &&
        this.schema.clipboard_enabled.value === false) return;
    this.clipEl = this._el("textarea", { rows: 3 });
    const send = this._el("button", {
      class: "secondary",
      onclick: () => this.client &&
        this.client.sendClipboard(this.clipEl.value),
    }, "Send to remote");
    const body = this._el("div", {}, this.clipEl, send);
    this.settingsHost.append(this._section("Clipboard", body, false));
  }

  onClipboard(text) {
    if (this.clipEl) this.clipEl.value = text;
    if (navigator.clipboard) {
      navigator.clipboard.writeText(text).catch(() => {});
    }
  }

  /* ------------------------------------------------------------ files */

  _renderFiles() {
    if (this.schema.ui_sidebar_show_files &&
        this.schema.ui_sidebar_show_files.value === false) return;
    const ft = (this.schema.file_transfers &&
      this.schema.file_transfers.value) || [];
    const body = this._el("div", {});
    if (ft.includes("upload")) {
      const picker = this._el("input", {
        type: "file", multiple: "", class: "hidden",
        onchange: async (ev) => {
          for (const f of ev.target.files) {
            if (this.client) await this.client.uploadFile(f);
          }
        },
      });
      body.append(picker, this._el("button", {
        class: "secondary", onclick: () => picker.click(),
      }, "Upload files"));
    }
    if (ft.includes("download")) {
      body.append(this._el("button", {
        class: "secondary", onclick: () => this._toggleFilesModal(),
      }, "Download files"));
    }
    if (body.childNodes.length) {
      this.settingsHost.append(this._section("Files", body, false));
    }
  }

  _toggleFilesModal() {
    if (this._filesModal) {
      this._filesModal.remove();
      this._filesModal = null;
      return;
    }
    const frame = this._el("iframe", { src: "./files/" });
    const close = this._el("button", {
      class: "modal-close",
      onclick: () => this._toggleFilesModal(),
    }, "×");
    this._filesModal = this._el("div", { class: "modal" }, close, frame);
    document.body.append(this._filesModal);
  }

  /* ------------------------------------------------------------- apps */

  _renderApps() {
    if (this.schema.ui_sidebar_show_apps &&
        this.schema.ui_sidebar_show_apps.value === false) return;
    if (this.schema.command_enabled &&
        this.schema.command_enabled.value === false) return;
    const cmd = this._el("input", { type: "text",
      placeholder: "xterm, firefox, ..." });
    const run = this._el("button", {
      class: "secondary",
      onclick: () => {
        if (this.client && cmd.value.trim()) {
          this.client.send("cmd," + cmd.value.trim());
        }
      },
    }, "Launch");
    const body = this._el("div", {}, cmd, run);
    this.settingsHost.append(this._section("Apps", body, false));
  }

  /* ---------------------------------------------------------- sharing */

  _renderSharing() {
    if (this.schema.ui_sidebar_show_sharing &&
        this.schema.ui_sidebar_show_sharing.value === false) return;
    if (this.schema.enable_sharing &&
        this.schema.enable_sharing.value === false) return;
    const base = location.href.split("#")[0];
    const body = this._el("div", {});
    const links = [];
    if (!this.schema.enable_shared ||
        this.schema.enable_shared.value) {
      links.push(["View only", base + "#shared"]);
    }
    for (const n of [2, 3, 4]) {
      const flag = this.schema["enable_player" + n];
      if (!flag || flag.value) {
        links.push(["Player " + n, base + "#player" + n]);
      }
    }
    for (const [label, url] of links) {
      body.append(this._el("div", { class: "share-row" },
        this._el("span", {}, label),
        this._el("button", {
          class: "secondary",
          onclick: (ev) => {
            navigator.clipboard && navigator.clipboard.writeText(url);
            ev.target.textContent = "Copied";
            setTimeout(() => { ev.target.textContent = "Copy"; }, 1200);
          },
        }, "Copy")));
    }
    this.settingsHost.append(this._section("Sharing", body, false));
  }

  /* --------------------------------------------------------- gamepads */

  _appendGamepadSection() {
    if (this.schema.ui_sidebar_show_gamepads &&
        this.schema.ui_sidebar_show_gamepads.value === false) return;
    if (this.schema.gamepad_enabled &&
        this.schema.gamepad_enabled.value === false) return;
    this.padCanvas = this._el("canvas", { width: 200, height: 88 });
    const body = this._el("div", {}, this.padCanvas);
    this.padSection = this._section("Gamepads", body, false);
    this.settingsHost.append(this.padSection);
  }

  _gamepadVisibility() {
    const visible = this.padSection &&
      !this.padSection._content.classList.contains("hidden");
    if (visible && !this._gamepadTimer) {
      this._gamepadTimer = setInterval(() => this._drawGamepads(), 100);
    } else if (!visible && this._gamepadTimer) {
      clearInterval(this._gamepadTimer);
      this._gamepadTimer = null;
    }
  }

  _drawGamepads() {
    const ctx = this.padCanvas.getContext("2d");
    const w = this.padCanvas.width, h = this.padCanvas.height;
    ctx.clearRect(0, 0, w, h);
    const pads = (navigator.getGamepads ? navigator.getGamepads() : [])
      .filter(Boolean);
    if (!pads.length) {
      ctx.fillStyle = "#5a646d";
      ctx.font = "12px system-ui";
      ctx.fillText("no gamepads", 8, 20);
      return;
    }
    const pad = pads[0];
    ctx.fillStyle = "#9fb6c9";
    ctx.font = "11px system-ui";
    ctx.fillText(pad.id.slice(0, 30), 4, 12);
    pad.axes.slice(0, 4).forEach((v, i) => {
      ctx.fillStyle = "#22272c";
      ctx.fillRect(4 + i * 50, 20, 40, 8);
      ctx.fillStyle = "#2a6db0";
      ctx.fillRect(4 + i * 50 + 20 + v * 20 - 2, 20, 4, 8);
    });
    pad.buttons.forEach((b, i) => {
      ctx.fillStyle = b.pressed ? "#86c28b" : "#22272c";
      ctx.beginPath();
      ctx.arc(10 + (i % 10) * 19, 44 + Math.floor(i / 10) * 18, 7,
        0, Math.PI * 2);
      ctx.fill();
    });
  }

  /* ------------------------------------------------------- connection */

  connect() {
    if (this.client) {
      this.client.disconnect();
      if (this.input) this.input.detach();
    }
    const viewOnly = this.mode === "shared";
    const gamepadOnly = /^player[234]$/.test(this.mode);
    this.client = new SelkiesClient({
      canvas: this.canvas,
      url: this.wsUrl,
      claimDisplay: !viewOnly && !gamepadOnly,
      settings: Object.assign({
        initialClientWidth: this.canvas.width,
        initialClientHeight: this.canvas.height,
      }, this.overrides),
      onStatus: (s) => { this.statusEl.textContent = s; },
      onStats: (s) => this.onStats(s),
      onServerSettings: (s) => this.onServerSettings(s),
      onClipboard: (t) => this.onClipboard(t),
    });
    this.client.connect();
    if (!viewOnly) {
      this.input = new SelkiesInput(this.client, this.canvas);
      if (gamepadOnly) {
        this.input.gamepadIndexOffset = parseInt(this.mode.slice(6), 10) - 1;
        this.input.attachGamepadOnly();
      } else {
        this.input.attach();
      }
    }
    this.canvas.focus();
  }
}

if (typeof module !== "undefined") module.exports = { SelkiesDashboard };
