/*
 * webrtc.js — browser WebRTC peer for selkies-tpu's WebRTC mode.
 *
 * Role parity with the reference's legacy webrtcbin peer
 * (addons/gst-web/src/webrtc.js:42-790) and signaling client
 * (signaling.js:36-320): registers with the in-process signaling server
 * (rtc/signaling.py HELLO/SESSION grammar), answers the server's SDP
 * offer, renders the H.264 track into a <video>, and carries the input
 * verbs over the server-created "input" data channel — the same wire
 * grammar web/input.js already speaks over WebSocket mode, so the
 * SelkiesInput class plugs in unchanged (its client contract is one
 * send(text) method).
 *
 * Flow (server is peer "0" and the caller, webrtc_main.py:59-63):
 *   browser → WS /ws: "HELLO 1 <meta_b64>"      → server ack "HELLO"
 *   server  → {"sdp": {type: "offer", ...}}     (after SESSION setup)
 *   browser → setRemoteDescription → createAnswer → {"sdp": answer}
 *   both    → {"ice": {candidate, sdpMLineIndex}} trickle
 *   server  → datachannel "input" (ordered) → SelkiesInput.send verbs up,
 *             JSON control objects (clipboard/cursor) down.
 */

"use strict";

class SelkiesWebRTCClient {
  constructor(opts) {
    this.signalingUrl = opts.signalingUrl;
    this.peerId = opts.peerId || "1";
    this.video = opts.video;
    this.onStatus = opts.onStatus || (() => {});
    this.onClipboard = opts.onClipboard || (() => {});
    this.onCursor = opts.onCursor || (() => {});
    this.onStats = opts.onStats || (() => {});
    this.rtcConfig = opts.rtcConfig || null;

    this.ws = null;
    this.pc = null;
    this.inputChannel = null;
    this._sendQueue = [];
    this._statsTimer = null;
    this._lastStats = { bytes: 0, frames: 0, t: 0 };
    this.state = "idle";
  }

  _status(s) {
    this.state = s;
    this.onStatus(s);
  }

  /* The signaling web server mints TURN credentials at /turn
     (rtc/signaling.py _turn_response; reference signaling.js app.config
     fetch). Missing config degrades to host candidates (LAN). */
  async fetchRtcConfig() {
    if (this.rtcConfig) return this.rtcConfig;
    try {
      const base = this.signalingUrl
        .replace(/^ws/, "http").replace(/\/ws$/, "");
      const resp = await fetch(base + "/turn");
      if (resp.ok) {
        const cfg = await resp.json();
        this.rtcConfig = { iceServers: cfg.iceServers || [] };
        return this.rtcConfig;
      }
    } catch (e) { /* no TURN plane: host candidates only */ }
    this.rtcConfig = { iceServers: [] };
    return this.rtcConfig;
  }

  async connect() {
    await this.fetchRtcConfig();
    this._status("connecting");
    this.ws = new WebSocket(this.signalingUrl);
    this.ws.onopen = () => {
      const meta = btoa(JSON.stringify({
        res: (screen && screen.width)
          ? `${screen.width}x${screen.height}` : "1280x720",
        scale: (typeof devicePixelRatio !== "undefined")
          ? devicePixelRatio : 1,
      }));
      this.ws.send(`HELLO ${this.peerId} ${meta}`);
    };
    this.ws.onmessage = (ev) => this._onSignal(ev.data);
    this.ws.onclose = () => {
      this._status("disconnected");
      this._teardownPc();
    };
    this.ws.onerror = () => this._status("error");
  }

  close() {
    if (this.ws) this.ws.close();
    this._teardownPc();
  }

  _teardownPc() {
    if (this._statsTimer) {
      clearInterval(this._statsTimer);
      this._statsTimer = null;
    }
    if (this.pc) {
      this.pc.close();
      this.pc = null;
    }
    this.inputChannel = null;
  }

  _onSignal(msg) {
    if (typeof msg !== "string") return;
    if (msg === "HELLO") {
      this._status("registered");
      return;
    }
    if (msg.startsWith("SESSION_OK")) {
      this._status("session");
      return;
    }
    if (msg.startsWith("ERROR")) {
      this._status("error");
      return;
    }
    let data;
    try {
      data = JSON.parse(msg);
    } catch (e) {
      return;                       // non-JSON control chatter
    }
    if (data.sdp) this._onRemoteSdp(data.sdp);
    else if (data.ice) this._onRemoteIce(data.ice);
  }

  async _onRemoteSdp(desc) {
    if (desc.type !== "offer") return;
    if (!this.pc) this._makePc();
    await this.pc.setRemoteDescription(desc);
    const answer = await this.pc.createAnswer();
    await this.pc.setLocalDescription(answer);
    this.ws.send(JSON.stringify({
      sdp: { type: answer.type, sdp: answer.sdp },
    }));
    this._status("negotiated");
  }

  async _onRemoteIce(ice) {
    if (!this.pc || !ice || !ice.candidate) return;
    try {
      await this.pc.addIceCandidate({
        candidate: ice.candidate,
        sdpMLineIndex: ice.sdpMLineIndex || 0,
      });
    } catch (e) { /* end-of-candidates / stale */ }
  }

  _makePc() {
    this.pc = new RTCPeerConnection(this.rtcConfig || { iceServers: [] });
    this.pc.ontrack = (ev) => {
      // one MediaStream carries the H.264 video + Opus audio tracks
      if (this.video && ev.streams && ev.streams[0]) {
        if (this.video.srcObject !== ev.streams[0]) {
          this.video.srcObject = ev.streams[0];
          if (typeof this.video.play === "function") {
            const p = this.video.play();
            if (p && p.catch) p.catch(() => {});
          }
        }
      }
    };
    this.pc.ondatachannel = (ev) => {
      if (ev.channel.label === "input") this._wireInput(ev.channel);
    };
    this.pc.onicecandidate = (ev) => {
      if (ev.candidate && ev.candidate.candidate) {
        this.ws.send(JSON.stringify({
          ice: {
            candidate: ev.candidate.candidate,
            sdpMLineIndex: ev.candidate.sdpMLineIndex || 0,
          },
        }));
      }
    };
    this.pc.onconnectionstatechange = () => {
      const st = this.pc ? this.pc.connectionState : "closed";
      if (st === "connected") {
        this._status("connected");
        this._startStats();
      } else if (st === "failed" || st === "closed") {
        this._status("disconnected");
      }
    };
  }

  _wireInput(channel) {
    this.inputChannel = channel;
    const flush = () => {
      const q = this._sendQueue;
      this._sendQueue = [];
      for (const m of q) channel.send(m);
      this._status("input-ready");
    };
    channel.onopen = flush;
    // a remotely-announced channel can arrive already open (the open
    // event fired before ondatachannel on the announcing side) — the
    // queue must flush now or queued input waits forever
    if (channel.readyState === "open") flush();
    channel.onmessage = (ev) => {
      // downstream control objects mirror the legacy data-channel
      // helpers (webrtc_app._send_control): clipboard + cursor
      let obj;
      try {
        obj = JSON.parse(ev.data);
      } catch (e) {
        return;
      }
      if (obj.type === "clipboard" && typeof obj.data === "string") {
        try {
          this.onClipboard(
            decodeURIComponent(escape(atob(obj.data))));
        } catch (e) { /* non-base64 payload */ }
      } else if (obj.type === "cursor") {
        this.onCursor(obj);
      }
    };
  }

  /* SelkiesInput's entire client contract. */
  send(text) {
    if (this.inputChannel && this.inputChannel.readyState === "open") {
      this.inputChannel.send(text);
    } else {
      this._sendQueue.push(text);
      if (this._sendQueue.length > 256) this._sendQueue.shift();
    }
  }

  sendClipboard(text) {
    this.send("cw," + btoa(unescape(encodeURIComponent(text))));
  }

  requestResolution(w, h) {
    this.send(`r,${w}x${h}`);
  }

  _startStats() {
    if (this._statsTimer || !this.pc || !this.pc.getStats) return;
    this._statsTimer = setInterval(async () => {
      if (!this.pc) return;
      const report = await this.pc.getStats();
      let bytes = 0, frames = 0, w = 0, h = 0;
      report.forEach((s) => {
        if (s.type === "inbound-rtp" && s.kind === "video") {
          bytes = s.bytesReceived || 0;
          frames = s.framesDecoded || 0;
          w = s.frameWidth || 0;
          h = s.frameHeight || 0;
        }
      });
      const now = Date.now();
      const prev = this._lastStats;
      if (prev.t) {
        const dt = (now - prev.t) / 1000;
        this.onStats({
          fps: dt > 0 ? (frames - prev.frames) / dt : 0,
          kbps: dt > 0 ? ((bytes - prev.bytes) * 8) / dt / 1000 : 0,
          width: w, height: h,
        });
      }
      this._lastStats = { bytes, frames, t: now };
    }, 1000);
  }
}

if (typeof module !== "undefined") {
  module.exports = { SelkiesWebRTCClient };
}
