"""Rate/distortion quality gate: tpuenc-H.264 vs x264 superfast.

VERDICT r3 item 4 (round-2 item 7): "matches the reference" includes what
pixels look like at a bitrate. The reference's daily driver is pixelflux's
x264 at preset superfast, tune zerolatency, with in-loop deblocking
(reference gstwebrtc_app.py:609-640); tpuenc ships integer-pel ME,
Intra16x16-only keyframes, and no deblocking. This tool measures what
those missing tools actually cost:

  * corpus: synthetic desktop content (scrolling text-like pattern,
    window/desktop pattern, smooth gradient pan) — the content class the
    product streams;
  * tpuenc: QP sweep over the real H264StripeEncoder; distortion comes
    from the encoder's reconstruction planes, which the conformance
    suite certifies bit-exact with libavcodec's decode of the stream;
  * x264: CRF sweep through the same libavcodec (native/conformance.cpp
    conf_x264_new), decoded back with the same conformance decoder;
  * metrics: mean Y-PSNR vs the BT.601 luma of the source, bits per
    frame, and the Bjøntegaard-delta rate (BD-rate) of tpuenc against
    x264 over the overlapping quality range.

Run: ``python tools/quality_measure.py [--width W --height H --frames N]``
→ one JSON document (also suitable for BASELINE.md tables).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------- corpus


def _text_pattern(h, w, rng):
    """Text-like rows: high-contrast fine horizontal structure."""
    img = np.full((h, w, 3), 242, np.uint8)
    y = 8
    while y < h - 12:
        n_words = rng.integers(4, 10)
        x = 12
        for _ in range(n_words):
            ww = int(rng.integers(20, 90))
            if x + ww >= w - 12:
                break
            img[y:y + 9, x:x + ww] = rng.integers(10, 70)
            x += ww + 12
        y += 16
    return img


def corpus(width, height, n_frames, kind, seed=0):
    """Yield n_frames of one content class."""
    rng = np.random.default_rng(seed)
    if kind == "scroll":
        page = _text_pattern(height * 2, width, rng)
        for t in range(n_frames):
            y0 = (7 * t) % height
            yield page[y0:y0 + height]
    elif kind == "desktop":
        base = np.full((height, width, 3), 52, np.uint8)
        for _ in range(6):                      # windows
            y0, x0 = rng.integers(0, height // 2), rng.integers(0, width // 2)
            hh, ww = rng.integers(80, height // 2), rng.integers(120, width // 2)
            base[y0:y0 + hh, x0:x0 + ww] = rng.integers(180, 250, 3)
            base[y0:y0 + 14, x0:x0 + ww] = rng.integers(60, 120, 3)
        cursor = rng.integers(0, 200, (24, 24, 3), dtype=np.uint8)
        for t in range(n_frames):
            f = base.copy()
            cy = (13 * t) % (height - 24)
            cx = (29 * t) % (width - 24)
            f[cy:cy + 24, cx:cx + 24] = cursor
            yield f
    elif kind == "gradient":
        yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
        for t in range(n_frames):
            r = (xx + 3 * t) % 256
            g = (yy + 2 * t) % 256
            b = ((xx + yy) / 2 + 5 * t) % 256
            yield np.stack([r, g, b], -1).astype(np.uint8)
    else:
        raise ValueError(kind)


def _bt601_y(rgb):
    r = rgb[..., 0].astype(np.float64)
    g = rgb[..., 1].astype(np.float64)
    b = rgb[..., 2].astype(np.float64)
    return np.clip(0.299 * r + 0.587 * g + 0.114 * b, 0, 255)


def _to_yuv420(rgb):
    """Full-range BT.601 4:2:0 planes (matches ops/color)."""
    r = rgb[..., 0].astype(np.float64)
    g = rgb[..., 1].astype(np.float64)
    b = rgb[..., 2].astype(np.float64)
    y = 0.299 * r + 0.587 * g + 0.114 * b
    cb = 128 - 0.168736 * r - 0.331264 * g + 0.5 * b
    cr = 128 + 0.5 * r - 0.418688 * g - 0.081312 * b

    def sub(p):
        h, w = p.shape
        return p.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))

    clip = lambda p: np.clip(np.round(p), 0, 255).astype(np.uint8)
    return clip(y), clip(sub(cb)), clip(sub(cr))


def _psnr(a, b):
    mse = float(np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2))
    if mse <= 0:
        return 99.0
    return 10.0 * np.log10(255.0 ** 2 / mse)


# ----------------------------------------------------------------- tpuenc


def measure_tpuenc(frames, width, height, qp):
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    # paint-over disabled (trigger unreachable): RD points must measure
    # one QP, not a mixture with the paint-over QP
    enc = H264StripeEncoder(width, height, qp=qp,
                            paint_over_trigger_frames=10 ** 9)
    total_bytes = 0
    psnrs = []
    for f in frames:
        stripes = enc.encode_frame(f)
        total_bytes += sum(len(s.annexb) for s in stripes)
        recon = np.asarray(enc._ref_y)[:height, :width]
        psnrs.append(_psnr(recon, _bt601_y(f)))
    return total_bytes / len(psnrs), float(np.mean(psnrs))


# ------------------------------------------------------------------ x264


def measure_x264(frames, width, height, crf, preset=b"superfast"):
    from selkies_tpu.encoder.conformance import ConformanceDecoder
    from selkies_tpu.native import conformance_lib

    lib = conformance_lib()
    if lib is None:
        raise RuntimeError("conformance/x264 lib unavailable")
    h = lib.conf_x264_new(width, height, crf, 0, preset)
    if not h:
        raise RuntimeError("libx264 encoder unavailable")
    dec = ConformanceDecoder("h264", max_dim=max(width, height))
    out = np.empty(1 << 24, np.uint8)
    total_bytes = 0
    psnrs = []
    pending = []                   # frames awaiting decode output
    try:
        for f in frames:
            y, u, v = _to_yuv420(f)
            n = lib.conf_enc_encode(h, np.ascontiguousarray(y.reshape(-1)),
                                    np.ascontiguousarray(u.reshape(-1)),
                                    np.ascontiguousarray(v.reshape(-1)),
                                    out, out.size)
            if n < 0:
                raise RuntimeError(f"x264 encode failed ({n})")
            pending.append(_bt601_y(f))
            if n > 0:
                total_bytes += int(n)
                got = dec.decode(bytes(out[:n]))
                if got is not None:
                    yd, _, _ = got
                    src_y = pending.pop(0)
                    psnrs.append(_psnr(yd[:height, :width], src_y))
        n = lib.conf_enc_flush(h, out, out.size)
        if n > 0:
            total_bytes += int(n)
            got = dec.decode(bytes(out[:n]))
            if got is not None:
                yd, _, _ = got
                psnrs.append(_psnr(yd[:height, :width], pending.pop(0)))
        for yd, _, _ in dec.flush():
            if pending:
                psnrs.append(_psnr(yd[:height, :width], pending.pop(0)))
    finally:
        lib.conf_enc_free(h)
        dec.close()
    return total_bytes / max(len(psnrs), 1), float(np.mean(psnrs))


# --------------------------------------------------------------- BD-rate


def bd_rate(rd_ref, rd_test):
    """Bjøntegaard delta rate of test vs ref (negative = test cheaper).

    rd_*: [(bytes_per_frame, psnr)] — integrated over the overlapping
    PSNR range with a cubic fit of log-rate vs PSNR.
    """
    ref = sorted(rd_ref, key=lambda p: p[1])
    test = sorted(rd_test, key=lambda p: p[1])
    lr_ref = np.log10([p[0] for p in ref])
    q_ref = np.array([p[1] for p in ref])
    lr_test = np.log10([p[0] for p in test])
    q_test = np.array([p[1] for p in test])
    lo = max(q_ref.min(), q_test.min())
    hi = min(q_ref.max(), q_test.max())
    if hi <= lo:
        return None
    pr = np.polyfit(q_ref, lr_ref, min(3, len(ref) - 1))
    pt = np.polyfit(q_test, lr_test, min(3, len(test) - 1))
    xs = np.linspace(lo, hi, 128)
    ir = np.trapezoid(np.polyval(pr, xs), xs)
    it = np.trapezoid(np.polyval(pt, xs), xs)
    return float((10 ** ((it - ir) / (hi - lo)) - 1) * 100.0)


# ------------------------------------------------------------------ main


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=1280)
    ap.add_argument("--height", type=int, default=704)
    ap.add_argument("--frames", type=int, default=48)
    ap.add_argument("--kinds", default="scroll,desktop,gradient")
    ap.add_argument("--tpu-qps", default="20,26,32,38")
    ap.add_argument("--x264-crfs", default="18,23,28,33")
    args = ap.parse_args()

    result = {"width": args.width, "height": args.height,
              "frames": args.frames,
              "x264": "libx264 superfast tune=zerolatency (the reference's "
                      "pixelflux posture, gstwebrtc_app.py:609-640)",
              "corpora": {}}
    for kind in args.kinds.split(","):
        frames = list(corpus(args.width, args.height, args.frames, kind))
        rd_tpu, rd_x264 = [], []
        for qp in (int(q) for q in args.tpu_qps.split(",")):
            bpf, psnr = measure_tpuenc(frames, args.width, args.height, qp)
            rd_tpu.append({"qp": qp, "bytes_per_frame": round(bpf),
                           "y_psnr": round(psnr, 2)})
        for crf in (int(c) for c in args.x264_crfs.split(",")):
            bpf, psnr = measure_x264(frames, args.width, args.height, crf)
            rd_x264.append({"crf": crf, "bytes_per_frame": round(bpf),
                            "y_psnr": round(psnr, 2)})
        bd = bd_rate(
            [(p["bytes_per_frame"], p["y_psnr"]) for p in rd_x264],
            [(p["bytes_per_frame"], p["y_psnr"]) for p in rd_tpu])
        result["corpora"][kind] = {
            "tpuenc": rd_tpu,
            "x264_superfast": rd_x264,
            "bd_rate_vs_x264_pct": round(bd, 1) if bd is not None else None,
        }
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
