#!/usr/bin/env python3
"""Chaos harness: a synthetic-capture session under random fault injection.

Runs an in-process ``DataStreamingServer`` session (real encoder factory,
synthetic capture, fake in-process websocket client — no network, no
``websockets`` package needed) while randomly arming fault points from the
``SELKIES_TPU_FAULTS`` menu, then asserts the session is still alive and
streaming once the faults stop: supervised restarts happened, no display
reached the terminal ``failed`` state, and frames flow after the last
fault. docs/robustness.md describes the subsystems this exercises.

Usage::

    python tools/chaos_run.py --duration 10 --seed 0
    python tools/chaos_run.py --duration 60 --fps 60 --width 640 --height 480

Also run (shortened) as the ``slow``-marked test
``tests/test_robustness.py::test_chaos_session_survives_fault_storm``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import random
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: (point, times, arg) entries the chaos loop draws from — short hangs so
#: a single run exercises both the hang-recovery and the watchdog paths.
#: fetch.hang is armed twice per draw (ISSUE 12): the point now has TWO
#: call sites — the capture loop's async stall and the async encode
#: driver's harvest thread (encoder/async_driver.py) — so one draw can
#: wedge either side of the D2H path.
FAULT_MENU = (
    ("capture.raise", 1, None),
    ("capture.stall", 1, "0.4"),
    ("encode.raise", 1, None),
    ("fetch.hang", 2, "0.4"),
    ("ws.drop", 1, None),
    ("ws.flood", 1, None),
    ("ws.garbage", 1, None),
    ("session.churn", 1, None),
)

#: mesh scheduler kinds (ISSUE 14): only drawn with --mesh, because their
#: call sites live in the coordinator's tick thread — tick_raise fails a
#: whole tick (worker backs off, survives), slot_raise fails ONE slot's
#: dispatch (cohabitants keep streaming; repeated hits quarantine the
#: slot and live-migrate its session, docs/scaling.md)
MESH_FAULT_MENU = (
    ("mesh.tick_raise", 1, None),
    ("mesh.slot_raise", 3, None),
)

#: SFE storm kinds (ISSUE 15): drawn with --sfe, where the session rides
#: a stripe-sharded lane spanning 2 virtual chips. The shard-targeted
#: ``mesh.slot_raise=shard:K`` arms hit ONE stripe shard of the frame;
#: the coordinator must degrade the whole session's tick (whole-frame
#: containment — cohabitants unaffected, never a torn access unit) and
#: walk the slot into quarantine + migration on repeats.
SFE_FAULT_MENU = (
    ("mesh.tick_raise", 1, None),
    ("mesh.slot_raise", 3, "shard:0"),
    ("mesh.slot_raise", 3, "shard:1"),
)

#: edge fault kinds (ISSUE 3): injected from the CLIENT side — a message
#: flood / garbage burst through the websocket, exercising the rate
#: limiter and per-message exception boundary rather than a server-side
#: fault point (server.faults has no call site that can forge client
#: input). session.churn is a storm of short-lived extra clients joining
#: and leaving mid-faults — admission, fan-out registration, and teardown
#: must all hold while the interior is being broken.
CLIENT_FAULTS = ("ws.flood", "ws.garbage", "session.churn")


from selkies_tpu.robustness.testing import InProcessClient as _ChaosClient  # noqa: E402


async def _churn_burst(server, rng) -> None:
    """session.churn: a burst of short-lived clients joins and leaves
    while the primary session is under fault injection — the scheduler
    and fan-out tables must absorb the membership churn without touching
    the session being tested."""
    for _ in range(5):
        ws = _ChaosClient()
        task = asyncio.create_task(server.ws_handler(ws))
        await asyncio.sleep(rng.uniform(0.02, 0.08))
        await ws.close()
        try:
            await asyncio.wait_for(task, 2.0)
        except asyncio.TimeoutError:
            task.cancel()


def _inject_client_fault(ws, point: str, rng) -> None:
    """Feed a hostile burst through the in-process client."""
    if point == "ws.flood":
        # input-plane flood past the token bucket's burst (default 2000):
        # the tail must be dropped by the limiter, none may kill the
        # session or starve the capture loop
        for i in range(3000):
            ws.feed(f"m,{rng.randrange(2000)},{rng.randrange(2000)},0,0")
    else:  # ws.garbage
        from tools.proto_fuzz import gen_message

        for _ in range(40):
            ws.feed(gen_message(rng))


async def chaos_session(duration_s: float = 10.0, seed: int = 0,
                        width: int = 160, height: int = 128,
                        fps: float = 30.0, mesh: bool = False,
                        sfe: bool = False) -> dict:
    """Run one chaos session; returns the survival report."""
    import tempfile

    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import (DataStreamingServer,
                                                default_encoder_factory)
    from selkies_tpu.settings import Settings

    # ws.garbage bursts may carry FILE_UPLOAD verbs: sandbox them
    # (honoring a caller-provided dir, e.g. pytest's tmp_path)
    if not os.environ.get("SELKIES_UPLOAD_DIR"):
        os.environ["SELKIES_UPLOAD_DIR"] = tempfile.mkdtemp(
            prefix="chaos_uploads_")

    env = {
        "SELKIES_PORT": "0",
        "SELKIES_AUDIO_ENABLED": "false",
        # ws.garbage bursts carry arbitrary text: NEVER let one reach a
        # shell, and never let a garbage SETTINGS spin up a second real
        # encoder pipeline at a random geometry
        "SELKIES_COMMAND_ENABLED": "false",
        "SELKIES_MAX_DISPLAYS": "1",
        # garbage "r,NxM" resizes are honored (clamped, owner-only) by
        # design, but every fresh geometry is a full jit compile —
        # minutes on this CPU host — which reads as a wedge and drowns
        # the faults actually being tested. Resize handling is covered
        # by tools/proto_fuzz.py + tests/test_edge.py against the edge;
        # chaos pins the resolution and tests the supervision interior.
        "SELKIES_IS_MANUAL_RESOLUTION_MODE": "true",
        # generous budget: chaos injects faults far faster than production
        "SELKIES_SUPERVISOR_MAX_RESTARTS": "1000",
        "SELKIES_SUPERVISOR_RESTART_WINDOW_S": "60",
        "SELKIES_WATCHDOG_FRAMES": str(int(fps * 2)),   # 2 s deadline
        "SELKIES_LADDER_FAIL_THRESHOLD": "3",
        "SELKIES_LADDER_PROBE_MS": "2000",
    }
    if sfe:
        # the session rides a split-frame-encoding lane: its frame's
        # stripe bands shard across 2 (virtual) chips, so shard-targeted
        # mesh.slot_raise arms have a live call site (docs/scaling.md).
        # sfe_min_pixels=1 makes even the tiny chaos geometry SFE.
        env["SELKIES_TPU_MESH"] = "session:2"
        env["SELKIES_SFE_MIN_PIXELS"] = "1"
        env["SELKIES_TPU_SESSIONS_PER_CHIP"] = "1"
    elif mesh:
        # the session rides the mesh scheduler instead of a solo encoder,
        # so the mesh.tick_raise / mesh.slot_raise kinds have a live
        # call site (docs/scaling.md)
        env["SELKIES_TPU_MESH"] = "session:1"
        env["SELKIES_TPU_SESSIONS_PER_CHIP"] = "2"
    settings = Settings(argv=[], env=env)

    # warm the jit cache outside the session so a cold compile is not
    # misread as a stall by the watchdog on slow CPUs
    warm = default_encoder_factory(width, height, settings, {})
    warm.submit(np.zeros((height, width, 3), np.uint8))
    warm.flush()
    close = getattr(warm, "close", None)
    if close:
        close()

    app = StreamingApp(settings)
    server = DataStreamingServer(settings, app=app, host="127.0.0.1")
    app.data_server = server
    rng = random.Random(seed)
    reconnects = 0
    #: supervisors (and their counters) die with their display when ws.drop
    #: churns the client, so totals accumulate across incarnations: the
    #: chaos loop OBSERVES the live counters continuously and COMMITS the
    #: last observation when an incarnation ends (a display torn down
    #: between observations loses at most the final fraction of a second)
    totals = {"restarts": 0, "failures": 0, "watchdog_restarts": 0}
    transitions = []
    last_obs = {}

    def observe():
        nonlocal last_obs
        st = server.display_clients.get("primary")
        if st is not None and st.supervisor is not None:
            sup = st.supervisor.stats()
            last_obs = {
                "restarts": sup["restarts_total"],
                "failures": sup["failures_total"],
                "watchdog_restarts": sup["watchdog_restarts_total"],
                "transitions": list(st.ladder.transitions),
            }

    def commit():
        nonlocal last_obs
        for k in totals:
            totals[k] += last_obs.get(k, 0)
        transitions.extend(last_obs.get("transitions", []))
        last_obs = {}

    async def connect():
        ws = _ChaosClient()
        task = asyncio.create_task(server.ws_handler(ws))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(ws.sent) < 2:
            await asyncio.sleep(0.01)
        ws.feed("SETTINGS," + json.dumps({
            "displayId": "primary",
            "initialClientWidth": width, "initialClientHeight": height,
            "framerate": fps}))
        return ws, task

    async def reap(ws, task):
        await ws.close()
        try:
            await asyncio.wait_for(task, 5.0)
        except asyncio.TimeoutError:
            task.cancel()

    ws, task = await connect()
    injected = []
    t_end = time.monotonic() + duration_s
    try:
        while time.monotonic() < t_end:
            await asyncio.sleep(rng.uniform(0.3, 0.7))
            observe()
            if ws.closed:                     # ws.drop churned the client
                commit()
                await reap(ws, task)
                ws, task = await connect()
                reconnects += 1
            menu = FAULT_MENU + (
                SFE_FAULT_MENU if sfe
                else MESH_FAULT_MENU if mesh else ())
            point, times, arg = menu[rng.randrange(len(menu))]
            if point == "session.churn":
                await _churn_burst(server, rng)
            elif point in CLIENT_FAULTS:
                _inject_client_fault(ws, point, rng)
            else:
                server.faults.arm(point, times=times, arg=arg)
            injected.append(point)

        # quiesce and verify recovery: no new faults, frames must flow
        server.faults.disarm()
        recovered = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            observe()
            if ws.closed:
                commit()
                await reap(ws, task)
                ws, task = await connect()
                reconnects += 1
            st_now = server.display_clients.get("primary")
            if st_now is not None and not st_now.video_active:
                # a ws.garbage burst can carry a legitimate owner
                # STOP_VIDEO; a real client would press play again —
                # recovery models that, it does not test amnesia
                ws.feed("START_VIDEO")
            n0 = ws.n_frames()
            await asyncio.sleep(0.5)
            if not ws.closed and ws.n_frames() > n0:
                recovered = True
                break

        observe()
        commit()
        st = server.display_clients.get("primary")
        report = {
            "duration_s": duration_s,
            "seed": seed,
            "injected": injected,
            "reconnects": reconnects,
            "restarts": totals["restarts"],
            "failures": totals["failures"],
            "watchdog_restarts": totals["watchdog_restarts"],
            "ladder_transitions": transitions,
            "rung": st.ladder.rung if st else None,
            "failed_displays": server._failed_displays(),
            "frames_delivered": ws.n_frames(),
            "protocol_errors": server.edge_stats["protocol_errors"],
            "rate_limited": dict(server.edge_stats["rate_limited"]),
            "slow_client_evictions":
                server.edge_stats["slow_client_evictions"],
        }
        # flight-recorder leak invariant (ISSUE 13): after teardown,
        # EVERY span opened during the fault storm must have reached a
        # terminal mark — dropped frames included. A nonzero residue
        # here is a span leak, and the run fails on it.
        coords = list(server.mesh_coordinators.values())
        await reap(ws, task)
        await server.stop()
        report["trace_open_spans"] = server.recorder.open_spans()
        report["frames_traced"] = server.recorder.closed_total
        report["trace_dropped"] = server.recorder.dropped_total
        report["trace_acked"] = server.recorder.acked_total
        leaked_slots = 0
        if coords:
            # scheduler leak invariant (ISSUE 14): the storm must not
            # strand sessions or slots in the mesh scheduler either
            leaked_slots = sum(c.active_sessions for c in coords) + len(
                [p for c in coords for p in c.verify_slot_accounting()])
            report["mesh_leaked_slots"] = leaked_slots
            report["mesh_tick_errors"] = sum(
                c.tick_errors_total for c in coords)
            report["mesh_slot_faults"] = sum(
                c.slot_faults_total for c in coords)
            report["mesh_quarantined"] = sum(
                c.quarantined_total for c in coords)
            report["mesh_migrations"] = sum(
                c.migrations_total for c in coords)
        report["alive"] = (recovered and server._failed_displays() == 0
                          and report["trace_open_spans"] == 0
                          and leaked_slots == 0)
        return report
    finally:
        await reap(ws, task)
        await server.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--width", type=int, default=160)
    p.add_argument("--height", type=int, default=128)
    p.add_argument("--fps", type=float, default=30.0)
    p.add_argument("--mesh", action="store_true",
                   help="run the session through the mesh scheduler and "
                        "draw mesh.tick_raise / mesh.slot_raise kinds")
    p.add_argument("--sfe", action="store_true",
                   help="run the session on a 2-shard split-frame-"
                        "encoding lane and draw shard-targeted "
                        "mesh.slot_raise kinds (whole-frame containment "
                        "storm, docs/scaling.md)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    if args.sfe and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # the SFE lane needs 2 chips; fork virtual CPU devices BEFORE
        # jax initializes (chaos imports jax lazily inside the session)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device"
                                     "_count=2").strip()
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.ERROR)
    report = asyncio.run(chaos_session(
        duration_s=args.duration, seed=args.seed,
        width=args.width, height=args.height, fps=args.fps,
        mesh=args.mesh, sfe=args.sfe))
    print(json.dumps(report, indent=2))
    return 0 if report["alive"] else 1


if __name__ == "__main__":
    sys.exit(main())
