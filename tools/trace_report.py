#!/usr/bin/env python3
"""Summarize a flight-recorder trace: per-stage percentiles + slowest frames.

Input is the Chrome trace-event JSON the server serves at
``/debug/trace`` on the metrics port (Perfetto-loadable; see
docs/observability.md). This CLI renders the same capture as text: a
per-stage p50/p95/p99 table per display, and the top-k slowest frames
with their stage timelines — the quick "where did the time go" answer
without opening a UI.

Usage::

    python tools/trace_report.py --url http://localhost:8000/debug/trace?s=30
    python tools/trace_report.py --file trace.json --top 10
    curl -s localhost:8000/debug/trace | python tools/trace_report.py

The stage glossary (capture/stage/dispatch/fetch_wait/pack/queue/send/
ack) is in docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List


def load(url: str = "", path: str = "") -> Dict[str, Any]:
    if url:
        from urllib.request import urlopen

        with urlopen(url, timeout=10.0) as r:
            return json.load(r)
    if path:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    return json.load(sys.stdin)


def _pct(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(len(s) * q / 100.0))]


def build_frames(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Regroup the flat event list into per-frame records: each frame is
    the set of X slices sharing (pid, tid, args.frame_id)."""
    frames: Dict[Any, Dict[str, Any]] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args", {})
        # the recorder stamps a unique span token per frame; fall back
        # to (pid, tid, frame_id) for captures from older exports
        key = ((ev.get("pid"), "span", args["span"])
               if "span" in args
               else (ev.get("pid"), ev.get("tid"), args.get("frame_id")))
        fr = frames.setdefault(key, {
            "display": args.get("display", f"pid{ev.get('pid')}"),
            "frame_id": args.get("frame_id", -1),
            "terminal": args.get("terminal", "?"),
            "stages": {},
            "t0": float("inf"),
            "t1": float("-inf"),
        })
        fr["stages"][ev["name"]] = ev.get("dur", 0.0) / 1000.0
        fr["t0"] = min(fr["t0"], ev.get("ts", 0.0))
        fr["t1"] = max(fr["t1"], ev.get("ts", 0.0) + ev.get("dur", 0.0))
        fr["terminal"] = args.get("terminal", fr["terminal"])
    out = list(frames.values())
    for fr in out:
        fr["total_ms"] = max(0.0, (fr["t1"] - fr["t0"]) / 1000.0)
    return out


#: canonical stage order for tables/timelines (unknown stages append)
STAGE_ORDER = ("capture", "stage", "dispatch", "fetch_wait", "pack",
               "queue", "send", "ack")


def _stage_sorted(names) -> List[str]:
    known = [s for s in STAGE_ORDER if s in names]
    return known + sorted(n for n in names if n not in STAGE_ORDER)


def render(trace: Dict[str, Any], top: int = 5) -> str:
    frames = build_frames(trace)
    lines: List[str] = []
    other = trace.get("otherData", {})
    lines.append(f"frames: {len(frames)}   open spans at export: "
                 f"{other.get('open_spans', '?')}")
    by_display: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for fr in frames:
        by_display[fr["display"]].append(fr)

    for display, frs in sorted(by_display.items()):
        lines.append(f"\n== display {display} ({len(frs)} frames) ==")
        acked = [f["total_ms"] for f in frs if f["terminal"] == "acked"]
        if acked:
            lines.append(
                f"glass-to-glass  p50 {_pct(acked, 50):8.2f} ms   "
                f"p95 {_pct(acked, 95):8.2f} ms   "
                f"p99 {_pct(acked, 99):8.2f} ms   ({len(acked)} acked)")
        stage_vals: Dict[str, List[float]] = defaultdict(list)
        for fr in frs:
            for stage, ms in fr["stages"].items():
                stage_vals[stage].append(ms)
        lines.append(f"{'stage':<12}{'p50 ms':>10}{'p95 ms':>10}"
                     f"{'p99 ms':>10}{'n':>8}")
        for stage in _stage_sorted(stage_vals):
            vals = stage_vals[stage]
            lines.append(f"{stage:<12}{_pct(vals, 50):>10.2f}"
                         f"{_pct(vals, 95):>10.2f}"
                         f"{_pct(vals, 99):>10.2f}{len(vals):>8}")
        terminals: Dict[str, int] = defaultdict(int)
        for fr in frs:
            terminals[fr["terminal"]] += 1
        lines.append("terminals: " + ", ".join(
            f"{k}={v}" for k, v in sorted(terminals.items())))

        slowest = sorted(frs, key=lambda f: f["total_ms"],
                         reverse=True)[:top]
        if slowest:
            lines.append(f"\nslowest {len(slowest)} frames:")
            for fr in slowest:
                timeline = "  ".join(
                    f"{s}={fr['stages'][s]:.2f}"
                    for s in _stage_sorted(fr["stages"]))
                lines.append(
                    f"  frame {fr['frame_id']:>6}  "
                    f"total {fr['total_ms']:8.2f} ms  "
                    f"[{fr['terminal']}]  {timeline}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default="",
                   help="fetch the trace from a /debug/trace endpoint")
    p.add_argument("--file", default="",
                   help="read a saved trace JSON (default: stdin)")
    p.add_argument("--top", type=int, default=5,
                   help="slowest frames to detail per display")
    args = p.parse_args(argv)
    try:
        trace = load(args.url, args.file)
    except Exception as e:
        print(f"could not load trace: {e!r}", file=sys.stderr)
        return 2
    print(render(trace, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
