"""Convert SDL_GameControllerDB mappings into per-vendor-product JSON files.

Role parity with the reference's ``addons/gst-web-core/gendb.js``: the web
client (or server gamepad mapper) looks up a controller's button/axis
layout by USB vendor:product; this tool splits the community
`gamecontrollerdb.txt` into one small JSON per device so clients fetch
only the mapping they need.

Usage:
  python tools/gendb.py gamecontrollerdb.txt out_dir/
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional, Tuple


def parse_guid(guid: str) -> Optional[Tuple[str, str]]:
    """SDL GUIDs encode bus/vendor/product/version as little-endian hex
    words; vendor is bytes 8-10, product bytes 16-18 (hex string offsets)."""
    if len(guid) != 32:
        return None
    vendor = guid[10:12] + guid[8:10]
    product = guid[18:20] + guid[16:18]
    if vendor == "0000" and product == "0000":
        return None
    return vendor.lower(), product.lower()


def parse_line(line: str) -> Optional[Dict]:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    parts = line.split(",")
    if len(parts) < 3:
        return None
    guid, name = parts[0], parts[1]
    ids = parse_guid(guid)
    mapping: Dict[str, str] = {}
    platform = ""
    for field in parts[2:]:
        if ":" not in field:
            continue
        key, _, value = field.partition(":")
        if key == "platform":
            platform = value
        elif key:
            mapping[key] = value
    if platform and platform != "Linux":
        return None
    return {
        "guid": guid,
        "name": name,
        "vendor": ids[0] if ids else None,
        "product": ids[1] if ids else None,
        "mapping": mapping,
    }


def main(argv) -> int:
    if len(argv) != 3:
        print(__doc__)
        return 2
    src, out_dir = argv[1], argv[2]
    os.makedirs(out_dir, exist_ok=True)
    count = 0
    with open(src, encoding="utf-8", errors="replace") as f:
        for line in f:
            entry = parse_line(line)
            if entry is None or not entry["vendor"]:
                continue
            path = os.path.join(
                out_dir, f"{entry['vendor']}-{entry['product']}.json")
            with open(path, "w") as out:
                json.dump(entry, out, indent=1)
            count += 1
    print(f"wrote {count} device mappings to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
