"""Static syntax lint for the bundled web client JavaScript.

The image carries no JS runtime (no node/bun/quickjs and no browser), so
the client can't be *executed* in CI; this tokenizer-level check is the
strongest automatic gate available: it is string/comment/template/regex
aware and catches the classes of typo that previously could ship silently
— unbalanced brackets, unterminated strings/comments, stray tokens, and
accidental reserved-word breakage. Semantic coverage comes from the
protocol contract tests in tests/test_web_client.py plus the server-side
integration tests that speak the same wire format.
"""

from __future__ import annotations

import sys
from typing import List, Tuple

PUNCT = set("{}()[];,<>+-*/%&|^!~?:=.#")

#: tokens after which a `/` starts a regex literal, not division
_REGEX_PRECEDERS = {
    "(", ",", "=", ":", "[", "!", "&", "|", "?", "{", "}", ";",
    "return", "typeof", "instanceof", "in", "of", "new", "delete",
    "void", "throw", "case", "do", "else", "yield", "await", "=>",
    "+", "-", "*", "/", "%", "<", ">", "^", "~",
}


class JsSyntaxError(ValueError):
    pass


def _err(src: str, pos: int, msg: str) -> JsSyntaxError:
    line = src.count("\n", 0, pos) + 1
    col = pos - (src.rfind("\n", 0, pos) + 1) + 1
    return JsSyntaxError(f"line {line}:{col}: {msg}")


def tokenize(src: str) -> List[Tuple[str, str, int]]:
    """→ [(kind, text, pos)]; kind ∈ ident|num|str|template|regex|punct."""
    out: List[Tuple[str, str, int]] = []
    i, n = 0, len(src)
    last_sig = ";"      # last significant token text

    def push(kind: str, text: str, pos: int) -> None:
        nonlocal last_sig
        out.append((kind, text, pos))
        last_sig = text

    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise _err(src, i, "unterminated block comment")
            i = j + 2
            continue
        if c in "'\"":
            j = i + 1
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == c:
                    break
                if src[j] == "\n":
                    raise _err(src, i, "unterminated string")
                j += 1
            else:
                raise _err(src, i, "unterminated string")
            push("str", src[i:j + 1], i)
            i = j + 1
            continue
        if c == "`":
            j = i + 1
            depth = 0
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src.startswith("${", j):
                    depth += 1
                    j += 2
                    continue
                if src[j] == "}" and depth:
                    depth -= 1
                    j += 1
                    continue
                if src[j] == "`" and depth == 0:
                    break
                j += 1
            else:
                raise _err(src, i, "unterminated template literal")
            push("template", src[i:j + 1], i)
            i = j + 1
            continue
        if c == "/" and last_sig in _REGEX_PRECEDERS:
            j = i + 1
            in_class = False
            while j < n:
                if src[j] == "\\":
                    j += 2
                    continue
                if src[j] == "[":
                    in_class = True
                elif src[j] == "]":
                    in_class = False
                elif src[j] == "/" and not in_class:
                    break
                elif src[j] == "\n":
                    raise _err(src, i, "unterminated regex literal")
                j += 1
            else:
                raise _err(src, i, "unterminated regex literal")
            j += 1
            while j < n and src[j].isalpha():
                j += 1
            push("regex", src[i:j], i)
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i + 1
            while j < n and (src[j].isalnum() or src[j] in "._xXoObBeE+-"):
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                j += 1
            push("num", src[i:j], i)
            i = j
            continue
        if c.isalpha() or c in "_$":
            j = i + 1
            while j < n and (src[j].isalnum() or src[j] in "_$"):
                j += 1
            push("ident", src[i:j], i)
            i = j
            continue
        if c == "=" and src.startswith("=>", i):
            push("punct", "=>", i)
            i += 2
            continue
        if src.startswith("++", i) or src.startswith("--", i):
            # postfix increment must not make a following "/" look like a
            # regex start ("n++ / 2" is division)
            push("punct", src[i:i + 2], i)
            i += 2
            continue
        if c in PUNCT:
            push("punct", c, i)
            i += 1
            continue
        raise _err(src, i, f"unexpected character {c!r}")
    return out


def check(src: str) -> List[Tuple[str, str, int]]:
    """Tokenize + bracket balance; raises JsSyntaxError on problems."""
    toks = tokenize(src)
    stack: List[Tuple[str, int]] = []
    pairs = {")": "(", "]": "[", "}": "{"}
    for kind, text, pos in toks:
        if kind != "punct":
            continue
        if text in "([{":
            stack.append((text, pos))
        elif text in ")]}":
            if not stack or stack[-1][0] != pairs[text]:
                raise _err(src, pos, f"unbalanced {text!r}")
            stack.pop()
    if stack:
        raise _err(src, stack[-1][1], f"unclosed {stack[-1][0]!r}")
    return toks


def main(argv: List[str]) -> int:
    rc = 0
    for path in argv:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            toks = check(src)
            print(f"{path}: OK ({len(toks)} tokens)")
        except JsSyntaxError as e:
            print(f"{path}: {e}")
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
