"""Config-3 gate measurement: Huffman scan vs adaptive-rANS bitstream on
identical quantized DCT planes (see docs/config3_decision.md).

Usage: JAX_PLATFORMS=cpu python tools/config3_measure.py [WIDTH HEIGHT]
Prints per-content-class byte counts for
  - the shipping JPEG Huffman scan (native coder, actual wire bytes), and
  - the rANS candidate profile (selkies_tpu/encoder/rans.py), which pairs
    per-frame adaptive models with the same symbol decomposition.
"""

import sys

import numpy as np

sys.path.insert(0, ".")

from selkies_tpu.encoder import rans  # noqa: E402
from selkies_tpu.encoder.jpeg import _encode_body, _entropy_encode_420  # noqa: E402
from selkies_tpu.ops.quant import quality_scaled_tables  # noqa: E402


def smooth(h, w):
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    r = 128 + 100 * np.sin(xx / 97.0) * np.cos(yy / 53.0)
    g = 128 + 100 * np.cos(xx / 71.0)
    b = 128 + 100 * np.sin(yy / 89.0)
    return np.clip(np.stack([r, g, b], -1), 0, 255).astype(np.uint8)


def desktop(h, w, seed=3):
    """Window rectangles + text-like speckle — the actual workload shape."""
    rng = np.random.default_rng(seed)
    f = np.full((h, w, 3), 235, np.uint8)
    for _ in range(12):
        y0, x0 = rng.integers(0, h - 40), rng.integers(0, w - 80)
        hh, ww = rng.integers(30, h - y0), rng.integers(60, w - x0)
        f[y0:y0 + 2, x0:x0 + ww] = rng.integers(40, 100, 3)
        f[y0:y0 + hh, x0:x0 + 2] = f[y0:y0 + 2, x0:x0 + 2][0, 0]
        f[y0 + 2:y0 + hh, x0 + 2:x0 + ww] = rng.integers(180, 255, 3)
    # text rows: high-contrast speckle lines
    for row in range(20, h - 10, 28):
        mask = rng.random((8, w - 40)) < 0.25
        band = f[row:row + 8, 20:w - 20]
        band[mask] = 20
    return f


def noisy(h, w, seed=9):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def measure(frame, quality=40, stripe_h=64):
    import jax.numpy as jnp
    h, w = frame.shape[:2]
    ly, lc = quality_scaled_tables(quality)
    qy = jnp.stack([jnp.asarray(ly, jnp.float32)] * 2)
    qc = jnp.stack([jnp.asarray(lc, jnp.float32)] * 2)
    qsel = jnp.zeros((h // stripe_h,), jnp.int32)
    yq, cbq, crq, _, _ = _encode_body(
        jnp.asarray(frame), jnp.zeros_like(jnp.asarray(frame)),
        qy, qc, qsel, stripe_h=stripe_h)
    yq, cbq, crq = (np.asarray(x) for x in (yq, cbq, crq))

    # shipping baseline: per-stripe Huffman scans (wire bytes incl. stuffing)
    by, bx = yq.shape[0] // 1, yq.shape[1]
    ys = h // stripe_h
    huff = 0
    rows_per_stripe = stripe_h // 8
    crows = stripe_h // 16
    for s in range(ys):
        yb = yq[s * rows_per_stripe:(s + 1) * rows_per_stripe]
        cb = cbq[s * crows:(s + 1) * crows]
        cr = crq[s * crows:(s + 1) * crows]
        huff += len(_entropy_encode_420(yb, cb, cr))

    blocks_per_stripe_y = rows_per_stripe * bx
    blob = rans.encode_planes(yq, cbq, crq, blocks_per_stripe_y)
    return huff, len(blob), yq, cbq, crq, blocks_per_stripe_y


def main():
    w, h = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 \
        else (1280, 704)
    print(f"frame {w}x{h}, q40, stripe 64")
    print(f"{'content':<10} {'huffman':>10} {'rans':>10} {'delta':>8}")
    for name, frame in (("smooth", smooth(h, w)),
                        ("desktop", desktop(h, w)),
                        ("noise", noisy(h, w))):
        huff, rb, yq, cbq, crq, bps = measure(frame)
        delta = 100.0 * (1 - rb / huff)
        print(f"{name:<10} {huff:>10} {rb:>10} {delta:>7.1f}%")
        # verify the rANS stream actually decodes back to the planes
        y2, c2 = rans.decode_planes(
            rans.encode_planes(yq, cbq, crq, bps),
            yq.shape[0] * yq.shape[1], 2 * cbq.shape[0] * cbq.shape[1], bps)
        ok = np.array_equal(y2, yq.reshape(-1, 64)) and np.array_equal(
            c2, np.concatenate([cbq.reshape(-1, 64), crq.reshape(-1, 64)]))
        print(f"{'':<10} rans round-trip: {'OK' if ok else 'FAIL'}")


if __name__ == "__main__":
    main()
