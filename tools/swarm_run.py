#!/usr/bin/env python3
"""Swarm churn harness: hundreds of in-process clients vs the real server.

Drives N :class:`~selkies_tpu.robustness.InProcessClient`\\ s through the
real ``ws_handler`` — settings handshake, per-display capture loops, the
mesh session scheduler (dynamic lanes, admission verdicts, slot health),
bounded send queues, the flight recorder — under a join/leave/resize
storm, and measures the millions-of-users shape the ROADMAP asks for:

* ``sessions_per_chip``  — peak concurrently-scheduled sessions per chip;
* ``fairness_jain_index`` — Jain's index over per-session delivered fps
  (1.0 = perfectly fair; a stalled session drags it down);
* ``eviction_ms_p95``    — client leave → scheduler slot freed;
* leak-freedom           — zero leaked slots, zero open trace spans, and
  clean lane/slot accounting after the storm drains.

By default the SPMD encoder is replaced with the device-free
:class:`~selkies_tpu.robustness.FakeMeshEncoder` (``--encoder fake``): the
harness then exercises the *scheduling* and *serving* planes at full churn
rate without compiling a single device program, which is what makes a
500-client soak tractable in CI. ``--encoder real`` keeps the real mesh
encoders (slow first-dispatch compiles; use small geometry).

``--sick-slot`` arms a ``mesh.slot_raise`` fault against one occupied
slot mid-storm and asserts the fault-domain story end to end: the victim
session is quarantined + live-migrated while its cohabitants' frame IDs
keep advancing (docs/scaling.md).

Usage::

    python tools/swarm_run.py --clients 200 --duration 10 --sick-slot
    python tools/swarm_run.py --clients 500 --duration 20 --concurrency 96

Prints ONE JSON line (MULTICHIP format); exit 0 iff the run is leak-free
and (when armed) the sick-slot assertions held. Also run (shortened) as
the tier-1 swarm smoke in ``tests/test_swarm.py``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import random
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from selkies_tpu.robustness.testing import (FakeMeshEncoder, FakeStripe,
                                            InProcessClient)

logger = logging.getLogger("selkies_tpu.swarm")

#: resize targets — exactly two geometries so churn exercises cross-bucket
#: moves without exceeding the server's 4-bucket coordinator cap
GEOMS = ((128, 96), (160, 128))


class _SwarmSource:
    """Frame source whose frames are opaque tokens: the fake mesh encoder
    never looks at pixels, so the capture loop can tick at storm rate
    without allocating image buffers."""

    def __init__(self, width, height, fps, x=0, y=0):
        self.width, self.height = width, height

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return b"frame"


class _SwarmSoloEncoder:
    """Solo-pipeline stand-in for the overflow/degraded paths."""

    def __init__(self):
        self._ready = []
        self._n = 0
        self.closed = False

    def submit(self, frame):
        self._n += 1
        self._ready.append((self._n, [FakeStripe()]))

    def poll(self):
        out, self._ready = self._ready, []
        return out

    def flush(self):
        return self.poll()

    def close(self):
        self.closed = True


class _Member:
    """One swarm client and its measurement state."""

    def __init__(self, idx: int, ws, task, display_id: str, geom) -> None:
        self.idx = idx
        self.ws = ws
        self.task = task
        self.display_id = display_id
        self.geom = geom
        self.joined_at = time.monotonic()
        self.left_at: Optional[float] = None
        self.read_pos = 0          # cursor into ws.sent
        self.frames = 0
        self.last_frame_id = 0
        self.shed = False
        self.killed_reason: Optional[str] = None


def _jain(values: List[float]) -> float:
    vals = [v for v in values if v >= 0]
    if not vals:
        return 0.0
    s = sum(vals)
    s2 = sum(v * v for v in vals)
    if s2 <= 0:
        return 0.0
    return (s * s) / (len(vals) * s2)


def _p95(samples: List[float]) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return round(s[min(len(s) - 1, int(len(s) * 0.95))], 2)


async def swarm_run(n_clients: int = 200, duration_s: float = 10.0,
                    seed: int = 0, concurrency: Optional[int] = None,
                    fps: float = 10.0, slots_per_lane: int = 8,
                    max_lanes: int = 4, encoder: str = "fake",
                    sick_slot: bool = False) -> dict:
    """Run one swarm storm; returns the report dict."""
    from selkies_tpu.parallel.coordinator import MeshEncodeCoordinator
    from selkies_tpu.protocol import VideoStripe, unpack_binary
    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import DataStreamingServer
    from selkies_tpu.settings import Settings

    env = {
        "SELKIES_PORT": "0",
        "SELKIES_AUDIO_ENABLED": "false",
        "SELKIES_COMMAND_ENABLED": "false",
        "SELKIES_SECOND_SCREEN": "true",
        # the swarm IS the load test: caps off, the scheduler is the gate
        "SELKIES_MAX_CLIENTS": "0",
        "SELKIES_MAX_DISPLAYS": "0",
        "SELKIES_TPU_MESH": "session:1",
        "SELKIES_TPU_SESSIONS_PER_CHIP": str(slots_per_lane),
        "SELKIES_MESH_MAX_LANES": str(max_lanes),
        "SELKIES_ADMISSION_QUEUE_MS": "100",
        "SELKIES_SLOT_QUARANTINE_ERRORS": "3",
        "SELKIES_SLOT_HEALTH_WINDOW_S": "30",
        # supervision generous: churn restarts are expected, not fatal
        "SELKIES_SUPERVISOR_MAX_RESTARTS": "10000",
        "SELKIES_SUPERVISOR_RESTART_WINDOW_S": "60",
        "SELKIES_WATCHDOG_FRAMES": "0",
        "SELKIES_RESIZE_DEBOUNCE_MS": "50",
    }
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)

    if encoder == "fake":
        def coordinator_factory(spec, spc, w, h, **kw):
            kw.pop("slots_per_lane", None)
            return MeshEncodeCoordinator(
                spec, spc, w, h, enc_factory=lambda n: FakeMeshEncoder(n),
                slots_per_lane=slots_per_lane,
                lane_retire_s=0.5, **kw)

        server = DataStreamingServer(
            settings, app=app,
            encoder_factory=lambda w, h, s, overrides=None:
                _SwarmSoloEncoder(),
            source_factory=_SwarmSource, host="127.0.0.1")
        server.coordinator_factory = coordinator_factory
    else:
        server = DataStreamingServer(settings, app=app, host="127.0.0.1")
    app.data_server = server

    rng = random.Random(seed)
    concurrency = int(concurrency or min(n_clients, 64))
    members: List[_Member] = []
    active: List[_Member] = []
    joins = leaves = resizes = 0
    eviction_ms: List[float] = []
    next_idx = 0

    async def join() -> Optional[_Member]:
        nonlocal next_idx, joins
        idx = next_idx
        next_idx += 1
        ws = InProcessClient()
        task = asyncio.create_task(server.ws_handler(ws))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(ws.sent) < 2 \
                and not ws.closed:
            await asyncio.sleep(0.005)
        geom = GEOMS[idx % len(GEOMS)]
        m = _Member(idx, ws, task, f"d{idx}", geom)
        ws.feed("SETTINGS," + json.dumps({
            "displayId": m.display_id,
            "initialClientWidth": geom[0],
            "initialClientHeight": geom[1],
            "framerate": fps}))
        members.append(m)
        active.append(m)
        joins += 1
        return m

    def _facade_of(m: _Member):
        st = server.display_clients.get(m.display_id)
        enc = getattr(st, "encoder", None)
        return enc if enc is not None and hasattr(enc, "sid") else None

    async def leave(m: _Member) -> None:
        nonlocal leaves
        facade = _facade_of(m)
        coord = facade._coord if facade is not None else None
        sid = facade.sid if facade is not None else None
        t0 = time.monotonic()
        await m.ws.close()
        try:
            await asyncio.wait_for(m.task, 5.0)
        except asyncio.TimeoutError:
            m.task.cancel()
        if coord is not None and sid is not None:
            while time.monotonic() - t0 < 2.0:
                if sid not in coord._sessions:
                    eviction_ms.append((time.monotonic() - t0) * 1000.0)
                    break
                await asyncio.sleep(0.002)
        m.left_at = time.monotonic()
        if m in active:
            active.remove(m)
        leaves += 1

    def pump(m: _Member) -> None:
        """Read new server→client traffic: count frames, detect KILLs,
        ACK the latest frame id (closing its flight span with real RTT)."""
        new = m.ws.sent[m.read_pos:]
        m.read_pos += len(new)
        latest = None
        for msg in new:
            if isinstance(msg, (bytes, bytearray)):
                try:
                    f = unpack_binary(bytes(msg))
                except Exception:
                    continue
                if isinstance(f, VideoStripe):
                    m.frames += 1
                    m.last_frame_id = f.frame_id
                    latest = f.frame_id
            elif isinstance(msg, str) and msg.startswith("KILL"):
                m.shed = True
                m.killed_reason = msg[5:40]
        if latest is not None and not m.ws.closed:
            m.ws.feed(f"CLIENT_FRAME_ACK,{latest}")

    # ---- the storm -------------------------------------------------------
    t_start = time.monotonic()
    t_end = t_start + duration_s
    t_fault = t_start + duration_s * 0.45 if sick_slot else None
    fault_report: Dict[str, object] = {}
    peak_sessions = 0
    last_pump = 0.0
    # probability of a leave per 4 ms step: enough replacement churn to
    # reach the distinct-client target inside the window (plus a floor
    # so small swarms still churn)
    need = max(0, n_clients - concurrency)
    leave_p = max(0.02, (need / max(1.0, duration_s * 0.8)) * 0.004)

    while time.monotonic() < t_end or joins < n_clients:
        now = time.monotonic()
        # fill toward the concurrency target (this also counts toward the
        # distinct-client goal: leavers are replaced by fresh joiners),
        # then churn: the leave rate is paced so the distinct-client
        # target is reachable within the storm window, plus a steady
        # trickle of resizes — every leave frees a slot a fresh joiner
        # immediately takes, which is exactly the admission churn the
        # scheduler must survive
        if len(active) < concurrency:
            await join()
        elif active and rng.random() < leave_p:
            await leave(rng.choice(active))
        elif active and rng.random() < 0.03:
            m = rng.choice(active)
            if not m.ws.closed:
                w, h = GEOMS[(GEOMS.index(m.geom) + 1) % len(GEOMS)]
                m.geom = (w, h)
                m.ws.feed(f"r,{w}x{h},{m.display_id}")
                resizes += 1
        if now - last_pump > 0.05:
            last_pump = now
            for m in list(active):
                pump(m)
                if m.ws.closed and m in active:   # server killed it
                    active.remove(m)
            peak_sessions = max(peak_sessions, sum(
                c.active_sessions
                for c in server.mesh_coordinators.values()))
        if t_fault is not None and now >= t_fault:
            t_fault = None
            fault_report = await _inject_sick_slot(server, active, pump)
        await asyncio.sleep(0.004)
        if time.monotonic() - t_start > duration_s * 6 + 60:
            break   # hard stop: a wedged storm must not hang CI

    # ---- drain + leak checks ---------------------------------------------
    coords = list(server.mesh_coordinators.values())
    for m in list(active):
        pump(m)
    while active:
        await leave(active[0])
    # clients the SERVER kicked (shed, superseded, slow-consumer) left
    # `active` without a reap: their handler tasks still own display
    # teardown — wait for every one before judging leaks
    for m in members:
        if m.task is not None and not m.task.done():
            if not m.ws.closed:
                await m.ws.close()
            try:
                await asyncio.wait_for(m.task, 3.0)
            except asyncio.TimeoutError:
                m.task.cancel()
            except Exception:
                pass
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and any(
            c.active_sessions for c in coords):
        await asyncio.sleep(0.01)
    leaked_slots = sum(c.active_sessions for c in coords)
    accounting = [p for c in coords for p in c.verify_slot_accounting()]
    migrations = sum(getattr(c, "migrations_total", 0) for c in coords)
    quarantined = sum(c.stats()["quarantined_slots"] for c in coords)
    slot_faults = sum(getattr(c, "slot_faults_total", 0) for c in coords)
    await server.stop()
    open_spans = server.recorder.open_spans()

    chips = max((getattr(c, "chips", 1) for c in coords), default=1)
    rates = []
    for m in members:
        end = m.left_at or time.monotonic()
        dt = end - m.joined_at
        if dt >= 0.5 and not m.shed:
            rates.append(m.frames / dt)
    sick_ok = (not sick_slot) or (
        bool(fault_report.get("victim_migrated"))
        and fault_report.get("cohabitants_stalled") == 0)
    report = {
        "metric": "swarm_churn",
        "swarm_clients": joins,
        "duration_s": round(time.monotonic() - t_start, 2),
        "seed": seed,
        "concurrency": concurrency,
        "encoder": encoder,
        "joins": joins, "leaves": leaves, "resizes": resizes,
        "sessions_peak": peak_sessions,
        "sessions_per_chip": round(peak_sessions / max(1, chips), 2),
        "fairness_jain_index": round(_jain(rates), 4),
        "eviction_ms_p95": _p95(eviction_ms),
        "eviction_samples": len(eviction_ms),
        "frames_delivered_total": sum(m.frames for m in members),
        "sessions_shed": sum(1 for m in members if m.shed),
        "sessions_queued": server.edge_stats["sessions_queued"],
        "sessions_rejected": server.edge_stats["sessions_rejected"],
        "migrations": migrations,
        "migrations_blocked": sum(
            getattr(c, "migrations_blocked_total", 0) for c in coords),
        "quarantined_slots": quarantined,
        "slot_faults_injected": slot_faults,
        "leaked_slots": leaked_slots,
        "slot_accounting_violations": accounting,
        "trace_open_spans": open_spans,
        **fault_report,
    }
    report["alive"] = (leaked_slots == 0 and open_spans == 0
                       and not accounting and sick_ok)
    return report


async def _inject_sick_slot(server, active, pump) -> Dict[str, object]:
    """Arm mesh.slot_raise against one occupied slot and verify: the
    victim migrates, cohabiting sessions' frame IDs advance throughout.

    Churn-resilient: if the victim happens to LEAVE mid-injection (its
    slot goes idle, so the remaining fault arms never fire), a fresh
    victim is picked and re-armed — up to 3 attempts."""

    def _sid_to_member():
        out = {}
        for m in active:
            st = server.display_clients.get(m.display_id)
            enc = getattr(st, "encoder", None)
            if enc is not None and hasattr(enc, "sid"):
                out[enc.sid] = m
        return out

    def _pick():
        """A lane with >= 2 sessions whose chosen victim is still an
        active, streaming swarm member."""
        members = _sid_to_member()
        for coord in server.mesh_coordinators.values():
            with coord._lock:
                for lane in coord.lanes:
                    if len(lane.sessions) < 2:
                        continue
                    for slot, sess in lane.sessions.items():
                        if sess.sid in members:
                            return (coord, lane.id, slot, sess.sid,
                                    members)
        return None

    result: Dict[str, object] = {"victim_migrated": False,
                                 "cohabitants_stalled": 0}

    def _migrations_all() -> int:
        return sum(c.migrations_total
                   for c in server.mesh_coordinators.values())

    migrations_global = _migrations_all()
    for _attempt in range(3):
        target = _pick()
        if target is None:
            result["sick_slot_skipped"] = True
            return result
        coord, lane_id, slot, victim_sid, members = target
        victim = members[victim_sid]
        cohort = [m for sid, m in members.items() if sid != victim_sid]
        before = {m.idx: m.frames for m in cohort}
        server.faults.arm("mesh.slot_raise",
                          times=int(coord._health_sick_errors) + 1,
                          arg=f"{lane_id}:{slot}")
        # generous: at soak scale the event loop lags, stretching the
        # victim's submit cadence (one fault fires per victim tick);
        # migration is detected globally so a re-picked attempt still
        # credits a previous attempt's late-landing migration
        deadline = time.monotonic() + 8.0
        migrated = False
        while time.monotonic() < deadline:
            if _migrations_all() > migrations_global:
                migrated = True
                break
            if victim_sid not in coord._sessions:
                break           # victim left; re-pick below
            await asyncio.sleep(0.02)
        server.faults.disarm("mesh.slot_raise")
        result.update({
            "victim_client": victim.idx,
            "sick_lane": lane_id,
            "sick_slot": slot,
            "cohabitants": len(cohort),
        })
        if migrated:
            await asyncio.sleep(0.4)    # let post-migration frames flow
            for m in cohort:
                pump(m)
            stalled = [m.idx for m in cohort
                       if not m.ws.closed and m in active
                       and m.frames <= before[m.idx]]
            result["victim_migrated"] = True
            result["cohabitants_stalled"] = len(stalled)
            return result
        if victim_sid in coord._sessions:
            return result       # armed + present but never migrated: fail
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--clients", type=int, default=200,
                   help="distinct clients joined across the storm")
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--concurrency", type=int, default=None,
                   help="max simultaneously-connected clients")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fps", type=float, default=10.0)
    p.add_argument("--slots-per-lane", type=int, default=8)
    p.add_argument("--max-lanes", type=int, default=4)
    p.add_argument("--encoder", choices=("fake", "real"), default="fake")
    p.add_argument("--sick-slot", action="store_true",
                   help="fault-inject one occupied slot mid-storm and "
                        "assert quarantine + live migration")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.ERROR)
    report = asyncio.run(swarm_run(
        n_clients=args.clients, duration_s=args.duration, seed=args.seed,
        concurrency=args.concurrency, fps=args.fps,
        slots_per_lane=args.slots_per_lane, max_lanes=args.max_lanes,
        encoder=args.encoder, sick_slot=args.sick_slot))
    print(json.dumps(report, indent=2))
    return 0 if report["alive"] else 1


if __name__ == "__main__":
    sys.exit(main())
