#!/usr/bin/env python3
"""Metrics/docs drift lint: every Prometheus series must be documented.

Cross-checks the series registered in
``selkies_tpu/observability/metrics.py`` against the metrics reference
table in ``docs/observability.md`` — in BOTH directions. A series added
to the code without documentation (or documented but deleted from the
code) fails tier-1 (tests/test_metrics_lint.py), so the operator-facing
reference can never silently drift from what the server actually
exports.

Conventions checked:
* code side: the first string literal of every ``Gauge(`` / ``Counter(``
  / ``Histogram(`` / ``Info(`` construction (the registered name, as
  written — counters keep their explicit ``_total`` suffix, Info keeps
  its base name);
* docs side: every backtick-quoted token in table rows of the
  "Metrics reference" section of docs/observability.md whose cell
  starts the row (``| `name` | ... |``).

Usage::

    python tools/metrics_lint.py          # prints drift, exit 1 on any
"""

from __future__ import annotations

import os
import re
import sys
from typing import Set, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
METRICS_PY = os.path.join(ROOT, "selkies_tpu", "observability",
                          "metrics.py")
DOCS_MD = os.path.join(ROOT, "docs", "observability.md")

_CODE_RE = re.compile(
    r"\b(?:Gauge|Counter|Histogram|Info)\(\s*\n?\s*\"([a-zA-Z_:][a-zA-Z0-9_:]*)\"")
_DOC_ROW_RE = re.compile(r"^\|\s*`([a-zA-Z_:][a-zA-Z0-9_:]*)`\s*\|")


def code_series(path: str = METRICS_PY) -> Set[str]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return set(_CODE_RE.findall(src))


def doc_series(path: str = DOCS_MD) -> Set[str]:
    names: Set[str] = set()
    in_section = False
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if line.startswith("#"):
                in_section = "metrics reference" in line.lower()
                continue
            if not in_section:
                continue
            m = _DOC_ROW_RE.match(line.strip())
            if m:
                names.add(m.group(1))
    return names


def check() -> Tuple[Set[str], Set[str]]:
    """Returns (registered but undocumented, documented but unregistered);
    both empty == no drift."""
    code = code_series()
    docs = doc_series()
    return code - docs, docs - code


def main() -> int:
    try:
        undocumented, stale = check()
    except FileNotFoundError as e:
        print(f"metrics lint: missing input ({e})", file=sys.stderr)
        return 2
    ok = True
    for name in sorted(undocumented):
        print(f"UNDOCUMENTED: {name} is registered in metrics.py but "
              f"missing from docs/observability.md")
        ok = False
    for name in sorted(stale):
        print(f"STALE DOC: {name} is documented in docs/observability.md "
              f"but not registered in metrics.py")
        ok = False
    if ok:
        print(f"metrics lint ok: {len(code_series())} series documented")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
