#!/usr/bin/env python3
"""Mutational protocol fuzzer for the websocket edge.

Drives ``DataStreamingServer.ws_handler`` with garbage, truncated, and
mutated text/binary frames through the in-process
``robustness.testing.InProcessClient`` (no network, no ``websockets``
package), while one *healthy* observer client streams alongside. The
invariant under test (docs/hardening.md) is that hostile input costs the
hostile client at most its own socket:

* no handler task ever dies of an unhandled exception;
* the fuzzing session survives every malformed message (with a generous
  error budget) — only a deliberate ``KILL`` may end it;
* the healthy observer keeps receiving frames throughout;
* ``_uploads`` is empty once the fuzz clients are gone (no leaked fds or
  partial files).

Deterministic for a given ``--seed``: a fixed corpus subset runs in
tier-1 (``tests/test_edge.py``); longer runs are the ``slow``-marked
test and ad-hoc::

    python tools/proto_fuzz.py --iterations 2000 --seed 7 -v
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import random
import string
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from selkies_tpu.robustness.testing import InProcessClient  # noqa: E402

logger = logging.getLogger("proto_fuzz")

#: plausible client verbs + argument shapes, straight from the grammar
#: table in protocol/wire.py — the mutation engine starts from these
_TEMPLATES = (
    "SETTINGS,{json}",
    "CLIENT_FRAME_ACK {int}",
    "r,{int}x{int},{disp}",
    "r,{int}x{int}",
    "s,{float}",
    "cmd,{text}",
    "SET_NATIVE_CURSOR_RENDERING,{bit}",
    "START_VIDEO", "STOP_VIDEO", "START_AUDIO", "STOP_AUDIO",
    "FILE_UPLOAD_START:{path}:{int}",
    "FILE_UPLOAD_END:{path}",
    "FILE_UPLOAD_ERROR:{path}:{text}",
    "cr", "cw,{b64}", "cb,{mime},{b64}",
    "cws,{int}", "cwd,{b64}", "cwe",
    "cbs,{mime},{int}", "cbd,{b64}", "cbe",
    "kd,{int}", "ku,{int}", "kr",
    "m,{int},{int},{int},{int}", "m2,{int},{int},{int},{int}",
    "js,c,{int},{text},{int},{int}", "js,b,{int},{int},{bit}",
    "js,a,{int},{int},{float}", "js,d,{int}",
    "_f {float}", "_l {float}",
    "p,{bit}", "vb,{int}", "ab,{int}", "pong",
)

#: server→client verbs a hostile client may try to spoof
_SERVER_VERBS = (
    "KILL go away", "PIPELINE_RESETTING primary", "MODE websockets",
    "VIDEO_STARTED", "VIDEO_STOPPED", "AUDIO_STARTED", "AUDIO_STOPPED",
    "KILL", "PIPELINE_RESETTING display2,extra",
)

_PATHS = ("a.txt", "dir/b.bin", "../evil", "dir/with:colon.txt",
          "/abs/path", "c\x00d", 'quo"te.txt', "." * 64)


def _fill(rng: random.Random, template: str) -> str:
    def text(n=12):
        return "".join(rng.choice(string.printable[:80]) for _ in range(n))

    return (template
            .replace("{json}", rng.choice((
                # every PARSEABLE dict carries a non-primary displayId: a
                # well-formed SETTINGS without one legitimately takes over
                # the observer's primary display (reference reconnect
                # semantics) — by design, not a finding
                json.dumps({"displayId": rng.choice(("display2", "display3")),
                            "framerate": rng.randrange(-5, 500),
                            "jpeg_quality": rng.randrange(-100, 300)}),
                "{not json", "[]",
                json.dumps({"displayId": "display2",
                            "a": int("9" * rng.randrange(1, 40))}),
                json.dumps({"displayId": "display3", text(4): text(4)}))))
            .replace("{disp}", rng.choice(("primary", "display2", text(6))))
            .replace("{path}", rng.choice(_PATHS))
            .replace("{mime}", rng.choice(("text/plain", "image/png", "x/" )))
            .replace("{b64}", rng.choice(("aGVsbG8=", "!!!notb64!!!", "")))
            .replace("{int}", str(rng.randrange(-10**6, 10**6)))
            .replace("{float}", repr(rng.uniform(-1e6, 1e6)))
            .replace("{bit}", rng.choice("01"))
            .replace("{text}", text(rng.randrange(0, 24))))


def _mutate(rng: random.Random, msg: str) -> str:
    ops = rng.randrange(1, 4)
    for _ in range(ops):
        kind = rng.randrange(6)
        if not msg:
            return msg
        if kind == 0:      # truncate
            msg = msg[:rng.randrange(len(msg))]
        elif kind == 1:    # splice junk
            i = rng.randrange(len(msg) + 1)
            msg = msg[:i] + "".join(
                chr(rng.randrange(1, 0x2FF))
                for _ in range(rng.randrange(1, 8))) + msg[i:]
        elif kind == 2:    # duplicate a delimiter
            msg = msg.replace(
                rng.choice(",: "), rng.choice(",: ") * 2, 1)
        elif kind == 3:    # glue a verb onto its args (prefix confusion)
            msg = msg.replace(" ", "", 1).replace(",", "", 1)
        elif kind == 4:    # case flip
            msg = msg.swapcase()
        else:              # oversize one argument
            msg = msg + "A" * rng.randrange(64, 4096)
    return msg


def gen_message(rng: random.Random):
    """One fuzz message: str (text plane) or bytes (binary plane)."""
    roll = rng.random()
    if roll < 0.40:       # plausible grammar, random args
        return _fill(rng, rng.choice(_TEMPLATES))
    if roll < 0.65:       # mutated grammar
        return _mutate(rng, _fill(rng, rng.choice(_TEMPLATES)))
    if roll < 0.75:       # spoofed server verbs
        m = rng.choice(_SERVER_VERBS)
        return _mutate(rng, m) if rng.random() < 0.3 else m
    if roll < 0.85:       # raw garbage text
        n = rng.randrange(0, 2048)
        return "".join(chr(rng.randrange(1, 0x500)) for _ in range(n))
    # binary plane: random/wrong-direction/oversize frames
    sub = rng.random()
    if sub < 0.2:
        return b""
    if sub < 0.5:
        t = rng.randrange(256)
        return bytes([t]) + rng.randbytes(rng.randrange(0, 4096))
    if sub < 0.7:         # file chunk (with or without an upload open)
        return b"\x01" + rng.randbytes(rng.randrange(0, 8192))
    if sub < 0.9:         # mic chunk, occasionally over the cap
        n = rng.choice((16, 1024, 300 * 1024))
        return b"\x02" + b"\x00" * n
    return rng.randbytes(rng.randrange(1, 64))


class _FuzzEncoder:
    """Minimal pipelined-encoder stand-in: the fuzzer targets the wire
    edge, not the encode path."""

    def __init__(self):
        self._n = 0

    def submit(self, frame):
        self._n += 1
        return self._n

    def poll(self):
        if self._n:
            n, self._n = self._n, 0
            from selkies_tpu.encoder.jpeg import StripeOutput
            return [(n, [StripeOutput(y_start=0, height=16,
                                      jpeg=b"\xff\xd8fuzz\xff\xd9",
                                      is_paintover=False)])]
        return []

    def flush(self):
        return self.poll()

    def close(self):
        pass


class _FuzzSource:
    def __init__(self, width, height, fps):
        self.width, self.height = width, height

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return np.zeros((self.height, self.width, 3), np.uint8)


async def _connect(server):
    ws = InProcessClient()
    task = asyncio.create_task(server.ws_handler(ws))
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(ws.sent) < 2 and not task.done():
        await asyncio.sleep(0.005)
    return ws, task


async def _drain(ws, task, timeout=20.0):
    """Wait until the handler consumed everything fed so far."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if task.done() or ws._incoming.empty():
            return
        await asyncio.sleep(0.01)


def _was_killed(ws) -> bool:
    return any(isinstance(m, str) and m.startswith("KILL")
               for m in ws.sent)


async def fuzz_session(iterations: int = 500, seed: int = 0,
                       error_budget: int = 10 ** 6,
                       settings_env=None) -> dict:
    """Run one deterministic fuzz session; returns the survival report."""
    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import DataStreamingServer
    from selkies_tpu.settings import Settings

    # sandbox uploads, honoring a caller-provided dir (pytest tmp_path)
    if not os.environ.get("SELKIES_UPLOAD_DIR"):
        os.environ["SELKIES_UPLOAD_DIR"] = tempfile.mkdtemp(
            prefix="proto_fuzz_uploads_")
    env = {
        "SELKIES_PORT": "0",
        "SELKIES_AUDIO_ENABLED": "false",
        # NEVER let fuzz input reach a shell
        "SELKIES_COMMAND_ENABLED": "false",
        "SELKIES_PROTOCOL_ERROR_BUDGET": str(error_budget),
        "SELKIES_MAX_DISPLAYS": "8",
        "SELKIES_RESIZE_DEBOUNCE_MS": "50",
    }
    env.update(settings_env or {})
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=lambda w, h, s, overrides=None: _FuzzEncoder(),
        source_factory=lambda w, h, fps, **kw: _FuzzSource(w, h, fps),
        host="127.0.0.1")
    app.data_server = server

    rng = random.Random(seed)
    report = {
        "iterations": iterations, "seed": seed,
        "kills": 0, "premature_deaths": 0, "reconnects": 0,
    }
    try:
        observer, obs_task = await _connect(server)
        observer.feed("SETTINGS," + json.dumps({
            "displayId": "primary", "initialClientWidth": 64,
            "initialClientHeight": 48, "framerate": 30}))
        fuzz, fuzz_task = await _connect(server)

        fed = 0
        while fed < iterations:
            for _ in range(min(25, iterations - fed)):
                fuzz.feed(gen_message(rng))
                fed += 1
            await _drain(fuzz, fuzz_task)
            if fuzz_task.done() or fuzz.closed:
                # a deliberate KILL (abuse budget / admission) is the
                # armor working; anything else is a session death
                if _was_killed(fuzz):
                    report["kills"] += 1
                else:
                    report["premature_deaths"] += 1
                await fuzz.close()
                await asyncio.wait_for(fuzz_task, 10.0)
                fuzz, fuzz_task = await _connect(server)
                report["reconnects"] += 1

        # quiesce: fuzz client leaves; the observer must still stream
        await _drain(fuzz, fuzz_task)
        await fuzz.close()
        await asyncio.wait_for(fuzz_task, 10.0)
        if fuzz_task.exception() is not None:
            report["premature_deaths"] += 1

        n0 = observer.n_frames()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and observer.n_frames() <= n0:
            await asyncio.sleep(0.05)
        report.update({
            "observer_alive": not observer.closed and not obs_task.done(),
            "observer_frames": observer.n_frames(),
            "observer_streaming": observer.n_frames() > n0,
            "uploads_leaked": len(server._uploads),
            "protocol_errors": server.edge_stats["protocol_errors"],
            "rate_limited": dict(server.edge_stats["rate_limited"]),
            "sessions_rejected": server.edge_stats["sessions_rejected"],
            "reconfigure_runs": server.edge_stats["reconfigure_runs"],
            "reconfigure_coalesced":
                server.edge_stats["reconfigure_coalesced"],
        })
        report["alive"] = bool(
            report["premature_deaths"] == 0
            and report["observer_alive"]
            and report["observer_streaming"]
            and report["uploads_leaked"] == 0)
        await observer.close()
        await asyncio.wait_for(obs_task, 10.0)
        return report
    finally:
        await server.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--iterations", type=int, default=500)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--error-budget", type=int, default=10 ** 6,
                   help="per-connection protocol error budget (small "
                        "values exercise the KILL protocol_abuse path)")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.ERROR)
    report = asyncio.run(fuzz_session(
        iterations=args.iterations, seed=args.seed,
        error_budget=args.error_budget))
    print(json.dumps(report, indent=2))
    return 0 if report["alive"] else 1


if __name__ == "__main__":
    sys.exit(main())
