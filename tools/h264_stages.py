"""Per-stage device-time attribution for the tpuenc H.264 path (config 2).

VERDICT r2 item 1: the 5× fps gap against BASELINE config 2 (60 fps
1080p H.264) was unattributed — this tool separates where a frame's time
actually goes, so "lifts on PCIe" claims are measured, not asserted:

  * ``sync_floor_ms``   — cost of one trivial dispatch + host sync on this
    transport (the tunnel's ~100 ms RPC floor; ~0 on PCIe). Every *timing*
    below amortizes it by chaining N async dispatches per one sync.
  * ``me_mc_ms``        — the fused exhaustive ME + MC scan alone
    (ops/pallas_me.py me_mc_stripes, VMEM-resident kernel).
  * ``pack_ms``         — block-sparse level pack alone (_pack_sparse).
  * ``full_step_ms``    — the complete device program the product runs per
    P frame (prepare_planes + ME/MC + transform/quant/recon + pack), i.e.
    the tunnel-excluded device-side frame cost. ``device_fps`` = 1000/this.
  * ``transform_ms``    — derived: full − ME/MC − pack (transform, quant,
    reconstruction, damage select, color conversion).
  * ``d2h_ms``          — wall time to fetch one typical sparse buffer
    (transport-bound on the tunnel; the pipeline overlaps several).
  * ``cavlc_ms``        — host entropy coding of one fetched frame.
  * ``me_tflops``       — analytic FLOP count of the SAD search divided by
    measured ME time (device-utilization estimate for the MXU portion).

Shared-chip protocol: each timing is best-of-``repeats`` (the tunnel's
timings swing ±40% with contention; the minimum is the least-contended
estimate — BASELINE.md round-2 variance note).

Run: ``python tools/h264_stages.py [--frames N]`` → one JSON line.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

W, H = 1920, 1080


def _best_of(fn, repeats: int):
    vals = []
    for _ in range(repeats):
        vals.append(fn())
    return min(vals), vals


def measure(frames: int = 12, repeats: int = 3, width: int = W,
            height: int = H) -> dict:
    import jax
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder import h264_device as dev
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    enc = H264StripeEncoder(width, height)
    src = DeviceScrollSource(width, enc.pad_h)
    S, sh = enc.n_stripes, enc.stripe_h

    def nxt():
        return src.next_frame()

    # ---- sync floor: a trivial program + one host sync ------------------
    tiny = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8, 128), jnp.float32)
    tiny(x).block_until_ready()

    def run_floor():
        t0 = time.perf_counter()
        tiny(x).block_until_ready()
        return (time.perf_counter() - t0) * 1000.0

    sync_floor_ms, floor_runs = _best_of(run_floor, max(repeats, 5))

    # ---- full device step (the product P-frame program) -----------------
    # chain `frames` dispatches through the encoder's own state, then one
    # sync: per-frame cost ≈ (total − sync floor) / frames
    enc.encode_frame(nxt())          # IDR + compile
    enc.encode_frame(nxt())          # P compile
    pend = None

    def run_full():
        nonlocal pend
        t0 = time.perf_counter()
        for _ in range(frames):
            pend = enc.dispatch(nxt(), fetch=False)
        pend.flat16.block_until_ready()
        return ((time.perf_counter() - t0) * 1000.0 - sync_floor_ms) / frames

    full_step_ms, full_runs = _best_of(run_full, repeats)

    # ---- fused ME/MC kernel alone ---------------------------------------
    from selkies_tpu.ops.pallas_me import me_mc_stripes
    y, cb, cr = dev.prepare_planes(nxt(), enc.pad_h, enc.pad_w)
    ys = y.reshape(S, sh, enc.pad_w)
    cbs = cb.reshape(S, sh // 2, enc.pad_w // 2)
    crs = cr.reshape(S, sh // 2, enc.pad_w // 2)
    me = functools.partial(me_mc_stripes, search=enc.search)
    me(ys, ys, cbs, crs)[0].block_until_ready()    # compile

    def run_me():
        t0 = time.perf_counter()
        out = None
        for _ in range(frames):
            out = me(ys, ys, cbs, crs)
        out[0].block_until_ready()
        return ((time.perf_counter() - t0) * 1000.0 - sync_floor_ms) / frames

    me_mc_ms, me_runs = _best_of(run_me, repeats)

    # ---- sparse pack alone ----------------------------------------------
    words = enc._stripe_words
    rng = np.random.default_rng(0)
    f16 = np.zeros((S, words), np.int16)
    nz = rng.random((S, words)) < 0.02             # typical sparsity
    f16[nz] = rng.integers(-40, 41, int(nz.sum()))
    f16j = jnp.asarray(f16)
    damage = jnp.ones((S,), bool)
    pack = jax.jit(functools.partial(dev._pack_sparse, cap_frac=4))
    pack(f16j, damage, damage).block_until_ready()

    def run_pack():
        t0 = time.perf_counter()
        out = None
        for _ in range(frames):
            out = pack(f16j, damage, damage)
        out.block_until_ready()
        return ((time.perf_counter() - t0) * 1000.0 - sync_floor_ms) / frames

    pack_ms, pack_runs = _best_of(run_pack, repeats)

    # ---- D2H of one typical sparse prefix -------------------------------
    # distinct device arrays per read (a repeated read of the same array
    # is host-cached and measures nothing), all computed before the timer
    # so only the transfer is on the clock
    buf = pack(f16j, damage, damage)
    n_reads = max(repeats, 5)
    prefixes = [(buf[:enc._sparse_guess] + jnp.uint8(i))
                for i in range(n_reads)]
    for p_ in prefixes:
        p_.block_until_ready()
    d2h_runs = []
    for p_ in prefixes:
        t0 = time.perf_counter()
        np.asarray(p_)
        d2h_runs.append((time.perf_counter() - t0) * 1000.0)
    d2h_ms = min(d2h_runs)

    # ---- host CAVLC for one frame's typical stripes ---------------------
    # fetch first (off the clock), then time only the entropy coding
    pend = enc.dispatch(nxt(), fetch=True)
    host = np.asarray(pend.fetch)
    t0 = time.perf_counter()
    stripes = enc.harvest(pend, host=host)
    cavlc_ms = (time.perf_counter() - t0) * 1000.0

    # ---- analytic FLOPs of the SAD search (MXU utilization) -------------
    n_offsets = (2 * enc.search + 1) ** 2
    nby, nbx = sh // 16, enc.pad_w // 16
    # per offset per stripe: abs-diff (sh*W) + two indicator matmuls
    flops_per_offset = S * (2 * nby * sh * enc.pad_w      # A @ |d|
                            + 2 * nby * enc.pad_w * nbx)  # (…) @ B
    me_flops = n_offsets * flops_per_offset
    me_tflops = me_flops / (me_mc_ms / 1000.0) / 1e12 if me_mc_ms > 0 else 0

    transform_ms = max(0.0, full_step_ms - me_mc_ms - pack_ms)
    return {
        "sync_floor_ms": round(sync_floor_ms, 2),
        "full_step_ms": round(full_step_ms, 2),
        "me_mc_ms": round(me_mc_ms, 2),
        "pack_ms": round(pack_ms, 2),
        "transform_ms": round(transform_ms, 2),
        "d2h_ms": round(d2h_ms, 2),
        "cavlc_ms": round(cavlc_ms, 2),
        "device_fps": round(1000.0 / full_step_ms, 2)
        if full_step_ms > 0 else None,
        "me_tflops": round(me_tflops, 2),
        "n_offsets": n_offsets,
        "stripes_out": len(stripes),
        "spread": {
            "full_step_ms": [round(v, 2) for v in full_runs],
            "me_mc_ms": [round(v, 2) for v in me_runs],
            "pack_ms": [round(v, 2) for v in pack_runs],
            "sync_floor_ms": [round(v, 2) for v in floor_runs],
            "d2h_ms": [round(v, 2) for v in d2h_runs],
        },
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--frames", type=int, default=12)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--width", type=int, default=W)
    ap.add_argument("--height", type=int, default=H)
    args = ap.parse_args()
    out = measure(frames=args.frames, repeats=args.repeats,
                  width=args.width, height=args.height)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
