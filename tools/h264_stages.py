"""Device-side cost attribution for the tpuenc H.264 path (config 2).

Round-3 lesson (VERDICT r3 weak #2 + the round-2/3 tunnel notes): on the
RPC-tunneled dev chip, per-stage chained-dispatch timings measure the
degraded per-dispatch round trip (~12-65 ms/program after the first
fetch), NOT device compute — the round-3 run of this tool reported a
"full_step_ms" that was mostly transport. The only tunnel-resistant
estimator is the **batch-size sweep**: time the batched scan program
(dev.encode_frame_p_batch_rgb, one dispatch for B frames) at two batch
sizes and take the slope,

    device_ms_per_frame = (T(B2) - T(B1)) / (chain * (B2 - B1)),

which cancels every fixed per-dispatch and per-fetch cost. Stage
attribution comes from re-running the sweep with a stage stubbed out
(``--attribute``): slope(full) - slope(without ME) ≈ ME's in-context
cost, etc. Host CAVLC is timed directly (it is host work).

Outputs one JSON line:
  device_ms_per_frame / device_fps  — tunnel-excluded device truth
  dispatch_overhead_ms              — fixed cost per batch dispatch
  fetch_floor_ms                    — one D2H round trip on this link
  me_ms / pack_ms / transform_ms    — in-context stage slopes (--attribute)
  me_tflops                         — analytic SAD FLOPs / measured ME time
  cavlc_ms_frame                    — host entropy coding per frame
  cavlc_scaling                     — CAVLC wall time at 1/2/4/8 pool threads

The sweep/attribution runs the HOST-entropy profile (entropy="host"):
this tool decomposes the sparse-levels + host-CAVLC path, and its stage
stubs target dev._pack_sparse / the native coder. The streaming default
is the on-device CAVLC tier (encoder/device_cavlc.py, docs/entropy.md);
its device cost shows up in the separate cavlc_pack_ms slope below.

Run: ``python tools/h264_stages.py [--frames N] [--attribute]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

W, H = 1920, 1080


def _sweep(enc, src, b1: int, b2: int, chain: int, reps: int):
    """Slope + intercept of the batched program's wall time vs B."""
    import jax.numpy as jnp

    def run_chain(B):
        frames = jnp.stack([src.next_frame() for _ in range(B)])
        pends = enc.dispatch_batch(frames, fetch=False)     # compile
        np.asarray(pends[-1].batch_heads[0, :64])           # real sync
        best = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(chain):
                pends = enc.dispatch_batch(frames, fetch=False)
            np.asarray(pends[-1].batch_heads[0, :64])       # one tiny fetch
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        return best

    floor = run_chain_floor(enc, src)
    t1, t2 = run_chain(b1), run_chain(b2)
    slope = (t2 - t1) / (chain * (b2 - b1))
    per_dispatch = max(0.0, (t1 - floor) / chain - b1 * slope)
    return slope, per_dispatch, floor, (t1, t2)


def run_chain_floor(enc, src):
    """One tiny fetch with zero extra dispatches = the D2H round trip."""
    import jax.numpy as jnp

    frames = jnp.stack([src.next_frame() for _ in range(2)])
    pends = enc.dispatch_batch(frames, fetch=False)
    np.asarray(pends[-1].batch_heads[0, :64])
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(pends[-1].batch_heads[0, 64:128])
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


def measure(width: int = W, height: int = H, b1: int = 6, b2: int = 12,
            chain: int = 4, reps: int = 3, attribute: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder import h264_device as dev
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    enc = H264StripeEncoder(width, height, entropy="host")
    src = DeviceScrollSource(width, enc.pad_h)
    enc.encode_frame(src.next_frame())          # IDR + compiles
    enc.encode_frame(src.next_frame())

    slope, per_dispatch, floor, raw = _sweep(enc, src, b1, b2, chain, reps)
    out = {
        "device_ms_per_frame": round(slope, 2),
        "device_fps": round(1000.0 / slope, 1) if slope > 0 else None,
        "dispatch_overhead_ms": round(per_dispatch, 2),
        "fetch_floor_ms": round(floor, 2),
        "sweep_raw_ms": [round(v, 1) for v in raw],
        "method": (
            f"slope of one-dispatch batched scan at B={b1} vs B={b2} "
            f"(chain={chain}, best-of-{reps}); cancels per-dispatch RPC"),
    }

    if attribute:
        # stage slopes by stubbing one stage at a time. A fresh encoder
        # object does NOT bust the module-level jit cache — the batched
        # program was already compiled with identical static args — so
        # the caches are cleared around each stubbed variant (this is a
        # standalone tool; recompiles are its cost, not the product's).
        real_me, real_pack = dev.me_mc_stripes, dev._pack_sparse

        def me_stub(cur, ref, ref_cb, ref_cr, search=12, interpret=None):
            S, h, w = cur.shape
            mv = jnp.zeros((S, h // 16, w // 16, 2), jnp.int32)
            return mv, ref, ref_cb, ref_cr

        def pack_stub(flat16, damage, update, cap_frac=4):
            S, Wd = flat16.shape
            _, n_cells, cap = dev.sparse_geometry(Wd, cap_frac)
            total = 4 * S + S * (n_cells // 8) + S * cap * dev.CELL
            return jnp.zeros((total,), jnp.uint8)

        try:
            jax.clear_caches()
            dev.me_mc_stripes = me_stub
            e2 = H264StripeEncoder(width, height, entropy="host")
            s2 = DeviceScrollSource(width, e2.pad_h)
            e2.encode_frame(s2.next_frame())
            e2.encode_frame(s2.next_frame())
            no_me, _, _, _ = _sweep(e2, s2, b1, b2, chain, reps)
        finally:
            dev.me_mc_stripes = real_me
        try:
            jax.clear_caches()
            dev._pack_sparse = pack_stub
            e3 = H264StripeEncoder(width, height, entropy="host")
            s3 = DeviceScrollSource(width, e3.pad_h)
            e3.encode_frame(s3.next_frame())
            e3.encode_frame(s3.next_frame())
            no_pack, _, _, _ = _sweep(e3, s3, b1, b2, chain, reps)
        finally:
            dev._pack_sparse = real_pack
            jax.clear_caches()

        me_ms = max(0.0, slope - no_me)
        pack_ms = max(0.0, slope - no_pack)
        out["me_ms"] = round(me_ms, 2)
        out["pack_ms"] = round(pack_ms, 2)
        out["transform_ms"] = round(max(0.0, slope - me_ms - pack_ms), 2)

        # analytic SAD FLOPs (abs-diff+sums+indicator matmul) / ME time
        S, sh = enc.n_stripes, enc.stripe_h
        n_off = (2 * enc.search + 1) ** 2
        nby, nbx = sh // 16, enc.pad_w // 16
        flops = n_off * S * (2 * nby * sh * enc.pad_w
                             + 2 * nby * enc.pad_w * nbx)
        out["me_tflops"] = round(flops / (me_ms / 1000.0) / 1e12, 2) \
            if me_ms > 0 else None

    # device-CAVLC tier: in-context slope of the streaming default's
    # batched program minus the host-tier program (both one-dispatch
    # scans; the difference is the device entropy pack net of the
    # sparse pack it replaces)
    try:
        jax.clear_caches()
        e4 = H264StripeEncoder(width, height)           # entropy="device"
        s4 = DeviceScrollSource(width, e4.pad_h)
        e4.encode_frame(s4.next_frame())
        e4.encode_frame(s4.next_frame())
        dev_slope, _, _, _ = _sweep(e4, s4, b1, b2, chain, reps)
        out["device_entropy_ms_per_frame"] = round(dev_slope, 2)
        out["cavlc_pack_ms"] = round(dev_slope - slope, 2)
    except Exception as e:
        out["device_entropy_error"] = repr(e)
    finally:
        jax.clear_caches()

    # host CAVLC: one frame fetched, then entropy-only timing; also its
    # scaling over pool sizes (headroom for 4K / multi-session)
    import concurrent.futures

    import selkies_tpu.encoder.h264 as h264mod

    pend = enc.dispatch(src.next_frame(), fetch=True)
    host = np.asarray(pend.fetch)
    scaling = {}
    saved_pool = h264mod._POOL
    try:
        for workers in (1, 2, 4, 8):
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="cavlc")
            h264mod._POOL = pool
            # re-encode the same fetched frame; harvest mutates
            # frame_num state, so rewind it between timings
            t0 = time.perf_counter()
            stripes = enc.harvest(pend, host=host)
            dt = (time.perf_counter() - t0) * 1000.0
            scaling[workers] = round(dt, 2)
            for st in enc.stripes:
                st.frame_num = (st.frame_num - 1) % 16
            pool.shutdown(wait=False)
    finally:
        h264mod._POOL = saved_pool
    out["cavlc_ms_frame"] = scaling[8]
    out["cavlc_scaling"] = scaling
    out["stripes_out"] = len(stripes)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--width", type=int, default=W)
    ap.add_argument("--height", type=int, default=H)
    ap.add_argument("--b1", type=int, default=6)
    ap.add_argument("--b2", type=int, default=12)
    ap.add_argument("--chain", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--attribute", action="store_true",
                    help="also slope-attribute ME/pack/transform (slow)")
    args = ap.parse_args()
    out = measure(width=args.width, height=args.height, b1=args.b1,
                  b2=args.b2, chain=args.chain, reps=args.repeats,
                  attribute=args.attribute)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
