"""CAVLC conformance fuzzer: crafted level arrays → C++ coder → ffmpeg,
and the device-CAVLC differential mode.

Mode 1 (``python tools/cavlc_fuzz.py [n]``): drives h264_encode_picture
with synthetic quantized-level arrays (bypassing the device transforms) so
every (totalCoeff, trailingOnes, nC-class, total_zeros, run_before) table
entry gets exercised, then decodes with OpenCV/ffmpeg and compares against
the NumpyMirror reconstruction.  Validates the hand-entered spec tables in
native/cavlc.cpp.

Mode 2 (``python tools/cavlc_fuzz.py --device [n]``): differential-fuzzes
the ON-DEVICE CAVLC packer (encoder/device_cavlc.py) against the native
_libselkies_cavlc.so reference over random P-frame level tensors — full
residual surface (luma + chroma DC/AC), random MVs (skip/mvd paths),
|level| > 127 edges and escape-overflow magnitudes.  Non-overflow stripes
must be BIT-IDENTICAL; overflow stripes must be flagged (they take the
flat16 + host fallback in the product).  tests/test_device_cavlc.py runs a
seeded subset of this under tier 1.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from selkies_tpu.encoder.h264 import make_pps, make_sps  # noqa: E402
from selkies_tpu.native import cavlc_lib  # noqa: E402
from selkies_tpu.ops.h264_transform import NumpyMirror  # noqa: E402


def mirror_recon_luma(levels, qp, pred=128):
    """Decoder-side luma recon for P-style plain 4×4 levels (n,16,4,4)."""
    d = NumpyMirror.dequant4(levels, qp)
    r = NumpyMirror.inverse_dct4(d)
    return r + pred  # caller clips


def assemble_plane(blocks, mb_w, mb_h):
    """(n,16,4,4) → (H, W) with raster 4×4 grid inside raster MBs."""
    n = mb_w * mb_h
    v = blocks.reshape(mb_h, mb_w, 4, 4, 4, 4)
    v = v.transpose(0, 2, 4, 1, 3, 5)
    return v.reshape(mb_h * 16, mb_w * 16)


def encode_two_frames(luma_levels, mb_w, mb_h, qp):
    lib = cavlc_lib()
    n = mb_w * mb_h
    zero_mv = np.zeros((n, 2), np.int32)
    zero_luma = np.zeros((n, 16, 16), np.int32)
    zero_ldc = np.zeros((n, 16), np.int32)
    zero_cdc = np.zeros((n, 2, 4), np.int32)
    zero_cac = np.zeros((n, 2, 4, 16), np.int32)
    cap = 1 << 22
    buf = np.empty(cap, np.uint8)
    # IDR: all-zero levels → flat 128
    sz = lib.h264_encode_picture(1, mb_w, mb_h, qp, 0, 0, zero_mv, zero_luma,
                                 zero_ldc, zero_cdc, zero_cac, buf, cap)
    idr = bytes(buf[:sz])
    ll = np.ascontiguousarray(luma_levels.reshape(n, 16, 16), np.int32)
    sz = lib.h264_encode_picture(0, mb_w, mb_h, qp, 1, 0, zero_mv, ll,
                                 zero_ldc, zero_cdc, zero_cac, buf, cap)
    p = bytes(buf[:sz])
    return make_sps(mb_w * 16, mb_h * 16) + make_pps() + idr + p


def decode_stream(data):
    import cv2  # lazy: the --device mode needs no decoder

    path = tempfile.mktemp(suffix=".h264")
    with open(path, "wb") as f:
        f.write(data)
    cap = cv2.VideoCapture(path)
    cap.set(cv2.CAP_PROP_CONVERT_RGB, 0)
    frames = []
    while True:
        ok, y = cap.read()
        if not ok:
            break
        frames.append(y.copy())
    os.unlink(path)
    return frames


def random_levels(rng, n_mb, density, magnitude):
    lv = rng.integers(-magnitude, magnitude + 1, (n_mb, 16, 4, 4))
    mask = rng.random((n_mb, 16, 4, 4)) < density
    return (lv * mask).astype(np.int32)


def check_seed(seed, qp=26, mb_w=2, mb_h=2, density=None, magnitude=None):
    rng = np.random.default_rng(seed)
    density = density if density is not None else rng.uniform(0.05, 0.9)
    magnitude = magnitude if magnitude is not None else int(rng.integers(1, 9))
    levels = random_levels(rng, mb_w * mb_h, density, magnitude)
    stream = encode_two_frames(levels, mb_w, mb_h, qp)
    frames = decode_stream(stream)
    if len(frames) != 2:
        return False, f"decoded {len(frames)} frames", levels
    expect = np.clip(
        mirror_recon_luma(levels, qp) .astype(np.int64), -10**9, 10**9)
    expect = np.clip(assemble_plane(expect, mb_w, mb_h) , 0, 255)
    got = frames[1].astype(np.int64)
    if not np.array_equal(got, expect):
        diff = int(np.abs(got - expect).max())
        return False, f"pixel mismatch max {diff}", levels
    return True, "", levels


def random_p_frame(rng, S, n_mb, density, magnitude, mv_range=12):
    """Random device-encoder-shaped P-frame level tensors for S stripes."""
    def sparse(shape, mag):
        lv = rng.integers(-mag, mag + 1, shape)
        return (lv * (rng.random(shape) < density)).astype(np.int32)

    mv = rng.integers(-mv_range, mv_range + 1, (S, n_mb, 2)).astype(np.int32)
    if rng.random() < 0.3:
        mv[:] = 0                        # all-skip / skip-run paths
    elif rng.random() < 0.3:
        mv[:] = mv[:, :1]                # uniform motion → long skip runs
    luma = sparse((S, n_mb, 16, 4, 4), magnitude)
    cdc = sparse((S, n_mb, 2, 2, 2), magnitude)
    cac = sparse((S, n_mb, 2, 4, 4, 4), magnitude)
    cac[..., 0, 0] = 0                   # device zeroes the AC DC slot
    return mv, luma, cdc, cac


def check_device_seed(seed, mb_w=None, mb_h=None, S=2, qp=None,
                      frame_num=None, max_stripe_bytes=65536):
    """Differential: device pack + host glue vs native coder, one seed.

    Returns (ok, why, n_overflow).  Overflow stripes are exempt from the
    bit-compare (the product recodes them from flat16 via the native
    path, which IS the reference — trivially identical) but must be
    flagged so that fallback actually engages.
    """
    import jax.numpy as jnp

    from selkies_tpu.encoder import device_cavlc as dcav
    from selkies_tpu.encoder.h264 import encode_picture_nals_np

    rng = np.random.default_rng(seed)
    mb_w = mb_w if mb_w is not None else int(rng.integers(2, 7))
    mb_h = mb_h if mb_h is not None else int(rng.integers(1, 4))
    qp = qp if qp is not None else int(rng.integers(10, 48))
    frame_num = frame_num if frame_num is not None else int(
        rng.integers(1, 16))
    density = rng.uniform(0.02, 0.9)
    # |level| > 127 (int8-sparse overflow) and escape-overflow (> ~2064)
    # edges both land regularly
    magnitude = int(rng.choice([1, 2, 8, 30, 127, 200, 2063, 2500]))
    n_mb = mb_w * mb_h
    mv, luma, cdc, cac = random_p_frame(rng, S, n_mb, density, magnitude)

    words, t_bits, base_words, ovf = [np.asarray(x) for x in (
        dcav.pack_p_frame_words(
            jnp.asarray(mv), jnp.asarray(luma), jnp.asarray(cdc),
            jnp.asarray(cac), jnp.ones(S, bool),
            mb_w=mb_w, mb_h=mb_h, max_stripe_bytes=max_stripe_bytes))]
    payload = np.stack(
        [(words >> 24) & 0xFF, (words >> 16) & 0xFF,
         (words >> 8) & 0xFF, words & 0xFF], -1).astype(np.uint8).reshape(-1)

    ldc = np.zeros((n_mb, 4, 4), np.int32)
    for s in range(S):
        ref = encode_picture_nals_np(
            mv[s], luma[s], ldc, cdc[s], cac[s], is_idr=False,
            mb_w=mb_w, mb_h=mb_h, qp=qp, frame_num=frame_num)
        if ovf[s]:
            continue
        start = int(base_words[s]) * 4
        nbits = int(t_bits[s])
        got = dcav.assemble_p_slice(
            payload[start:start + ((nbits + 31) // 32) * 4],
            nbits, qp, frame_num)
        if got != ref:
            return False, f"stripe {s} bit mismatch", int(ovf.sum())
    return True, "", int(ovf.sum())


def main_device(n):
    fails, n_ovf = [], 0
    for seed in range(n):
        ok, why, ovf = check_device_seed(seed)
        n_ovf += ovf
        if not ok:
            fails.append((seed, why))
            print(f"seed {seed}: FAIL ({why})")
    print(f"{n - len(fails)}/{n} passed ({n_ovf} overflow stripes "
          "took the flagged fallback)")
    return 1 if fails else 0


def main():
    args = [a for a in sys.argv[1:] if a != "--device"]
    n = int(args[0]) if args else 500
    if "--device" in sys.argv:
        return main_device(n)
    fails = []
    for seed in range(n):
        ok, why, _ = check_seed(seed)
        if not ok:
            fails.append((seed, why))
            print(f"seed {seed}: FAIL ({why})")
    print(f"{n - len(fails)}/{n} passed")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
