"""CAVLC conformance fuzzer: crafted level arrays → C++ coder → ffmpeg.

Drives h264_encode_picture with synthetic quantized-level arrays (bypassing
the device transforms) so every (totalCoeff, trailingOnes, nC-class,
total_zeros, run_before) table entry gets exercised, then decodes with
OpenCV/ffmpeg and compares against the NumpyMirror reconstruction.  Used to
validate the hand-entered spec tables in native/cavlc.cpp; kept as a tool
(tests run a bounded version).
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import cv2  # noqa: E402

from selkies_tpu.encoder.h264 import make_pps, make_sps  # noqa: E402
from selkies_tpu.native import cavlc_lib  # noqa: E402
from selkies_tpu.ops.h264_transform import NumpyMirror  # noqa: E402


def mirror_recon_luma(levels, qp, pred=128):
    """Decoder-side luma recon for P-style plain 4×4 levels (n,16,4,4)."""
    d = NumpyMirror.dequant4(levels, qp)
    r = NumpyMirror.inverse_dct4(d)
    return r + pred  # caller clips


def assemble_plane(blocks, mb_w, mb_h):
    """(n,16,4,4) → (H, W) with raster 4×4 grid inside raster MBs."""
    n = mb_w * mb_h
    v = blocks.reshape(mb_h, mb_w, 4, 4, 4, 4)
    v = v.transpose(0, 2, 4, 1, 3, 5)
    return v.reshape(mb_h * 16, mb_w * 16)


def encode_two_frames(luma_levels, mb_w, mb_h, qp):
    lib = cavlc_lib()
    n = mb_w * mb_h
    zero_mv = np.zeros((n, 2), np.int32)
    zero_luma = np.zeros((n, 16, 16), np.int32)
    zero_ldc = np.zeros((n, 16), np.int32)
    zero_cdc = np.zeros((n, 2, 4), np.int32)
    zero_cac = np.zeros((n, 2, 4, 16), np.int32)
    cap = 1 << 22
    buf = np.empty(cap, np.uint8)
    # IDR: all-zero levels → flat 128
    sz = lib.h264_encode_picture(1, mb_w, mb_h, qp, 0, 0, zero_mv, zero_luma,
                                 zero_ldc, zero_cdc, zero_cac, buf, cap)
    idr = bytes(buf[:sz])
    ll = np.ascontiguousarray(luma_levels.reshape(n, 16, 16), np.int32)
    sz = lib.h264_encode_picture(0, mb_w, mb_h, qp, 1, 0, zero_mv, ll,
                                 zero_ldc, zero_cdc, zero_cac, buf, cap)
    p = bytes(buf[:sz])
    return make_sps(mb_w * 16, mb_h * 16) + make_pps() + idr + p


def decode_stream(data):
    path = tempfile.mktemp(suffix=".h264")
    with open(path, "wb") as f:
        f.write(data)
    cap = cv2.VideoCapture(path)
    cap.set(cv2.CAP_PROP_CONVERT_RGB, 0)
    frames = []
    while True:
        ok, y = cap.read()
        if not ok:
            break
        frames.append(y.copy())
    os.unlink(path)
    return frames


def random_levels(rng, n_mb, density, magnitude):
    lv = rng.integers(-magnitude, magnitude + 1, (n_mb, 16, 4, 4))
    mask = rng.random((n_mb, 16, 4, 4)) < density
    return (lv * mask).astype(np.int32)


def check_seed(seed, qp=26, mb_w=2, mb_h=2, density=None, magnitude=None):
    rng = np.random.default_rng(seed)
    density = density if density is not None else rng.uniform(0.05, 0.9)
    magnitude = magnitude if magnitude is not None else int(rng.integers(1, 9))
    levels = random_levels(rng, mb_w * mb_h, density, magnitude)
    stream = encode_two_frames(levels, mb_w, mb_h, qp)
    frames = decode_stream(stream)
    if len(frames) != 2:
        return False, f"decoded {len(frames)} frames", levels
    expect = np.clip(
        mirror_recon_luma(levels, qp) .astype(np.int64), -10**9, 10**9)
    expect = np.clip(assemble_plane(expect, mb_w, mb_h) , 0, 255)
    got = frames[1].astype(np.int64)
    if not np.array_equal(got, expect):
        diff = int(np.abs(got - expect).max())
        return False, f"pixel mismatch max {diff}", levels
    return True, "", levels


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    fails = []
    for seed in range(n):
        ok, why, _ = check_seed(seed)
        if not ok:
            fails.append((seed, why))
            print(f"seed {seed}: FAIL ({why})")
    print(f"{n - len(fails)}/{n} passed")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
