"""minijs — a small ES2017-subset interpreter, enough to EXECUTE the web
client (web/*.js) in CI.

Why this exists: the image ships no JS runtime (no node/deno/quickjs, no
embeddable engine package), and VERDICT round 1 flagged that the client
tests only regexed the source. This module parses and tree-walks the
actual client files against Python-implemented DOM/WebCodecs stubs
(tests/web_stubs.py), so the demux, ACK, input-mapping and dashboard
logic run for real under pytest.

Supported subset (scoped to what web/*.js uses — see tests):
  let/const/var, functions, arrow functions, default+rest params, array/
  object destructuring, classes (methods, static methods/fields, instance
  fields), template literals, regex literals, for/for-of/for-in, while,
  do-while, switch, try/catch/finally, throw, spread in calls/arrays,
  optional chaining, ?? and ||= style compound assignment, typeof/in/
  instanceof/delete, async/await (eager promises + a microtask queue),
  Map/Set, typed arrays (Uint8Array/Int16Array/Float32Array/DataView/
  ArrayBuffer), JSON, Math, String/Array/Object builtins, btoa/atob.

Deliberately NOT supported: prototype mutation, getters/setters, labels,
generators, `with`, eval, symbols, proxies.
"""

from __future__ import annotations

import json as _json
import math as _math
import re as _re
import struct as _struct
from typing import Any, Callable, Dict, List, Optional, Tuple

# ============================================================= lexer

KEYWORDS = {
    "var", "let", "const", "function", "return", "if", "else", "for", "of",
    "in", "while", "do", "break", "continue", "new", "delete", "typeof",
    "instanceof", "this", "null", "undefined", "true", "false", "class",
    "static", "throw", "try", "catch", "finally", "switch", "case",
    "default", "async", "await", "void",
}

PUNCT = [
    "?.", "...", "===", "!==", "**=", "<<=", ">>=", ">>>=", ">>>", "&&=",
    "||=", "??=", "==", "!=", "<=", ">=", "&&", "||", "??", "++", "--",
    "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "=>", "<<", ">>", "**",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/",
    "%", "&", "|", "^", "!", "~", "?", ":", "=", ".",
]


class Tok:
    __slots__ = ("kind", "value", "line")

    def __init__(self, kind: str, value: Any, line: int):
        self.kind = kind        # num str tmpl regex ident kw punct eof
        self.value = value
        self.line = line

    def __repr__(self):
        return f"Tok({self.kind},{self.value!r})"


class LexError(SyntaxError):
    pass


def tokenize(src: str) -> List[Tok]:
    toks: List[Tok] = []
    i = 0
    n = len(src)
    line = 1

    def prev_allows_regex() -> bool:
        """A '/' starts a regex (not division) after operators/keywords."""
        for t in reversed(toks):
            if t.kind in ("num", "str", "tmpl", "regex"):
                return False
            if t.kind == "ident":
                return False
            if t.kind == "kw":
                return t.value not in ("this", "null", "true", "false",
                                       "undefined")
            if t.kind == "punct":
                return t.value not in (")", "]", "}", "++", "--")
            return True
        return True

    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated comment at line {line}")
            line += src.count("\n", i, j)
            i = j + 2
            continue
        if c == "`":
            # template literal: list of ('s', str) / ('e', token-list) parts
            i += 1
            parts: List[Tuple[str, Any]] = []
            buf = []
            while i < n:
                ch = src[i]
                if ch == "`":
                    i += 1
                    break
                if ch == "\\":
                    esc, i2 = _read_escape(src, i, line)
                    buf.append(esc)
                    i = i2
                    continue
                if src.startswith("${", i):
                    if buf:
                        parts.append(("s", "".join(buf)))
                        buf = []
                    depth = 1
                    j = i + 2
                    while j < n and depth:
                        if src[j] == "{":
                            depth += 1
                        elif src[j] == "}":
                            depth -= 1
                        elif src[j] in "\"'`":
                            j = _skip_string(src, j, line)
                            continue
                        j += 1
                    sub = src[i + 2:j - 1]
                    parts.append(("e", tokenize(sub)))
                    line += src.count("\n", i, j)
                    i = j
                    continue
                if ch == "\n":
                    line += 1
                buf.append(ch)
                i += 1
            else:
                raise LexError(f"unterminated template at line {line}")
            if buf:
                parts.append(("s", "".join(buf)))
            toks.append(Tok("tmpl", parts, line))
            continue
        if c in "\"'":
            quote = c
            i += 1
            buf = []
            while i < n and src[i] != quote:
                if src[i] == "\\":
                    esc, i = _read_escape(src, i, line)
                    buf.append(esc)
                else:
                    if src[i] == "\n":
                        raise LexError(f"newline in string at line {line}")
                    buf.append(src[i])
                    i += 1
            if i >= n:
                raise LexError(f"unterminated string at line {line}")
            i += 1
            toks.append(Tok("str", "".join(buf), line))
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            m = _re.match(
                r"0[xX][0-9a-fA-F]+|0[bB][01]+|0[oO][0-7]+|"
                r"\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?",
                src[i:])
            text = m.group(0)
            if text[:2].lower() == "0x":
                val = float(int(text, 16))
            elif text[:2].lower() == "0b":
                val = float(int(text, 2))
            elif text[:2].lower() == "0o":
                val = float(int(text, 8))
            else:
                val = float(text)
            toks.append(Tok("num", val, line))
            i += len(text)
            continue
        if c.isalpha() or c in "_$":
            m = _re.match(r"[A-Za-z_$][A-Za-z0-9_$]*", src[i:])
            word = m.group(0)
            toks.append(Tok("kw" if word in KEYWORDS else "ident",
                            word, line))
            i += len(word)
            continue
        if c == "/" and prev_allows_regex():
            j = i + 1
            in_class = False
            while j < n:
                ch = src[j]
                if ch == "\\":
                    j += 2
                    continue
                if ch == "[":
                    in_class = True
                elif ch == "]":
                    in_class = False
                elif ch == "/" and not in_class:
                    break
                elif ch == "\n":
                    raise LexError(f"unterminated regex at line {line}")
                j += 1
            pattern = src[i + 1:j]
            j += 1
            fm = _re.match(r"[a-z]*", src[j:])
            flags = fm.group(0)
            toks.append(Tok("regex", (pattern, flags), line))
            i = j + len(flags)
            continue
        for p in PUNCT:
            if src.startswith(p, i):
                toks.append(Tok("punct", p, line))
                i += len(p)
                break
        else:
            raise LexError(f"unexpected char {c!r} at line {line}")
    toks.append(Tok("eof", None, line))
    return toks


def _read_escape(src: str, i: int, line: int) -> Tuple[str, int]:
    """i points at the backslash; returns (char, next_i)."""
    c = src[i + 1]
    simple = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
              "v": "\v", "0": "\0", "\n": ""}
    if c in simple:
        return simple[c], i + 2
    if c == "x":
        return chr(int(src[i + 2:i + 4], 16)), i + 4
    if c == "u":
        if src[i + 2] == "{":
            j = src.index("}", i)
            return chr(int(src[i + 3:j], 16)), j + 1
        return chr(int(src[i + 2:i + 6], 16)), i + 6
    return c, i + 2


def _skip_string(src: str, i: int, line: int) -> int:
    quote = src[i]
    i += 1
    while i < len(src) and src[i] != quote:
        if src[i] == "\\":
            i += 1
        i += 1
    return i + 1


# ============================================================= parser

ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<=",
              ">>=", ">>>=", "**=", "&&=", "||=", "??="}

BIN_PREC = {
    "??": 1, "||": 2, "&&": 3, "|": 4, "^": 5, "&": 6,
    "==": 7, "!=": 7, "===": 7, "!==": 7,
    "<": 8, ">": 8, "<=": 8, ">=": 8, "in": 8, "instanceof": 8,
    "<<": 9, ">>": 9, ">>>": 9,
    "+": 10, "-": 10,
    "*": 11, "/": 11, "%": 11,
    "**": 12,
}


class Parser:
    def __init__(self, toks: List[Tok]):
        self.toks = toks
        self.i = 0

    # ---- helpers

    def peek(self, k: int = 0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, kind: str, value: Any = None) -> bool:
        t = self.peek()
        return t.kind == kind and (value is None or t.value == value)

    def eat(self, kind: str, value: Any = None) -> Optional[Tok]:
        if self.at(kind, value):
            return self.next()
        return None

    def expect(self, kind: str, value: Any = None) -> Tok:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            raise SyntaxError(
                f"expected {value or kind}, got {t.kind} {t.value!r} "
                f"at line {t.line}")
        return t

    def semi(self) -> None:
        self.eat("punct", ";")

    # ---- program

    def parse_program(self) -> list:
        stmts = []
        while not self.at("eof"):
            stmts.append(self.statement())
        return stmts

    # ---- statements

    def statement(self):
        t = self.peek()
        if t.kind == "punct" and t.value == "{":
            return self.block()
        if t.kind == "punct" and t.value == ";":
            self.next()
            return ("empty",)
        if t.kind == "kw":
            v = t.value
            if v in ("var", "let", "const"):
                d = self.var_decl()
                self.semi()
                return d
            if v == "function":
                return self.func_decl(False)
            if v == "async" and self.peek(1).kind == "kw" \
                    and self.peek(1).value == "function":
                self.next()
                return self.func_decl(True)
            if v == "class":
                return self.class_decl()
            if v == "if":
                return self.if_stmt()
            if v == "for":
                return self.for_stmt()
            if v == "while":
                self.next()
                self.expect("punct", "(")
                test = self.expression()
                self.expect("punct", ")")
                return ("while", test, self.statement())
            if v == "do":
                self.next()
                body = self.statement()
                self.expect("kw", "while")
                self.expect("punct", "(")
                test = self.expression()
                self.expect("punct", ")")
                self.semi()
                return ("dowhile", body, test)
            if v == "return":
                self.next()
                if self.at("punct", ";") or self.at("punct", "}") \
                        or self.at("eof"):
                    self.semi()
                    return ("ret", None)
                e = self.expression()
                self.semi()
                return ("ret", e)
            if v == "break":
                self.next()
                self.semi()
                return ("break",)
            if v == "continue":
                self.next()
                self.semi()
                return ("continue",)
            if v == "throw":
                self.next()
                e = self.expression()
                self.semi()
                return ("throw", e)
            if v == "try":
                return self.try_stmt()
            if v == "switch":
                return self.switch_stmt()
        e = self.expression()
        self.semi()
        return ("expr", e)

    def block(self):
        self.expect("punct", "{")
        stmts = []
        while not self.at("punct", "}"):
            stmts.append(self.statement())
        self.expect("punct", "}")
        return ("block", stmts)

    def var_decl(self):
        kind = self.next().value
        decls = []
        while True:
            target = self.binding_target()
            init = None
            if self.eat("punct", "="):
                init = self.assignment()
            decls.append((target, init))
            if not self.eat("punct", ","):
                break
        return ("var", kind, decls)

    def binding_target(self):
        if self.at("punct", "["):
            self.next()
            elems = []
            while not self.at("punct", "]"):
                if self.eat("punct", ","):
                    elems.append(None)
                    continue
                pat = self.binding_target()
                default = None
                if self.eat("punct", "="):
                    default = self.assignment()
                elems.append(("el", pat, default))
                if not self.at("punct", "]"):
                    self.expect("punct", ",")
            self.expect("punct", "]")
            return ("arrpat", elems)
        if self.at("punct", "{"):
            self.next()
            props = []
            while not self.at("punct", "}"):
                key = self.next()
                if key.kind not in ("ident", "kw", "str"):
                    raise SyntaxError(f"bad objpat key at line {key.line}")
                name = key.value
                pat = ("ident", name)
                if self.eat("punct", ":"):
                    pat = self.binding_target()
                default = None
                if self.eat("punct", "="):
                    default = self.assignment()
                props.append((name, pat, default))
                if not self.at("punct", "}"):
                    self.expect("punct", ",")
            self.expect("punct", "}")
            return ("objpat", props)
        t = self.next()
        if t.kind not in ("ident", "kw"):
            raise SyntaxError(f"bad binding at line {t.line}")
        return ("ident", t.value)

    def func_decl(self, is_async: bool):
        self.expect("kw", "function")
        name = self.expect("ident").value
        params = self.param_list()
        body = self.block()
        return ("func", name, params, body, is_async)

    def param_list(self):
        self.expect("punct", "(")
        params = []
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                params.append(("rest", self.expect("ident").value))
            else:
                pat = self.binding_target()
                default = None
                if self.eat("punct", "="):
                    default = self.assignment()
                params.append(("p", pat, default))
            if not self.at("punct", ")"):
                self.expect("punct", ",")
        self.expect("punct", ")")
        return params

    def class_decl(self):
        self.expect("kw", "class")
        name = self.expect("ident").value
        parent = None
        if self.at("ident", "extends") or self.at("kw", "extends"):
            self.next()
            parent = self.expression()
        methods = []
        fields = []
        self.expect("punct", "{")
        while not self.at("punct", "}"):
            if self.eat("punct", ";"):
                continue
            is_static = False
            if self.at("kw", "static"):
                self.next()
                is_static = True
            is_async = False
            if self.at("kw", "async") and not (
                    self.peek(1).kind == "punct"
                    and self.peek(1).value in ("(", "=")):
                self.next()
                is_async = True
            t = self.next()
            if t.kind not in ("ident", "kw", "str"):
                raise SyntaxError(f"bad class member at line {t.line}")
            mname = t.value
            if self.at("punct", "("):
                params = self.param_list()
                body = self.block()
                methods.append((is_static, mname, params, body, is_async))
            else:
                init = None
                if self.eat("punct", "="):
                    init = self.assignment()
                self.semi()
                fields.append((is_static, mname, init))
        self.expect("punct", "}")
        return ("class", name, parent, methods, fields)

    def if_stmt(self):
        self.expect("kw", "if")
        self.expect("punct", "(")
        test = self.expression()
        self.expect("punct", ")")
        cons = self.statement()
        alt = None
        if self.eat("kw", "else"):
            alt = self.statement()
        return ("if", test, cons, alt)

    def for_stmt(self):
        self.expect("kw", "for")
        self.expect("punct", "(")
        init = None
        if not self.at("punct", ";"):
            if self.at("kw", "var") or self.at("kw", "let") \
                    or self.at("kw", "const"):
                kind = self.next().value
                target = self.binding_target()
                if self.at("kw", "of"):
                    self.next()
                    it = self.expression()
                    self.expect("punct", ")")
                    return ("forof", kind, target, it, self.statement())
                if self.at("kw", "in"):
                    self.next()
                    obj = self.expression()
                    self.expect("punct", ")")
                    return ("forin", kind, target, obj, self.statement())
                decls = []
                i0 = None
                if self.eat("punct", "="):
                    i0 = self.assignment()
                decls.append((target, i0))
                while self.eat("punct", ","):
                    tgt = self.binding_target()
                    i1 = None
                    if self.eat("punct", "="):
                        i1 = self.assignment()
                    decls.append((tgt, i1))
                init = ("var", kind, decls)
            else:
                e = self.expression()
                if self.at("kw", "of"):
                    self.next()
                    it = self.expression()
                    self.expect("punct", ")")
                    return ("forof", None, _expr_to_pattern(e), it,
                            self.statement())
                if self.at("kw", "in"):
                    self.next()
                    obj = self.expression()
                    self.expect("punct", ")")
                    return ("forin", None, _expr_to_pattern(e), obj,
                            self.statement())
                init = ("expr", e)
        self.expect("punct", ";")
        test = None if self.at("punct", ";") else self.expression()
        self.expect("punct", ";")
        update = None if self.at("punct", ")") else self.expression()
        self.expect("punct", ")")
        return ("for", init, test, update, self.statement())

    def try_stmt(self):
        self.expect("kw", "try")
        block = self.block()
        param = catch = final = None
        if self.eat("kw", "catch"):
            if self.eat("punct", "("):
                param = self.binding_target()
                self.expect("punct", ")")
            catch = self.block()
        if self.eat("kw", "finally"):
            final = self.block()
        return ("try", block, param, catch, final)

    def switch_stmt(self):
        self.expect("kw", "switch")
        self.expect("punct", "(")
        disc = self.expression()
        self.expect("punct", ")")
        self.expect("punct", "{")
        cases = []
        while not self.at("punct", "}"):
            if self.eat("kw", "case"):
                test = self.expression()
                self.expect("punct", ":")
            else:
                self.expect("kw", "default")
                self.expect("punct", ":")
                test = None
            body = []
            while not (self.at("kw", "case") or self.at("kw", "default")
                       or self.at("punct", "}")):
                body.append(self.statement())
            cases.append((test, body))
        self.expect("punct", "}")
        return ("switch", disc, cases)

    # ---- expressions

    def expression(self):
        e = self.assignment()
        if self.at("punct", ","):
            exprs = [e]
            while self.eat("punct", ","):
                exprs.append(self.assignment())
            return ("seq", exprs)
        return e

    def assignment(self):
        if self._arrow_ahead():
            return self.arrow_function(False)
        if self.at("kw", "async") and self._arrow_ahead(1):
            self.next()
            return self.arrow_function(True)
        left = self.conditional()
        t = self.peek()
        if t.kind == "punct" and t.value in ASSIGN_OPS:
            op = self.next().value
            right = self.assignment()
            return ("assign", op, left, right)
        return left

    def _arrow_ahead(self, offset: int = 0) -> bool:
        """Lookahead: identifier=> or (params)=> from position i+offset."""
        t = self.peek(offset)
        if t.kind == "ident" and self.peek(offset + 1).kind == "punct" \
                and self.peek(offset + 1).value == "=>":
            return True
        if t.kind == "punct" and t.value == "(":
            depth = 0
            j = self.i + offset
            while j < len(self.toks):
                tk = self.toks[j]
                if tk.kind == "punct" and tk.value == "(":
                    depth += 1
                elif tk.kind == "punct" and tk.value == ")":
                    depth -= 1
                    if depth == 0:
                        nxt = self.toks[j + 1] if j + 1 < len(self.toks) \
                            else None
                        return (nxt is not None and nxt.kind == "punct"
                                and nxt.value == "=>")
                elif tk.kind == "eof":
                    return False
                j += 1
        return False

    def arrow_function(self, is_async: bool):
        if self.at("ident"):
            params = [("p", ("ident", self.next().value), None)]
        else:
            params = self.param_list()
        self.expect("punct", "=>")
        if self.at("punct", "{"):
            body = self.block()
            expr_body = False
        else:
            body = self.assignment()
            expr_body = True
        return ("fn", None, params, body, is_async, True, expr_body)

    def conditional(self):
        test = self.binary(0)
        if self.at("punct", "?") and not self.at("punct", "?."):
            self.next()
            cons = self.assignment()
            self.expect("punct", ":")
            alt = self.assignment()
            return ("cond", test, cons, alt)
        return test

    def binary(self, min_prec: int):
        left = self.unary()
        while True:
            t = self.peek()
            op = None
            if t.kind == "punct" and t.value in BIN_PREC:
                op = t.value
            elif t.kind == "kw" and t.value in ("in", "instanceof"):
                op = t.value
            if op is None:
                return left
            prec = BIN_PREC[op]
            if prec < min_prec:
                return left
            self.next()
            right = self.binary(prec + 1)
            kind = "logic" if op in ("&&", "||", "??") else "bin"
            left = (kind, op, left, right)

    def unary(self):
        t = self.peek()
        if t.kind == "punct" and t.value in ("!", "-", "+", "~"):
            self.next()
            return ("un", t.value, self.unary())
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            return ("update", t.value, True, self.unary())
        if t.kind == "kw" and t.value in ("typeof", "delete", "void"):
            self.next()
            return ("un", t.value, self.unary())
        if t.kind == "kw" and t.value == "await":
            self.next()
            return ("await", self.unary())
        if t.kind == "kw" and t.value == "new":
            self.next()
            callee = self.member_chain(self.primary(), no_call=True)
            args = []
            if self.at("punct", "("):
                args = self.arguments()
            return self.member_chain(("new", callee, args))
        e = self.postfix()
        return e

    def postfix(self):
        e = self.member_chain(self.primary())
        t = self.peek()
        if t.kind == "punct" and t.value in ("++", "--"):
            self.next()
            return ("update", t.value, False, e)
        return e

    def member_chain(self, e, no_call: bool = False):
        while True:
            if self.at("punct", "."):
                self.next()
                prop = self.next()
                if prop.kind not in ("ident", "kw"):
                    raise SyntaxError(f"bad member at line {prop.line}")
                e = ("member", e, ("str", prop.value), False, False)
            elif self.at("punct", "?."):
                self.next()
                if self.at("punct", "("):
                    e = ("call", e, self.arguments(), True)
                elif self.at("punct", "["):
                    self.next()
                    idx = self.expression()
                    self.expect("punct", "]")
                    e = ("member", e, idx, True, True)
                else:
                    prop = self.next()
                    e = ("member", e, ("str", prop.value), False, True)
            elif self.at("punct", "["):
                self.next()
                idx = self.expression()
                self.expect("punct", "]")
                e = ("member", e, idx, True, False)
            elif self.at("punct", "(") and not no_call:
                e = ("call", e, self.arguments(), False)
            else:
                return e

    def arguments(self):
        self.expect("punct", "(")
        args = []
        while not self.at("punct", ")"):
            if self.eat("punct", "..."):
                args.append(("spread", self.assignment()))
            else:
                args.append(self.assignment())
            if not self.at("punct", ")"):
                self.expect("punct", ",")
        self.expect("punct", ")")
        return args

    def primary(self):
        t = self.next()
        if t.kind == "num":
            return ("num", t.value)
        if t.kind == "str":
            return ("str", t.value)
        if t.kind == "regex":
            return ("regex", t.value[0], t.value[1])
        if t.kind == "tmpl":
            parts = []
            for k, v in t.value:
                if k == "s":
                    parts.append(("s", v))
                else:
                    parts.append(("e", Parser(v).expression()))
            return ("tmpl", parts)
        if t.kind == "ident":
            return ("ident", t.value)
        if t.kind == "kw":
            v = t.value
            if v == "this":
                return ("this",)
            if v == "null":
                return ("null",)
            if v == "undefined":
                return ("undef",)
            if v == "true":
                return ("bool", True)
            if v == "false":
                return ("bool", False)
            if v == "function":
                name = None
                if self.at("ident"):
                    name = self.next().value
                params = self.param_list()
                body = self.block()
                return ("fn", name, params, body, False, False, False)
            if v == "async" and self.at("kw", "function"):
                self.next()
                name = None
                if self.at("ident"):
                    name = self.next().value
                params = self.param_list()
                body = self.block()
                return ("fn", name, params, body, True, False, False)
            if v == "class":
                # anonymous class expression — not used by the client
                raise SyntaxError(f"class expression at line {t.line}")
            if v in ("of", "static", "async", "let"):   # contextual
                return ("ident", v)
            raise SyntaxError(f"unexpected keyword {v} at line {t.line}")
        if t.kind == "punct":
            if t.value == "(":
                e = self.expression()
                self.expect("punct", ")")
                return e
            if t.value == "[":
                elems = []
                while not self.at("punct", "]"):
                    if self.at("punct", ","):
                        self.next()
                        elems.append(("undef",))
                        continue
                    if self.eat("punct", "..."):
                        elems.append(("spread", self.assignment()))
                    else:
                        elems.append(self.assignment())
                    if not self.at("punct", "]"):
                        self.expect("punct", ",")
                self.expect("punct", "]")
                return ("arr", elems)
            if t.value == "{":
                props = []
                while not self.at("punct", "}"):
                    if self.eat("punct", "..."):
                        props.append(("spread", self.assignment()))
                        if not self.at("punct", "}"):
                            self.expect("punct", ",")
                        continue
                    key = self.next()
                    computed = False
                    if key.kind == "punct" and key.value == "[":
                        kexpr = self.assignment()
                        self.expect("punct", "]")
                        computed = True
                    elif key.kind in ("ident", "kw", "str"):
                        kexpr = ("str", key.value)
                    elif key.kind == "num":
                        kexpr = ("str", _num_to_str(key.value))
                    else:
                        raise SyntaxError(
                            f"bad object key at line {key.line}")
                    if self.at("punct", "("):
                        params = self.param_list()
                        body = self.block()
                        props.append(("kv", kexpr, (
                            "fn", None, params, body, False, False, False),
                            computed))
                    elif self.eat("punct", ":"):
                        props.append(("kv", kexpr, self.assignment(),
                                      computed))
                    else:   # shorthand
                        props.append(("kv", kexpr,
                                      ("ident", key.value), False))
                    if not self.at("punct", "}"):
                        self.expect("punct", ",")
                self.expect("punct", "}")
                return ("obj", props)
        raise SyntaxError(f"unexpected token {t.kind} {t.value!r} "
                          f"at line {t.line}")


def _expr_to_pattern(e):
    if e[0] == "ident":
        return e
    if e[0] == "arr":
        return ("arrpat", [("el", _expr_to_pattern(x), None)
                           for x in e[1]])
    raise SyntaxError(f"unsupported for-loop target {e[0]}")


def _num_to_str(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "Infinity"
    if v == float("-inf"):
        return "-Infinity"
    if v == int(v) and abs(v) < 1e21:
        return str(int(v))
    return repr(v)


def parse(src: str) -> list:
    return Parser(tokenize(src)).parse_program()


# ============================================================ runtime

class JSUndefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEF = JSUndefined()


class JSObject:
    def __init__(self, props: Optional[dict] = None, klass=None):
        self.props = props or {}
        self.klass = klass

    def __repr__(self):
        return f"JSObject({list(self.props)[:6]})"


class JSArray:
    def __init__(self, elems: Optional[list] = None):
        self.elems = elems if elems is not None else []

    def __repr__(self):
        return f"JSArray({self.elems!r})"


class JSFunction:
    def __init__(self, name, params, body, env, is_async, is_arrow,
                 expr_body, this_val=UNDEF, interp=None):
        self.name = name or ""
        self.params = params
        self.body = body
        self.env = env
        self.is_async = is_async
        self.is_arrow = is_arrow
        self.expr_body = expr_body
        self.this_val = this_val      # captured `this` for arrows
        self.interp = interp

    def __repr__(self):
        return f"JSFunction({self.name})"


class BoundMethod:
    def __init__(self, fn, this):
        self.fn = fn
        self.this = this


class JSClass:
    def __init__(self, name, methods, fields, statics):
        self.name = name
        self.methods = methods        # name -> JSFunction
        self.fields = fields          # [(name, init_expr, env)]
        self.props = statics          # static members

    def __repr__(self):
        return f"JSClass({self.name})"


class JSRegExp:
    def __init__(self, pattern: str, flags: str):
        self.source = pattern
        self.flags = flags
        pyflags = 0
        if "i" in flags:
            pyflags |= _re.IGNORECASE
        if "m" in flags:
            pyflags |= _re.MULTILINE
        if "s" in flags:
            pyflags |= _re.DOTALL
        self.re = _re.compile(_js_regex_to_py(pattern), pyflags)
        self.global_ = "g" in flags


def _js_regex_to_py(p: str) -> str:
    # the client's regexes are simple; translate the few divergences
    return p.replace(r"\d", "[0-9]").replace(r"\w", "[A-Za-z0-9_]") \
            .replace(r"\b", r"\b")


class JSPromise:
    def __init__(self, interp):
        self.interp = interp
        self.state = "pending"        # pending | fulfilled | rejected
        self.value = UNDEF
        self.callbacks: List[Tuple[Any, Any]] = []

    def resolve(self, value):
        if self.state != "pending":
            return
        if isinstance(value, JSPromise):
            value.then_native(self.resolve, self.reject)
            return
        self.state = "fulfilled"
        self.value = value
        self._flush()

    def reject(self, value):
        if self.state != "pending":
            return
        self.state = "rejected"
        self.value = value
        self._flush()

    def _flush(self):
        for on_ok, on_err in self.callbacks:
            self._schedule(on_ok, on_err)
        self.callbacks = []

    def _schedule(self, on_ok, on_err):
        def task():
            if self.state == "fulfilled" and on_ok is not None:
                on_ok(self.value)
            elif self.state == "rejected" and on_err is not None:
                on_err(self.value)
        self.interp.microtasks.append(task)

    def then_native(self, on_ok, on_err=None):
        if self.state == "pending":
            self.callbacks.append((on_ok, on_err))
        else:
            self._schedule(on_ok, on_err)


class JSArrayBuffer:
    def __init__(self, data):
        self.data = bytearray(data) if not isinstance(data, bytearray) \
            else data

    @property
    def byteLength(self):
        return float(len(self.data))

    def slice(self, start=0.0, end=None):
        n = len(self.data)
        s = int(to_num(start))
        e = n if end is None or end is UNDEF else int(to_num(end))
        if s < 0:
            s += n
        if e < 0:
            e += n
        s = max(0, min(n, s))
        e = max(s, min(n, e))
        return JSArrayBuffer(bytearray(self.data[s:e]))


_DTYPES = {"u1": ("B", 1), "i2": ("h", 2), "f4": ("f", 4)}


class JSTypedArray:
    def __init__(self, kind: str, buffer: JSArrayBuffer, offset: int = 0,
                 length: Optional[int] = None):
        self.kind = kind
        fmt, size = _DTYPES[kind]
        self.fmt, self.itemsize = fmt, size
        self.buffer = buffer
        self.offset = offset
        avail = (len(buffer.data) - offset) // size
        self.length = avail if length is None else length

    def get(self, i: int):
        if not 0 <= i < self.length:
            return UNDEF
        off = self.offset + i * self.itemsize
        return float(_struct.unpack_from(
            "<" + self.fmt, self.buffer.data, off)[0])

    def set_index(self, i: int, v: float):
        if not 0 <= i < self.length:
            return
        off = self.offset + i * self.itemsize
        if self.fmt == "B":
            v = int(v) & 0xFF
        elif self.fmt == "h":
            v = ((int(v) + 0x8000) & 0xFFFF) - 0x8000
        _struct.pack_into("<" + self.fmt, self.buffer.data, off, v)

    def tolist(self):
        return [self.get(i) for i in range(self.length)]


class JSDataView:
    def __init__(self, buffer: JSArrayBuffer, offset: int = 0,
                 length: Optional[int] = None):
        self.buffer = buffer
        self.offset = offset
        self.length = (len(buffer.data) - offset) if length is None \
            else length


class JSThrow(Exception):
    def __init__(self, value):
        self.value = value
        super().__init__(_safe_str(value))


class ReturnEx(Exception):
    def __init__(self, value):
        self.value = value


class BreakEx(Exception):
    pass


class ContinueEx(Exception):
    pass


def _safe_str(v):
    try:
        if isinstance(v, JSObject) and "message" in v.props:
            return str(v.props.get("name", "Error")) + ": " + \
                str(v.props["message"])
        return str(v)
    except Exception:
        return "<js value>"


class Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None, vars=None):
        self.vars = vars or {}
        self.parent = parent

    def lookup(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        raise JSThrow(make_error("ReferenceError", f"{name} is not defined"))

    def set_existing(self, name, value) -> bool:
        e = self
        while e is not None:
            if name in e.vars:
                e.vars[name] = value
                return True
            e = e.parent
        return False

    def declare(self, name, value):
        self.vars[name] = value


def make_error(name: str, message: str) -> JSObject:
    return JSObject({"name": name, "message": message,
                     "stack": name + ": " + message})


# ========================================================== evaluator

def to_num(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if isinstance(v, int):
        return float(v)
    if v is UNDEF:
        return float("nan")
    if v is None:
        return 0.0
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            if s[:2].lower() == "0x":
                return float(int(s, 16))
            return float(s)
        except ValueError:
            return float("nan")
    return float("nan")


def to_int32(v) -> int:
    f = to_num(v)
    if f != f or f in (float("inf"), float("-inf")):
        return 0
    i = int(f) & 0xFFFFFFFF
    return i - 0x100000000 if i >= 0x80000000 else i


def to_uint32(v) -> int:
    f = to_num(v)
    if f != f or f in (float("inf"), float("-inf")):
        return 0
    return int(f) & 0xFFFFFFFF


def truthy(v) -> bool:
    if v is UNDEF or v is None:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return v == v and v != 0.0
    if isinstance(v, int):
        return v != 0
    if isinstance(v, str):
        return len(v) > 0
    return True


def to_str(v) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return _num_to_str(v)
    if isinstance(v, int):
        return _num_to_str(float(v))
    if v is UNDEF:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, JSArray):
        return ",".join("" if (e is UNDEF or e is None) else to_str(e)
                        for e in v.elems)
    if isinstance(v, JSObject):
        if "message" in v.props and "name" in v.props:
            return f"{to_str(v.props['name'])}: {to_str(v.props['message'])}"
        return "[object Object]"
    if isinstance(v, (JSFunction, BoundMethod)):
        return "function"
    if isinstance(v, JSTypedArray):
        return ",".join(_num_to_str(x) for x in v.tolist())
    return str(v)


def strict_eq(a, b) -> bool:
    if a is UNDEF and b is UNDEF:
        return True
    if a is None and b is None:
        return True
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b


def loose_eq(a, b) -> bool:
    if (a is UNDEF or a is None) and (b is UNDEF or b is None):
        return True
    if (a is UNDEF or a is None) or (b is UNDEF or b is None):
        return False
    if isinstance(a, str) and isinstance(b, (int, float)) \
            and not isinstance(b, bool):
        return to_num(a) == float(b)
    if isinstance(b, str) and isinstance(a, (int, float)) \
            and not isinstance(a, bool):
        return to_num(b) == float(a)
    if isinstance(a, bool) or isinstance(b, bool):
        return to_num(a) == to_num(b)
    return strict_eq(a, b)


class NativeFunction:
    """Python callable exposed to JS. fn(this, args, interp) -> value."""

    def __init__(self, fn: Callable, name: str = ""):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "")

    def __repr__(self):
        return f"NativeFunction({self.name})"


class Interp:
    def __init__(self):
        self.globals = Env()
        self.microtasks: List[Callable] = []
        self.timers: List[Tuple[float, Any, float, bool]] = []
        self._timer_id = 1
        self.timer_map: Dict[int, Tuple[Any, float, bool]] = {}
        install_stdlib(self)

    # ------------------------------------------------------ entry points

    def run(self, src: str, env: Optional[Env] = None):
        stmts = parse(src)
        env = env or self.globals
        self.hoist(stmts, env)
        result = UNDEF
        for s in stmts:
            result = self.exec_stmt(s, env)
        return result

    def run_microtasks(self, limit: int = 10000):
        n = 0
        while self.microtasks and n < limit:
            task = self.microtasks.pop(0)
            task()
            n += 1

    def fire_timers(self, count: int = 1):
        """Fire every registered interval/timeout ``count`` times (tests
        drive time manually)."""
        for _ in range(count):
            for tid in list(self.timer_map):
                entry = self.timer_map.get(tid)
                if entry is None:
                    continue
                fn, _delay, repeat = entry
                if not repeat:
                    del self.timer_map[tid]
                self.call(fn, [])
                self.run_microtasks()

    # ------------------------------------------------------ declarations

    def hoist(self, stmts, env):
        for s in stmts:
            if s[0] == "func":
                _, name, params, body, is_async = s
                env.declare(name, JSFunction(
                    name, params, body, env, is_async, False, False,
                    interp=self))
            elif s[0] == "var" and s[1] == "var":
                for target, _init in s[2]:
                    if target[0] == "ident" and target[1] not in env.vars:
                        env.declare(target[1], UNDEF)

    # ------------------------------------------------------- statements

    def exec_stmt(self, s, env):
        kind = s[0]
        if kind == "expr":
            return self.eval(s[1], env)
        if kind == "var":
            for target, init in s[2]:
                val = UNDEF if init is None else self.eval(init, env)
                self.bind_pattern(target, val, env, declare=True)
            return UNDEF
        if kind == "block":
            inner = Env(env)
            self.hoist(s[1], inner)
            for st in s[1]:
                self.exec_stmt(st, inner)
            return UNDEF
        if kind == "if":
            if truthy(self.eval(s[1], env)):
                self.exec_stmt(s[2], env)
            elif s[3] is not None:
                self.exec_stmt(s[3], env)
            return UNDEF
        if kind == "while":
            while truthy(self.eval(s[1], env)):
                try:
                    self.exec_stmt(s[2], env)
                except BreakEx:
                    break
                except ContinueEx:
                    continue
            return UNDEF
        if kind == "dowhile":
            while True:
                try:
                    self.exec_stmt(s[1], env)
                except BreakEx:
                    break
                except ContinueEx:
                    pass
                if not truthy(self.eval(s[2], env)):
                    break
            return UNDEF
        if kind == "for":
            _, init, test, update, body = s
            loop_env = Env(env)
            if init is not None:
                self.exec_stmt(init, loop_env)
            while test is None or truthy(self.eval(test, loop_env)):
                try:
                    self.exec_stmt(body, Env(loop_env))
                except BreakEx:
                    break
                except ContinueEx:
                    pass
                if update is not None:
                    self.eval(update, loop_env)
            return UNDEF
        if kind == "forof":
            _, dkind, target, iterable, body = s
            it = self.eval(iterable, env)
            for item in self.js_iter(it):
                inner = Env(env)
                self.bind_pattern(target, item, inner, declare=True)
                try:
                    self.exec_stmt(body, inner)
                except BreakEx:
                    break
                except ContinueEx:
                    continue
            return UNDEF
        if kind == "forin":
            _, dkind, target, objexpr, body = s
            obj = self.eval(objexpr, env)
            for key in self.enum_keys(obj):
                inner = Env(env)
                self.bind_pattern(target, key, inner, declare=True)
                try:
                    self.exec_stmt(body, inner)
                except BreakEx:
                    break
                except ContinueEx:
                    continue
            return UNDEF
        if kind == "switch":
            _, disc_e, cases = s
            disc = self.eval(disc_e, env)
            inner = Env(env)
            matched = False
            try:
                for test, body in cases:
                    if not matched and test is not None \
                            and strict_eq(self.eval(test, inner), disc):
                        matched = True
                    if matched:
                        for st in body:
                            self.exec_stmt(st, inner)
                if not matched:
                    seen_default = False
                    for test, body in cases:
                        if test is None:
                            seen_default = True
                        if seen_default:
                            for st in body:
                                self.exec_stmt(st, inner)
            except BreakEx:
                pass
            return UNDEF
        if kind == "try":
            _, block, param, catch, final = s
            try:
                self.exec_stmt(block, env)
            except JSThrow as ex:
                if catch is not None:
                    inner = Env(env)
                    if param is not None:
                        self.bind_pattern(param, ex.value, inner,
                                          declare=True)
                    self.exec_stmt(catch, inner)
                elif final is None:
                    raise
            finally:
                if final is not None:
                    self.exec_stmt(final, env)
            return UNDEF
        if kind == "throw":
            raise JSThrow(self.eval(s[1], env))
        if kind == "ret":
            raise ReturnEx(UNDEF if s[1] is None else self.eval(s[1], env))
        if kind == "break":
            raise BreakEx()
        if kind == "continue":
            raise ContinueEx()
        if kind == "func":
            return UNDEF          # hoisted
        if kind == "class":
            _, name, parent, methods, fields = s
            env.declare(name, self.make_class(s, env))
            return UNDEF
        if kind == "empty":
            return UNDEF
        raise RuntimeError(f"unknown statement {kind}")

    def make_class(self, s, env):
        _, name, parent, methods, fields = s
        meth = {}
        statics = {}
        inst_fields = []
        for is_static, mname, params, body, is_async in methods:
            fn = JSFunction(mname, params, body, env, is_async, False,
                            False, interp=self)
            if is_static:
                statics[mname] = fn
            else:
                meth[mname] = fn
        klass = JSClass(name, meth, inst_fields, statics)
        for is_static, fname, init in fields:
            if is_static:
                statics[fname] = UNDEF if init is None \
                    else self.eval(init, env)
            else:
                inst_fields.append((fname, init, env))
        return klass

    # ------------------------------------------------------ expressions

    def eval(self, e, env):
        kind = e[0]
        if kind == "num":
            return e[1]
        if kind == "str":
            return e[1]
        if kind == "bool":
            return e[1]
        if kind == "null":
            return None
        if kind == "undef":
            return UNDEF
        if kind == "regex":
            return JSRegExp(e[1], e[2])
        if kind == "tmpl":
            out = []
            for k, v in e[1]:
                out.append(v if k == "s" else to_str(self.eval(v, env)))
            return "".join(out)
        if kind == "ident":
            return env.lookup(e[1])
        if kind == "this":
            return env.lookup("this")
        if kind == "arr":
            elems = []
            for el in e[1]:
                if el[0] == "spread":
                    elems.extend(self.js_iter(self.eval(el[1], env)))
                else:
                    elems.append(self.eval(el, env))
            return JSArray(elems)
        if kind == "obj":
            props = {}
            for p in e[1]:
                if p[0] == "spread":
                    src = self.eval(p[1], env)
                    if isinstance(src, JSObject):
                        props.update(src.props)
                    continue
                _, kexpr, vexpr, computed = p
                key = to_str(self.eval(kexpr, env)) if computed \
                    else kexpr[1]
                props[key] = self.eval(vexpr, env)
            return JSObject(props)
        if kind == "fn":
            _, name, params, body, is_async, is_arrow, expr_body = e
            this_val = UNDEF
            if is_arrow:
                try:
                    this_val = env.lookup("this")
                except JSThrow:
                    this_val = UNDEF
            return JSFunction(name, params, body, env, is_async, is_arrow,
                              expr_body, this_val, interp=self)
        if kind == "seq":
            out = UNDEF
            for sub in e[1]:
                out = self.eval(sub, env)
            return out
        if kind == "cond":
            return self.eval(e[2], env) if truthy(self.eval(e[1], env)) \
                else self.eval(e[3], env)
        if kind == "logic":
            op = e[1]
            left = self.eval(e[2], env)
            if op == "&&":
                return self.eval(e[3], env) if truthy(left) else left
            if op == "||":
                return left if truthy(left) else self.eval(e[3], env)
            if op == "??":
                return self.eval(e[3], env) \
                    if (left is UNDEF or left is None) else left
        if kind == "bin":
            return self.binop(e[1], self.eval(e[2], env),
                              self.eval(e[3], env))
        if kind == "un":
            op = e[1]
            if op == "typeof":
                try:
                    v = self.eval(e[2], env)
                except JSThrow:
                    return "undefined"
                return js_typeof(v)
            if op == "delete":
                tgt = e[2]
                if tgt[0] == "member":
                    obj = self.eval(tgt[1], env)
                    key = to_str(self.eval(tgt[2], env))
                    if isinstance(obj, JSObject):
                        obj.props.pop(key, None)
                    elif isinstance(obj, JSArray) and key.isdigit():
                        i = int(key)
                        if 0 <= i < len(obj.elems):
                            obj.elems[i] = UNDEF
                return True
            v = self.eval(e[2], env)
            if op == "!":
                return not truthy(v)
            if op == "-":
                return -to_num(v)
            if op == "+":
                return to_num(v)
            if op == "~":
                return float(~to_int32(v))
            if op == "void":
                return UNDEF
        if kind == "update":
            _, op, prefix, target = e
            old = to_num(self.eval(target, env))
            new = old + (1.0 if op == "++" else -1.0)
            self.assign_to(target, new, env)
            return new if prefix else old
        if kind == "assign":
            _, op, target, vexpr = e
            if op == "=":
                val = self.eval(vexpr, env)
            elif op in ("&&=", "||=", "??="):
                cur = self.eval(target, env)
                if op == "&&=" and not truthy(cur):
                    return cur
                if op == "||=" and truthy(cur):
                    return cur
                if op == "??=" and not (cur is UNDEF or cur is None):
                    return cur
                val = self.eval(vexpr, env)
            else:
                cur = self.eval(target, env)
                val = self.binop(op[:-1], cur, self.eval(vexpr, env))
            self.assign_to(target, val, env)
            return val
        if kind == "member":
            _, oexpr, pexpr, computed, optional = e
            obj = self.eval(oexpr, env)
            if optional and (obj is UNDEF or obj is None):
                return UNDEF
            key = self.eval(pexpr, env)
            return self.get_prop(obj, key)
        if kind == "call":
            _, callee, args, optional = e
            if callee[0] == "member":
                obj = self.eval(callee[1], env)
                if (optional or callee[4]) and (obj is UNDEF or obj is None):
                    return UNDEF
                key = self.eval(callee[2], env)
                fn = self.get_prop(obj, key)
                if optional and (fn is UNDEF or fn is None):
                    return UNDEF
                argv = self.eval_args(args, env)
                return self.call(fn, argv, this=obj)
            fn = self.eval(callee, env)
            if optional and (fn is UNDEF or fn is None):
                return UNDEF
            argv = self.eval_args(args, env)
            return self.call(fn, argv)
        if kind == "new":
            _, cexpr, args = e
            ctor = self.eval(cexpr, env)
            argv = self.eval_args(args, env)
            return self.construct(ctor, argv)
        if kind == "await":
            v = self.eval(e[1], env)
            return self.await_value(v)
        raise RuntimeError(f"unknown expression {kind}")

    def eval_args(self, args, env) -> list:
        out = []
        for a in args:
            if a[0] == "spread":
                out.extend(self.js_iter(self.eval(a[1], env)))
            else:
                out.append(self.eval(a, env))
        return out

    def await_value(self, v):
        if isinstance(v, JSPromise):
            self.run_microtasks()
            for _ in range(10000):
                if v.state != "pending":
                    break
                if not self.microtasks:
                    raise JSThrow(make_error(
                        "Error", "await on a promise that never settles "
                        "(stub should resolve synchronously)"))
                self.run_microtasks()
            if v.state == "rejected":
                raise JSThrow(v.value)
            return v.value
        return v

    def binop(self, op, a, b):
        if op == "+":
            if isinstance(a, str) or isinstance(b, str) \
                    or isinstance(a, (JSArray, JSObject)) \
                    or isinstance(b, (JSArray, JSObject)):
                return to_str(a) + to_str(b)
            return to_num(a) + to_num(b)
        if op == "-":
            return to_num(a) - to_num(b)
        if op == "*":
            return to_num(a) * to_num(b)
        if op == "/":
            x, y = to_num(a), to_num(b)
            if y == 0:
                if x == 0 or x != x:
                    return float("nan")
                return float("inf") if x > 0 else float("-inf")
            return x / y
        if op == "%":
            x, y = to_num(a), to_num(b)
            if y == 0 or x != x or y != y:
                return float("nan")
            return _math.fmod(x, y)
        if op == "**":
            return to_num(a) ** to_num(b)
        if op == "==":
            return loose_eq(a, b)
        if op == "!=":
            return not loose_eq(a, b)
        if op == "===":
            return strict_eq(a, b)
        if op == "!==":
            return not strict_eq(a, b)
        if op in ("<", ">", "<=", ">="):
            if isinstance(a, str) and isinstance(b, str):
                return {"<": a < b, ">": a > b,
                        "<=": a <= b, ">=": a >= b}[op]
            x, y = to_num(a), to_num(b)
            if x != x or y != y:
                return False
            return {"<": x < y, ">": x > y, "<=": x <= y, ">=": x >= y}[op]
        if op == "&":
            return float(to_int32(a) & to_int32(b))
        if op == "|":
            return float(to_int32(a) | to_int32(b))
        if op == "^":
            return float(to_int32(a) ^ to_int32(b))
        if op == "<<":
            return float(to_int32(to_int32(a) << (to_uint32(b) & 31)))
        if op == ">>":
            return float(to_int32(a) >> (to_uint32(b) & 31))
        if op == ">>>":
            return float(to_uint32(a) >> (to_uint32(b) & 31))
        if op == "in":
            key = to_str(a)
            if isinstance(b, JSObject):
                return key in b.props
            if isinstance(b, JSArray):
                return key.isdigit() and int(key) < len(b.elems)
            if isinstance(b, dict):
                return key in b
            return hasattr(b, key)
        if op == "instanceof":
            if isinstance(b, JSClass):
                return isinstance(a, JSObject) and a.klass is b
            if isinstance(b, NativeFunction):
                return js_instanceof_native(a, b.name)
            return False
        raise RuntimeError(f"unknown binop {op}")

    # -------------------------------------------------- binding/assign

    def bind_pattern(self, pat, val, env, declare=False):
        kind = pat[0]
        if kind == "ident":
            if declare:
                env.declare(pat[1], val)
            elif not env.set_existing(pat[1], val):
                self.globals.declare(pat[1], val)
            return
        if kind == "arrpat":
            items = list(self.js_iter(val)) if val not in (UNDEF, None) \
                else []
            for i, el in enumerate(pat[1]):
                if el is None:
                    continue
                _, sub, default = el
                v = items[i] if i < len(items) else UNDEF
                if v is UNDEF and default is not None:
                    v = self.eval(default, env)
                self.bind_pattern(sub, v, env, declare)
            return
        if kind == "objpat":
            for name, sub, default in pat[1]:
                v = self.get_prop(val, name)
                if v is UNDEF and default is not None:
                    v = self.eval(default, env)
                self.bind_pattern(sub, v, env, declare)
            return
        raise RuntimeError(f"unknown pattern {kind}")

    def assign_to(self, target, val, env):
        if target[0] == "ident":
            if not env.set_existing(target[1], val):
                self.globals.declare(target[1], val)
            return
        if target[0] == "member":
            obj = self.eval(target[1], env)
            key = self.eval(target[2], env)
            self.set_prop(obj, key, val)
            return
        if target[0] == "arr":
            self.bind_pattern(_expr_to_pattern(target), val, env)
            return
        raise JSThrow(make_error("SyntaxError", "bad assignment target"))

    # ------------------------------------------------------- functions

    def call(self, fn, args: list, this=UNDEF):
        if isinstance(fn, BoundMethod):
            return self.call(fn.fn, args, this=fn.this)
        if isinstance(fn, NativeFunction):
            try:
                return fn.fn(this, args, self)
            except (JSThrow, ReturnEx, BreakEx, ContinueEx):
                raise
            except Exception as e:
                # host failures surface as catchable JS exceptions, the
                # way a browser API throwing does
                raise JSThrow(make_error("Error", str(e)))
        if isinstance(fn, JSFunction):
            return self.invoke(fn, args, this)
        if callable(fn):
            try:
                out = fn(*args)
            except (JSThrow, ReturnEx, BreakEx, ContinueEx):
                raise
            except Exception as e:
                raise JSThrow(make_error("Error", str(e)))
            return normalize_host(out)
        raise JSThrow(make_error("TypeError",
                                 f"{_safe_str(fn)} is not a function"))

    def invoke(self, fn: JSFunction, args: list, this=UNDEF):
        env = Env(fn.env)
        if fn.is_arrow:
            env.declare("this", fn.this_val)
        else:
            env.declare("this", this)
        i = 0
        for p in fn.params:
            if p[0] == "rest":
                env.declare(p[1], JSArray(list(args[i:])))
                break
            _, pat, default = p
            v = args[i] if i < len(args) else UNDEF
            if v is UNDEF and default is not None:
                v = self.eval(default, env)
            self.bind_pattern(pat, v, env, declare=True)
            i += 1
        try:
            if fn.expr_body:
                result = self.eval(fn.body, env)
            else:
                self.hoist(fn.body[1], env)
                for st in fn.body[1]:
                    self.exec_stmt(st, env)
                result = UNDEF
        except ReturnEx as r:
            result = r.value
        except JSThrow:
            if fn.is_async:
                p = JSPromise(self)
                import sys
                p.reject(sys.exc_info()[1].value)
                return p
            raise
        if fn.is_async:
            p = JSPromise(self)
            p.resolve(result)
            return p
        return result

    def construct(self, ctor, args: list):
        if isinstance(ctor, JSClass):
            obj = JSObject({}, klass=ctor)
            for fname, init, fenv in ctor.fields:
                fe = Env(fenv)
                fe.declare("this", obj)
                obj.props[fname] = UNDEF if init is None \
                    else self.eval(init, fe)
            ctor_fn = ctor.methods.get("constructor")
            if ctor_fn is not None:
                self.invoke(ctor_fn, args, this=obj)
            return obj
        if isinstance(ctor, NativeFunction):
            return ctor.fn(None, args, self)
        if callable(ctor):
            return normalize_host(ctor(*args))
        raise JSThrow(make_error("TypeError", "not a constructor"))

    # ------------------------------------------------------ iteration

    def js_iter(self, v):
        if isinstance(v, JSArray):
            return list(v.elems)
        if isinstance(v, str):
            return list(v)
        if isinstance(v, JSTypedArray):
            return v.tolist()
        if isinstance(v, dict):       # Map
            return [JSArray([k, val]) for k, val in v.items()]
        if isinstance(v, set):
            return list(v)
        if isinstance(v, JSObject) and "__iter__" in v.props:
            return self.call(v.props["__iter__"], [], this=v)
        if isinstance(v, (list, tuple)):
            return list(v)
        if hasattr(v, "__js_iter__"):
            return list(v.__js_iter__())
        raise JSThrow(make_error("TypeError",
                                 f"{_safe_str(v)} is not iterable"))

    def enum_keys(self, v):
        if isinstance(v, JSObject):
            return list(v.props.keys())
        if isinstance(v, JSArray):
            return [_num_to_str(float(i)) for i in range(len(v.elems))]
        if isinstance(v, dict):
            return list(v.keys())
        return []


def js_typeof(v) -> str:
    if v is UNDEF:
        return "undefined"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (JSFunction, NativeFunction, BoundMethod, JSClass)) \
            or callable(v):
        return "function"
    return "object"


def js_instanceof_native(v, name: str) -> bool:
    return {
        "Uint8Array": isinstance(v, JSTypedArray) and v.kind == "u1",
        "Int16Array": isinstance(v, JSTypedArray) and v.kind == "i2",
        "Float32Array": isinstance(v, JSTypedArray) and v.kind == "f4",
        "ArrayBuffer": isinstance(v, JSArrayBuffer),
        "Array": isinstance(v, JSArray),
        "Map": isinstance(v, dict),
        "Set": isinstance(v, set),
    }.get(name, False)


def normalize_host(v):
    """Host (python) return values → JS values."""
    if v is None:
        return UNDEF
    if isinstance(v, int) and not isinstance(v, bool):
        return float(v)
    if isinstance(v, (bytes, bytearray)):
        return JSArrayBuffer(bytearray(v))
    return v


# ======================================================= property layer

def _nf(fn, name=""):
    return NativeFunction(fn, name)


def _method(table, obj, key):
    fn = table.get(key)
    if fn is None:
        return None
    return BoundMethod(_nf(fn, key), obj)


def _get_prop(self, obj, key):
    if isinstance(key, float) and not isinstance(obj, (JSObject, JSClass)):
        idx = int(key)
        if isinstance(obj, JSArray):
            return obj.elems[idx] if 0 <= idx < len(obj.elems) else UNDEF
        if isinstance(obj, str):
            return obj[idx] if 0 <= idx < len(obj) else UNDEF
        if isinstance(obj, JSTypedArray):
            return obj.get(idx)
    key = to_str(key)
    if obj is UNDEF or obj is None:
        raise JSThrow(make_error(
            "TypeError", f"cannot read {key!r} of {to_str(obj)}"))
    if isinstance(obj, JSObject):
        if key in obj.props:
            v = obj.props[key]
            if isinstance(v, JSFunction) and not v.is_arrow:
                return BoundMethod(v, obj)
            return v
        k = obj.klass
        if k is not None and key in k.methods:
            return BoundMethod(k.methods[key], obj)
        if k is not None and key == "constructor":
            return k
        if key == "hasOwnProperty":
            return NativeFunction(
                lambda t, a, i, _o=obj: to_str(a[0]) in _o.props
                if a else False, "hasOwnProperty")
        return UNDEF
    if isinstance(obj, JSArray):
        if key == "length":
            return float(len(obj.elems))
        if key.lstrip("-").isdigit():
            i = int(key)
            return obj.elems[i] if 0 <= i < len(obj.elems) else UNDEF
        m = _method(ARRAY_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, str):
        if key == "length":
            return float(len(obj))
        if key.isdigit():
            i = int(key)
            return obj[i] if i < len(obj) else UNDEF
        m = _method(STRING_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, bool):
        m = _method(BOOL_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, float):
        m = _method(NUMBER_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, dict):
        if key == "size":
            return float(len(obj))
        m = _method(MAP_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, set):
        if key == "size":
            return float(len(obj))
        m = _method(SET_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, JSTypedArray):
        if key == "length":
            return float(obj.length)
        if key == "byteLength":
            return float(obj.length * obj.itemsize)
        if key == "byteOffset":
            return float(obj.offset)
        if key == "buffer":
            return obj.buffer
        if key.isdigit():
            return obj.get(int(key))
        m = _method(TYPED_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, JSArrayBuffer):
        if key == "byteLength":
            return float(len(obj.data))
        if key == "slice":
            return obj.slice          # copying slice, like the spec's
        return UNDEF
    if isinstance(obj, JSDataView):
        if key == "byteLength":
            return float(obj.length)
        if key == "buffer":
            return obj.buffer
        m = _method(DATAVIEW_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, JSPromise):
        m = _method(PROMISE_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, JSRegExp):
        if key == "source":
            return obj.source
        if key == "flags":
            return obj.flags
        m = _method(REGEX_METHODS, obj, key)
        if m:
            return m
        return UNDEF
    if isinstance(obj, (JSFunction, BoundMethod, NativeFunction)):
        if key == "name":
            return getattr(obj, "name", "")
        if key == "bind":
            def _bind(this, args, interp, _f=obj):
                bt = args[0] if args else UNDEF
                pre = list(args[1:])
                def bound(this2, args2, interp2):
                    return interp2.call(_f, pre + list(args2), this=bt)
                return _nf(bound, "bound")
            return BoundMethod(_nf(_bind, "bind"), obj)
        if key == "call":
            def _call(this, args, interp, _f=obj):
                t = args[0] if args else UNDEF
                return interp.call(_f, list(args[1:]), this=t)
            return BoundMethod(_nf(_call, "call"), obj)
        if key == "apply":
            def _apply(this, args, interp, _f=obj):
                t = args[0] if args else UNDEF
                rest = args[1] if len(args) > 1 else JSArray([])
                return interp.call(_f, list(interp.js_iter(rest)), this=t)
            return BoundMethod(_nf(_apply, "apply"), obj)
        # constructor statics (WebSocket.OPEN, Array.isArray, ...) live as
        # host attributes on the function object
        return normalize_host(getattr(obj, key, UNDEF))
    if isinstance(obj, JSClass):
        if key in obj.props:
            v = obj.props[key]
            if isinstance(v, JSFunction):
                return BoundMethod(v, obj)
            return v
        if key == "name":
            return obj.name
        return UNDEF
    # host object
    v = getattr(obj, key, UNDEF)
    return normalize_host(v)


def _set_prop(self, obj, key, val):
    if isinstance(key, float) and isinstance(obj, JSArray):
        i = int(key)
        while len(obj.elems) <= i:
            obj.elems.append(UNDEF)
        obj.elems[i] = val
        return
    if isinstance(key, float) and isinstance(obj, JSTypedArray):
        obj.set_index(int(key), to_num(val))
        return
    key = to_str(key)
    if isinstance(obj, JSObject):
        obj.props[key] = val
        return
    if isinstance(obj, JSClass):
        obj.props[key] = val
        return
    if isinstance(obj, JSArray):
        if key == "length":
            n = int(to_num(val))
            del obj.elems[n:]
            return
        if key.isdigit():
            i = int(key)
            while len(obj.elems) <= i:
                obj.elems.append(UNDEF)
            obj.elems[i] = val
            return
        return
    if isinstance(obj, JSTypedArray) and key.isdigit():
        obj.set_index(int(key), to_num(val))
        return
    if obj is UNDEF or obj is None:
        raise JSThrow(make_error(
            "TypeError", f"cannot set {key!r} of {to_str(obj)}"))
    try:
        setattr(obj, key, val)
    except (AttributeError, TypeError):
        pass


Interp.get_prop = _get_prop
Interp.set_prop = _set_prop


# ========================================================== method tables

def _arg(args, i, default=UNDEF):
    return args[i] if i < len(args) else default


# ---- strings

def _str_replace(this, args, interp):
    pat, repl = _arg(args, 0), _arg(args, 1)

    def do_repl(m):
        if isinstance(repl, (JSFunction, BoundMethod, NativeFunction)):
            groups = [m.group(0)] + [g if g is not None else UNDEF
                                     for g in m.groups()]
            return to_str(interp.call(repl, [
                g for g in groups] + [float(m.start()), this]))
        out = to_str(repl)
        out = out.replace("$&", m.group(0))
        return out

    if isinstance(pat, JSRegExp):
        count = 0 if pat.global_ else 1
        return pat.re.sub(do_repl, this, count=count)
    pat_s = to_str(pat)
    if isinstance(repl, (JSFunction, BoundMethod, NativeFunction)):
        idx = this.find(pat_s)
        if idx < 0:
            return this
        rep = to_str(interp.call(repl, [pat_s, float(idx), this]))
        return this[:idx] + rep + this[idx + len(pat_s):]
    return this.replace(pat_s, to_str(repl), 1)


def _str_replace_all(this, args, interp):
    pat = to_str(_arg(args, 0))
    repl = to_str(_arg(args, 1))
    return this.replace(pat, repl)


def _str_split(this, args, interp):
    sep = _arg(args, 0)
    if sep is UNDEF:
        return JSArray([this])
    if isinstance(sep, JSRegExp):
        return JSArray(sep.re.split(this))
    sep = to_str(sep)
    if sep == "":
        return JSArray(list(this))
    limit = _arg(args, 1)
    parts = this.split(sep)
    if limit is not UNDEF:
        parts = parts[:int(to_num(limit))]
    return JSArray(parts)


def _str_slice(this, args, interp):
    n = len(this)
    a = int(to_num(_arg(args, 0, 0.0)))
    b = _arg(args, 1)
    b = n if b is UNDEF else int(to_num(b))
    return this[slice(*_norm_range(a, b, n))]


def _norm_range(a, b, n):
    if a < 0:
        a = max(0, n + a)
    if b < 0:
        b = max(0, n + b)
    return min(a, n), min(b, n)


STRING_METHODS = {
    "charCodeAt": lambda t, a, i: (
        float(ord(t[int(to_num(_arg(a, 0, 0.0)))]))
        if 0 <= int(to_num(_arg(a, 0, 0.0))) < len(t) else float("nan")),
    "codePointAt": lambda t, a, i: (
        float(ord(t[int(to_num(_arg(a, 0, 0.0)))]))
        if 0 <= int(to_num(_arg(a, 0, 0.0))) < len(t) else UNDEF),
    "charAt": lambda t, a, i: (
        t[int(to_num(_arg(a, 0, 0.0)))]
        if 0 <= int(to_num(_arg(a, 0, 0.0))) < len(t) else ""),
    "startsWith": lambda t, a, i: t.startswith(to_str(_arg(a, 0))),
    "endsWith": lambda t, a, i: t.endswith(to_str(_arg(a, 0))),
    "includes": lambda t, a, i: to_str(_arg(a, 0)) in t,
    "indexOf": lambda t, a, i: float(t.find(to_str(_arg(a, 0)))),
    "lastIndexOf": lambda t, a, i: float(t.rfind(to_str(_arg(a, 0)))),
    "toUpperCase": lambda t, a, i: t.upper(),
    "toLowerCase": lambda t, a, i: t.lower(),
    "trim": lambda t, a, i: t.strip(),
    "padStart": lambda t, a, i: t.rjust(int(to_num(_arg(a, 0, 0.0))),
                                        to_str(_arg(a, 1, " ")) or " "),
    "padEnd": lambda t, a, i: t.ljust(int(to_num(_arg(a, 0, 0.0))),
                                      to_str(_arg(a, 1, " ")) or " "),
    "repeat": lambda t, a, i: t * int(to_num(_arg(a, 0, 0.0))),
    "substring": lambda t, a, i: _str_slice(t, a, i),
    "slice": _str_slice,
    "split": _str_split,
    "replace": _str_replace,
    "replaceAll": _str_replace_all,
    "concat": lambda t, a, i: t + "".join(to_str(x) for x in a),
    "match": lambda t, a, i: (
        (lambda m: JSArray([m.group(0)] + [g if g is not None else UNDEF
                                           for g in m.groups()])
         if m else None)(_arg(a, 0).re.search(t))
        if isinstance(_arg(a, 0), JSRegExp) else None),
    "toString": lambda t, a, i: t,
}


# ---- numbers

def _num_tostring(this, args, interp):
    base = _arg(args, 0)
    if base is UNDEF:
        return _num_to_str(this)
    b = int(to_num(base))
    n = int(this)
    if n == 0:
        return "0"
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg = n < 0
    n = abs(n)
    out = []
    while n:
        out.append(digits[n % b])
        n //= b
    return ("-" if neg else "") + "".join(reversed(out))


NUMBER_METHODS = {
    "toFixed": lambda t, a, i: f"{t:.{int(to_num(_arg(a, 0, 0.0)))}f}",
    "toString": _num_tostring,
    "valueOf": lambda t, a, i: t,
}

BOOL_METHODS = {
    "toString": lambda t, a, i: "true" if t else "false",
    "valueOf": lambda t, a, i: t,
}


# ---- arrays

def _arr_sort(this, args, interp):
    cmp = _arg(args, 0)
    if cmp is UNDEF:
        this.elems.sort(key=to_str)
    else:
        import functools
        this.elems.sort(key=functools.cmp_to_key(
            lambda x, y: (lambda r: -1 if r < 0 else (1 if r > 0 else 0))(
                to_num(interp.call(cmp, [x, y])))))
    return this


def _arr_splice(this, args, interp):
    start = int(to_num(_arg(args, 0, 0.0)))
    n = len(this.elems)
    if start < 0:
        start = max(0, n + start)
    dc = _arg(args, 1)
    delete_count = n - start if dc is UNDEF else int(to_num(dc))
    removed = this.elems[start:start + delete_count]
    this.elems[start:start + delete_count] = list(args[2:])
    return JSArray(removed)


ARRAY_METHODS = {
    "push": lambda t, a, i: (t.elems.extend(a), float(len(t.elems)))[1],
    "pop": lambda t, a, i: t.elems.pop() if t.elems else UNDEF,
    "shift": lambda t, a, i: t.elems.pop(0) if t.elems else UNDEF,
    "unshift": lambda t, a, i: (t.elems.__setitem__(
        slice(0, 0), list(a)), float(len(t.elems)))[1],
    "slice": lambda t, a, i: JSArray(t.elems[slice(*_norm_range(
        int(to_num(_arg(a, 0, 0.0))),
        len(t.elems) if _arg(a, 1) is UNDEF else int(to_num(_arg(a, 1))),
        len(t.elems)))]),
    "splice": _arr_splice,
    "join": lambda t, a, i: to_str(_arg(a, 0, ",")).join(
        "" if (e is UNDEF or e is None) else to_str(e) for e in t.elems),
    "toString": lambda t, a, i: ",".join(
        "" if (e is UNDEF or e is None) else to_str(e) for e in t.elems),
    "indexOf": lambda t, a, i: float(next(
        (j for j, e in enumerate(t.elems)
         if strict_eq(e, _arg(a, 0))), -1)),
    "includes": lambda t, a, i: any(
        strict_eq(e, _arg(a, 0)) for e in t.elems),
    "find": lambda t, a, i: next(
        (e for j, e in enumerate(t.elems)
         if truthy(i.call(_arg(a, 0), [e, float(j), t]))), UNDEF),
    "findIndex": lambda t, a, i: float(next(
        (j for j, e in enumerate(t.elems)
         if truthy(i.call(_arg(a, 0), [e, float(j), t]))), -1)),
    "map": lambda t, a, i: JSArray([
        i.call(_arg(a, 0), [e, float(j), t])
        for j, e in enumerate(t.elems)]),
    "filter": lambda t, a, i: JSArray([
        e for j, e in enumerate(t.elems)
        if truthy(i.call(_arg(a, 0), [e, float(j), t]))]),
    "forEach": lambda t, a, i: ([
        i.call(_arg(a, 0), [e, float(j), t])
        for j, e in enumerate(list(t.elems))], UNDEF)[1],
    "some": lambda t, a, i: any(
        truthy(i.call(_arg(a, 0), [e, float(j), t]))
        for j, e in enumerate(t.elems)),
    "every": lambda t, a, i: all(
        truthy(i.call(_arg(a, 0), [e, float(j), t]))
        for j, e in enumerate(t.elems)),
    "reduce": lambda t, a, i: _arr_reduce(t, a, i),
    "concat": lambda t, a, i: JSArray(list(t.elems) + [
        x for arg in a
        for x in (arg.elems if isinstance(arg, JSArray) else [arg])]),
    "reverse": lambda t, a, i: (t.elems.reverse(), t)[1],
    "fill": lambda t, a, i: (t.elems.__setitem__(
        slice(None), [_arg(a, 0)] * len(t.elems)), t)[1],
    "sort": _arr_sort,
    "flat": lambda t, a, i: JSArray([
        x for e in t.elems
        for x in (e.elems if isinstance(e, JSArray) else [e])]),
    "keys": lambda t, a, i: JSArray([float(j)
                                     for j in range(len(t.elems))]),
    "entries": lambda t, a, i: JSArray([
        JSArray([float(j), e]) for j, e in enumerate(t.elems)]),
}


def _arr_reduce(t, a, i):
    fn = _arg(a, 0)
    acc = _arg(a, 1)
    start = 0
    if acc is UNDEF:
        if not t.elems:
            raise JSThrow(make_error("TypeError",
                                     "reduce of empty array"))
        acc = t.elems[0]
        start = 1
    for j in range(start, len(t.elems)):
        acc = i.call(fn, [acc, t.elems[j], float(j), t])
    return acc


# ---- Map / Set

MAP_METHODS = {
    "get": lambda t, a, i: t.get(_map_key(_arg(a, 0)), UNDEF),
    "set": lambda t, a, i: (t.__setitem__(
        _map_key(_arg(a, 0)), _arg(a, 1)), t)[1],
    "has": lambda t, a, i: _map_key(_arg(a, 0)) in t,
    "delete": lambda t, a, i: t.pop(_map_key(_arg(a, 0)), None) is not None,
    "clear": lambda t, a, i: (t.clear(), UNDEF)[1],
    "keys": lambda t, a, i: JSArray(list(t.keys())),
    "values": lambda t, a, i: JSArray(list(t.values())),
    "entries": lambda t, a, i: JSArray([
        JSArray([k, v]) for k, v in t.items()]),
    "forEach": lambda t, a, i: ([
        i.call(_arg(a, 0), [v, k, t]) for k, v in list(t.items())],
        UNDEF)[1],
}


def _map_key(k):
    """SameValueZero-ish hashable key."""
    if isinstance(k, float) and k == int(k):
        return k
    if isinstance(k, (str, float, bool, int)) or k is None or k is UNDEF:
        return k
    return id(k)


SET_METHODS = {
    "add": lambda t, a, i: (t.add(_map_key(_arg(a, 0))), t)[1],
    "has": lambda t, a, i: _map_key(_arg(a, 0)) in t,
    "delete": lambda t, a, i: (
        t.discard(_map_key(_arg(a, 0))), UNDEF)[1],
    "clear": lambda t, a, i: (t.clear(), UNDEF)[1],
    "forEach": lambda t, a, i: ([
        i.call(_arg(a, 0), [v, v, t]) for v in list(t)], UNDEF)[1],
}


# ---- typed arrays / DataView

def _typed_set(this, args, interp):
    src = _arg(args, 0)
    off = int(to_num(_arg(args, 1, 0.0)))
    vals = interp.js_iter(src)
    for j, v in enumerate(vals):
        this.set_index(off + j, to_num(v))
    return UNDEF


TYPED_METHODS = {
    "set": _typed_set,
    "subarray": lambda t, a, i: JSTypedArray(
        t.kind, t.buffer,
        t.offset + int(to_num(_arg(a, 0, 0.0))) * t.itemsize,
        (t.length if _arg(a, 1) is UNDEF else int(to_num(_arg(a, 1))))
        - int(to_num(_arg(a, 0, 0.0)))),
    "slice": lambda t, a, i: _typed_slice(t, a),
    "fill": lambda t, a, i: ([t.set_index(j, to_num(_arg(a, 0, 0.0)))
                              for j in range(t.length)], t)[1],
}


def _typed_slice(t, a):
    lo = int(to_num(_arg(a, 0, 0.0)))
    hi = t.length if _arg(a, 1) is UNDEF else int(to_num(_arg(a, 1)))
    lo, hi = _norm_range(lo, hi, t.length)
    out = JSTypedArray(t.kind, JSArrayBuffer(
        bytearray((hi - lo) * t.itemsize)))
    for j in range(hi - lo):
        out.set_index(j, t.get(lo + j))
    return out


def _dv_get(fmt, size, signed_default=False):
    def get(this, args, interp):
        off = int(to_num(_arg(args, 0, 0.0)))
        little = truthy(_arg(args, 1, False))
        endian = "<" if little else ">"
        return float(_struct.unpack_from(
            endian + fmt, this.buffer.data, this.offset + off)[0])
    return get


def _dv_set(fmt, size):
    def setter(this, args, interp):
        off = int(to_num(_arg(args, 0, 0.0)))
        val = to_num(_arg(args, 1, 0.0))
        little = truthy(_arg(args, 2, False))
        endian = "<" if little else ">"
        if fmt in ("B", "H", "I"):
            val = int(val) & ((1 << (8 * size)) - 1)
        elif fmt in ("b", "h", "i"):
            val = int(val)
        _struct.pack_into(endian + fmt, this.buffer.data,
                          this.offset + off, val)
        return UNDEF
    return setter


DATAVIEW_METHODS = {
    "getUint8": _dv_get("B", 1),
    "getInt8": _dv_get("b", 1),
    "getUint16": _dv_get("H", 2),
    "getInt16": _dv_get("h", 2),
    "getUint32": _dv_get("I", 4),
    "getInt32": _dv_get("i", 4),
    "getFloat32": _dv_get("f", 4),
    "getFloat64": _dv_get("d", 8),
    "setUint8": _dv_set("B", 1),
    "setUint16": _dv_set("H", 2),
    "setUint32": _dv_set("I", 4),
    "setInt16": _dv_set("h", 2),
    "setFloat32": _dv_set("f", 4),
}


# ---- promises

def _promise_then(this, args, interp):
    on_ok, on_err = _arg(args, 0), _arg(args, 1)
    out = JSPromise(interp)

    def ok(v):
        if on_ok is UNDEF or on_ok is None:
            out.resolve(v)
            return
        try:
            out.resolve(interp.call(on_ok, [v]))
        except JSThrow as ex:
            out.reject(ex.value)

    def err(v):
        if on_err is UNDEF or on_err is None:
            out.reject(v)
            return
        try:
            out.resolve(interp.call(on_err, [v]))
        except JSThrow as ex:
            out.reject(ex.value)

    this.then_native(ok, err)
    return out


PROMISE_METHODS = {
    "then": _promise_then,
    "catch": lambda t, a, i: _promise_then(t, [UNDEF, _arg(a, 0)], i),
    "finally": lambda t, a, i: _promise_then(
        t, [_arg(a, 0), _arg(a, 0)], i),
}


REGEX_METHODS = {
    "test": lambda t, a, i: t.re.search(to_str(_arg(a, 0))) is not None,
    "exec": lambda t, a, i: (
        (lambda m: JSArray([m.group(0)] + [
            g if g is not None else UNDEF for g in m.groups()])
         if m else None)(t.re.search(to_str(_arg(a, 0))))),
}


# ============================================================== stdlib

def install_stdlib(interp: Interp) -> None:
    g = interp.globals

    def nfg(name, fn):
        g.declare(name, _nf(fn, name))

    g.declare("undefined", UNDEF)
    g.declare("NaN", float("nan"))
    g.declare("Infinity", float("inf"))
    g.declare("globalThis", JSObject())

    # console
    logs: List[str] = []

    def _log(this, args, i):
        logs.append(" ".join(to_str(a) for a in args))
        return UNDEF

    console = JSObject({
        "log": _nf(_log, "log"), "warn": _nf(_log, "warn"),
        "error": _nf(_log, "error"), "info": _nf(_log, "info"),
        "debug": _nf(_log, "debug"),
    })
    g.declare("console", console)
    interp.console_lines = logs

    # Math
    def _m1(f):
        return lambda t, a, i: float(f(to_num(_arg(a, 0, float("nan")))))

    math_obj = JSObject({
        "abs": _nf(_m1(abs)), "floor": _nf(_m1(_math.floor)),
        "ceil": _nf(_m1(_math.ceil)),
        "round": _nf(lambda t, a, i: float(
            _math.floor(to_num(_arg(a, 0, 0.0)) + 0.5))),
        "sqrt": _nf(_m1(_math.sqrt)), "sign": _nf(_m1(
            lambda x: (x > 0) - (x < 0))),
        "trunc": _nf(_m1(_math.trunc)),
        "log2": _nf(_m1(_math.log2)), "log": _nf(_m1(_math.log)),
        "sin": _nf(_m1(_math.sin)), "cos": _nf(_m1(_math.cos)),
        "atan2": _nf(lambda t, a, i: _math.atan2(
            to_num(_arg(a, 0)), to_num(_arg(a, 1)))),
        "hypot": _nf(lambda t, a, i: _math.hypot(
            *[to_num(x) for x in a])),
        "pow": _nf(lambda t, a, i: to_num(_arg(a, 0))
                   ** to_num(_arg(a, 1))),
        "min": _nf(lambda t, a, i: min(
            (to_num(x) for x in a), default=float("inf"))),
        "max": _nf(lambda t, a, i: max(
            (to_num(x) for x in a), default=float("-inf"))),
        "random": _nf(lambda t, a, i: 0.42),   # deterministic for tests
        "PI": _math.pi, "E": _math.e,
    })
    g.declare("Math", math_obj)

    # JSON
    def js_to_py(v):
        if isinstance(v, JSArray):
            return [js_to_py(x) for x in v.elems]
        if isinstance(v, JSObject):
            return {k: js_to_py(x) for k, x in v.props.items()
                    if not isinstance(
                        x, (JSFunction, NativeFunction, BoundMethod))}
        if v is UNDEF:
            return None
        if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
            return int(v)
        return v

    def py_to_js(v):
        if isinstance(v, dict):
            return JSObject({k: py_to_js(x) for k, x in v.items()})
        if isinstance(v, (list, tuple)):
            return JSArray([py_to_js(x) for x in v])
        if v is None:
            return None
        if isinstance(v, bool):
            return v
        if isinstance(v, (int, float)):
            return float(v)
        return v

    def _stringify(this, args, i):
        indent = _arg(args, 2)
        kw = {}
        if indent is not UNDEF:
            kw["indent"] = int(to_num(indent))
        return _json.dumps(js_to_py(_arg(args, 0)), **kw)

    json_obj = JSObject({
        "stringify": _nf(_stringify, "stringify"),
        "parse": _nf(lambda t, a, i: py_to_js(
            _json.loads(to_str(_arg(a, 0)))), "parse"),
    })
    g.declare("JSON", json_obj)
    interp.py_to_js = py_to_js
    interp.js_to_py = js_to_py

    # Object
    obj_ns = JSObject({
        "keys": _nf(lambda t, a, i: JSArray(
            list(interp.enum_keys(_arg(a, 0))))),
        "values": _nf(lambda t, a, i: JSArray([
            interp.get_prop(_arg(a, 0), k)
            for k in interp.enum_keys(_arg(a, 0))])),
        "entries": _nf(lambda t, a, i: JSArray([
            JSArray([k, interp.get_prop(_arg(a, 0), k)])
            for k in interp.enum_keys(_arg(a, 0))])),
        "assign": _nf(_object_assign),
        "freeze": _nf(lambda t, a, i: _arg(a, 0)),
    })
    g.declare("Object", obj_ns)

    # Array
    def _array_ctor(this, args, i):
        if len(args) == 1 and isinstance(args[0], float):
            return JSArray([UNDEF] * int(args[0]))
        return JSArray(list(args))

    def _array_from(this, args, i):
        src = _arg(args, 0)
        fn = _arg(args, 1)
        if isinstance(src, JSObject) and "length" in src.props:
            items = [UNDEF] * int(to_num(src.props["length"]))
        else:
            items = list(i.js_iter(src))
        if fn is not UNDEF:
            items = [i.call(fn, [x, float(j)])
                     for j, x in enumerate(items)]
        return JSArray(items)

    arr_ctor = _nf(_array_ctor, "Array")
    g.declare("Array", arr_ctor)
    # statics via host-attr lookup on NativeFunction
    arr_ctor.isArray = _nf(
        lambda t, a, i: isinstance(_arg(a, 0), JSArray), "isArray")
    arr_ctor.from_ = None  # placeholder (JS name "from" set below)
    setattr(arr_ctor, "from", _nf(_array_from, "from"))

    # String / Number / parse*
    str_ctor = _nf(lambda t, a, i: to_str(_arg(a, 0, "")), "String")
    str_ctor.fromCharCode = _nf(lambda t, a, i: "".join(
        chr(int(to_num(x))) for x in a), "fromCharCode")
    g.declare("String", str_ctor)

    num_ctor = _nf(lambda t, a, i: to_num(_arg(a, 0, 0.0)), "Number")
    num_ctor.isInteger = _nf(lambda t, a, i: isinstance(
        _arg(a, 0), float) and _arg(a, 0) == int(_arg(a, 0)))
    num_ctor.isFinite = _nf(lambda t, a, i: isinstance(
        _arg(a, 0), float) and _math.isfinite(_arg(a, 0)))
    num_ctor.parseFloat = _nf(lambda t, a, i: to_num(_arg(a, 0)))
    g.declare("Number", num_ctor)
    g.declare("Boolean", _nf(lambda t, a, i: truthy(_arg(a, 0))))

    def _parse_int(this, args, i):
        s = to_str(_arg(args, 0)).strip()
        base = _arg(args, 1)
        b = 10 if base is UNDEF else int(to_num(base))
        m = _re.match(r"[+-]?(0[xX][0-9a-fA-F]+|[0-9a-zA-Z]*)", s)
        try:
            return float(int(m.group(0), 16 if s[:2].lower() == "0x"
                             else b))
        except (ValueError, IndexError):
            return float("nan")

    nfg("parseInt", _parse_int)
    nfg("parseFloat", lambda t, a, i: to_num(_arg(a, 0)))
    nfg("isNaN", lambda t, a, i: to_num(_arg(a, 0)) != to_num(_arg(a, 0)))
    nfg("isFinite", lambda t, a, i: _math.isfinite(to_num(_arg(a, 0))))

    # Error constructors
    for ename in ("Error", "TypeError", "RangeError", "SyntaxError",
                  "ReferenceError"):
        def _mk_err(this, args, i, _n=ename):
            return make_error(_n, to_str(_arg(args, 0, "")))
        nfg(ename, _mk_err)

    # collections
    def _map_ctor(this, args, i):
        m = {}
        src = _arg(args, 0)
        if src is not UNDEF and src is not None:
            for pair in i.js_iter(src):
                k, v = i.js_iter(pair)[:2]
                m[_map_key(k)] = v
        return m

    def _set_ctor(this, args, i):
        s = set()
        src = _arg(args, 0)
        if src is not UNDEF and src is not None:
            for x in i.js_iter(src):
                s.add(_map_key(x))
        return s

    nfg("Map", _map_ctor)
    nfg("Set", _set_ctor)

    # typed arrays
    def _typed_ctor(kind):
        def ctor(this, args, i):
            a0 = _arg(args, 0)
            fmt, size = _DTYPES[kind]
            if isinstance(a0, float):
                return JSTypedArray(kind, JSArrayBuffer(
                    bytearray(int(a0) * size)))
            if isinstance(a0, JSArrayBuffer):
                off = int(to_num(_arg(args, 1, 0.0)))
                ln = _arg(args, 2)
                return JSTypedArray(
                    kind, a0, off,
                    None if ln is UNDEF else int(to_num(ln)))
            if a0 is UNDEF:
                return JSTypedArray(kind, JSArrayBuffer(bytearray()))
            items = [to_num(x) for x in i.js_iter(a0)]
            out = JSTypedArray(kind, JSArrayBuffer(
                bytearray(len(items) * size)))
            for j, v in enumerate(items):
                out.set_index(j, v)
            return out
        return ctor

    for name, kind in (("Uint8Array", "u1"), ("Int16Array", "i2"),
                       ("Float32Array", "f4")):
        ctor = _nf(_typed_ctor(kind), name)
        ctor.BYTES_PER_ELEMENT = float(_DTYPES[kind][1])
        g.declare(name, ctor)
    nfg("ArrayBuffer", lambda t, a, i: JSArrayBuffer(
        bytearray(int(to_num(_arg(a, 0, 0.0))))))
    nfg("DataView", lambda t, a, i: JSDataView(
        _arg(a, 0),
        int(to_num(_arg(a, 1, 0.0))),
        None if _arg(a, 2) is UNDEF else int(to_num(_arg(a, 2)))))

    # Promise
    def _promise_ctor(this, args, i):
        p = JSPromise(i)
        executor = _arg(args, 0)
        if executor is not UNDEF:
            res = _nf(lambda t2, a2, i2: (p.resolve(_arg(a2, 0)),
                                          UNDEF)[1])
            rej = _nf(lambda t2, a2, i2: (p.reject(_arg(a2, 0)),
                                          UNDEF)[1])
            try:
                i.call(executor, [res, rej])
            except JSThrow as ex:
                p.reject(ex.value)
        return p

    promise_ctor = _nf(_promise_ctor, "Promise")

    def _promise_resolve(this, args, i):
        p = JSPromise(i)
        p.resolve(_arg(args, 0))
        return p

    def _promise_all(this, args, i):
        items = list(i.js_iter(_arg(args, 0)))
        out = JSPromise(i)
        results = [UNDEF] * len(items)
        remaining = [len(items)]
        if not items:
            out.resolve(JSArray([]))
            return out
        for j, it in enumerate(items):
            if isinstance(it, JSPromise):
                def ok(v, _j=j):
                    results[_j] = v
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        out.resolve(JSArray(results))
                it.then_native(ok, out.reject)
            else:
                results[j] = it
                remaining[0] -= 1
        if remaining[0] == 0:
            out.resolve(JSArray(results))
        return out

    promise_ctor.resolve = _nf(_promise_resolve, "resolve")
    promise_ctor.all = _nf(_promise_all, "all")
    promise_ctor.reject = _nf(
        lambda t, a, i: (lambda p: (p.reject(_arg(a, 0)), p)[1])(
            JSPromise(i)), "reject")
    g.declare("Promise", promise_ctor)

    # timers (manually fired from tests via interp.fire_timers)
    def _set_timer(repeat):
        def fn(this, args, i):
            cb = _arg(args, 0)
            delay = to_num(_arg(args, 1, 0.0))
            tid = i._timer_id
            i._timer_id += 1
            i.timer_map[tid] = (cb, delay, repeat)
            return float(tid)
        return fn

    nfg("setTimeout", _set_timer(False))
    nfg("setInterval", _set_timer(True))
    nfg("clearTimeout", lambda t, a, i: (
        i.timer_map.pop(int(to_num(_arg(a, 0, -1.0))), None), UNDEF)[1])
    nfg("clearInterval", lambda t, a, i: (
        i.timer_map.pop(int(to_num(_arg(a, 0, -1.0))), None), UNDEF)[1])

    # base64 (latin-1 binary strings, like the browser)
    import base64 as _b64
    nfg("btoa", lambda t, a, i: _b64.b64encode(
        to_str(_arg(a, 0)).encode("latin-1")).decode("ascii"))
    nfg("atob", lambda t, a, i: _b64.b64decode(
        to_str(_arg(a, 0))).decode("latin-1"))

    # Date.now (tests control time via interp.now_ms)
    interp.now_ms = 1_000_000.0
    date_ctor = _nf(lambda t, a, i: JSObject(
        {"getTime": _nf(lambda t2, a2, i2: i.now_ms)}), "Date")
    date_ctor.now = _nf(lambda t, a, i: i.now_ms, "now")
    g.declare("Date", date_ctor)

    def _regexp_ctor(this, args, i):
        return JSRegExp(to_str(_arg(args, 0, "")),
                        to_str(_arg(args, 1, "")))

    nfg("RegExp", _regexp_ctor)


def _object_assign(this, args, interp):
    target = _arg(args, 0)
    for src in args[1:]:
        if isinstance(src, JSObject) and isinstance(target, JSObject):
            target.props.update(src.props)
        elif isinstance(src, JSObject):
            for k, v in src.props.items():
                interp.set_prop(target, k, v)
    return target
