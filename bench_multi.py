"""Multi-session encode benchmark (BASELINE config 5, single-chip slice).

Measures aggregate 1080p encode throughput with N desktop sessions on the
available devices, two ways:

  1. time-shared: each session runs its own pipelined solo encoder and the
     round-robin scheduler keeps the device queue full (round-1 mode);
  2. mesh-batched: every session's frame rides ONE sharded
     MeshStripeEncoder dispatch (the tpu_mesh product path) — on a
     multi-chip slice sessions are data-parallel over the "session" mesh
     axis; on one chip the batch amortizes per-dispatch overhead.

Plus the scaling-story series (ISSUE 14): a short swarm churn storm
(tools/swarm_run.py, device-free scheduler path) contributes
``sessions_per_chip``, ``fairness_jain_index``, and ``eviction_ms_p95``
so MULTICHIP_*.json tracks multi-tenant packing across PRs, not only raw
encoder throughput.

Prints ONE JSON line with the better aggregate as the headline value and
both breakdowns.
"""

from __future__ import annotations

import json
import sys
import time

N_SESSIONS = 4
W, H = 1920, 1080
WARMUP_FRAMES = 24
BENCH_FRAMES = 400           # across all sessions
MAX_SECONDS = 90.0


def bench_mesh() -> dict:
    """Mesh-batched aggregate: one sharded dispatch per tick for all N.

    The mesh geometry honors the full ``session:N,stripe:M`` form of the
    ``tpu_mesh`` setting (env ``SELKIES_TPU_MESH``) instead of
    hardcoding the stripe axis to 1 (ISSUE 15 satellite) — so this
    bench runs on real 2-D meshes: M > 1 stripe-shards every session's
    frame across chips on top of the session data-parallelism."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from selkies_tpu.parallel import MeshStripeEncoder, parse_mesh_spec

    devices = jax.devices()
    n_dev = len(devices)
    spec = os.environ.get("SELKIES_TPU_MESH", "") or f"session:{n_dev}"
    mesh = parse_mesh_spec(spec, devices)
    n_sess_ax = mesh.shape["session"]
    per_chip = max(1, N_SESSIONS // n_sess_ax)
    n_sessions = per_chip * n_sess_ax
    enc = MeshStripeEncoder(mesh, n_sessions, W, H)

    # device-resident scrolling batch: full damage every tick, no H2D cost,
    # same "scroll" content as the solo bench (noise would quadruple the
    # bitstream and measure the D2H link instead of the encoder)
    from selkies_tpu.capture.synthetic import SyntheticSource

    base = np.stack([
        np.pad(SyntheticSource(W, H, pattern="scroll", seed=i)._bg,
               ((0, enc.pad_h - H), (0, enc.pad_w - W), (0, 0)), mode="edge")
        for i in range(n_sessions)])
    batch = jnp.asarray(base)
    roll = jax.jit(lambda b: jnp.roll(b, -8, axis=1))

    for _ in range(3):
        batch = roll(batch)
        enc.encode_frames(batch)

    frames = 0
    total_bytes = 0
    d2h_bytes = 0
    fetch_ms = []
    dispatch_ms = []
    pack_ms = []
    ticks = max(1, BENCH_FRAMES // n_sessions)
    from collections import deque

    def dispatch_timed(b):
        # per-shard stage truth (ISSUE 13 satellite): the mesh path's
        # dispatch/fetch/pack decomposition, same stage names as the
        # solo flight recorder so MULTICHIP and BENCH rows compare
        t0 = time.perf_counter()
        p = enc.dispatch(b)
        dispatch_ms.append((time.perf_counter() - t0) * 1000.0)
        return p

    def harvest_timed(p):
        # per-shard fetch truth (ISSUE 1 satellite — MULTICHIP files
        # carried no transfer numbers): wall time until the dispatched
        # tick's prefix is host-readable, and its aggregate byte size
        nonlocal d2h_bytes
        t0 = time.perf_counter()
        p.prefix.block_until_ready()
        t1 = time.perf_counter()
        fetch_ms.append((t1 - t0) * 1000.0)
        d2h_bytes += int(np.prod(p.prefix.shape)) * p.prefix.dtype.itemsize
        out = enc.harvest(p)
        pack_ms.append((time.perf_counter() - t1) * 1000.0)
        return out

    start = time.perf_counter()
    pending = deque()
    for _ in range(ticks):
        if time.perf_counter() - start > MAX_SECONDS / 2:
            break
        batch = roll(batch)
        pending.append(dispatch_timed(batch))  # overlap: 2 steps in flight
        if len(pending) >= 3:
            out, _bytes = harvest_timed(pending.popleft())
            frames += sum(1 for s in out if s)
            total_bytes += sum(len(st.jpeg) for s in out for st in s)
    while pending:
        out, _bytes = harvest_timed(pending.popleft())
        frames += sum(1 for s in out if s)
        total_bytes += sum(len(st.jpeg) for s in out for st in s)
    elapsed = time.perf_counter() - start
    fps = frames / elapsed if elapsed > 0 else 0.0
    fetch_sorted = sorted(fetch_ms) or [0.0]

    def p(vals, q):
        s = sorted(vals) or [0.0]
        return round(s[min(len(s) - 1, int(len(s) * q / 100))], 2)

    return {
        # per-shard stage breakdown (tick-granular: one dispatch covers
        # every shard's sessions, so per-frame cost is value/n_sessions)
        "mesh_stage_breakdown": {
            "dispatch": {"p50_ms": p(dispatch_ms, 50),
                         "p95_ms": p(dispatch_ms, 95)},
            "fetch_wait": {"p50_ms": p(fetch_ms, 50),
                           "p95_ms": p(fetch_ms, 95)},
            "pack": {"p50_ms": p(pack_ms, 50),
                     "p95_ms": p(pack_ms, 95)},
        },
        "mesh_aggregate_fps": round(fps, 2),
        "mesh_sessions": n_sessions,
        # the devices the mesh actually spans (a SELKIES_TPU_MESH spec
        # may use fewer than the host has) — per-chip derivations from
        # MULTICHIP_*.json must divide by this, not the host inventory
        "mesh_devices": int(mesh.devices.size),
        "mesh_spec": (f"session:{n_sess_ax},"
                      f"stripe:{mesh.shape['stripe']}"),
        "mesh_frames": frames,
        "mesh_mean_frame_kb": round(total_bytes / max(frames, 1) / 1024, 1),
        "mesh_fetch_ms_p50": round(
            fetch_sorted[len(fetch_sorted) // 2], 2),
        "mesh_fetch_ms_p95": round(
            fetch_sorted[min(len(fetch_sorted) - 1,
                             int(len(fetch_sorted) * 0.95))], 2),
        "mesh_d2h_bytes_per_frame": round(d2h_bytes / max(frames, 1)),
    }


def sfe_drive(enc, frames_target: int, budget_s: float) -> dict:
    """Shared SFE drive discipline for one single-session
    ``MeshH264Encoder`` (used by ``bench_sfe_scaling`` here AND
    bench.py's ``_bench_4k_sfe``, so the two reported series can never
    diverge): device-resident scrolling source, IDR + steady-state
    warmup ticks, then a 2-deep dispatch/harvest window. Returns
    fps/frames plus the harvest stage samples."""
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    from selkies_tpu.capture.synthetic import SyntheticSource

    assert enc.n_sessions == 1
    base = np.pad(
        SyntheticSource(enc.width, enc.height, pattern="scroll")._bg,
        ((0, enc.pad_h - enc.height), (0, enc.pad_w - enc.width), (0, 0)),
        mode="edge")
    batch = jax.device_put(jnp.asarray(base[None]), enc._frame_sharding)
    roll = jax.jit(lambda b: jnp.roll(b, -8, axis=1))
    enc.encode_frames(batch)          # IDR tick (mixed-program compile)
    batch = roll(batch)
    enc.encode_frames(batch)          # steady-state P compile

    frames = 0
    concat_ms, fetch_ms = [], []
    pending = deque()
    start = time.perf_counter()

    def harvest_one():
        nonlocal frames
        enc.harvest(pending.popleft())
        frames += 1
        st = enc.last_harvest_stages or {}
        concat_ms.append(st.get("concat_ms", 0.0))
        fetch_ms.append(st.get("fetch_ms", 0.0))

    while frames < frames_target and \
            time.perf_counter() - start < budget_s:
        batch = roll(batch)
        pending.append(enc.dispatch(batch))  # >=2 sharded batches in flight
        if len(pending) >= 2:
            harvest_one()
    while pending:
        harvest_one()
    elapsed = time.perf_counter() - start
    from selkies_tpu.parallel.coordinator import _p50
    return {
        "fps": round(frames / elapsed, 2) if elapsed > 0 else 0.0,
        "frames": frames,
        "concat_ms_p50": _p50(concat_ms, 2),
        "fetch_ms_p50": _p50(fetch_ms, 2),
    }


def bench_sfe_scaling(width: int = 3840, height: int = 2160,
                      shard_counts=(1, 2, 4), frames_target: int = 96,
                      budget_per_shard: float = MAX_SECONDS / 6) -> dict:
    """Split-frame encoding scaling (ISSUE 15 acceptance): ONE 4K H.264
    session's frames stripe-sharded across 1 / 2 / 4 chips
    (`MeshH264Encoder` over ``session:1,stripe:M``), identical content
    and drive discipline per shard count (2-deep dispatch/harvest
    window), so the fps series isolates the ICI shard speedup. The
    acceptance bar is >=1.7x at 2 shards over the 1-shard baseline with
    a near-linear trend to 4. (Geometry parameterized so the code path
    smoke-tests at toy sizes on CPU hosts.)"""
    import jax

    from selkies_tpu.parallel import parse_mesh_spec
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    devices = jax.devices()
    series = {}
    concat = {}
    for shards in shard_counts:
        if shards > len(devices):
            continue
        mesh = parse_mesh_spec(f"session:1,stripe:{shards}",
                               devices[:shards])
        d = sfe_drive(MeshH264Encoder(mesh, 1, width, height),
                      frames_target, budget_per_shard)
        series[str(shards)] = d["fps"]
        concat[str(shards)] = d["concat_ms_p50"]
    if not series:
        return {}
    out = {
        "sfe_scaling": series,
        "sfe_concat_ms_p50": concat,
        "fourk_sfe_fps": max(series.values()),
        "sfe_shards_best": max(
            (int(k) for k, v in series.items()
             if v == max(series.values())), default=1),
    }
    if "1" in series and "2" in series and series["1"] > 0:
        out["sfe_speedup_2shard"] = round(series["2"] / series["1"], 2)
    if "1" in series and "4" in series and series["1"] > 0:
        out["sfe_speedup_4shard"] = round(series["4"] / series["1"], 2)
    return out


def bench_swarm() -> dict:
    """Scheduler-plane churn metrics (docs/scaling.md): a bounded swarm
    storm through the real ws_handler with device-free lanes — measures
    packing, fairness, and eviction latency, not codec throughput (the
    mesh/solo sections above own that)."""
    import asyncio
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.swarm_run import swarm_run

    try:
        r = asyncio.run(swarm_run(
            n_clients=64, duration_s=6.0, seed=0, concurrency=48,
            slots_per_lane=8, max_lanes=4, encoder="fake",
            sick_slot=True))
    except Exception as e:
        return {"swarm_error": repr(e)}
    return {
        "sessions_per_chip": r["sessions_per_chip"],
        "fairness_jain_index": r["fairness_jain_index"],
        "eviction_ms_p95": r["eviction_ms_p95"],
        "swarm_clients": r["swarm_clients"],
        "swarm_sessions_peak": r["sessions_peak"],
        "swarm_frames": r["frames_delivered_total"],
        "swarm_migrations": r["migrations"],
        "swarm_leak_free": bool(r["alive"]),
    }


def main() -> None:
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    sessions = []
    for i in range(N_SESSIONS):
        base = JpegStripeEncoder(W, H)
        sessions.append((
            PipelinedJpegEncoder(base, depth=4, fetch_group=4),
            DeviceScrollSource(W, H, seed=i),
            base,
        ))

    def padded(base, frame):
        if frame.shape[0] == base.pad_h:
            return frame
        return jnp.pad(
            frame, ((0, base.pad_h - frame.shape[0]),
                    (0, base.pad_w - frame.shape[1]), (0, 0)), mode="edge")

    for i in range(WARMUP_FRAMES):
        enc, src, base = sessions[i % N_SESSIONS]
        enc.submit(padded(base, src.next_frame()))
        enc.poll()
    for enc, _, _ in sessions:
        enc.flush()

    done = 0
    total_bytes = 0
    submitted = 0
    start = time.perf_counter()
    while submitted < BENCH_FRAMES and \
            time.perf_counter() - start < MAX_SECONDS:
        enc, src, base = sessions[submitted % N_SESSIONS]
        enc.submit(padded(base, src.next_frame()))
        submitted += 1
        for _seq, stripes in enc.poll():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
    for enc, _, _ in sessions:
        for _seq, stripes in enc.flush():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
    elapsed = time.perf_counter() - start

    fps = done / elapsed if elapsed > 0 else 0.0
    try:
        mesh = bench_mesh()
    except Exception as e:          # e.g. a prod SELKIES_TPU_MESH spec
        mesh = {"mesh_aggregate_fps": 0.0,  # too big for this bench host
                "mesh_sessions": 0, "mesh_error": repr(e)}
    try:
        # ISSUE 15 acceptance series: fps vs SFE shard count at 4K
        sfe = bench_sfe_scaling()
    except Exception as e:          # the headline must survive a sub-bench
        sfe = {"sfe_error": repr(e)}
    # headline: the better mode, with per-session figures computed against
    # THAT mode's session count (mesh may batch more sessions on big slices)
    if mesh["mesh_aggregate_fps"] > fps:
        best, best_sessions = mesh["mesh_aggregate_fps"], mesh["mesh_sessions"]
        mode = "mesh"
    else:
        best, best_sessions = fps, N_SESSIONS
        mode = "solo"
    print(json.dumps({
        "metric": "tpuenc_jpeg_multisession_aggregate_fps",
        "value": round(best, 2),
        "unit": "fps",
        "mode": mode,
        "sessions": best_sessions,
        "per_session_fps": round(best / best_sessions, 2),
        "vs_baseline": round(best / (60.0 * best_sessions), 3),
        "solo_sessions": N_SESSIONS,
        "solo_aggregate_fps": round(fps, 2),
        "solo_frames": done,
        "solo_d2h_bytes_per_frame": round(
            sum(e.stats()["d2h_bytes_per_frame"] * max(e.stats()["frames"], 1)
                for e, _, _ in sessions)
            / max(sum(e.stats()["frames"] for e, _, _ in sessions), 1)),
        "elapsed_s": round(elapsed, 2),
        "mean_frame_kb": round(total_bytes / max(done, 1) / 1024, 1),
        **mesh,
        **sfe,
        **bench_swarm(),
    }))


if __name__ == "__main__":
    sys.exit(main())
