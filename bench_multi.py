"""Multi-session encode benchmark (BASELINE config 5, single-chip slice).

Measures aggregate 1080p encode throughput with N independent desktop
sessions time-sharing ONE chip — the realistic single-chip serving mode:
each session runs its own pipelined encoder (own damage state, own
bitstreams) and the round-robin scheduler keeps the device queue full.
Cross-chip scaling of the same step (sessions data-parallel, stripes
spatially sharded, psum rate feedback) lives in selkies_tpu.parallel and
is validated by __graft_entry__.dryrun_multichip on a virtual mesh; real
aggregate numbers on a v5e-8 slice are expected to scale with chips since
sessions are embarrassingly parallel across the "session" axis.

Prints ONE JSON line:
  {"metric": "tpuenc_jpeg_multisession_aggregate_fps", ...}
"""

from __future__ import annotations

import json
import sys
import time

N_SESSIONS = 4
W, H = 1920, 1080
WARMUP_FRAMES = 24
BENCH_FRAMES = 400           # across all sessions
MAX_SECONDS = 90.0


def main() -> None:
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    sessions = []
    for i in range(N_SESSIONS):
        base = JpegStripeEncoder(W, H)
        sessions.append((
            PipelinedJpegEncoder(base, depth=4, fetch_group=4),
            DeviceScrollSource(W, H, seed=i),
            base,
        ))

    def padded(base, frame):
        if frame.shape[0] == base.pad_h:
            return frame
        return jnp.pad(
            frame, ((0, base.pad_h - frame.shape[0]),
                    (0, base.pad_w - frame.shape[1]), (0, 0)), mode="edge")

    for i in range(WARMUP_FRAMES):
        enc, src, base = sessions[i % N_SESSIONS]
        enc.submit(padded(base, src.next_frame()))
        enc.poll()
    for enc, _, _ in sessions:
        enc.flush()

    done = 0
    total_bytes = 0
    submitted = 0
    start = time.perf_counter()
    while submitted < BENCH_FRAMES and \
            time.perf_counter() - start < MAX_SECONDS:
        enc, src, base = sessions[submitted % N_SESSIONS]
        enc.submit(padded(base, src.next_frame()))
        submitted += 1
        for _seq, stripes in enc.poll():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
    for enc, _, _ in sessions:
        for _seq, stripes in enc.flush():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
    elapsed = time.perf_counter() - start

    fps = done / elapsed if elapsed > 0 else 0.0
    print(json.dumps({
        "metric": "tpuenc_jpeg_multisession_aggregate_fps",
        "value": round(fps, 2),
        "unit": "fps",
        "sessions": N_SESSIONS,
        "per_session_fps": round(fps / N_SESSIONS, 2),
        "vs_baseline": round(fps / (60.0 * N_SESSIONS), 3),
        "frames": done,
        "elapsed_s": round(elapsed, 2),
        "mean_frame_kb": round(total_bytes / max(done, 1) / 1024, 1),
    }))


if __name__ == "__main__":
    sys.exit(main())
