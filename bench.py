"""Headline benchmark: 1080p streaming-encode throughput on one chip.

Mirrors the reference's headline claim — 60 fps @ 1920×1080 desktop encode
(reference docs/README.md:12, docs/design.md:11; BASELINE.md) — against the
tpuenc JPEG-stripe profile with device-side entropy coding, run through the
pipelined (dispatch/D2H-overlapped) encoder exactly as the streaming server
drives it: per frame, the damage/size metadata and the packed bitstream are
fetched to the host and assembled into per-stripe JPEGs.

Also measures the rest of the BASELINE matrix on the same chip:
  * p50/p95 glass-to-glass (capture handoff → stripes decodable on the
    client side of the wire) — the declared BASELINE latency metric;
  * tpuenc H.264 1080p (config 2) through the dense one-dispatch device
    encode + host CAVLC;
  * 4K JPEG single-chip (config 4's single-chip share; the cross-chip
    stripe-sharded path is validated by __graft_entry__.dryrun_multichip).

Frames come from a device-resident scrolling source (every stripe damaged
every frame — the no-shortcuts worst case for damage gating). On production
hosts capture feeds the chip over PCIe (~0.4 ms for a 6 MB 1080p frame); on
the tunneled dev chip this benchmark runs on, the same upload costs ~150 ms
(and D2H pays ~25-100 ms/RPC), which would measure the tunnel, not the
encoder — so the source materializes frames on device with a jitted roll.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "fps", "vs_baseline": N, ...}
vs_baseline is the ratio against the reference's 60 fps 1080p target.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_FPS = 60.0  # reference headline: 60 fps @ 1080p
W, H = 1920, 1080
WARMUP_FRAMES = 24
BENCH_FRAMES = 300
MAX_SECONDS = 90.0
PIPELINE_DEPTH = 12   # deep enough to hide ~100 ms tunneled-D2H latency
FETCH_GROUP = 4      # frames per D2H read (tunnel allows ~6 concurrent RPCs)


def _pipelined_jpeg_fps(width, height, frames, seconds, depth=PIPELINE_DEPTH,
                        fetch_group=FETCH_GROUP):
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    base = JpegStripeEncoder(width, height)
    src = DeviceScrollSource(width, height)
    enc = PipelinedJpegEncoder(base, depth=depth, fetch_group=fetch_group)

    def padded(frame):
        if frame.shape[0] == base.pad_h and frame.shape[1] == base.pad_w:
            return frame
        return jnp.pad(
            frame,
            ((0, base.pad_h - frame.shape[0]),
             (0, base.pad_w - frame.shape[1]), (0, 0)),
            mode="edge")

    for _ in range(WARMUP_FRAMES):  # includes compile
        enc.submit(padded(src.next_frame()))
        for _ in enc.poll():
            pass
    for _ in enc.flush():
        pass

    done = 0
    total_bytes = 0
    start = time.perf_counter()
    submitted = 0
    while submitted < frames:
        enc.submit(padded(src.next_frame()))
        submitted += 1
        for _seq, stripes in enc.poll():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
        if time.perf_counter() - start > seconds:
            break
    for _seq, stripes in enc.flush():
        done += 1
        total_bytes += sum(len(s.jpeg) for s in stripes)
    elapsed = time.perf_counter() - start
    fps = done / elapsed if elapsed > 0 else 0.0
    return fps, done, elapsed, total_bytes, enc.stats()


def _h264_d2h_baseline() -> dict:
    """Short host-entropy-path stint: the sparse-level-buffer transfer
    the device-CAVLC tier replaces — so the reduction acceptance
    criterion is measured against a live number, not BENCH history."""
    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    B = 12
    enc = H264StripeEncoder(W, H, entropy="host")
    pipe = PipelinedH264Encoder(enc, depth=3 * B, batch=B)
    src = DeviceScrollSource(W, enc.pad_h)
    enc.encode_frame(src.next_frame())
    enc.encode_frame(src.next_frame())
    for _ in range(2):                       # compile + prefix settle
        pipe.submit_batch(src.next_batch(B))
        for _ in pipe.poll(flush_partial=False):
            pass
    for _ in pipe.flush():
        pass
    pipe.d2h_bytes_total = 0
    pipe.frames_completed = 0
    enc.d2h_refetch_bytes_total = 0
    enc.host_entropy_ms_total = 0.0
    deadline = time.perf_counter() + MAX_SECONDS / 8
    while pipe.frames_completed < 60 and time.perf_counter() < deadline:
        pipe.submit_batch(src.next_batch(B))
        for _ in pipe.poll(flush_partial=False):
            pass
    for _ in pipe.flush():
        pass
    st = pipe.stats()
    return {
        "h264_d2h_bytes_per_frame_host_baseline":
            round(st["d2h_bytes_per_frame"]),
        "h264_host_entropy_ms_per_frame_baseline":
            round(st["host_entropy_ms_per_frame"], 2),
    }


def bench_h264() -> dict:
    """Config 2: tpuenc H.264 1080p via the dense one-dispatch device
    encode (ME/transform/quant/recon + on-device CAVLC entropy packing
    — encoder/device_cavlc.py; the host only glues slice headers),
    pipelined with grouped D2H reads."""
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    BATCH = 12
    enc = H264StripeEncoder(W, H)
    # the P-frame reference chain rides a lax.scan inside ONE device
    # program per batch (dev.encode_frame_p_batch_rgb) and the source
    # emits the whole batch in one program, so the tunnel's fixed
    # per-dispatch RPC cost is paid ~2x per 12 frames instead of ~4x
    # per frame (round 2: 12 fps; with batching: ~46 fps same chip)
    pipe = PipelinedH264Encoder(enc, depth=3 * BATCH, batch=BATCH)
    src = DeviceScrollSource(W, enc.pad_h)

    for _ in range(2):
        enc.encode_frame(src.next_frame())  # IDR + single-frame compile
    for _ in range(2):                      # batch-program compile
        pipe.submit_batch(src.next_batch(BATCH))
    for _ in pipe.flush():
        pass
    pipe.d2h_bytes_total = 0                 # exclude warmup/IDR transfers
    pipe.frames_completed = 0
    enc.d2h_refetch_bytes_total = 0
    enc.host_entropy_ms_total = 0.0
    done, nb = 0, 0
    start = time.perf_counter()
    while done < 300 and time.perf_counter() - start < MAX_SECONDS / 3:
        pipe.submit_batch(src.next_batch(BATCH))
        for _seq, out in pipe.poll(flush_partial=False):
            done += 1
            nb += sum(len(s.annexb) for s in out)
    for _seq, out in pipe.flush():
        done += 1
        nb += sum(len(s.annexb) for s in out)
    elapsed = time.perf_counter() - start
    fps = done / elapsed if elapsed > 0 else 0.0

    # Device-side truth (VERDICT r3 item 1): chain-slope over the
    # already-compiled batched program. Chained dispatches + ONE tiny
    # fetch; the difference between 4-deep and 2-deep chains cancels
    # the fetch round trip, leaving (dispatch_rpc + B*frame)*2 — so
    # frame_ms here is a slight OVERestimate (includes ~1/B of the
    # dispatch RPC), i.e. device_fps is conservative.
    import numpy as _np

    def chain_ms(n_chains, reps=3):
        best = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n_chains):
                pends = enc.dispatch_batch(src.next_batch(BATCH),
                                           fetch=False)
            _np.asarray(pends[-1].batch_heads[0, :64])
            best = min(best, (time.perf_counter() - t0) * 1000.0)
        return best

    t2, t4 = chain_ms(2), chain_ms(4)
    dev_ms = max(0.0, (t4 - t2) / (2 * BATCH))
    st = pipe.stats()
    out = {
        "h264_1080p_fps": round(fps, 2),
        "h264_batch": BATCH,
        "h264_entropy": enc.entropy,
        "h264_mean_frame_kb": round(nb / max(done, 1) / 1024, 1),
        # ISSUE 12: the dispatch/fetch-floor claim measured per round —
        # dispatch launch cost, host time blocked on D2H, and proof that
        # >=2 batches actually rode the device concurrently
        "dispatch_p50_ms": st.get("dispatch_p50_ms", 0.0),
        "fetch_wait_p50_ms": st.get("fetch_wait_p50_ms", 0.0),
        "inflight_batches_max": st.get("inflight_batches_max", 0),
        # ISSUE 1 satellites: the bottleneck claim measured, not inferred
        "h264_d2h_bytes_per_frame": round(st["d2h_bytes_per_frame"]),
        "h264_host_entropy_ms_per_frame":
            round(st["host_entropy_ms_per_frame"], 2),
        "h264_frames_dropped": st.get("frames_dropped", 0),
        "h264_entropy_errors": st.get("entropy_errors", 0),
        "h264_device_ms_per_frame": round(dev_ms, 2),
        "h264_device_fps": round(1000.0 / dev_ms, 1) if dev_ms > 0 else None,
        "h264_device_note": (
            "chain-slope of the one-dispatch batched program; cancels "
            "fetch+fixed costs, includes ~1/B of dispatch RPC "
            "(conservative). tools/h264_stages.py has the full method."),
        # the r05 bottleneck ("per-batch D2H read over tunneled
        # transport") is what the device-CAVLC tier attacks; report the
        # claim per measured mode instead of restating it unconditionally
        "h264_bottleneck": (
            "per-batch D2H read over tunneled transport"
            if enc.entropy == "host" else
            "per-batch D2H read, payload now bitstream-sized "
            "(device CAVLC; see h264_d2h_bytes_per_frame vs baseline)"),
    }
    try:
        out.update(_h264_d2h_baseline())
    except Exception as e:                   # baseline must not kill config 2
        out["h264_d2h_baseline_error"] = repr(e)
    return out


def _bench_4k_sfe(width=3840, height=2160, max_shards=4,
                  frames_target=120, seconds=MAX_SECONDS / 4) -> dict:
    """Split-frame encoding (ISSUE 15): ONE 4K frame's stripe bands
    sharded across the stripe mesh axis (`MeshH264Encoder`, shard-local
    device CAVLC, host slice concat), driven with a 2-deep
    dispatch/harvest window like the coordinator's SFE lanes — the
    drive discipline is bench_multi.sfe_drive, shared with the
    `sfe_scaling` series so the two can never diverge. On one chip this
    measures the mesh-path overhead floor; on a multi-chip slice
    `fourk_sfe_fps` should scale near-linearly with shard count."""
    import jax

    import bench_multi
    from selkies_tpu.parallel import parse_mesh_spec
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    devices = jax.devices()
    shards = min(len(devices), max_shards)
    mesh = parse_mesh_spec(f"session:1,stripe:{shards}", devices[:shards])
    enc = MeshH264Encoder(mesh, 1, width, height)
    d = bench_multi.sfe_drive(enc, frames_target, seconds)
    return {
        "fourk_sfe_fps": d["fps"],
        "fourk_sfe_shards": shards,
        "fourk_sfe_frames": d["frames"],
        "fourk_sfe_concat_ms_p50": d["concat_ms_p50"],
        "fourk_sfe_fetch_ms_p50": d["fetch_ms_p50"],
        "fourk_sfe_host_fallback_stripes": enc.host_fallback_stripes_total,
    }


def bench_4k() -> dict:
    """Config 4: 4K JPEG + 4K H.264 throughput, single-chip AND the
    split-frame-encoding lane.

    The v5e-4 target (30 fps) rides the stripe-axis mesh shard
    (parallel/, validated by __graft_entry__.dryrun_multichip); the
    `fourk_sfe_*` fields measure that path live (ISSUE 15) so the
    speedup over the single-chip `fourk_h264_fps` shows in one BENCH
    round."""
    fps, done, elapsed, total, jst = _pipelined_jpeg_fps(
        3840, 2160, 120, MAX_SECONDS / 4)
    out = {
        "fourk_jpeg_fps": round(fps, 2),
        "fourk_mean_frame_kb": round(total / max(done, 1) / 1024, 1),
        "fourk_d2h_bytes_per_frame": round(jst["d2h_bytes_per_frame"]),
    }
    try:
        from selkies_tpu.capture.synthetic import DeviceScrollSource
        from selkies_tpu.encoder.h264 import H264StripeEncoder
        from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

        B = 8
        enc = H264StripeEncoder(3840, 2160)
        src = DeviceScrollSource(3840, enc.pad_h)
        pipe = PipelinedH264Encoder(enc, depth=3 * B, batch=B)
        enc.encode_frame(src.next_frame())
        enc.encode_frame(src.next_frame())
        for _ in range(3):                   # compile + prefix settle
            pipe.submit_batch(src.next_batch(B))
            for _ in pipe.poll(flush_partial=False):
                pass
        for _ in pipe.flush():
            pass
        done = 0
        start = time.perf_counter()
        while done < 150 and time.perf_counter() - start < MAX_SECONDS / 4:
            pipe.submit_batch(src.next_batch(B))
            for _seq, _o in pipe.poll(flush_partial=False):
                done += 1
        for _seq, _o in pipe.flush():
            done += 1
        el = time.perf_counter() - start
        out["fourk_h264_fps"] = round(done / el, 2) if el > 0 else 0.0
    except Exception as e:
        out["fourk_h264_error"] = repr(e)
    try:
        # ISSUE 15: the SFE lane measured next to the single-chip number
        out.update(_bench_4k_sfe())
    except Exception as e:
        out["fourk_sfe_error"] = repr(e)
    return out


def bench_glass_to_glass() -> dict:
    """p50/p95 capture→client-decodable latency through the REAL server:
    DataStreamingServer + websocket client on loopback; the client ACKs
    every frame and PIL-decodes one stripe per frame as the stand-in for
    the browser's ImageDecoder."""
    import asyncio
    import io

    import numpy as np
    from PIL import Image

    from selkies_tpu.protocol import unpack_binary, VideoStripe
    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import DataStreamingServer
    from selkies_tpu.settings import Settings

    from selkies_tpu.capture.synthetic import SyntheticSource
    from selkies_tpu.server.data_server import default_encoder_factory

    #: wire frame id → (capture-handoff time, harvest time). The wrapper
    #: mirrors the capture loop's id assignment exactly: ids are handed
    #: to non-empty results in poll order, which is submission order.
    #: The harvest stamp splits the end-to-end number into the encode
    #: share (dispatch → levels on host) vs the serve/transport share.
    fid_times = {}

    class TimedEncoder:
        def __init__(self, inner):
            self.inner = inner
            self._t = {}
            self._next_fid = 1

        def try_submit(self, frame):
            seq = self.inner.try_submit(frame)
            if seq is not None:
                self._t[seq] = time.monotonic()
            return seq

        submit = try_submit

        def poll(self):
            out = self.inner.poll()
            now = time.monotonic()
            for seq, stripes in out:
                t = self._t.pop(seq, None)
                if stripes and t is not None:
                    fid_times[self._next_fid] = (t, now)
                    self._next_fid += 1
            return out

        def flush(self):
            return self.inner.flush()

        def pop_trace(self, seq):
            # flight-recorder passthrough: without it the served-path
            # stage breakdown would lose the encoder-side intervals
            pt = getattr(self.inner, "pop_trace", None)
            return pt(seq) if pt else None

        def force_keyframe(self):
            self.inner.force_keyframe()

        def stats(self):
            st = getattr(self.inner, "stats", None)
            return st() if st else {}

        def close(self):
            close = getattr(self.inner, "close", None)
            if close:
                close()

    made = []      # every encoder the server built (reconfigures rebuild)

    def encoder_factory(w, h, settings, overrides=None):
        enc = TimedEncoder(default_encoder_factory(w, h, settings,
                                                   overrides))
        made.append(enc)
        return enc

    def source_factory(w, h, fps, x=0, y=0):
        return SyntheticSource(w, h, fps, pattern="scroll")

    lat_ms = []

    async def run():
        import websockets
        import websockets.asyncio.server as ws_server

        settings = Settings(argv=[], env={"SELKIES_PORT": "0"})
        app = StreamingApp(settings)
        server = DataStreamingServer(
            settings, app=app, source_factory=source_factory,
            encoder_factory=encoder_factory, host="127.0.0.1")
        app.data_server = server
        server._stop_event = asyncio.Event()
        srv = await ws_server.serve(server.ws_handler, "127.0.0.1", 0,
                                    compression=None, max_size=None)
        port = srv.sockets[0].getsockname()[1]

        async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
            await ws.recv()             # MODE
            await ws.recv()             # server_settings
            await ws.send('SETTINGS,{"displayId": "primary", '
                          '"initialClientWidth": 1920, '
                          '"initialClientHeight": 1080, '
                          '"framerate": 30}')
            seen = set()
            deadline = time.monotonic() + 30.0
            while len(lat_ms) < 140 and time.monotonic() < deadline:
                try:
                    m = await asyncio.wait_for(ws.recv(), 10)
                except asyncio.TimeoutError:
                    break
                if not isinstance(m, bytes):
                    continue
                f = unpack_binary(m)
                if not isinstance(f, VideoStripe):
                    continue
                if f.frame_id in seen:
                    continue
                seen.add(f.frame_id)
                # decode one stripe as the browser-ImageDecoder stand-in;
                # latency = capture handoff → stripe decodable client-side
                t_recv = time.monotonic()
                Image.open(io.BytesIO(f.payload)).load()
                t_dec = time.monotonic()
                stamps = fid_times.get(f.frame_id)
                if stamps is not None:
                    t0, t_harvest = stamps
                    lat_ms.append(((t_dec - t0) * 1000.0,
                                   (t_harvest - t0) * 1000.0,
                                   (t_recv - t_harvest) * 1000.0,
                                   (t_dec - t_recv) * 1000.0))
                await ws.send(f"CLIENT_FRAME_ACK {f.frame_id}")
        # driver gauges BEFORE stop() closes the encoders (ISSUE 12:
        # the served path must show >=2 batches in flight, not just the
        # standalone pipeline stints)
        for enc in made:
            try:
                enc_stats.append(enc.stats())
            except Exception:
                pass
        # flight-recorder stage breakdown (ISSUE 13): the ROADMAP item 1
        # criterion measured per stage on the REAL served path
        rec_summary.update(server.recorder.summary("primary"))
        await server.stop()
        rec_open[0] = server.recorder.open_spans()
        srv.close()

    enc_stats: list = []
    rec_summary: dict = {}
    rec_open = [None]
    asyncio.run(run())
    # the first frames pay jit warmup + display reconfigure churn
    samples = lat_ms[20:] if len(lat_ms) > 40 else lat_ms
    if not samples:
        return {"p50_glass_to_glass_ms": None}
    arr = np.asarray(samples)   # [total, encode, serve, client_decode]

    def pct(col, q):
        vals = np.sort(arr[:, col])
        return round(float(vals[min(len(vals) - 1,
                                    int(len(vals) * q / 100))]), 1)

    busiest = max(enc_stats, key=lambda s: s.get("frames", 0), default={})
    # per-stage p50/p95 for all eight stages (ISSUE 13 satellite): the
    # flight recorder measured the REAL path, so the ROADMAP item 1
    # criterion (encode_only vs device ms/frame) is a single bench field
    # with its decomposition alongside
    stage_fields = {}
    for stage, v in (rec_summary.get("stages") or {}).items():
        stage_fields[f"served_{stage}_p50_ms"] = v["p50_ms"]
        stage_fields[f"served_{stage}_p95_ms"] = v["p95_ms"]
    for k in ("glass_to_glass_p50_ms", "glass_to_glass_p95_ms",
              "encode_only_p50_ms", "encode_only_p95_ms"):
        if k in rec_summary:
            stage_fields[f"recorder_{k}"] = rec_summary[k]
    stage_fields["served_frames_traced"] = rec_summary.get("frames", 0)
    stage_fields["served_frames_acked"] = rec_summary.get("acked", 0)
    # must be 0 after stop(): the recorder's span-leak invariant
    stage_fields["served_trace_open_spans"] = rec_open[0]
    return {
        "p50_glass_to_glass_ms": pct(0, 50),
        "p95_glass_to_glass_ms": pct(0, 95),
        **stage_fields,
        # ISSUE 12 acceptance evidence from the SERVED path: the async
        # driver's in-flight window and the dispatch/fetch-wait medians
        # behind encode_only_p50_ms
        "inflight_batches_max": busiest.get("inflight_batches_max", 0),
        "served_dispatch_p50_ms": busiest.get("dispatch_p50_ms", 0.0),
        "served_fetch_wait_p50_ms": busiest.get("fetch_wait_p50_ms", 0.0),
        # stage decomposition (VERDICT r2 item 3): the encode stage is
        # capture handoff → levels on host (device dispatch + D2H — the
        # transport-bound share on the tunnel, sub-frame on PCIe); serve
        # is host assembly + websocket; decode is the client-side share
        "encode_only_p50_ms": pct(1, 50),
        "encode_only_p95_ms": pct(1, 95),
        "serve_p50_ms": pct(2, 50),
        "client_decode_p50_ms": pct(3, 50),
        "latency_samples": len(arr),
        "latency_note": "encode share is tunnel-RPC-bound on this dev "
                        "chip; serve+decode shares are transport-free",
    }


def main() -> None:
    # median-of-N protocol (VERDICT r2 item 8): the shared dev chip's
    # timings swing ±40% with contention, so the headline is the median
    # of three shorter runs with the spread published alongside
    runs = []
    total_bytes = done = 0
    jpeg_stats = {}
    for _ in range(3):
        fps, d, _el, tb, jpeg_stats = _pipelined_jpeg_fps(
            W, H, BENCH_FRAMES // 3, MAX_SECONDS / 4)
        runs.append(round(fps, 2))
        done += d
        total_bytes += tb
    med = sorted(runs)[1]
    result = {
        "metric": "tpuenc_jpeg_1080p_encode_fps",
        "value": med,
        "unit": "fps",
        "vs_baseline": round(med / BASELINE_FPS, 3),
        "runs": runs,
        "spread": round(max(runs) - min(runs), 2),
        "frames": done,
        "mean_frame_kb": round(total_bytes / max(done, 1) / 1024, 1),
        # per-frame transfer + host-entropy gauges (ISSUE 1 satellite:
        # BENCH bottleneck claims must be measured, not inferred)
        "jpeg_d2h_bytes_per_frame":
            round(jpeg_stats.get("d2h_bytes_per_frame", 0)),
        "jpeg_host_entropy_ms_per_frame":
            round(jpeg_stats.get("host_entropy_ms_per_frame", 0), 2),
        # robustness accounting (ISSUE 2 satellite): dropped/errored
        # frames and host entropy fallbacks are results, not log noise —
        # a throughput headline that silently dropped frames is a lie
        "jpeg_frames_dropped": jpeg_stats.get("frames_dropped", 0),
        "jpeg_host_fallback_stripes":
            jpeg_stats.get("host_fallback_stripes", 0),
        # ISSUE 12 satellites on the headline path too
        "jpeg_dispatch_p50_ms": jpeg_stats.get("dispatch_p50_ms", 0.0),
        "jpeg_fetch_wait_p50_ms": jpeg_stats.get("fetch_wait_p50_ms", 0.0),
        "jpeg_inflight_batches_max":
            jpeg_stats.get("inflight_batches_max", 0),
    }
    try:
        result.update(bench_glass_to_glass())
    except Exception as e:  # the headline number must survive a sub-bench
        result["glass_to_glass_error"] = repr(e)
    try:
        result.update(bench_h264())
    except Exception as e:
        result["h264_error"] = repr(e)
    try:
        result.update(bench_4k())
    except Exception as e:
        result["fourk_error"] = repr(e)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
