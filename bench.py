"""Headline benchmark: 1080p streaming-encode throughput on one chip.

Mirrors the reference's headline claim — 60 fps @ 1920×1080 desktop encode
(reference docs/README.md:12, docs/design.md:11; BASELINE.md) — against the
tpuenc JPEG-stripe profile with device-side entropy coding, run through the
pipelined (dispatch/D2H-overlapped) encoder exactly as the streaming server
drives it: per frame, the damage/size metadata and the packed bitstream are
fetched to the host and assembled into per-stripe JPEGs.

Frames come from a device-resident scrolling source (every stripe damaged
every frame — the no-shortcuts worst case for damage gating). On production
hosts capture feeds the chip over PCIe (~0.4 ms for a 6 MB 1080p frame); on
the tunneled dev chip this benchmark runs on, the same upload costs ~450 ms
(14 MB/s), which would measure the tunnel, not the encoder — so the source
materializes frames on device with a jitted roll.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "fps", "vs_baseline": N}
vs_baseline is the ratio against the reference's 60 fps 1080p target.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_FPS = 60.0  # reference headline: 60 fps @ 1080p
W, H = 1920, 1080
WARMUP_FRAMES = 24
BENCH_FRAMES = 300
MAX_SECONDS = 90.0
PIPELINE_DEPTH = 12   # deep enough to hide ~100 ms tunneled-D2H latency
FETCH_GROUP = 4      # frames per D2H read (tunnel allows ~6 concurrent RPCs)


def main() -> None:
    import jax.numpy as jnp

    from selkies_tpu.capture.synthetic import DeviceScrollSource
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    base = JpegStripeEncoder(W, H)
    src = DeviceScrollSource(W, H)
    enc = PipelinedJpegEncoder(base, depth=PIPELINE_DEPTH, fetch_group=FETCH_GROUP)

    def padded(frame):
        if frame.shape[0] == base.pad_h and frame.shape[1] == base.pad_w:
            return frame
        return jnp.pad(
            frame,
            ((0, base.pad_h - frame.shape[0]),
             (0, base.pad_w - frame.shape[1]), (0, 0)),
            mode="edge")

    done = 0
    for _ in range(WARMUP_FRAMES):  # includes compile
        enc.submit(padded(src.next_frame()))
        for _ in enc.poll():
            pass
    for _ in enc.flush():
        pass

    start = time.perf_counter()
    submitted = 0
    total_bytes = 0
    while submitted < BENCH_FRAMES:
        enc.submit(padded(src.next_frame()))
        submitted += 1
        for _seq, stripes in enc.poll():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
        if time.perf_counter() - start > MAX_SECONDS:
            break
    for _seq, stripes in enc.flush():
        done += 1
        total_bytes += sum(len(s.jpeg) for s in stripes)
    elapsed = time.perf_counter() - start

    fps = done / elapsed if elapsed > 0 else 0.0
    result = {
        "metric": "tpuenc_jpeg_1080p_encode_fps",
        "value": round(fps, 2),
        "unit": "fps",
        "vs_baseline": round(fps / BASELINE_FPS, 3),
        "frames": done,
        "elapsed_s": round(elapsed, 2),
        "mean_frame_kb": round(total_bytes / max(done, 1) / 1024, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
