"""Headline benchmark: 1080p streaming-encode throughput on one chip.

Mirrors the reference's headline claim — 60 fps @ 1920×1080 desktop encode
(reference docs/README.md:12, docs/design.md:11; BASELINE.md) — against the
tpuenc JPEG-stripe profile with device-side entropy coding, run through the
pipelined (depth-3, dispatch/D2H-overlapped) encoder exactly as the streaming
server drives it.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "fps", "vs_baseline": N}
vs_baseline is the ratio against the reference's 60 fps 1080p target.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_FPS = 60.0  # reference headline: 60 fps @ 1080p
W, H = 1920, 1080
WARMUP_FRAMES = 12
BENCH_FRAMES = 180
MAX_SECONDS = 60.0


def main() -> None:
    from selkies_tpu.capture.synthetic import SyntheticSource
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    # "scroll" damages every stripe every frame — full-frame work, no
    # damage-gating shortcuts; this is the honest worst-ish case.
    src = SyntheticSource(W, H, pattern="scroll")
    frames = [src.next_frame() for _ in range(16)]

    enc = PipelinedJpegEncoder(JpegStripeEncoder(W, H), depth=3)

    done = 0
    for i in range(WARMUP_FRAMES):  # includes compile
        enc.submit(frames[i % len(frames)])
        for _ in enc.poll():
            pass
    for _ in enc.flush():
        pass

    start = time.perf_counter()
    submitted = 0
    total_bytes = 0
    while submitted < BENCH_FRAMES:
        enc.submit(frames[submitted % len(frames)])
        submitted += 1
        for _seq, stripes in enc.poll():
            done += 1
            total_bytes += sum(len(s.jpeg) for s in stripes)
        if time.perf_counter() - start > MAX_SECONDS:
            break
    for _seq, stripes in enc.flush():
        done += 1
        total_bytes += sum(len(s.jpeg) for s in stripes)
    elapsed = time.perf_counter() - start

    fps = done / elapsed if elapsed > 0 else 0.0
    result = {
        "metric": "tpuenc_jpeg_1080p_encode_fps",
        "value": round(fps, 2),
        "unit": "fps",
        "vs_baseline": round(fps / BASELINE_FPS, 3),
        "frames": done,
        "elapsed_s": round(elapsed, 2),
        "mean_frame_kb": round(total_bytes / max(done, 1) / 1024, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
