"""Input plane tests: message grammar, injection semantics, gamepad protocol.

Drives the full InputHandler logic against fake backends — the reference has
no automated tests here (SURVEY.md §4); this suite covers the grammar of
input_handler.py:1507-1697 behaviorally.
"""

import asyncio
import base64
import struct

import pytest

from selkies_tpu.input import (FakeX11Backend, InputHandler, MemoryClipboard,
                               keysym_to_char, keysym_to_name)
from selkies_tpu.input.cursor import (CursorImage, cursor_to_msg,
                                      encode_png_rgba)
from selkies_tpu.input.gamepad import (ABS_HAT0Y, ABS_RZ, ABS_X, AXIS_MAX,
                                       BTN_A, CONFIG_STRUCT_SIZE, EV_ABS,
                                       EV_KEY, EV_SYN, GamepadManager,
                                       GamepadMapper, VirtualGamepad,
                                       XPAD_MODEL, pack_config)


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def make_handler(**kw):
    backend = FakeX11Backend()
    clip = MemoryClipboard()
    h = InputHandler(backend=backend, clipboard=clip, **kw)
    return h, backend, clip


# ---------------------------------------------------------------------------
# keysyms


def test_keysym_names():
    assert keysym_to_name(0xFF0D) == "Return"
    assert keysym_to_name(0xFFE1) == "Shift_L"
    assert keysym_to_name(0xFFBE) == "F1"
    assert keysym_to_name(0xFFC8) == "F11"
    assert keysym_to_name(0x20) == "space"
    assert keysym_to_name(0x61) == "a"
    assert keysym_to_name(0x01000394) == "U0394"  # unicode Δ
    assert keysym_to_char(0x01000394) == "Δ"
    assert keysym_to_char(0x41) == "A"
    assert keysym_to_char(0xFF0D) is None


# ---------------------------------------------------------------------------
# keyboard grammar


def test_alpha_key_press_release():
    h, be, _ = make_handler()
    run(h.on_message("kd,97"))   # 'a'
    assert ("key", 97, True) in be.events
    run(h.on_message("ku,97"))
    assert ("key", 97, False) in be.events


def test_non_alpha_printable_typed_atomically():
    h, be, _ = make_handler()
    run(h.on_message("kd,33"))   # '!'
    assert ("type", "!") in be.events
    # matching keyup must be swallowed (no stray key event)
    run(h.on_message("ku,33"))
    assert not any(e[0] == "key" for e in be.events)


def test_modifier_tracking_disables_atomic_typing():
    h, be, _ = make_handler()
    run(h.on_message("kd,65507"))  # Control_L (0xFFE3)
    run(h.on_message("kd,33"))     # '!' while ctrl held → real key event
    assert ("key", 33, True) in be.events
    assert not any(e[0] == "type" for e in be.events)
    run(h.on_message("ku,65507"))
    assert 0xFFE3 not in h.active_modifiers


def test_keyboard_reset_releases_pressed():
    h, be, _ = make_handler()
    run(h.on_message("kd,97"))
    run(h.on_message("kd,65507"))
    run(h.on_message("kr"))
    assert ("key", 97, False) in be.events
    assert ("key", 65507, False) in be.events
    assert not h.pressed_keysyms and not h.active_modifiers


def test_atomic_type_verb():
    h, be, _ = make_handler()
    run(h.on_message("co,end,hello, world"))
    assert ("type", "hello, world") in be.events


# ---------------------------------------------------------------------------
# mouse grammar


def test_mouse_move_and_click():
    h, be, _ = make_handler()
    run(h.on_message("m,100,200,1,0"))
    assert ("move", 100, 200) in be.events
    assert ("button", 1, True) in be.events
    run(h.on_message("m,100,200,0,0"))
    assert ("button", 1, False) in be.events


def test_mouse_relative():
    h, be, _ = make_handler()
    run(h.on_message("m2,5,-3,0,0"))
    assert ("rel", 5, -3) in be.events


def test_scroll_up_with_magnitude():
    h, be, _ = make_handler()
    run(h.on_message("m,0,0,8,3"))  # bit 3 + magnitude → 3× button-4 click
    ups = [e for e in be.events if e == ("button", 4, True)]
    assert len(ups) == 3


def test_back_synthesizes_alt_left():
    h, be, _ = make_handler()
    run(h.on_message("m,0,0,8,0"))  # bit 3, no magnitude → Alt+Left
    assert ("key", 0xFFE9, True) in be.events
    assert ("key", 0xFF51, True) in be.events
    assert ("key", 0xFFE9, False) in be.events


def test_display_offset_applied():
    class FakeServer:
        display_layouts = {"display2": {"x": 1920, "y": 0}}

    h, be, _ = make_handler(data_server=FakeServer())
    run(h.on_message("m,10,20,0,0", "display2"))
    assert ("move", 1930, 20) in be.events


# ---------------------------------------------------------------------------
# clipboard grammar


def test_clipboard_write_read_roundtrip():
    h, _, clip = make_handler()
    payload = base64.b64encode("héllo".encode()).decode()
    run(h.on_message(f"cw,{payload}"))
    assert clip.data == "héllo".encode()

    got = []

    async def capture(data, mime):
        got.append((data, mime))

    h.on_clipboard_read = capture
    run(h.on_message("cr"))
    assert got == [("héllo".encode(), "text/plain")]


def test_clipboard_disabled_drops_write():
    h, _, clip = make_handler(enable_clipboard="out")
    payload = base64.b64encode(b"x").decode()
    run(h.on_message(f"cw,{payload}"))
    assert clip.data == b""


def test_multipart_clipboard():
    h, _, clip = make_handler()

    async def scenario():
        data = b"A" * 1000
        await h.on_message(f"cws,{len(data)}")
        half = base64.b64encode(data[:500]).decode()
        rest = base64.b64encode(data[500:]).decode()
        await h.on_message(f"cwd,{half}")
        await h.on_message(f"cwd,{rest}")
        await h.on_message("cwe")

    run(scenario())
    assert clip.data == b"A" * 1000


def test_multipart_size_mismatch_rejected():
    h, _, clip = make_handler()

    async def scenario():
        await h.on_message("cws,999")
        await h.on_message(f"cwd,{base64.b64encode(b'short').decode()}")
        await h.on_message("cwe")

    run(scenario())
    assert clip.data == b""


def test_binary_clipboard():
    h, _, clip = make_handler(enable_binary_clipboard=True)
    png = b"\x89PNG fake"
    payload = base64.b64encode(png).decode()
    run(h.on_message(f"cb,image/png,{payload}"))
    assert clip.data == png and clip.mime_type == "image/png"


# ---------------------------------------------------------------------------
# callbacks


def test_bitrate_fps_latency_callbacks():
    h, _, _ = make_handler()
    seen = {}
    h.on_video_bitrate = lambda v: seen.setdefault("vb", v)
    h.on_audio_bitrate = lambda v: seen.setdefault("ab", v)
    h.on_set_fps = lambda v: seen.setdefault("fps", v)
    h.on_client_fps = lambda v: seen.setdefault("_f", v)
    h.on_client_latency = lambda v: seen.setdefault("_l", v)
    for m in ("vb,4000", "ab,128", "_arg_fps,30", "_f,59", "_l,12"):
        run(h.on_message(m))
    assert seen == {"vb": 4000, "ab": 128, "fps": 30, "_f": 59, "_l": 12}


def test_arg_resize_parses_even_alignment():
    h, _, _ = make_handler()
    seen = {}
    h.on_set_enable_resize = lambda e, r: seen.update(enabled=e, res=r)
    run(h.on_message("_arg_resize,true,1921x1079"))
    assert seen == {"enabled": True, "res": "1922x1080"}


def test_malformed_messages_do_not_raise():
    h, _, _ = make_handler()
    for m in ("kd", "kd,notanint", "m,1,2", "js,b", "cw,!!!notb64",
              "_arg_fps,x", "zzz,1"):
        run(h.on_message(m))


# ---------------------------------------------------------------------------
# gamepad protocol


def test_config_struct_layout():
    blob = pack_config(XPAD_MODEL)
    assert len(blob) == CONFIG_STRUCT_SIZE == 1360
    name = blob[:255].split(b"\0")[0].decode()
    assert name == "Microsoft X-Box 360 pad"
    vendor, product, version, nbtn, nax = struct.unpack_from("=5H", blob, 256)
    assert (vendor, product, version) == (0x045E, 0x028E, 0x0114)
    assert nbtn == 11 and nax == 8
    btn_map = struct.unpack_from("=512H", blob, 266)
    assert btn_map[0] == BTN_A
    axes_map = struct.unpack_from("=64B", blob, 1290)
    assert axes_map[0] == ABS_X


def test_mapper_buttons_axes_triggers_dpad():
    m = GamepadMapper()
    ev = m.map_button(0, 1.0)              # A button
    assert ev.is_button and ev.evdev_code == BTN_A and ev.value_evdev == 1
    ev = m.map_button(7, 1.0)              # right trigger → ABS_RZ
    assert not ev.is_button and ev.evdev_code == ABS_RZ
    assert ev.value_evdev == AXIS_MAX
    ev = m.map_button(12, 1.0)             # dpad up → HAT0Y = -1
    assert ev.evdev_code == ABS_HAT0Y and ev.value_evdev == -1
    assert ev.value_js == -AXIS_MAX        # js hats scale to full range
    ev = m.map_axis(0, -1.0)               # left stick X full left
    assert ev.evdev_code == ABS_X and ev.value_evdev == -AXIS_MAX
    ev = m.map_axis(1, 0.0)
    assert abs(ev.value_evdev) <= 1        # centered
    assert m.map_button(99, 1.0) is None


def test_gamepad_socket_end_to_end(tmp_path):
    async def scenario():
        pad = VirtualGamepad(0, socket_dir=str(tmp_path))
        await pad.start()
        # --- js client
        r, w = await asyncio.open_unix_connection(pad.js_path)
        cfg = await r.readexactly(CONFIG_STRUCT_SIZE)
        assert cfg[:8] == b"Microsof"
        w.write(bytes([8]))  # 64-bit arch
        await w.drain()
        # --- evdev client
        r2, w2 = await asyncio.open_unix_connection(pad.ev_path)
        await r2.readexactly(CONFIG_STRUCT_SIZE)
        w2.write(bytes([8]))
        await w2.drain()
        await asyncio.sleep(0.05)

        pad.send_button(0, 1.0)  # A down
        js_ev = await asyncio.wait_for(r.readexactly(8), timeout=2)
        ts, value, ev_type, number = struct.unpack("=IhBB", js_ev)
        assert (value, ev_type, number) == (1, 0x01, 0)

        ev_pair = await asyncio.wait_for(r2.readexactly(48), timeout=2)
        sec, usec, t, code, val = struct.unpack_from("=qqHHi", ev_pair, 0)
        assert (t, code, val) == (EV_KEY, BTN_A, 1)
        sec, usec, t, code, val = struct.unpack_from("=qqHHi", ev_pair, 24)
        assert (t, code) == (EV_SYN, 0)

        w.close()
        w2.close()
        await pad.stop()

    run(scenario())


def test_gamepad_manager_via_grammar(tmp_path):
    async def scenario():
        mgr = GamepadManager(socket_dir=str(tmp_path))
        h = InputHandler(backend=FakeX11Backend(), gamepads=mgr)
        name = base64.b64encode(b"Test Pad").decode()
        await h.on_message(f"js,c,0,{name},4,17")
        assert 0 in mgr.pads and mgr.pads[0].running
        # connect a client and exercise b/a events through the grammar
        pad = mgr.pads[0]
        r, w = await asyncio.open_unix_connection(pad.js_path)
        await r.readexactly(CONFIG_STRUCT_SIZE)
        w.write(bytes([8]))
        await w.drain()
        await asyncio.sleep(0.05)
        await h.on_message("js,a,0,0,0.5")
        ev = await asyncio.wait_for(r.readexactly(8), timeout=2)
        _, value, ev_type, number = struct.unpack("=IhBB", ev)
        assert ev_type == 0x02 and number == 0 and value > 0
        await h.on_message("js,d,0")
        assert not pad.running
        w.close()
        await mgr.close()

    run(scenario())


def test_out_of_range_gamepad_index(tmp_path):
    async def scenario():
        mgr = GamepadManager(num_slots=2, socket_dir=str(tmp_path))
        h = InputHandler(backend=FakeX11Backend(), gamepads=mgr)
        await h.on_message("js,c,7,{},4,17")
        assert not mgr.pads
        await mgr.close()

    run(scenario())


# ---------------------------------------------------------------------------
# cursor


def test_cursor_to_msg_crops_and_encodes():
    # 8×8 transparent image with an opaque 2×2 block at (3,2)
    import numpy as np
    img = np.zeros((8, 8, 4), np.uint8)
    img[2:4, 3:5] = [255, 0, 0, 255]
    cur = CursorImage(8, 8, xhot=4, yhot=3, serial=7, rgba=img.tobytes())
    msg = cursor_to_msg(cur)
    assert msg["width"] == 2 and msg["height"] == 2
    assert msg["hotx"] == 1 and msg["hoty"] == 1
    assert msg["handle"] == 7
    png = base64.b64decode(msg["curdata"])
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_cursor_size_cap():
    import numpy as np
    img = np.full((128, 128, 4), 255, np.uint8)
    cur = CursorImage(128, 128, 64, 64, 1, img.tobytes())
    msg = cursor_to_msg(cur, size_cap=64)
    assert max(msg["width"], msg["height"]) == 64


def test_empty_cursor():
    msg = cursor_to_msg(None)
    assert msg["curdata"] == "" and msg["width"] == 0
    import numpy as np
    img = np.zeros((4, 4, 4), np.uint8)  # fully transparent
    msg = cursor_to_msg(CursorImage(4, 4, 0, 0, 3, img.tobytes()))
    assert msg["curdata"] == "" and msg["handle"] == 3


def test_png_encoder_valid():
    import zlib
    png = encode_png_rgba(bytes(range(16)) * 4, 4, 4)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    # IDAT decompresses to 4 rows × (1 filter + 16 pixel bytes)
    idat_off = png.index(b"IDAT") + 4
    idat_len = struct.unpack(">I", png[idat_off - 8:idat_off - 4])[0]
    raw = zlib.decompress(png[idat_off:idat_off + idat_len])
    assert len(raw) == 4 * (1 + 16)


# ---------------------------------------------------------------------------
# international / IME coverage (VERDICT round-1 weakness 9)


def test_cyrillic_keysym_reaches_backend():
    h, be, _ = make_handler()
    zhe = 0x01000000 | ord("Ж")      # client unicode rule for non-latin keys
    run(h.on_message(f"kd,{zhe}"))
    run(h.on_message(f"ku,{zhe}"))
    # printable non-latin: atomically typed (stuck-modifier-safe), exactly
    # like latin printables — never silently dropped
    assert ("type", "Ж") in be.events or ("key", zhe, True) in be.events


def test_cjk_ime_composition_types_atomically():
    h, be, _ = make_handler()
    run(h.on_message("co,end,こんにちは世界"))
    assert ("type", "こんにちは世界") in be.events


def test_dead_key_composed_character():
    h, be, _ = make_handler()
    run(h.on_message("co,end,é"))    # dead-acute + e composed client-side
    assert ("type", "é") in be.events


def test_xf86_media_keysym_not_dropped():
    from selkies_tpu.input.keysyms import keysym_to_name

    h, be, _ = make_handler()
    vol_up = 0x1008ff13              # XF86AudioRaiseVolume
    run(h.on_message(f"kd,{vol_up}"))
    assert ("key", vol_up, True) in be.events
    assert keysym_to_name(vol_up) is not None


def test_keypad_keysyms_roundtrip():
    h, be, _ = make_handler()
    run(h.on_message("kd,65421"))    # KP_Enter 0xff8d
    assert ("key", 0xff8d, True) in be.events
