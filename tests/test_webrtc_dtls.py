"""DTLS 1.2 handshake + SRTP keying tests (loopback, lossy transport).

Parity target: vendored ``webrtc/rtcdtlstransport.py`` behavior — mutual
certificate handshake, fingerprint verification, SRTP key export, app
data — without OpenSSL/pylibsrtp (SURVEY.md §2.4)."""

import random

import pytest

from selkies_tpu.webrtc.dtls import DtlsCertificate, DtlsEndpoint
from selkies_tpu.webrtc.srtp import (SrtpContext, kdf, srtp_pair_from_dtls,
                                     SRTP_AES128_CM_HMAC_SHA1_80)
from selkies_tpu.webrtc.rtp import RtpPacket, RtcpReceiverReport


def pump(client, server, client_out, server_out, drop=None, max_iters=200):
    """Deliver queued datagrams until both sides are done or stuck."""
    rng = random.Random(7)
    for _ in range(max_iters):
        moved = False
        while client_out:
            d = client_out.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                server.receive(d)
        while server_out:
            d = server_out.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                client.receive(d)
        if client.handshake_complete and server.handshake_complete:
            return True
        if client.handshake_failed or server.handshake_failed:
            return False
        if not moved:
            # simulate timers
            client.check_retransmit(now=1e9)
            server.check_retransmit(now=1e9)
            if not client_out and not server_out:
                return client.handshake_complete and server.handshake_complete
    return client.handshake_complete and server.handshake_complete


def make_pair(check_fp=True):
    ccert = DtlsCertificate.generate()
    scert = DtlsCertificate.generate()
    client_out, server_out = [], []
    client = DtlsEndpoint(
        is_client=True, certificate=ccert,
        on_send=client_out.append,
        remote_fingerprint=scert.fingerprint() if check_fp else None)
    server = DtlsEndpoint(
        is_client=False, certificate=scert,
        on_send=server_out.append,
        remote_fingerprint=ccert.fingerprint() if check_fp else None)
    return client, server, client_out, server_out


def test_handshake_loopback():
    client, server, co, so = make_pair()
    server.start()
    client.start()
    assert pump(client, server, co, so)
    assert client.handshake_complete and server.handshake_complete
    # both export identical SRTP keying material
    assert client.export_srtp() == server.export_srtp()
    assert len(client.export_srtp()) == 60


def test_handshake_rejects_wrong_fingerprint():
    ccert = DtlsCertificate.generate()
    scert = DtlsCertificate.generate()
    rogue = DtlsCertificate.generate()
    co, so = [], []
    client = DtlsEndpoint(True, ccert, co.append,
                          remote_fingerprint=rogue.fingerprint())
    server = DtlsEndpoint(False, scert, so.append,
                          remote_fingerprint=ccert.fingerprint())
    server.start()
    client.start()
    assert not pump(client, server, co, so)
    assert client.handshake_failed


def test_app_data_after_handshake():
    client, server, co, so = make_pair()
    server.start()
    client.start()
    assert pump(client, server, co, so)
    got = []
    server.on_data = got.append
    client.send_app_data(b"sctp-chunk-here")
    while co:
        server.receive(co.pop(0))
    assert got == [b"sctp-chunk-here"]
    got_c = []
    client.on_data = got_c.append
    server.send_app_data(b"reply")
    while so:
        client.receive(so.pop(0))
    assert got_c == [b"reply"]


def test_handshake_with_packet_loss():
    client, server, co, so = make_pair()
    server.start()
    client.start()
    assert pump(client, server, co, so, drop=0.25, max_iters=1000)
    assert client.export_srtp() == server.export_srtp()


def test_fingerprint_format():
    cert = DtlsCertificate.generate()
    fp = cert.fingerprint()
    assert fp.startswith("sha-256 ")
    parts = fp.split(" ")[1].split(":")
    assert len(parts) == 32 and all(len(p) == 2 for p in parts)


# ------------------------------------------------------------------ SRTP


def test_srtp_kdf_rfc3711_vectors():
    mk = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
    ms = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")
    assert kdf(mk, ms, 0x00, 16).hex().upper() == \
        "C61E7A93744F39EE10734AFE3FF7A087"
    assert kdf(mk, ms, 0x02, 14).hex().upper() == \
        "30CBBC08863D8C85D49DB34A9AE1"
    assert kdf(mk, ms, 0x01, 20).hex().upper() == \
        "CEBE321F6FF7716B6FD4AB49AF256A156D38BAA4"


def test_srtp_rtp_roundtrip_and_replay():
    key, salt = b"k" * 16, b"s" * 14
    tx = SrtpContext(key, salt)
    rx = SrtpContext(key, salt)
    pkt = RtpPacket(payload_type=102, sequence_number=1000, timestamp=90000,
                    ssrc=0x1234, payload=b"video-bytes" * 20).serialize()
    protected = tx.protect_rtp(pkt)
    assert protected != pkt and len(protected) == len(pkt) + 10
    assert rx.unprotect_rtp(protected) == pkt
    with pytest.raises(ValueError, match="replay"):
        rx.unprotect_rtp(protected)


def test_srtp_auth_failure():
    tx = SrtpContext(b"k" * 16, b"s" * 14)
    rx = SrtpContext(b"k" * 16, b"s" * 14)
    pkt = RtpPacket(payload_type=96, sequence_number=5, ssrc=9,
                    payload=b"x" * 50).serialize()
    protected = bytearray(tx.protect_rtp(pkt))
    protected[20] ^= 0xFF
    with pytest.raises(ValueError, match="auth"):
        rx.unprotect_rtp(bytes(protected))


def test_srtp_seq_rollover():
    key, salt = b"a" * 16, b"b" * 14
    tx = SrtpContext(key, salt)
    rx = SrtpContext(key, salt)
    for seq in (65534, 65535, 0, 1):   # crosses ROC boundary
        pkt = RtpPacket(payload_type=96, sequence_number=seq, ssrc=7,
                        payload=bytes([seq & 0xFF]) * 10).serialize()
        assert rx.unprotect_rtp(tx.protect_rtp(pkt)) == pkt
    assert tx._roc[7] == 1


def test_srtcp_roundtrip():
    key, salt = b"q" * 16, b"w" * 14
    tx = SrtpContext(key, salt)
    rx = SrtpContext(key, salt)
    rtcp = RtcpReceiverReport(ssrc=77).serialize()
    protected = tx.protect_rtcp(rtcp)
    assert rx.unprotect_rtcp(protected) == rtcp
    with pytest.raises(ValueError, match="replay"):
        rx.unprotect_rtcp(protected)


def test_dtls_srtp_end_to_end():
    """Full stack: DTLS handshake → exporter → SRTP contexts → media."""
    client, server, co, so = make_pair()
    server.start()
    client.start()
    assert pump(client, server, co, so)
    c_tx, c_rx = srtp_pair_from_dtls(client.export_srtp(), is_client=True)
    s_tx, s_rx = srtp_pair_from_dtls(server.export_srtp(), is_client=False)
    media = RtpPacket(payload_type=102, sequence_number=42, ssrc=1,
                      payload=b"h264" * 100).serialize()
    assert s_rx.unprotect_rtp(c_tx.protect_rtp(media)) == media
    back = RtpPacket(payload_type=111, sequence_number=1, ssrc=2,
                     payload=b"opus" * 40).serialize()
    assert c_rx.unprotect_rtp(s_tx.protect_rtp(back)) == back


def test_merge_range_overlaps():
    from selkies_tpu.webrtc.dtls import _merge_range
    r = []
    _merge_range(r, 0, 10)
    _merge_range(r, 0, 10)          # exact retransmit: no double count
    assert r == [(0, 10)]
    _merge_range(r, 20, 30)
    assert r == [(0, 10), (20, 30)]
    _merge_range(r, 5, 25)          # bridge the hole
    assert r == [(0, 30)]
    assert sum(e - s for s, e in r) == 30


def test_retransmitted_fragment_does_not_complete_early():
    from selkies_tpu.webrtc.dtls import DtlsEndpoint
    ep = DtlsEndpoint(is_client=False)
    seq = ep._next_recv_msg_seq
    # 20-byte handshake message, first half arrives twice (retransmit);
    # byte-counting would declare it complete with a zero-filled tail
    ep._feed_fragment(1, 20, seq, 0, b"A" * 10)
    ep._feed_fragment(1, 20, seq, 0, b"A" * 10)
    assert seq in ep._frag_buf          # still incomplete
    assert ep.handshake_failed is None  # no corrupted-transcript attempt
    ep._feed_fragment(1, 20, seq, 10, b"B" * 10)
    assert seq not in ep._frag_buf      # now processed (and consumed)
