"""Media plumbing tests: player/recorder round-trips, relay fan-out.

Parity target: vendored contrib/media.py
(``/root/reference/src/selkies/webrtc/contrib/media.py:87-300``)."""

import asyncio
import math
import struct

import numpy as np
import pytest

from selkies_tpu.webrtc.media import (MediaBlackhole, MediaPlayer,
                                      MediaRecorder, MediaRelay,
                                      MediaStreamError, _split_access_units,
                                      stream_to)


def write_wav(path, seconds=0.2, rate=48000, channels=2, freq=440.0):
    n = int(rate * seconds)
    t = np.arange(n) / rate
    tone = (np.sin(2 * math.pi * freq * t) * 12000).astype(np.int16)
    pcm = np.stack([tone] * channels, axis=-1).tobytes()
    with open(path, "wb") as f:
        f.write(b"RIFF" + struct.pack("<I", 36 + len(pcm)) + b"WAVE")
        f.write(b"fmt " + struct.pack("<IHHIIHH", 16, 1, channels, rate,
                                      rate * channels * 2, channels * 2, 16))
        f.write(b"data" + struct.pack("<I", len(pcm)) + pcm)
    return pcm


def make_annexb(n_aus=5, slices_per_au=1):
    sps = b"\x00\x00\x00\x01\x67\x42\x00\x1f"
    pps = b"\x00\x00\x00\x01\x68\xce\x06\xe2"
    aus = []
    for i in range(n_aus):
        au = sps + pps if i == 0 else b""
        for s in range(slices_per_au):
            # slice-header first byte: MSB set ⇔ first_mb_in_slice == 0
            # (ue(v) == 0), which is how real first slices look; later
            # slices of the same picture have it clear
            hdr = (0x80 | i) if s == 0 else (i & 0x7F)
            nal = bytes([0x65 if i == 0 else 0x41, hdr]) + bytes([i]) * 49
            au += b"\x00\x00\x00\x01" + nal
        aus.append(au)
    return b"".join(aus), aus


def test_split_access_units_roundtrip():
    stream, aus = make_annexb(5)
    got = _split_access_units(stream)
    assert got == aus
    assert b"".join(got) == stream


def test_split_access_units_multislice():
    """Multi-slice pictures (one slice NAL per stripe, as this framework's
    own recordings produce) must group into ONE access unit per frame, not
    one per slice (ADVICE r2: media.py:80)."""
    stream, aus = make_annexb(4, slices_per_au=3)
    got = _split_access_units(stream)
    assert got == aus
    assert b"".join(got) == stream


def test_split_access_units_aud_boundary():
    """An access-unit delimiter NAL opens a new AU even when the next
    slice's first_mb_in_slice bits are unreadable."""
    aud = b"\x00\x00\x00\x01\x09\xf0"
    slice0 = b"\x00\x00\x00\x01\x65\x88" + b"A" * 20
    slice1 = b"\x00\x00\x00\x01\x41\x00" + b"B" * 20   # MSB clear
    stream = aud + slice0 + aud + slice1
    got = _split_access_units(stream)
    assert got == [aud + slice0, aud + slice1]


def test_split_access_units_empty_and_garbage():
    assert _split_access_units(b"") == []
    assert _split_access_units(b"\x01\x02\x03") == [b"\x01\x02\x03"]


def test_wav_player_to_recorder_roundtrip(tmp_path):
    src = tmp_path / "in.wav"
    dst = tmp_path / "out.wav"
    pcm = write_wav(str(src), seconds=0.1)

    async def run():
        # raw-PCM mode keeps the round trip bit-comparable regardless of
        # whether libopus is present
        player = MediaPlayer(str(src), encode_opus=False)
        assert player.audio is not None and player.audio.kind == "audio"
        rec = MediaRecorder(str(dst), sample_rate=48000, channels=2)
        rec.addTrack(player.audio)
        await rec.start()
        await asyncio.sleep(0.4)
        await rec.stop()

    asyncio.run(run())
    from selkies_tpu.webrtc.media import _parse_wav
    data, rate, ch = _parse_wav(str(dst))
    assert (rate, ch) == (48000, 2)
    assert data == pcm                       # every 20 ms frame, in order


def test_h264_player_paces_and_preserves_aus(tmp_path):
    path = tmp_path / "clip.h264"
    stream, aus = make_annexb(6)
    path.write_bytes(stream)

    async def run():
        player = MediaPlayer(str(path), fps=120.0)
        got = []
        while True:
            try:
                au, ts = await player.video.recv()
            except MediaStreamError:
                break
            got.append((au, ts))
        return got

    got = asyncio.run(run())
    assert [a for a, _ in got] == aus
    # 90 kHz timestamps at 120 fps → 750 ticks apart
    assert [ts for _, ts in got] == [i * 750 for i in range(6)]


def test_h264_recorder_concatenates(tmp_path):
    src = tmp_path / "clip.h264"
    dst = tmp_path / "copy.h264"
    stream, _ = make_annexb(4)
    src.write_bytes(stream)

    async def run():
        player = MediaPlayer(str(src), fps=240.0)
        rec = MediaRecorder(str(dst))
        rec.addTrack(player.video)
        await rec.start()
        await asyncio.sleep(0.3)
        await rec.stop()

    asyncio.run(run())
    assert dst.read_bytes() == stream


def test_relay_fans_out_to_multiple_subscribers(tmp_path):
    path = tmp_path / "clip.h264"
    stream, aus = make_annexb(4)
    path.write_bytes(stream)

    async def run():
        player = MediaPlayer(str(path), fps=240.0)
        relay = MediaRelay()
        t1 = relay.subscribe(player.video, buffered=True)
        t2 = relay.subscribe(player.video, buffered=True)

        async def drain(t):
            out = []
            while True:
                try:
                    au, _ = await t.recv()
                except MediaStreamError:
                    return out
                out.append(au)

        r1, r2 = await asyncio.gather(drain(t1), drain(t2))
        relay.stop()
        return r1, r2

    r1, r2 = asyncio.run(run())
    assert r1 == aus and r2 == aus


def test_relay_live_mode_drops_stale_frames(tmp_path):
    path = tmp_path / "clip.h264"
    stream, aus = make_annexb(6)
    path.write_bytes(stream)

    async def run():
        player = MediaPlayer(str(path), fps=1000.0)
        relay = MediaRelay()
        slow = relay.subscribe(player.video, buffered=False)
        # let the pump outrun the consumer completely
        await asyncio.sleep(0.3)
        got = []
        while True:
            try:
                au, _ = await asyncio.wait_for(slow.recv(), 0.5)
            except (MediaStreamError, asyncio.TimeoutError):
                break
            got.append(au)
        relay.stop()
        return got

    got = asyncio.run(run())
    # live mode: the slow consumer sees ≤2 frames (latest + close), not all 6
    assert 1 <= len(got) <= 2


def test_blackhole_consumes_everything(tmp_path):
    path = tmp_path / "clip.h264"
    stream, aus = make_annexb(5)
    path.write_bytes(stream)

    async def run():
        player = MediaPlayer(str(path), fps=500.0)
        bh = MediaBlackhole()
        bh.addTrack(player.video)
        await bh.start()
        await asyncio.sleep(0.3)
        await bh.stop()
        return bh.consumed

    assert asyncio.run(run()) == 5


def test_stream_to_pumps_sender(tmp_path):
    path = tmp_path / "clip.h264"
    stream, aus = make_annexb(3)
    path.write_bytes(stream)

    class FakeSender:
        def __init__(self):
            self.frames = []

        def send_frame(self, payload, timestamp):
            self.frames.append((payload, timestamp))

    async def run():
        player = MediaPlayer(str(path), fps=500.0)
        s = FakeSender()
        n = await stream_to(s, player.video)
        return n, s.frames

    n, frames = asyncio.run(run())
    assert n == 3
    assert [f for f, _ in frames] == aus


def test_player_rejects_unknown_container(tmp_path):
    p = tmp_path / "x.mp4"
    p.write_bytes(b"")
    with pytest.raises(ValueError):
        MediaPlayer(str(p))


def test_y4m_player(tmp_path):
    w, h, n = 16, 8, 3
    path = tmp_path / "clip.y4m"
    frames = [bytes([i]) * (w * h * 3 // 2) for i in range(n)]
    with open(path, "wb") as f:
        f.write(b"YUV4MPEG2 W16 H8 F1000:1 Ip A1:1 C420\n")
        for fr in frames:
            f.write(b"FRAME\n" + fr)

    async def run():
        player = MediaPlayer(str(path))
        assert player.video.width == w and player.video.height == h
        got = []
        while True:
            try:
                fr, _ = await player.video.recv()
            except MediaStreamError:
                break
            got.append(fr)
        player.stop()
        return got

    assert asyncio.run(run()) == frames
