"""Browser environment stubs for executing web/*.js under tools/minijs.

Python implementations of the DOM/WebCodecs/WebAudio surface the client
uses — just behavioral enough that the real client logic (demux, ACK,
decode ordering, input mapping, dashboard rendering) runs and can be
asserted against. Every stub records what the client did to it.
"""

from __future__ import annotations

import os
import sys
import urllib.parse
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.minijs import (  # noqa: E402
    UNDEF, Interp, JSArray, JSArrayBuffer, JSObject, JSPromise,
    JSTypedArray, NativeFunction, normalize_host, to_num, to_str)

WEB = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "web")


def _nf(fn, name=""):
    return NativeFunction(lambda this, args, interp: fn(*args), name)


# ------------------------------------------------------------------- DOM


class ClassList:
    def __init__(self):
        self._set = set()

    def add(self, *names):
        self._set.update(to_str(n) for n in names)

    def remove(self, *names):
        for n in names:
            self._set.discard(to_str(n))

    def toggle(self, name, force=UNDEF):
        name = to_str(name)
        if force is not UNDEF:
            (self._set.add if force else self._set.discard)(name)
            return bool(force)
        if name in self._set:
            self._set.discard(name)
            return False
        self._set.add(name)
        return True

    def contains(self, name):
        return to_str(name) in self._set


class Style:
    def __init__(self):
        self.cssText = ""
        self.display = ""
        self.width = ""
        self.height = ""
        self.left = ""
        self.top = ""
        self.background = ""
        self.transform = ""
        self._props: Dict[str, str] = {}

    def setProperty(self, name, value, *rest):
        self._props[to_str(name)] = to_str(value)

    def getPropertyValue(self, name):
        return self._props.get(to_str(name), "")

    def removeProperty(self, name):
        return self._props.pop(to_str(name), "")


class Element:
    def __init__(self, env: "BrowserEnv", tag: str):
        self._env = env
        self.tagName = tag.upper()
        self.children = JSArray([])
        self.style = Style()
        self.classList = ClassList()
        self.attrs: Dict[str, Any] = {}
        self.listeners: Dict[str, list] = {}
        self.textContent = ""
        self.value = ""
        self.checked = False
        self.disabled = False
        self.className = ""
        self.id = ""
        self.type = ""
        self.min = ""
        self.max = ""
        self.step = ""
        self.title = ""
        self.href = ""
        self.download = ""
        self.placeholder = ""
        self.parentNode = None
        self.width = 0.0
        self.height = 0.0
        self.files = JSArray([])
        self.onclick = None
        self.oninput = None
        self.onchange = None
        self.innerHTML = ""
        self.src = ""
        self.rows = ""
        self.multiple = ""

    @property
    def childNodes(self):
        return self.children

    # -- tree
    def appendChild(self, child):
        self.children.elems.append(child)
        if isinstance(child, Element):
            child.parentNode = self
        return child

    def append(self, *children):
        for c in children:
            if isinstance(c, Element):
                self.appendChild(c)
            else:
                self.textContent += to_str(c)

    def remove(self):
        if self.parentNode is not None:
            try:
                self.parentNode.children.elems.remove(self)
            except ValueError:
                pass
            self.parentNode = None

    def contains(self, other):
        for c in self.children.elems:
            if c is other or (isinstance(c, Element) and c.contains(other)):
                return True
        return False

    # -- attributes / events
    def setAttribute(self, name, value):
        name = to_str(name)
        self.attrs[name] = value
        if name in ("id", "type", "min", "max", "step", "title",
                    "placeholder", "value", "download"):
            setattr(self, name, value)
        if name == "disabled":
            self.disabled = True

    def getAttribute(self, name):
        return self.attrs.get(to_str(name))

    def addEventListener(self, type_, fn, opts=UNDEF):
        self.listeners.setdefault(to_str(type_), []).append(fn)

    def removeEventListener(self, type_, fn, opts=UNDEF):
        lst = self.listeners.get(to_str(type_), [])
        if fn in lst:
            lst.remove(fn)

    def dispatchEvent(self, ev):
        self._env.fire(self, getattr(ev, "type", "event"), ev)

    # -- misc behavior
    def focus(self, opts=UNDEF):
        self._env.focused = self

    def click(self):
        self._env.fire(self, "click")

    def getContext(self, kind):
        if self._ctx is None:
            self._ctx = Context2D()
        return self._ctx

    _ctx = None

    def getBoundingClientRect(self):
        return JSObject({"left": 0.0, "top": 0.0,
                         "width": float(self.width or 100),
                         "height": float(self.height or 100)})

    def requestPointerLock(self):
        self._env.pointer_lock_target = self

    def requestFullscreen(self):
        return self._env.resolved(UNDEF)

    def arc(self, *a):
        pass

    # tree search used by tests
    def find_all(self, pred, out=None):
        out = out if out is not None else []
        for c in self.children.elems:
            if isinstance(c, Element):
                if pred(c):
                    out.append(c)
                c.find_all(pred, out)
        return out


class Context2D:
    def __init__(self):
        self.draw_calls: List[tuple] = []
        self.fillStyle = ""
        self.strokeStyle = ""
        self.font = ""
        self.lineWidth = 1.0

    def drawImage(self, img, x, y, *rest):
        self.draw_calls.append((img, to_num(x), to_num(y)))

    def clearRect(self, *a):
        self.draw_calls.append(("clear",))

    def setTransform(self, *a):
        pass

    def fillRect(self, *a):
        pass

    def fillText(self, *a):
        pass

    def beginPath(self, *a):
        pass

    def arc(self, *a):
        pass

    def stroke(self, *a):
        pass

    def fill(self, *a):
        pass


class Document:
    def __init__(self, env):
        self._env = env
        self.body = Element(env, "body")
        self.documentElement = Element(env, "html")
        self.listeners: Dict[str, list] = {}
        self.pointerLockElement = None
        self.visibilityState = "visible"
        self.title = ""

    def createElement(self, tag):
        return Element(self._env, to_str(tag))

    def addEventListener(self, type_, fn, opts=UNDEF):
        self.listeners.setdefault(to_str(type_), []).append(fn)

    def removeEventListener(self, type_, fn, opts=UNDEF):
        lst = self.listeners.get(to_str(type_), [])
        if fn in lst:
            lst.remove(fn)

    def exitPointerLock(self):
        self.pointerLockElement = None


class FakeWindow:
    def __init__(self, env):
        self._env = env
        self.listeners: Dict[str, list] = {}
        self.devicePixelRatio = 1.0
        self.innerWidth = 1920.0
        self.innerHeight = 1080.0

    def addEventListener(self, type_, fn, opts=UNDEF):
        self.listeners.setdefault(to_str(type_), []).append(fn)

    def removeEventListener(self, type_, fn, opts=UNDEF):
        lst = self.listeners.get(to_str(type_), [])
        if fn in lst:
            lst.remove(fn)

    def dispatchEvent(self, ev):
        type_ = to_str(self._env.interp.get_prop(ev, "type"))
        for fn in list(self.listeners.get(type_, [])):
            self._env.call(fn, [ev])


# ------------------------------------------------------------ WebSocket


class FakeWebSocket:
    CONNECTING, OPEN, CLOSING, CLOSED = 0.0, 1.0, 2.0, 3.0

    def __init__(self, env, url):
        self._env = env
        self.url = to_str(url)
        self.binaryType = ""
        self.readyState = FakeWebSocket.CONNECTING
        self.sent: List[Any] = []          # str or bytes
        self.bufferedAmount = 0.0
        self.onopen = None
        self.onmessage = None
        self.onclose = None
        self.onerror = None
        env.sockets.append(self)

    def send(self, data):
        if isinstance(data, str):
            self.sent.append(data)
        elif isinstance(data, JSArrayBuffer):
            self.sent.append(bytes(data.data))
        elif isinstance(data, JSTypedArray):
            off = data.offset
            self.sent.append(bytes(
                data.buffer.data[off:off + data.length * data.itemsize]))
        else:
            self.sent.append(data)

    def close(self):
        self.readyState = FakeWebSocket.CLOSED
        if self.onclose is not None:
            self._env.call(self.onclose, [JSObject({})])

    # test helpers -----------------------------------------------------
    def server_open(self):
        self.readyState = FakeWebSocket.OPEN
        if self.onopen is not None:
            self._env.call(self.onopen, [JSObject({})])

    def server_text(self, text: str):
        ev = JSObject({"data": text})
        if self.onmessage is not None:
            self._env.call(self.onmessage, [ev])

    def server_binary(self, data: bytes):
        ev = JSObject({"data": JSArrayBuffer(bytearray(data))})
        if self.onmessage is not None:
            self._env.call(self.onmessage, [ev])

    def texts(self) -> List[str]:
        return [s for s in self.sent if isinstance(s, str)]


# ------------------------------------------------------------ WebCodecs


class FakeBitmap:
    def __init__(self, data: bytes):
        self.data = data
        self.width = 0.0
        self.height = 0.0
        self.closed = False

    def close(self):
        self.closed = True


class FakeChunk:
    def __init__(self, env, init: JSObject):
        self.type = to_str(init.props.get("type", ""))
        self.timestamp = to_num(init.props.get("timestamp", 0.0))
        data = init.props.get("data")
        if isinstance(data, JSTypedArray):
            off = data.offset
            self.data = bytes(
                data.buffer.data[off:off + data.length * data.itemsize])
        elif isinstance(data, JSArrayBuffer):
            self.data = bytes(data.data)
        else:
            self.data = b""


class FakeVideoDecoder:
    def __init__(self, env, init: JSObject):
        self._env = env
        self.output_cb = init.props.get("output")
        self.error_cb = init.props.get("error")
        self.state = "unconfigured"
        self.config = None
        self.decodeQueueSize = 0.0
        self.chunks: List[FakeChunk] = []
        self.fail_next = False
        env.video_decoders.append(self)

    def configure(self, cfg):
        self.state = "configured"
        self.config = cfg

    def decode(self, chunk):
        if self.state == "closed":
            raise RuntimeError("decoder closed")
        if self.fail_next:
            self.fail_next = False
            raise RuntimeError("decode error (injected)")
        self.chunks.append(chunk)
        frame = JSObject({
            "close": NativeFunction(lambda t, a, i: UNDEF, "close"),
            "displayWidth": 0.0,
            "codedWidth": 0.0,
            "_chunk": chunk,
        })
        if self.output_cb is not None:
            self._env.call(self.output_cb, [frame])

    def close(self):
        self.state = "closed"


class FakeAudioData:
    def __init__(self, frames: int, channels: int):
        self.numberOfFrames = float(frames)
        self.numberOfChannels = float(channels)
        self.closed = False

    def copyTo(self, arr, opts):
        plane = to_num(opts.props.get("planeIndex", 0.0)) \
            if isinstance(opts, JSObject) else 0.0
        if isinstance(arr, JSTypedArray):
            for i in range(arr.length):
                arr.set_index(i, 0.25 + plane * 0.5)

    def close(self):
        self.closed = True


class FakeAudioDecoder:
    def __init__(self, env, init: JSObject):
        self._env = env
        self.output_cb = init.props.get("output")
        self.state = "unconfigured"
        self.chunks: List[FakeChunk] = []
        env.audio_decoders.append(self)

    def configure(self, cfg):
        self.state = "configured"

    def decode(self, chunk):
        self.chunks.append(chunk)
        if self.output_cb is not None:
            self._env.call(self.output_cb, [FakeAudioData(960, 2)])

    def close(self):
        self.state = "closed"


# ------------------------------------------------------------ WebAudio


class FakePort:
    def __init__(self):
        self.messages: List[Any] = []
        self.onmessage = None

    def postMessage(self, msg, transfer=UNDEF):
        self.messages.append(msg)


class FakeWorkletNode:
    def __init__(self, env, ctx, name, opts=UNDEF):
        self.name = to_str(name)
        self.port = FakePort()
        self.connected_to = None
        env.worklet_nodes.append(self)

    def connect(self, dest):
        self.connected_to = dest


class FakeAudioContext:
    def __init__(self, env, opts=UNDEF):
        self._env = env
        self.sampleRate = 48000.0
        self.currentTime = 0.0
        self.destination = JSObject({"kind": "destination"})
        self.audioWorklet = JSObject({
            "addModule": NativeFunction(
                lambda t, a, i: env.resolved(UNDEF), "addModule"),
        })
        env.audio_contexts.append(self)

    def createBuffer(self, channels, frames, rate):
        return JSObject({
            "duration": to_num(frames) / to_num(rate),
            "copyToChannel": NativeFunction(
                lambda t, a, i: UNDEF, "copyToChannel"),
        })

    def createBufferSource(self):
        src = JSObject({
            "buffer": None,
            "connect": NativeFunction(lambda t, a, i: UNDEF, "connect"),
            "start": NativeFunction(lambda t, a, i: UNDEF, "start"),
        })
        return src

    def createMediaStreamSource(self, stream):
        return JSObject({"connect": NativeFunction(
            lambda t, a, i: UNDEF, "connect")})

    def createScriptProcessor(self, size, ins, outs):
        proc = Element(self._env, "scriptprocessor")
        return proc


# --------------------------------------------------------------- Blob


class FakeBlob:
    def __init__(self, env, parts=UNDEF, opts=UNDEF):
        self._env = env
        buf = bytearray()
        if isinstance(parts, JSArray):
            for p in parts.elems:
                if isinstance(p, JSTypedArray):
                    off = p.offset
                    buf += p.buffer.data[off:off + p.length * p.itemsize]
                elif isinstance(p, JSArrayBuffer):
                    buf += p.data
                elif isinstance(p, str):
                    buf += p.encode()
        self.data = bytes(buf)
        self.size = float(len(self.data))
        self.type = ""
        if isinstance(opts, JSObject):
            self.type = to_str(opts.props.get("type", ""))

    def arrayBuffer(self):
        return self._env.resolved(JSArrayBuffer(bytearray(self.data)))

    def slice(self, a, b):
        return FakeBlobSlice(self._env,
                             self.data[int(to_num(a)):int(to_num(b))])


class FakeBlobSlice:
    def __init__(self, env, data):
        self._env = env
        self.data = data

    def arrayBuffer(self):
        return self._env.resolved(JSArrayBuffer(bytearray(self.data)))


# ---------------------------------------------------------------- env


class BrowserEnv:
    """One interpreter + browser globals + loaded client files."""

    def __init__(self, files=("selkies-client.js",)):
        self.interp = Interp()
        self.sockets: List[FakeWebSocket] = []
        self.video_decoders: List[FakeVideoDecoder] = []
        self.audio_decoders: List[FakeAudioDecoder] = []
        self.audio_contexts: List[FakeAudioContext] = []
        self.worklet_nodes: List[FakeWorkletNode] = []
        self.bitmaps: List[FakeBitmap] = []
        self.focused: Optional[Element] = None
        self.pointer_lock_target: Optional[Element] = None
        self.exports: Dict[str, Any] = {}

        g = self.interp.globals
        self.document = Document(self)
        self.window = FakeWindow(self)
        g.declare("document", self.document)
        g.declare("window", self.window)
        g.declare("location", JSObject({
            "protocol": "http:", "host": "testhost:8080",
            "href": "http://testhost:8080/"}))
        g.declare("Event", NativeFunction(
            lambda t, a, i: JSObject({"type": to_str(a[0])}), "Event"))
        g.declare("screen", JSObject({"width": 1920.0, "height": 1080.0}))
        g.declare("performance", JSObject({
            "now": NativeFunction(
                lambda t, a, i: self.interp.now_ms, "now")}))
        self.local_storage: Dict[str, str] = {}
        g.declare("localStorage", JSObject({
            "getItem": NativeFunction(
                lambda t, a, i: self.local_storage.get(to_str(a[0]), None),
                "getItem"),
            "setItem": NativeFunction(
                lambda t, a, i: (self.local_storage.__setitem__(
                    to_str(a[0]), to_str(a[1])), UNDEF)[1], "setItem"),
            "removeItem": NativeFunction(
                lambda t, a, i: (self.local_storage.pop(
                    to_str(a[0]), None), UNDEF)[1], "removeItem"),
        }))
        self.gamepads = JSArray([])
        self.clipboard_writes: List[str] = []
        g.declare("navigator", JSObject({
            "getGamepads": NativeFunction(
                lambda t, a, i: self.gamepads, "getGamepads"),
            "clipboard": JSObject({
                "writeText": NativeFunction(
                    lambda t, a, i: (self.clipboard_writes.append(
                        to_str(a[0])), self.resolved(UNDEF))[1],
                    "writeText"),
            }),
            "mediaDevices": JSObject({
                "getUserMedia": NativeFunction(
                    lambda t, a, i: self.resolved(JSObject({})),
                    "getUserMedia"),
            }),
            "wakeLock": JSObject({
                "request": NativeFunction(
                    lambda t, a, i: self._wake_request(), "request"),
            }),
        }))
        self.wake_locks: List[JSObject] = []
        ws_ctor = NativeFunction(
            lambda t, a, i: FakeWebSocket(self, a[0]), "WebSocket")
        ws_ctor.OPEN = FakeWebSocket.OPEN
        ws_ctor.CONNECTING = FakeWebSocket.CONNECTING
        ws_ctor.CLOSED = FakeWebSocket.CLOSED
        g.declare("WebSocket", ws_ctor)
        g.declare("VideoDecoder", NativeFunction(
            lambda t, a, i: FakeVideoDecoder(self, a[0]), "VideoDecoder"))
        g.declare("AudioDecoder", NativeFunction(
            lambda t, a, i: FakeAudioDecoder(self, a[0]), "AudioDecoder"))
        g.declare("EncodedVideoChunk", NativeFunction(
            lambda t, a, i: FakeChunk(self, a[0]), "EncodedVideoChunk"))
        g.declare("EncodedAudioChunk", NativeFunction(
            lambda t, a, i: FakeChunk(self, a[0]), "EncodedAudioChunk"))
        g.declare("AudioContext", NativeFunction(
            lambda t, a, i: FakeAudioContext(self, *a), "AudioContext"))
        g.declare("AudioWorkletNode", NativeFunction(
            lambda t, a, i: FakeWorkletNode(self, *a), "AudioWorkletNode"))
        g.declare("Blob", NativeFunction(
            lambda t, a, i: FakeBlob(self, *a), "Blob"))
        g.declare("createImageBitmap", NativeFunction(
            lambda t, a, i: self._create_bitmap(a[0]), "createImageBitmap"))
        url_ns = JSObject({
            "createObjectURL": NativeFunction(
                lambda t, a, i: "blob:fake", "createObjectURL"),
            "revokeObjectURL": NativeFunction(
                lambda t, a, i: UNDEF, "revokeObjectURL"),
        })
        g.declare("URL", url_ns)
        g.declare("Audio", NativeFunction(
            lambda t, a, i: Element(self, "audio"), "Audio"))
        g.declare("requestAnimationFrame", NativeFunction(
            lambda t, a, i: 1.0, "requestAnimationFrame"))

        # URI coders (clipboard path uses the classic escape/unescape trick)
        g.declare("encodeURIComponent", _nf(
            lambda s: urllib.parse.quote(
                to_str(s), safe="!'()*-._~"), "encodeURIComponent"))
        g.declare("decodeURIComponent", _nf(
            lambda s: urllib.parse.unquote(to_str(s)),
            "decodeURIComponent"))
        g.declare("escape", _nf(
            lambda s: "".join(
                c if ((c.isascii() and c.isalnum()) or c in "*@-_+./")
                else f"%{ord(c):02X}" for c in to_str(s)), "escape"))

        def _unescape(s):
            s = to_str(s)
            out = []
            i = 0
            while i < len(s):
                if s[i] == "%" and i + 2 < len(s) + 1:
                    try:
                        out.append(chr(int(s[i + 1:i + 3], 16)))
                        i += 3
                        continue
                    except ValueError:
                        pass
                out.append(s[i])
                i += 1
            return "".join(out)

        g.declare("unescape", _nf(_unescape, "unescape"))

        for f in files:
            self.load(f)

    # ---------------------------------------------------------- helpers

    def load(self, filename: str):
        """Run one client file with a fresh CommonJS-ish module object."""
        module = JSObject({"exports": JSObject({})})
        self.interp.globals.declare("module", module)
        src = open(os.path.join(WEB, filename)).read()
        self.interp.run(src)
        exports = module.props["exports"]
        if isinstance(exports, JSObject):
            self.exports.update(exports.props)
        self.interp.globals.vars.pop("module", None)
        return exports

    def call(self, fn, args=(), this=UNDEF):
        out = self.interp.call(fn, list(args), this=this)
        self.interp.run_microtasks()
        return out

    def construct(self, ctor, args=()):
        return self.interp.construct(ctor, list(args))

    def resolved(self, value) -> JSPromise:
        p = JSPromise(self.interp)
        p.resolve(value)
        return p

    def _wake_request(self) -> JSPromise:
        lock = JSObject({"released": False})
        lock.props["release"] = NativeFunction(
            lambda t, a, i: (lock.props.__setitem__("released", True),
                             UNDEF)[1], "release")
        self.wake_locks.append(lock)
        return self.resolved(lock)

    def _create_bitmap(self, blob) -> JSPromise:
        bmp = FakeBitmap(getattr(blob, "data", b""))
        self.bitmaps.append(bmp)
        return self.resolved(bmp)

    def get(self, obj, key):
        return self.interp.get_prop(obj, key)

    def fire(self, target, type_: str, ev=None):
        """Dispatch an event to element/document/window listeners and
        onXXX handler attributes."""
        if ev is None:
            ev = self.make_event(type_, target=target)
        handler = getattr(target, "on" + type_, None)
        if handler not in (None, UNDEF):
            self.call(handler, [ev])
        for fn in list(getattr(target, "listeners", {}).get(type_, [])):
            self.call(fn, [ev])
        return ev

    def make_event(self, type_: str, target=None, **props):
        base = {
            "type": type_,
            "target": target if target is not None else UNDEF,
            "preventDefault": NativeFunction(
                lambda t, a, i: UNDEF, "preventDefault"),
            "stopPropagation": NativeFunction(
                lambda t, a, i: UNDEF, "stopPropagation"),
        }
        for k, v in props.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                v = float(v)
            base[k] = v
        return JSObject(base)


# ------------------------------------------------------------- WebRTC


class FakeRTCDataChannel:
    """Server-created data channel as seen from the browser."""

    def __init__(self, env, label="input"):
        self._env = env
        self.label = label
        self.readyState = "connecting"
        self.sent: List[Any] = []
        self.onopen = None
        self.onmessage = None

    def send(self, data):
        self.sent.append(to_str(data) if isinstance(data, str) else data)

    # test helpers -----------------------------------------------------
    def server_open(self):
        self.readyState = "open"
        if self.onopen not in (None, UNDEF):
            self._env.call(self.onopen, [JSObject({})])

    def server_message(self, text: str):
        if self.onmessage not in (None, UNDEF):
            self._env.call(self.onmessage, [JSObject({"data": text})])


class FakeRTCPeerConnection:
    def __init__(self, env, cfg=UNDEF):
        self._env = env
        self.config = cfg
        self.remoteDescription = None
        self.localDescription = None
        self.added_ice: List[Any] = []
        self.connectionState = "new"
        self.ontrack = None
        self.ondatachannel = None
        self.onicecandidate = None
        self.onconnectionstatechange = None
        self.closed = False
        env.peer_connections.append(self)

    def setRemoteDescription(self, desc):
        self.remoteDescription = desc
        return self._env.resolved(UNDEF)

    def createAnswer(self):
        return self._env.resolved(JSObject(
            {"type": "answer", "sdp": "v=0\r\ns=fake-answer\r\n"}))

    def setLocalDescription(self, desc):
        self.localDescription = desc
        return self._env.resolved(UNDEF)

    def addIceCandidate(self, cand):
        self.added_ice.append(cand)
        return self._env.resolved(UNDEF)

    def close(self):
        self.closed = True
        self.connectionState = "closed"

    # test helpers -----------------------------------------------------
    def server_datachannel(self, label="input") -> FakeRTCDataChannel:
        ch = FakeRTCDataChannel(self._env, label)
        if self.ondatachannel not in (None, UNDEF):
            self._env.call(self.ondatachannel,
                           [JSObject({"channel": ch})])
        return ch

    def server_track(self, stream):
        if self.ontrack not in (None, UNDEF):
            self._env.call(self.ontrack, [JSObject(
                {"streams": JSArray([stream])})])

    def fire_local_ice(self, candidate: str, mline: float = 0.0):
        if self.onicecandidate not in (None, UNDEF):
            self._env.call(self.onicecandidate, [JSObject({
                "candidate": JSObject({"candidate": candidate,
                                       "sdpMLineIndex": mline})})])

    def set_connection_state(self, state: str):
        self.connectionState = state
        if self.onconnectionstatechange not in (None, UNDEF):
            self._env.call(self.onconnectionstatechange, [JSObject({})])


def install_webrtc_stubs(env):
    """Declare RTCPeerConnection + fetch for webrtc.js tests."""
    env.peer_connections = []
    env.fetch_calls = []
    env.turn_config = JSObject({"iceServers": JSArray([JSObject(
        {"urls": JSArray(["stun:stun.fake:3478"])})])})

    g = env.interp.globals
    g.declare("RTCPeerConnection", NativeFunction(
        lambda t, a, i: FakeRTCPeerConnection(env, a[0] if a else UNDEF),
        "RTCPeerConnection"))

    def _fetch(t, a, i):
        url = to_str(a[0])
        env.fetch_calls.append(url)
        resp = JSObject({
            "ok": True,
            "json": NativeFunction(
                lambda tt, aa, ii: env.resolved(env.turn_config), "json"),
        })
        return env.resolved(resp)

    g.declare("fetch", NativeFunction(_fetch, "fetch"))
    g.declare("devicePixelRatio", 2.0)
