"""Metric/doc drift gate (ISSUE 13 satellite): every Prometheus series
registered in observability/metrics.py must be documented in
docs/observability.md and vice versa — silent metric drift fails
tier-1, not a quarterly docs audit."""

from tools.metrics_lint import check, code_series, doc_series


def test_no_metric_doc_drift():
    undocumented, stale = check()
    assert not undocumented, (
        f"series registered in metrics.py but missing from "
        f"docs/observability.md: {sorted(undocumented)}")
    assert not stale, (
        f"series documented in docs/observability.md but not registered "
        f"in metrics.py: {sorted(stale)}")


def test_lint_actually_parses_both_sides():
    # a regression that parses zero names on either side would make the
    # drift check vacuously green — pin a floor and known members
    code = code_series()
    docs = doc_series()
    assert len(code) >= 30
    assert "frame_stage_ms" in code
    assert "glass_to_glass_ms" in docs
    assert "fps" in code and "fps" in docs
