"""Display plane: GTF modelines, layout geometry, xrandr command grammar,
DPI fan-out (reference parity: selkies.py:216-470, 2616-2779)."""

import pytest

from selkies_tpu.display import (DpiManager, XrandrManager, compute_layout,
                                 fit_res, gtf_modeline, parse_res)


# ---------------------------------------------------------------------------
# modeline


def test_gtf_1080p60_matches_gtf_utility():
    """Canonical `gtf 1920 1080 60` output:
    172.80 MHz, 1920 2040 2248 2576, 1080 1081 1084 1118."""
    m = gtf_modeline(1920, 1080, 60)
    assert m.pclk_mhz == pytest.approx(172.80, abs=0.01)
    assert (m.hdisp, m.hsync_start, m.hsync_end, m.htotal) == (
        1920, 2040, 2248, 2576)
    assert (m.vdisp, m.vsync_start, m.vsync_end, m.vtotal) == (
        1080, 1081, 1084, 1118)


def test_gtf_1024x768_matches_gtf_utility():
    """Canonical `gtf 1024 768 60`: 64.11 MHz, 1024 1080 1184 1344,
    768 769 772 795."""
    m = gtf_modeline(1024, 768, 60)
    assert m.pclk_mhz == pytest.approx(64.11, abs=0.01)
    assert (m.hdisp, m.hsync_start, m.hsync_end, m.htotal) == (
        1024, 1080, 1184, 1344)
    assert (m.vdisp, m.vsync_start, m.vsync_end, m.vtotal) == (
        768, 769, 772, 795)


def test_gtf_refresh_close_to_request():
    for w, h, r in [(1920, 1080, 60), (2560, 1440, 75), (803, 601, 60),
                    (640, 480, 120)]:
        m = gtf_modeline(w, h, r)
        assert m.refresh_hz == pytest.approx(r, rel=0.01), (w, h, r)
        # xrandr args shape
        args = m.xrandr_args()
        assert len(args) == 12 and args[-2:] == ["-HSync", "+VSync"]


def test_gtf_rejects_nonsense():
    with pytest.raises(ValueError):
        gtf_modeline(0, 1080)
    with pytest.raises(ValueError):
        gtf_modeline(1920, 1080, -5)


# ---------------------------------------------------------------------------
# layout / sanitizers


def test_parse_res_even_aligns():
    assert parse_res("1921x1081") == (1920, 1080)
    assert parse_res("640X480") == (640, 480)
    for bad in ("", "x", "axb", "-2x100", "0x0"):
        with pytest.raises(ValueError):
            parse_res(bad)


def test_fit_res_preserves_aspect():
    w, h = fit_res(3840, 2160, 1920, 1200)
    assert (w, h) == (1920, 1080)
    assert fit_res(800, 600, 1920, 1080) == (800, 600)


def test_layout_right_left_up_down():
    d = {"primary": (1920, 1080), "display2": (1280, 720)}
    right = compute_layout(d, "right")
    assert (right.fb_width, right.fb_height) == (3200, 1080)
    assert right.offset_of("primary") == (0, 0)
    assert right.offset_of("display2") == (1920, 0)

    left = compute_layout(d, "left")
    assert (left.fb_width, left.fb_height) == (3200, 1080)
    assert left.offset_of("display2") == (0, 0)
    assert left.offset_of("primary") == (1280, 0)

    down = compute_layout(d, "down")
    assert (down.fb_width, down.fb_height) == (1920, 1800)
    assert down.offset_of("primary") == (0, 0)
    assert down.offset_of("display2") == (0, 1080)

    up = compute_layout(d, "up")
    assert up.offset_of("display2") == (0, 0)
    assert up.offset_of("primary") == (0, 720)


def test_layout_single_display():
    lay = compute_layout({"primary": (1280, 800)})
    assert (lay.fb_width, lay.fb_height) == (1280, 800)
    assert lay.placements[0].display_id == "primary"


# ---------------------------------------------------------------------------
# xrandr command grammar (fake runner; no X server needed)

XRANDR_QUERY = """\
Screen 0: minimum 8 x 8, current 1920 x 1080, maximum 16384 x 16384
DVI-D-0 connected primary 1920x1080+0+0 (normal left inverted) 530mm x 300mm
   1920x1080     60.00*+  59.94
   1280x720      60.00
HDMI-0 disconnected (normal left inverted right x axis y axis)
"""

LISTMONITORS = """\
Monitors: 2
 0: +*selkies-primary 1920/530x1080/300+0+0  DVI-D-0
 1: +selkies-display2 1280/340x720/190+1920+0
"""


class FakeRunner:
    def __init__(self):
        self.calls = []

    def __call__(self, argv):
        self.calls.append(list(argv))
        if "--query" in argv:
            return 0, XRANDR_QUERY
        if "--listmonitors" in argv:
            return 0, LISTMONITORS
        return 0, ""


def test_connected_outputs_and_modes():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    assert mgr.connected_outputs() == ["DVI-D-0"]
    assert mgr.output_modes("DVI-D-0") == ["1920x1080", "1280x720"]
    assert mgr.output_modes("HDMI-0") == []


def test_ensure_mode_prefers_existing_native():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    assert mgr.ensure_mode("DVI-D-0", 1920, 1080) == "1920x1080"
    assert not any("--newmode" in c for c in r.calls)


def test_ensure_mode_creates_gtf_mode():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    name = mgr.ensure_mode("DVI-D-0", 1600, 900)
    assert name == "1600x900_60.00"
    newmode = next(c for c in r.calls if "--newmode" in c)
    i = newmode.index("--newmode")
    assert newmode[i + 1] == "1600x900_60.00"
    addmode = next(c for c in r.calls if "--addmode" in c)
    assert addmode[-2:] == ["DVI-D-0", "1600x900_60.00"]


def test_resize_issues_output_mode():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    mode = mgr.resize(1280, 720)
    assert mode == "1280x720"
    assert ["xrandr", "--output", "DVI-D-0", "--mode", "1280x720"] in r.calls


def test_apply_layout_full_grammar():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    lay = compute_layout({"primary": (1920, 1080), "display2": (1280, 720)},
                         "right")
    mgr.apply_layout(lay)
    flat = ["\x00".join(c) for c in r.calls]
    # stale logical monitors removed
    assert any("--delmonitor\x00selkies-primary" in f for f in flat)
    assert any("--delmonitor\x00selkies-display2" in f for f in flat)
    # framebuffer grown
    assert ["xrandr", "--fb", "3200x1080"] in r.calls
    # one logical monitor per placement, geometry WxH+X+Y with mm spans
    setmons = [c for c in r.calls if "--setmonitor" in c]
    geoms = {c[c.index("--setmonitor") + 1]: c[c.index("--setmonitor") + 2]
             for c in setmons}
    assert geoms["selkies-primary"] == "1920/1920x1080/1080+0+0"
    assert geoms["selkies-display2"] == "1280/1280x720/720+1920+0"


def test_monitor_parsing():
    r = FakeRunner()
    mgr = XrandrManager(runner=r)
    assert mgr.list_monitors() == ["selkies-primary", "selkies-display2"]


# ---------------------------------------------------------------------------
# DPI


def test_dpi_validation_and_fanout(monkeypatch):
    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 0, ""

    monkeypatch.setattr("selkies_tpu.display.dpi._have", lambda t: True)
    mgr = DpiManager(runner=runner)
    assert mgr.set_dpi(120)
    joined = [" ".join(c) for c in calls]
    assert any("Xft.dpi: 120" in j for j in joined)
    assert any("/Xft/DPI" in j and "120" in j for j in joined)
    assert any("text-scaling-factor 1.25" in j for j in joined)
    with pytest.raises(ValueError):
        mgr.set_dpi(5)

    calls.clear()
    assert mgr.set_cursor_size(48)
    joined = [" ".join(c) for c in calls]
    assert any("cursor-size 48" in j for j in joined)
    assert any("Xcursor.size: 48" in j for j in joined)
    with pytest.raises(ValueError):
        mgr.set_cursor_size(0)
