"""Batched H.264 dispatch: the headline-path guarantees.

The 46 fps config-2 number rides dispatch_batch/submit_batch
(h264_device.encode_frame_p_batch_rgb — the reference chain inside one
program); these tests pin the claims BASELINE.md makes about it:
bitstreams bit-identical to sequential encoding, IDR recovery through
the single-frame path, partial batches, two-tier head prefixes, and the
undershoot fallback.
"""

import numpy as np
import pytest

from selkies_tpu.encoder.h264 import H264StripeEncoder
from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

W, H = 128, 96


def frames_seq(n, seed=0, still_after=None):
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 256, (H, W, 3), np.uint8)
    out = []
    for i in range(n):
        k = i if still_after is None else min(i, still_after)
        out.append(np.roll(base, 3 * k, axis=0))
    return out


def annexbs(stripes):
    return [s.annexb for s in stripes]


def encode_sequential(frames, key_at=()):
    enc = H264StripeEncoder(W, H, stripe_height=32)
    out = []
    for i, f in enumerate(frames):
        if i in key_at:
            enc.request_keyframe()
        out.append(enc.encode_frame(f))
    return out


def encode_batched(frames, batch, key_at=(), use_submit_batch=False):
    enc = H264StripeEncoder(W, H, stripe_height=32)
    pipe = PipelinedH264Encoder(enc, depth=4 * batch, batch=batch)
    got = {}
    if use_submit_batch:
        import jax.numpy as jnp
        for i in range(0, len(frames), batch):
            chunk = frames[i:i + batch]
            if len(chunk) == batch:
                pipe.submit_batch(jnp.stack([jnp.asarray(f)
                                             for f in chunk]))
            else:
                for f in chunk:
                    pipe.submit(f)
            for seq, s in pipe.poll(flush_partial=False):
                got[seq] = s
    else:
        for i, f in enumerate(frames):
            if i in key_at:
                for seq, s in pipe.flush():
                    got[seq] = s
                pipe.request_keyframe()
            pipe.submit(f)
            for seq, s in pipe.poll(flush_partial=False):
                got[seq] = s
    for seq, s in pipe.flush():
        got[seq] = s
    assert len(got) == len(frames)
    return [got[i] for i in range(len(frames))]


def test_batch_bitstreams_match_sequential():
    frames = frames_seq(9)
    ref = encode_sequential(frames)
    got = encode_batched(frames, batch=3)
    for i in range(len(frames)):
        assert annexbs(ref[i]) == annexbs(got[i]), f"frame {i}"


def test_submit_batch_matches_sequential():
    frames = frames_seq(8)
    ref = encode_sequential(frames)
    got = encode_batched(frames, batch=4, use_submit_batch=True)
    for i in range(len(frames)):
        assert annexbs(ref[i]) == annexbs(got[i]), f"frame {i}"


def test_partial_batch_and_midstream_idr_match_sequential():
    frames = frames_seq(8)
    ref = encode_sequential(frames, key_at=(5,))
    got = encode_batched(frames, batch=3, key_at=(5,))
    for i in range(len(frames)):
        assert annexbs(ref[i]) == annexbs(got[i]), f"frame {i}"
    # the mid-stream keyframe really landed
    assert any(s.is_key for s in got[5])


def test_idr_recovery_avoids_batch_program(monkeypatch):
    """While any stripe needs an IDR, dispatch_batch must ride the
    already-compiled single-frame path, never a fresh (B-1)-shaped
    batched program."""
    import jax.numpy as jnp

    import selkies_tpu.encoder.h264_device as dev

    enc = H264StripeEncoder(W, H, stripe_height=32)
    calls = []
    for name in ("encode_frame_p_batch_rgb", "encode_frame_p_batch_cavlc_rgb"):
        real = getattr(dev, name)

        def spy(*a, _real=real, **k):
            calls.append(a[0].shape[0])
            return _real(*a, **k)

        monkeypatch.setattr(dev, name, spy)
    frames = frames_seq(4)
    rgbs = jnp.stack([jnp.asarray(f) for f in frames])
    pends = enc.dispatch_batch(rgbs, fetch=True)   # first call: IDR path
    assert calls == []                             # no batch program ran
    for p in pends:
        enc.harvest(p)
    pends = enc.dispatch_batch(rgbs, fetch=True)   # steady state
    assert calls == [4]


def test_two_tier_prefix_shrinks_for_static_content():
    """Static frames must ship the small head, not the worst-case one
    (code-review r3: a fixed large prefix costs 10-30x the D2H bytes on
    an idle desktop). Uses a geometry large enough that the two tiers
    are distinct buckets."""
    rng = np.random.default_rng(3)
    base = rng.integers(0, 256, (256, 320, 3), np.uint8)
    frames = [np.roll(base, 5 * min(i, 2), axis=0) for i in range(8)]
    enc = H264StripeEncoder(320, 256, stripe_height=32)
    assert enc._prefix_small < enc._batch_prefix
    lens = []
    for f in frames:
        p = enc.dispatch(f, fetch=True)
        enc.harvest(p)
        if not p.is_idr:
            lens.append(p.head_len)
    # busy frames ship the large head, quiet frames re-tier to small
    assert lens[0] == enc._batch_prefix
    assert lens[-1] == enc._prefix_small


def test_batch_undershoot_recovers_exactly():
    """Force a tiny large-tier prefix so every batch frame undershoots:
    the flat16 fallback must still produce bitstreams identical to
    sequential encoding."""
    frames = frames_seq(7)
    ref = encode_sequential(frames)

    enc = H264StripeEncoder(W, H, stripe_height=32)
    enc._batch_prefix = enc._bucket(enc._fixed_bytes + 64)
    enc._prefix_small = enc._batch_prefix
    pipe = PipelinedH264Encoder(enc, depth=12, batch=3)
    got = {}
    for f in frames:
        pipe.submit(f)
        for seq, s in pipe.poll(flush_partial=False):
            got[seq] = s
    for seq, s in pipe.flush():
        got[seq] = s
    for i in range(len(frames)):
        assert annexbs(ref[i]) == annexbs(got[i]), f"frame {i}"


def test_flush_drains_partial_batch_buffer():
    """flush() must dispatch and drain a tail smaller than ``batch``
    immediately — with the poll deadline pushed out of reach, the only
    way the buffered frames can exit is flush() itself draining
    ``_batch_frames``."""
    frames = frames_seq(5)
    ref = encode_sequential(frames)
    enc = H264StripeEncoder(W, H, stripe_height=32)
    pipe = PipelinedH264Encoder(enc, depth=12, batch=3,
                                batch_deadline_s=3600.0)
    got = {}
    for f in frames:
        pipe.submit(f)          # one full batch dispatches; 2 stay buffered
    assert len(pipe._batch_frames) == 2
    for seq, s in pipe.flush():
        got[seq] = s
    assert sorted(got) == list(range(len(frames)))
    assert not pipe._batch_frames and pipe.n_inflight == 0
    for i in range(len(frames)):
        assert annexbs(ref[i]) == annexbs(got[i]), f"frame {i}"


def test_me_backends_agree(monkeypatch):
    """pallas / chunked-xla / scan backends produce identical bitstreams
    (the bit-identical-winners contract of ops/pallas_me.py). The
    backend is a static jit arg, so flipping it mid-process takes effect
    (code-review r3: env read at trace time was invisible to the cache).
    """
    import selkies_tpu.encoder.h264_device as dev

    frames = frames_seq(4, seed=7)
    res = {}
    for backend in ("pallas", "xla", "scan"):
        enc = H264StripeEncoder(W, H, stripe_height=32)
        monkeypatch.setattr(dev, "_me_backend", lambda b=backend: b)
        res[backend] = [annexbs(enc.encode_frame(f)) for f in frames]
    assert res["pallas"] == res["xla"] == res["scan"]
