"""RTP/RTCP codec, H.264/Opus payloader, jitter buffer, and GCC tests
(parity targets: vendored aiortc stack, SURVEY.md §2.4; GCC element,
legacy/gstwebrtc_app.py:1555)."""

import struct

import pytest

from selkies_tpu.webrtc.h264 import (H264Depayloader, H264Payloader,
                                     split_annexb)
from selkies_tpu.webrtc.jitterbuffer import JitterBuffer
from selkies_tpu.webrtc.opus import OpusDepayloader, OpusPayloader
from selkies_tpu.webrtc.rate import (DelayBasedEstimator, GccEstimator,
                                     LossBasedEstimator)
from selkies_tpu.webrtc.rtp import (RtcpBye, RtcpNack, RtcpPli,
                                    RtcpReceiverReport, RtcpRemb, RtcpSdes,
                                    RtcpSenderReport, RtcpTwcc, ReceiverReport,
                                    RtpPacket, is_rtcp, parse_rtcp,
                                    pack_abs_send_time, pack_playout_delay,
                                    unpack_abs_send_time, unwrap_seq)


# ------------------------------------------------------------------ RTP


def test_rtp_roundtrip_basic():
    p = RtpPacket(payload_type=96, sequence_number=1234, timestamp=567890,
                  ssrc=0xDEADBEEF, payload=b"hello", marker=1)
    q = RtpPacket.parse(p.serialize())
    assert (q.payload_type, q.sequence_number, q.timestamp, q.ssrc,
            q.payload, q.marker) == (96, 1234, 567890, 0xDEADBEEF, b"hello", 1)


def test_rtp_roundtrip_extensions_and_csrc():
    p = RtpPacket(payload_type=111, sequence_number=7, timestamp=1,
                  ssrc=42, payload=b"x" * 100, csrc=[1, 2],
                  extensions={3: pack_abs_send_time(12.5),
                              5: struct.pack("!H", 999)})
    q = RtpPacket.parse(p.serialize())
    assert q.csrc == [1, 2]
    assert abs(unpack_abs_send_time(q.extensions[3]) - 12.5) < 1e-4
    assert struct.unpack("!H", q.extensions[5])[0] == 999
    assert q.payload == b"x" * 100


def test_rtp_padding():
    p = RtpPacket(payload_type=0, payload=b"abc", padding=4)
    q = RtpPacket.parse(p.serialize())
    assert q.payload == b"abc" and q.padding == 4


def test_unwrap_seq():
    assert unwrap_seq(-1, 5) == 5
    assert unwrap_seq(65534, 1) == 65537
    assert unwrap_seq(65537, 65534) == 65534
    assert unwrap_seq(100, 99) == 99


def test_playout_delay_zero():
    assert pack_playout_delay(0, 0) == b"\x00\x00\x00"


# ------------------------------------------------------------------ RTCP


def test_rtcp_sr_rr_roundtrip():
    rr = ReceiverReport(ssrc=7, fraction_lost=12, packets_lost=-3,
                        highest_sequence=70000, jitter=55, lsr=1, dlsr=2)
    sr = RtcpSenderReport(ssrc=99, ntp_time=0x0102030405060708,
                          rtp_time=12345, packet_count=10, octet_count=999,
                          reports=[rr])
    out = parse_rtcp(sr.serialize())
    assert len(out) == 1
    got = out[0]
    assert isinstance(got, RtcpSenderReport)
    assert got.ntp_time == 0x0102030405060708
    assert got.reports[0].packets_lost == -3
    assert got.reports[0].fraction_lost == 12

    rrp = RtcpReceiverReport(ssrc=1, reports=[rr])
    got = parse_rtcp(rrp.serialize())[0]
    assert isinstance(got, RtcpReceiverReport)
    assert got.reports[0].highest_sequence == 70000


def test_rtcp_compound_and_demux():
    sr = RtcpSenderReport(ssrc=9).serialize()
    sdes = RtcpSdes(items=[(9, "user@host")]).serialize()
    bye = RtcpBye(sources=[9]).serialize()
    compound = sr + sdes + bye
    assert is_rtcp(compound)
    pkts = parse_rtcp(compound)
    assert [type(p).__name__ for p in pkts] == [
        "RtcpSenderReport", "RtcpSdes", "RtcpBye"]
    assert pkts[1].items[0][1] == "user@host"
    media = RtpPacket(payload_type=96, payload=b"z").serialize()
    assert not is_rtcp(media)


def test_rtcp_nack_blp():
    n = RtcpNack(sender_ssrc=1, media_ssrc=2, lost=[100, 101, 110, 200])
    got = parse_rtcp(n.serialize())[0]
    assert isinstance(got, RtcpNack)
    assert set(got.lost) == {100, 101, 110, 200}


def test_rtcp_pli_fir():
    pli = RtcpPli(sender_ssrc=5, media_ssrc=6)
    got = parse_rtcp(pli.serialize())[0]
    assert isinstance(got, RtcpPli)
    assert (got.sender_ssrc, got.media_ssrc) == (5, 6)


def test_rtcp_remb_roundtrip():
    for rate in (150_000, 2_500_000, 25_000_000):
        r = RtcpRemb(sender_ssrc=3, bitrate=rate, ssrcs=[10, 11])
        got = parse_rtcp(r.serialize())[0]
        assert isinstance(got, RtcpRemb)
        assert got.ssrcs == [10, 11]
        assert abs(got.bitrate - rate) / rate < 0.01


def test_rtcp_twcc_roundtrip():
    base_us = 64000 * 100
    received = [(100, base_us), (101, base_us + 250), (102, None),
                (103, base_us + 10_000)]
    t = RtcpTwcc(sender_ssrc=1, media_ssrc=2, base_seq=100, fb_count=7,
                 ref_time=100, received=received)
    got = parse_rtcp(t.serialize())[0]
    assert isinstance(got, RtcpTwcc)
    assert got.base_seq == 100 and got.fb_count == 7
    seqs = [s for s, _ in got.received]
    assert seqs == [100, 101, 102, 103]
    assert got.received[2][1] is None
    assert got.received[0][1] == base_us
    assert abs(got.received[3][1] - (base_us + 10_000)) < 250


# ------------------------------------------------------------------ H264


def make_au():
    sps = bytes([0x67, 1, 2, 3])
    pps = bytes([0x68, 4, 5])
    idr = bytes([0x65]) + bytes(range(256)) * 20  # 5121 bytes
    return b"\x00\x00\x00\x01" + sps + b"\x00\x00\x01" + pps \
        + b"\x00\x00\x00\x01" + idr, [sps, pps, idr]


def test_split_annexb():
    au, nals = make_au()
    assert split_annexb(au) == nals


def test_h264_payload_roundtrip():
    au, nals = make_au()
    pay = H264Payloader(mtu=1200)
    pkts = pay.packetize(au, ssrc=1, payload_type=102,
                         sequence_number=10, timestamp=3000)
    assert pkts[-1].marker == 1
    assert all(len(p.payload) <= 1200 for p in pkts)
    assert len({p.timestamp for p in pkts}) == 1
    depay = H264Depayloader()
    out = None
    for p in pkts:
        got = depay.feed(p)
        if got is not None:
            out = got
    assert out is not None
    assert split_annexb(out) == nals


def test_h264_fua_mid_loss_drops_only_fragmented_nal():
    au, nals = make_au()
    pay = H264Payloader(mtu=500)
    pkts = pay.packetize(au, ssrc=1, payload_type=102,
                         sequence_number=0, timestamp=0)
    # drop one middle FU-A fragment
    fua = [i for i, p in enumerate(pkts) if p.payload[0] & 0x1F == 28]
    assert len(fua) >= 3
    del pkts[fua[1]]
    depay = H264Depayloader()
    out = None
    for p in pkts:
        got = depay.feed(p)
        if got is not None:
            out = got
    # corrupted large NAL is present-but-damaged or absent; SPS/PPS survive
    assert out is not None
    recovered = split_annexb(out)
    assert nals[0] in recovered and nals[1] in recovered


def test_opus_payloader():
    pay = OpusPayloader()
    pkts = pay.packetize(b"opusframe", ssrc=2, payload_type=111,
                         sequence_number=1, timestamp=960)
    assert len(pkts) == 1
    assert OpusDepayloader().feed(pkts[0]) == b"opusframe"


# ------------------------------------------------------------------ jitter


def test_jitterbuffer_reorder_and_missing():
    jb = JitterBuffer()
    mk = lambda s: RtpPacket(sequence_number=s, payload=bytes([s & 0xFF]))
    assert [p.sequence_number for p in jb.add(mk(10))] == [10]
    assert jb.add(mk(12)) == []
    assert jb.missing() == [11]
    out = jb.add(mk(11))
    assert [p.sequence_number for p in out] == [11, 12]
    assert jb.missing() == []


def test_jitterbuffer_wraparound():
    jb = JitterBuffer()
    jb.add(RtpPacket(sequence_number=65535))
    out = jb.add(RtpPacket(sequence_number=0))
    assert [p.sequence_number for p in out] == [0]


def test_jitterbuffer_late_packet_ignored():
    jb = JitterBuffer()
    jb.add(RtpPacket(sequence_number=5))
    jb.add(RtpPacket(sequence_number=6))
    assert jb.add(RtpPacket(sequence_number=5)) == []


# ------------------------------------------------------------------ GCC


def test_delay_estimator_grows_when_uncongested():
    est = DelayBasedEstimator(start_bitrate=1_000_000)
    t = 0.0
    for i in range(500):
        # send and receive in lockstep: no queuing delay
        est.add_packet(send_ms=t, arrival_ms=t + 20.0, size=1200)
        t += 6.0
    assert est.bitrate > 1_000_000


def test_delay_estimator_backs_off_under_congestion():
    est = DelayBasedEstimator(start_bitrate=5_000_000)
    t = 0.0
    queue = 0.0
    for i in range(600):
        queue += 1.2   # queue grows 1.2 ms per packet: persistent overuse
        est.add_packet(send_ms=t, arrival_ms=t + 20.0 + queue, size=1200)
        t += 6.0
    assert est.bitrate < 5_000_000


def test_loss_estimator():
    l = LossBasedEstimator(1_000_000)
    for _ in range(10):
        l.update(0.0)
    grown = l.bitrate
    assert grown > 1_000_000
    for _ in range(10):
        l.update(0.5)
    assert l.bitrate < grown


def test_gcc_combined_takes_min():
    g = GccEstimator(2_000_000)
    g.add_loss_report(0.5)
    assert g.bitrate == g.loss.bitrate < 2_000_000


def test_rtcp_sdes_multiple_chunks():
    # regression: chunk padding must not eat the next chunk's SSRC
    from selkies_tpu.webrtc.rtp import RtcpSdes
    s = RtcpSdes(items=[(9, "a"), (7, "b"), (0x01020304, "ccc")])
    got = parse_rtcp(s.serialize())[0]
    assert got.items == [(9, "a"), (7, "b"), (0x01020304, "ccc")]


def test_h264_fua_gap_resets_reassembly():
    au, nals = make_au()
    pay = H264Payloader(mtu=500)
    pkts = pay.packetize(au, ssrc=1, payload_type=102,
                         sequence_number=0, timestamp=0)
    fua = [i for i, p in enumerate(pkts) if p.payload[0] & 0x1F == 28]
    del pkts[fua[1]]
    depay = H264Depayloader()
    out = None
    for p in pkts:
        got = depay.feed(p)
        if got is not None:
            out = got
    recovered = split_annexb(out)
    # the fragmented IDR must be absent entirely, not spliced corrupt
    assert nals[0] in recovered and nals[1] in recovered
    assert all(len(n) < 1000 for n in recovered)
