"""Async pipeline driver + donated staging ring (ISSUE 12).

Covers the tentpole's safety obligations, not its throughput claims
(bench.py measures those on the real chip):

* in-flight depth is bounded — a full pipeline backpressures at the
  submit edge instead of growing without limit;
* ``flush()`` drains deterministically, including when the drain
  errors mid-way;
* donated staging buffers are never read (or re-donated) after
  donation — the ring's use-after-donate guard falls back to a fresh
  allocation instead;
* a supervisor-style restart mid-flight (close + rebuild) neither
  deadlocks nor leaks a ring slot;
* the batch deadline re-arms per submit, so slow or paused-then-resumed
  streams return to full ``fetch_group`` batching (the
  PipelinedH264Encoder pause-degradation edge);
* slow-marked soak: ~10 s under ``fetch.hang`` chaos with no wedge and
  no monotonic in-flight growth.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np
import pytest

from selkies_tpu.encoder.async_driver import AsyncEncodeDriver
from selkies_tpu.encoder.h264_device import StagingRing
from selkies_tpu.robustness import FaultInjector


#: geometries match test_h264_batch (128x96, stripe 32, batch 3) and
#: test_jpeg_encoder (160x128, stripe 64): in a full tier-1 run the jit
#: executables are already compiled and these tests ride the cache
def _frame(h=128, w=160, seed=0):
    return np.random.RandomState(seed).randint(0, 255, (h, w, 3), np.uint8)


# ---------------------------------------------------------------------------
# staging ring


def test_staging_ring_ping_pongs_and_releases():
    ring = StagingRing(depth=2)
    a, ta = ring.stage(_frame(seed=1))
    assert ta is not None and ring.in_use == 1
    b, tb = ring.stage(_frame(seed=2))
    assert tb is not None and ring.in_use == 2
    np.testing.assert_array_equal(np.asarray(a), _frame(seed=1))
    np.testing.assert_array_equal(np.asarray(b), _frame(seed=2))
    ring.release(ta)
    ring.release(tb)
    assert ring.in_use == 0
    # the freed slots are reused (donated) in rotation
    c, tc = ring.stage(_frame(seed=3))
    assert tc == ta
    np.testing.assert_array_equal(np.asarray(c), _frame(seed=3))
    assert ring.stalls_total == 0


def test_use_after_donate_guard_never_donates_busy_slot():
    """A slot whose ticket is still held must NOT be donated: the guard
    allocates fresh instead (counted), and the busy slots' arrays stay
    readable — the in-flight batch that references them is safe."""
    ring = StagingRing(depth=2)
    a, ta = ring.stage(_frame(seed=1))
    b, tb = ring.stage(_frame(seed=2))
    c, tc = ring.stage(_frame(seed=3))     # ring exhausted → fallback
    assert tc is None
    assert ring.stalls_total == 1
    assert ring.in_use == 2                # fallback holds no slot
    # the would-be-donated slot was not touched: both staged arrays are
    # still alive and bit-exact
    np.testing.assert_array_equal(np.asarray(a), _frame(seed=1))
    np.testing.assert_array_equal(np.asarray(b), _frame(seed=2))
    np.testing.assert_array_equal(np.asarray(c), _frame(seed=3))
    ring.release(ta)
    _, td = ring.stage(_frame(seed=4))     # freed slot donates again
    assert td == ta
    ring.release(tb)
    ring.release(td)
    ring.release(None)                      # fallback ticket is a no-op
    assert ring.in_use == 0


def test_staging_ring_shape_change_starts_fresh_lane():
    ring = StagingRing(depth=2)
    _, t0 = ring.stage(_frame(96, 128))
    assert ring.in_use == 1
    staged, t1 = ring.stage(_frame(64, 64))     # resize: new lane
    assert staged.shape == (64, 64, 3)
    assert ring.in_use == 1 and t1 is not None


def test_stale_ticket_from_retired_lane_is_a_noop():
    """A ticket issued before a shape change must NOT free the new
    lane's same-index slot: that slot's array may ride an in-flight
    batch, and freeing it would let the next stage() donate (delete)
    a live buffer."""
    ring = StagingRing(depth=2)
    _, ta = ring.stage(_frame(96, 128))         # lane A, slot 0
    _, tb = ring.stage(_frame(64, 64))          # lane B, slot 0 (A retired)
    assert ring.in_use == 1
    ring.release(ta)                            # stale A-ticket: no-op
    assert ring.in_use == 1
    _, tc = ring.stage(_frame(64, 64))          # lane B, slot 1
    _, td = ring.stage(_frame(64, 64))          # exhausted → guard, not donate
    assert td is None and ring.stalls_total == 1
    ring.release(tb)
    ring.release(tc)
    assert ring.in_use == 0


# ---------------------------------------------------------------------------
# driver on a stub pipe (no jax — pure threading semantics)


class _StubPipe:
    """Pipelined-encoder lookalike with a controllable completion gate:
    while the gate is cleared, 'fetches' never land, so submit() blocks
    once the depth is reached — the shape of a stalled transport."""

    def __init__(self, depth=3, fail_on=()):
        self.depth = depth
        self.metrics = None
        self.gate = threading.Event()
        self.gate.set()
        self._inflight: deque = deque()
        self._ready: list = []
        self._seq = 0
        self.fail_on = set(fail_on)
        self.closed = False

    @property
    def n_inflight(self):
        return len(self._inflight)

    def submit(self, frame):
        while len(self._inflight) >= self.depth:
            # like the real pipelines: a full submit harvests the oldest
            # into the ready list for the next poll/flush
            self._ready.append(self._drain_one())
        if self._seq in self.fail_on:
            self._seq += 1
            raise RuntimeError("injected submit failure")
        seq = self._seq
        self._seq += 1
        self._inflight.append(seq)
        return seq

    def _drain_one(self):
        self.gate.wait()
        return (self._inflight.popleft(), ["stripe"])

    def poll(self, flush_partial=True):
        out, self._ready = self._ready, []
        while self._inflight and self.gate.is_set():
            out.append(self._drain_one())
        return out

    def flush(self):
        out, self._ready = self._ready, []
        while self._inflight:
            out.append(self._drain_one())
        return out

    def stats(self):
        return {"frames": self._seq}

    def close(self):
        self.closed = True
        self._inflight.clear()


def test_driver_bounds_inflight_and_backpressures():
    pipe = _StubPipe(depth=3)
    pipe.gate.clear()                       # nothing ever completes
    drv = AsyncEncodeDriver(pipe, submit_depth=4)
    try:
        accepted = dropped = 0
        for i in range(50):
            if drv.try_submit(i) is not None:
                accepted += 1
            else:
                dropped += 1
            time.sleep(0.005)
        # the pipe holds at most depth; the queue at most submit_depth;
        # +1 for the frame the driver thread may hold between the two
        assert pipe.n_inflight <= pipe.depth
        assert accepted <= pipe.depth + 4 + 1
        assert dropped > 0
        assert drv.frames_dropped_total == dropped
    finally:
        pipe.gate.set()
        drv.close()                          # non-blocking teardown
    drv._thread.join(timeout=10.0)           # thread reaps itself
    assert not drv._thread.is_alive()
    assert pipe.closed                       # thread-side cleanup ran


def test_driver_flush_drains_deterministically_in_order():
    pipe = _StubPipe(depth=4)
    drv = AsyncEncodeDriver(pipe, submit_depth=16)
    try:
        seqs = [drv.try_submit(i) for i in range(9)]
        assert all(s is not None for s in seqs)
        out = drv.flush()
        got = [s for s, _ in out]
        assert got == seqs                   # everything, in order
        assert drv.flush() == []             # drained means drained
    finally:
        drv.close()


def test_driver_flush_survives_submit_errors():
    pipe = _StubPipe(depth=4, fail_on={2})
    drv = AsyncEncodeDriver(pipe, submit_depth=16)
    errors = []
    drv.on_error = errors.append
    try:
        for i in range(5):
            assert drv.try_submit(i) is not None
        out = drv.flush()
        # frame 2 died; the other four complete with the RIGHT seqs
        assert len(out) == 4
        assert [s for s, _ in out] == [0, 1, 3, 4]
        assert drv.encode_errors_total >= 1
        assert errors and isinstance(errors[0], RuntimeError)
    finally:
        drv.close()


# ---------------------------------------------------------------------------
# driver on the real pipelines


def _jpeg_driver(**kw):
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    pipe = PipelinedJpegEncoder(JpegStripeEncoder(160, 128), depth=3,
                                fetch_group=2)
    return AsyncEncodeDriver(pipe, **kw), pipe


def test_driver_streams_real_jpeg_and_reports_gauges():
    drv, pipe = _jpeg_driver()
    try:
        want = 6
        sent = 0
        deadline = time.monotonic() + 60.0
        while sent < want and time.monotonic() < deadline:
            if drv.try_submit(_frame(seed=sent)) is not None:
                sent += 1
            time.sleep(0.01)
        out = drv.flush()
        assert len(out) == sent
        assert all(stripes for _s, stripes in out)   # every frame emitted
        st = drv.stats()
        for key in ("inflight_batches", "inflight_batches_max",
                    "dispatch_p50_ms", "fetch_wait_p50_ms",
                    "frames_dropped", "encode_errors"):
            assert key in st
        assert st["inflight_batches"] == 0           # drained
        assert st["dispatch_p50_ms"] > 0.0
    finally:
        drv.close()


def test_restart_midflight_releases_ring_and_recovers():
    """Supervisor-style restart: close() with work in flight must return
    promptly, leave no busy staging slot behind, and a rebuilt driver
    must stream normally (the PR 2 restart path rebuilds the encoder)."""
    drv, pipe = _jpeg_driver()
    for i in range(3):
        drv.try_submit(_frame(seed=i))
    t0 = time.monotonic()
    drv.close()                              # mid-flight teardown
    assert time.monotonic() - t0 < 1.0       # close never blocks the loop
    drv._thread.join(timeout=30.0)           # thread reaps itself
    assert not drv._thread.is_alive()
    assert pipe._staging.in_use == 0         # no leaked ring slot
    # rebuilt pipeline streams fine (fresh ring, fresh thread)
    drv2, pipe2 = _jpeg_driver()
    try:
        sent = 0
        deadline = time.monotonic() + 60.0
        while sent < 3 and time.monotonic() < deadline:
            if drv2.try_submit(_frame(seed=sent)) is not None:
                sent += 1
            time.sleep(0.01)
        assert len(drv2.flush()) == sent
        assert pipe2._staging.in_use == 0
    finally:
        drv2.close()


# ---------------------------------------------------------------------------
# batch deadline re-arm (the pause-degradation edge)


def test_deadline_flush_rearms_group_for_resumed_stream():
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    enc = H264StripeEncoder(128, 96, stripe_height=32)
    calls = {"solo": 0, "batch": 0}
    orig_d, orig_db = enc.dispatch, enc.dispatch_batch

    def d(frame, fetch=True):
        calls["solo"] += 1
        return orig_d(frame, fetch=fetch)

    def db(rgbs, fetch=True):
        calls["batch"] += 1
        return orig_db(rgbs, fetch=fetch)

    enc.dispatch, enc.dispatch_batch = d, db
    pipe = PipelinedH264Encoder(enc, depth=12, batch=3,
                                batch_deadline_s=0.15)
    for i in range(4):                      # warm: IDR + compiles
        pipe.submit(_frame(96, 128, seed=i))
    pipe.flush()
    calls["solo"] = calls["batch"] = 0

    # a stream ticking slower than deadline/batch still forms full
    # batches: the deadline re-arms on every submit (pause detection),
    # it does not run down from the group's first frame
    for i in range(9):
        pipe.submit(_frame(96, 128, seed=i))
        time.sleep(0.05)                    # 0.05 < 0.15 — still live
        pipe.poll(flush_partial=False)
    pipe.flush()
    assert calls["batch"] == 3
    assert calls["solo"] == 0

    # a PAUSE flushes the partial group (liveness)...
    calls["solo"] = calls["batch"] = 0
    pipe.submit(_frame(96, 128, seed=100))
    deadline = time.monotonic() + 10.0
    while not calls["solo"] and time.monotonic() < deadline:
        time.sleep(0.03)
        pipe.poll(flush_partial=False)
    assert calls["solo"] == 1               # partial shipped solo
    pipe.flush()

    # ...and the RESUMED stream returns to full batching immediately
    calls["solo"] = calls["batch"] = 0
    for i in range(6):
        pipe.submit(_frame(96, 128, seed=i))
        pipe.poll(flush_partial=False)
    pipe.flush()
    assert calls["batch"] == 2
    assert calls["solo"] == 0


def test_staleness_bounded_under_sub_deadline_cadence():
    """Frame staleness is intrinsically bounded at batch * deadline:
    every inter-submit gap under the deadline means the batch fills
    within (batch - 1) such gaps — a steadily ticking stream's frames
    always ship, batched, within the bound."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    enc = H264StripeEncoder(128, 96, stripe_height=32)
    pipe = PipelinedH264Encoder(enc, depth=12, batch=3,
                                batch_deadline_s=0.08)
    for i in range(6):                       # warm solo + batch programs
        pipe.submit(_frame(96, 128, seed=i))
    pipe.flush()
    t0 = time.monotonic()
    shipped_at = None
    for i in range(12):
        pipe.submit(_frame(96, 128, seed=i))
        time.sleep(0.04)                     # < deadline: never a pause
        if pipe.poll(flush_partial=False):
            shipped_at = time.monotonic() - t0
            break
    # the first full batch ships well inside batch * deadline worth of
    # submit gaps (plus device time), never stranded
    assert shipped_at is not None
    pipe.flush()


def test_midpass_harvest_error_preserves_completed_frames_and_tickets():
    """A harvest raising mid-drain must not discard frames already
    completed in the same pass, and the failing frame's staging ticket
    must be released — under the async driver this is a steady-state
    catch-and-continue path, so a leak here accumulates forever."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedH264Encoder

    enc = H264StripeEncoder(128, 96, stripe_height=32)
    pipe = PipelinedH264Encoder(enc, depth=8, fetch_group=2)
    pipe.submit(_frame(96, 128, seed=0))     # warm (IDR + compiles)
    pipe.submit(_frame(96, 128, seed=1))
    pipe.flush()

    orig = enc.harvest
    calls = {"n": 0}

    def harvest(p, host=None):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected harvest failure")
        return orig(p, host=host)

    enc.harvest = harvest
    pipe.submit(_frame(96, 128, seed=2))     # seq 2
    pipe.submit(_frame(96, 128, seed=3))     # seq 3 — one fetch group
    with pytest.raises(RuntimeError):
        pipe.flush()
    enc.harvest = orig
    # seq 2 completed before the failure and must still surface
    assert [s for s, _ in pipe.flush()] == [2]
    # the failed frame's ring slot was freed, not leaked
    assert pipe._staging.in_use == 0


# ---------------------------------------------------------------------------
# soak (slow): fetch.hang chaos — no wedge, no monotonic in-flight growth


@pytest.mark.slow
def test_soak():
    faults = FaultInjector()
    drv, pipe = _jpeg_driver()
    drv.faults = faults
    try:
        t_end = time.monotonic() + 10.0
        inflight_high = 0
        completed = 0
        i = 0
        next_arm = time.monotonic() + 0.5
        while time.monotonic() < t_end:
            if time.monotonic() >= next_arm:
                # repeated short D2H stalls at the driver's harvest site
                faults.arm("fetch.hang", times=1, arg="0.2")
                next_arm += 0.7
            drv.try_submit(_frame(seed=i % 7))
            i += 1
            completed += len(drv.poll())
            inflight_high = max(inflight_high,
                                drv.stats()["inflight_batches"])
            time.sleep(0.02)
        faults.disarm()
        completed += len(drv.flush())
        st = drv.stats()
        assert completed > 0                       # streamed through chaos
        assert inflight_high <= pipe.depth         # bounded, not monotonic
        assert st["inflight_batches"] == 0         # fully drained → no wedge
        assert pipe._staging.in_use == 0           # no leaked slots
    finally:
        drv.close()
