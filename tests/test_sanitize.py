"""Sanitizer builds of the native components (SURVEY.md §5 race detection).

The reference relies on by-construction safety plus external tooling; here
the native build system itself carries the instrumentation option
(``SELKIES_NATIVE_SANITIZE`` for the lazily built libs, ``SANITIZE=`` for
the Makefile shims), and this test actually EXECUTES the JPEG entropy
coder under AddressSanitizer and cross-checks its bitstream against the
pure-Python oracle.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _libasan() -> str:
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
    except (subprocess.SubprocessError, FileNotFoundError):
        return ""
    return out if os.path.isabs(out) and os.path.exists(out) else ""


# jax must stay unimported here: the ASAN __cxa_throw interceptor check
# fails inside jaxlib's uninstrumented nanobind, which has nothing to do
# with our code — so the child mirrors _entropy_encode_420's ctypes call
# instead of importing selkies_tpu.encoder.jpeg
CHILD = r"""
import numpy as np
from selkies_tpu.native import entropy_lib
from selkies_tpu.encoder import entropy_py
from selkies_tpu.encoder.jpeg_tables import std_tables

lib = entropy_lib()
assert lib is not None, "sanitized entropy lib failed to build"
rng = np.random.default_rng(7)
# [block_rows, block_cols, 64] zigzagged coefficient planes (4:2:0)
y = rng.integers(-128, 128, (4, 4, 64), dtype=np.int16)
cb = rng.integers(-64, 64, (2, 2, 64), dtype=np.int16)
cr = rng.integers(-64, 64, (2, 2, 64), dtype=np.int16)
dc_l, ac_l, dc_c, ac_c = std_tables()
cap = (y.size + cb.size + cr.size) * 4 + 4096
out = np.empty(cap, dtype=np.uint8)
n = lib.jpeg_encode_scan_420(
    np.ascontiguousarray(y), np.ascontiguousarray(cb),
    np.ascontiguousarray(cr), y.shape[0], y.shape[1],
    dc_l.code_arr, dc_l.len_arr, ac_l.code_arr, ac_l.len_arr,
    dc_c.code_arr, dc_c.len_arr, ac_c.code_arr, ac_c.len_arr,
    out, cap)
assert n > 0, n
got = out[:n].tobytes()
want = entropy_py.encode_scan_420(y, cb, cr)
assert got == want, "sanitized coder diverged from the python oracle"
print("SANITIZED_OK", len(got))
"""


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_entropy_coder_runs_clean_under_asan(tmp_path):
    libasan = _libasan()
    if not libasan:
        pytest.skip("libasan.so not installed")
    env = dict(os.environ)
    env["SELKIES_NATIVE_SANITIZE"] = "address"
    env["LD_PRELOAD"] = libasan
    # leak checking would flag the Python interpreter itself, not our lib
    env["ASAN_OPTIONS"] = "detect_leaks=0:abort_on_error=1"
    proc = subprocess.run(
        [sys.executable, "-c", CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "SANITIZED_OK" in proc.stdout
    san_so = os.path.join(
        REPO, "selkies_tpu", "native", "_libselkies_entropy_address.so")
    assert os.path.exists(san_so)  # cached under its own name


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("cc") is None,
                    reason="no make/cc")
def test_interposer_builds_with_sanitize_flag(tmp_path):
    src = os.path.join(REPO, "native", "interposer")
    build = tmp_path / "interposer"
    shutil.copytree(src, build)
    proc = subprocess.run(
        ["make", "-B", "SANITIZE=address"], cwd=build,  # -B: a prebuilt .so
        capture_output=True, text=True, timeout=120,    # ships in the repo
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    so = build / "selkies_joystick_interposer.so"
    assert so.exists()
    syms = subprocess.run(["nm", "-D", str(so)], capture_output=True,
                          text=True, timeout=30).stdout
    assert "__asan" in syms  # instrumentation actually present
