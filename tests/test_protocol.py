import pytest

from selkies_tpu.protocol import (
    AudioChunk,
    FrameId,
    FullFrame,
    VideoStripe,
    pack_audio_chunk,
    pack_full_frame,
    pack_h264_stripe,
    pack_jpeg_stripe,
    parse_text_message,
    unpack_binary,
)


def test_jpeg_stripe_layout():
    b = pack_jpeg_stripe(frame_id=300, y_start=128, jpeg=b"\xff\xd8data")
    # exact byte layout the client demuxes: selkies-core.js:2908-2915
    assert b[0] == 0x03 and b[1] == 0x00
    assert int.from_bytes(b[2:4], "big") == 300
    assert int.from_bytes(b[4:6], "big") == 128
    assert b[6:] == b"\xff\xd8data"
    f = unpack_binary(b)
    assert isinstance(f, VideoStripe)
    assert (f.frame_id, f.y_start, f.payload) == (300, 128, b"\xff\xd8data")


def test_h264_stripe_layout():
    b = pack_h264_stripe(5, 256, 1920, 64, b"\x00\x00\x01NAL", is_key=True)
    assert b[0] == 0x04 and b[1] == 0x01
    assert int.from_bytes(b[2:4], "big") == 5
    assert int.from_bytes(b[4:6], "big") == 256
    assert int.from_bytes(b[6:8], "big") == 1920
    assert int.from_bytes(b[8:10], "big") == 64
    f = unpack_binary(b)
    assert isinstance(f, VideoStripe)
    assert f.is_key and f.width == 1920 and f.height == 64


def test_full_frame_and_audio():
    b = pack_full_frame(65535, b"nal", is_key=False)
    f = unpack_binary(b)
    assert isinstance(f, FullFrame)
    assert f.frame_id == 65535 and not f.is_key and f.payload == b"nal"

    a = unpack_binary(pack_audio_chunk(b"opus"))
    assert isinstance(a, AudioChunk) and a.payload == b"opus"


def test_frame_id_wraparound():
    assert FrameId.next(65535) == 0
    assert FrameId.desync(3, 65533) == 6  # wrapped sender
    assert not FrameId.is_anomalous(3, 65533)
    assert FrameId.is_anomalous(0, 1)  # acked "ahead" of sent


def test_short_frames_rejected():
    with pytest.raises(ValueError):
        unpack_binary(b"\x03\x00\x00")
    with pytest.raises(ValueError):
        unpack_binary(b"")


@pytest.mark.parametrize(
    "raw,verb,args",
    [
        ("CLIENT_FRAME_ACK 42", "CLIENT_FRAME_ACK", ("42",)),
        ("r,1920x1080,primary", "r", ("1920x1080", "primary")),
        ("START_VIDEO", "START_VIDEO", ()),
        ("SET_NATIVE_CURSOR_RENDERING,1", "SET_NATIVE_CURSOR_RENDERING", ("1",)),
        ("kd,65", "kd", ("65",)),
        ("FILE_UPLOAD_END:a/b.txt", "FILE_UPLOAD_END", ("a/b.txt",)),
        ("cmd,xdg-open .", "cmd", ("xdg-open .",)),
        ("_f 60", "_f", ("60",)),
        ("cr", "cr", ()),
    ],
)
def test_text_grammar(raw, verb, args):
    m = parse_text_message(raw)
    assert m.verb == verb and m.args == args


def test_settings_json_body():
    m = parse_text_message('SETTINGS,{"encoder": "jpeg"}')
    assert m.verb == "SETTINGS"
    assert m.json_body == '{"encoder": "jpeg"}'


def test_file_upload_start_path_with_colons():
    m = parse_text_message("FILE_UPLOAD_START:dir/with:colon.txt:123")
    assert m.verb == "FILE_UPLOAD_START"
    assert m.args == ("dir/with:colon.txt", "123")


def test_client_binary_direction():
    from selkies_tpu.protocol import (
        FileChunk, MicChunk, pack_file_chunk, pack_mic_chunk, unpack_client_binary,
    )
    f = unpack_client_binary(pack_file_chunk(b"\x01\x02data"))
    assert isinstance(f, FileChunk) and f.payload == b"\x01\x02data"
    m = unpack_client_binary(pack_mic_chunk(b"pcm"))
    assert isinstance(m, MicChunk) and m.payload == b"pcm"


def test_cmd_with_commas_is_single_arg():
    m = parse_text_message("cmd,ffmpeg -vf scale=1280:720,fps=30")
    assert m.verb == "cmd" and m.args == ("ffmpeg -vf scale=1280:720,fps=30",)


def test_gamepad_comma_form():
    m = parse_text_message("js,c,0,Xbox,1118,654")
    assert m.verb == "js" and m.args == ("c", "0", "Xbox", "1118", "654")
