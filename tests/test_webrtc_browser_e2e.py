"""Full-stack browser E2E: the SHIPPED webrtc.js against the REAL server.

The strongest in-CI proof of the WebRTC product path: the actual
web/webrtc.js logic executes under tools/minijs, its WebSocket is
bridged to a live connection against the real SignalingServer, and its
RTCPeerConnection is bridged to a real in-repo PeerConnection (the
browser-engine stand-in, running real ICE/DTLS/SRTP/SCTP over
loopback). The real WebRTCStreamingApp calls the browser peer exactly
as `selkies-tpu-webrtc` does in production:

  app(peer 0) ── SignalingServer ── webrtc.js(peer 1) ── PeerConnection

and the test asserts H.264 media arrives, the input verbs typed through
the JS client reach the server's input handler, and the clipboard
control object round-trips. Reference counterpart:
addons/gst-web/src/webrtc.js against legacy/signalling_web.py.

Threading note: minijs's ``await`` settles promises by spinning the
microtask queue synchronously, so the browser-side PeerConnection runs
on a dedicated thread loop; bridge promises re-queue a sleeping
microtask until the cross-thread future completes, and native → JS
events are marshalled back through a queue drained on the main loop
(the interpreter is not thread-safe).
"""

import asyncio
import base64
import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from web_stubs import BrowserEnv, install_webrtc_stubs  # noqa: E402
from tools.minijs import (  # noqa: E402
    JSArray, JSObject, JSPromise, NativeFunction, UNDEF, to_str)

from selkies_tpu.rtc.signaling import SignalingServer  # noqa: E402
from selkies_tpu.server.webrtc_app import WebRTCStreamingApp  # noqa: E402
from selkies_tpu.webrtc.peerconnection import PeerConnection  # noqa: E402

from test_webrtc_app import (  # noqa: E402
    FakeEncoder, FakeSource, RecordingInput, Settings)


class BridgePC:
    """webrtc.js's RTCPeerConnection, backed by the real Python stack."""

    def __init__(self, env, thread_loop):
        self._env = env
        self._tloop = thread_loop
        self.pc = None
        self.got_frames = []
        self.events = []                  # thread-safe enough: append-only
        self.connectionState = "new"
        self.ontrack = None
        self.ondatachannel = None
        self.onicecandidate = None
        self.onconnectionstatechange = None
        self._track_fired = False

        def build():
            self.pc = PeerConnection(interfaces=["127.0.0.1"])
            self.pc.video_receiver().on_frame = \
                lambda f, ts: self._native_frame(f)
            self.pc.on_channel = \
                lambda ch: self.events.append(("channel", ch))
        asyncio.run_coroutine_threadsafe(_acall(build), thread_loop).result()

    # -- promise plumbing ---------------------------------------------

    def _promise(self, coro):
        """JSPromise settled from a cross-thread future; minijs awaits by
        spinning microtasks, so a sleeping re-queueing task bridges."""
        p = JSPromise(self._env.interp)
        fut = asyncio.run_coroutine_threadsafe(coro, self._tloop)

        def pump():
            if fut.done():
                try:
                    value = fut.result()
                except Exception as exc:
                    p.reject(str(exc))
                    return
                p.resolve(UNDEF if value is None else value)
            else:
                time.sleep(0.005)
                self._env.interp.microtasks.append(pump)

        self._env.interp.microtasks.append(pump)
        return p

    # -- RTCPeerConnection surface ------------------------------------

    def setRemoteDescription(self, desc):
        sdp = to_str(self._env.get(desc, "sdp"))
        return self._promise(self.pc.set_remote_description(sdp, "offer"))

    def createAnswer(self):
        async def go():
            answer = await self.pc.create_answer()
            return JSObject({"type": "answer", "sdp": answer})
        return self._promise(go())

    def setLocalDescription(self, desc):
        return self._env.resolved(UNDEF)

    def addIceCandidate(self, cand):
        # the Python stack's SDP answers carry end-of-candidates
        return self._env.resolved(UNDEF)

    def close(self):
        if self.pc is not None:
            asyncio.run_coroutine_threadsafe(self.pc.close(), self._tloop)
        self.connectionState = "closed"

    # -- native-side events (thread-loop context) ---------------------

    def _native_frame(self, frame):
        self.got_frames.append(frame)
        if not self._track_fired:
            self._track_fired = True
            self.events.append(("track", None))

    # -- main-loop event dispatch into JS -----------------------------

    def drain_events(self):
        env = self._env
        while self.events:
            kind, payload = self.events.pop(0)
            if kind == "track" and self.ontrack not in (None, UNDEF):
                stream = JSObject({"id": "bridge-stream"})
                env.call(self.ontrack, [JSObject(
                    {"streams": JSArray([stream])})])
            elif kind == "channel":
                self._wire_channel(payload)
            elif kind == "chmsg":
                wrapper, data = payload
                onmessage = wrapper.props.get("onmessage")
                if onmessage not in (None, UNDEF):
                    text = data.decode() if isinstance(data, bytes) \
                        else str(data)
                    env.call(onmessage, [JSObject({"data": text})])

    def _wire_channel(self, ch):
        env = self._env
        wrapper = JSObject({"label": ch.label, "readyState": "open"})

        def js_send(t, a, i):
            text = to_str(a[0])
            asyncio.run_coroutine_threadsafe(
                _acall(lambda: self.pc.sctp.send(ch, text)), self._tloop)
            return UNDEF

        wrapper.props["send"] = NativeFunction(js_send, "send")
        ch.on_message = lambda data: self.events.append(
            ("chmsg", (wrapper, data)))
        if self.ondatachannel not in (None, UNDEF):
            env.call(self.ondatachannel, [JSObject({"channel": wrapper})])
        onopen = wrapper.props.get("onopen")
        if onopen not in (None, UNDEF):
            env.call(onopen, [JSObject({})])


async def _acall(fn):
    return fn()


def test_shipped_webrtc_js_full_session_against_real_server():
    # the browser's WebRTC engine lives on its own thread loop
    tloop = asyncio.new_event_loop()
    tthread = threading.Thread(target=tloop.run_forever, daemon=True)
    tthread.start()

    async def run():
        server = SignalingServer(addr="127.0.0.1", port=0)
        stask = asyncio.create_task(server.run())
        for _ in range(100):
            if server.server is not None:
                break
            await asyncio.sleep(0.01)
        uri = f"ws://127.0.0.1:{server.port}/ws"

        env = BrowserEnv(files=())
        install_webrtc_stubs(env)
        bridges = []
        env.interp.globals.vars["RTCPeerConnection"] = NativeFunction(
            lambda t, a, i: bridges.append(BridgePC(env, tloop))
            or bridges[-1], "RTCPeerConnection")
        env.load("webrtc.js")

        statuses = []
        clips = []
        video = env.document.createElement("video")
        client = env.construct(env.exports["SelkiesWebRTCClient"], [
            JSObject({
                "signalingUrl": uri,
                "video": video,
                "rtcConfig": JSObject({}),   # skip the /turn fetch
                "onStatus": NativeFunction(
                    lambda t, a, i: (statuses.append(to_str(a[0])),
                                     UNDEF)[1]),
                "onClipboard": NativeFunction(
                    lambda t, a, i: (clips.append(to_str(a[0])),
                                     UNDEF)[1]),
            })])
        env.call(env.get(client, "connect"), [], this=client)
        fake_ws = env.sockets[-1]

        import websockets
        real_ws = await websockets.connect(uri)
        fake_ws.server_open()                 # JS sends HELLO 1 <meta>
        sent_idx = 0

        async def pump_out():
            nonlocal sent_idx
            while True:
                while sent_idx < len(fake_ws.sent):
                    await real_ws.send(fake_ws.sent[sent_idx])
                    sent_idx += 1
                for b in bridges:
                    b.drain_events()
                await asyncio.sleep(0.005)

        async def pump_in():
            async for msg in real_ws:
                if isinstance(msg, str):
                    fake_ws.server_text(msg)

        pumps = [asyncio.create_task(pump_out()),
                 asyncio.create_task(pump_in())]

        recorder = RecordingInput()
        app = WebRTCStreamingApp(
            Settings(),
            encoder_factory=lambda w, h: FakeEncoder(),
            source_factory=lambda w, h, fps: FakeSource(w, h, fps),
            input_handler=recorder,
            interfaces=["127.0.0.1"])
        atask = asyncio.create_task(app.run(uri, "0", "1"))

        try:
            for _ in range(600):
                if "negotiated" in statuses:
                    break
                await asyncio.sleep(0.05)
            assert "negotiated" in statuses, statuses
            assert bridges, "RTCPeerConnection never constructed"
            bridge = bridges[0]

            # media arrives through the real ICE/DTLS/SRTP path
            for _ in range(600):
                if len(bridge.got_frames) >= 3:
                    break
                await asyncio.sleep(0.05)
            assert len(bridge.got_frames) >= 3, "no video frames"
            assert bridge.got_frames[0].startswith(b"\x00\x00\x00\x01\x67")
            assert env.get(video, "srcObject") is not UNDEF

            # input channel: JS-side send() verbs reach the server's
            # input handler through the real data channel
            for _ in range(200):
                if "input-ready" in statuses:
                    break
                await asyncio.sleep(0.05)
            assert "input-ready" in statuses, statuses
            env.call(env.get(client, "send"), ["kd,65"], this=client)
            env.call(env.get(client, "send"), ["m,10,20,0,0"],
                     this=client)
            for _ in range(200):
                if len(recorder.messages) >= 2:
                    break
                await asyncio.sleep(0.05)
            assert recorder.messages[:2] == ["kd,65", "m,10,20,0,0"]

            # clipboard control object → JS onClipboard
            app.send_json({"type": "clipboard",
                           "data": base64.b64encode(b"hi").decode()})
            for _ in range(200):
                if clips:
                    break
                await asyncio.sleep(0.05)
            assert clips == ["hi"]
        finally:
            await app.stop_pipeline()
            if bridges:
                bridges[0].close()
            for t in pumps + [atask, stask]:
                t.cancel()
            await real_ws.close()
            await server.stop()

    try:
        asyncio.run(run())
    finally:
        tloop.call_soon_threadsafe(tloop.stop)
        tthread.join(timeout=5)
