"""Metrics + flight-recorder tests (ISSUE 13).

Covers the recorder's span-leak invariant (every opened span reaches a
terminal mark — including under SELKIES_TPU_FAULTS chaos), the
trace-event export golden shape, ACK-RTT correctness through the real
ws_handler with the fake-websocket InProcessClient, the stage breakdown
riding system_health, and the hardened metrics HTTP endpoint
(/healthz, /debug/trace, non-fatal bind failure)."""

import asyncio
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from selkies_tpu.encoder.jpeg import StripeOutput
from selkies_tpu.observability import (STAGES, FlightRecorder, FrameTracer,
                                       Metrics)
from selkies_tpu.protocol import VideoStripe, unpack_binary
from selkies_tpu.robustness import InProcessClient
from selkies_tpu.server.app import StreamingApp
from selkies_tpu.server.data_server import DataStreamingServer
from selkies_tpu.settings import Settings


@pytest.fixture
def anyio_backend():
    return "asyncio"


# ---------------------------------------------------------------------------
# metrics registry


def test_metrics_render():
    m = Metrics(port=0)
    m.set_fps(60.0)
    m.set_latency(12.5)
    m.set_tpu_utilization(45.0)
    m.observe_encode(8.0, 50_000)
    m.set_clients(3)
    m.set_backpressured(1)
    m.set_webrtc_stats({"bitrate": "8000000"})
    text = m.render().decode()
    assert "fps 60.0" in text
    assert "latency 12.5" in text
    assert "tpu_utilization 45.0" in text
    assert "gpu_utilization 45.0" in text      # reference-compatible alias
    assert "connected_clients 3.0" in text
    assert 'webrtc_statistics_info{bitrate="8000000"}' in text
    assert "tpuenc_encode_ms_bucket" in text


def test_metrics_d2h_and_host_entropy_gauges():
    """ISSUE 1 satellite: the bottleneck gauges the pipelined encoders
    record must render."""
    m = Metrics(port=0)
    m.set_d2h_bytes_per_frame(12700.0)
    m.set_host_entropy_ms_per_frame(0.4)
    text = m.render().decode()
    assert "tpuenc_d2h_bytes_per_frame 12700.0" in text
    assert "tpuenc_host_entropy_ms_per_frame 0.4" in text


def test_metrics_stage_series_render():
    """ISSUE 13: the flight-recorder series render with their labels."""
    m = Metrics(port=0)
    m.observe_stage("primary", "dispatch", 4.0)
    m.observe_glass_to_glass("primary", 42.0)
    m.observe_encode_only("primary", 17.0)
    m.set_trace_open_spans(3)
    m.inc_trace_dropped("queue")
    text = m.render().decode()
    assert 'frame_stage_ms_bucket{display="primary"' in text \
        or 'frame_stage_ms_bucket{' in text
    assert 'glass_to_glass_ms_count{display="primary"}' in text
    assert 'encode_only_ms_count{display="primary"}' in text
    assert "trace_open_spans 3.0" in text
    assert 'trace_dropped_total{stage="queue"}' in text


# ---------------------------------------------------------------------------
# flight recorder core


def test_recorder_span_lifecycle_and_summary():
    clock = [0.0]
    rec = FlightRecorder(capacity=32, clock=lambda: clock[0])
    tr = rec.begin("primary", t=0.0)
    tr.mark("capture", 0.0, 0.001)
    tr.mark("dispatch", 0.001, 0.005)
    tr.mark("pack", 0.006, 0.007)
    tr.frame_id = 1
    rec.sent(tr)
    tr.mark("send", 0.008, 0.009)
    assert rec.open_spans() == 1
    clock[0] = 0.025
    out = rec.ack("primary", 1)
    assert out is tr
    assert tr.terminal == "acked"
    assert rec.open_spans() == 0
    s = rec.summary("primary")
    assert s["frames"] == 1 and s["acked"] == 1
    assert s["stages"]["dispatch"]["p50_ms"] == pytest.approx(4.0)
    # ack = send end (0.009) -> ack arrival (0.025) = 16 ms: true RTT
    assert s["stages"]["ack"]["p50_ms"] == pytest.approx(16.0)
    assert s["glass_to_glass_p50_ms"] == pytest.approx(25.0)
    # encode_only: dispatch start (0.001) -> pack end (0.007)
    assert s["encode_only_p50_ms"] == pytest.approx(6.0)


def test_recorder_terminal_marks_and_ring_bound():
    rec = FlightRecorder(capacity=16, clock=lambda: 0.0)
    # dropped frames get dropped@<stage>, empties close quietly
    t1 = rec.begin("a", t=0.0)
    rec.drop(t1, "submit")
    assert t1.terminal == "dropped@submit"
    t2 = rec.begin("a", t=0.0)
    rec.finish_empty(t2)
    assert t2.terminal == "empty"
    # double-close is idempotent
    rec.drop(t2, "send")
    assert t2.terminal == "empty"
    assert rec.open_spans() == 0
    # ring stays bounded
    for i in range(100):
        tr = rec.begin("a", t=float(i))
        rec.drop(tr, "submit")
    assert rec.open_spans() == 0
    assert rec.summary()["frames"] <= 16


def test_recorder_expiry_and_wire_id_collision():
    clock = [0.0]
    rec = FlightRecorder(capacity=32, clock=lambda: clock[0])
    stale = rec.begin("a")
    stale.mark("send", 0.0, 0.001)
    stale.frame_id = 9
    rec.sent(stale)
    # same wire id re-registered (2^16 wrap): the stale span must close
    fresh = rec.begin("a")
    fresh.frame_id = 9
    rec.sent(fresh)
    assert stale.terminal == "expired@send"
    clock[0] = 100.0
    assert rec.expire() == 1                  # fresh span aged out
    assert rec.open_spans() == 0
    assert fresh.terminal.startswith("expired@")


def test_recorder_trace_event_export_golden():
    """Deterministic clock -> exact Chrome trace-event shape (the
    contract Perfetto and tools/trace_report.py consume)."""
    rec = FlightRecorder(capacity=8, clock=lambda: 0.0)
    tr = rec.begin("primary", t=0.0)
    tr.mark("capture", 0.0, 0.002)
    tr.mark("send", 0.004, 0.0045)
    tr.frame_id = 3
    rec.sent(tr)
    rec.ack("primary", 3, t=0.01)
    data = rec.export_trace_events()
    assert data["displayTimeUnit"] == "ms"
    assert data["otherData"]["open_spans"] == 0
    xs = [e for e in data["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in data["traceEvents"] if e["ph"] == "M"]
    assert metas == [{
        "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
        "args": {"name": "display:primary"},
    }]
    assert [e["name"] for e in xs] == ["capture", "send", "ack"]
    cap = xs[0]
    assert cap == {
        "name": "capture", "cat": "frame", "ph": "X", "pid": 1,
        "tid": 3 % 64 + 1, "ts": 0.0, "dur": 2000.0,
        "args": {"frame_id": 3, "display": "primary",
                 "terminal": "acked", "span": 1},
    }
    # every event is valid for the trace_report consumer too
    from tools.trace_report import build_frames, render

    frames = build_frames(data)
    assert len(frames) == 1
    assert frames[0]["terminal"] == "acked"
    text = render(data, top=3)
    assert "glass-to-glass" in text and "capture" in text


def test_trace_report_does_not_merge_unsent_drops():
    """Dropped-before-wire frames share frame_id -1 and recycle tids mod
    64: the per-span token must keep them distinct in trace_report."""
    from tools.trace_report import build_frames

    rec = FlightRecorder(capacity=256, clock=lambda: 0.0)
    for i in range(130):                      # > 2 full tid cycles
        tr = rec.begin("a", t=float(i))
        tr.mark("capture", float(i), float(i) + 0.001)
        rec.drop(tr, "submit")
    frames = build_frames(rec.export_trace_events())
    assert len(frames) == 130
    assert all(f["total_ms"] == pytest.approx(1.0) for f in frames)


def test_mesh_submit_seq_accounts_for_inflight_window():
    """Regression (review finding): with frames in the in-flight window,
    _submit must return the seq the NEW frame will harvest under — not
    the in-flight frame's — or trace correlation shifts off by one in
    mesh steady state. Stale-generation entries (a migrated binding's
    leftovers) must NOT count: their harvests are dropped, not
    delivered."""
    from selkies_tpu.parallel.coordinator import MeshEncodeCoordinator
    from selkies_tpu.robustness import FakeMeshEncoder

    coord = MeshEncodeCoordinator(
        "session:1", 1, 64, 48, enc_factory=lambda n: FakeMeshEncoder(n),
        slots_per_lane=1, max_lanes=1)
    coord.stop()                       # no ticking: window driven by hand
    facade = coord.acquire(64, 48)
    coord.stop()
    with coord._lock:
        sess = coord._sessions[facade.sid]
        sess.seq = 5
        sess.lane.inflight_q.append(
            (object(), [(sess, 0, sess.gen)], (0.0, 0.0)))      # counts
        sess.lane.inflight_q.append(
            (object(), [(sess, 0, sess.gen - 1)], (0.0, 0.0)))  # stale
    assert facade.try_submit("frame") == 6    # 5 + 1 in-flight (live gen)
    # a second submit before the tick replaces the pending frame: drop
    assert facade.try_submit("frame2") is None


def test_frame_tracer_compat_shim():
    """The pre-recorder API stays importable and functional."""
    tr = FrameTracer(capacity=5)
    for fid in range(20):
        span = tr.begin(fid)
        span.stamps["dispatch"] = 0.001
        span.stamps["harvest"] = 0.002 + 0.0001 * fid
        tr.finish(fid)
    assert tr.summary()["frames"] == 5
    assert tr.finish(999) is None
    assert tr.percentile_ms("dispatch", "harvest", 50) >= 1.0


# ---------------------------------------------------------------------------
# served path: ACK-RTT + span closure through the real ws_handler


class FakeEncoder:
    """Minimal pipelined-encoder lookalike whose submit returns no seq —
    exercising the capture loop's FIFO trace correlation."""

    def __init__(self):
        self.submitted = 0
        self._ready = []
        self.closed = False

    def submit(self, frame):
        self.submitted += 1
        self._ready.append(
            (self.submitted,
             [StripeOutput(y_start=0, height=64,
                           jpeg=b"\xff\xd8FAKE\xff\xd9",
                           is_paintover=False)]))

    def poll(self):
        out, self._ready = self._ready, []
        return out

    def flush(self):
        return self.poll()

    def close(self):
        self.closed = True


class FakeSource:
    def __init__(self, width, height, fps):
        self.width, self.height = width, height

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return np.zeros((self.height, self.width, 3), np.uint8)


def make_server(**settings_env):
    env = {"SELKIES_PORT": "0", "SELKIES_AUDIO_ENABLED": "false"}
    env.update(settings_env)
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=lambda w, h, s, overrides=None: FakeEncoder(),
        source_factory=lambda w, h, fps, **kw: FakeSource(w, h, fps),
        host="127.0.0.1",
    )
    app.data_server = server
    return server


async def wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return False


async def open_client(server, settings_body):
    ws = InProcessClient()
    task = asyncio.create_task(server.ws_handler(ws))
    assert await wait_until(lambda: len(ws.sent) >= 2, timeout=5.0)
    ws.feed("SETTINGS," + json.dumps(settings_body))
    return ws, task


async def close_client(ws, task):
    await ws.close()
    try:
        await asyncio.wait_for(task, 5.0)
    except asyncio.TimeoutError:
        task.cancel()


SETTINGS_BODY = {"displayId": "primary", "initialClientWidth": 320,
                 "initialClientHeight": 240, "framerate": 60}


@pytest.mark.anyio
async def test_ack_rtt_closes_spans_through_real_handler():
    server = make_server()
    ws, task = await open_client(server, SETTINGS_BODY)
    try:
        assert await wait_until(lambda: len(ws.binary()) >= 3)
        # ack every delivered frame like the browser client does
        acked = set()
        for raw in list(ws.binary()):
            f = unpack_binary(bytes(raw))
            if isinstance(f, VideoStripe) and f.frame_id not in acked:
                acked.add(f.frame_id)
                ws.feed(f"CLIENT_FRAME_ACK {f.frame_id}")
        assert await wait_until(
            lambda: server.recorder.acked_total >= len(acked))
        summ = server.recorder.summary("primary")
        st = summ["stages"]
        # the full wire half of the path was measured per frame
        for stage in ("capture", "queue", "send", "ack"):
            assert stage in st, f"missing stage {stage}: {st.keys()}"
            assert st[stage]["p50_ms"] >= 0.0
        assert "glass_to_glass_p50_ms" in summ
        # ack RTT is bounded by the observed end-to-end wall
        assert st["ack"]["p50_ms"] <= summ["glass_to_glass_p95_ms"]
    finally:
        await close_client(ws, task)
        await server.stop()
    assert server.recorder.open_spans() == 0


@pytest.mark.anyio
@pytest.mark.parametrize("fault", ["capture.raise", "encode.raise",
                                   "fetch.hang", "ws.drop"])
async def test_chaos_faults_leave_no_open_spans(fault):
    """ISSUE 13 acceptance: each fault class produces terminal marks,
    never recorder growth (capture.raise -> restart drops; encode.raise
    -> dropped@submit; fetch.hang -> watchdog restart; ws.drop ->
    send/queue/reset drops)."""
    server = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="50",
        SELKIES_WATCHDOG_FRAMES="30",
    )
    ws, task = await open_client(server, SETTINGS_BODY)
    try:
        assert await wait_until(lambda: len(ws.binary()) >= 2)
        server.faults.arm(fault, times=2,
                          arg="0.3" if fault == "fetch.hang" else None)
        await asyncio.sleep(0.5)
        assert await wait_until(
            lambda: server.faults.fired.get(fault, 0) >= 1)
    finally:
        await close_client(ws, task)
        await server.stop()
    rec = server.recorder
    assert rec.open_spans() == 0, (
        f"{fault}: {rec.open_spans()} spans leaked")
    assert rec.closed_total > 0
    if fault in ("capture.raise", "encode.raise"):
        # the fault cost frames, and each loss carries a terminal mark
        terminals = {t.terminal
                     for t in rec._completed() if t.terminal}
        assert any(term.startswith("dropped@") for term in terminals), \
            terminals


@pytest.mark.anyio
async def test_health_payload_carries_stage_breakdown():
    server = make_server()
    ws, task = await open_client(server, SETTINGS_BODY)
    try:
        assert await wait_until(lambda: len(ws.binary()) >= 2)
        for raw in list(ws.binary())[:3]:
            f = unpack_binary(bytes(raw))
            if isinstance(f, VideoStripe):
                ws.feed(f"CLIENT_FRAME_ACK {f.frame_id}")
        assert await wait_until(lambda: server.recorder.closed_total >= 1)
        payload = json.loads(server._health_payload())
        d = payload["displays"]["primary"]
        assert "stages" in d
        assert "capture" in d["stages"]
        assert {"p50_ms", "p95_ms"} <= set(d["stages"]["capture"])
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_ack_racing_transport_send_still_closes_span():
    """Regression (review finding): under write backpressure the client
    can ACK while the drainer is still suspended in ws.send — the span
    must already be registered for correlation, not expire later."""
    from selkies_tpu.robustness import BoundedSendQueue
    from selkies_tpu.server.data_server import _ClientSendQueue

    rec = FlightRecorder(capacity=16)
    gate = asyncio.Event()
    sent_payloads = []

    class SlowWs:
        async def send(self, payload):
            sent_payloads.append(payload)
            await gate.wait()          # transport backpressure

    cq = _ClientSendQueue(SlowWs(), BoundedSendQueue(max_video=8),
                          on_evict=lambda c: None, recorder=rec)
    try:
        tr = rec.begin("primary")
        tr.mark("capture", tr.t0, tr.t0 + 0.001)
        tr.frame_id = 7
        cq.offer_traced(b"\x03payload", tr)
        # the payload reached the transport but send has not returned
        assert await wait_until(lambda: len(sent_payloads) == 1)
        out = rec.ack("primary", 7)    # ACK lands mid-send
        assert out is tr and tr.terminal == "acked"
        gate.set()                     # transport drains afterwards
        await asyncio.sleep(0.05)
        assert rec.open_spans() == 0
        assert rec.acked_total == 1 and rec.expired_total == 0
    finally:
        cq.close()


# ---------------------------------------------------------------------------
# metrics HTTP endpoint hardening


def test_http_endpoint_healthz_trace_and_nonfatal_bind():
    m = Metrics(port=0)
    rec = FlightRecorder(capacity=8)
    tr = rec.begin("primary")
    tr.mark("capture", tr.t0, tr.t0 + 0.001)
    rec.drop(tr, "submit")
    m.recorder = rec
    assert m.start_http() is True
    try:
        base = f"http://127.0.0.1:{m.http_port}"
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200 and r.read() == b"ok\n"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert b"trace_open_spans" in r.read()
        with urllib.request.urlopen(base + "/debug/trace?s=9999",
                                    timeout=5) as r:
            data = json.loads(r.read())
            assert data["displayTimeUnit"] == "ms"
            assert any(e.get("ph") == "X" for e in data["traceEvents"])
        # jax tracing is opt-in: 403 until the setting enables it
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/debug/jax-trace", timeout=5)
        assert exc.value.code == 403
        # a second server on the same port must NOT raise — bind
        # failure logs and disables (the data server stays up)
        m2 = Metrics(port=m.http_port)
        assert m2.start_http() is False
    finally:
        m.stop_http()


def test_stage_names_stable():
    """The eight-stage glossary is a wire/bench/docs contract."""
    assert STAGES == ("capture", "stage", "dispatch", "fetch_wait",
                      "pack", "queue", "send", "ack")
