"""Metrics + tracing tests (parity: legacy/metrics.py gauges/histograms)."""

import time

from selkies_tpu.observability import FrameTracer, Metrics


def test_metrics_render():
    m = Metrics(port=0)
    m.set_fps(60.0)
    m.set_latency(12.5)
    m.set_tpu_utilization(45.0)
    m.observe_encode(8.0, 50_000)
    m.set_clients(3)
    m.set_backpressured(1)
    m.set_webrtc_stats({"bitrate": "8000000"})
    text = m.render().decode()
    assert "fps 60.0" in text
    assert "latency 12.5" in text
    assert "tpu_utilization 45.0" in text
    assert "gpu_utilization 45.0" in text      # reference-compatible alias
    assert "connected_clients 3.0" in text
    assert 'webrtc_statistics_info{bitrate="8000000"}' in text
    assert "tpuenc_encode_ms_bucket" in text


def test_metrics_d2h_and_host_entropy_gauges():
    """ISSUE 1 satellite: the bottleneck gauges the pipelined encoders
    record must render."""
    m = Metrics(port=0)
    m.set_d2h_bytes_per_frame(12700.0)
    m.set_host_entropy_ms_per_frame(0.4)
    text = m.render().decode()
    assert "tpuenc_d2h_bytes_per_frame 12700.0" in text
    assert "tpuenc_host_entropy_ms_per_frame 0.4" in text


def test_frame_tracer_percentiles():
    tr = FrameTracer(capacity=100)
    for fid in range(10):
        span = tr.begin(fid)
        span.stamps["capture"] = 0.0
        span.stamps["dispatch"] = 0.001
        span.stamps["harvest"] = 0.001 + 0.001 * (fid + 1)
        tr.finish(fid)
        span.stamps["send"] = span.stamps["harvest"] + 0.0005
    s = tr.summary()
    assert s["frames"] == 10
    assert 1.0 <= s["p50_encode_ms"] <= 10.5
    p95 = tr.percentile_ms("dispatch", "harvest", 95)
    assert p95 >= s["p50_encode_ms"]


def test_frame_tracer_ring_bound():
    tr = FrameTracer(capacity=5)
    for fid in range(20):
        tr.begin(fid)
        tr.finish(fid)
    assert tr.summary()["frames"] == 5
    assert tr.finish(999) is None
