"""Full-stack PeerConnection loopback: SDP offer/answer → ICE → DTLS-SRTP →
H.264/Opus media + data channels over real UDP sockets.

This is the transport-phase E2E the reference stages its vendored aiortc
for (SURVEY.md §2.4): externally-encoded H.264 carried without re-encode.
"""

import asyncio

import pytest

from selkies_tpu.webrtc.peerconnection import PeerConnection


def make_au(tag: bytes) -> bytes:
    sps = bytes([0x67, 1, 2, 3])
    idr = bytes([0x65]) + tag * 300
    return b"\x00\x00\x00\x01" + sps + b"\x00\x00\x00\x01" + idr


def test_peerconnection_end_to_end():
    async def run():
        offerer = PeerConnection(interfaces=["127.0.0.1"])
        answerer = PeerConnection(interfaces=["127.0.0.1"])

        video_out = offerer.add_video_sender(ssrc=0x1111)
        audio_out = offerer.add_audio_sender(ssrc=0x2222)
        input_ch = offerer.create_data_channel("input")

        got_video = []
        got_audio = []
        got_input = []
        answerer.video_receiver().on_frame = \
            lambda f, ts: got_video.append((f, ts))
        answerer.audio_receiver().on_frame = \
            lambda f, ts: got_audio.append((f, ts))

        def on_channel(ch):
            ch.on_message = got_input.append
        answerer.on_channel = on_channel

        offer = await offerer.create_offer()
        await answerer.set_remote_description(offer, "offer")
        answer = await answerer.create_answer()
        await offerer.set_remote_description(answer, "answer")

        await asyncio.gather(offerer.wait_connected(15),
                             answerer.wait_connected(15))

        # media: 5 video AUs + 5 opus frames
        for i in range(5):
            video_out.send_frame(make_au(bytes([i + 1])), timestamp=i * 3000)
            audio_out.send_frame(b"opus-%d" % i, timestamp=i * 960)
            await asyncio.sleep(0.02)
        for _ in range(100):
            if len(got_video) >= 5 and len(got_audio) >= 5:
                break
            await asyncio.sleep(0.05)
        assert len(got_video) == 5
        assert len(got_audio) == 5
        frame0, ts0 = got_video[0]
        assert ts0 == 0 and frame0.startswith(b"\x00\x00\x00\x01\x67")
        assert bytes([0x65]) + b"\x01" * 3 in frame0
        assert got_audio[0][0] == b"opus-0"

        # data channel: wait for DCEP then exchange input messages
        for _ in range(200):
            if input_ch.open:
                break
            await asyncio.sleep(0.05)
        assert input_ch.open
        input_ch.send("kd,65")
        input_ch.send(b"\x02binary")
        for _ in range(100):
            if len(got_input) >= 2:
                break
            await asyncio.sleep(0.05)
        assert got_input == [b"kd,65", b"\x02binary"]

        # TWCC loop closed: the answerer fed back arrival times and the
        # offerer's sender-side GCC estimator consumed them
        for _ in range(100):
            if offerer.gcc.delay._recv_window:
                break
            await asyncio.sleep(0.05)
        assert offerer.gcc.delay._recv_window, "no TWCC feedback reached GCC"
        assert offerer.gcc.bitrate > 0

        await offerer.close()
        await answerer.close()

    asyncio.run(run())


def test_peerconnection_bidirectional_media():
    async def run():
        a = PeerConnection(interfaces=["127.0.0.1"])
        b = PeerConnection(interfaces=["127.0.0.1"])
        a_video = a.add_video_sender(ssrc=0xA)
        b_video = b.add_video_sender(ssrc=0xB)
        got_a, got_b = [], []
        a.video_receiver().on_frame = lambda f, ts: got_a.append(f)
        b.video_receiver().on_frame = lambda f, ts: got_b.append(f)

        offer = await a.create_offer()
        await b.set_remote_description(offer, "offer")
        answer = await b.create_answer()
        await a.set_remote_description(answer, "answer")
        await asyncio.gather(a.wait_connected(15), b.wait_connected(15))

        a_video.send_frame(make_au(b"\xaa"), timestamp=1)
        b_video.send_frame(make_au(b"\xbb"), timestamp=2)
        for _ in range(100):
            if got_a and got_b:
                break
            await asyncio.sleep(0.05)
        assert got_a and b"\xbb" in got_a[0]
        assert got_b and b"\xaa" in got_b[0]
        await a.close()
        await b.close()

    asyncio.run(run())


def test_nack_retransmission_recovers_loss():
    """A dropped media packet is NACKed by the receiver and resent from
    the sender's retransmission buffer, so the frame still assembles."""
    async def run():
        a = PeerConnection(interfaces=["127.0.0.1"])
        b = PeerConnection(interfaces=["127.0.0.1"])
        video = a.add_video_sender(ssrc=0xAB)
        got = []
        b.video_receiver().on_frame = lambda f, ts: got.append(f)

        offer = await a.create_offer()
        await b.set_remote_description(offer, "offer")
        answer = await b.create_answer()
        await a.set_remote_description(answer, "answer")
        await asyncio.gather(a.wait_connected(15), b.wait_connected(15))

        # drop the first large outgoing SRTP packet (an FU-A fragment of
        # a multi-packet frame, so a later packet exposes the gap)
        real_send = a.ice.send
        dropped = {"n": 0}

        def lossy(data):
            if 128 <= data[0] <= 191 and dropped["n"] == 0 and len(data) > 900:
                dropped["n"] = 1
                return
            real_send(data)
        a.ice.send = lossy

        big_idr = bytes([0x65]) + b"\x77" * 3000   # 3 FU-A packets
        au = b"\x00\x00\x00\x01" + bytes([0x67, 1, 2, 3]) \
            + b"\x00\x00\x00\x01" + big_idr
        video.send_frame(au, timestamp=1000)
        for _ in range(200):
            if got:
                break
            await asyncio.sleep(0.05)
        assert dropped["n"] == 1, "test did not exercise a drop"
        assert got and b"\x77" in got[0], "NACK retransmission failed"
        await a.close()
        await b.close()

    asyncio.run(run())


def test_missing_fingerprint_fails_closed():
    async def run():
        offerer = PeerConnection(interfaces=["127.0.0.1"])
        answerer = PeerConnection(interfaces=["127.0.0.1"])
        offerer.add_video_sender(ssrc=0x1111)
        offerer.create_data_channel("input")
        offer = await offerer.create_offer()
        stripped = "\r\n".join(
            line for line in offer.split("\r\n")
            if not line.startswith("a=fingerprint"))
        with pytest.raises(ValueError, match="fingerprint"):
            await answerer.set_remote_description(stripped, "offer")
        await offerer.close()
        await answerer.close()
    asyncio.run(run())


def test_twcc_eviction_keeps_newest_across_wrap():
    from selkies_tpu.webrtc import peerconnection as pcmod
    pc = pcmod.PeerConnection.__new__(pcmod.PeerConnection)
    pc._twcc_sent = {}
    seqs = [i & 0xFFFF for i in range(65000, 65000 + 3000)]  # crosses wrap
    for s in seqs:
        pc._record_twcc_send(s, 1200)
    # the survivors must be the newest TWCC_HISTORY records in send order,
    # not the numerically largest (which right after the wrap would evict
    # the newest, stalling the GCC estimator)
    assert list(pc._twcc_sent) == seqs[-pcmod.TWCC_HISTORY:]
