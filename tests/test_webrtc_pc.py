"""Full-stack PeerConnection loopback: SDP offer/answer → ICE → DTLS-SRTP →
H.264/Opus media + data channels over real UDP sockets.

This is the transport-phase E2E the reference stages its vendored aiortc
for (SURVEY.md §2.4): externally-encoded H.264 carried without re-encode.
"""

import asyncio

import pytest

from selkies_tpu.webrtc.peerconnection import PeerConnection


def make_au(tag: bytes) -> bytes:
    sps = bytes([0x67, 1, 2, 3])
    idr = bytes([0x65]) + tag * 300
    return b"\x00\x00\x00\x01" + sps + b"\x00\x00\x00\x01" + idr


def test_peerconnection_end_to_end():
    async def run():
        offerer = PeerConnection(interfaces=["127.0.0.1"])
        answerer = PeerConnection(interfaces=["127.0.0.1"])

        video_out = offerer.add_video_sender(ssrc=0x1111)
        audio_out = offerer.add_audio_sender(ssrc=0x2222)
        input_ch = offerer.create_data_channel("input")

        got_video = []
        got_audio = []
        got_input = []
        answerer.video_receiver().on_frame = \
            lambda f, ts: got_video.append((f, ts))
        answerer.audio_receiver().on_frame = \
            lambda f, ts: got_audio.append((f, ts))

        def on_channel(ch):
            ch.on_message = got_input.append
        answerer.on_channel = on_channel

        offer = await offerer.create_offer()
        await answerer.set_remote_description(offer, "offer")
        answer = await answerer.create_answer()
        await offerer.set_remote_description(answer, "answer")

        await asyncio.gather(offerer.wait_connected(15),
                             answerer.wait_connected(15))

        # media: 5 video AUs + 5 opus frames
        for i in range(5):
            video_out.send_frame(make_au(bytes([i + 1])), timestamp=i * 3000)
            audio_out.send_frame(b"opus-%d" % i, timestamp=i * 960)
            await asyncio.sleep(0.02)
        for _ in range(100):
            if len(got_video) >= 5 and len(got_audio) >= 5:
                break
            await asyncio.sleep(0.05)
        assert len(got_video) == 5
        assert len(got_audio) == 5
        frame0, ts0 = got_video[0]
        assert ts0 == 0 and frame0.startswith(b"\x00\x00\x00\x01\x67")
        assert bytes([0x65]) + b"\x01" * 3 in frame0
        assert got_audio[0][0] == b"opus-0"

        # data channel: wait for DCEP then exchange input messages
        for _ in range(200):
            if input_ch.open:
                break
            await asyncio.sleep(0.05)
        assert input_ch.open
        input_ch.send("kd,65")
        input_ch.send(b"\x02binary")
        for _ in range(100):
            if len(got_input) >= 2:
                break
            await asyncio.sleep(0.05)
        assert got_input == [b"kd,65", b"\x02binary"]

        # TWCC loop closed: the answerer fed back arrival times and the
        # offerer's sender-side GCC estimator consumed them
        for _ in range(100):
            if offerer.gcc.delay._recv_window:
                break
            await asyncio.sleep(0.05)
        assert offerer.gcc.delay._recv_window, "no TWCC feedback reached GCC"
        assert offerer.gcc.bitrate > 0

        await offerer.close()
        await answerer.close()

    asyncio.run(run())


def test_peerconnection_bidirectional_media():
    async def run():
        a = PeerConnection(interfaces=["127.0.0.1"])
        b = PeerConnection(interfaces=["127.0.0.1"])
        a_video = a.add_video_sender(ssrc=0xA)
        b_video = b.add_video_sender(ssrc=0xB)
        got_a, got_b = [], []
        a.video_receiver().on_frame = lambda f, ts: got_a.append(f)
        b.video_receiver().on_frame = lambda f, ts: got_b.append(f)

        offer = await a.create_offer()
        await b.set_remote_description(offer, "offer")
        answer = await b.create_answer()
        await a.set_remote_description(answer, "answer")
        await asyncio.gather(a.wait_connected(15), b.wait_connected(15))

        a_video.send_frame(make_au(b"\xaa"), timestamp=1)
        b_video.send_frame(make_au(b"\xbb"), timestamp=2)
        for _ in range(100):
            if got_a and got_b:
                break
            await asyncio.sleep(0.05)
        assert got_a and b"\xbb" in got_a[0]
        assert got_b and b"\xaa" in got_b[0]
        await a.close()
        await b.close()

    asyncio.run(run())


def test_nack_retransmission_recovers_loss():
    """A dropped media packet is NACKed by the receiver and resent from
    the sender's retransmission buffer, so the frame still assembles."""
    async def run():
        a = PeerConnection(interfaces=["127.0.0.1"])
        b = PeerConnection(interfaces=["127.0.0.1"])
        video = a.add_video_sender(ssrc=0xAB)
        got = []
        b.video_receiver().on_frame = lambda f, ts: got.append(f)

        offer = await a.create_offer()
        await b.set_remote_description(offer, "offer")
        answer = await b.create_answer()
        await a.set_remote_description(answer, "answer")
        await asyncio.gather(a.wait_connected(15), b.wait_connected(15))

        # drop the first large outgoing SRTP packet (an FU-A fragment of
        # a multi-packet frame, so a later packet exposes the gap)
        real_send = a.ice.send
        dropped = {"n": 0}

        def lossy(data):
            if 128 <= data[0] <= 191 and dropped["n"] == 0 and len(data) > 900:
                dropped["n"] = 1
                return
            real_send(data)
        a.ice.send = lossy

        big_idr = bytes([0x65]) + b"\x77" * 3000   # 3 FU-A packets
        au = b"\x00\x00\x00\x01" + bytes([0x67, 1, 2, 3]) \
            + b"\x00\x00\x00\x01" + big_idr
        video.send_frame(au, timestamp=1000)
        for _ in range(200):
            if got:
                break
            await asyncio.sleep(0.05)
        assert dropped["n"] == 1, "test did not exercise a drop"
        assert got and b"\x77" in got[0], "NACK retransmission failed"
        await a.close()
        await b.close()

    asyncio.run(run())


def test_missing_fingerprint_fails_closed():
    async def run():
        offerer = PeerConnection(interfaces=["127.0.0.1"])
        answerer = PeerConnection(interfaces=["127.0.0.1"])
        offerer.add_video_sender(ssrc=0x1111)
        offerer.create_data_channel("input")
        offer = await offerer.create_offer()
        stripped = "\r\n".join(
            line for line in offer.split("\r\n")
            if not line.startswith("a=fingerprint"))
        with pytest.raises(ValueError, match="fingerprint"):
            await answerer.set_remote_description(stripped, "offer")
        await offerer.close()
        await answerer.close()
    asyncio.run(run())


def test_twcc_eviction_keeps_newest_across_wrap():
    from selkies_tpu.webrtc import peerconnection as pcmod
    pc = pcmod.PeerConnection.__new__(pcmod.PeerConnection)
    pc._twcc_sent = {}
    seqs = [i & 0xFFFF for i in range(65000, 65000 + 3000)]  # crosses wrap
    for s in seqs:
        pc._record_twcc_send(s, 1200)
    # the survivors must be the newest TWCC_HISTORY records in send order,
    # not the numerically largest (which right after the wrap would evict
    # the newest, stalling the GCC estimator)
    assert list(pc._twcc_sent) == seqs[-pcmod.TWCC_HISTORY:]


def test_red_pts_follow_remote_description():
    """ADVICE r2 (peerconnection.py:469): RED/ULPFEC payload types come
    from the negotiated remote description — a peer that remaps them gets
    the remapped numbers, a peer that rejects them gets no RED at all."""
    from selkies_tpu.webrtc.sdp import SessionDescription

    def sdp_with_codecs(codec_lines):
        return "\r\n".join([
            "v=0", "o=- 1 1 IN IP4 0.0.0.0", "s=-", "t=0 0",
            "a=fingerprint:sha-256 " + ":".join(["AB"] * 32),
            "m=video 9 UDP/TLS/RTP/SAVPF 102 110 111",
            "c=IN IP4 0.0.0.0", "a=mid:0",
            "a=rtpmap:102 H264/90000",
        ] + codec_lines + [""])

    pc = PeerConnection()
    # remapped red/ulpfec → adopt the remote's numbers
    pc._remote_desc = SessionDescription.parse(sdp_with_codecs(
        ["a=rtpmap:110 red/90000", "a=rtpmap:111 ulpfec/90000"]))
    pc._negotiate_fec()
    assert (pc._red_pt, pc._ulpfec_pt) == (110, 111)
    assert pc.video_receiver().ulpfec_pt == 111

    # rejected red → the RED send/receive path disengages entirely
    pc2 = PeerConnection()
    pc2._remote_desc = SessionDescription.parse(sdp_with_codecs([]))
    pc2._negotiate_fec()
    assert pc2._red_pt is None and pc2._ulpfec_pt is None

    # red without ulpfec is not a usable FEC arrangement
    pc3 = PeerConnection()
    pc3._remote_desc = SessionDescription.parse(sdp_with_codecs(
        ["a=rtpmap:110 red/90000"]))
    pc3._negotiate_fec()
    assert pc3._red_pt is None and pc3._ulpfec_pt is None


def test_media_pts_follow_remote_description():
    """Remapped H264/opus payload types in the remote description re-key
    receivers and re-stamp senders — fixed media PTs break the same way
    fixed FEC PTs did."""
    from selkies_tpu.webrtc.sdp import SessionDescription

    sdp = "\r\n".join([
        "v=0", "o=- 1 1 IN IP4 0.0.0.0", "s=-", "t=0 0",
        "a=fingerprint:sha-256 " + ":".join(["AB"] * 32),
        "m=video 9 UDP/TLS/RTP/SAVPF 96",
        "c=IN IP4 0.0.0.0", "a=mid:0",
        "a=rtpmap:96 H264/90000",
        "m=audio 9 UDP/TLS/RTP/SAVPF 97",
        "c=IN IP4 0.0.0.0", "a=mid:1",
        "a=rtpmap:97 opus/48000/2", ""])

    pc = PeerConnection()
    vs = pc.add_video_sender(ssrc=0x10)
    recv = pc.video_receiver()
    pc._remote_desc = SessionDescription.parse(sdp)
    pc._negotiate_fec()
    assert pc._video_pt == 96 and pc._audio_pt == 97
    assert vs.payload_type == 96                 # sender re-stamped
    assert pc.receivers.get(96) is recv          # receiver re-keyed
    assert pc.video_receiver() is recv
    assert pc.audio_receiver() is pc.receivers[97]


def test_decode_planes_huge_nsym_rejected():
    """A tiny blob claiming a giant symbol count must fail fast, not
    allocate gigabytes (code-review r3 finding)."""
    import struct as _s

    import numpy as np
    import pytest as _pytest

    from selkies_tpu.encoder import rans
    y = np.zeros((8, 64), np.int16)
    c = np.zeros((2, 64), np.int16)
    blob = bytearray(rans.encode_planes(y, c, c, 8))
    _s.pack_into("<I", blob, 0, 0x0FFFFFFF)      # nsym → absurd
    with _pytest.raises(ValueError, match="malformed"):
        rans.decode_planes(bytes(blob), 8, 4, 8)


def test_h264_pt_adoption_prefers_mode1_baseline():
    """Among several remote H264 entries, adopt the packetization-mode=1
    constrained-baseline one — this stack sends FU-A mode-1 streams."""
    from selkies_tpu.webrtc.sdp import SessionDescription

    sdp = "\r\n".join([
        "v=0", "o=- 1 1 IN IP4 0.0.0.0", "s=-", "t=0 0",
        "a=fingerprint:sha-256 " + ":".join(["AB"] * 32),
        "m=video 9 UDP/TLS/RTP/SAVPF 98 99",
        "c=IN IP4 0.0.0.0", "a=mid:0",
        "a=rtpmap:98 H264/90000",
        "a=fmtp:98 packetization-mode=0;profile-level-id=42e01f",
        "a=rtpmap:99 H264/90000",
        "a=fmtp:99 level-asymmetry-allowed=1;packetization-mode=1;"
        "profile-level-id=42e01f", ""])
    pc = PeerConnection()
    pc._remote_desc = SessionDescription.parse(sdp)
    pc._negotiate_fec()
    assert pc._video_pt == 99
