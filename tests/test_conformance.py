"""Bitstream conformance: decode tpuenc output with a production decoder.

The browser's WebCodecs decoders are the real consumers (reference client
selkies-core.js:2032/2155/2925); libavcodec stands in for them here.  The
H.264 check is the strong one: the decoder's pixels must be BIT-EXACT with
the encoder's own reconstruction loop, because both are required to run the
identical §8.5 integer arithmetic.
"""

import numpy as np
import pytest

from selkies_tpu.encoder import conformance

pytestmark = pytest.mark.skipif(
    not conformance.available(), reason="libavcodec conformance decoder unavailable")

RNG = np.random.default_rng(7)


def _smooth_frame(h, w, seed=0, shift=0):
    """Natural-ish content: smooth gradients + a few rectangles, rolled by
    ``shift`` pixels to exercise motion search."""
    yy, xx = np.mgrid[0:h, 0:w]
    base = (128 + 90 * np.sin(xx / 37.0 + seed) * np.cos(yy / 23.0)).astype(np.float32)
    img = np.stack([base, np.roll(base, 5, 1), 255 - base], axis=-1)
    r = np.random.default_rng(seed)
    for _ in range(6):
        y0, x0 = r.integers(0, h - 16), r.integers(0, w - 16)
        hh, ww = r.integers(8, h - y0 + 1), r.integers(8, w - x0 + 1)
        img[y0:y0 + hh, x0:x0 + ww] = r.integers(0, 256, 3)
    img = np.roll(img, shift, axis=1)
    return np.clip(img, 0, 255).astype(np.uint8)



def _src_planes(frame):
    """Source YCbCr 4:2:0 planes via the encoder's own color path."""
    import jax.numpy as jnp
    from selkies_tpu.encoder.h264_device import prepare_planes
    h, w = frame.shape[:2]
    return tuple(np.asarray(p) for p in
                 prepare_planes(jnp.asarray(frame), h, w))

# ---------------------------------------------------------------------------
# H.264


def test_h264_idr_bit_exact_with_recon():
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h, sh = 128, 96, 48
    enc = H264StripeEncoder(w, h, stripe_height=sh, qp=24)
    frame = _smooth_frame(h, w, seed=1)
    stripes = enc.encode_frame(frame)
    assert len(stripes) == len(enc.stripes)
    decoders = {st.y0: conformance.ConformanceDecoder("h264", max_dim=256)
                for st in enc.stripes}
    for s in stripes:
        assert s.is_key
        got = decoders[s.y_start].decode(s.annexb)
        assert got is not None
        dy, du, dv = got
        i = s.y_start // enc.stripe_h
        ry, rcb, rcr = enc.stripe_ref(i)
        np.testing.assert_array_equal(dy, ry[:s.height, :w])
        np.testing.assert_array_equal(du, rcb[:s.height // 2, :w // 2])
        np.testing.assert_array_equal(dv, rcr[:s.height // 2, :w // 2])
    for d in decoders.values():
        d.close()


def test_h264_p_frames_bit_exact_over_gop():
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h, sh = 112, 64, 32
    enc = H264StripeEncoder(w, h, stripe_height=sh, qp=28, search=8)
    decoders = {st.y0: conformance.ConformanceDecoder("h264", max_dim=256)
                for st in enc.stripes}
    # 6 frames of horizontally-scrolling content → P frames with real MVs
    for t in range(6):
        frame = _smooth_frame(h, w, seed=3, shift=3 * t)
        stripes = enc.encode_frame(frame)
        for s in stripes:
            got = decoders[s.y_start].decode(s.annexb)
            assert got is not None, f"t={t} stripe {s.y_start}: no frame out"
            dy, du, dv = got
            ry, rcb, rcr = enc.stripe_ref(s.y_start // enc.stripe_h)
            np.testing.assert_array_equal(
                dy, ry[:s.height, :w],
                err_msg=f"t={t} stripe {s.y_start} luma mismatch")
            np.testing.assert_array_equal(du, rcb[:s.height // 2, :w // 2])
            np.testing.assert_array_equal(dv, rcr[:s.height // 2, :w // 2])
    for d in decoders.values():
        d.close()


@pytest.mark.slow  # ~50 s (a fresh 2-shard SPMD compile); transitively
# covered in tier 1 — test_parallel pins the SFE bytes to the solo
# encoder's, whose output the tier-1 conformance tests above decode
def test_sfe_multi_shard_stream_decodes_bit_exact():
    """Split-frame encoding (ISSUE 15): one frame's stripe bands encoded
    on DIFFERENT chips must decode in libavcodec bit-exact with the
    encoder's own sharded reconstruction planes — IDR then P — i.e. the
    host-concatenated access unit is a conformant stream, not merely
    byte-stable."""
    import jax

    from selkies_tpu.parallel import parse_mesh_spec
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    w, h, sh = 112, 64, 32                    # 2 stripes, one per shard
    mesh = parse_mesh_spec("session:1,stripe:2", jax.devices()[:2])
    enc = MeshH264Encoder(mesh, 1, w, h, stripe_h=sh, qp=28, search=4,
                          me="xla")
    decoders = {i * sh: conformance.ConformanceDecoder("h264", max_dim=256)
                for i in range(h // sh)}
    for t in range(3):
        frame = _smooth_frame(h, w, seed=3, shift=3 * t)
        (stripes,), _ = enc.encode_frames([frame])
        assert len(stripes) == h // sh, f"t={t}: torn access unit"
        ref_y = np.asarray(enc._ref_y)[0]
        ref_cb = np.asarray(enc._ref_cb)[0]
        ref_cr = np.asarray(enc._ref_cr)[0]
        for s in stripes:
            got = decoders[s.y_start].decode(s.annexb)
            assert got is not None, f"t={t} stripe {s.y_start}: no frame"
            dy, du, dv = got
            y0 = s.y_start
            np.testing.assert_array_equal(
                dy, ref_y[y0:y0 + s.height, :w],
                err_msg=f"t={t} stripe {y0} luma mismatch")
            np.testing.assert_array_equal(
                du, ref_cb[y0 // 2:(y0 + s.height) // 2, :w // 2])
            np.testing.assert_array_equal(
                dv, ref_cr[y0 // 2:(y0 + s.height) // 2, :w // 2])
    for d in decoders.values():
        d.close()


def test_h264_quality_reasonable():
    """Decoded pixels must resemble the source (catches e.g. swapped
    chroma or broken prediction that bit-exactness alone can't: if recon
    itself were broken, recon==decode would still pass)."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h = 128, 64
    enc = H264StripeEncoder(w, h, stripe_height=64, qp=18)
    frame = _smooth_frame(h, w, seed=5)
    (s,) = enc.encode_frame(frame)
    dec = conformance.ConformanceDecoder("h264", max_dim=256)
    dy, du, dv = dec.decode(s.annexb)
    dec.close()
    sy, scb, scr = _src_planes(frame)
    err = np.abs(dy.astype(np.int32) - sy.astype(np.int32))
    assert err.mean() < 4.0, err.mean()
    cerr = np.abs(du.astype(np.int32) - scb.astype(np.int32))
    assert cerr.mean() < 5.0, cerr.mean()


def test_h264_fullframe_mode():
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h = 96, 80
    enc = H264StripeEncoder(w, h, qp=26, fullframe=True)
    assert len(enc.stripes) == 1
    dec = conformance.ConformanceDecoder("h264", max_dim=256)
    for t in range(3):
        stripes = enc.encode_frame(_smooth_frame(h, w, seed=9, shift=2 * t))
        (s,) = stripes
        assert s.height == h
        dy, _, _ = dec.decode(s.annexb)
        np.testing.assert_array_equal(dy, enc.stripe_ref(0)[0][:h, :w])
    dec.close()


def test_h264_device_cavlc_bit_identical_to_host_path_and_decodes():
    """ISSUE 1 acceptance: device-packed stripes (entropy='device') must
    be byte-identical to the host-CAVLC path for P frames AND for the
    IDR fallback, and must decode bit-exact against the encoder's own
    reconstruction in libavcodec."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h, sh = 112, 64, 32
    dev_enc = H264StripeEncoder(w, h, stripe_height=sh, qp=28, search=8,
                                entropy="device")
    host_enc = H264StripeEncoder(w, h, stripe_height=sh, qp=28, search=8,
                                 entropy="host")
    assert dev_enc.entropy == "device" and host_enc.entropy == "host"
    decoders = {st.y0: conformance.ConformanceDecoder("h264", max_dim=256)
                for st in dev_enc.stripes}
    saw_p = False
    for t in range(5):
        frame = _smooth_frame(h, w, seed=13, shift=3 * t)
        d_stripes = dev_enc.encode_frame(frame)
        h_stripes = host_enc.encode_frame(frame)
        assert [s.annexb for s in d_stripes] == \
            [s.annexb for s in h_stripes], f"t={t}: entropy modes differ"
        for s in d_stripes:
            saw_p |= not s.is_key
            got = decoders[s.y_start].decode(s.annexb)
            assert got is not None, f"t={t} stripe {s.y_start}"
            dy, du, dv = got
            ry, rcb, rcr = dev_enc.stripe_ref(s.y_start // dev_enc.stripe_h)
            np.testing.assert_array_equal(dy, ry[:s.height, :w])
            np.testing.assert_array_equal(du, rcb[:s.height // 2, :w // 2])
            np.testing.assert_array_equal(dv, rcr[:s.height // 2, :w // 2])
    assert saw_p, "no P frames exercised the device packer"
    for d in decoders.values():
        d.close()


# ---------------------------------------------------------------------------
# JPEG


@pytest.mark.parametrize("entropy", ["device", "host"])
def test_jpeg_stripes_decode_and_match_source(entropy):
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder

    w, h, sh = 128, 96, 48
    enc = JpegStripeEncoder(w, h, stripe_height=sh, quality=90,
                            entropy=entropy)
    frame = _smooth_frame(h, w, seed=11)
    stripes = enc.encode_frame(frame)
    assert stripes, "first frame must emit all stripes"
    sy, scb, scr = _src_planes(frame)
    for s in stripes:
        dec = conformance.ConformanceDecoder("mjpeg", max_dim=256)
        got = dec.decode(s.jpeg)
        dec.close()
        assert got is not None
        dy, du, dv = got
        assert dy.shape == (sh, enc.pad_w)
        ref = sy[s.y_start:s.y_start + sh]
        err = np.abs(dy[:ref.shape[0], :w].astype(np.int32)
                     - ref[:, :w].astype(np.int32))
        assert err.mean() < 3.5, (s.y_start, err.mean())
        cref = scb[s.y_start // 2:(s.y_start + sh) // 2]
        cerr = np.abs(du[:cref.shape[0], :w // 2].astype(np.int32)
                      - cref[:, :w // 2].astype(np.int32))
        assert cerr.mean() < 4.5, (s.y_start, cerr.mean())


def test_h264_partial_last_stripe_decodes():
    """A display height that is not a stripe multiple leaves a short last
    stripe; the uniform encode grid codes full stripe_h rows, so the SPS
    must declare the coded height and crop — libavcodec rejected the old
    mismatched headers with 'first_mb_in_slice overflow'."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder

    w, h, sh = 128, 80, 64           # stripes: 64 rows + 16-row remainder
    enc = H264StripeEncoder(w, h, stripe_height=sh, qp=24)
    frame = _smooth_frame(h, w, seed=7)
    stripes = enc.encode_frame(frame)
    assert [s.height for s in stripes] == [64, 16]
    for s in stripes:
        dec = conformance.ConformanceDecoder("h264", max_dim=256)
        got = dec.decode(s.annexb)
        dec.close()
        assert got is not None, f"stripe {s.y_start} undecodable"
        dy, _, _ = got
        assert dy.shape == (s.height, w)
        i = s.y_start // enc.stripe_h
        ry, _, _ = enc.stripe_ref(i)
        np.testing.assert_array_equal(dy, ry[:s.height, :w])


def test_deblock_enabled_slice_header_decodes():
    """STAGED deblocking groundwork: a P slice written with
    disable_deblocking_filter_idc=0 (+ the two offset fields) must
    parse and decode in libavcodec, and the decoder's in-loop filter
    must actually engage (pixels differ from the unfiltered stream).
    The flag is off in the product until the device reconstruction
    mirrors the filter (see encode_picture_nals_np docstring)."""
    import numpy as np

    from selkies_tpu.encoder import h264_device as dev
    from selkies_tpu.encoder.h264 import (H264StripeEncoder,
                                          encode_picture_nals_np)

    # smooth content at a high QP: deblocking only engages where the
    # step across a block edge is SMALLER than alpha(qp) — flat
    # gradients with coarse quantization, not high-contrast noise
    W, H = 128, 64
    yy, xx = np.mgrid[0:H, 0:W].astype(np.float32)
    f0 = np.clip(np.stack([96 + xx / 3 + yy / 5] * 3, -1),
                 0, 255).astype(np.uint8)
    f1 = np.clip(np.stack([96 + (xx + 2) / 3 + (yy + 7) / 5] * 3, -1),
                 0, 255).astype(np.uint8)

    def encode(deblock):
        enc = H264StripeEncoder(W, H, stripe_height=64, qp=44)
        out = []
        for t, f in enumerate((f0, f1)):
            p = enc.dispatch(f, fetch=True)
            host = np.asarray(p.fetch)
            if p.is_idr:
                stripes = enc.harvest(p, host=host)
                out.append(b"".join(s.annexb for s in stripes))
                continue
            # P frame: re-code the fetched levels with the flag
            S = enc.n_stripes
            row = np.asarray(p.flat16[0]).astype(np.int32)
            parts, pos = [], 0
            for shape, size in enc._shapes:
                parts.append(row[pos:pos + size].reshape(shape))
                pos += size
            mv, luma, luma_dc, chroma_dc, chroma_ac = parts
            nals = encode_picture_nals_np(
                mv, luma, luma_dc, chroma_dc, chroma_ac,
                is_idr=False, mb_w=enc.pad_w // 16,
                mb_h=enc.stripe_h // 16, qp=44, frame_num=1,
                deblock=deblock)
            out.append(nals)
        return out

    plain = encode(False)
    filtered = encode(True)
    assert plain[0] == filtered[0]            # IDR untouched
    assert plain[1] != filtered[1]            # P slice header differs

    def decode(streams):
        dec = conformance.ConformanceDecoder("h264", max_dim=256)
        frames = []
        for s in streams:
            got = dec.decode(s)
            if got is not None:
                frames.append(got)
        frames.extend(dec.flush())
        dec.close()
        return frames

    fa = decode(plain)
    fb = decode(filtered)
    assert len(fa) == 2 and len(fb) == 2      # both streams fully decode
    np.testing.assert_array_equal(fa[0][0], fb[0][0])   # IDR identical
    # the in-loop filter engaged: P pictures differ between streams
    assert not np.array_equal(fa[1][0], fb[1][0])
