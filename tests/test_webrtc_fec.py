"""RED + ULP FEC (RFC 2198/5109): unit round-trips and loss recovery
through the full PeerConnection stack.

Parity target: the reference's ulpfec video protection
(``legacy/gstwebrtc_app.py:996-1000``, video_packetloss_percent knob).
"""

import asyncio
import struct

import pytest

from selkies_tpu.webrtc.fec import (RED_PT, ULPFEC_PT, UlpFecDecoder,
                                    UlpFecEncoder, build_fec, parse_fec,
                                    recover, red_unwrap, red_wrap)
from selkies_tpu.webrtc.rtp import RtpPacket


def mk_media(seq, payload, ts=1000, ssrc=0x42, marker=0, pt=102):
    return RtpPacket(payload_type=pt, sequence_number=seq, timestamp=ts,
                     ssrc=ssrc, payload=payload, marker=marker).serialize()


def test_red_wrap_unwrap_roundtrip():
    blocks = red_unwrap(red_wrap(102, b"hello"))
    assert blocks == [(102, b"hello")]


def test_red_unwrap_redundant_blocks():
    # one redundant block (4-byte header) + primary
    red = bytes([0x80 | 104, 0, 0, 3]) + bytes([102]) + b"FEC" + b"primary"
    assert red_unwrap(red) == [(104, b"FEC"), (102, b"primary")]


def test_red_unwrap_truncated():
    assert red_unwrap(bytes([0x80 | 104, 0])) == []
    assert red_unwrap(b"") == []


def test_fec_recovers_each_single_loss():
    pkts = [mk_media(100 + i, bytes([i]) * (20 + 7 * i), ts=5000 + i)
            for i in range(4)]
    fec_payload = build_fec(pkts)
    fec = parse_fec(fec_payload)
    assert fec is not None
    assert fec.sn_base == 100 and fec.offsets == (0, 1, 2, 3)
    for lost in range(4):
        have = {100 + i: pkts[i] for i in range(4) if i != lost}
        got = recover(fec, have, ssrc=0x42)
        assert got is not None
        seq, raw = got
        assert seq == 100 + lost
        assert raw == pkts[lost], f"loss {lost} not bit-exact"


def test_fec_refuses_double_loss():
    pkts = [mk_media(7 + i, b"x" * 30) for i in range(4)]
    fec = parse_fec(build_fec(pkts))
    have = {7: pkts[0], 8: pkts[1]}          # two missing
    assert recover(fec, have, ssrc=1) is None


def test_fec_sequence_wrap():
    pkts = [mk_media((0xFFFE + i) & 0xFFFF, bytes([i]) * 25) for i in range(4)]
    fec = parse_fec(build_fec(pkts))
    have = {p: r for p, r in
            zip([0xFFFE, 0xFFFF, 0, 1], pkts) if p != 0}
    got = recover(fec, have, ssrc=0x42)
    assert got is not None and got[0] == 0
    assert got[1] == pkts[2]


def test_fec_preserves_marker_and_extension():
    pkt = RtpPacket(payload_type=102, sequence_number=55, timestamp=777,
                    ssrc=3, payload=b"z" * 40, marker=1,
                    extensions={2: b"\x01\x02"})
    other = mk_media(56, b"w" * 10)
    fec = parse_fec(build_fec([pkt.serialize(), other]))
    got = recover(fec, {56: other}, ssrc=3)
    assert got is not None
    rec = RtpPacket.parse(got[1])
    assert rec.marker == 1
    assert rec.extensions.get(2) == b"\x01\x02"
    assert rec.payload == b"z" * 40
    assert rec.timestamp == 777


def test_decoder_recovers_out_of_order_fec_first():
    enc = UlpFecEncoder(50)                  # group of 2
    dec = UlpFecDecoder()
    p0, p1 = mk_media(10, b"a" * 21), mk_media(11, b"b" * 33)
    assert enc.push(p0) is None
    fec_payload = enc.push(p1)
    assert fec_payload is not None
    dec.add_fec(fec_payload)                 # FEC arrives before media
    dec.add_media(p0)
    recovered = dec.try_recover(ssrc=0x42)
    assert recovered == [p1]
    assert dec.recovered_count == 1
    # group satisfied: FEC must be spent, not re-recovered
    assert dec.try_recover(ssrc=0x42) == []


def test_encoder_percentage_to_group_size():
    assert UlpFecEncoder(100).group == 1
    assert UlpFecEncoder(50).group == 2
    assert UlpFecEncoder(25).group == 4
    assert UlpFecEncoder(5).group == 16
    assert UlpFecEncoder(1).group == 16


def test_sender_emits_red_and_fec(monkeypatch):
    """MediaSender with FEC on: media goes out RED-wrapped, one FEC packet
    per group, and the receiver-side path reassembles frames."""
    from selkies_tpu.webrtc.peerconnection import MediaReceiver, MediaSender

    class FakePC:
        def __init__(self):
            self.sent = []
            self._twcc = 0
            # RED/ULPFEC only ride once the remote description negotiated
            # them; the fake peer agreed to the default PTs
            self._red_pt = RED_PT
            self._ulpfec_pt = ULPFEC_PT

        def _next_twcc(self):
            self._twcc = (self._twcc + 1) & 0xFFFF
            return self._twcc

        def _send_rtp(self, raw, record_twcc=True):
            self.sent.append(raw)

    pc = FakePC()
    sender = MediaSender(pc, "video", ssrc=0x77, payload_type=102,
                         clock_rate=90000)
    sender.enable_fec(50)                    # 1 FEC per 2 media packets
    # large enough that the payloader fragments into several packets, so
    # the group-of-2 FEC encoder completes at least one group
    au = b"\x00\x00\x00\x01\x67\x01\x02" + b"\x00\x00\x00\x01\x65" + b"Q" * 3000
    sender.send_frame(au, timestamp=3000)
    pkts = [RtpPacket.parse(r) for r in pc.sent]
    assert all(p.payload_type == RED_PT for p in pkts)
    inner = [red_unwrap(p.payload)[0][0] for p in pkts]
    assert ULPFEC_PT in inner and 102 in inner

    # drop ONE media packet; the receiver must still produce the frame
    media = [p for p in pkts if red_unwrap(p.payload)[0][0] == 102]
    keep = [p for p in pkts if p is not media[0]]
    recv = MediaReceiver("video")
    frames = []
    recv.on_frame = lambda f, ts: frames.append(f)
    for p in keep:
        recv.feed_red(p)
    assert frames and frames[0].endswith(b"Q" * 3000)
    assert recv.fec.recovered_count == 1


def test_fec_recovery_end_to_end_no_nack():
    """Full stack loopback with deterministic media loss and NACK disabled:
    only FEC can heal the stream."""
    from selkies_tpu.webrtc.peerconnection import PeerConnection

    async def run():
        a = PeerConnection(interfaces=["127.0.0.1"])
        b = PeerConnection(interfaces=["127.0.0.1"])
        b._send_nacks = lambda: None         # force FEC-only recovery
        video = a.add_video_sender(ssrc=0xAA)
        video.enable_fec(50)
        got = []
        b.video_receiver().on_frame = lambda f, ts: got.append((f, ts))

        offer = await a.create_offer()
        await b.set_remote_description(offer, "offer")
        answer = await b.create_answer()
        await a.set_remote_description(answer, "answer")
        await asyncio.gather(a.wait_connected(15), b.wait_connected(15))

        # deterministically drop every 3rd MEDIA packet at the sender.
        # (A lost FEC packet is recovered by NACK/RTX in production — FEC's
        # own promise, tested here with NACK disabled, is healing media
        # loss with zero feedback round trips.)
        real_send = a._send_rtp
        media_count = [0]

        def lossy_send(raw, record_twcc=True):
            pkt = RtpPacket.parse(raw)
            inner_pt = pkt.payload[0] & 0x7F if pkt.payload else -1
            if pkt.payload_type == RED_PT and inner_pt != ULPFEC_PT:
                media_count[0] += 1
                if media_count[0] % 3 == 0:
                    return                   # media lost on the "wire"
            real_send(raw, record_twcc)

        a._send_rtp = lossy_send
        sps = bytes([0x67, 1, 2, 3])
        for i in range(12):
            au = (b"\x00\x00\x00\x01" + sps + b"\x00\x00\x00\x01" +
                  bytes([0x65]) + bytes([i]) * 700)
            video.send_frame(au, timestamp=i * 3000)
            await asyncio.sleep(0.02)
        for _ in range(150):
            if len(got) >= 12:
                break
            await asyncio.sleep(0.05)
        assert len(got) == 12, f"only {len(got)} frames under 33% media loss"
        assert b.video_receiver().fec.recovered_count >= 3
        await a.close()
        await b.close()

    asyncio.run(run())
