"""Regression tests for minijs semantics the reference-client oracle
depends on (VERDICT r3 item 7).

Round 3 proved interpreter gaps are a product hazard: the oracle test
was red because the reference client's settings handler called
``bool.toString()`` / ``[].toString()`` / ``ArrayBuffer.slice()`` and
minijs silently returned undefined for each. These tests pin the added
semantics so they cannot regress out from under the certification.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.minijs import Interp, JSArrayBuffer, UNDEF  # noqa: E402


def run(src):
    it = Interp()
    it.run("let __out; " + src)
    return it.globals.vars.get("__out")


def test_bool_tostring():
    assert run("__out = true.toString();") == "true"
    assert run("__out = false.toString();") == "false"
    # the oracle's actual shape: a settings value interpolated via toString
    assert run("let v = {value: true}; __out = '' + v.value.toString();") \
        == "true"


def test_bool_valueof():
    assert run("__out = true.valueOf();") is True


def test_array_tostring():
    assert run("__out = [1, 2, 3].toString();") == "1,2,3"
    assert run("__out = [].toString();") == ""
    # undefined/null stringify as empty slots, like join(',')
    assert run("__out = [1, undefined, null, 'x'].toString();") \
        == "1,,,x"
    # allowed-list interpolation, the sanitize log-line pattern
    assert run("let a = ['jpeg', 'x264enc']; __out = `[${a}]`;") \
        == "[jpeg,x264enc]"


def test_arraybuffer_slice_via_property():
    out = run(
        "let buf = new Uint8Array([1,2,3,4,5,6]).buffer;"
        "__out = new Uint8Array(buf.slice(2));")
    assert bytes(out.buffer.data) == bytes([3, 4, 5, 6])
    out = run(
        "let buf = new Uint8Array([1,2,3,4,5,6]).buffer;"
        "__out = new Uint8Array(buf.slice(1, 3));")
    assert bytes(out.buffer.data) == bytes([2, 3])


def test_arraybuffer_slice_is_copy():
    it = Interp()
    it.run(
        "let src = new Uint8Array([9, 9]);"
        "let cut = src.buffer.slice(0);"
        "src[0] = 1;"
        "let got = new Uint8Array(cut)[0];")
    assert it.globals.vars["got"] == 9.0
