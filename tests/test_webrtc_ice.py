"""STUN codec, ICE loopback connectivity, and SDP round-trip tests."""

import asyncio

import pytest

from selkies_tpu.webrtc import stun
from selkies_tpu.webrtc.ice import Candidate, IceAgent
from selkies_tpu.webrtc.sdp import (MediaSection, SessionDescription,
                                    default_audio_codecs,
                                    default_video_codecs)


# ------------------------------------------------------------------ STUN


def test_stun_roundtrip_with_integrity_and_fingerprint():
    msg = stun.StunMessage(method=stun.BINDING, msg_class=stun.CLASS_REQUEST)
    msg.set_username("remote:local")
    msg.attributes[stun.ATTR_PRIORITY] = (12345).to_bytes(4, "big")
    data = msg.serialize(integrity_key=b"swordfish")
    assert stun.is_stun(data)
    parsed = stun.StunMessage.parse(data)
    assert parsed.method == stun.BINDING
    assert parsed.msg_class == stun.CLASS_REQUEST
    assert parsed.username() == "remote:local"
    assert parsed.verify_integrity(b"swordfish")
    assert not parsed.verify_integrity(b"wrong")


def test_stun_xor_mapped_address():
    msg = stun.StunMessage(msg_class=stun.CLASS_SUCCESS)
    msg.set_xor_mapped_address(("192.0.2.1", 32853))
    got = stun.StunMessage.parse(msg.serialize()).xor_mapped_address()
    assert got == ("192.0.2.1", 32853)


def test_stun_error_attr():
    msg = stun.StunMessage(msg_class=stun.CLASS_ERROR)
    msg.set_error(401, "Unauthorized")
    code, reason = stun.StunMessage.parse(msg.serialize()).error()
    assert code == 401 and reason == "Unauthorized"


def test_stun_rejects_rtp():
    from selkies_tpu.webrtc.rtp import RtpPacket
    assert not stun.is_stun(RtpPacket(payload_type=96).serialize())


def test_message_type_interleave():
    # binding success response is 0x0101 on the wire
    assert stun.message_type(stun.BINDING, stun.CLASS_SUCCESS) == 0x0101
    assert stun.split_type(0x0101) == (stun.BINDING, stun.CLASS_SUCCESS)
    assert stun.message_type(stun.BINDING, stun.CLASS_REQUEST) == 0x0001


# ------------------------------------------------------------------ ICE


def test_ice_loopback_connect_and_data():
    async def run():
        a = IceAgent(controlling=True, interfaces=["127.0.0.1"])
        b = IceAgent(controlling=False, interfaces=["127.0.0.1"])
        await a.gather()
        await b.gather()
        assert a.local_candidates and b.local_candidates

        a.set_remote_credentials(b.local_ufrag, b.local_pwd)
        b.set_remote_credentials(a.local_ufrag, a.local_pwd)
        for c in b.local_candidates:
            a.add_remote_candidate(c)
        for c in a.local_candidates:
            b.add_remote_candidate(c)

        got_b = asyncio.get_running_loop().create_future()
        got_a = asyncio.get_running_loop().create_future()
        b.on_data = lambda d: got_b.done() or got_b.set_result(d)
        a.on_data = lambda d: got_a.done() or got_a.set_result(d)

        await asyncio.gather(a.connect(timeout=5), b.connect(timeout=5))
        assert a.selected_pair is not None and b.selected_pair is not None

        a.send(b"ping-from-a")
        assert await asyncio.wait_for(got_b, 2) == b"ping-from-a"
        b.send(b"pong-from-b")
        assert await asyncio.wait_for(got_a, 2) == b"pong-from-b"

        await a.close()
        await b.close()

    asyncio.run(run())


def test_candidate_sdp_roundtrip():
    c = Candidate("abcd1234", 1, "udp", 2130706431, "10.0.0.5", 9999, "host")
    line = c.to_sdp()
    assert Candidate.from_sdp(line) == c
    assert Candidate.from_sdp("a=" + line) == c


# ------------------------------------------------------------------ SDP


def test_sdp_offer_roundtrip():
    offer = SessionDescription(
        session_id=4242,
        bundle=["0", "1", "2"],
        media=[
            MediaSection(
                kind="video", mid="0", codecs=default_video_codecs(),
                ssrc=1111, cname="selkies", msid="stream track-v",
                ice_ufrag="uf", ice_pwd="pw",
                dtls_fingerprint="sha-256 AA:BB", dtls_setup="actpass",
                extmap={2: "http://www.ietf.org/id/draft-holmer-rmcat-"
                           "transport-wide-cc-extensions-01"},
                candidates=[Candidate("f", 1, "udp", 1, "1.2.3.4", 5, "host")],
            ),
            MediaSection(kind="audio", mid="1",
                         codecs=default_audio_codecs(), ssrc=2222),
            MediaSection(kind="application", mid="2", sctp_port=5000,
                         protocol="UDP/DTLS/SCTP", max_message_size=262144),
        ])
    text = offer.serialize()
    got = SessionDescription.parse(text)
    assert got.session_id == 4242
    assert got.bundle == ["0", "1", "2"]
    assert [m.kind for m in got.media] == ["video", "audio", "application"]

    v = got.media[0]
    assert v.codecs[0].name == "H264"
    assert v.codecs[0].payload_type == 102
    assert "packetization-mode=1" in v.codecs[0].fmtp
    assert "nack pli" in v.codecs[0].rtcp_fb
    assert v.ssrc == 1111 and v.msid == "stream track-v"
    assert v.ice_ufrag == "uf" and v.dtls_setup == "actpass"
    assert v.extmap[2].endswith("transport-wide-cc-extensions-01")
    assert len(v.candidates) == 1 and v.candidates[0].port == 5

    a = got.media[1]
    assert a.codecs[0].name == "opus" and a.codecs[0].channels == 2

    d = got.media[2]
    assert d.sctp_port == 5000 and d.max_message_size == 262144


def test_sdp_parses_browser_style_offer():
    text = (
        "v=0\r\no=- 77 2 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
        "a=group:BUNDLE 0\r\n"
        "m=video 9 UDP/TLS/RTP/SAVPF 96 97\r\n"
        "c=IN IP4 0.0.0.0\r\n"
        "a=mid:0\r\na=sendrecv\r\na=rtcp-mux\r\n"
        "a=ice-ufrag:x7Zy\r\na=ice-pwd:abcdefghijklmnopqrstuv\r\n"
        "a=setup:active\r\n"
        "a=rtpmap:96 VP8/90000\r\n"
        "a=rtpmap:97 H264/90000\r\n"
        "a=fmtp:97 packetization-mode=1\r\n"
        "a=candidate:1 1 UDP 2130706431 192.168.1.4 50000 typ host\r\n")
    got = SessionDescription.parse(text)
    m = got.media[0]
    assert [c.name for c in m.codecs] == ["VP8", "H264"]
    assert m.codecs[1].fmtp == "packetization-mode=1"
    assert m.candidates[0].host == "192.168.1.4"
    assert m.dtls_setup == "active"


def test_sdp_session_level_attributes_apply_to_media():
    # Firefox places fingerprint/ice credentials at session level; they
    # must flow down to every media section as defaults.
    text = (
        "v=0\r\no=- 88 2 IN IP4 127.0.0.1\r\ns=-\r\nt=0 0\r\n"
        "a=fingerprint:sha-256 AA:BB:CC\r\n"
        "a=ice-ufrag:sess-uf\r\na=ice-pwd:sess-pw\r\n"
        "a=setup:actpass\r\n"
        "a=group:BUNDLE 0 1\r\n"
        "m=video 9 UDP/TLS/RTP/SAVPF 96\r\n"
        "a=mid:0\r\na=rtpmap:96 H264/90000\r\n"
        "m=audio 9 UDP/TLS/RTP/SAVPF 111\r\n"
        "a=mid:1\r\na=ice-ufrag:media-uf\r\n"
        "a=rtpmap:111 opus/48000/2\r\n")
    got = SessionDescription.parse(text)
    assert got.bundle == ["0", "1"]
    for m in got.media:
        assert m.dtls_fingerprint == "sha-256 AA:BB:CC"
        assert m.dtls_setup == "actpass"
        assert m.ice_pwd == "sess-pw"
    # media-level values win over session defaults
    assert got.media[0].ice_ufrag == "sess-uf"
    assert got.media[1].ice_ufrag == "media-uf"
