"""The REFERENCE web client as the compatibility oracle (VERDICT r2 #5).

SURVEY §7 step 1 kept the wire grammar byte-identical with the
reference precisely so its client could certify this server. This test
executes the reference's real selkies-core.js (4.2k LoC, unmodified
except its two ES-module imports) under tools/minijs, bridges its
WebSocket to a live DataStreamingServer with the real JPEG encode
pipeline, and asserts the whole contract at once:

  * the reference client accepts our MODE/server_settings handshake
    and emits its SETTINGS payload, which our server parses;
  * our binary 0x03 stripes reach its ImageDecoder with decodable
    JPEG bytes at the right stripe offsets;
  * its CLIENT_FRAME_ACK heartbeat drives our backpressure state.

One green run certifies the entire wire surface against the client a
reference user actually runs. Reference: selkies-core.js:2720-2990.
"""

import asyncio
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from reference_env import (REFERENCE_CORE, fire_dom_ready,  # noqa: E402
                           make_reference_env)

pytestmark = pytest.mark.skipif(
    not os.path.isfile(REFERENCE_CORE),
    reason="reference checkout not mounted")


@pytest.mark.anyio
async def test_reference_client_negotiates_decodes_and_acks(tmp_path):
    import websockets
    import websockets.asyncio.server as ws_server

    from selkies_tpu.capture.synthetic import SyntheticSource
    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import (DataStreamingServer,
                                                default_encoder_factory)
    from selkies_tpu.settings import Settings

    # Single-value enum override = the reference's documented "lock the
    # choice" semantics (reference settings.py:29-31): the schema's allowed
    # list becomes ["jpeg"], so the client's sanitize pass switches its
    # stored x264enc default to jpeg and tells the server — the flow a
    # jpeg-only deployment exercises.
    settings = Settings(argv=[], env={"SELKIES_PORT": "0",
                                      "SELKIES_ENCODER": "jpeg"})
    app = StreamingApp(settings)
    server = DataStreamingServer(
        settings, app=app,
        source_factory=lambda w, h, fps, x=0, y=0: SyntheticSource(
            w, h, fps, pattern="scroll"),
        encoder_factory=default_encoder_factory,
        host="127.0.0.1")
    app.data_server = server
    server._stop_event = asyncio.Event()
    srv = await ws_server.serve(server.ws_handler, "127.0.0.1", 0,
                                compression=None, max_size=None)
    port = srv.sockets[0].getsockname()[1]

    # the reference client boots at DOMContentLoaded and opens its
    # socket; bridge that fake socket to the real server
    env = make_reference_env()
    fire_dom_ready(env)
    assert env.sockets, "reference client opened no websocket"
    fake_ws = env.sockets[0]
    assert fake_ws.url.endswith("/websockets")

    real_ws = await websockets.connect(
        f"ws://127.0.0.1:{port}/websockets", max_size=None)
    fake_ws.server_open()
    sent_idx = 0
    text_log = []

    async def pump():
        nonlocal sent_idx
        while True:
            while sent_idx < len(fake_ws.sent):
                m = fake_ws.sent[sent_idx]
                sent_idx += 1
                if isinstance(m, str):
                    text_log.append(m)
                await real_ws.send(m)
            env.interp.fire_timers(1)      # ACK heartbeat interval
            await asyncio.sleep(0.01)

    pump_task = asyncio.create_task(pump())

    async def feed():
        async for msg in real_ws:
            if isinstance(msg, bytes):
                fake_ws.server_binary(msg)
            else:
                fake_ws.server_text(msg)

    feed_task = asyncio.create_task(feed())

    def check_bridge():
        # a minijs gap inside a handler must fail the test loudly, not
        # decay into a timeout (VERDICT r3 weak #1/#7)
        for t in (pump_task, feed_task):
            if t.done() and not t.cancelled() and t.exception():
                raise t.exception()

    try:
        # 1. the reference client's SETTINGS handshake parsed server-side
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            check_bridge()
            if server.display_clients:
                break
            await asyncio.sleep(0.05)
        assert server.display_clients, \
            f"server never registered the client; sent={text_log[:3]}"
        settings_msgs = [m for m in text_log if m.startswith("SETTINGS,")]
        assert settings_msgs, text_log[:5]
        payload = json.loads(settings_msgs[0].split(",", 1)[1])
        assert "initialClientWidth" in payload
        # the locked enum actually drove the client off its x264enc
        # default: its sanitize pass reported the switch
        assert any('"encoder": "jpeg"' in m or "'encoder': 'jpeg'" in m
                   or '"encoder":"jpeg"' in m for m in text_log), \
            "client never adopted the server-locked jpeg encoder"

        # 2. our 0x03 stripes reach its ImageDecoder as decodable JPEG
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            check_bridge()
            if len(env.image_decoders) >= 6:
                break
            await asyncio.sleep(0.05)
        assert len(env.image_decoders) >= 6, \
            "reference client decoded no JPEG stripes"
        import io
        from PIL import Image
        for dec in env.image_decoders[:6]:
            assert dec.type == "image/jpeg"
            img = Image.open(io.BytesIO(dec.data))
            img.load()                    # PIL = independent decode proof

        # 3. its CLIENT_FRAME_ACK heartbeat reached our backpressure gate
        deadline = time.monotonic() + 30
        acked = 0
        while time.monotonic() < deadline:
            check_bridge()
            st = next(iter(server.display_clients.values()))
            acked = st.bp.acknowledged_frame_id
            if acked > 0:
                break
            await asyncio.sleep(0.05)
        assert acked > 0, "no CLIENT_FRAME_ACK processed by the server"
        assert any(m.startswith("CLIENT_FRAME_ACK") for m in text_log)
    finally:
        pump_task.cancel()
        feed_task.cancel()
        await real_ws.close()
        await server.stop()
        srv.close()
