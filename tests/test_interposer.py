"""C joystick-interposer integration: LD_PRELOAD subprocess opens
/dev/input/js0, queries ioctls, and reads a live event from the
VirtualGamepad unix-socket server.

Parity target: addons/js-interposer + its manual js-interposer-test.py
harness in the reference (SURVEY.md §2.2, §4) — here automated."""

import asyncio
import os
import shutil
import struct
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC_DIR = os.path.join(ROOT, "native", "interposer")
SHIM = os.path.join(SRC_DIR, "selkies_joystick_interposer.so")


def build_shim():
    if os.path.exists(SHIM):
        return True
    if shutil.which("make") is None or shutil.which("cc") is None:
        return False
    r = subprocess.run(["make", "-C", SRC_DIR], capture_output=True)
    return r.returncode == 0 and os.path.exists(SHIM)


CHILD_SCRIPT = textwrap.dedent("""
    import fcntl, os, struct, sys
    fd = os.open("/dev/input/js0", os.O_RDONLY)
    # JSIOCGAXES / JSIOCGBUTTONS / JSIOCGNAME(128)
    buf = bytearray(1)
    fcntl.ioctl(fd, 0x80016a11, buf)       # JSIOCGAXES
    axes = buf[0]
    buf = bytearray(1)
    fcntl.ioctl(fd, 0x80016a12, buf)       # JSIOCGBUTTONS
    btns = buf[0]
    name = bytearray(128)
    fcntl.ioctl(fd, 0x80806a13, name)      # JSIOCGNAME(128)
    name = name.split(b"\\0")[0].decode()
    ev = os.read(fd, 8)                     # one js_event
    t_ms, value, etype, num = struct.unpack("=IhBB", ev)
    print(f"{axes} {btns} {etype} {num} {value} {name}")
    os.close(fd)
""")


@pytest.mark.skipif(not build_shim(), reason="C toolchain unavailable")
def test_interposer_end_to_end(tmp_path):
    from selkies_tpu.input.gamepad import VirtualGamepad

    async def run():
        pad = VirtualGamepad(0, socket_dir=str(tmp_path))
        await pad.start()

        env = dict(os.environ)
        env["LD_PRELOAD"] = SHIM
        env["SELKIES_INTERPOSER_SOCKET_DIR"] = str(tmp_path)
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-c", CHILD_SCRIPT,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)

        # wait for the child to connect, then press button A
        for _ in range(100):
            if pad.client_count:
                break
            await asyncio.sleep(0.05)
        assert pad.client_count, "child never connected through the shim"
        pad.send_button(0, 1.0)

        out, err = await asyncio.wait_for(proc.communicate(), 15)
        assert proc.returncode == 0, err.decode()
        axes, btns, etype, num, value, name = out.decode().split(None, 5)
        assert int(axes) == 8
        assert int(btns) == 11
        assert "X-Box 360" in name
        assert int(etype) == 1      # JS_EVENT_BUTTON
        assert int(num) == 0 and int(value) == 1
        await pad.stop()

    asyncio.run(run())


@pytest.mark.skipif(not build_shim(), reason="C toolchain unavailable")
def test_interposer_evdev_ioctls(tmp_path):
    from selkies_tpu.input.gamepad import VirtualGamepad

    child = textwrap.dedent("""
        import fcntl, os
        fd = os.open("/dev/input/event1000", os.O_RDONLY)
        ver = bytearray(4)
        fcntl.ioctl(fd, 0x80044501, ver)     # EVIOCGVERSION
        iid = bytearray(8)
        fcntl.ioctl(fd, 0x80084502, iid)     # EVIOCGID
        import struct
        bus, vid, pid, rev = struct.unpack("=HHHH", iid)
        name = bytearray(64)
        fcntl.ioctl(fd, 0x80404506, name)    # EVIOCGNAME(64)
        print(hex(vid), hex(pid), name.split(b"\\0")[0].decode())
        os.close(fd)
    """)

    async def run():
        pad = VirtualGamepad(0, socket_dir=str(tmp_path))
        await pad.start()
        env = dict(os.environ)
        env["LD_PRELOAD"] = SHIM
        env["SELKIES_INTERPOSER_SOCKET_DIR"] = str(tmp_path)
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-c", child,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        out, err = await asyncio.wait_for(proc.communicate(), 15)
        assert proc.returncode == 0, err.decode()
        vid, pid, name = out.decode().split(None, 2)
        assert vid == "0x45e" and pid == "0x28e"
        assert "X-Box 360" in name
        await pad.stop()

    asyncio.run(run())


FAKE_UDEV_DIR = os.path.join(ROOT, "native", "fake-udev")
FAKE_UDEV = os.path.join(FAKE_UDEV_DIR, "libudev.so.1.0.0-fake")

UDEV_TEST_C = os.path.join(FAKE_UDEV_DIR, ".test_udev.c")

UDEV_TEST_SRC = r'''
#include <stdio.h>
#include <string.h>
struct udev; struct udev_enumerate; struct udev_list_entry;
struct udev_device; struct udev_monitor;
extern struct udev *udev_new(void);
extern struct udev_enumerate *udev_enumerate_new(struct udev *);
extern int udev_enumerate_add_match_subsystem(struct udev_enumerate *, const char *);
extern int udev_enumerate_scan_devices(struct udev_enumerate *);
extern struct udev_list_entry *udev_enumerate_get_list_entry(struct udev_enumerate *);
extern struct udev_list_entry *udev_list_entry_get_next(struct udev_list_entry *);
extern const char *udev_list_entry_get_name(struct udev_list_entry *);
extern struct udev_device *udev_device_new_from_syspath(struct udev *, const char *);
extern const char *udev_device_get_devnode(struct udev_device *);
extern const char *udev_device_get_property_value(struct udev_device *, const char *);
int main(void) {
    struct udev *u = udev_new();
    struct udev_enumerate *e = udev_enumerate_new(u);
    udev_enumerate_add_match_subsystem(e, "input");
    udev_enumerate_scan_devices(e);
    int n = 0, joy = 0;
    struct udev_list_entry *ent = udev_enumerate_get_list_entry(e);
    for (; ent; ent = udev_list_entry_get_next(ent)) {
        struct udev_device *d =
            udev_device_new_from_syspath(u, udev_list_entry_get_name(ent));
        const char *j = udev_device_get_property_value(d, "ID_INPUT_JOYSTICK");
        const char *node = udev_device_get_devnode(d);
        if (node && j && !strcmp(j, "1")) joy++;
        n++;
    }
    printf("%d %d\n", n, joy);
    return 0;
}
'''


def build_fake_udev():
    if not os.path.exists(FAKE_UDEV):
        if shutil.which("make") is None or shutil.which("cc") is None:
            return False
        r = subprocess.run(["make", "-C", FAKE_UDEV_DIR], capture_output=True)
        if r.returncode != 0:
            return False
    return os.path.exists(FAKE_UDEV)


@pytest.mark.skipif(not build_fake_udev(), reason="C toolchain unavailable")
def test_fake_udev_enumeration(tmp_path):
    src = tmp_path / "t.c"
    src.write_text(UDEV_TEST_SRC)
    binary = tmp_path / "t"
    r = subprocess.run(["cc", "-o", str(binary), str(src), FAKE_UDEV],
                       capture_output=True)
    assert r.returncode == 0, r.stderr.decode()
    env = dict(os.environ)
    env["LD_PRELOAD"] = FAKE_UDEV
    out = subprocess.run([str(binary)], env=env, capture_output=True)
    assert out.returncode == 0
    n, joy = out.stdout.split()
    assert (int(n), int(joy)) == (8, 8)
