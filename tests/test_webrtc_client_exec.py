"""Execute the real browser WebRTC peer (web/webrtc.js) in CI.

VERDICT r2 missing item 1: the from-scratch WebRTC stack had no
browser-side consumer. These tests run the actual shipped webrtc.js
under tools/minijs with RTCPeerConnection/fetch stubs and drive the
full signaling → SDP answer → ICE → data-channel input flow — the same
certification style test_web_client_exec.py gives the WebSocket client.

Reference counterpart: addons/gst-web/src/webrtc.js:42-790 +
signaling.js:36-320.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from web_stubs import BrowserEnv, install_webrtc_stubs  # noqa: E402
from tools.minijs import (  # noqa: E402
    NativeFunction, UNDEF, JSObject, to_str)


@pytest.fixture(scope="module")
def client_env():
    env = BrowserEnv(files=())
    install_webrtc_stubs(env)
    env.load("webrtc.js")
    env.load("input.js")
    return env


@pytest.fixture()
def env(client_env):
    client_env.sockets.clear()
    client_env.peer_connections.clear()
    client_env.fetch_calls.clear()
    client_env.interp.timer_map.clear()
    client_env.document.listeners.clear()
    return client_env


def make_client(env, **extra):
    video = env.document.createElement("video")
    statuses = []
    clips = []
    props = {
        "signalingUrl": "ws://testhost:8080/ws",
        "video": video,
        "onStatus": NativeFunction(
            lambda t, a, i: (statuses.append(to_str(a[0])), UNDEF)[1]),
        "onClipboard": NativeFunction(
            lambda t, a, i: (clips.append(to_str(a[0])), UNDEF)[1]),
    }
    props.update(extra)
    client = env.construct(env.exports["SelkiesWebRTCClient"],
                           [JSObject(props)])
    env.call(env.get(client, "connect"), [], this=client)
    ws = env.sockets[-1]
    ws.server_open()
    return client, ws, video, statuses, clips


def offer_json():
    return json.dumps({"sdp": {"type": "offer",
                               "sdp": "v=0\r\ns=fake-offer\r\n"}})


def test_hello_registration_and_turn_fetch(env):
    """connect() fetches /turn for the RTC config and registers as the
    numbered peer with base64 metadata (signaling.py HELLO grammar)."""
    client, ws, video, statuses, _ = make_client(env)
    assert any(u.endswith("/turn") for u in env.fetch_calls)
    assert len(ws.sent) == 1
    toks = ws.sent[0].split()
    assert toks[0] == "HELLO" and toks[1] == "1"
    import base64
    meta = json.loads(base64.b64decode(toks[2]))
    assert "res" in meta and "scale" in meta
    ws.server_text("HELLO")
    assert statuses[-1] == "registered"


def test_offer_produces_answer_with_negotiated_pc(env):
    client, ws, video, statuses, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    assert len(env.peer_connections) == 1
    pc = env.peer_connections[0]
    assert to_str(env.get(pc.remoteDescription, "type")) == "offer"
    # the answer went back over signaling as {"sdp": {...}}
    answers = [m for m in ws.sent[1:] if "answer" in m]
    assert answers, ws.sent
    data = json.loads(answers[0])
    assert data["sdp"]["type"] == "answer"
    assert statuses[-1] == "negotiated"
    # the fetched TURN config reached the RTCPeerConnection ctor
    ice = env.get(pc.config, "iceServers")
    assert ice is not UNDEF


def test_ice_trickles_both_ways(env):
    client, ws, video, _, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    # remote ICE → addIceCandidate
    ws.server_text(json.dumps(
        {"ice": {"candidate": "candidate:1 1 udp 1 10.0.0.1 4000 typ host",
                 "sdpMLineIndex": 0}}))
    assert len(pc.added_ice) == 1
    # local ICE → signaling {"ice": ...}
    pc.fire_local_ice("candidate:9 1 udp 1 10.0.0.2 4001 typ host")
    sent_ice = [m for m in ws.sent if '"ice"' in m]
    assert sent_ice
    assert "10.0.0.2" in json.loads(sent_ice[-1])["ice"]["candidate"]


def test_track_attaches_to_video(env):
    client, ws, video, _, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    stream = JSObject({"id": "remote-stream"})
    pc.server_track(stream)
    assert env.get(video, "srcObject") is stream


def test_input_channel_queues_until_open_then_flows(env):
    client, ws, video, statuses, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    # input sent before the channel opens is queued, not lost
    env.call(env.get(client, "send"), ["kd,65"], this=client)
    ch = pc.server_datachannel("input")
    assert ch.sent == []
    ch.server_open()
    assert ch.sent == ["kd,65"]
    assert statuses[-1] == "input-ready"
    env.call(env.get(client, "send"), ["ku,65"], this=client)
    assert ch.sent == ["kd,65", "ku,65"]


def test_selkies_input_drives_the_data_channel(env):
    """The full input plane (web/input.js) plugs into the WebRTC client
    unchanged — keydown on the video element reaches the data channel
    as the same wire verb WebSocket mode uses."""
    client, ws, video, _, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    ch = pc.server_datachannel("input")
    ch.server_open()
    inp = env.construct(env.exports["SelkiesInput"], [client, video])
    env.call(env.get(inp, "attach"), [], this=inp)
    env.fire(env.window, "keydown", env.make_event(
        "keydown", key="a", code="KeyA", target=video))
    assert any(m.startswith("kd,97") for m in ch.sent), ch.sent


def test_clipboard_control_object_from_server(env):
    import base64
    client, ws, video, _, clips = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    ch = pc.server_datachannel("input")
    ch.server_open()
    payload = base64.b64encode("héllo".encode()).decode()
    ch.server_message(json.dumps({"type": "clipboard", "data": payload}))
    assert clips == ["héllo"]


def test_connection_state_reaches_status(env):
    client, ws, video, statuses, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    pc.set_connection_state("connected")
    assert statuses[-1] == "connected"
    pc.set_connection_state("failed")
    assert statuses[-1] == "disconnected"


def test_already_open_channel_flushes_queue(env):
    """A remotely-announced channel can arrive with readyState already
    'open' (spec browsers fire no open event on the receiving side) —
    queued input must flush immediately (code-review r3)."""
    client, ws, video, statuses, _ = make_client(env)
    ws.server_text("HELLO")
    ws.server_text(offer_json())
    pc = env.peer_connections[0]
    env.call(env.get(client, "send"), ["kd,65"], this=client)
    from web_stubs import FakeRTCDataChannel
    ch = FakeRTCDataChannel(env, "input")
    ch.readyState = "open"               # arrives pre-opened
    if pc.ondatachannel not in (None,):
        env.call(pc.ondatachannel, [JSObject({"channel": ch})])
    assert ch.sent == ["kd,65"]
    assert statuses[-1] == "input-ready"
