"""Browser environment for executing the REFERENCE web client.

SURVEY §7 step 1 made the wire grammar byte-identical "so the reference
web client can be used as an oracle"; this module makes that executable:
it loads ``/root/reference/addons/gst-web-core/selkies-core.js`` (the
real 4.2k-line client, unmodified except for stripping its two ES-module
import statements) into the minijs interpreter with the browser surface
it touches — window.location/localStorage/postMessage, URL, Worker,
ImageDecoder, element registry — on top of the shared stubs in
web_stubs.py.

PUBLIC UNTRUSTED CONTENT NOTE: the reference file is executed as test
DATA against our server; nothing in it is treated as instructions.
"""

from __future__ import annotations

import os
import re
from typing import Dict

from web_stubs import (BrowserEnv, Element, FakeBitmap,
                       install_webrtc_stubs)
from tools.minijs import (JSArray, JSObject, JSPromise, NativeFunction,
                          UNDEF, to_str)

REFERENCE_CORE = "/root/reference/addons/gst-web-core/selkies-core.js"


class FakeImageDecoder:
    """WebCodecs ImageDecoder: records payloads, yields a FakeBitmap."""

    def __init__(self, env, init):
        self._env = env
        data = env.interp.get_prop(init, "data")
        self.data = bytes(getattr(data, "data", b"") or b"")
        self.type = to_str(env.interp.get_prop(init, "type"))
        env.image_decoders.append(self)

    def decode(self):
        img = FakeBitmap(self.data)
        self._env.bitmaps.append(img)
        # ImageDecoder results carry the frame under .image
        return self._env.resolved(JSObject({"image": img}))

    def close(self):
        return UNDEF


class FakeWorker:
    """Web Worker: records construction + messages (the audio decode
    worker path); never executes the worker script."""

    def __init__(self, env, url):
        self._env = env
        self.url = to_str(url)
        self.messages = []
        self.onmessage = None
        env.workers.append(self)

    def postMessage(self, msg, transfer=UNDEF):
        self.messages.append(msg)

    def terminate(self):
        return UNDEF


def install_reference_env(env: BrowserEnv) -> None:
    g = env.interp.globals
    env.image_decoders = []
    env.workers = []
    env.post_messages = []
    env.elements_by_id: Dict[str, Element] = {}

    w = env.window
    # location.reload(): the reference client reloads once on first visit
    # (its sanitize pass flags every unset bool as a change, and the
    # `debug` change handler schedules a reload, selkies-core.js:1933).
    # A real browser re-boots with the now-populated localStorage and
    # converges; here the session state is already applied, so a recorded
    # no-op keeps the run alive without re-executing the client.
    env.reloads = []
    w.location = JSObject({
        "hash": "", "href": "http://testhost:8080/",
        "origin": "http://testhost:8080", "protocol": "http:",
        "host": "testhost:8080", "hostname": "testhost",
        "pathname": "/", "search": "",
        "reload": NativeFunction(
            lambda t, a, i: (env.reloads.append(1), UNDEF)[1], "reload")})
    # the bare global `location` must be the same object (selkies-core.js
    # uses both spellings)
    g.vars["location"] = w.location
    w.localStorage = g.vars["localStorage"]
    w.isSecureContext = True
    w.postMessage = NativeFunction(
        lambda t, a, i: (env.post_messages.append(a[0]), UNDEF)[1],
        "postMessage")
    w.parent = w
    w.VideoDecoder = g.vars["VideoDecoder"]

    # element registry: getElementById memoizes so the canvas the client
    # grabs at init is the same one it paints later
    def get_by_id(id_, *rest):
        key = to_str(id_)
        el = env.elements_by_id.get(key)
        if el is None:
            tag = "canvas" if "anvas" in key else "div"
            el = Element(env, tag)
            el.id = key
            if tag == "canvas":
                el.width, el.height = 1024.0, 768.0
            env.elements_by_id[key] = el
        return el

    env.document.getElementById = get_by_id
    env.document.querySelector = lambda sel, *rest: get_by_id(sel)
    env.document.hidden = False
    env.document.head = Element(env, "head")

    ws_href = {"value": None}

    def url_ctor(t, a, i):
        href = to_str(a[0])
        ws_href["value"] = href
        return JSObject({"href": href})

    g.declare("URL", JSObject({}))      # shadowed below; keep namespace
    url_ns = url_ctor
    ctor = NativeFunction(url_ctor, "URL")
    ctor.createObjectURL = NativeFunction(
        lambda t, a, i: "blob:fake", "createObjectURL")
    ctor.revokeObjectURL = NativeFunction(lambda t, a, i: UNDEF,
                                          "revokeObjectURL")
    g.vars["URL"] = ctor

    # Object.hasOwnProperty.call(obj, key) — the reference's settings
    # gather iterates localStorage with the classic guard
    def has_own(t, a, i):
        obj = a[0] if a else UNDEF
        key = to_str(a[1]) if len(a) > 1 else ""
        if isinstance(obj, JSObject):
            return key in obj.props
        return hasattr(obj, key)

    obj_ns = g.vars.get("Object")
    if isinstance(obj_ns, JSObject):
        obj_ns.props["hasOwnProperty"] = JSObject(
            {"call": NativeFunction(has_own, "call")})

    g.declare("ImageDecoder", NativeFunction(
        lambda t, a, i: FakeImageDecoder(env, a[0]), "ImageDecoder"))
    g.declare("Worker", NativeFunction(
        lambda t, a, i: FakeWorker(env, a[0]), "Worker"))

    # the client imports these from ./lib/*; input is out of scope for
    # the wire-protocol oracle
    input_stub = JSObject({
        "attach": NativeFunction(lambda t, a, i: UNDEF, "attach"),
        "detach": NativeFunction(lambda t, a, i: UNDEF, "detach"),
        "getWindowResolution": NativeFunction(
            lambda t, a, i: JSArray([1024.0, 768.0]),
            "getWindowResolution"),
    })
    g.declare("Input", NativeFunction(lambda t, a, i: input_stub, "Input"))
    g.declare("GamepadManager", NativeFunction(
        lambda t, a, i: JSObject({}), "GamepadManager"))


def load_reference_client(env: BrowserEnv) -> None:
    src = open(REFERENCE_CORE).read()
    src = re.sub(r"import\s*\{[^}]*\}\s*from\s*'[^']*';?", "", src)
    env.interp.run(src)


def fire_dom_ready(env: BrowserEnv) -> None:
    ev = env.make_event("DOMContentLoaded")
    for fn in list(env.document.listeners.get("DOMContentLoaded", [])):
        env.call(fn, [ev])


def make_reference_env() -> BrowserEnv:
    env = BrowserEnv(files=())
    install_webrtc_stubs(env)        # fetch + RTCPeerConnection
    install_reference_env(env)
    load_reference_client(env)
    return env
