"""SCTP association + DCEP data channel tests, standalone and over DTLS.

Parity target: vendored ``webrtc/rtcsctptransport.py`` (SURVEY.md §2.4) and
the reference's "input" data channel semantics
(``legacy/gstwebrtc_app.py:1700-1704``)."""

import random

import pytest

from selkies_tpu.webrtc.sctp import (DataChannel, SctpAssociation, crc32c,
                                     crc32c_fast, tsn_gt)


def pump(a, b, qa, qb, drop=None, iters=400):
    rng = random.Random(3)
    clock = 1e6
    for _ in range(iters):
        moved = False
        while qa:
            d = qa.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                b.receive(d)
        while qb:
            d = qb.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                a.receive(d)
        if not moved:
            clock += 20.0   # advance the virtual clock past every RTO tier
            a.check_retransmit(now=clock)
            b.check_retransmit(now=clock)
            if not qa and not qb:
                return


def make_pair():
    qa, qb = [], []
    a = SctpAssociation(is_client=True, on_send=qa.append)
    b = SctpAssociation(is_client=False, on_send=qb.append)
    return a, b, qa, qb


def test_crc32c_vectors():
    # well-known CRC32c check value for "123456789"
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c_fast(b"123456789") == 0xE3069283
    assert crc32c_fast(b"") == 0


def test_tsn_compare():
    assert tsn_gt(1, 0)
    assert tsn_gt(0, 0xFFFFFFFF)   # wraparound
    assert not tsn_gt(5, 5)
    assert not tsn_gt(0xFFFFFFFF, 0)


def test_association_and_channel():
    a, b, qa, qb = make_pair()
    opened = []
    b.on_channel = opened.append
    b.start()
    a.start()
    pump(a, b, qa, qb)
    assert a.state == "established" and b.state == "established"

    ch = a.create_channel("input", protocol="selkies")
    pump(a, b, qa, qb)
    assert ch.open
    assert opened and opened[0].label == "input"
    assert opened[0].protocol == "selkies"

    got = []
    opened[0].on_message = got.append
    a.send(ch, "kd,65")
    a.send(ch, b"\x01\x02\x03")
    a.send(ch, "")
    pump(a, b, qa, qb)
    assert got == [b"kd,65", b"\x01\x02\x03", b""]


def test_bidirectional_channels():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch_a = a.create_channel("from-client")
    ch_b = b.create_channel("from-server")
    pump(a, b, qa, qb)
    # odd/even stream id split avoids collisions (RFC 8832 §6)
    assert ch_a.stream_id % 2 == 0
    assert ch_b.stream_id % 2 == 1
    assert ch_a.open and ch_b.open


def test_large_message_fragmentation():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("files")
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    blob = bytes(range(256)) * 40   # 10240 bytes, ~9 fragments
    a.send(ch, blob)
    pump(a, b, qa, qb)
    assert got == [blob]


def test_retransmission_under_loss():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb, drop=0.2, iters=2000)
    assert a.state == "established"
    ch = a.create_channel("lossy")
    pump(a, b, qa, qb, drop=0.2, iters=2000)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    for i in range(20):
        a.send(ch, b"msg-%d" % i)
    pump(a, b, qa, qb, drop=0.2, iters=4000)
    assert set(got) == {b"msg-%d" % i for i in range(20)}


def test_sctp_over_dtls():
    from selkies_tpu.webrtc.dtls import DtlsEndpoint, DtlsCertificate
    from tests.test_webrtc_dtls import make_pair as dtls_pair, pump as dtls_pump

    client, server, co, so = dtls_pair()
    server.start()
    client.start()
    assert dtls_pump(client, server, co, so)

    a = SctpAssociation(is_client=True, on_send=client.send_app_data)
    b = SctpAssociation(is_client=False, on_send=server.send_app_data)
    client.on_data = a.receive
    server.on_data = b.receive

    b.start()
    a.start()
    for _ in range(50):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
        if a.state == "established" and b.state == "established":
            break
    assert a.state == "established"

    ch = a.create_channel("input")
    got = []
    b.on_channel = lambda c: setattr(c, "on_message", got.append)
    for _ in range(50):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
        if ch.open:
            break
    a.send(ch, "m,100,200,0,0")
    for _ in range(20):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
    assert got == [b"m,100,200,0,0"]
