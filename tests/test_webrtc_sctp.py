"""SCTP association + DCEP data channel tests, standalone and over DTLS.

Parity target: vendored ``webrtc/rtcsctptransport.py`` (SURVEY.md §2.4) and
the reference's "input" data channel semantics
(``legacy/gstwebrtc_app.py:1700-1704``)."""

import random

import pytest

from selkies_tpu.webrtc.sctp import (MTU as MTU_BYTES, DataChannel,
                                     SctpAssociation, crc32c, crc32c_fast,
                                     tsn_gt)


def pump(a, b, qa, qb, drop=None, iters=400):
    rng = random.Random(3)
    clock = 1e6
    for _ in range(iters):
        moved = False
        while qa:
            d = qa.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                b.receive(d)
        while qb:
            d = qb.pop(0)
            moved = True
            if drop is None or rng.random() > drop:
                a.receive(d)
        if not moved:
            clock += 20.0   # advance the virtual clock past every RTO tier
            a.check_retransmit(now=clock)
            b.check_retransmit(now=clock)
            if not qa and not qb:
                return


def make_pair():
    qa, qb = [], []
    a = SctpAssociation(is_client=True, on_send=qa.append)
    b = SctpAssociation(is_client=False, on_send=qb.append)
    return a, b, qa, qb


def test_crc32c_vectors():
    # well-known CRC32c check value for "123456789"
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c_fast(b"123456789") == 0xE3069283
    assert crc32c_fast(b"") == 0


def test_tsn_compare():
    assert tsn_gt(1, 0)
    assert tsn_gt(0, 0xFFFFFFFF)   # wraparound
    assert not tsn_gt(5, 5)
    assert not tsn_gt(0xFFFFFFFF, 0)


def test_association_and_channel():
    a, b, qa, qb = make_pair()
    opened = []
    b.on_channel = opened.append
    b.start()
    a.start()
    pump(a, b, qa, qb)
    assert a.state == "established" and b.state == "established"

    ch = a.create_channel("input", protocol="selkies")
    pump(a, b, qa, qb)
    assert ch.open
    assert opened and opened[0].label == "input"
    assert opened[0].protocol == "selkies"

    got = []
    opened[0].on_message = got.append
    a.send(ch, "kd,65")
    a.send(ch, b"\x01\x02\x03")
    a.send(ch, "")
    pump(a, b, qa, qb)
    assert got == [b"kd,65", b"\x01\x02\x03", b""]


def test_bidirectional_channels():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch_a = a.create_channel("from-client")
    ch_b = b.create_channel("from-server")
    pump(a, b, qa, qb)
    # odd/even stream id split avoids collisions (RFC 8832 §6)
    assert ch_a.stream_id % 2 == 0
    assert ch_b.stream_id % 2 == 1
    assert ch_a.open and ch_b.open


def test_large_message_fragmentation():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("files")
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    blob = bytes(range(256)) * 40   # 10240 bytes, ~9 fragments
    a.send(ch, blob)
    pump(a, b, qa, qb)
    assert got == [blob]


def test_retransmission_under_loss():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb, drop=0.2, iters=2000)
    assert a.state == "established"
    ch = a.create_channel("lossy")
    pump(a, b, qa, qb, drop=0.2, iters=2000)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    for i in range(20):
        a.send(ch, b"msg-%d" % i)
    pump(a, b, qa, qb, drop=0.2, iters=4000)
    # ordered channel: exact send order must survive loss + retransmission
    assert got == [b"msg-%d" % i for i in range(20)]


def test_sctp_over_dtls():
    from selkies_tpu.webrtc.dtls import DtlsEndpoint, DtlsCertificate
    from tests.test_webrtc_dtls import make_pair as dtls_pair, pump as dtls_pump

    client, server, co, so = dtls_pair()
    server.start()
    client.start()
    assert dtls_pump(client, server, co, so)

    a = SctpAssociation(is_client=True, on_send=client.send_app_data)
    b = SctpAssociation(is_client=False, on_send=server.send_app_data)
    client.on_data = a.receive
    server.on_data = b.receive

    b.start()
    a.start()
    for _ in range(50):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
        if a.state == "established" and b.state == "established":
            break
    assert a.state == "established"

    ch = a.create_channel("input")
    got = []
    b.on_channel = lambda c: setattr(c, "on_message", got.append)
    for _ in range(50):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
        if ch.open:
            break
    a.send(ch, "m,100,200,0,0")
    for _ in range(20):
        while co:
            server.receive(co.pop(0))
        while so:
            client.receive(so.pop(0))
    assert got == [b"m,100,200,0,0"]


def test_ordered_delivery_under_reordering():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("input")   # ordered (default)
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    a.send(ch, b"kd,65")
    a.send(ch, b"ku,65")
    a.send(ch, b"kd,66")
    packets = [qa.pop(0) for _ in range(len(qa))]
    for p in reversed(packets):      # worst-case UDP reordering
        b.receive(p)
    assert got == [b"kd,65", b"ku,65", b"kd,66"]


def test_unordered_channel_delivers_immediately():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("stats", ordered=False)
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    a.send(ch, b"one")
    a.send(ch, b"two")
    packets = [qa.pop(0) for _ in range(len(qa))]
    for p in reversed(packets):
        b.receive(p)
    # unordered: surfaced in arrival order, no holdback
    assert sorted(got) == [b"one", b"two"]
    assert got == [b"two", b"one"]


def test_sack_gap_beyond_u16_does_not_raise():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("jumpy")
    pump(a, b, qa, qb)
    # a TSN far (>65535) ahead of b's cumulative ack must not blow up
    # b's SACK encoding (struct 'H' overflow) in the receive path
    a.next_tsn = (a.next_tsn + 0x20000) & 0xFFFFFFFF
    a.send(ch, b"far-future")
    while qa:
        b.receive(qa.pop(0))
    while qb:
        a.receive(qb.pop(0))


def test_unordered_fragmented_interleaved():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("bulk", ordered=False)
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append
    m1 = b"X" * 1500   # 2 fragments each; unordered messages all carry
    m2 = b"Y" * 1500   # the same SSN, so reassembly must key on TSN runs
    a.send(ch, m1)
    a.send(ch, m2)
    pkts = [qa.pop(0) for _ in range(len(qa))]
    assert len(pkts) == 4
    b.receive(pkts[0])   # B1
    b.receive(pkts[2])   # B2 (interleaved)
    b.receive(pkts[1])   # E1
    b.receive(pkts[3])   # E2
    assert got == [m1, m2]


def test_forward_tsn_unblocks_ordered_hold():
    import struct as _s
    from selkies_tpu.webrtc.sctp import CT_FORWARD_TSN
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("input")
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append

    lost_tsn = a.next_tsn
    lost_ssn = a._ssn.get(ch.stream_id, 0)   # DCEP OPEN consumed ssn 0
    a.send(ch, b"lost")      # this packet will be dropped
    qa.pop(0)
    a.send(ch, b"held")      # arrives, must be held back
    b.receive(qa.pop(0))
    assert got == []         # ordered: held behind the lost ssn

    # peer abandons the lost chunk (RFC 3758 FORWARD TSN)
    body = _s.pack("!IHH", lost_tsn, ch.stream_id, lost_ssn)
    a._send_packet([a._chunk(CT_FORWARD_TSN, 0, body)])
    while qa:
        b.receive(qa.pop(0))
    assert got == [b"held"]  # hold released, stream alive
    a._out.clear()           # the abandoned chunk is no longer our problem
    got_after = []
    b.channels[ch.stream_id].on_message = lambda m: got_after.append(m)
    a.send(ch, b"next")
    pump(a, b, qa, qb)
    assert got_after == [b"next"]


def test_forward_tsn_delivers_skipped_over_hold():
    import struct as _s
    from selkies_tpu.webrtc.sctp import CT_FORWARD_TSN
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("input")
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append

    base_ssn = a._ssn.get(ch.stream_id, 0)
    tsns, pkts = [], []
    for m in (b"s0-lost", b"s1-held", b"s2-held", b"s3-lost"):
        tsns.append(a.next_tsn)
        a.send(ch, m)
        pkts.append(qa.pop(0))
    b.receive(pkts[1])
    b.receive(pkts[2])
    assert got == []     # both held behind the lost first message

    # abandon BOTH lost messages in one FORWARD TSN listing the last ssn;
    # the fully received middle messages must be delivered, not dropped
    body = _s.pack("!IHH", tsns[3], ch.stream_id, (base_ssn + 3) & 0xFFFF)
    a._send_packet([a._chunk(CT_FORWARD_TSN, 0, body)])
    while qa:
        b.receive(qa.pop(0))
    assert got == [b"s1-held", b"s2-held"]


def test_forward_tsn_prunes_unordered_fragments():
    import struct as _s
    from selkies_tpu.webrtc.sctp import CT_FORWARD_TSN
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("bulk", ordered=False)
    pump(a, b, qa, qb)
    got = []
    b.channels[ch.stream_id].on_message = got.append

    last_tsn = a.next_tsn + 1           # E fragment's TSN
    a.send(ch, b"Z" * 1500)             # 2 fragments
    qa.pop(0)                           # B fragment lost
    b.receive(qa.pop(0))                # E fragment arrives, buffered
    assert b._u_reasm[ch.stream_id]

    body = _s.pack("!IHH", last_tsn, 0xFFFF, 0)  # no affected ordered stream
    a._send_packet([a._chunk(CT_FORWARD_TSN, 0, body)])
    while qa:
        b.receive(qa.pop(0))
    assert not b._u_reasm[ch.stream_id]  # abandoned fragments freed
    assert got == []


def _established_pair():
    a, b, qa, qb = make_pair()
    b.start()
    a.start()
    pump(a, b, qa, qb)
    ch = a.create_channel("bulk")
    pump(a, b, qa, qb)
    return a, b, qa, qb, ch


def test_cwnd_gates_bulk_send():
    """RFC 4960 §7: a bulk send must not dump the whole message on the wire
    — only ~cwnd bytes leave, the rest queue and drain on SACKs."""
    a, b, qa, qb, ch = _established_pair()
    got = []
    b.channels[ch.stream_id].on_message = got.append
    blob = bytes(range(256)) * 800          # ~200 KB, ~180 fragments
    a.send(ch, blob)
    assert a._queue                          # not everything went out
    assert a.flight <= a.cwnd + MTU_BYTES
    on_wire = sum(len(p) for p in qa)
    assert on_wire < len(blob) // 2
    start_cwnd = a.cwnd
    pump(a, b, qa, qb, iters=5000)
    assert got == [blob]
    assert a.flight == 0 and not a._queue
    assert a.cwnd > start_cwnd               # slow start grew the window


def test_fast_retransmit_on_three_gap_reports():
    a, b, qa, qb, ch = _established_pair()
    got = []
    b.channels[ch.stream_id].on_message = got.append
    a.cwnd = 50_000      # a grown window, so the 4*MTU ssthresh floor
    msgs = [b"m%d" % i for i in range(5)]   # doesn't mask the decrease
    for m in msgs:
        a.send(ch, m)
    pkts = [qa.pop(0) for _ in range(5)]
    cwnd_before = a.cwnd
    for p in pkts[1:]:                       # first DATA packet is lost
        b.receive(p)
        a.receive(qb.pop(0))                 # its gap-reporting SACK
    # the third missing report triggers fast retransmit without any timer
    assert qa, "fast retransmit did not fire"
    assert a.cwnd < cwnd_before              # multiplicative decrease
    while qa:
        b.receive(qa.pop(0))
    while qb:
        a.receive(qb.pop(0))
    assert got == msgs                       # ordered delivery preserved


def test_rto_collapses_cwnd_to_one_mtu():
    a, b, qa, qb, ch = _established_pair()
    a.cwnd = 50_000
    a.send(ch, b"probe")
    qa.clear()                               # lose it
    a.check_retransmit(now=1e9)
    assert a.cwnd == MTU_BYTES
    assert a.ssthresh >= 4 * MTU_BYTES


def test_flight_accounting_on_ack():
    a, b, qa, qb, ch = _established_pair()
    a.send(ch, b"x" * 4000)
    assert a.flight > 0
    pump(a, b, qa, qb)
    assert a.flight == 0 and not a._out


def test_rto_retransmits_at_most_one_mtu():
    """RFC 4960 §7.2.3 (ADVICE r2): a T3 timeout must collapse cwnd FIRST
    and then retransmit only the earliest chunk(s) fitting one MTU — not
    re-blast the entire expired flight into the congested path."""
    a, b, qa, qb, ch = _established_pair()
    a.cwnd = 200_000
    blob = bytes(range(256)) * 400           # ~100 KB, many fragments
    a.send(ch, blob)
    n_out = len(a._out)
    assert n_out > 10
    qa.clear()                               # the whole flight is lost
    a.check_retransmit(now=1e9)
    # only what fits one MTU went back out (plus whatever _flush then
    # admits from the queue under the collapsed 1-MTU window: nothing,
    # because the flight is still outstanding)
    rtx_bytes = sum(len(p) for p in qa)
    assert rtx_bytes <= 2 * MTU_BYTES        # 1 MTU of DATA + headers
    assert a.cwnd == MTU_BYTES
    # untouched chunks keep their send stamp and drain on later fires
    assert sum(1 for c in a._out.values() if c.retransmits) <= 2
    # the association still completes once the path heals
    got = []
    b.channels[ch.stream_id].on_message = got.append
    for _ in range(n_out + 50):
        a.check_retransmit(now=2e9)
        while qa:
            b.receive(qa.pop(0))
        while qb:
            a.receive(qb.pop(0))
        if got:
            break
    assert got == [blob]


def test_sack_rwnd_discounts_flight():
    """RFC 4960 §6.2.1 (ADVICE r2): the usable peer window is a_rwnd minus
    bytes still in flight that the SACK did not cover."""
    a, b, qa, qb, ch = _established_pair()
    a.cwnd = 200_000
    a.send(ch, b"z" * 40_000)
    sent_first = qa.pop(0)
    qa.clear()                               # rest of the flight in the air
    in_flight_before = a.flight
    b.receive(sent_first)
    sack = qb.pop(0)
    a.receive(sack)                          # SACK covers only chunk 1
    assert a.flight < in_flight_before
    assert a.peer_rwnd <= max(0, b.a_rwnd - a.flight)


def test_start_does_not_regress_established_association():
    """On fast transports the whole INIT/COOKIE handshake can finish
    (driven by receive()) before the owning transport calls start();
    start() must not clobber the established state — the regression left
    the data channel permanently unopened while media flowed."""
    a, b, qa, qb = make_pair()
    ch = a.create_channel("input")
    a.start()                       # client sends INIT
    # server side never called start() yet; drive the full handshake
    pump(a, b, qa, qb)
    assert b.state == "established"
    b.start()                       # late start must be a no-op
    assert b.state == "established"
    got = []
    b.channels[ch.stream_id].on_message = got.append
    a.send(ch, "kd,65")
    pump(a, b, qa, qb)
    assert got == [b"kd,65"]
