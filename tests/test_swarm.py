"""Session scheduler + swarm churn tests (ISSUE 14, docs/scaling.md).

Three layers:

* pure policy — :class:`SlotHealth` EWMAs/quarantine and the keyed
  ``mesh.slot_raise`` fault grammar;
* the scheduler — ``MeshEncodeCoordinator`` with injected (device-free)
  :class:`FakeMeshEncoder` lanes: dynamic lane growth/retirement,
  lane-contained failures, quarantine + live migration, churn with zero
  slot leaks, flush never wedging mid-rebalance;
* the serving plane — scheduler-driven admission verdicts through the
  real ``ws_handler`` and the swarm churn harness (tools/swarm_run.py)
  smoke: ~32 clients, one fault-injected slot, zero leaked slots, zero
  open trace spans, no cross-session stall. The 500-client soak is
  slow-marked.
"""

import asyncio
import json
import random
import time

import pytest

from selkies_tpu.parallel.coordinator import MeshEncodeCoordinator
from selkies_tpu.robustness import (FakeMeshEncoder, FaultInjector,
                                    InProcessClient, SlotHealth)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def make_coord(slots_per_lane=2, max_lanes=3, framerate=200.0,
               lane_retire_s=5.0, sick_errors=3, encs=None, **kw):
    def factory(n):
        enc = FakeMeshEncoder(n)
        if encs is not None:
            encs.append(enc)
        return enc

    return MeshEncodeCoordinator(
        "session:1", slots_per_lane, 64, 48, enc_factory=factory,
        slots_per_lane=slots_per_lane, max_lanes=max_lanes,
        framerate=framerate, health_sick_errors=sick_errors,
        health_window_s=30.0, lane_retire_s=lane_retire_s, **kw)


def pump_until(pred, coord_facades, timeout=5.0, interval=0.005):
    """Submit/poll every facade until pred() or timeout; returns per-
    facade harvested counts."""
    counts = [0] * len(coord_facades)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline and not pred():
        for i, f in enumerate(coord_facades):
            if not f.closed:
                f.try_submit(b"frame")
                counts[i] += len(f.poll())
        time.sleep(interval)
    return counts


# ---------------------------------------------------------------------------
# pure policy


def test_slot_health_ewma_decay_and_quarantine():
    t = [0.0]
    h = SlotHealth(2, sick_errors=3.0, window_s=10.0, clock=lambda: t[0])
    assert not h.is_sick(0)
    for _ in range(3):
        h.record_error(0)
    assert h.is_sick(0)
    assert not h.is_sick(1)           # the neighbour slot is untouched
    # decay: one half-life halves the score below the threshold
    t[0] += 10.0
    assert not h.is_sick(0)
    assert h.errors_total[0] == 3     # lifetime counter never decays
    # quarantine is sticky and ends sickness (out of service != sick)
    for _ in range(4):
        h.record_error(1)
    h.quarantine(1)
    assert not h.is_sick(1)
    assert h.state()["quarantined"] == [1]
    # latency EWMA is observability only
    h.record_ok(0, latency_ms=10.0)
    h.record_ok(0, latency_ms=20.0)
    assert 10.0 < h.latency_ewma_ms[0] < 20.0


def test_should_fire_for_keyed_arming():
    f = FaultInjector()
    f.arm("mesh.slot_raise", times=2, arg="7:1")
    assert not f.should_fire_for("mesh.slot_raise", "7:0", 0)
    assert "mesh.slot_raise" in f.armed      # non-match never consumes
    assert f.should_fire_for("mesh.slot_raise", "7:1", 1)
    # bare-slot identity matches too (single-lane chaos arms "1")
    f.arm("mesh.slot_raise", times=1, arg="1")
    assert f.should_fire_for("mesh.slot_raise", "9:1", 1)
    # argless arming fires for the first site checked
    f.arm("mesh.slot_raise", times=1)
    assert f.should_fire_for("mesh.slot_raise", "3:0", 0)
    assert f.fired["mesh.slot_raise"] >= 3


# ---------------------------------------------------------------------------
# the scheduler: dynamic lanes


def test_lanes_grow_on_demand_and_retire_when_drained():
    coord = make_coord(slots_per_lane=2, max_lanes=2, lane_retire_s=0.0)
    try:
        fs = [coord.acquire(64, 48) for _ in range(4)]
        assert all(f is not None for f in fs)
        assert coord.stats()["lanes"] == 2          # grew on demand
        assert coord.acquire(64, 48) is None        # genuinely full
        cap = coord.capacity()
        assert cap["slots_free"] == 0 and cap["growable_slots"] == 0
        # geometry mismatch is still a hard no
        assert coord.acquire(128, 128) is None
        for f in fs:
            f.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and coord.stats()["lanes"] > 1:
            coord._kick.set()
            time.sleep(0.01)
        st = coord.stats()
        # drained lanes retire; ONE healthy lane stays warm
        assert st["lanes"] == 1
        assert st["lanes_retired_total"] >= 1
        assert st["active_sessions"] == 0
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_lane_failure_is_contained_and_attributed():
    """A failing lane charges its own slots and backs off by itself;
    the cohabiting lane keeps streaming and flush never wedges."""
    encs = []
    coord = make_coord(slots_per_lane=1, max_lanes=2, encs=encs,
                       sick_errors=100)     # no migration in this test
    try:
        fa = coord.acquire(64, 48)
        fb = coord.acquire(64, 48)          # second lane
        assert coord.stats()["lanes"] == 2
        pump_until(lambda: False, [fa, fb], timeout=0.1)
        encs[0].fail_dispatches = 2
        counts = pump_until(
            lambda: coord.tick_errors_total >= 2, [fa, fb], timeout=5.0)
        st = coord.stats()
        assert st["tick_errors_total"] >= 2
        assert sum(st["slot_errors"]) >= 2          # attributed per slot
        assert counts[1] > 0                        # lane B kept flowing
        # worker thread survived the lane failures
        assert coord._thread is not None and coord._thread.is_alive()
        # flush is deadline-bounded and does not wedge on the sick lane
        t0 = time.monotonic()
        fa.flush()
        assert time.monotonic() - t0 < 3.0
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_tick_raise_fault_hits_every_lane_but_worker_survives():
    coord = make_coord(slots_per_lane=2)
    coord.faults = FaultInjector()
    try:
        f = coord.acquire(64, 48)
        pump_until(lambda: False, [f], timeout=0.1)
        errors_before = coord.tick_errors_total
        coord.faults.arm("mesh.tick_raise", times=1)
        counts = pump_until(
            lambda: coord.tick_errors_total > errors_before
            and coord.faults.fired.get("mesh.tick_raise", 0) >= 1,
            [f], timeout=5.0)
        assert coord.faults.fired["mesh.tick_raise"] == 1
        assert coord.tick_errors_total > errors_before
        # recovery: frames flow again after the backoff
        n0 = counts[0]
        counts = pump_until(lambda: False, [f], timeout=1.5)
        assert counts[0] > 0 or n0 > 0
        assert coord._thread is not None and coord._thread.is_alive()
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# quarantine + live migration


def test_sick_slot_quarantined_session_migrates_cohabitant_streams():
    coord = make_coord(slots_per_lane=2, max_lanes=2, sick_errors=3)
    coord.faults = FaultInjector()
    try:
        victim = coord.acquire(64, 48)
        cohab = coord.acquire(64, 48)
        lane0, slot0 = victim.lane_id, victim.slot
        coord.faults.arm("mesh.slot_raise", times=4,
                         arg=f"{lane0}:{slot0}")
        counts = pump_until(lambda: coord.migrations_total >= 1,
                            [victim, cohab], timeout=5.0)
        st = coord.stats()
        assert st["migrations_total"] == 1
        assert st["quarantined_total"] == 1
        assert st["slot_faults_total"] >= 3
        # the facade survived the rebind: new lane, migration flag set
        assert victim.lane_id != lane0
        assert victim.consume_migration() is True
        assert victim.consume_migration() is False     # one-shot
        # the cohabitant never stopped streaming through the fault
        assert counts[1] > 0
        # and the victim streams again on the new lane
        counts = pump_until(lambda: False, [victim, cohab], timeout=0.6)
        assert counts[0] > 0
        assert coord.verify_slot_accounting() == []
        # the quarantined slot never returns to the free list
        sick_lane = next((ln for ln in coord.lanes if ln.id == lane0),
                         None)
        if sick_lane is not None:
            assert slot0 in sick_lane.health.quarantined
            assert slot0 not in sick_lane.free
    finally:
        coord.stop()


def test_migration_blocked_at_full_occupancy_keeps_serving():
    """No healthy slot anywhere: the sick session keeps its slot
    (degraded beats dead), the block is counted, and nothing leaks."""
    coord = make_coord(slots_per_lane=1, max_lanes=1, sick_errors=2)
    coord.faults = FaultInjector()
    try:
        f = coord.acquire(64, 48)
        coord.faults.arm("mesh.slot_raise", times=3,
                         arg=f"{f.lane_id}:{f.slot}")
        pump_until(lambda: coord.migrations_blocked_total >= 1, [f],
                   timeout=5.0)
        assert coord.migrations_blocked_total >= 1
        assert coord.migrations_total == 0
        # still serving on the sick slot once the faults are exhausted
        counts = pump_until(lambda: False, [f], timeout=0.6)
        assert counts[0] > 0
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# churn regression (satellite): no leaks, gen guards, flush never wedges


def test_churn_storm_no_slot_leaks_and_flush_never_wedges():
    rng = random.Random(7)
    coord = make_coord(slots_per_lane=4, max_lanes=3, lane_retire_s=0.05)
    try:
        live = []
        for step in range(300):
            r = rng.random()
            if r < 0.45 or not live:
                f = coord.acquire(64, 48)
                if f is not None:
                    live.append(f)
            elif r < 0.75:
                f = live.pop(rng.randrange(len(live)))
                f.try_submit(b"parting-frame")
                if rng.random() < 0.5:
                    # flush mid-rebalance must return, not wedge
                    t0 = time.monotonic()
                    f.flush()
                    assert time.monotonic() - t0 < 3.0
                f.close()
            else:
                f = rng.choice(live)
                f.try_submit(b"frame")
                f.poll()
            if step % 50 == 0:
                assert coord.verify_slot_accounting() == []
        for f in live:
            f.close()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline \
                and coord.stats()["active_sessions"]:
            time.sleep(0.01)
        st = coord.stats()
        assert st["active_sessions"] == 0
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_generation_guard_on_slot_reuse():
    """A released session's in-flight result must never reach the slot's
    next occupant, and a migrated session must not receive its old
    binding's pixels."""
    coord = make_coord(slots_per_lane=1, max_lanes=1, framerate=50.0)
    try:
        f1 = coord.acquire(64, 48)
        f1.try_submit(b"old-occupant-frame")
        # release while the frame may still be pending/in-flight, then
        # immediately reuse the slot
        f1.close()
        f2 = coord.acquire(64, 48)
        assert f2 is not None and f2.slot == 0
        # whatever lands on f2 must be ITS frames, numbered from seq 0
        f2.try_submit(b"new-occupant-frame")
        got = []
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not got:
            got = f2.poll()
            time.sleep(0.01)
        assert got and got[0][0] == 0        # fresh seq for the new owner
        assert f1.poll() == []               # the dead facade gets nothing
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_submit_seq_accounts_for_inflight_window():
    """With frames in the in-flight window, _submit must return the seq
    the NEW frame will harvest under — not the in-flight frame's — or
    trace correlation shifts off by one in overlapped steady state."""
    coord = make_coord(slots_per_lane=1, max_lanes=1)
    coord.stop()                             # drive ticks by hand
    f = coord.acquire(64, 48)
    coord.stop()
    with coord._lock:
        sess = coord._sessions[f.sid]
        sess.seq = 5
        lane = sess.lane
        lane.inflight_q.append(
            (object(), [(sess, 0, sess.gen)], (0.0, 0.0)))          # live
        lane.inflight_q.append(
            (object(), [(sess, 0, sess.gen - 1)], (0.0, 0.0)))      # stale
    assert f.try_submit(b"frame") == 6       # 5 + 1 live in-flight
    # a second submit before the tick replaces the pending frame: drop
    assert f.try_submit(b"frame2") is None


# ---------------------------------------------------------------------------
# SFE lanes (ISSUE 15): stripe-sharded sessions in the scheduler


def test_chips_from_spec_parses_full_form_and_rejects_malformed():
    """The textual device-count parse honors session:N,stripe:M and
    REJECTS malformed parts instead of silently collapsing a multi-chip
    slice to one chip."""
    assert MeshEncodeCoordinator._chips_from_spec("session:2,stripe:3") == 6
    assert MeshEncodeCoordinator._chips_from_spec("session:8") == 8
    assert MeshEncodeCoordinator._chips_from_spec("") == 1
    assert MeshEncodeCoordinator._chips_from_spec(" session:2 , ") == 2
    for bad in ("session:banana", "4", "session", "session:2,oops"):
        with pytest.raises(ValueError):
            MeshEncodeCoordinator._chips_from_spec(bad)


def test_sfe_shard_count_policy():
    """Pure SFE sizing policy: below sfe_min_pixels or on one chip a
    session stays solo-slotted; above it the frame spans sfe_shards
    chips (0 = all), clamped to a count that tiles the slice."""
    from types import SimpleNamespace as NS

    f = MeshEncodeCoordinator._sfe_shard_count
    fourk = NS(sfe_min_pixels=3840 * 2160, sfe_shards=0)
    assert f(4, 1920, 1080, fourk) == 1          # below the threshold
    assert f(1, 3840, 2160, fourk) == 1          # single chip: no SFE
    assert f(4, 3840, 2160, fourk) == 4          # auto: every chip
    assert f(8, 7680, 4320, fourk) == 8          # 8K spans the slice too
    assert f(4, 3840, 2160,
             NS(sfe_min_pixels=3840 * 2160, sfe_shards=3)) == 2
    assert f(4, 3840, 2160, NS(sfe_min_pixels=0, sfe_shards=0)) == 1
    assert f(4, 3840, 2160, None) == 1


def make_sfe_coord(n_shards=4, max_lanes=2, encs=None, sick_errors=3):
    def factory(n):
        enc = FakeMeshEncoder(n, n_shards=n_shards)
        if encs is not None:
            encs.append(enc)
        return enc

    return MeshEncodeCoordinator(
        f"session:{n_shards}", 1, 3840, 2160, enc_factory=factory,
        slots_per_lane=1, max_lanes=max_lanes, framerate=200.0,
        health_sick_errors=sick_errors, health_window_s=30.0,
        lane_retire_s=5.0, sfe_shards=n_shards)


def test_sfe_shard_fault_contains_whole_frame_and_migrates():
    """A mesh.slot_raise targeting ONE stripe shard of an SFE session
    must degrade that SESSION — whole-frame containment (every
    delivered harvest carries ALL shard stripes, never a torn access
    unit), quarantine + live migration on repeats — while the
    neighbouring SFE lane keeps streaming."""
    coord = make_sfe_coord(n_shards=4, max_lanes=3)
    coord.faults = FaultInjector()
    try:
        victim = coord.acquire(3840, 2160)
        cohab = coord.acquire(3840, 2160)        # second SFE lane
        cap = coord.capacity()
        assert cap["sfe_shards"] == 4 and cap["chips_per_slot"] == 4
        lane0, slot0 = victim.lane_id, victim.slot
        # target shard 2 of the victim's slot, nobody else
        coord.faults.arm("mesh.slot_raise", times=4,
                         arg=f"{lane0}:{slot0}:2")
        got = {0: [], 1: []}
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and coord.migrations_total < 1:
            for i, f in enumerate((victim, cohab)):
                f.try_submit(b"frame")
                got[i] += f.poll()
            time.sleep(0.005)
        st = coord.stats()
        assert st["migrations_total"] == 1       # session, not shard, moved
        assert st["quarantined_total"] == 1
        assert st["slot_faults_total"] >= 3
        assert victim.lane_id != lane0
        assert victim.consume_migration() is True
        assert len(got[1]) > 0                   # cohabitant kept flowing
        # whole-frame containment: every delivered frame carries ALL
        # four shard stripes — a dropped tick yields nothing, never part
        for i in got:
            for _seq, stripes in got[i]:
                assert len(stripes) == 4, "torn SFE access unit"
        deadline = time.monotonic() + 1.0
        n0 = len(got[0])
        while time.monotonic() < deadline and len(got[0]) == n0:
            victim.try_submit(b"frame")
            got[0] += victim.poll()
            time.sleep(0.005)
        assert len(got[0]) > n0                  # victim streams again
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_encoder_internal_failure_charges_slot_and_migrates():
    """A stripe-job failure INSIDE the encoder's harvest (whole-frame
    containment withholds the AU; harvest returns normally, nothing
    raises) must charge the slot's health exactly like an injected
    fault — repeated hits quarantine the slot and live-migrate the
    session to a healthy lane, instead of health recording ok while the
    session's stream is frozen forever."""
    encs = []
    coord = make_sfe_coord(n_shards=2, max_lanes=2, encs=encs)
    try:
        f = coord.acquire(3840, 2160)
        lane0 = f.lane_id
        encs[0].fail_sessions.add(f.slot)    # the sick shard chip
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and coord.migrations_total < 1:
            f.try_submit(b"frame")
            f.poll()
            time.sleep(0.005)
        st = coord.stats()
        assert st["migrations_total"] == 1
        assert st["quarantined_total"] == 1
        assert f.lane_id != lane0
        # on the healthy lane the session streams full AUs again
        # (withheld/empty results harvested around the migration may
        # still drain first — wait for real content)
        got = []
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not got:
            f.try_submit(b"frame")
            got += [r for r in f.poll() if r[1]]
            time.sleep(0.005)
        assert got and len(got[-1][1]) == 2
        assert coord.verify_slot_accounting() == []
    finally:
        coord.stop()


def test_sfe_harvest_trace_splits_fetch_and_pack():
    """The coordinator folds the encoder's last_harvest_stages split
    into the frame trace: fetch_wait (per-shard D2H) and pack (host
    slice concat) both present, and stats surfaces the concat p50."""
    coord = make_sfe_coord(n_shards=2, max_lanes=1)
    try:
        f = coord.acquire(3840, 2160)
        tr = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and tr is None:
            f.try_submit(b"frame")
            for seq, _stripes in f.poll():
                tr = f.pop_trace(seq)
            time.sleep(0.005)
        assert tr is not None
        assert "dispatch" in tr and "fetch_wait" in tr and "pack" in tr
        fw0, fw1 = tr["fetch_wait"]
        pk0, pk1 = tr["pack"]
        assert fw1 == pk0 and fw0 <= fw1 <= pk1  # contiguous split
        st = coord.stats()
        assert st["sfe_shards"] == 2
        assert st["sfe_concat_ms_p50"] > 0.0
        assert st["sfe_fetch_ms_p50"] > 0.0
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# serving plane: scheduler-driven admission through the real ws_handler


def make_admission_server(slots_per_lane=1, max_lanes=1, queue_ms=60):
    from selkies_tpu.server.app import StreamingApp
    from selkies_tpu.server.data_server import DataStreamingServer
    from selkies_tpu.settings import Settings
    from tools.swarm_run import _SwarmSoloEncoder, _SwarmSource

    env = {
        "SELKIES_PORT": "0", "SELKIES_AUDIO_ENABLED": "false",
        "SELKIES_SECOND_SCREEN": "true",
        "SELKIES_MAX_CLIENTS": "0", "SELKIES_MAX_DISPLAYS": "0",
        "SELKIES_TPU_MESH": "session:1",
        "SELKIES_TPU_SESSIONS_PER_CHIP": str(slots_per_lane),
        "SELKIES_MESH_MAX_LANES": str(max_lanes),
        "SELKIES_ADMISSION_QUEUE_MS": str(queue_ms),
        "SELKIES_WATCHDOG_FRAMES": "0",
        "SELKIES_SUPERVISOR_MAX_RESTARTS": "1000",
        "SELKIES_RESIZE_DEBOUNCE_MS": "10",
    }
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=lambda w, h, s, overrides=None:
            _SwarmSoloEncoder(),
        source_factory=_SwarmSource, host="127.0.0.1")
    server.coordinator_factory = \
        lambda spec, spc, w, h, **kw: MeshEncodeCoordinator(
            spec, spc, w, h,
            enc_factory=lambda n: FakeMeshEncoder(n),
            slots_per_lane=slots_per_lane, lane_retire_s=0.2,
            **{k: v for k, v in kw.items()
               if k != "slots_per_lane"})
    app.data_server = server
    return server


async def open_display(server, display_id, w=64, h=48, fps=30):
    ws = InProcessClient()
    task = asyncio.create_task(server.ws_handler(ws))
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(ws.sent) < 2:
        await asyncio.sleep(0.005)
    ws.feed("SETTINGS," + json.dumps({
        "displayId": display_id, "initialClientWidth": w,
        "initialClientHeight": h, "framerate": fps}))
    return ws, task


async def reap(ws, task):
    await ws.close()
    try:
        await asyncio.wait_for(task, 5.0)
    except asyncio.TimeoutError:
        task.cancel()


@pytest.mark.anyio
async def test_admission_queue_then_shed_then_readmit():
    """Capacity 1: the second display queues then is shed with
    KILL server_full; after the first leaves, a third is admitted."""

    async def frames_flowing(ws, timeout=5.0):
        n0 = len(ws.binary())
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(ws.binary()) > n0:
                return True
            await asyncio.sleep(0.01)
        return False

    server = make_admission_server(slots_per_lane=1, max_lanes=1)
    try:
        ws1, t1 = await open_display(server, "d1")
        assert await frames_flowing(ws1)

        ws2, t2 = await open_display(server, "d2")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not ws2.closed:
            await asyncio.sleep(0.01)
        assert ws2.closed                      # shed after the queue wait
        assert any("KILL server_full" in t for t in ws2.texts())
        assert server.edge_stats["sessions_queued"] >= 1
        assert server.edge_stats["sessions_rejected"] >= 1
        await reap(ws2, t2)

        await reap(ws1, t1)                    # leave frees the slot
        ws3, t3 = await open_display(server, "d3")
        assert await frames_flowing(ws3)
        assert not ws3.closed
        await reap(ws3, t3)
    finally:
        await server.stop()


@pytest.mark.anyio
async def test_admission_queue_admits_when_slot_frees_during_wait():
    server = make_admission_server(slots_per_lane=1, max_lanes=1,
                                   queue_ms=1500)
    try:
        ws1, t1 = await open_display(server, "d1")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not any(
                isinstance(m, (bytes, bytearray)) for m in ws1.sent):
            await asyncio.sleep(0.01)
        # join while full, then free the slot inside the queue window
        ws2, t2 = await open_display(server, "d2")
        await asyncio.sleep(0.15)
        assert not ws2.closed                  # still queued, not shed
        await reap(ws1, t1)
        deadline = time.monotonic() + 5.0
        ok = False
        while time.monotonic() < deadline:
            if any(isinstance(m, (bytes, bytearray)) for m in ws2.sent):
                ok = True
                break
            await asyncio.sleep(0.01)
        assert ok and not ws2.closed           # admitted after the wait
        assert server.edge_stats["sessions_queued"] >= 1
        await reap(ws2, t2)
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# the swarm harness (acceptance): tier-1 smoke + slow soak


@pytest.mark.anyio
async def test_swarm_smoke_churn_storm_with_sick_slot():
    """~32 clients, join/leave/resize storm, one slot fault-injected:
    the victim is quarantined + migrated, cohabitants never stall, and
    the run ends with zero leaked slots and zero open trace spans."""
    from tools.swarm_run import swarm_run

    report = await swarm_run(n_clients=32, duration_s=3.0, seed=1,
                             concurrency=12, fps=15.0, slots_per_lane=4,
                             max_lanes=2, sick_slot=True)
    assert report["swarm_clients"] >= 32
    assert report["leaked_slots"] == 0
    assert report["trace_open_spans"] == 0
    assert report["slot_accounting_violations"] == []
    assert report["victim_migrated"] is True
    assert report["cohabitants_stalled"] == 0
    assert report["quarantined_slots"] + report.get(
        "migrations", 0) >= 1
    assert report["frames_delivered_total"] > 0
    assert report["alive"] is True


@pytest.mark.slow
@pytest.mark.anyio
async def test_swarm_soak_500_clients():
    """The acceptance-scale storm: 500 distinct clients through the real
    ws_handler, ending leak-free with the fault-domain story proven."""
    from tools.swarm_run import swarm_run

    report = await swarm_run(n_clients=500, duration_s=20.0, seed=2,
                             concurrency=56, sick_slot=True)
    assert report["swarm_clients"] >= 500
    assert report["alive"] is True
    assert report["fairness_jain_index"] > 0.8
    assert report["sessions_per_chip"] >= 32
