import io

import numpy as np
import pytest
from PIL import Image

from selkies_tpu.encoder.jpeg import JpegStripeEncoder
from selkies_tpu.encoder import entropy_py
from selkies_tpu.native import entropy_lib
from selkies_tpu.encoder.jpeg_tables import std_tables


def smooth_frame(h, w, seed=0):
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    r = 128 + 100 * np.sin(xx / 97.0) * np.cos(yy / 53.0)
    g = 128 + 100 * np.cos(xx / 71.0)
    b = 128 + 100 * np.sin(yy / 89.0)
    return np.clip(np.stack([r, g, b], axis=-1), 0, 255).astype(np.uint8)


def psnr(a, b):
    mse = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(255.0**2 / mse)


def decode_stripes(stripes, h, w):
    """Composite decoded stripes onto a canvas like the client does."""
    canvas = np.zeros((h, w, 3), dtype=np.uint8)
    for s in stripes:
        img = np.asarray(Image.open(io.BytesIO(s.jpeg)).convert("RGB"))
        rows = min(img.shape[0], h - s.y_start)
        canvas[s.y_start:s.y_start + rows, :, :] = img[:rows, :w]
    return canvas


def test_stripes_decode_and_psnr():
    h, w = 128, 160
    frame = smooth_frame(h, w)
    enc = JpegStripeEncoder(w, h, stripe_height=64, quality=90)
    stripes = enc.encode_frame(frame)
    assert len(stripes) == 2
    assert [s.y_start for s in stripes] == [0, 64]
    for s in stripes:
        assert s.jpeg.startswith(b"\xff\xd8") and s.jpeg.endswith(b"\xff\xd9")
    rec = decode_stripes(stripes, h, w)
    assert psnr(frame, rec) > 35.0


def test_unpadded_dimensions():
    # 1080 is not a multiple of 64; 150 not a multiple of 16
    h, w = 100, 150
    enc = JpegStripeEncoder(w, h, stripe_height=64, quality=85)
    stripes = enc.encode_frame(smooth_frame(h, w))
    assert len(stripes) == 2  # padded to 128 rows
    rec = decode_stripes(stripes, h, w)
    assert psnr(smooth_frame(h, w), rec) > 30.0


def test_damage_gating_skips_static_stripes():
    h, w = 128, 160
    frame = smooth_frame(h, w)
    enc = JpegStripeEncoder(w, h, stripe_height=64, quality=80,
                            use_paint_over_quality=False)
    assert len(enc.encode_frame(frame)) == 2
    assert enc.encode_frame(frame) == []  # identical frame → nothing
    frame2 = frame.copy()
    frame2[70, 10] ^= 0xFF  # touch stripe 1 only
    out = enc.encode_frame(frame2)
    assert [s.y_start for s in out] == [64]


def test_paintover_escalation():
    h, w = 64, 64
    frame = smooth_frame(h, w)
    enc = JpegStripeEncoder(w, h, stripe_height=64, quality=40,
                            paintover_quality=95, paint_over_trigger_frames=3)
    first = enc.encode_frame(frame)
    assert len(first) == 1 and not first[0].is_paintover
    outs = [enc.encode_frame(frame) for _ in range(6)]
    paint = [o for frame_out in outs for o in frame_out]
    assert len(paint) == 1 and paint[0].is_paintover
    # paint-over stripe is visibly better than the low-quality first pass
    rec_low = decode_stripes(first, h, w)
    rec_hi = decode_stripes(paint, h, w)
    assert psnr(frame, rec_hi) > psnr(frame, rec_low) + 3


def test_force_keyframe_reemits_everything():
    h, w = 128, 64
    frame = smooth_frame(h, w)
    enc = JpegStripeEncoder(w, h, stripe_height=64, quality=70,
                            use_paint_over_quality=False)
    enc.encode_frame(frame)
    assert enc.encode_frame(frame) == []
    enc.force_keyframe()
    assert len(enc.encode_frame(frame)) == 2


def test_native_entropy_matches_python_oracle():
    lib = entropy_lib()
    if lib is None:
        pytest.skip("no C++ toolchain")
    rng = np.random.default_rng(7)
    by, bx = 4, 6
    # sparse, mixed-sign coefficients exercising runs, ZRL, and categories
    y = (rng.integers(-40, 40, size=(by, bx, 64))
         * (rng.random((by, bx, 64)) < 0.15)).astype(np.int16)
    cb = (rng.integers(-20, 20, size=(by // 2, bx // 2, 64))
          * (rng.random((by // 2, bx // 2, 64)) < 0.1)).astype(np.int16)
    cr = np.zeros_like(cb)
    dc_l, ac_l, dc_c, ac_c = std_tables()
    cap = y.size * 4 + cb.size * 8 + 4096
    out = np.empty(cap, dtype=np.uint8)
    n = lib.jpeg_encode_scan_420(
        y, cb, cr, by, bx,
        dc_l.code_arr, dc_l.len_arr, ac_l.code_arr, ac_l.len_arr,
        dc_c.code_arr, dc_c.len_arr, ac_c.code_arr, ac_c.len_arr,
        out, cap)
    assert n > 0
    assert out[:n].tobytes() == entropy_py.encode_scan_420(y, cb, cr)


def test_device_entropy_mode_matches_host_mode():
    h, w = 128, 160
    frame = smooth_frame(h, w)
    frames = [frame, frame, np.roll(frame, 5, axis=1)]
    enc_d = JpegStripeEncoder(w, h, stripe_height=64, quality=60, entropy="device")
    enc_h = JpegStripeEncoder(w, h, stripe_height=64, quality=60, entropy="host")
    for f in frames:
        out_d = enc_d.encode_frame(f)
        out_h = enc_h.encode_frame(f)
        assert [(s.y_start, s.jpeg) for s in out_d] == \
               [(s.y_start, s.jpeg) for s in out_h]


def test_pipelined_encoder_matches_sync():
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder
    h, w = 128, 96
    frames = [smooth_frame(h, w), smooth_frame(h, w),
              np.roll(smooth_frame(h, w), 7, axis=0),
              np.roll(smooth_frame(h, w), 9, axis=1)]
    sync = JpegStripeEncoder(w, h, stripe_height=64, quality=55)
    want = [[(s.y_start, s.jpeg) for s in sync.encode_frame(f)] for f in frames]

    pipe = PipelinedJpegEncoder(
        JpegStripeEncoder(w, h, stripe_height=64, quality=55), depth=3)
    got = {}
    for f in frames:
        pipe.submit(f)
        for seq, stripes in pipe.poll():
            got[seq] = [(s.y_start, s.jpeg) for s in stripes]
    for seq, stripes in pipe.flush():
        got[seq] = [(s.y_start, s.jpeg) for s in stripes]
    assert [got[i] for i in range(len(frames))] == want


def test_pipelined_paintover_not_duplicated():
    """With frames in flight, a paint-over must fire exactly once."""
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder
    h, w = 64, 64
    frame = smooth_frame(h, w)
    pipe = PipelinedJpegEncoder(
        JpegStripeEncoder(w, h, stripe_height=64, quality=40,
                          paintover_quality=95, paint_over_trigger_frames=3),
        depth=3)
    outs = []
    for _ in range(12):
        pipe.submit(frame)
        outs.extend(s for _, st in pipe.poll() for s in st)
    outs.extend(s for _, st in pipe.flush() for s in st)
    paint = [s for s in outs if s.is_paintover]
    assert len(paint) == 1


def test_pipeline_partial_group_flushed_by_poll():
    """fetch_group > 1 must not strand frames when submissions pause
    (regression: poll() flushes a partial fetch group)."""
    import numpy as np

    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.encoder.pipeline import PipelinedJpegEncoder

    enc = PipelinedJpegEncoder(
        JpegStripeEncoder(64, 64, stripe_height=64), depth=8, fetch_group=4)
    rng = np.random.default_rng(0)
    for i in range(2):   # fewer than fetch_group
        enc.submit(rng.integers(0, 255, (64, 64, 3), dtype=np.uint8))
    got = []
    for _ in range(50):
        got += enc.poll()
        if len(got) == 2:
            break
    assert len(got) == 2
    assert all(stripes for _, stripes in got)


def test_watermark_overlay(tmp_path):
    """pixelflux watermark parity: PNG blended on device at the configured
    location; output decodes with the mark present."""
    import io

    import numpy as np
    from PIL import Image

    from selkies_tpu.encoder.jpeg import JpegStripeEncoder

    wm = Image.new("RGBA", (32, 16), (255, 0, 0, 255))
    wm_path = tmp_path / "wm.png"
    wm.save(wm_path)

    frame = np.full((64, 128, 3), 32, np.uint8)
    plain = JpegStripeEncoder(128, 64, stripe_height=64, quality=90)
    marked = JpegStripeEncoder(128, 64, stripe_height=64, quality=90,
                               watermark_path=str(wm_path),
                               watermark_location=0)  # top-left
    out_p = plain.encode_frame(frame)
    out_m = marked.encode_frame(frame)
    img_p = np.asarray(Image.open(io.BytesIO(out_p[0].jpeg)).convert("RGB"))
    img_m = np.asarray(Image.open(io.BytesIO(out_m[0].jpeg)).convert("RGB"))
    # top-left region (16px margin) turns red; far corner unchanged
    assert img_m[20, 20, 0] > 180 and img_m[20, 20, 1] < 90
    assert abs(int(img_p[60, 120, 0]) - int(img_m[60, 120, 0])) < 10
    # opaque overlay exact: (32*0 + 255*255 + 127)//255 == 255
    assert img_p[20, 20, 0] < 60


def test_watermark_missing_file_disabled(tmp_path):
    import numpy as np

    from selkies_tpu.encoder.jpeg import JpegStripeEncoder

    enc = JpegStripeEncoder(64, 64, watermark_path=str(tmp_path / "nope.png"))
    assert enc._wm_scaled is None
    assert enc.encode_frame(np.zeros((64, 64, 3), np.uint8))


def test_watermark_clamped_at_frame_edge(tmp_path):
    """A mark bigger than the space at its placement is cropped, never a
    constructor crash (regression)."""
    import numpy as np
    from PIL import Image

    from selkies_tpu.encoder.jpeg import JpegStripeEncoder

    wm_path = tmp_path / "big.png"
    Image.new("RGBA", (64, 64), (0, 255, 0, 255)).save(wm_path)
    enc = JpegStripeEncoder(64, 64, stripe_height=64,
                            watermark_path=str(wm_path),
                            watermark_location=0)
    assert enc._wm_scaled is not None
    assert enc.encode_frame(np.zeros((64, 64, 3), np.uint8))
