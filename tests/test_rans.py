"""rANS coder tests (config-3 gate instrument, encoder/rans.py)."""

import numpy as np
import pytest

from selkies_tpu.encoder import rans


def sparse_planes(seed=0, ny=64, nc=16):
    rng = np.random.default_rng(seed)

    def mk(n):
        p = np.zeros((n, 64), np.int16)
        for i in range(n):
            k = int(rng.integers(1, 20))
            idx = np.sort(rng.choice(64, size=k, replace=False))
            p[i, idx] = rng.integers(-40, 41, size=k)
            p[i, 0] = rng.integers(-200, 201)
        return p

    return mk(ny), mk(nc), mk(nc)


def test_rans_stream_roundtrip():
    rng = np.random.default_rng(1)
    syms = rng.integers(0, 20, 5000).astype(np.int32)
    freqs = rans.build_model(syms, alphabet=32)
    blob = rans.rans_encode(syms, freqs)
    out = rans.rans_decode(blob, freqs, len(syms))
    assert np.array_equal(out, syms)


def test_rans_skewed_model():
    # heavily skewed distribution — the case rANS is for
    syms = np.asarray([0] * 9000 + [1] * 100 + [7] * 5, np.int32)
    np.random.default_rng(2).shuffle(syms)
    freqs = rans.build_model(syms, alphabet=8)
    blob = rans.rans_encode(syms, freqs)
    assert np.array_equal(rans.rans_decode(blob, freqs, len(syms)), syms)
    # ~0.12 bits/symbol entropy → far under 1 byte/symbol
    assert len(blob) < len(syms) // 4


def test_model_header_roundtrip():
    syms = np.asarray([3, 3, 3, 7, 250], np.int32)
    freqs = rans.build_model(syms)
    hdr = rans.model_header(freqs)
    freqs2, consumed = rans.parse_model_header(hdr)
    assert consumed == len(hdr)
    assert np.array_equal(freqs, freqs2)
    assert int(freqs2.sum()) == rans.PROB_SCALE


def test_value_bits_roundtrip():
    rng = np.random.default_rng(3)
    vlens = rng.integers(1, 11, 200).astype(np.int32)
    vbits = np.asarray([int(rng.integers(0, 1 << l)) for l in vlens],
                       np.int64)
    packed = rans.pack_value_bits(vbits, vlens)
    out = rans.unpack_value_bits(packed, vlens)
    assert np.array_equal(out, vbits)


def test_planes_roundtrip():
    y, cb, cr = sparse_planes()
    blob = rans.encode_planes(y, cb, cr, blocks_per_stripe_y=16)
    y2, c2 = rans.decode_planes(blob, len(y), len(cb) + len(cr), 16)
    assert np.array_equal(y2, y)
    assert np.array_equal(c2, np.concatenate([cb, cr]))


def test_planes_roundtrip_all_zero():
    z = np.zeros((8, 64), np.int16)
    zc = np.zeros((4, 64), np.int16)
    blob = rans.encode_planes(z, zc, zc, 8)
    y2, c2 = rans.decode_planes(blob, 8, 8, 8)
    assert not y2.any() and not c2.any()
    assert len(blob) < 100


def test_planes_roundtrip_max_magnitude():
    # size-10 AC values and large DC swings
    y = np.zeros((4, 64), np.int16)
    y[:, 0] = [1000, -1000, 900, -900]
    y[:, 1] = [1023, -1023, 512, -512]
    y[:, 63] = 5                       # block ends on coeff 63 — no EOB
    zc = np.zeros((2, 64), np.int16)
    blob = rans.encode_planes(y, zc, zc, 4)
    y2, _ = rans.decode_planes(blob, 4, 4, 4)
    assert np.array_equal(y2, y)


def test_zrl_runs():
    # 16+ zero runs exercise ZRL symbols
    y = np.zeros((2, 64), np.int16)
    y[0, 40] = 3                       # run of 39 zeros → 2 ZRLs + (7,size)
    y[1, 17] = -2
    zc = np.zeros((2, 64), np.int16)
    blob = rans.encode_planes(y, zc, zc, 2)
    y2, _ = rans.decode_planes(blob, 2, 4, 2)
    assert np.array_equal(y2, y)


def test_decision_memo_exists():
    import os
    memo = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "config3_decision.md")
    text = open(memo).read()
    assert "Decision" in text and "rANS" in text


def test_decode_planes_truncated_raises_cleanly():
    """Corrupt/truncated input must raise ValueError, not IndexError
    (ADVICE r2: rans.py decode_planes bounds)."""
    y, cb, cr = sparse_planes(seed=9)
    blob = rans.encode_planes(y, cb, cr, blocks_per_stripe_y=16)
    # claim more blocks than the stream encodes → symbol exhaustion
    with pytest.raises(ValueError, match="malformed"):
        rans.decode_planes(blob, len(y) * 2, len(cb) + len(cr), 16)


def test_decode_planes_corrupt_symbols_raise_cleanly():
    y, cb, cr = sparse_planes(seed=10)
    blob = bytearray(rans.encode_planes(y, cb, cr, blocks_per_stripe_y=16))
    rng = np.random.default_rng(11)
    for _ in range(40):
        trial = bytearray(blob)
        i = int(rng.integers(12, len(trial)))
        trial[i] ^= 0xFF
        try:
            rans.decode_planes(bytes(trial), len(y), len(cb) + len(cr), 16)
        except ValueError:
            pass              # clean decode error is the contract
        except Exception as exc:   # IndexError/struct.error are NOT
            raise AssertionError(
                f"corrupt byte {i} raised {type(exc).__name__}: {exc}")
