"""The driver-facing entry points must work under a hostile ambient env.

Round 1's MULTICHIP artifact failed because ``dryrun_multichip`` inherited
the wedged ambient TPU plugin; it now isolates itself in a scrubbed child
interpreter. This test runs it the way the driver does — including with the
hazard variable present — and asserts it completes.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_survives_ambient_tpu_plugin():
    env = dict(os.environ)
    # Simulate the hazard: the sitecustomize TPU-plugin gate is set and the
    # parent env requests a TPU backend. The entry point must override both.
    env["PALLAS_AXON_POOL_IPS"] = "203.0.113.1"
    env.pop("JAX_PLATFORMS", None)
    code = ("import sys; sys.path.insert(0, %r); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)" % REPO)
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
