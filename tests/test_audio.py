"""Audio subsystem: Opus codec round-trip, silence gate, capture loop,
and the server pipeline (reference parity: pcmflux surface selkies.py:939-1090).
"""

import asyncio
import time

import numpy as np
import pytest

from selkies_tpu.audio import (AudioCapture, AudioCaptureSettings,
                               AudioPipeline, OpusDecoder, OpusEncoder,
                               SilenceSource, SyntheticTone, opus_available)

pytestmark = pytest.mark.skipif(
    not opus_available(), reason="libopus unavailable")


def _sine_chunk(t0, frames, rate=48000, ch=2, freq=440.0, amp=0.5):
    n = np.arange(t0, t0 + frames)
    wave = (np.sin(2 * np.pi * freq * n / rate) * amp * 32767).astype(np.int16)
    return np.repeat(wave, ch)


def test_opus_roundtrip_sine():
    enc = OpusEncoder(48000, 2, bitrate=128000)
    dec = OpusDecoder(48000, 2)
    frames = 960  # 20 ms @ 48 kHz
    # Opus is stateful: prime a few chunks, then measure
    decoded = []
    for i in range(10):
        packet = enc.encode(_sine_chunk(i * frames, frames))
        assert 0 < len(packet) < 1500
        decoded.append(dec.decode(packet))
    out = np.concatenate(decoded)[:, 0].astype(np.float64)
    ref = np.concatenate(
        [_sine_chunk(i * frames, frames)[::2] for i in range(10)]
    ).astype(np.float64)
    # skip codec warmup; the decoded signal LAGS the source by the codec
    # delay (~312 samples lookahead + resampler), so search d ∈ [0, 1000)
    a = out[4800:8800]
    best = max(
        np.corrcoef(a, ref[4800 - d:8800 - d])[0, 1] for d in range(1000))
    assert best > 0.97, best


def test_opus_vbr_silence_is_small():
    enc = OpusEncoder(48000, 2, bitrate=128000, vbr=True)
    sizes = [len(enc.encode(np.zeros(960 * 2, np.int16))) for _ in range(10)]
    assert sizes[-1] <= 8  # VBR emits tiny DTX-ish packets for silence


def test_capture_loop_synthetic_tone():
    settings = AudioCaptureSettings(channels=2, frame_duration_ms=20)
    got = []
    cap = AudioCapture(settings, got.append,
                       source=SyntheticTone(settings, realtime=False))
    cap.start_capture()
    deadline = time.time() + 5
    while len(got) < 20 and time.time() < deadline:
        time.sleep(0.01)
    cap.stop_capture()
    assert len(got) >= 20
    assert all(isinstance(p, bytes) and p for p in got)
    assert cap.chunks_gated == 0


def test_capture_silence_gate():
    settings = AudioCaptureSettings(use_silence_gate=True)
    got = []
    cap = AudioCapture(settings, got.append,
                       source=SilenceSource(settings, realtime=False))
    cap.start_capture()
    deadline = time.time() + 3
    while cap.chunks_gated < 30 and time.time() < deadline:
        time.sleep(0.01)
    cap.stop_capture()
    assert cap.chunks_gated >= 30
    assert got == []  # starts gated; silence never opens the gate


def test_silence_gate_hangover_reopens():
    from selkies_tpu.audio.capture import SILENCE_HANGOVER_CHUNKS

    class ToneThenSilence:
        def __init__(self):
            self.i = 0

        def read_chunk(self, frames):
            self.i += 1
            if self.i <= 5:
                return _sine_chunk(self.i * frames, frames)
            return np.zeros(frames * 2, np.int16)

        def close(self):
            pass

    settings = AudioCaptureSettings(use_silence_gate=True)
    got = []
    cap = AudioCapture(settings, got.append, source=ToneThenSilence())
    cap.start_capture()
    deadline = time.time() + 5
    while cap.chunks_gated < 10 and time.time() < deadline:
        time.sleep(0.01)
    cap.stop_capture()
    # 5 tone chunks + hangover chunks of silence pass; then gated
    assert len(got) == 5 + SILENCE_HANGOVER_CHUNKS, len(got)


class _FakeServer:
    def __init__(self):
        self.sent = []

    def broadcast(self, msg):
        self.sent.append(msg)


def test_pipeline_broadcasts_prefixed_chunks():
    async def main():
        server = _FakeServer()
        settings = AudioCaptureSettings(channels=2)
        pipe = AudioPipeline(server, settings,
                             source=SyntheticTone(settings, realtime=False))
        await pipe.start()
        deadline = time.time() + 5
        while len(server.sent) < 10 and time.time() < deadline:
            await asyncio.sleep(0.02)
        await pipe.stop()
        pipe.close()
        assert len(server.sent) >= 10
        for msg in server.sent:
            assert msg[:2] == b"\x01\x00"
            assert len(msg) > 2
        # mic reverse path: count frames even with no pulse backend
        await pipe.on_mic_data(b"\x00\x01" * 480)
        assert pipe.mic.frames_in == 1

    asyncio.run(main())


def test_pipeline_drop_oldest_under_stall():
    async def main():
        server = _FakeServer()
        settings = AudioCaptureSettings(channels=2)
        pipe = AudioPipeline(server, settings,
                             source=SyntheticTone(settings, realtime=False))
        # fill the queue directly without a sender draining it
        pipe._loop = asyncio.get_running_loop()
        pipe._queue = asyncio.Queue(4)
        for i in range(10):
            pipe._enqueue(pipe._queue, bytes([i]))
        assert pipe._queue.qsize() == 4
        assert pipe.chunks_dropped == 6
        # newest survive
        items = [pipe._queue.get_nowait() for _ in range(4)]
        assert items == [bytes([i]) for i in (6, 7, 8, 9)]

    asyncio.run(main())


def test_opus_inband_fec_recovers_lost_frames():
    """Audio must survive packet loss without audible gaps: encode a tone
    with in-band FEC (as the WebRTC audio path does), drop 5% of packets,
    reconstruct each lost frame from the FOLLOWING packet's FEC data
    (falling back to PLC when the next packet is also lost)."""
    from selkies_tpu.audio.codec import (OpusDecoder, OpusEncoder,
                                         opus_available)

    if not opus_available():
        import pytest as _pytest

        _pytest.skip("libopus unavailable")

    rate, ch, frames = 48000, 2, 960
    t = np.arange(frames * 100) / rate
    tone = (np.sin(2 * np.pi * 440 * t) * 12000).astype(np.int16)
    pcm = np.stack([tone, tone], axis=1)

    enc = OpusEncoder(rate, ch, 128000, inband_fec=True)
    packets = [enc.encode(pcm[i * frames:(i + 1) * frames])
               for i in range(100)]
    enc.close()

    rng = np.random.default_rng(4)
    lost = set(int(i) for i in rng.choice(np.arange(5, 95), 5,
                                          replace=False))
    dec = OpusDecoder(rate, ch)
    out = []
    for i in range(100):
        if i in lost:
            if i + 1 not in lost:
                out.append(dec.decode_fec(packets[i + 1], frames))
            else:
                out.append(dec.decode_plc(frames))
        else:
            out.append(dec.decode(packets[i]))
    dec.close()

    audio = np.concatenate(out).astype(np.float64)
    assert audio.shape[0] == 100 * frames
    # every recovered window must still carry the tone: no dropout —
    # compare per-frame RMS energy against the source's
    src_rms = np.sqrt(np.mean(pcm.astype(np.float64) ** 2))
    for i in sorted(lost):
        w = audio[i * frames:(i + 1) * frames]
        rms = np.sqrt(np.mean(w ** 2))
        assert rms > 0.25 * src_rms, (i, rms, src_rms)
    # and overall the decode tracks the source closely
    full_rms = np.sqrt(np.mean(audio ** 2))
    assert abs(full_rms - src_rms) / src_rms < 0.25
