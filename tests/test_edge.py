"""Wire-edge hardening tests (ISSUE 3): protocol armor, admission
control, rate limiting, resize coalescing, and slow-consumer eviction.

Like tests/test_robustness.py, everything drives the real
``DataStreamingServer.ws_handler`` through in-process fake websockets
(``robustness.testing.InProcessClient``) — no network, no ``websockets``
package. Acceptance criteria covered here:

(a) a deterministic 500-message fuzz corpus through ``ws_handler`` kills
    zero sessions and leaves ``_uploads`` empty;
(b) a 50-message resize storm triggers ≤ 3 reconfigurations while a
    concurrent healthy client keeps receiving frames;
(c) a stalled consumer is evicted (``slow_client_evictions_total``)
    while a second client's frame IDs keep advancing;
(d) the (max_clients+1)-th connection is rejected with
    ``KILL server_full`` and ``sessions_rejected_total`` incremented.
"""

import asyncio
import json
import os
import sys
import time

import numpy as np
import pytest

from selkies_tpu.encoder.jpeg import StripeOutput
from selkies_tpu.observability.metrics import HAVE_PROM, Metrics
from selkies_tpu.protocol import VideoStripe, unpack_binary
from selkies_tpu.robustness import (BoundedSendQueue, ConnectionGuard,
                                    InProcessClient, TokenBucket,
                                    classify_verb, parse_limit_spec)
from selkies_tpu.server.app import StreamingApp
from selkies_tpu.server.data_server import DataStreamingServer
from selkies_tpu.settings import Settings

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture
def anyio_backend():
    return "asyncio"


# ---------------------------------------------------------------------------
# fakes (same shapes as test_robustness.py)


class FakeEncoder:
    def __init__(self, overrides=None):
        self.submitted = 0
        self.closed = False
        self._ready = []

    def submit(self, frame):
        self.submitted += 1
        self._ready.append(
            (self.submitted,
             [StripeOutput(y_start=0, height=64,
                           jpeg=b"\xff\xd8FAKE%d" % self.submitted
                           + b"\xff\xd9",
                           is_paintover=False)]))

    def poll(self):
        out, self._ready = self._ready, []
        return out

    def flush(self):
        return self.poll()

    def close(self):
        self.closed = True


class FakeSource:
    def __init__(self, width, height, fps):
        self.width, self.height, self.fps = width, height, fps

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return np.zeros((self.height, self.width, 3), np.uint8)


class StalledClient(InProcessClient):
    """A consumer whose reads stall after the handshake: ``send`` blocks
    forever once ``stall`` is set, like a TCP peer that stopped ACKing."""

    def __init__(self):
        super().__init__()
        self.stall = False
        self._stalled = asyncio.Event()

    # send_nowait stays (pre-queue handshake broadcasts); the bounded
    # send queue's drainer always awaits async send, where the stall bites

    async def send(self, message):
        if self.stall:
            self._stalled.set()
            await asyncio.Event().wait()    # never set: blocks forever
        await super().send(message)


def make_server(**settings_env):
    env = {"SELKIES_PORT": "0", "SELKIES_AUDIO_ENABLED": "false",
           "SELKIES_COMMAND_ENABLED": "false"}
    env.update(settings_env)
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)

    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=lambda w, h, s, overrides=None: FakeEncoder(),
        source_factory=lambda w, h, fps, **kw: FakeSource(w, h, fps),
        host="127.0.0.1",
    )
    app.data_server = server
    return server


async def wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return False


async def open_client(server, settings_body=None, ws=None):
    ws = ws or InProcessClient()
    task = asyncio.create_task(server.ws_handler(ws))
    assert await wait_until(
        lambda: len(ws.sent) >= 2 or task.done(), timeout=5.0)
    if settings_body is not None:
        ws.feed("SETTINGS," + json.dumps(settings_body))
    return ws, task


async def close_client(ws, task):
    await ws.close()
    try:
        await asyncio.wait_for(task, 5.0)
    except asyncio.TimeoutError:
        task.cancel()


PRIMARY = {"displayId": "primary", "initialClientWidth": 320,
           "initialClientHeight": 240, "framerate": 60}


# ---------------------------------------------------------------------------
# ratelimit primitives (pure, clock-injected)


def test_token_bucket_refill_and_burst():
    now = [0.0]
    b = TokenBucket(rate=10.0, burst=5.0, clock=lambda: now[0])
    assert all(b.try_take() for _ in range(5))
    assert not b.try_take()            # burst exhausted
    now[0] = 0.3                       # +3 tokens
    assert b.try_take() and b.try_take() and b.try_take()
    assert not b.try_take()
    now[0] = 100.0
    assert b.tokens == 5.0             # capped at burst


def test_parse_limit_spec_overrides_and_rejects():
    limits = parse_limit_spec("settings=2:10,mic=512000")
    assert limits["settings"] == (2.0, 10.0)
    assert limits["mic"] == (512000.0, 1024000.0)   # burst defaults to 2x
    assert limits["input"][0] > 0                   # defaults kept
    with pytest.raises(ValueError):
        parse_limit_spec("nosuchclass=5")
    with pytest.raises(ValueError):
        parse_limit_spec("settings=-1")
    with pytest.raises(ValueError):
        parse_limit_spec("garbage")


def test_classify_verb_table():
    assert classify_verb("SETTINGS") == "settings"
    assert classify_verb("cmd") == "settings"
    # pipeline-toggling verbs are as heavy as SETTINGS (stop/start a
    # capture pipeline or the shared audio pipeline), so they share the
    # human-scale bucket — not the 300/s control bucket
    for v in ("START_VIDEO", "STOP_VIDEO", "START_AUDIO", "STOP_AUDIO"):
        assert classify_verb(v) == "settings"
    assert classify_verb("r") == "resize"
    assert classify_verb("s") == "resize"
    assert classify_verb("CLIENT_FRAME_ACK") == "control"
    # stateful upload verbs ride the upload (paced, never dropped) lane:
    # a dropped FILE_UPLOAD_END would corrupt the transfer
    for v in ("FILE_UPLOAD_START", "FILE_UPLOAD_END", "FILE_UPLOAD_ERROR"):
        assert classify_verb(v) == "upload"
    for v in ("kd", "m", "m2", "js", "cw", "pong", "whatever"):
        assert classify_verb(v) == "input"


def test_allow_clamps_units_to_burst():
    # a unit larger than the burst must still be admissible at a bounded
    # rate (size gating is the caps' job, the bucket meters rate)
    now = [0.0]
    g = ConnectionGuard(limits={"mic": (100.0, 50.0)}, clock=lambda: now[0])
    assert g.allow("mic", 500)         # burst-sized charge, admitted
    assert not g.allow("mic", 500)     # bucket drained: limited now
    now[0] = 0.5                       # refill at the configured rate
    assert g.allow("mic", 500)


def test_upload_bytes_are_paced_not_dropped():
    now = [0.0]
    b = TokenBucket(rate=100.0, burst=50.0, clock=lambda: now[0])
    assert b.take_with_debt(50) == 0.0
    assert b.take_with_debt(100) == pytest.approx(1.0)   # 100 in debt
    now[0] = 2.0                           # debt repaid, burst restored
    assert b.take_with_debt(1) == 0.0
    g = ConnectionGuard(limits={"upload": (100.0, 50.0)},
                        clock=lambda: now[0])
    assert g.throttle("upload", 10) == 0.0
    assert g.throttle("upload", 1000) > 0.0               # paced, accepted
    assert g.throttle("upload", 10 ** 9) <= 30.0          # wait is capped


def test_connection_guard_error_budget_refills():
    now = [0.0]
    g = ConnectionGuard(error_budget=3, error_refill_per_s=1.0,
                        clock=lambda: now[0])
    assert not g.record_error()
    assert not g.record_error()
    assert not g.record_error()
    assert g.record_error()            # budget exhausted
    now[0] = 2.0                       # slow refill forgives old sins
    assert not g.record_error()
    assert g.errors_total == 5


def test_bounded_send_queue_drop_oldest_video_never_control():
    now = [0.0]
    q = BoundedSendQueue(max_video=3, evict_after_s=1.0,
                         clock=lambda: now[0])
    q.offer("control-1", control=True)
    for i in range(3):
        q.offer(b"v%d" % i)
    assert q.offer(b"v3") is False     # drops v0, keeps control
    assert q.dropped_video_total == 1
    assert q.overflow_since == 0.0
    got = [q.pop() for _ in range(4)]
    assert got == ["control-1", b"v1", b"v2", b"v3"]
    assert q.pop() is None
    assert q.overflow_since is None    # drained below half: caught up
    # sustained overflow → eviction verdict
    for i in range(10):
        q.offer(b"x%d" % i)
    assert not q.should_evict
    now[0] = 2.0
    q.offer(b"y")
    assert q.should_evict


# ---------------------------------------------------------------------------
# acceptance (a): deterministic fuzz corpus — zero session deaths


@pytest.mark.anyio
async def test_fuzz_corpus_kills_no_sessions(tmp_path, monkeypatch):
    from tools.proto_fuzz import fuzz_session

    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path / "up"))
    report = await fuzz_session(iterations=500, seed=0)
    assert report["premature_deaths"] == 0, report
    assert report["kills"] == 0, report
    assert report["uploads_leaked"] == 0, report
    assert report["observer_alive"], report
    assert report["observer_streaming"], report
    # the corpus actually exercised the boundary
    assert report["protocol_errors"] > 0, report


# ---------------------------------------------------------------------------
# acceptance (b): resize storm coalesces; healthy client keeps streaming


@pytest.mark.anyio
async def test_resize_storm_coalesces_reconfigures(monkeypatch):
    server = make_server(SELKIES_RESIZE_DEBOUNCE_MS="150")
    runs_before = None
    ws, task = await open_client(server, PRIMARY)
    viewer, viewer_task = await open_client(server)   # healthy co-viewer
    try:
        assert await wait_until(lambda: viewer.n_frames() >= 2)
        runs_before = server.edge_stats["reconfigure_runs"]
        n0 = viewer.n_frames()
        for i in range(50):
            ws.feed(f"r,{320 + 2 * (i % 7)}x{240 + 2 * (i % 5)},primary")
        # let the handler ingest the whole storm, then the debounced
        # worker settle
        assert await wait_until(lambda: ws._incoming.empty(), timeout=10.0)
        assert await wait_until(
            lambda: not server._reconfig_dirty
            and (server._reconfig_task is None
                 or server._reconfig_task.done()),
            timeout=10.0)
        runs = server.edge_stats["reconfigure_runs"] - runs_before
        assert 1 <= runs <= 3, f"storm cost {runs} reconfigurations"
        # most of the storm was absorbed: coalesced or rate-limited
        absorbed = (server.edge_stats["reconfigure_coalesced"]
                    + server.edge_stats["rate_limited"].get("resize", 0))
        assert absorbed >= 40, server.edge_stats
        # the healthy viewer kept receiving frames through the storm
        assert await wait_until(lambda: viewer.n_frames() > n0 + 2)
        assert not viewer.closed
    finally:
        await close_client(viewer, viewer_task)
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# acceptance (c): slow-consumer eviction; healthy client unaffected


@pytest.mark.anyio
async def test_stalled_consumer_evicted_healthy_keeps_streaming():
    server = make_server(
        SELKIES_MAX_SEND_QUEUE="8",
        SELKIES_SLOW_CLIENT_EVICT_S="0",   # evict on first sustained drop
    )
    if HAVE_PROM:
        server.metrics = Metrics(port=0)
    owner, owner_task = await open_client(server, PRIMARY)
    slow = StalledClient()
    slow, slow_task = await open_client(server, ws=slow)
    try:
        assert await wait_until(lambda: owner.n_frames() >= 2)
        assert await wait_until(lambda: slow.n_frames() >= 1)
        slow.stall = True                  # the viewer stops reading
        assert await wait_until(
            lambda: server.edge_stats["slow_client_evictions"] >= 1,
            timeout=15.0)
        assert await wait_until(lambda: slow.closed, timeout=10.0)
        # the owner's frame ids kept advancing past the eviction
        ids = [unpack_binary(m).frame_id for m in owner.binary()[-2:]]
        assert await wait_until(lambda: owner.binary() and isinstance(
            unpack_binary(owner.binary()[-1]), VideoStripe)
            and unpack_binary(owner.binary()[-1]).frame_id > max(ids))
        assert not owner.closed
        if HAVE_PROM:
            text = server.metrics.render().decode()
            assert "slow_client_evictions_total 1.0" in text
    finally:
        await close_client(slow, slow_task)
        await close_client(owner, owner_task)
        await server.stop()


# ---------------------------------------------------------------------------
# acceptance (d): admission control


@pytest.mark.anyio
async def test_max_clients_rejects_with_kill_server_full():
    server = make_server(SELKIES_MAX_CLIENTS="2")
    if HAVE_PROM:
        server.metrics = Metrics(port=0)
    ws1, t1 = await open_client(server, PRIMARY)
    ws2, t2 = await open_client(server)
    try:
        assert len(server.clients) == 2
        ws3 = InProcessClient()
        t3 = asyncio.create_task(server.ws_handler(ws3))
        await asyncio.wait_for(t3, 5.0)          # rejected → handler returns
        assert ws3.sent == ["KILL server_full"]
        assert ws3.closed
        assert server.edge_stats["sessions_rejected"] == 1
        assert len(server.clients) == 2          # never admitted
        # the admitted clients are untouched
        assert await wait_until(lambda: ws2.n_frames() >= 1)
        if HAVE_PROM:
            assert "sessions_rejected_total 1.0" in \
                server.metrics.render().decode()
        # a slot freeing up re-opens admission
        await close_client(ws2, t2)
        ws4, t4 = await open_client(server)
        assert not ws4.closed
        await close_client(ws4, t4)
    finally:
        await close_client(ws1, t1)
        await server.stop()


@pytest.mark.anyio
async def test_max_displays_rejects_further_pipelines():
    server = make_server(SELKIES_MAX_DISPLAYS="1")
    ws1, t1 = await open_client(server, PRIMARY)
    ws2, t2 = await open_client(server)
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        ws2.feed("SETTINGS," + json.dumps({"displayId": "display2"}))
        assert await wait_until(
            lambda: any(isinstance(m, str) and m == "KILL server_full"
                        for m in ws2.sent))
        assert await wait_until(lambda: ws2.closed)
        assert server.edge_stats["sessions_rejected"] == 1
        assert "display2" not in server.display_clients
        assert not ws1.closed
    finally:
        await close_client(ws2, t2)
        await close_client(ws1, t1)
        await server.stop()


@pytest.mark.anyio
async def test_load_shedding_rejects_new_connections():
    server = make_server(SELKIES_SHED_DROP_THRESHOLD="10")
    ws1, t1 = await open_client(server, PRIMARY)
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]

        class DroppyEncoder(FakeEncoder):
            dropped = 0

            def stats(self):
                return {"frames_dropped": self.dropped}

        assert await wait_until(lambda: st.encoder is not None)
        enc = DroppyEncoder()
        st.encoder = enc
        # two consecutive over-threshold ticks engage shedding
        enc.dropped = 20
        server._update_load_shed()
        enc.dropped = 40
        server._update_load_shed()
        assert server._load_shedding
        ws2 = InProcessClient()
        t2 = asyncio.create_task(server.ws_handler(ws2))
        await asyncio.wait_for(t2, 5.0)
        assert ws2.sent == ["KILL server_full"]
        assert server.edge_stats["sessions_rejected"] == 1
        # a supervised restart resets the encoder's cumulative counter;
        # the post-reset total still counts as new drops (no spurious
        # strike reset mid-overload)
        enc.dropped = 15
        server._update_load_shed()
        assert server._load_shedding
        # recovery: drops stop → shedding releases → admission resumes
        enc.dropped = 15               # unchanged: delta 0 this tick
        server._update_load_shed()
        assert not server._load_shedding
        ws3, t3 = await open_client(server)
        assert not ws3.closed
        await close_client(ws3, t3)
    finally:
        await close_client(ws1, t1)
        await server.stop()


# ---------------------------------------------------------------------------
# tentpole: per-message boundary + error budget


@pytest.mark.anyio
async def test_malformed_messages_never_kill_session():
    server = make_server()
    ws, task = await open_client(server, PRIMARY)
    try:
        assert await wait_until(lambda: ws.n_frames() >= 1)
        for bad in ("KILL you", "PIPELINE_RESETTING primary",
                    b"\x7fgarbage", b"", b"\x00\x01\x00\x02fullframe",
                    "SETTINGS,[]"):
            ws.feed(bad)
        n_err = 6
        assert await wait_until(
            lambda: server.edge_stats["protocol_errors"] >= n_err)
        n0 = ws.n_frames()
        assert await wait_until(lambda: ws.n_frames() > n0 + 2)
        assert not ws.closed and not task.done()
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_error_budget_exhaustion_kills_only_abuser():
    server = make_server(SELKIES_PROTOCOL_ERROR_BUDGET="5")
    if HAVE_PROM:
        server.metrics = Metrics(port=0)
    owner, owner_task = await open_client(server, PRIMARY)
    abuser, abuser_task = await open_client(server)
    try:
        assert await wait_until(lambda: owner.n_frames() >= 1)
        for _ in range(10):
            abuser.feed(b"\xee hostile binary")
        assert await wait_until(
            lambda: any(m == "KILL protocol_abuse"
                        for m in abuser.texts()), timeout=10.0)
        await asyncio.wait_for(abuser_task, 5.0)
        assert abuser.closed
        # one socket died; the session loop of others is untouched
        n0 = owner.n_frames()
        assert await wait_until(lambda: owner.n_frames() > n0 + 2)
        assert not owner.closed
        if HAVE_PROM:
            assert "protocol_errors_total" in \
                server.metrics.render().decode()
    finally:
        await close_client(abuser, abuser_task)
        await close_client(owner, owner_task)
        await server.stop()


@pytest.mark.anyio
async def test_input_flood_is_rate_limited_not_fatal():
    server = make_server(SELKIES_RATE_LIMITS="input=50:100")
    if HAVE_PROM:
        server.metrics = Metrics(port=0)
    ws, task = await open_client(server, PRIMARY)
    try:
        assert await wait_until(lambda: ws.n_frames() >= 1)
        for i in range(500):
            ws.feed(f"m,{i},{i},0,0")
        assert await wait_until(
            lambda: server.edge_stats["rate_limited"].get("input", 0) >= 300)
        assert not ws.closed
        n0 = ws.n_frames()
        assert await wait_until(lambda: ws.n_frames() > n0)
        if HAVE_PROM:
            text = server.metrics.render().decode()
            assert 'rate_limited_total{klass="input"}' in text
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# satellites: upload fd hygiene, mic cap, viewer ownership


@pytest.mark.anyio
async def test_upload_cleanup_on_disconnect(tmp_path, monkeypatch):
    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path))
    server = make_server()
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed("FILE_UPLOAD_START:partial.bin:1000")
        ws.feed(b"\x01" + b"x" * 100)
        assert await wait_until(lambda: ws in server._uploads)
        up = server._uploads[ws]
        # disconnect mid-upload: fd closed, partial file unlinked
        await close_client(ws, task)
        assert server._uploads == {}
        assert up.fobj.closed
        assert not os.path.exists(up.path)
    finally:
        await server.stop()


@pytest.mark.anyio
async def test_short_upload_detected_and_unlinked(tmp_path, monkeypatch):
    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path))
    server = make_server()
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed("FILE_UPLOAD_START:short.bin:1000")
        ws.feed(b"\x01" + b"x" * 10)
        ws.feed("FILE_UPLOAD_END:short.bin")
        assert await wait_until(
            lambda: any(isinstance(m, str)
                        and m.startswith("FILE_UPLOAD_ERROR:short.bin")
                        for m in ws.sent))
        assert not (tmp_path / "short.bin").exists()
        assert server._uploads == {}
        # a complete upload still lands
        ws.feed("FILE_UPLOAD_START:ok.bin:4")
        ws.feed(b"\x01good")
        ws.feed("FILE_UPLOAD_END:ok.bin")
        assert await wait_until(lambda: (tmp_path / "ok.bin").exists())
        assert (tmp_path / "ok.bin").read_bytes() == b"good"
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_orphan_file_chunks_are_metered():
    """0x01 frames with no open upload must still charge the upload
    pacer — a free unmetered byte lane would defeat the rate limiting."""
    server = make_server(SELKIES_RATE_LIMITS="upload=1000:2000")
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed(b"\x01" + b"x" * 2100)     # no FILE_UPLOAD_START ever sent
        ws.feed(b"\x01" + b"x" * 2100)
        assert await wait_until(
            lambda: server.edge_stats["upload_paced"] >= 1)
        assert not ws.closed
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_superseded_upload_partial_unlinked(tmp_path, monkeypatch):
    """A new FILE_UPLOAD_START while one is open must abort the old
    transfer completely — fd closed AND the truncated partial removed."""
    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path))
    server = make_server()
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed("FILE_UPLOAD_START:first.bin:1000")
        ws.feed(b"\x01" + b"x" * 10)
        assert await wait_until(lambda: (tmp_path / "first.bin").exists())
        ws.feed("FILE_UPLOAD_START:second.bin:4")
        ws.feed(b"\x01good")
        ws.feed("FILE_UPLOAD_END:second.bin")
        assert await wait_until(lambda: (tmp_path / "second.bin").exists())
        assert not (tmp_path / "first.bin").exists()
        assert server._uploads == {}
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_mic_chunk_cap_enforced():
    server = make_server(SELKIES_MAX_MIC_CHUNK_KB="1")
    seen = []

    class FakeAudio:
        running = True

        async def on_mic_data(self, pcm):
            seen.append(len(pcm))

        async def start(self):
            pass

        async def stop(self):
            pass

        def close(self):
            pass

    server.audio_pipeline = FakeAudio()
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed(b"\x02" + b"\x00" * 512)          # under the 1 KiB cap
        ws.feed(b"\x02" + b"\x00" * (64 * 1024))  # over: dropped + counted
        assert await wait_until(lambda: seen == [512])
        assert await wait_until(
            lambda: server.edge_stats["protocol_errors"] >= 1)
        assert not ws.closed
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_bad_setting_values_ignored_not_fatal():
    """A garbage value inside SETTINGS costs only itself: the rest of the
    payload applies, the display registers fully (no zombie holding a
    max_displays slot), and nothing hits the error budget."""
    server = make_server()
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": "garbage",
        "initialClientHeight": 240, "framerate": "also-garbage",
        "jpeg_quality": 77})
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]
        assert st.height == 240                  # good value applied
        assert st.width == 1024                  # default kept, not zombie
        assert st.overrides.get("jpeg_quality") == 77
        assert await wait_until(lambda: ws.n_frames() >= 1)
        assert server.edge_stats["protocol_errors"] == 0
    finally:
        await close_client(ws, task)
        await server.stop()


@pytest.mark.anyio
async def test_transport_death_not_charged_as_abuse(tmp_path, monkeypatch):
    """A handler failing to SEND to a dead peer ends the session like any
    transport error — it must not count as a protocol error or burn the
    abuse budget."""
    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path))
    server = make_server()
    ws, task = await open_client(server, PRIMARY)
    try:
        ws.feed("FILE_UPLOAD_START:x.bin:100")
        ws.feed(b"\x01short")
        assert await wait_until(lambda: ws in server._uploads)
        ws.closed = True                   # peer died without a close frame
        ws.feed("FILE_UPLOAD_END:x.bin")   # short-upload reply hits a corpse
        await asyncio.wait_for(task, 10.0)
        assert server.edge_stats["protocol_errors"] == 0
        assert server._uploads == {}
    finally:
        await server.stop()


@pytest.mark.anyio
async def test_viewer_cannot_mutate_owned_display():
    """A shared-mode viewer must not stop, resize, or ACK-poison the
    owner's display (the fuzzer found all three)."""
    server = make_server()
    owner, owner_task = await open_client(server, PRIMARY)
    viewer, viewer_task = await open_client(server)
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]
        viewer.feed("STOP_VIDEO")
        viewer.feed("r,640x480,primary")
        viewer.feed("CLIENT_FRAME_ACK 40000")
        await asyncio.sleep(0.3)
        assert st.video_active
        assert (st.width, st.height) == (320, 240)
        assert st.bp.acknowledged_frame_id == -1
        # the owner still can
        owner.feed("CLIENT_FRAME_ACK 3")
        assert await wait_until(lambda: st.bp.acknowledged_frame_id == 3)
    finally:
        await close_client(viewer, viewer_task)
        await close_client(owner, owner_task)
        await server.stop()


@pytest.mark.anyio
async def test_resize_dimensions_clamped():
    server = make_server(SELKIES_RESIZE_DEBOUNCE_MS="10")
    ws, task = await open_client(server, PRIMARY)
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]
        ws.feed("r,1000000x1000000,primary")
        assert await wait_until(lambda: st.width == 8192)
        assert st.height == 8192
        ws.feed("r,2x2,primary")
        assert await wait_until(lambda: st.width == 16)
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# slow: longer fuzz run (satellite: CI wiring like tools/chaos_run.py)


@pytest.mark.slow
@pytest.mark.anyio
async def test_fuzz_long_run_survives(tmp_path, monkeypatch):
    from tools.proto_fuzz import fuzz_session

    monkeypatch.setenv("SELKIES_UPLOAD_DIR", str(tmp_path / "up"))
    report = await fuzz_session(iterations=3000, seed=1234)
    assert report["alive"], report
    # and with a tiny budget, the abuse kill path fires without collateral
    report2 = await fuzz_session(iterations=400, seed=99, error_budget=5)
    assert report2["kills"] >= 1, report2
    assert report2["premature_deaths"] == 0, report2
    assert report2["observer_alive"], report2
