"""The parser as a trust boundary (ISSUE 3 satellite).

``parse_text_message`` and ``unpack_client_binary`` face hostile input:
these tests sweep the full verb grammar table (round trips), truncations,
prefix confusion, wrong-direction frames, and oversize arguments. The
invariant everywhere: a parse either returns a typed message or raises
``ProtocolError``/``ValueError`` — never any other exception, never a
misclassified verb.
"""

import random
import string

import pytest

from selkies_tpu.protocol import (
    BinaryType,
    FileChunk,
    MicChunk,
    ProtocolError,
    pack_file_chunk,
    pack_mic_chunk,
    parse_text_message,
    unpack_binary,
    unpack_client_binary,
)

# ---------------------------------------------------------------------------
# round trips over the full client-verb grammar table (wire.py doc block)

GRAMMAR_TABLE = [
    # (message, verb, args)
    ("SETTINGS,{}", "SETTINGS", ()),
    ("CLIENT_FRAME_ACK 7", "CLIENT_FRAME_ACK", ("7",)),
    ("CLIENT_FRAME_ACK", "CLIENT_FRAME_ACK", ()),
    ("r,1920x1080,primary", "r", ("1920x1080", "primary")),
    ("r,640x480", "r", ("640x480",)),
    ("s,1.5", "s", ("1.5",)),
    ("cmd,echo a,b c", "cmd", ("echo a,b c",)),
    ("SET_NATIVE_CURSOR_RENDERING,1", "SET_NATIVE_CURSOR_RENDERING", ("1",)),
    ("START_VIDEO", "START_VIDEO", ()),
    ("STOP_VIDEO", "STOP_VIDEO", ()),
    ("START_AUDIO", "START_AUDIO", ()),
    ("STOP_AUDIO", "STOP_AUDIO", ()),
    ("FILE_UPLOAD_START:a/b.txt:123", "FILE_UPLOAD_START", ("a/b.txt", "123")),
    ("FILE_UPLOAD_END:a/b.txt", "FILE_UPLOAD_END", ("a/b.txt",)),
    ("FILE_UPLOAD_ERROR:a.txt:oops", "FILE_UPLOAD_ERROR", ("a.txt", "oops")),
    ("cr", "cr", ()),
    ("cw,aGk=", "cw", ("aGk=",)),
    ("cb,text/plain,aGk=", "cb", ("text/plain", "aGk=")),
    ("cws,12", "cws", ("12",)),
    ("cwd,aGk=", "cwd", ("aGk=",)),
    ("cwe", "cwe", ()),
    ("cbs,text/plain,9", "cbs", ("text/plain", "9")),
    ("cbd,aGk=", "cbd", ("aGk=",)),
    ("cbe", "cbe", ()),
    ("kd,65", "kd", ("65",)),
    ("ku,65", "ku", ("65",)),
    ("kr", "kr", ()),
    ("m,10,20,0,0", "m", ("10", "20", "0", "0")),
    ("m2,-1,-2,4,1", "m2", ("-1", "-2", "4", "1")),
    ("js,c,0,Xbox,1118,654", "js", ("c", "0", "Xbox", "1118", "654")),
    ("js,b,0,3,1", "js", ("b", "0", "3", "1")),
    ("js,a,0,1,0.5", "js", ("a", "0", "1", "0.5")),
    ("js,d,0", "js", ("d", "0")),
    ("_f 59.9", "_f", ("59.9",)),
    ("_l 12.5", "_l", ("12.5",)),
    ("pong", "pong", ()),
    ("p,1", "p", ("1",)),
    ("vb,4000", "vb", ("4000",)),
    ("ab,128000", "ab", ("128000",)),
]


@pytest.mark.parametrize("raw,verb,args", GRAMMAR_TABLE)
def test_grammar_round_trip(raw, verb, args):
    m = parse_text_message(raw)
    assert m.verb == verb
    assert m.args == args


def test_settings_json_body_preserved():
    m = parse_text_message('SETTINGS,{"a": "b,c"}')
    assert m.verb == "SETTINGS" and m.json_body == '{"a": "b,c"}'


# ---------------------------------------------------------------------------
# exact verb-plus-delimiter matching (no prefix confusion)


@pytest.mark.parametrize("raw", [
    "CLIENT_FRAME_ACKjunk",
    "START_VIDEOO",
    "_fjunk",
    "_f5",                       # missing the space delimiter
    "SETTINGSjunk",
    "FILE_UPLOAD_STARTjunk",
])
def test_glued_verbs_are_not_their_prefix(raw):
    m = parse_text_message(raw)
    assert m.verb not in (
        "CLIENT_FRAME_ACK", "START_VIDEO", "_f", "SETTINGS",
        "FILE_UPLOAD_START"), raw


# ---------------------------------------------------------------------------
# server→client verbs are rejected from the client side


@pytest.mark.parametrize("raw", [
    "KILL",
    "KILL go away",
    "KILL,reason",
    "PIPELINE_RESETTING primary",
    "PIPELINE_RESETTING,primary",
    "MODE websockets",
    "VIDEO_STARTED",
    "VIDEO_STOPPED",
    "AUDIO_STARTED",
    "AUDIO_STOPPED",
])
def test_server_only_verbs_rejected(raw):
    with pytest.raises(ProtocolError):
        parse_text_message(raw)


def test_server_verb_lookalikes_are_unknown_not_rejected():
    # "KILLx" is not KILL: it must not raise, just parse as unknown
    assert parse_text_message("KILLx").verb == "KILLx"
    assert parse_text_message("PIPELINE_RESETTINGx").verb == \
        "PIPELINE_RESETTINGx"


# ---------------------------------------------------------------------------
# property-style sweep: parse never raises anything but ProtocolError


def test_parse_total_over_mutations():
    rng = random.Random(42)
    corpus = [raw for raw, _, _ in GRAMMAR_TABLE]
    alphabet = string.printable + "\x00\x7fé☃"
    for _ in range(2000):
        base = rng.choice(corpus)
        kind = rng.randrange(4)
        if kind == 0:
            msg = base[:rng.randrange(len(base) + 1)]
        elif kind == 1:
            i = rng.randrange(len(base) + 1)
            msg = base[:i] + "".join(rng.choice(alphabet)
                                     for _ in range(rng.randrange(1, 6))) \
                + base[i:]
        elif kind == 2:
            msg = base + rng.choice(",: ") + "A" * rng.randrange(0, 10000)
        else:
            msg = "".join(rng.choice(alphabet)
                          for _ in range(rng.randrange(0, 200)))
        try:
            m = parse_text_message(msg)
        except ProtocolError:
            continue
        assert isinstance(m.verb, str)
        assert all(isinstance(a, str) for a in m.args)


def test_oversize_args_parse_without_blowup():
    huge = "r," + "9" * 100000 + "x" + "9" * 100000
    m = parse_text_message(huge)
    assert m.verb == "r" and len(m.args) == 1
    m = parse_text_message("CLIENT_FRAME_ACK " + "1" * 100000)
    assert m.verb == "CLIENT_FRAME_ACK"


# ---------------------------------------------------------------------------
# client binary plane: direction is part of the contract


def test_client_binary_round_trip():
    f = unpack_client_binary(pack_file_chunk(b"\x00\x01data"))
    assert isinstance(f, FileChunk) and f.payload == b"\x00\x01data"
    m = unpack_client_binary(pack_mic_chunk(b"\x00" * 32))
    assert isinstance(m, MicChunk) and len(m.payload) == 32


@pytest.mark.parametrize("t", [
    int(BinaryType.H264_FULL_FRAME),
    int(BinaryType.JPEG_STRIPE),
    int(BinaryType.H264_STRIPE),
])
def test_wrong_direction_type_bytes_rejected(t):
    with pytest.raises(ProtocolError):
        unpack_client_binary(bytes([t]) + b"payload")


def test_unknown_and_empty_client_binary_rejected():
    with pytest.raises(ProtocolError):
        unpack_client_binary(b"")
    for t in (0x05, 0x10, 0x7f, 0xff):
        with pytest.raises(ProtocolError):
            unpack_client_binary(bytes([t]))


def test_truncated_server_binary_still_rejected_as_valueerror():
    # unpack_binary's truncation errors remain ValueError (ProtocolError
    # subclasses it) — pre-existing callers keep working
    for frame in (b"", b"\x00\x01", b"\x03\x00\x00", b"\x04" + b"\x00" * 5):
        with pytest.raises(ValueError):
            unpack_binary(frame)
