"""Pallas fused DCT+quant+zigzag kernel vs the XLA reference path.

Runs in interpreter mode on the CPU test backend; the same kernel
compiles for real on TPU (opt-in, see ops/pallas_dct.py)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")


def xla_reference(plane, row_recip):
    import jax.numpy as jnp

    from selkies_tpu.ops.dct import block_dct2, blockify
    from selkies_tpu.ops.quant import ZIGZAG

    blocks = blockify(jnp.asarray(plane, jnp.float32)) - 128.0
    coeffs = block_dct2(blocks)                      # [by, bx, 8, 8]
    q = jnp.round(coeffs * jnp.asarray(row_recip)[:, None])
    by, bx = q.shape[:2]
    return np.asarray(jnp.take(q.reshape(by, bx, 64),
                               jnp.asarray(ZIGZAG), axis=-1))


def test_pallas_matches_xla_path():
    from selkies_tpu.ops.pallas_dct import dct8_quant_zigzag
    from selkies_tpu.ops.quant import quality_scaled_tables

    rng = np.random.default_rng(0)
    h, w = 32, 256
    plane = rng.integers(0, 256, (h, w)).astype(np.float32)
    ly, _ = quality_scaled_tables(40)
    py, _ = quality_scaled_tables(90)
    # distinct table per 8-row band exercises the per-band recip block
    row_recip = np.stack(
        [1.0 / (ly if i % 2 == 0 else py) for i in range(h // 8)]
    ).astype(np.float32)

    got = np.asarray(dct8_quant_zigzag(plane, row_recip, interpret=True))
    want = xla_reference(plane, row_recip)
    assert got.shape == want.shape == (h // 8, w // 8, 64)
    # same math, same rounding: bit-identical up to f32 associativity (the
    # DCT contractions are reordered) — allow only the rounding boundary
    assert np.max(np.abs(got - want)) <= 1.0
    assert (got == want).mean() > 0.999


def test_pallas_flat_plane_dc_only():
    from selkies_tpu.ops.pallas_dct import dct8_quant_zigzag
    from selkies_tpu.ops.quant import quality_scaled_tables

    plane = np.full((16, 128), 200, np.float32)
    ly, _ = quality_scaled_tables(50)
    row_recip = np.stack([1.0 / ly] * 2).astype(np.float32)
    out = np.asarray(dct8_quant_zigzag(plane, row_recip, interpret=True))
    assert np.all(out[:, :, 1:] == 0)       # flat block: DC only
    assert np.all(out[:, :, 0] == out[0, 0, 0])
