"""Supervision, degradation-ladder, and fault-injection tests (ISSUE 2).

The end-to-end tests drive ``DataStreamingServer.ws_handler`` with
in-process fake websockets: the server's fan-out path duck-types on
``send_nowait`` (data_server._ws_broadcast), so the full
capture → encode → transport pipeline — supervisor restarts, watchdog,
ladder transitions, health broadcasts — runs without the ``websockets``
package or any network, and faults are injected deterministically through
``server.faults`` at the real call sites.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from selkies_tpu.encoder.jpeg import StripeOutput
from selkies_tpu.observability.metrics import HAVE_PROM, Metrics
from selkies_tpu.protocol import VideoStripe, unpack_binary
from selkies_tpu.robustness import (FAILED, DegradationLadder, EncoderFault,
                                    FaultInjected, FaultInjector,
                                    InProcessClient, Supervisor)
from selkies_tpu.server.app import StreamingApp
from selkies_tpu.server.data_server import DataStreamingServer, DisplayState
from selkies_tpu.settings import Settings


@pytest.fixture
def anyio_backend():
    return "asyncio"


# ---------------------------------------------------------------------------
# in-process fakes


#: the canonical in-process websocket stand-in lives with the robustness
#: package so the chaos harness and these tests share one surface
FakeWs = InProcessClient


class FakeEncoder:
    """Pipelined-encoder lookalike; records the overrides it was built
    with so rung switches are observable."""

    def __init__(self, overrides=None):
        ov = overrides or {}
        self.entropy = ov.get("tpu_entropy", "device")
        self.profile = ov.get("encoder", "")
        self.submitted = 0
        self.closed = False
        self._ready = []

    def submit(self, frame):
        self.submitted += 1
        self._ready.append(
            (self.submitted,
             [StripeOutput(y_start=0, height=64,
                           jpeg=b"\xff\xd8FAKE%d" % self.submitted
                           + b"\xff\xd9",
                           is_paintover=False)]))

    def poll(self):
        out, self._ready = self._ready, []
        return out

    def flush(self):
        return self.poll()

    def close(self):
        self.closed = True


class FakeSource:
    def __init__(self, width, height, fps):
        self.width, self.height, self.fps = width, height, fps

    def start(self):
        pass

    def stop(self):
        pass

    def next_frame(self):
        return np.zeros((self.height, self.width, 3), np.uint8)


def make_server(**settings_env):
    env = {"SELKIES_PORT": "0", "SELKIES_AUDIO_ENABLED": "false"}
    env.update(settings_env)
    settings = Settings(argv=[], env=env)
    app = StreamingApp(settings)
    encoders = []

    def encoder_factory(w, h, s, overrides=None):
        enc = FakeEncoder(overrides)
        encoders.append(enc)
        return enc

    server = DataStreamingServer(
        settings, app=app,
        encoder_factory=encoder_factory,
        source_factory=lambda w, h, fps, **kw: FakeSource(w, h, fps),
        host="127.0.0.1",
    )
    app.data_server = server
    return server, encoders


async def wait_until(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return False


async def open_client(server, settings_body=None):
    ws = FakeWs()
    task = asyncio.create_task(server.ws_handler(ws))
    assert await wait_until(lambda: len(ws.sent) >= 2, timeout=5.0)
    assert ws.sent[0] == "MODE websockets"
    if settings_body is not None:
        ws.feed("SETTINGS," + json.dumps(settings_body))
    return ws, task


async def close_client(ws, task):
    await ws.close()
    try:
        await asyncio.wait_for(task, 5.0)
    except asyncio.TimeoutError:
        task.cancel()


# ---------------------------------------------------------------------------
# fault injector


def test_fault_injector_grammar():
    f = FaultInjector("capture.raise*2,fetch.hang=1.5,ws.drop")
    assert set(f.armed) == {"capture.raise", "fetch.hang", "ws.drop"}
    # counts decrement and the point disarms at zero
    assert f.should_fire("capture.raise")
    assert f.should_fire("capture.raise")
    assert not f.should_fire("capture.raise")
    assert f.fired["capture.raise"] == 2
    # an unarmed point is free
    assert not f.should_fire("encode.raise")
    with pytest.raises(FaultInjected):
        f.arm("ws.drop")
        f.maybe_raise("ws.drop")
    with pytest.raises(ValueError):
        f.arm("no.such.point")
    with pytest.raises(ValueError):
        FaultInjector("what even is this*")
    f.reset()
    assert f.armed == () and f.fired == {}


@pytest.mark.anyio
async def test_fault_injector_hang_is_cancellable():
    f = FaultInjector("capture.stall=30")
    t = asyncio.ensure_future(f.maybe_hang("capture.stall"))
    await asyncio.sleep(0.05)
    assert not t.done()          # hanging
    t.cancel()
    with pytest.raises(asyncio.CancelledError):
        await t
    # disarmed after firing once
    await asyncio.wait_for(f.maybe_hang("capture.stall"), 1.0)


# ---------------------------------------------------------------------------
# supervisor


@pytest.mark.anyio
async def test_supervisor_restarts_crashing_task_then_runs():
    crashes = []
    ran = asyncio.Event()

    async def child():
        if len(crashes) < 2:
            crashes.append(1)
            raise RuntimeError("boom")
        ran.set()
        await asyncio.sleep(3600)

    events = []
    sup = Supervisor("t", child, base_delay_s=0.01, max_delay_s=0.05,
                     max_restarts=5,
                     on_event=lambda k, i: events.append(k))
    task = asyncio.create_task(sup.run())
    await asyncio.wait_for(ran.wait(), 5.0)
    assert sup.failures_total == 2
    assert sup.restarts_total >= 2
    assert sup.state == "running"
    assert events.count("failure") == 2
    task.cancel()
    await asyncio.gather(task, return_exceptions=True)
    assert sup.state == "stopped"


@pytest.mark.anyio
async def test_supervisor_budget_exhaustion_is_terminal():
    async def child():
        raise RuntimeError("always")

    events = []
    sup = Supervisor("t", child, base_delay_s=0.005, max_delay_s=0.01,
                     max_restarts=3, restart_window_s=30.0,
                     on_event=lambda k, i: events.append(k))
    await asyncio.wait_for(sup.run(), 10.0)   # returns (terminal), no raise
    assert sup.state == FAILED
    assert sup.failures_total == 4            # budget 3 + the final straw
    assert "failed" in events


@pytest.mark.anyio
async def test_supervisor_watchdog_restarts_stalled_child():
    recovered = asyncio.Event()
    runs = []

    async def child():
        runs.append(1)
        if len(runs) == 1:
            await asyncio.sleep(3600)   # stalls without ever beating
        while True:
            sup.beat()
            recovered.set()
            await asyncio.sleep(0.01)

    sup = Supervisor("t", child, base_delay_s=0.01,
                     watchdog_timeout_s=0.2, max_restarts=5)
    task = asyncio.create_task(sup.run())
    await asyncio.wait_for(recovered.wait(), 5.0)
    assert sup.watchdog_restarts_total == 1
    assert sup.failures_total == 0
    task.cancel()
    await asyncio.gather(task, return_exceptions=True)


# ---------------------------------------------------------------------------
# degradation ladder


def test_ladder_steps_down_and_probes_up():
    now = [0.0]
    ladder = DegradationLadder(fail_threshold=2, probe_after_s=5.0,
                               clock=lambda: now[0])
    assert ladder.rung == "device"
    assert not ladder.record_failure()
    assert ladder.record_failure()             # 2 consecutive -> step down
    assert ladder.rung == "host"
    # success resets the consecutive count; no probe before the window
    assert not ladder.record_success()
    now[0] = 3.0
    ladder.record_failure()                    # 1 of 2: no step
    assert ladder.rung == "host"
    ladder.record_failure()
    assert ladder.rung == "jpeg"               # bottom rung
    ladder.record_failure()
    ladder.record_failure()
    assert ladder.rung == "jpeg"               # clamped
    now[0] = 10.0
    assert ladder.record_success()             # clean probe window -> up
    assert ladder.rung == "host"
    now[0] = 16.0
    assert ladder.record_success()
    assert ladder.rung == "device"
    assert ladder.transitions == [
        "device->host", "host->jpeg", "jpeg->host", "host->device"]
    assert ladder.failures_total == 6
    # single-shot overwhelming evidence (wedge) bypasses the threshold
    assert ladder.force_step_down()
    assert ladder.rung == "host"
    ladder.force_step_down()
    assert not ladder.force_step_down()        # bottom rung: no step
    assert ladder.rung == "jpeg"


def test_backoff_delay_formula():
    from selkies_tpu.robustness import backoff_delay

    assert backoff_delay(1, 0.5, 10.0) == 0.5
    assert backoff_delay(3, 0.5, 10.0) == 2.0
    assert backoff_delay(50, 0.5, 10.0) == 10.0          # capped, no overflow
    d = backoff_delay(1, 1.0, 10.0, jitter=0.5)
    assert 1.0 <= d <= 1.5


# ---------------------------------------------------------------------------
# bind backoff (satellite: run_server retry policy)


@pytest.mark.anyio
async def test_run_server_bind_backoff_gives_up(monkeypatch):
    import sys
    import types

    calls = []

    def serve(*a, **k):
        calls.append(1)
        raise OSError(98, "address in use")

    ws = types.ModuleType("websockets")
    ws_asyncio = types.ModuleType("websockets.asyncio")
    ws_server = types.ModuleType("websockets.asyncio.server")
    ws_server.serve = serve
    ws.asyncio = ws_asyncio
    ws_asyncio.server = ws_server
    monkeypatch.setitem(sys.modules, "websockets", ws)
    monkeypatch.setitem(sys.modules, "websockets.asyncio", ws_asyncio)
    monkeypatch.setitem(sys.modules, "websockets.asyncio.server", ws_server)

    server, _ = make_server()
    server.BIND_MAX_ATTEMPTS = 3
    server.BIND_BASE_DELAY_S = 0.01
    server.BIND_MAX_DELAY_S = 0.02
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="could not bind"):
        await asyncio.wait_for(server.run_server(), 10.0)
    assert len(calls) == 3
    assert time.monotonic() - t0 < 5.0         # capped, not 1s-per-retry


# ---------------------------------------------------------------------------
# encoder adapter accounting (satellite: _harvest counts, not just logs)


def test_threaded_adapter_counts_errors_and_drops():
    import threading

    from selkies_tpu.encoder.pipeline import ThreadedEncoderAdapter

    gate = threading.Event()

    class FlakyBase:
        def __init__(self):
            self.calls = 0

        def encode_frame(self, frame):
            gate.wait(5.0)
            self.calls += 1
            if self.calls % 2:
                raise RuntimeError("entropy exploded")
            return ["stripe"]

    seen_errors = []
    adapter = ThreadedEncoderAdapter(FlakyBase(), depth=2)
    adapter.on_error = seen_errors.append
    frame = np.zeros((16, 16, 3), np.uint8)
    assert adapter.try_submit(frame) is not None
    assert adapter.try_submit(frame) is not None
    assert adapter.try_submit(frame) is None    # full -> counted drop
    assert adapter.frames_dropped_total == 1
    gate.set()
    deadline = time.monotonic() + 5.0
    got = []
    while time.monotonic() < deadline and len(got) < 1:
        got.extend(adapter.poll())
        time.sleep(0.01)
    assert adapter.encode_errors_total == 1
    assert len(seen_errors) == 1
    st = adapter.stats()
    assert st["encode_errors"] == 1
    assert st["frames_dropped"] == 1
    assert st["frames"] == 1
    # the flush drain counts errors identically to poll (no silent path)
    assert adapter.submit(frame) is not None   # call 3: raises
    assert adapter.submit(frame) is not None   # call 4: ok
    flushed = adapter.flush()
    assert adapter.encode_errors_total == 2
    assert len(seen_errors) == 2
    assert len(flushed) == 1
    adapter.close()


# ---------------------------------------------------------------------------
# teardown safety (satellite: _stop_display_locked exception-safe)


@pytest.mark.anyio
async def test_stop_display_teardown_is_exception_safe():
    server, _ = make_server()
    st = DisplayState(display_id="primary")

    async def bad_cleanup():
        try:
            await asyncio.sleep(3600)
        except asyncio.CancelledError:
            raise RuntimeError("cleanup raised instead of cancelling")

    async def good_loop():
        await asyncio.sleep(3600)

    closed = []

    class Enc:
        def close(self):
            closed.append(True)
            raise RuntimeError("close also raised")

    st.capture_task = asyncio.create_task(bad_cleanup())
    st.backpressure_task = asyncio.create_task(good_loop())
    st.encoder = Enc()
    await asyncio.sleep(0.05)
    await asyncio.wait_for(server._stop_display(st), 5.0)
    # the first task's RuntimeError did not abort the teardown
    assert st.capture_task is None
    assert st.backpressure_task is None
    assert st.encoder is None
    assert closed == [True]


# ---------------------------------------------------------------------------
# mesh coordinator per-shard accounting


def test_mesh_tick_failure_attributes_slots_and_unblocks_flush():
    """A failed lane dispatch charges the slots that were in that tick
    and releases their in-flight holds — a stranded hold would block
    facade.flush for its full timeout (ISSUE 14: failures are contained
    to the lane; the worker thread never sees them)."""
    from selkies_tpu.parallel.coordinator import MeshEncodeCoordinator

    class BadEnc:
        n_sessions = 2

        def reset_session(self, s):
            pass

        def force_keyframe(self, s):
            pass

        def dispatch(self, frames):
            raise RuntimeError("device gone")

    coord = MeshEncodeCoordinator(
        "session:1", 2, 64, 48, enc_factory=lambda n: BadEnc(),
        slots_per_lane=2, max_lanes=1, framerate=60.0,
        health_sick_errors=100)
    coord.stop()                    # drive the tick by hand
    fa = coord.acquire(64, 48)
    fb = coord.acquire(64, 48)
    coord.stop()
    fa.try_submit("frame0")
    fb.try_submit("frame1")
    coord._tick()                   # contained: does NOT raise
    st_stats = coord.stats()
    assert st_stats["slot_errors"] == [1, 1]
    assert st_stats["tick_errors_total"] == 1
    # the holds were released: flush returns immediately, no wedge
    t0 = time.monotonic()
    assert fa.flush() == []
    assert time.monotonic() - t0 < 1.0
    assert coord.verify_slot_accounting() == []
    coord.stop()


# ---------------------------------------------------------------------------
# acceptance (a): capture-loop crash restarts; websocket session survives


@pytest.mark.anyio
async def test_capture_crash_restarts_without_killing_session():
    server, encoders = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="10",
        SELKIES_WATCHDOG_FRAMES="0",
    )
    server.faults.arm("capture.raise", times=2)
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        st_ok = await wait_until(
            lambda: "primary" in server.display_clients
            and server.display_clients["primary"].supervisor is not None
            and server.display_clients["primary"]
                .supervisor.failures_total >= 2)
        assert st_ok
        st = server.display_clients["primary"]
        # recovery: frames flow after the crashes, on the SAME websocket
        n0 = len(ws.binary())
        assert await wait_until(lambda: len(ws.binary()) > n0 + 2)
        assert not ws.closed
        assert st.supervisor.state in ("running", "backoff")
        assert st.supervisor.failures_total == 2
        assert len(encoders) >= 3               # one encoder per (re)start
        assert server.faults.fired["capture.raise"] == 2
        # frame ids were resynchronized on each restart
        first = unpack_binary(ws.binary()[0])
        assert isinstance(first, VideoStripe) and first.frame_id == 1
        assert any("PIPELINE_RESETTING" in t for t in ws.texts())
        # supervision events rode the system,health feed
        healths = [t for t in ws.texts()
                   if isinstance(t, str) and '"system_health"' in t]
        assert healths
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# acceptance (b): repeated device failures degrade to host, then recover


@pytest.mark.anyio
async def test_ladder_degrades_to_host_and_recovers_to_device():
    server, encoders = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="20",
        SELKIES_WATCHDOG_FRAMES="0",
        SELKIES_LADDER_FAIL_THRESHOLD="3",
        SELKIES_LADDER_PROBE_MS="300",
    )
    server.faults.arm("encode.raise", times=3)
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        st_ok = await wait_until(lambda: "primary" in server.display_clients)
        assert st_ok
        st = server.display_clients["primary"]
        # three injected device-entropy failures step the ladder down …
        assert await wait_until(
            lambda: any(e.entropy == "host" for e in encoders))
        host_at = next(i for i, e in enumerate(encoders)
                       if e.entropy == "host")
        assert "device->host" in st.ladder.transitions
        # … and a clean probe window steps it back up: a LATER encoder is
        # built at device entropy again
        assert await wait_until(
            lambda: any(e.entropy == "device"
                        for e in encoders[host_at + 1:]))
        assert "host->device" in st.ladder.transitions
        assert st.ladder.rung == "device"
        assert st.ladder.failures_total == 3
        # the rung transitions were visible on the wire
        rungs = []
        for t in ws.texts():
            if '"system_health"' in t:
                payload = json.loads(t)
                rungs.append(payload["displays"]["primary"]["rung"])
        assert "host" in rungs and "device" in rungs
        # and frames flow again at the recovered rung
        n0 = len(ws.binary())
        assert await wait_until(lambda: len(ws.binary()) > n0 + 2)
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# acceptance (c): a stalled fetch trips the watchdog


@pytest.mark.anyio
async def test_stalled_fetch_trips_watchdog():
    server, encoders = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="10",
        SELKIES_WATCHDOG_FRAMES="30",     # 30/60fps -> 0.5s deadline
    )
    if HAVE_PROM:
        server.metrics = Metrics(port=0)
    server.faults.arm("fetch.hang", times=1)
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(
            lambda: "primary" in server.display_clients
            and server.display_clients["primary"].supervisor is not None
            and server.display_clients["primary"]
                .supervisor.watchdog_restarts_total >= 1,
            timeout=15.0)
        st = server.display_clients["primary"]
        assert st.supervisor.failures_total == 0   # a stall, not a crash
        # the restarted pipeline streams again
        n0 = len(ws.binary())
        assert await wait_until(lambda: len(ws.binary()) > n0 + 2)
        if HAVE_PROM:
            text = server.metrics.render().decode()
            assert "watchdog_restarts_total 1.0" in text
        # watchdog restarts ride the health feed too
        assert any('"watchdog_restarts": 1' in t or
                   '"watchdog_restarts": 2' in t for t in ws.texts())
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# satellite: reconnect/resync path


@pytest.mark.anyio
async def test_reconnect_resyncs_frame_ids_with_keyframe():
    server, encoders = make_server()
    ws1, task1 = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(lambda: len(ws1.binary()) >= 3)
        ids = [unpack_binary(m).frame_id for m in ws1.binary()[:3]]
        assert ids == [1, 2, 3]
        n_enc = len(encoders)
        # disconnect mid-stream: the handler tears the display down
        await close_client(ws1, task1)
        assert await wait_until(
            lambda: "primary" not in server.display_clients)

        # reconnect: new handshake, new SETTINGS
        ws2, task2 = await open_client(server, {
            "displayId": "primary", "initialClientWidth": 320,
            "initialClientHeight": 240, "framerate": 60})
        try:
            assert await wait_until(lambda: len(ws2.binary()) >= 1)
            # PIPELINE_RESETTING preceded the media
            reset_i = next(i for i, m in enumerate(ws2.sent)
                           if isinstance(m, str)
                           and m.startswith("PIPELINE_RESETTING"))
            frame_i = next(i for i, m in enumerate(ws2.sent)
                           if isinstance(m, (bytes, bytearray)))
            assert reset_i < frame_i
            # frame ids restarted at 1 (ACK horizon reset), fresh encoder
            # means the first frame is a keyframe
            f = unpack_binary(ws2.binary()[0])
            assert f.frame_id == 1
            assert f.is_key
            assert len(encoders) > n_enc       # rebuilt, not reused
            st = server.display_clients["primary"]
            assert st.bp.last_sent_frame_id < 100
            assert st.bp.send_enabled
        finally:
            await close_client(ws2, task2)
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# ladder step-downs forgive the restart budget (degrading != dying)


@pytest.mark.anyio
async def test_ladder_stepdowns_do_not_exhaust_restart_budget():
    """6 encoder faults with a budget of 3: each ladder step-down resets
    the budget, so the display walks device→host→jpeg instead of dying."""
    server, encoders = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="3",
        SELKIES_SUPERVISOR_RESTART_WINDOW_S="60",
        SELKIES_WATCHDOG_FRAMES="0",
        SELKIES_LADDER_FAIL_THRESHOLD="2",
        SELKIES_LADDER_PROBE_MS="600000",   # no probe-up during the test
    )
    server.faults.arm("encode.raise", times=6)
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]
        assert await wait_until(lambda: st.ladder.rung == "jpeg")
        assert st.ladder.transitions == ["device->host", "host->jpeg"]
        assert not st.failed
        assert st.supervisor is not None and st.supervisor.state != FAILED
        # the bottom-rung encoder streams (profile forced to jpeg)
        assert await wait_until(
            lambda: any(e.profile == "jpeg" and e.submitted > 0
                        for e in encoders))
        n0 = len(ws.binary())
        assert await wait_until(lambda: len(ws.binary()) > n0 + 2)
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# bottom rung + persistent off-loop errors: rebuild, then terminal failure
# (a display streaming nothing must never read as healthy forever)


@pytest.mark.anyio
async def test_bottom_rung_persistent_errors_walk_ladder_then_fail():
    settings = Settings(argv=[], env={
        "SELKIES_PORT": "0", "SELKIES_AUDIO_ENABLED": "false",
        "SELKIES_SUPERVISOR_MAX_RESTARTS": "2",
        "SELKIES_WATCHDOG_FRAMES": "0",
        "SELKIES_LADDER_FAIL_THRESHOLD": "2",
        "SELKIES_LADDER_PROBE_MS": "600000",
    })
    app = StreamingApp(settings)
    built = []

    class SickEncoder:
        """Every harvested frame errors (reported via on_error, like the
        threaded adapter) and nothing is ever delivered."""

        def __init__(self):
            self.on_error = None

        def try_submit(self, frame):
            return 1

        def poll(self):
            if self.on_error is not None:
                self.on_error(RuntimeError("sick"))
            return []

        def flush(self):
            return []

        def close(self):
            pass

    def factory(w, h, s, overrides=None):
        built.append(dict(overrides or {}))
        return SickEncoder()

    server = DataStreamingServer(
        settings, app=app, encoder_factory=factory,
        source_factory=lambda w, h, fps, **kw: FakeSource(w, h, fps),
        host="127.0.0.1")
    app.data_server = server
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(lambda: "primary" in server.display_clients)
        st = server.display_clients["primary"]
        # off-loop errors walk the whole ladder down …
        assert await wait_until(lambda: st.ladder.rung == "jpeg")
        assert st.ladder.transitions[:2] == ["device->host", "host->jpeg"]
        assert await wait_until(
            lambda: any(o.get("tpu_entropy") == "host" for o in built))
        assert await wait_until(
            lambda: any(o.get("encoder") == "jpeg" for o in built))
        # … and at the bottom rung, persistent errors force supervised
        # rebuilds until the budget marks the display terminally failed
        # instead of streaming nothing forever with a "running" state
        assert await wait_until(lambda: st.failed, timeout=20.0)
        assert not ws.closed
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# terminal failure: budget exhaustion tears the display down, sticky marker


@pytest.mark.anyio
async def test_restart_budget_exhaustion_fails_display_and_tears_down():
    server, encoders = make_server(
        SELKIES_SUPERVISOR_MAX_RESTARTS="2",
        SELKIES_SUPERVISOR_RESTART_WINDOW_S="60",
        SELKIES_WATCHDOG_FRAMES="0",
    )
    server.faults.arm("capture.raise", times=50)   # crash every run
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(
            lambda: "primary" in server.display_clients
            and server.display_clients["primary"].failed)
        st = server.display_clients["primary"]
        # the sibling backpressure loop must not tick forever for a dead
        # pipeline — the failed event tears the whole display down
        assert await wait_until(lambda: st.capture_task is None
                                and st.backpressure_task is None)
        assert server._failed_displays() == 1
        assert not ws.closed       # the websocket session itself survives
        assert any('"failed": true' in t for t in ws.texts()
                   if '"system_health"' in t)
        # an explicit START_VIDEO clears the marker and recovers
        server.faults.disarm()
        ws.feed("START_VIDEO")
        assert await wait_until(
            lambda: not st.failed and st.capture_task is not None)
        n0 = len(ws.binary())
        assert await wait_until(lambda: len(ws.binary()) > n0)
        assert server._failed_displays() == 0
    finally:
        await close_client(ws, task)
        await server.stop()


# ---------------------------------------------------------------------------
# chaos (slow): random fault storm over the REAL encoder factory


@pytest.mark.slow
@pytest.mark.anyio
async def test_chaos_session_survives_fault_storm():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.chaos_run import chaos_session

    report = await chaos_session(duration_s=5.0, seed=1)
    assert report["alive"], report
    assert report["injected"], report
    assert report["failed_displays"] == 0
    assert (report["restarts"] + report["watchdog_restarts"]
            + report["reconnects"]) >= 1, report
    assert report["frames_delivered"] > 0


# ---------------------------------------------------------------------------
# ws.drop fault: client churn mid-stream leaves the server healthy


@pytest.mark.anyio
async def test_ws_drop_fault_closes_client_server_survives():
    server, encoders = make_server()
    server.faults.arm("ws.drop", times=1)
    ws, task = await open_client(server, {
        "displayId": "primary", "initialClientWidth": 320,
        "initialClientHeight": 240, "framerate": 60})
    try:
        assert await wait_until(lambda: ws.closed, timeout=10.0)
        await asyncio.wait_for(task, 5.0)       # handler exited cleanly
        assert await wait_until(
            lambda: "primary" not in server.display_clients)
        # a new client gets a fresh, working session
        ws2, task2 = await open_client(server, {
            "displayId": "primary", "initialClientWidth": 320,
            "initialClientHeight": 240, "framerate": 60})
        try:
            assert await wait_until(lambda: len(ws2.binary()) >= 2)
        finally:
            await close_client(ws2, task2)
    finally:
        await close_client(ws, task)
        await server.stop()
