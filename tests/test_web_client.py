"""Web client contract tests.

No JS runtime exists in this image, so the client is validated against
the wire-protocol contract structurally: the demux branches, verbs, and
frame layouts it implements must match selkies_tpu/protocol/wire.py, and
the server must actually serve it over HTTP."""

import asyncio
import os
import re
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WEB = os.path.join(ROOT, "web")


def read(name):
    with open(os.path.join(WEB, name)) as f:
        return f.read()


def test_client_implements_binary_demux():
    js = read("selkies-client.js")
    # all four server->client binary types are demuxed
    for t in ("0x00", "0x01", "0x03", "0x04"):
        assert re.search(rf"case {t}:", js), f"missing demux for {t}"
    # header offsets match wire.py: frame_id at 2, y_start at 4,
    # JPEG payload at 6, H.264 stripe payload at 10, full-frame at 4
    assert "subarray(6)" in js     # JPEG stripe payload
    assert "subarray(10)" in js    # H.264 stripe payload
    assert "subarray(4)" in js     # full-frame payload
    assert "subarray(2)" in js     # audio payload


def test_client_speaks_protocol_verbs():
    js = read("selkies-client.js")
    for verb in ("SETTINGS,", "CLIENT_FRAME_ACK", "PIPELINE_RESETTING",
                 "FILE_UPLOAD_START", "FILE_UPLOAD_END", "START_VIDEO",
                 "STOP_VIDEO", "cw,", "cr", "_f "):
        assert verb in js, f"client missing verb {verb!r}"
    # client->server binary framing: file chunk 0x01, mic 0x02
    assert "framed[0] = 0x01" in js
    assert "framed[0] = 0x02" in js


def test_input_speaks_protocol_verbs():
    js = read("input.js")
    for verb in ('"kd,"', '"ku,"', '"kr"', "js,c", "js,b", "js,a", "js,d"):
        assert verb.strip('"') in js.replace('"', ""), f"missing {verb}"
    assert "m2," in js and "m," in js
    # X11 unicode keysym rule
    assert "0x01000000" in js
    # keysym table sanity: essential keys present
    for key in ("Backspace: 0xff08", "Enter: 0xff0d", "Escape: 0xff1b",
                "Shift: 0xffe1", "F12: 0xffc9"):
        assert key in js


def test_index_wires_modules():
    html = read("index.html")
    assert "selkies-client.js" in html
    assert "input.js" in html
    assert "dashboard.js" in html
    # client/input construction moved into the dashboard layer
    dash = read("dashboard.js")
    assert "new SelkiesClient" in dash and "new SelkiesInput" in dash


def test_web_root_served_over_http():
    from selkies_tpu.rtc import SignalingServer

    async def run():
        server = SignalingServer(addr="127.0.0.1", port=0, web_root=WEB)
        task = asyncio.create_task(server.run())
        for _ in range(100):
            if server.server is not None:
                break
            await asyncio.sleep(0.01)

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}{path}") as r:
                return r.status, r.read(), r.headers.get("Content-Type")

        status, body, ctype = await asyncio.to_thread(get, "/")
        assert status == 200 and b"selkies-tpu" in body
        status, body, ctype = await asyncio.to_thread(get, "/selkies-client.js")
        assert status == 200 and b"SelkiesClient" in body
        assert "javascript" in ctype
        await server.stop()
        task.cancel()

    asyncio.run(run())


def test_gendb_parses_sdl_mappings(tmp_path):
    """gendb parity: SDL GUID vendor/product extraction + per-device JSON."""
    import json
    import subprocess
    import sys

    # xbox 360 pad GUID: bus 03, vendor 045e (LE: 5e04), product 028e (8e02)
    db = tmp_path / "db.txt"
    db.write_text(
        "# comment line\n"
        "030000005e0400008e02000014010000,X360 Controller,"
        "a:b0,b:b1,x:b2,y:b3,leftx:a0,lefty:a1,platform:Linux,\n"
        "030000005e0400008e02000014010000,Mac pad,a:b0,platform:Mac OS X,\n")
    out = tmp_path / "out"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "gendb.py"),
         str(db), str(out)], capture_output=True)
    assert r.returncode == 0, r.stderr.decode()
    written = list(out.iterdir())
    assert len(written) == 1
    entry = json.loads(written[0].read_text())
    assert entry["vendor"] == "045e" and entry["product"] == "028e"
    assert entry["mapping"]["a"] == "b0"
    assert entry["mapping"]["leftx"] == "a0"


def test_touch_gamepad_contract():
    js = read("touch-gamepad.js")
    assert "getGamepads" in js
    assert "gamepadconnected" in js and "gamepaddisconnected" in js
    assert '"standard"' in js     # mapping: standard-gamepad layout


# ------------------------------------------------------------ syntax lint
# No JS runtime ships in this image (no node/bun/quickjs, no browser), so
# the client is EXECUTED by tools/minijs.py instead — see
# tests/test_web_client_exec.py for the behavioral coverage (demux, ACK
# wraparound, decoder pools, input mapping, dashboard). tools/jscheck.py
# remains as a fast whole-file lint gate alongside it.

import pathlib
import sys

REPO = pathlib.Path(ROOT)
sys.path.insert(0, str(REPO / "tools"))


def _jscheck(src: str):
    import jscheck

    return jscheck.check(src)


def test_jscheck_accepts_all_bundled_js():
    for path in sorted((REPO / "web").glob("*.js")):
        toks = _jscheck(path.read_text())
        assert len(toks) > 100, path


def test_jscheck_catches_broken_js():
    import jscheck
    import pytest as _pytest

    good = 'const x = { a: [1, 2], b: "s" }; f(`t ${x.a[0]} u`);'
    jscheck.check(good)
    for bad in (
        'function f() { return 1; ',          # unclosed brace
        'const s = "oops;',                   # unterminated string
        'const t = `tpl ${1};',               # unterminated template
        'if (a) { g(] }',                     # mismatched bracket
        '/* never closed',                    # unterminated comment
        'const r = /abc;',                    # unterminated regex
    ):
        with _pytest.raises(jscheck.JsSyntaxError):
            jscheck.check(bad)


def test_jscheck_regex_vs_division():
    import jscheck

    toks = jscheck.check('const a = b / c / d; const r = /x[/]y/g;')
    kinds = [k for k, _, _ in toks]
    assert "regex" in kinds
    assert kinds.count("regex") == 1


# ------------------------------------------------------- dashboard contract


def test_dashboard_is_schema_driven():
    src = (REPO / "web" / "dashboard.js").read_text()
    # settings widgets render from the server_settings push, not a
    # hardcoded list: bool/range/enum shapes all handled, locked honored
    assert "onServerSettings" in src
    assert "entry.locked" in src
    assert '"min" in entry' in src and '"max" in entry' in src
    assert "entry.allowed" in src
    # changes round-trip to the server via a SETTINGS re-send
    assert '"SETTINGS," + JSON.stringify' in src
    assert "localStorage" in src


def test_dashboard_covers_reference_sidebar_surface():
    src = (REPO / "web" / "dashboard.js").read_text()
    for needle in (
        "_renderSharing",          # sharing links per enable_* flag
        "#shared", "#player",
        "_toggleFilesModal",       # download modal → ./files/
        "./files/",
        "uploadFile",
        "cmd,",                    # apps launcher (command_enabled gated)
        "_drawGamepads",           # gamepad visualizer
        "getGamepads",
        "requestFullscreen",
        "requestPointerLock",
        "startMicrophone",
        "ui_sidebar_show_stats",   # server-driven UI gating
        "ui_title",
    ):
        assert needle in src, needle


def test_index_html_wires_dashboard():
    src = (REPO / "web" / "index.html").read_text()
    assert "dashboard.js" in src
    assert "SelkiesDashboard" in src


def test_input_ime_and_trackpad_surface():
    js = read("input.js")
    # composition/IME: hidden proxy, composition events → atomic typing
    for needle in ("compositionstart", "compositionend", "co,end,",
                   "isComposing", '"Dead"', "popKeyboard",
                   "toggleTrackpadMode", "_touchTrackpad",
                   "deleteContentBackward"):
        assert needle in js, needle
    # keypad + media keysyms present
    for needle in ("NumpadEnter: 0xff8d", "AudioVolumeUp: 0x1008ff13",
                   "Convert: 0xff21"):
        assert needle in js, needle


def test_input_js_lints():
    _jscheck(read("input.js"))


def test_client_audio_worklet_ring():
    js = read("selkies-client.js")
    for needle in ("AudioWorkletProcessor", "registerProcessor",
                   "selkies-ring", "audioWorklet.addModule",
                   "AudioWorkletNode", "this.jitter"):
        assert needle in js, needle
    # jitter floor + underrun rebuffering, not per-chunk scheduling only
    assert "underrun" in js
    assert "createBufferSource" in js      # fallback retained
