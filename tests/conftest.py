"""Test configuration: force a clean JAX CPU backend with 8 virtual devices.

All tests run on CPU (the real chip is reserved for bench.py); multi-chip
sharding tests use the 8 virtual devices as a simulated mesh, per the test
strategy in SURVEY.md §4.

Why the re-exec: the ambient environment injects a TPU PJRT plugin into
every interpreter via sitecustomize (PYTHONPATH=/root/.axon_site) gated on
``PALLAS_AXON_POOL_IPS``, and that registration can block every JAX backend
init — including CPU — when the device tunnel is wedged. By the time this
conftest runs, sitecustomize has already executed, so scrubbing the
environment and re-exec'ing pytest is the only reliable isolation.
"""

import os
import sys

_SCRUB = ("PALLAS_AXON_POOL_IPS",)

if any(v in os.environ for v in _SCRUB):
    env = dict(os.environ)
    for v in _SCRUB:
        env.pop(v, None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
