"""Test configuration: force the JAX CPU backend with 8 virtual devices.

All tests run on CPU (the real chip is reserved for bench.py); multi-chip
sharding tests use the 8 virtual devices as a simulated mesh, per the test
strategy in SURVEY.md §4.
"""

import os

# Hard override: the ambient environment may pin JAX_PLATFORMS to the TPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
