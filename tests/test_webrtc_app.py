"""WebRTC streaming-mode integration: app ↔ signaling server ↔ fake
browser peer, full media + input over the in-repo stack on loopback UDP.

Parity target: the reference's legacy session flow
(webrtc.py on_session → gstwebrtc_app start_pipeline → webrtcbin offer →
browser answer → media + "input" data channel)."""

import asyncio

import numpy as np
import pytest

from selkies_tpu.rtc import SignalingServer, SignalingClient
from selkies_tpu.server.webrtc_app import WebRTCStreamingApp, bitrate_to_qp
from selkies_tpu.webrtc.peerconnection import PeerConnection


class FakeEncoder:
    """Stands in for the TPU H.264 encoder (jit-free for CPU CI)."""

    def __init__(self):
        self.qp = 26
        self.keyframes_requested = 0
        self._n = 0

    def encode_frame(self, rgb):
        self._n += 1
        class S:
            pass
        s = S()
        s.annexb = (b"\x00\x00\x00\x01\x67\x42\x00\x28"
                    b"\x00\x00\x00\x01\x65" + bytes([self._n & 0xFF]) * 500)
        s.is_key = True
        return [s]

    def request_keyframe(self):
        self.keyframes_requested += 1


class FakeSource:
    def __init__(self, w, h, fps):
        self.w, self.h = w, h

    def next_frame(self):
        return np.zeros((self.h, self.w, 3), np.uint8)


class RecordingInput:
    def __init__(self):
        self.messages = []

    def on_message(self, msg):
        self.messages.append(msg)


class Settings:
    initial_width = 320
    initial_height = 240
    framerate = 30


def test_bitrate_to_qp_monotone():
    assert bitrate_to_qp(8_000_000) == 26
    assert bitrate_to_qp(2_000_000) > bitrate_to_qp(8_000_000)
    assert bitrate_to_qp(64_000_000) < bitrate_to_qp(8_000_000)
    assert 18 <= bitrate_to_qp(100) <= 46
    assert bitrate_to_qp(0) == 46


def test_webrtc_app_full_session():
    async def run():
        # 1. signaling server
        server = SignalingServer(addr="127.0.0.1", port=0)
        stask = asyncio.create_task(server.run())
        for _ in range(100):
            if server.server is not None:
                break
            await asyncio.sleep(0.01)
        uri = f"ws://127.0.0.1:{server.port}/ws"

        # 2. fake browser: registers as peer "1", answers the offer
        browser_pc = PeerConnection(interfaces=["127.0.0.1"])
        got_frames = []
        browser_pc.video_receiver().on_frame = \
            lambda f, ts: got_frames.append(f)
        opened = {}

        def on_channel(ch):
            opened["ch"] = ch
        browser_pc.on_channel = on_channel

        browser_sig = SignalingClient(uri, "1")

        async def browser_on_sdp(sdp_type, sdp):
            assert sdp_type == "offer"
            await browser_pc.set_remote_description(sdp, "offer")
            answer = await browser_pc.create_answer()
            await browser_sig.send_sdp("answer", answer)
        browser_sig.on_sdp = browser_on_sdp
        await browser_sig.connect()
        btask = asyncio.create_task(browser_sig.start())

        # 3. streaming app: registers as "0", calls peer "1"
        recorder = RecordingInput()
        app = WebRTCStreamingApp(
            Settings(),
            encoder_factory=lambda w, h: FakeEncoder(),
            source_factory=lambda w, h, fps: FakeSource(w, h, fps),
            input_handler=recorder,
            interfaces=["127.0.0.1"])
        atask = asyncio.create_task(app.run(uri, "0", "1"))

        # 4. media flows
        for _ in range(300):
            if len(got_frames) >= 3:
                break
            await asyncio.sleep(0.05)
        assert len(got_frames) >= 3, "no video frames arrived"
        assert got_frames[0].startswith(b"\x00\x00\x00\x01\x67")

        # 5. input channel: browser → app
        for _ in range(200):
            if "ch" in opened and opened["ch"].open:
                break
            await asyncio.sleep(0.05)
        assert "ch" in opened
        browser_pc.sctp.send(opened["ch"], "kd,65")
        browser_pc.sctp.send(opened["ch"], "m,10,20,0,0")
        for _ in range(100):
            if len(recorder.messages) >= 2:
                break
            await asyncio.sleep(0.05)
        assert recorder.messages == ["kd,65", "m,10,20,0,0"]

        # 6. congestion feedback adjusts QP
        app.set_video_bitrate(1_000_000)
        assert app.encoder.qp == bitrate_to_qp(1_000_000)

        await app.stop_pipeline()
        await browser_pc.close()
        await browser_sig.stop()
        await server.stop()
        for t in (stask, btask, atask):
            t.cancel()

    asyncio.run(run())


def test_app_constructs_with_real_settings():
    """Regression: the production entry passes the REAL Settings (where
    framerate is a RangeValue); construction must not raise — the local
    Settings stub above masked a float(RangeValue) crash that broke
    selkies-tpu-webrtc at startup."""
    from selkies_tpu.settings import Settings as RealSettings

    app = WebRTCStreamingApp(RealSettings(argv=[], env={}),
                             input_handler=RecordingInput(),
                             interfaces=["127.0.0.1"])
    assert app.framerate == 60.0
