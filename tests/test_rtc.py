"""TURN credential/RTC-config and signaling server/client tests.

Covers the behavior of the reference's legacy/signalling_web.py,
legacy/webrtc.py RTC-config plumbing, and addons/turn-rest/app.py
(see SURVEY.md §2.3/§2.6)."""

import asyncio
import base64
import os
import hashlib
import hmac as hmac_mod
import json
import urllib.request

import pytest

from selkies_tpu.rtc import (
    HMACRTCMonitor,
    RTCConfigFileMonitor,
    SignalingClient,
    SignalingServer,
    build_rtc_config,
    generate_rtc_config,
    hmac_credentials,
    parse_rtc_config,
)
from selkies_tpu.rtc.turn_rest import TurnRestService


# ------------------------------------------------------------------ TURN


def test_hmac_credentials_verify():
    creds = hmac_credentials("s3cret", "alice", ttl_seconds=3600, now=1_000_000)
    exp, user = creds.username.split(":")
    assert user == "alice"
    assert int(exp) == 1_000_000 + 3600
    expect = base64.b64encode(
        hmac_mod.new(b"s3cret", creds.username.encode(), hashlib.sha1).digest()
    ).decode()
    assert creds.password == expect


def test_hmac_credentials_sanitizes_colons():
    creds = hmac_credentials("s", "a:b:c", now=0)
    assert creds.username.split(":", 1)[1] == "a-b-c"


def test_rtc_config_roundtrip():
    cfg = generate_rtc_config("turn.example.com", 3478, "secret", "bob",
                              protocol="tcp", turn_tls=True,
                              stun_host="stun.example.com", stun_port=3479)
    stun, turn, raw = parse_rtc_config(cfg)
    assert "stun://stun.example.com:3479" in stun
    assert "stun://turn.example.com:3478" in stun
    assert len(turn) == 1 and turn[0].startswith("turns://")
    assert "@turn.example.com:3478" in turn[0]
    parsed = json.loads(raw)
    assert parsed["iceServers"][1]["urls"][0].endswith("?transport=tcp")


def test_parse_rtc_config_escapes_special_chars():
    creds = hmac_credentials("k", "u", now=0)
    cfg = json.loads(build_rtc_config("h", 1, creds))
    cfg["iceServers"][1]["credential"] = "p/w+x="
    _, turn, _ = parse_rtc_config(json.dumps(cfg))
    assert "p%2Fw%2Bx%3D" in turn[0]


# ------------------------------------------------------------------ monitors


def test_hmac_monitor_fires_immediately():
    async def run():
        mon = HMACRTCMonitor("turn.local", 3478, "sec", "user", period=60.0)
        got = []

        def cb(stun, turn, cfg):
            got.append((stun, turn))

        mon.on_rtc_config = cb
        task = asyncio.create_task(mon.start())
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.01)
        await mon.stop()
        await asyncio.wait_for(task, 2)
        assert got and got[0][1], "monitor should emit a TURN uri on start"

    asyncio.run(run())


def test_file_monitor_detects_change(tmp_path):
    async def run():
        path = tmp_path / "rtc.json"
        path.write_text(generate_rtc_config("h1", 1, "s", "u"))
        mon = RTCConfigFileMonitor(str(path), poll_interval=0.02)
        seen = []
        mon.on_rtc_config = lambda st, tu, cfg: seen.append(tu[0])
        task = asyncio.create_task(mon.start())
        for _ in range(100):
            if seen:
                break
            await asyncio.sleep(0.01)
        assert seen, "should fire on start"
        import os
        path.write_text(generate_rtc_config("h2", 2, "s", "u"))
        os.utime(path, (1e9, 1e9))  # force distinct mtime
        for _ in range(200):
            if len(seen) > 1:
                break
            await asyncio.sleep(0.01)
        await mon.stop()
        await asyncio.wait_for(task, 2)
        assert len(seen) >= 2 and "h2" in seen[-1]

    asyncio.run(run())


# ------------------------------------------------------------------ turn-rest


def test_turn_rest_service():
    async def run():
        svc = TurnRestService(shared_secret="tops3cret", turn_host="relay.example",
                              turn_port="3478")
        runner = await svc.start("127.0.0.1", 0)
        port = runner.addresses[0][1]
        try:
            def fetch():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/",
                    headers={"x-auth-user": "Carol", "x-turn-protocol": "tcp"})
                with urllib.request.urlopen(req) as resp:
                    return json.loads(resp.read())
            cfg = await asyncio.to_thread(fetch)
        finally:
            await runner.cleanup()
        turn_entry = cfg["iceServers"][1]
        assert turn_entry["urls"][0] == "turn:relay.example:3478?transport=tcp"
        exp, user = turn_entry["username"].split(":")
        assert user == "carol"
        expect = base64.b64encode(
            hmac_mod.new(b"tops3cret", turn_entry["username"].encode(),
                         hashlib.sha1).digest()).decode()
        assert turn_entry["credential"] == expect

    asyncio.run(run())


# ------------------------------------------------------------------ signaling


def _start_server(**kwargs):
    server = SignalingServer(addr="127.0.0.1", port=0, **kwargs)
    task = asyncio.create_task(server.run())
    return server, task


async def _wait_port(server):
    for _ in range(200):
        if server.server is not None and server.port:
            return server.port
        await asyncio.sleep(0.01)
    raise TimeoutError("server did not start")


def test_signaling_session_relay():
    async def run():
        server, stask = _start_server()
        port = await _wait_port(server)
        uri = f"ws://127.0.0.1:{port}/ws"

        a = SignalingClient(uri, "1", peer_id="2")
        b = SignalingClient(uri, "2", meta={"res": "1920x1080"})
        got_sdp = asyncio.get_running_loop().create_future()
        got_session = asyncio.get_running_loop().create_future()

        b.on_sdp = lambda t, s: got_sdp.set_result((t, s))
        a.on_session = lambda pid, meta: got_session.set_result(meta)

        await b.connect()
        await a.connect()
        btask = asyncio.create_task(b.start())
        atask = asyncio.create_task(a.start())
        await a.setup_call()
        meta = await asyncio.wait_for(got_session, 5)
        assert meta == {"res": "1920x1080"}

        await a.send_sdp("offer", "v=0...")
        t, s = await asyncio.wait_for(got_sdp, 5)
        assert (t, s) == ("offer", "v=0...")

        await a.stop()
        await b.stop()
        await server.stop()
        for task in (stask, atask, btask):
            task.cancel()

    asyncio.run(run())


def test_signaling_rejects_duplicate_uid():
    async def run():
        server, stask = _start_server()
        port = await _wait_port(server)
        uri = f"ws://127.0.0.1:{port}/ws"
        a = SignalingClient(uri, "dup")
        await a.connect()
        import websockets.asyncio.client
        ws = await websockets.asyncio.client.connect(uri)
        await ws.send("HELLO dup")
        import websockets.exceptions
        with pytest.raises(websockets.exceptions.ConnectionClosed):
            for _ in range(10):
                await asyncio.wait_for(ws.recv(), 2)
        await a.stop()
        await server.stop()
        stask.cancel()

    asyncio.run(run())


def test_signaling_http_endpoints(tmp_path):
    (tmp_path / "index.html").write_text("<html>ok</html>")
    (tmp_path / "secret.txt").write_text("hidden")

    async def run():
        server, stask = _start_server(
            web_root=str(tmp_path), turn_shared_secret="zz", turn_host="t",
            turn_port="3478")
        port = await _wait_port(server)

        def get(path, headers=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers=headers or {})
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, r.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        status, body = await asyncio.to_thread(get, "/health")
        assert status == 200 and body == b"OK\n"
        status, body = await asyncio.to_thread(get, "/")
        assert status == 200 and b"<html>ok</html>" in body
        status, body = await asyncio.to_thread(get, "/../etc/passwd")
        assert status == 404
        status, body = await asyncio.to_thread(get, "/turn", {"x-auth-user": "u"})
        assert status == 200
        cfg = json.loads(body)
        assert cfg["iceServers"][1]["username"]
        await server.stop()
        stask.cancel()

    asyncio.run(run())


def test_signaling_basic_auth(tmp_path):
    (tmp_path / "index.html").write_text("x")

    async def run():
        server, stask = _start_server(
            web_root=str(tmp_path), enable_basic_auth=True,
            basic_auth_user="u", basic_auth_password="p")
        port = await _wait_port(server)

        def get(path, headers=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers=headers or {})
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        assert await asyncio.to_thread(get, "/") == 401
        auth = base64.b64encode(b"u:p").decode()
        assert await asyncio.to_thread(
            get, "/", {"Authorization": f"Basic {auth}"}) == 200
        await server.stop()
        stask.cancel()

    asyncio.run(run())


def test_signaling_rooms():
    async def run():
        server, stask = _start_server()
        port = await _wait_port(server)
        uri = f"ws://127.0.0.1:{port}/ws"
        import websockets.asyncio.client as wsc

        w1 = await wsc.connect(uri)
        await w1.send("HELLO r1")
        assert await w1.recv() == "HELLO"
        await w1.send("ROOM lobby")
        assert (await w1.recv()).startswith("ROOM_OK")

        w2 = await wsc.connect(uri)
        await w2.send("HELLO r2")
        assert await w2.recv() == "HELLO"
        await w2.send("ROOM lobby")
        ok = await w2.recv()
        assert "r1" in ok
        assert await w1.recv() == "ROOM_PEER_JOINED r2"

        await w2.send("ROOM_PEER_MSG r1 hello-there")
        assert await w1.recv() == "ROOM_PEER_MSG r2 hello-there"

        await w2.close()
        assert await w1.recv() == "ROOM_PEER_LEFT r2"
        await w1.close()
        await server.stop()
        stask.cancel()

    asyncio.run(run())


def test_files_download_plane(tmp_path):
    """The dashboard's "Download files" modal points at ./files/ — a
    directory listing + attachment serving from the file-manager root
    (reference: Sidebar.jsx files modal iframe; FILE_MANAGER_PATH)."""
    web = tmp_path / "web"
    web.mkdir()
    (web / "index.html").write_text("<html>ok</html>")
    froot = tmp_path / "managed"
    (froot / "sub").mkdir(parents=True)
    (froot / "report.txt").write_text("data!")
    (froot / "sub" / "inner.bin").write_bytes(b"\x00\x01\x02")

    async def run():
        server, stask = _start_server(
            web_root=str(web), files_root=str(froot))
        port = await _wait_port(server)

        def get(path):
            req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), e.read()

        status, _, body = await asyncio.to_thread(get, "/files/")
        assert status == 200
        assert b"report.txt" in body and b"sub/" in body

        status, hdrs, body = await asyncio.to_thread(get, "/files/report.txt")
        assert status == 200 and body == b"data!"
        assert "attachment" in hdrs.get("Content-Disposition", "")

        status, _, body = await asyncio.to_thread(get, "/files/sub/")
        assert status == 200 and b"inner.bin" in body
        status, _, body = await asyncio.to_thread(get, "/files/sub/inner.bin")
        assert status == 200 and body == b"\x00\x01\x02"

        status, _, _ = await asyncio.to_thread(get, "/files/../web/index.html")
        assert status == 404
        status, _, _ = await asyncio.to_thread(get, "/files/absent.txt")
        assert status == 404
        await server.stop()
        stask.cancel()

    asyncio.run(run())


def test_files_plane_hostile_names(tmp_path):
    """Hostile entry names must neither break the listing markup (XSS)
    nor inject headers; broken symlinks must not 500 the listing."""
    web = tmp_path / "web"
    web.mkdir()
    froot = tmp_path / "managed"
    (froot / '"><script>alert(1)<').mkdir(parents=True)
    (froot / "ok.txt").write_text("x")
    os.symlink(str(tmp_path / "gone"), str(froot / "dangling"))

    async def run():
        server, stask = _start_server(
            web_root=str(web), files_root=str(froot))
        port = await _wait_port(server)

        def get(path):
            req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
            try:
                with urllib.request.urlopen(req) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), e.read()

        status, _, body = await asyncio.to_thread(get, "/files/")
        assert status == 200
        assert b"<script>alert" not in body      # escaped, not raw
        assert b"ok.txt" in body

        # oversized file → 413 instead of pinning it all in memory
        big = froot / "big.bin"
        with open(big, "wb") as f:
            f.seek(SignalingServer.MAX_DOWNLOAD_BYTES)
            f.write(b"x")
        status, _, _ = await asyncio.to_thread(get, "/files/big.bin")
        assert status == 413
        await server.stop()
        stask.cancel()

    asyncio.run(run())
