"""Sharded multi-session encode vs. the single-frame encoder oracle."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from selkies_tpu.encoder.jpeg import _encode_body
from selkies_tpu.ops.quant import quality_scaled_tables
from selkies_tpu.parallel import BatchedSessionEncoder, make_mesh


STRIPE_H = 16
W, H = 32, 64  # 4 stripes
N_SESSIONS = 4


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(jax.devices()[:8])  # (4, 2)


def _quant_tables():
    ly, lc = quality_scaled_tables(40)
    py, pc = quality_scaled_tables(90)
    qy = jnp.stack([jnp.asarray(ly, jnp.float32), jnp.asarray(py, jnp.float32)])
    qc = jnp.stack([jnp.asarray(lc, jnp.float32), jnp.asarray(pc, jnp.float32)])
    return qy, qc


def test_mesh_shape(mesh):
    assert mesh.shape["session"] == 4
    assert mesh.shape["stripe"] == 2


def test_batched_matches_single_frame_oracle(mesh):
    rng = np.random.default_rng(7)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    qsel = np.zeros((N_SESSIONS, H // STRIPE_H), np.int32)
    qsel[1, 2] = 1  # one paint-over stripe to exercise per-stripe tables

    enc = BatchedSessionEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    yq, cbq, crq, damage, session_bits, total_bits = enc.step(frames, qsel)

    qy, qc = _quant_tables()
    body = functools.partial(_encode_body, stripe_h=STRIPE_H)
    for n in range(N_SESSIONS):
        ref = body(
            jnp.asarray(frames[n]), jnp.zeros((H, W, 3), jnp.uint8),
            qy, qc, jnp.asarray(qsel[n]))
        np.testing.assert_array_equal(np.asarray(yq)[n], np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(cbq)[n], np.asarray(ref[1]))
        np.testing.assert_array_equal(np.asarray(crq)[n], np.asarray(ref[2]))
        np.testing.assert_array_equal(np.asarray(damage)[n], np.asarray(ref[3]))
    assert int(total_bits) == int(np.asarray(session_bits).sum())


def test_prev_chain_damage_goes_quiet(mesh):
    rng = np.random.default_rng(3)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    enc = BatchedSessionEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    enc.step(frames)
    _, _, _, damage2, _, _ = enc.step(frames)  # identical frame → no damage
    assert int(np.asarray(damage2).max()) == 0


def test_geometry_validation(mesh):
    with pytest.raises(ValueError):
        BatchedSessionEncoder(mesh, 3, W, H, stripe_h=STRIPE_H)  # 3 % 4
    with pytest.raises(ValueError):
        BatchedSessionEncoder(mesh, 4, W, 48, stripe_h=STRIPE_H)  # 48 % 32


@pytest.mark.slow  # ~114 s; the graft-entry ambient-plugin variant keeps
# the entrypoint covered in tier 1
def test_dryrun_multichip_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    g.dryrun_multichip(8)


def test_entry_compiles_and_runs():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, example_args = g.entry()
    out = jax.jit(fn)(*example_args)
    jax.block_until_ready(out)
    words, nbytes, base, ovf, damage, new_prev = out
    assert not bool(np.asarray(ovf).any())
    assert int(np.asarray(nbytes).min()) > 0


# ---------------------------------------------------------------- config 5
# Entropy-through sharded step: wire-ready stripes for N sessions from one
# mesh dispatch, bit-exact with the solo JpegStripeEncoder.


def _frame_seq(rng, n_frames):
    """Per-session frame sequence: random → static → partial change."""
    f0 = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    seq = [f0, f0.copy()]
    f2 = f0.copy()
    f2[H // 2:H // 2 + STRIPE_H] = rng.integers(
        0, 256, (STRIPE_H, W, 3), dtype=np.uint8)
    seq.append(f2)
    while len(seq) < n_frames:
        seq.append(seq[-1].copy())
    return seq


def test_mesh_stripe_encoder_matches_solo(mesh):
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.parallel import MeshStripeEncoder

    rng = np.random.default_rng(11)
    n_frames = 5
    seqs = [_frame_seq(np.random.default_rng(100 + n), n_frames)
            for n in range(N_SESSIONS)]

    menc = MeshStripeEncoder(
        mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
        paint_over_trigger_frames=2)
    solos = [JpegStripeEncoder(
        W, H, stripe_height=STRIPE_H, paint_over_trigger_frames=2,
        entropy="device") for _ in range(N_SESSIONS)]

    for t in range(n_frames):
        frames = np.stack([seqs[n][t] for n in range(N_SESSIONS)])
        mesh_out, session_bytes = menc.encode_frames(frames)
        assert session_bytes.shape == (N_SESSIONS,)
        for n in range(N_SESSIONS):
            solo_out = solos[n].encode_frame(seqs[n][t])
            assert [s.y_start for s in mesh_out[n]] == \
                [s.y_start for s in solo_out], f"frame {t} session {n}"
            assert [s.is_paintover for s in mesh_out[n]] == \
                [s.is_paintover for s in solo_out]
            for ms, ss in zip(mesh_out[n], solo_out):
                assert ms.jpeg == ss.jpeg, \
                    f"frame {t} session {n} stripe {ms.y_start}"


def test_mesh_stripe_encoder_none_frames_and_keyframe(mesh):
    from selkies_tpu.parallel import MeshStripeEncoder

    rng = np.random.default_rng(5)
    menc = MeshStripeEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    out, _ = menc.encode_frames(frames)
    assert all(len(s) == H // STRIPE_H for s in out)   # first: all stripes

    # idle slots (None) produce nothing and keep the keyframe flag armed
    menc.force_keyframe(2)
    out, _ = menc.encode_frames([None] * N_SESSIONS)
    assert all(len(s) == 0 for s in out)
    assert menc._first[2]
    out, _ = menc.encode_frames(frames)                # same content
    assert len(out[2]) == H // STRIPE_H                # keyframe fired
    assert all(len(out[n]) == 0 for n in range(N_SESSIONS) if n != 2)


def test_parse_mesh_spec():
    from selkies_tpu.parallel import parse_mesh_spec

    m = parse_mesh_spec("session:4,stripe:2", jax.devices()[:8])
    assert m.shape["session"] == 4 and m.shape["stripe"] == 2
    m = parse_mesh_spec("session:8", jax.devices()[:8])
    assert m.shape["session"] == 8 and m.shape["stripe"] == 1
    with pytest.raises(ValueError):
        parse_mesh_spec("session:64", jax.devices()[:8])
    with pytest.raises(ValueError):
        parse_mesh_spec("tensor:2", jax.devices()[:8])


def test_reset_session_zeroes_prev_planes(mesh):
    """Slot recycling must not leak the previous occupant's pixels: the
    prev planes and the idle-tick re-present buffer go to zero (VERDICT
    r2 weak item 6)."""
    import numpy as np
    from selkies_tpu.parallel.mesh import MeshStripeEncoder

    enc = MeshStripeEncoder(mesh, 4, 128, 128, stripe_h=64)
    frames = [np.full((128, 128, 3), 200, np.uint8)] * 4
    out, _ = enc.harvest(enc.dispatch(frames))
    assert any(stripes for stripes in out)
    assert np.asarray(enc._prev).any()
    enc.reset_session(1)
    prev = np.asarray(enc._prev)
    assert not prev[1].any()           # recycled slot zeroed
    assert prev[0].any()               # neighbours untouched
    assert not enc._last_host[1].any()
    assert enc._first[1]


# ---------------------------------------------------------------- mesh H.264
# VERDICT r3 item 3: the H.264 profile over the ("session", "stripe") mesh,
# bit-exact against the solo H264StripeEncoder oracle.


def _h264_seq(rng, n_frames):
    """random → shifted (motion) → static → one-stripe change → static."""
    f0 = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    f1 = np.roll(f0, 4, axis=0)                       # vertical scroll
    seq = [f0, f1, f1.copy()]
    f3 = f1.copy()
    f3[H // 2:H // 2 + STRIPE_H] = rng.integers(
        0, 256, (STRIPE_H, W, 3), dtype=np.uint8)
    seq.append(f3)
    while len(seq) < n_frames:
        seq.append(seq[-1].copy())
    return seq


def test_mesh_h264_matches_solo(mesh):
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    n_frames = 6
    seqs = [_h264_seq(np.random.default_rng(200 + n), n_frames)
            for n in range(N_SESSIONS)]

    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           paint_over_trigger_frames=2, me="xla")
    solos = [H264StripeEncoder(W, H, stripe_height=STRIPE_H,
                               paint_over_trigger_frames=2)
             for _ in range(N_SESSIONS)]

    for t in range(n_frames):
        frames = np.stack([seqs[n][t] for n in range(N_SESSIONS)])
        mesh_out, coded = menc.encode_frames(frames)
        assert coded.shape == (N_SESSIONS,)
        for n in range(N_SESSIONS):
            solo_out = solos[n].encode_frame(seqs[n][t])
            assert [(s.y_start, s.is_key) for s in mesh_out[n]] == \
                [(s.y_start, s.is_key) for s in solo_out], \
                f"frame {t} session {n}"
            for ms, ss in zip(mesh_out[n], solo_out):
                assert ms.annexb == ss.annexb, \
                    f"frame {t} session {n} stripe {ms.y_start}"


def test_mesh_h264_idle_keyframe_and_reset(mesh):
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    rng = np.random.default_rng(6)
    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           me="xla")
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    out, _ = menc.encode_frames(frames)
    assert all(len(s) == H // STRIPE_H for s in out)      # join: all IDR
    assert all(s.is_key for sess in out for s in sess)

    # idle (None) slots emit nothing; a pending keyframe stays armed
    menc.force_keyframe(2)
    out, _ = menc.encode_frames([None] * N_SESSIONS)
    assert all(len(s) == 0 for s in out)
    assert menc._need_idr[2].all()
    out, _ = menc.encode_frames(frames)                   # same pixels
    assert len(out[2]) == H // STRIPE_H and all(
        s.is_key for s in out[2])                         # IDR fired
    assert all(len(out[n]) == 0 for n in range(N_SESSIONS) if n != 2)

    # reset zeroes the inter reference planes (no cross-occupant leak)
    menc.reset_session(1)
    assert not np.asarray(menc._ref_y)[1].any()
    assert not np.asarray(menc._prev_y)[1].any()
    assert np.asarray(menc._ref_y)[0].any()


# ------------------------------------------------------------- SFE (ISSUE 15)
# Split-frame encoding: ONE session's frame stripe-sharded across every
# chip of the mesh. The concatenated multi-shard access unit must be
# byte-identical to the single-chip encode — IDR, P, and the
# overflow→flat16 fallback stripes — and a failed stripe job must never
# tear the access unit.


@pytest.fixture(scope="module")
def sfe_mesh():
    from selkies_tpu.parallel import parse_mesh_spec

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    return parse_mesh_spec("session:1,stripe:4", jax.devices()[:4])


def test_sfe_concat_bit_exact_and_never_torn(mesh, monkeypatch):
    """Multi-shard SFE vs the solo single-chip oracle over an IDR + P +
    still + partial-change sequence: per-stripe bytes AND the
    concatenated access unit must match, and the harvest must attribute
    per-shard fetch walls. Then whole-frame containment on the SAME
    encoder: one stripe job failing mid-harvest must withhold the WHOLE
    frame — sibling stripes' device references already advanced, so
    emitting them would drift every later P frame — and resync with a
    full IDR next tick.

    Runs on the module mesh (stripe axis 2) with the exact encoder
    geometry test_mesh_h264_matches_solo already compiled, so the SPMD
    programs come from the in-process compile cache — tier-1 pays for
    the containment coverage, not a duplicate ~60 s compile; the wider
    4-shard fan-out stays covered by the slow-marked overflow +
    conformance tests on sfe_mesh."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.parallel import mesh_h264 as m
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    seq = _h264_seq(np.random.default_rng(300), 5)
    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           paint_over_trigger_frames=2, me="xla")
    solo = H264StripeEncoder(W, H, stripe_height=STRIPE_H,
                             paint_over_trigger_frames=2)
    assert menc.n_shards == 2
    idle = [None] * (N_SESSIONS - 1)            # single-session SFE drive

    for t, frame in enumerate(seq):
        mesh_out, coded = menc.encode_frames([frame] + idle)
        solo_out = solo.encode_frame(frame)
        assert [(s.y_start, s.is_key) for s in mesh_out[0]] == \
            [(s.y_start, s.is_key) for s in solo_out], f"frame {t}"
        cat_mesh = b"".join(s.annexb for s in mesh_out[0])
        cat_solo = b"".join(s.annexb for s in solo_out)
        assert cat_mesh == cat_solo, f"frame {t} access unit differs"
    st = menc.last_harvest_stages
    assert st is not None
    assert len(st["per_shard_fetch_ms"]) == 2
    assert st["concat_ms"] >= 0.0

    # --- whole-frame containment: no torn access unit, ever -----------
    real = m.dcav.assemble_p_slice
    fails = {"n": 0}

    def fail_once(*a, **kw):
        if fails["n"] == 0:
            fails["n"] += 1
            raise RuntimeError("injected stripe entropy failure")
        return real(*a, **kw)

    monkeypatch.setattr(m.dcav, "assemble_p_slice", fail_once)
    pa = menc.dispatch([np.roll(seq[-1], 4, axis=0)] + idle)
    pb = menc.dispatch([np.roll(seq[-1], 8, axis=0)] + idle)  # successor
    out1, coded1 = menc.harvest(pa)             # stripe job fails here
    assert out1[0] == []                        # withheld, not torn
    assert int(coded1[0]) == 0
    assert menc._need_idr[0].all()              # full resync armed
    monkeypatch.setattr(m.dcav, "assemble_p_slice", real)
    # the successor was dispatched as P BEFORE the failure surfaced: its
    # prediction chain consumed the withheld frame's references, so it
    # must be withheld too — never a client frame predicted off pixels
    # the client never received
    out_b, _ = menc.harvest(pb)
    assert out_b[0] == []
    out2, _ = menc.encode_frames([np.roll(seq[-1], 12, axis=0)] + idle)
    assert len(out2[0]) == H // STRIPE_H
    assert all(s.is_key for s in out2[0])       # clean full IDR AU

    # --- idle sessions must still resync: the withheld frame's content
    # never reached the client, so a None re-present is NOT a no-op for
    # a withheld session — the armed full-frame IDR runs anyway instead
    # of deferring until fresh damage (which may never come)
    fails["n"] = 0
    monkeypatch.setattr(m.dcav, "assemble_p_slice", fail_once)
    out3, _ = menc.encode_frames([np.roll(seq[-1], 16, axis=0)] + idle)
    assert out3[0] == []                        # withheld again
    monkeypatch.setattr(m.dcav, "assemble_p_slice", real)
    out4, _ = menc.encode_frames([None] + idle)  # idle tick
    assert len(out4[0]) == H // STRIPE_H        # full IDR resync anyway
    assert all(s.is_key for s in out4[0])


@pytest.mark.slow  # ~44 s (a fresh SPMD compile); the flat16 recovery
# path itself is tier-1-covered: the concat test's IDR stripes recover
# through the same exact[(n,g)] flat16 route (host_path = ovf | idr),
# and the device-side ovf FLAG is pinned by test_device_cavlc — this
# pins their end-to-end combination on the SFE mesh
def test_sfe_overflow_flat16_fallback_bit_exact(sfe_mesh):
    """Pathological stripes overflow the device CAVLC budget and recover
    through the exact flat16 host coder — on the SFE mesh this fallback
    must stay byte-identical to the solo encoder taking the same
    fallback (shrunken budget forces it deterministically)."""
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    rng = np.random.default_rng(17)
    menc = MeshH264Encoder(sfe_mesh, 1, W, H, stripe_h=STRIPE_H, me="xla",
                           search=4)
    solo = H264StripeEncoder(W, H, stripe_height=STRIPE_H, search=4)
    # identical tiny per-stripe budgets BEFORE the first (lazy) step
    # build: full-noise P frames then exceed it and take the flat16 path
    menc._cavlc_msb = 64
    solo._cavlc_msb = 64
    for t in range(2):
        frame = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
        mesh_out, _ = menc.encode_frames([frame])
        solo_out = solo.encode_frame(frame)
        assert b"".join(s.annexb for s in mesh_out[0]) == \
            b"".join(s.annexb for s in solo_out), f"frame {t}"
    assert menc.host_fallback_stripes_total > 0


@pytest.mark.slow  # ~43 s; transitively covered in tier 1 —
# test_mesh_h264_matches_solo pins mesh bytes to the solo encoder's, and
# test_conformance decodes the solo output in libavcodec
def test_mesh_h264_decodes_in_conformance_oracle(mesh):
    """Mesh-encoded stripes must decode in libavcodec, IDR then P."""
    from selkies_tpu.encoder import conformance
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    if conformance.ConformanceDecoder is None:
        pytest.skip("conformance decoder unavailable")
    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           me="xla")
    smooth = np.zeros((H, W, 3), np.uint8)
    yy, xx = np.mgrid[0:H, 0:W]
    smooth[..., 0] = (xx * 4) % 256
    smooth[..., 1] = (yy * 4) % 256
    smooth[..., 2] = 128
    out, _ = menc.encode_frames(np.stack([smooth] * N_SESSIONS))
    shifted = np.roll(smooth, 2, axis=0)
    out2, _ = menc.encode_frames(np.stack([shifted] * N_SESSIONS))

    dec = conformance.ConformanceDecoder("h264", max_dim=256)
    n_dec = 0
    for s in (x for x in out[0] + out2[0] if x.y_start == 0):
        got = dec.decode(s.annexb)
        if got is not None:
            n_dec += 1
            y, u, v = got
            assert y.shape == (STRIPE_H, W)
    got = dec.flush()
    n_dec += 1 if got else 0
    assert n_dec >= 2
    dec.close()
