"""Sharded multi-session encode vs. the single-frame encoder oracle."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from selkies_tpu.encoder.jpeg import _encode_body
from selkies_tpu.ops.quant import quality_scaled_tables
from selkies_tpu.parallel import BatchedSessionEncoder, make_mesh


STRIPE_H = 16
W, H = 32, 64  # 4 stripes
N_SESSIONS = 4


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(jax.devices()[:8])  # (4, 2)


def _quant_tables():
    ly, lc = quality_scaled_tables(40)
    py, pc = quality_scaled_tables(90)
    qy = jnp.stack([jnp.asarray(ly, jnp.float32), jnp.asarray(py, jnp.float32)])
    qc = jnp.stack([jnp.asarray(lc, jnp.float32), jnp.asarray(pc, jnp.float32)])
    return qy, qc


def test_mesh_shape(mesh):
    assert mesh.shape["session"] == 4
    assert mesh.shape["stripe"] == 2


def test_batched_matches_single_frame_oracle(mesh):
    rng = np.random.default_rng(7)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    qsel = np.zeros((N_SESSIONS, H // STRIPE_H), np.int32)
    qsel[1, 2] = 1  # one paint-over stripe to exercise per-stripe tables

    enc = BatchedSessionEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    yq, cbq, crq, damage, session_bits, total_bits = enc.step(frames, qsel)

    qy, qc = _quant_tables()
    body = functools.partial(_encode_body, stripe_h=STRIPE_H)
    for n in range(N_SESSIONS):
        ref = body(
            jnp.asarray(frames[n]), jnp.zeros((H, W, 3), jnp.uint8),
            qy, qc, jnp.asarray(qsel[n]))
        np.testing.assert_array_equal(np.asarray(yq)[n], np.asarray(ref[0]))
        np.testing.assert_array_equal(np.asarray(cbq)[n], np.asarray(ref[1]))
        np.testing.assert_array_equal(np.asarray(crq)[n], np.asarray(ref[2]))
        np.testing.assert_array_equal(np.asarray(damage)[n], np.asarray(ref[3]))
    assert int(total_bits) == int(np.asarray(session_bits).sum())


def test_prev_chain_damage_goes_quiet(mesh):
    rng = np.random.default_rng(3)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    enc = BatchedSessionEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    enc.step(frames)
    _, _, _, damage2, _, _ = enc.step(frames)  # identical frame → no damage
    assert int(np.asarray(damage2).max()) == 0


def test_geometry_validation(mesh):
    with pytest.raises(ValueError):
        BatchedSessionEncoder(mesh, 3, W, H, stripe_h=STRIPE_H)  # 3 % 4
    with pytest.raises(ValueError):
        BatchedSessionEncoder(mesh, 4, W, 48, stripe_h=STRIPE_H)  # 48 % 32


@pytest.mark.slow  # ~114 s; the graft-entry ambient-plugin variant keeps
# the entrypoint covered in tier 1
def test_dryrun_multichip_entrypoint():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    g.dryrun_multichip(8)


def test_entry_compiles_and_runs():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, example_args = g.entry()
    out = jax.jit(fn)(*example_args)
    jax.block_until_ready(out)
    words, nbytes, base, ovf, damage, new_prev = out
    assert not bool(np.asarray(ovf).any())
    assert int(np.asarray(nbytes).min()) > 0


# ---------------------------------------------------------------- config 5
# Entropy-through sharded step: wire-ready stripes for N sessions from one
# mesh dispatch, bit-exact with the solo JpegStripeEncoder.


def _frame_seq(rng, n_frames):
    """Per-session frame sequence: random → static → partial change."""
    f0 = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    seq = [f0, f0.copy()]
    f2 = f0.copy()
    f2[H // 2:H // 2 + STRIPE_H] = rng.integers(
        0, 256, (STRIPE_H, W, 3), dtype=np.uint8)
    seq.append(f2)
    while len(seq) < n_frames:
        seq.append(seq[-1].copy())
    return seq


def test_mesh_stripe_encoder_matches_solo(mesh):
    from selkies_tpu.encoder.jpeg import JpegStripeEncoder
    from selkies_tpu.parallel import MeshStripeEncoder

    rng = np.random.default_rng(11)
    n_frames = 5
    seqs = [_frame_seq(np.random.default_rng(100 + n), n_frames)
            for n in range(N_SESSIONS)]

    menc = MeshStripeEncoder(
        mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
        paint_over_trigger_frames=2)
    solos = [JpegStripeEncoder(
        W, H, stripe_height=STRIPE_H, paint_over_trigger_frames=2,
        entropy="device") for _ in range(N_SESSIONS)]

    for t in range(n_frames):
        frames = np.stack([seqs[n][t] for n in range(N_SESSIONS)])
        mesh_out, session_bytes = menc.encode_frames(frames)
        assert session_bytes.shape == (N_SESSIONS,)
        for n in range(N_SESSIONS):
            solo_out = solos[n].encode_frame(seqs[n][t])
            assert [s.y_start for s in mesh_out[n]] == \
                [s.y_start for s in solo_out], f"frame {t} session {n}"
            assert [s.is_paintover for s in mesh_out[n]] == \
                [s.is_paintover for s in solo_out]
            for ms, ss in zip(mesh_out[n], solo_out):
                assert ms.jpeg == ss.jpeg, \
                    f"frame {t} session {n} stripe {ms.y_start}"


def test_mesh_stripe_encoder_none_frames_and_keyframe(mesh):
    from selkies_tpu.parallel import MeshStripeEncoder

    rng = np.random.default_rng(5)
    menc = MeshStripeEncoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H)
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    out, _ = menc.encode_frames(frames)
    assert all(len(s) == H // STRIPE_H for s in out)   # first: all stripes

    # idle slots (None) produce nothing and keep the keyframe flag armed
    menc.force_keyframe(2)
    out, _ = menc.encode_frames([None] * N_SESSIONS)
    assert all(len(s) == 0 for s in out)
    assert menc._first[2]
    out, _ = menc.encode_frames(frames)                # same content
    assert len(out[2]) == H // STRIPE_H                # keyframe fired
    assert all(len(out[n]) == 0 for n in range(N_SESSIONS) if n != 2)


def test_parse_mesh_spec():
    from selkies_tpu.parallel import parse_mesh_spec

    m = parse_mesh_spec("session:4,stripe:2", jax.devices()[:8])
    assert m.shape["session"] == 4 and m.shape["stripe"] == 2
    m = parse_mesh_spec("session:8", jax.devices()[:8])
    assert m.shape["session"] == 8 and m.shape["stripe"] == 1
    with pytest.raises(ValueError):
        parse_mesh_spec("session:64", jax.devices()[:8])
    with pytest.raises(ValueError):
        parse_mesh_spec("tensor:2", jax.devices()[:8])


def test_reset_session_zeroes_prev_planes(mesh):
    """Slot recycling must not leak the previous occupant's pixels: the
    prev planes and the idle-tick re-present buffer go to zero (VERDICT
    r2 weak item 6)."""
    import numpy as np
    from selkies_tpu.parallel.mesh import MeshStripeEncoder

    enc = MeshStripeEncoder(mesh, 4, 128, 128, stripe_h=64)
    frames = [np.full((128, 128, 3), 200, np.uint8)] * 4
    out, _ = enc.harvest(enc.dispatch(frames))
    assert any(stripes for stripes in out)
    assert np.asarray(enc._prev).any()
    enc.reset_session(1)
    prev = np.asarray(enc._prev)
    assert not prev[1].any()           # recycled slot zeroed
    assert prev[0].any()               # neighbours untouched
    assert not enc._last_host[1].any()
    assert enc._first[1]


# ---------------------------------------------------------------- mesh H.264
# VERDICT r3 item 3: the H.264 profile over the ("session", "stripe") mesh,
# bit-exact against the solo H264StripeEncoder oracle.


def _h264_seq(rng, n_frames):
    """random → shifted (motion) → static → one-stripe change → static."""
    f0 = rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
    f1 = np.roll(f0, 4, axis=0)                       # vertical scroll
    seq = [f0, f1, f1.copy()]
    f3 = f1.copy()
    f3[H // 2:H // 2 + STRIPE_H] = rng.integers(
        0, 256, (STRIPE_H, W, 3), dtype=np.uint8)
    seq.append(f3)
    while len(seq) < n_frames:
        seq.append(seq[-1].copy())
    return seq


def test_mesh_h264_matches_solo(mesh):
    from selkies_tpu.encoder.h264 import H264StripeEncoder
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    n_frames = 6
    seqs = [_h264_seq(np.random.default_rng(200 + n), n_frames)
            for n in range(N_SESSIONS)]

    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           paint_over_trigger_frames=2, me="xla")
    solos = [H264StripeEncoder(W, H, stripe_height=STRIPE_H,
                               paint_over_trigger_frames=2)
             for _ in range(N_SESSIONS)]

    for t in range(n_frames):
        frames = np.stack([seqs[n][t] for n in range(N_SESSIONS)])
        mesh_out, coded = menc.encode_frames(frames)
        assert coded.shape == (N_SESSIONS,)
        for n in range(N_SESSIONS):
            solo_out = solos[n].encode_frame(seqs[n][t])
            assert [(s.y_start, s.is_key) for s in mesh_out[n]] == \
                [(s.y_start, s.is_key) for s in solo_out], \
                f"frame {t} session {n}"
            for ms, ss in zip(mesh_out[n], solo_out):
                assert ms.annexb == ss.annexb, \
                    f"frame {t} session {n} stripe {ms.y_start}"


def test_mesh_h264_idle_keyframe_and_reset(mesh):
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    rng = np.random.default_rng(6)
    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           me="xla")
    frames = rng.integers(0, 256, (N_SESSIONS, H, W, 3), dtype=np.uint8)
    out, _ = menc.encode_frames(frames)
    assert all(len(s) == H // STRIPE_H for s in out)      # join: all IDR
    assert all(s.is_key for sess in out for s in sess)

    # idle (None) slots emit nothing; a pending keyframe stays armed
    menc.force_keyframe(2)
    out, _ = menc.encode_frames([None] * N_SESSIONS)
    assert all(len(s) == 0 for s in out)
    assert menc._need_idr[2].all()
    out, _ = menc.encode_frames(frames)                   # same pixels
    assert len(out[2]) == H // STRIPE_H and all(
        s.is_key for s in out[2])                         # IDR fired
    assert all(len(out[n]) == 0 for n in range(N_SESSIONS) if n != 2)

    # reset zeroes the inter reference planes (no cross-occupant leak)
    menc.reset_session(1)
    assert not np.asarray(menc._ref_y)[1].any()
    assert not np.asarray(menc._prev_y)[1].any()
    assert np.asarray(menc._ref_y)[0].any()


@pytest.mark.slow  # ~43 s; transitively covered in tier 1 —
# test_mesh_h264_matches_solo pins mesh bytes to the solo encoder's, and
# test_conformance decodes the solo output in libavcodec
def test_mesh_h264_decodes_in_conformance_oracle(mesh):
    """Mesh-encoded stripes must decode in libavcodec, IDR then P."""
    from selkies_tpu.encoder import conformance
    from selkies_tpu.parallel.mesh_h264 import MeshH264Encoder

    if conformance.ConformanceDecoder is None:
        pytest.skip("conformance decoder unavailable")
    menc = MeshH264Encoder(mesh, N_SESSIONS, W, H, stripe_h=STRIPE_H,
                           me="xla")
    smooth = np.zeros((H, W, 3), np.uint8)
    yy, xx = np.mgrid[0:H, 0:W]
    smooth[..., 0] = (xx * 4) % 256
    smooth[..., 1] = (yy * 4) % 256
    smooth[..., 2] = 128
    out, _ = menc.encode_frames(np.stack([smooth] * N_SESSIONS))
    shifted = np.roll(smooth, 2, axis=0)
    out2, _ = menc.encode_frames(np.stack([shifted] * N_SESSIONS))

    dec = conformance.ConformanceDecoder("h264", max_dim=256)
    n_dec = 0
    for s in (x for x in out[0] + out2[0] if x.y_start == 0):
        got = dec.decode(s.annexb)
        if got is not None:
            n_dec += 1
            y, u, v = got
            assert y.shape == (STRIPE_H, W)
    got = dec.flush()
    n_dec += 1 if got else 0
    assert n_dec >= 2
    dec.close()
